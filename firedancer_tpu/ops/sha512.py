"""Batched SHA-512 for TPU (JAX/XLA), 64-bit words as uint32 hi/lo pairs.

Role: the TPU replacement for the reference's AVX2-asm SHA-512 core and its
4-way batched API (/root/reference/src/ballet/sha512/fd_sha512.h:221-251,
fd_sha512_batch_avx.c) — the batch axis here is the TPU lane axis instead of
4 AVX lanes.

TPU-first decisions:
- **No 64-bit integers.** TPU int64 is emulated and slow; every 64-bit word
  is an explicit (hi, lo) pair of uint32 arrays, with ripple-carry adds and
  pairwise rotates. All ops are VPU-friendly elementwise uint32.
- **Lane-major batch.** Words have shape (*, B): the batch dimension rides
  the 128-wide lane axis (same layout rationale as fe25519).
- **Variable message length via masking, not bucketing.** All lanes run
  max_blocks compression rounds; a lane's state only updates while
  block_idx < its block count. Padding (0x80 marker + 128-bit big-endian
  bit length) is placed arithmetically from per-lane lengths, so the whole
  batch is one jit with static shapes. This is the batch-uniform control
  flow the TPU mandates (SURVEY.md section 7 "uniform control flow").

Message-schedule and round structure follow FIPS 180-4.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

U32 = jnp.uint32

# FIPS 180-4 SHA-512 round constants (first 64 bits of fractional parts of
# cube roots of the first 80 primes) and initial hash state.
_K = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F, 0xE9B5DBA58189DBBC,
    0x3956C25BF348B538, 0x59F111F1B605D019, 0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118,
    0xD807AA98A3030242, 0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235, 0xC19BF174CF692694,
    0xE49B69C19EF14AD2, 0xEFBE4786384F25E3, 0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65,
    0x2DE92C6F592B0275, 0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F, 0xBF597FC7BEEF0EE4,
    0xC6E00BF33DA88FC2, 0xD5A79147930AA725, 0x06CA6351E003826F, 0x142929670A0E6E70,
    0x27B70A8546D22FFC, 0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6, 0x92722C851482353B,
    0xA2BFE8A14CF10364, 0xA81A664BBC423001, 0xC24B8B70D0F89791, 0xC76C51A30654BE30,
    0xD192E819D6EF5218, 0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99, 0x34B0BCB5E19B48A8,
    0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB, 0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3,
    0x748F82EE5DEFB2FC, 0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915, 0xC67178F2E372532B,
    0xCA273ECEEA26619C, 0xD186B8C721C0C207, 0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178,
    0x06F067AA72176FBA, 0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC, 0x431D67C49C100D4C,
    0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A, 0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
_IV = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]

_K_HI = jnp.asarray(np.asarray([k >> 32 for k in _K], np.uint32))
_K_LO = jnp.asarray(np.asarray([k & 0xFFFFFFFF for k in _K], np.uint32))
_IV_HI = np.asarray([v >> 32 for v in _IV], np.uint32)
_IV_LO = np.asarray([v & 0xFFFFFFFF for v in _IV], np.uint32)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(U32)
    return ah + bh + carry, lo


def _rotr64(h, l, n):
    n = n % 64
    if n == 0:
        return h, l
    if n < 32:
        nh = (h >> n) | (l << (32 - n))
        nl = (l >> n) | (h << (32 - n))
        return nh, nl
    if n == 32:
        return l, h
    m = n - 32
    nh = (l >> m) | (h << (32 - m))
    nl = (h >> m) | (l << (32 - m))
    return nh, nl


def _shr64(h, l, n):
    if n < 32:
        return h >> n, (l >> n) | (h << (32 - n))
    if n == 32:
        return jnp.zeros_like(h), h
    return jnp.zeros_like(h), h >> (n - 32)


def _xor3(a, b, c):
    return a ^ b ^ c


def _compress_block(state, w_hi, w_lo):
    """One SHA-512 compression: state (8,2,B) uint32, block words (16, B)."""

    def big_sigma0(h, l):
        return _xor3_pair(_rotr64(h, l, 28), _rotr64(h, l, 34), _rotr64(h, l, 39))

    def big_sigma1(h, l):
        return _xor3_pair(_rotr64(h, l, 14), _rotr64(h, l, 18), _rotr64(h, l, 41))

    def small_sigma0(h, l):
        return _xor3_pair(_rotr64(h, l, 1), _rotr64(h, l, 8), _shr64(h, l, 7))

    def small_sigma1(h, l):
        return _xor3_pair(_rotr64(h, l, 19), _rotr64(h, l, 61), _shr64(h, l, 6))

    def _xor3_pair(p0, p1, p2):
        return _xor3(p0[0], p1[0], p2[0]), _xor3(p0[1], p1[1], p2[1])

    # Extend 16 -> 80 schedule words with a scan carrying a 16-word window.
    def extend(window, _):
        wh, wl = window  # (16, B) each
        s0 = small_sigma0(wh[1], wl[1])
        s1 = small_sigma1(wh[14], wl[14])
        nh, nl = _add64(wh[0], wl[0], s0[0], s0[1])
        nh, nl = _add64(nh, nl, wh[9], wl[9])
        nh, nl = _add64(nh, nl, s1[0], s1[1])
        new_h = jnp.concatenate([wh[1:], nh[None]], axis=0)
        new_l = jnp.concatenate([wl[1:], nl[None]], axis=0)
        return (new_h, new_l), (nh, nl)

    (_, _), (ext_h, ext_l) = jax.lax.scan(extend, (w_hi, w_lo), None, length=64)
    sched_h = jnp.concatenate([w_hi, ext_h], axis=0)  # (80, B)
    sched_l = jnp.concatenate([w_lo, ext_l], axis=0)

    def round_fn(abcdefgh, inputs):
        kh, kl, wh, wl = inputs
        a_h, a_l, b_h, b_l, c_h, c_l, d_h, d_l, e_h, e_l, f_h, f_l, g_h, g_l, h_h, h_l = abcdefgh
        s1 = big_sigma1(e_h, e_l)
        ch_h = (e_h & f_h) ^ (~e_h & g_h)
        ch_l = (e_l & f_l) ^ (~e_l & g_l)
        t1h, t1l = _add64(h_h, h_l, s1[0], s1[1])
        t1h, t1l = _add64(t1h, t1l, ch_h, ch_l)
        t1h, t1l = _add64(t1h, t1l, kh, kl)
        t1h, t1l = _add64(t1h, t1l, wh, wl)
        s0 = big_sigma0(a_h, a_l)
        maj_h = (a_h & b_h) ^ (a_h & c_h) ^ (b_h & c_h)
        maj_l = (a_l & b_l) ^ (a_l & c_l) ^ (b_l & c_l)
        t2h, t2l = _add64(s0[0], s0[1], maj_h, maj_l)
        ne_h, ne_l = _add64(d_h, d_l, t1h, t1l)
        na_h, na_l = _add64(t1h, t1l, t2h, t2l)
        return (na_h, na_l, a_h, a_l, b_h, b_l, c_h, c_l,
                ne_h, ne_l, e_h, e_l, f_h, f_l, g_h, g_l), None

    batch = w_hi.shape[1:]
    init = tuple(
        jnp.broadcast_to(state[i // 2, i % 2], batch)
        for i in range(16)
    )
    k_h = jnp.broadcast_to(_K_HI[:, None], (80,) + batch) if batch else _K_HI
    k_l = jnp.broadcast_to(_K_LO[:, None], (80,) + batch) if batch else _K_LO
    final, _ = jax.lax.scan(round_fn, init, (k_h, k_l, sched_h, sched_l))

    out = []
    for i in range(8):
        sh, sl = _add64(state[i, 0], state[i, 1], final[2 * i], final[2 * i + 1])
        out.append(jnp.stack([sh, sl]))
    return jnp.stack(out)  # (8, 2, B)


def _bytes_to_words(block_bytes):
    """(16*8, B) uint8 big-endian -> two (16, B) uint32 arrays."""
    b = block_bytes.astype(U32).reshape((16, 8) + block_bytes.shape[1:])
    hi = (b[:, 0] << 24) | (b[:, 1] << 16) | (b[:, 2] << 8) | b[:, 3]
    lo = (b[:, 4] << 24) | (b[:, 5] << 16) | (b[:, 6] << 8) | b[:, 7]
    return hi, lo


def sha512_batch_auto(msgs: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """Backend-dispatched batch SHA-512: the VMEM compression kernel on
    TPU (ops/sha512_pallas.py), this module's XLA graph elsewhere."""
    from .backend import use_pallas

    if use_pallas("FD_SHA_IMPL"):
        from .sha512_pallas import sha512_batch_pallas

        return sha512_batch_pallas(msgs, lengths)
    return sha512_batch(msgs, lengths)


def sha512_batch(msgs: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-512 of variable-length messages.

    msgs: (B, max_len) uint8, each row's message in bytes [0, lengths[b]).
    lengths: (B,) int32 byte lengths (<= max_len).
    Returns (B, 64) uint8 digests.

    All lanes run ceil((max_len+17)/128) compressions; per-lane block counts
    mask the state updates.
    """
    bsz, max_len = msgs.shape
    max_blocks = (max_len + 17 + 127) // 128
    total = max_blocks * 128
    lengths = lengths.astype(jnp.int32)

    # Build padded buffer (total, B): message | 0x80 | zeros | 128-bit bitlen.
    data = jnp.moveaxis(msgs.astype(U32), -1, 0)  # (max_len, B)
    if total > max_len:
        data = jnp.concatenate(
            [data, jnp.zeros((total - max_len, bsz), U32)], axis=0
        )
    pos = jnp.arange(total, dtype=jnp.int32)[:, None]          # (total, 1)
    ln = lengths[None, :]                                       # (1, B)
    data = jnp.where(pos < ln, data, 0)
    data = jnp.where(pos == ln, 0x80, data)
    # Per-lane final block and big-endian length field (bit length < 2^32+3
    # for any practical max_len, but compute full 64 bits of it anyway).
    nblocks = (lengths + 17 + 127) // 128                       # (B,)
    len_start = nblocks * 128 - 8                               # low 8 bytes
    # 64-bit bit length as a uint32 hi/lo pair (lengths up to 2^32 bytes);
    # the upper 8 bytes of the 128-bit field stay zero.
    bitlen_lo = lengths.astype(U32) << 3
    bitlen_hi = lengths.astype(U32) >> 29
    # byte k of the 8-byte big-endian field at offset len_start + k
    k = pos - len_start[None, :]
    word = jnp.where(k < 4, bitlen_hi[None, :], bitlen_lo[None, :])
    shift = (3 - (k & 3)) * 8
    lenbyte = jnp.where(
        (k >= 0) & (k < 8),
        (word >> jnp.clip(shift, 0, 31)) & 0xFF,
        0,
    ).astype(U32)
    data = data | lenbyte

    state = jnp.broadcast_to(
        jnp.stack([jnp.stack([_IV_HI[i], _IV_LO[i]]) for i in range(8)])[..., None],
        (8, 2, bsz),
    ).astype(U32)

    def per_block(state, i):
        block = jax.lax.dynamic_slice_in_dim(data, i * 128, 128, axis=0)
        w_hi, w_lo = _bytes_to_words(block)
        new_state = _compress_block(state, w_hi, w_lo)
        active = (i < nblocks)[None, None, :]
        return jnp.where(active, new_state, state), None

    state, _ = jax.lax.scan(per_block, state, jnp.arange(max_blocks))

    # state (8, 2, B) -> (B, 64) big-endian bytes
    words = state.transpose(2, 0, 1)  # (B, 8, 2) hi/lo
    shifts = jnp.asarray([24, 16, 8, 0], U32)
    hi_b = (words[:, :, 0:1] >> shifts[None, None, :]) & 0xFF
    lo_b = (words[:, :, 1:2] >> shifts[None, None, :]) & 0xFF
    return jnp.concatenate([hi_b, lo_b], axis=-1).reshape(bsz, 64).astype(jnp.uint8)
