"""Fused Pallas TPU kernels for point decompression and compression.

The XLA decompress/compress graphs interleave a handful of field muls
and canonical-form compares around the Pallas power chains; at
production batch sizes each stray XLA fe_mul streams its operands
through HBM (~0.8 ms amortized at B=8192 on v5e) and each canonicalize
costs a multi-kernel elementwise chain (~7.6 ms measured) — together
they dwarf the in-VMEM power chain (8.3 ms). These kernels run the
ENTIRE decompress (square-root candidate via z^((p-5)/8), root checks,
sign fix-up, identity poison for failed lanes) and compress (per-lane
inversion chain, canonical bytes, sign bit) on one VMEM-resident lane
tile, leaving only byte<->limb transposes outside.

Reference semantics: donna-style decompression and canonical encoding,
identical to curve25519.decompress/compress (the XLA path, which stays
as the CPU/dryrun implementation and the correctness oracle) — see
/root/reference/src/ballet/ed25519/ref/fd_ed25519_ge.c:242 (frombytes)
and fe_tobytes usage therein.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import fe25519 as fe

NLIMBS = fe.NLIMBS
LANES = 512
# Below this batch the padded kernel launch loses to the XLA graph;
# callers that pre-gate (e.g. verify_rlc's want_niels) reference this.
MIN_KERNEL_BATCH = 128


# One kernel-safe power-chain implementation for all Pallas modules
# (backend.use_specialized_square's dispatch lives behind these).
from .pow_pallas import _mul, _sq
from .pow_pallas import invert_chain as _invert
from .pow_pallas import pow22523_chain as _pow22523


def _sel(m, a, b):
    """Arithmetic lane select: m (1, L) int32 in {0,1}."""
    return m * a + (1 - m) * b


@functools.lru_cache(maxsize=1)
def _const_cols() -> np.ndarray:
    """(32, 3) int32: columns = d, sqrt(-1), 2d (kernel input — Pallas
    kernels cannot capture constant arrays)."""
    out = np.zeros((NLIMBS, 3), np.int32)
    consts = (fe.D_INT, fe.SQRT_M1_INT, 2 * fe.D_INT % fe.P)
    for c, val in enumerate(consts):
        for i in range(NLIMBS):
            out[i, c] = (val >> (8 * i)) & 0xFF
    return out


def _point_double_k(x1, y1, z1):
    """dbl-2008-hwcd a=-1 (T-free), in-kernel field ops."""
    a = _sq(x1)
    b = _sq(y1)
    zz = _sq(z1)
    c = fe.fe_add(zz, zz)
    d_ = fe.fe_neg(a)
    e = fe.fe_sub(fe.fe_sub(_sq(fe.fe_add(x1, y1)), a), b)
    g = fe.fe_add(d_, b)
    f = fe.fe_sub(g, c)
    h = fe.fe_sub(d_, b)
    return _mul(e, f), _mul(g, h), _mul(f, g)


def _small_order_k(x, y, z):
    """(1, L) mask: 8*P == identity — the reference's
    fd_ed25519_ge_p3_is_small_order (fd_ed25519_ge.c:62-66), in-VMEM."""
    for _ in range(3):
        x, y, z = _point_double_k(x, y, z)
    return fe.fe_is_zero_k(x) * fe.fe_is_zero_k(fe.fe_sub(y, z))


def _decompress_so_kernel(yin, sign, consts, ox, oy, oz, ot, ook, oxz,
                          oso):
    """_decompress_kernel plus the small-order mask, computed on the
    just-decompressed point while it sits in VMEM (the verify path's
    2-point semantics; failed lanes carry the identity poison, which
    reads small_order=1 — callers gate on ok first)."""
    _decompress_body(yin, sign, consts, ox, oy, oz, ot, ook, oxz)
    oso[...] = _small_order_k(ox[...], oy[...], oz[...])


def _decompress_niels_kernel(yin, sign, consts, ox, oy, oz, ot, ook, oxz,
                             oyp, oym, ot2d, ot2dn):
    """_decompress_kernel plus niels-form outputs for the MSM fills:
    yp = y+x, ym = y-x, t2d = 2d*t, t2dn = -2d*t (the niels form of the
    NEGATED point is (ym, yp, t2dn), so both signs come for free).
    Failed lanes carry the niels identity (1, 1, 0)."""
    _decompress_body(yin, sign, consts, ox, oy, oz, ot, ook, oxz)
    lanes = yin[...].shape[1]
    # Poisoned lanes already hold the identity (0, 1, 1, 0), whose
    # niels form (1, 1, 0) falls out of the same arithmetic — no
    # extra select needed.
    x = ox[...]
    y = oy[...]
    t = ot[...]
    d2 = jnp.broadcast_to(consts[:, 2:3], (NLIMBS, lanes))
    t2d = _mul(t, d2)
    oyp[...] = fe.fe_add(y, x)
    oym[...] = fe.fe_sub(y, x)
    ot2d[...] = t2d
    ot2dn[...] = fe.fe_neg(t2d)


def _decompress_kernel(yin, sign, consts, ox, oy, oz, ot, ook, oxz):
    _decompress_body(yin, sign, consts, ox, oy, oz, ot, ook, oxz)


def _decompress_body(yin, sign, consts, ox, oy, oz, ot, ook, oxz):
    y = yin[...]
    lanes = y.shape[1]
    # PR 14: the Montgomery-batched body (one invert chain per
    # FD_DECOMPRESS_BATCH-group via the in-tile half-split tree, a
    # pure-squaring ladder for the sqrt ratio) replaces the per-lane
    # pow22523 chain whenever the tile can fold; FD_DECOMPRESS_BATCH=0
    # or a narrow test tile keeps the staged chain below — decided at
    # trace time like every *_IMPL selector, bit-exact either way.
    from .decompress_pallas import (
        _decompress_batched_body,
        use_batched_kernel,
    )

    if use_batched_kernel(lanes):
        x, yv, z, t, ok, xz = _decompress_batched_body(
            y, sign[...], consts)
        ox[...] = x
        oy[...] = yv
        oz[...] = z
        ot[...] = t
        ook[...] = ok
        oxz[...] = xz
        return ok
    d_c = jnp.broadcast_to(consts[:, 0:1], (NLIMBS, lanes))
    sqrtm1 = jnp.broadcast_to(consts[:, 1:2], (NLIMBS, lanes))
    one = (jax.lax.broadcasted_iota(jnp.int32, (NLIMBS, lanes), 0) == 0)
    one = one.astype(jnp.int32)

    yy = _sq(y)
    u = fe.fe_sub(yy, one)                      # y^2 - 1
    v = fe.fe_add(_mul(yy, d_c), one)           # d y^2 + 1
    v3 = _mul(_sq(v), v)
    uv7 = _mul(_mul(_sq(v3), v), u)             # u v^7
    x = _mul(_mul(_pow22523(uv7), v3), u)       # u v^3 (uv^7)^((p-5)/8)

    vxx = _mul(_sq(x), v)
    root_ok = fe.fe_is_zero_k(fe.fe_sub(vxx, u))           # (1, L)
    neg_ok = fe.fe_is_zero_k(fe.fe_add(vxx, u))
    x = _sel(root_ok, x, _mul(x, sqrtm1))
    ok = root_ok | neg_ok

    flip = fe.fe_parity_k(x) ^ sign[...]
    x = _sel(flip, fe.fe_neg(x), x)

    t = _mul(x, y)
    zero = jnp.zeros((NLIMBS, lanes), jnp.int32)
    # Failed lanes carry the identity (0, 1, 1, 0) — harmless poison.
    ox[...] = _sel(ok, x, zero)
    oy[...] = _sel(ok, y, one)
    oz[...] = one
    ot[...] = _sel(ok, t, zero)
    ook[...] = ok
    # x == 0 mod p of the DECOMPRESSED point (before identity poison;
    # negation preserves zero). Costs one in-VMEM canonicalize here vs
    # a ~7.6 ms XLA chain for the caller (verify_rlc's r-canonicality).
    oxz[...] = fe.fe_is_zero_k(x)
    return ok


def decompress_pallas(y_bytes: jnp.ndarray, interpret: bool = False,
                      lanes: int | None = None,
                      want_x_zero: bool = False,
                      want_niels: bool = False,
                      want_small_order: bool = False):
    """Drop-in for curve25519.decompress on TPU: (B, 32) uint8 ->
    ((X, Y, Z, T) of (32, B) limbs, (B,) bool ok). lanes overrides the
    kernel tile width (tests use a small tile to exercise padding).
    want_x_zero=True appends an (B,) bool x==0-mod-p mask (of the
    decompressed x, before identity poison — only meaningful for
    ok lanes). want_niels=True appends (yp, ym, t2d, t2dn) niels-form
    limbs for the MSM fills (identity-form on failed lanes); the
    NEGATED point's niels form is (ym, yp, t2dn). Requires the kernel
    path (bsz >= 128) when want_niels is set."""
    from jax.experimental import pallas as pl

    if want_niels and want_small_order:
        raise ValueError("want_niels and want_small_order are exclusive")
    bsz = y_bytes.shape[0]
    if bsz < MIN_KERNEL_BATCH:
        # Sub-tile batches: the XLA path beats a padded kernel launch.
        from . import curve25519 as ge

        if want_niels:
            raise ValueError("want_niels requires a kernel-tile batch")
        if want_small_order:
            if want_x_zero:
                pt, ok, xz = ge.decompress_xla(y_bytes, True)
                return pt, ok, xz, ge.small_order_mask(pt)
            pt, ok = ge.decompress_xla(y_bytes)
            return pt, ok, ge.small_order_mask(pt)
        return ge.decompress_xla(y_bytes, want_x_zero)
    sign = (y_bytes[:, 31] >> 7).astype(jnp.int32)[None, :]    # (1, B)
    y = fe.fe_from_bytes(y_bytes, mask_high_bit=True)          # (32, B)
    lanes = lanes or min(LANES, bsz)
    pad = (-bsz) % lanes
    if pad:
        y = jnp.pad(y, ((0, 0), (0, pad)))
        sign = jnp.pad(sign, ((0, 0), (0, pad)))
    n = (bsz + pad) // lanes

    spec_fe = pl.BlockSpec((NLIMBS, lanes), lambda i: (0, i))
    spec_row = pl.BlockSpec((1, lanes), lambda i: (0, i))
    spec_c = pl.BlockSpec((NLIMBS, 3), lambda i: (0, 0))
    out_fe = jax.ShapeDtypeStruct((NLIMBS, bsz + pad), jnp.int32)
    out_row = jax.ShapeDtypeStruct((1, bsz + pad), jnp.int32)
    n_fe_out = 8 if want_niels else 4
    n_row_out = 3 if want_small_order else 2
    if want_niels:
        kernel = _decompress_niels_kernel
    elif want_small_order:
        kernel = _decompress_so_kernel
    else:
        kernel = _decompress_kernel
    outs = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[spec_fe, spec_row, spec_c],
        out_specs=[spec_fe] * 4 + [spec_row] * n_row_out
        + [spec_fe] * (n_fe_out - 4),
        out_shape=[out_fe] * 4 + [out_row] * n_row_out
        + [out_fe] * (n_fe_out - 4),
        interpret=interpret,
    )(y, sign, jnp.asarray(_const_cols()))
    x, yy, z, t = outs[:4]
    ok, xz = outs[4:6]
    so = outs[6] if want_small_order else None
    niels = outs[6:] if want_niels else ()
    if pad:
        x, yy, z, t = (c[:, :bsz] for c in (x, yy, z, t))
        niels = tuple(c[:, :bsz] for c in niels)
        ok = ok[:, :bsz]
        xz = xz[:, :bsz]
        if so is not None:
            so = so[:, :bsz]
    ret = [(x, yy, z, t), ok[0] != 0]
    if want_x_zero:
        ret.append(xz[0] != 0)
    if want_niels:
        ret.append(tuple(niels))
    if want_small_order:
        ret.append(so[0] != 0)
    return tuple(ret)


def _point_eq_kernel(axin, ayin, xin, yin, zin, om):
    """(1, L) mask: affine (ax, ay) == projective (X:Y:Z) — the verify
    2-point final compare (fd_ed25519_user.c:424-430): ax*Z == X and
    ay*Z == Y, two in-VMEM muls + zero tests, no inversion."""
    z = zin[...]
    d1 = fe.fe_sub(_mul(axin[...], z), xin[...])
    d2 = fe.fe_sub(_mul(ayin[...], z), yin[...])
    om[...] = fe.fe_is_zero_k(d1) * fe.fe_is_zero_k(d2)


def point_eq_affine_pallas(aff, proj, interpret: bool = False,
                           lanes: int | None = None):
    """Drop-in for curve25519.point_eq_affine_xla on TPU: (B,) bool."""
    from jax.experimental import pallas as pl

    ax, ay = aff
    x, y, z, _ = proj
    bsz = ax.shape[1]
    if bsz < MIN_KERNEL_BATCH:
        from . import curve25519 as ge

        return ge.point_eq_affine_xla(aff, proj)
    lanes = lanes or min(LANES, bsz)
    pad = (-bsz) % lanes
    if pad:
        # Pad lanes are sliced off before return; their values are moot.
        ax, ay, x, y, z = (jnp.pad(c, ((0, 0), (0, pad)))
                           for c in (ax, ay, x, y, z))
    n = (bsz + pad) // lanes
    spec_fe = pl.BlockSpec((NLIMBS, lanes), lambda i: (0, i))
    spec_row = pl.BlockSpec((1, lanes), lambda i: (0, i))
    m = pl.pallas_call(
        _point_eq_kernel,
        grid=(n,),
        in_specs=[spec_fe] * 5,
        out_specs=spec_row,
        out_shape=jax.ShapeDtypeStruct((1, bsz + pad), jnp.int32),
        interpret=interpret,
    )(ax, ay, x, y, z)
    return m[0, :bsz] != 0


def _compress_kernel(xin, yin, zin, ocy, osign):
    x = xin[...]
    y = yin[...]
    z = zin[...]
    zinv = _invert(z)
    ax = _mul(x, zinv)
    ay = _mul(y, zinv)
    ocy[...] = fe._canonicalize_k(ay)
    osign[...] = fe.fe_parity_k(ax)


def compress_pallas(p, interpret: bool = False,
                    lanes: int | None = None) -> jnp.ndarray:
    """Drop-in for curve25519.compress on TPU: (X:Y:Z:T) limbs ->
    (B, 32) uint8 canonical encodings. Runs the per-lane inversion
    chain in VMEM (the grouped Montgomery tree needs cross-lane muls,
    which cost more in XLA launches than the extra in-kernel chain)."""
    from jax.experimental import pallas as pl

    x, y, z, _ = p
    bsz = x.shape[1]
    if bsz < MIN_KERNEL_BATCH:
        from . import curve25519 as ge

        return ge.compress(p)
    lanes = lanes or min(LANES, bsz)
    pad = (-bsz) % lanes
    if pad:
        x, y, z = (jnp.pad(c, ((0, 0), (0, pad))) for c in (x, y, z))
    n = (bsz + pad) // lanes

    spec_fe = pl.BlockSpec((NLIMBS, lanes), lambda i: (0, i))
    spec_row = pl.BlockSpec((1, lanes), lambda i: (0, i))
    cy, sgn = pl.pallas_call(
        _compress_kernel,
        grid=(n,),
        in_specs=[spec_fe] * 3,
        out_specs=[spec_fe, spec_row],
        out_shape=[
            jax.ShapeDtypeStruct((NLIMBS, bsz + pad), jnp.int32),
            jax.ShapeDtypeStruct((1, bsz + pad), jnp.int32),
        ],
        interpret=interpret,
    )(x, y, z)
    if pad:
        cy, sgn = cy[:, :bsz], sgn[:, :bsz]
    out = jnp.moveaxis(cy, 0, -1).astype(jnp.uint8)
    signbit = (sgn[0] << 7).astype(jnp.uint8)
    return out.at[..., 31].set(out[..., 31] | signbit)
