"""Pallas TPU kernels for the Pippenger MSM (ops/msm.py fast path).

Two kernels replace the HBM-streamed XLA inner loops:

1. **Bucket fill** (`fill_buckets_pallas`): the (windows x buckets) lane
   grid lives in VMEM scratch across a sequential grid; every grid step
   streams one round's gathered points from HBM and performs ONE unified
   mixed point-add across all lanes. Points arrive in precomputed niels
   form (y+x, y-x, 2d*t, Z==1), cutting the add to 7 field muls — the
   same precomputation the reference bakes into its constant base tables
   (ref/fd_ed25519_ge.c precomp), applied here to runtime points.
   Invalid slots are staged as the niels identity (1, 1, 0), which the
   unified formulas absorb exactly — no masks in the hot loop.

2. **Bucket aggregation** (`aggregate_buckets_pallas`): sum_b b * S_b
   per window via the classic two-running-sums walk (b = 255 .. 1),
   sequential over the bucket axis but vectorized across windows on the
   lane axis — 510 point-adds on (32, nw)-lane tiles, microseconds in
   VMEM versus milliseconds if XLA streamed each through HBM.

The surrounding sort/gather staging and the final cross-window Horner
stay in XLA (gathers and fused elementwise chains are what XLA is good
at). See ops/msm.py for the algorithm-level description.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import fe25519 as fe

NLIMBS = fe.NLIMBS


def _tpu_compiler_params(**kw):
    """pltpu compiler-params across jax versions: 0.4.x exposes
    TPUCompilerParams, newer releases renamed it CompilerParams. The
    parked round-4 code used only the new name, so the kernels failed
    to TRACE on this image's jax — exactly the kind of rot the round-6
    un-park (and its CI smoke lane) exists to catch."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kw)


def _madd_niels(p, q_niels):
    """Unified mixed add: p extended (x, y, z, t) + q in niels form
    (yp = y+x, ym = y-x, t2d = 2d*t), q.Z == 1. 7 field muls."""
    x1, y1, z1, t1 = p
    yp2, ym2, t2d2 = q_niels
    a = fe.fe_mul_kernel(fe.fe_sub(y1, x1), ym2)
    b = fe.fe_mul_kernel(fe.fe_add(y1, x1), yp2)
    c = fe.fe_mul_kernel(t1, t2d2)
    d = fe.fe_add(z1, z1)
    e = fe.fe_sub(b, a)
    f = fe.fe_sub(d, c)
    g = fe.fe_add(d, c)
    h = fe.fe_add(b, a)
    return (fe.fe_mul_kernel(e, f), fe.fe_mul_kernel(g, h),
            fe.fe_mul_kernel(f, g), fe.fe_mul_kernel(e, h))


def _point_add_ext(p, q, d2):
    """Unified extended add (9 muls); d2 = limbs of 2d, (NLIMBS, 1)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe.fe_mul_kernel(fe.fe_sub(y1, x1), fe.fe_sub(y2, x2))
    b = fe.fe_mul_kernel(fe.fe_add(y1, x1), fe.fe_add(y2, x2))
    c = fe.fe_mul_kernel(fe.fe_mul_kernel(t1, t2), d2)
    zz = fe.fe_mul_kernel(z1, z2)
    d = fe.fe_add(zz, zz)
    e = fe.fe_sub(b, a)
    f = fe.fe_sub(d, c)
    g = fe.fe_add(d, c)
    h = fe.fe_add(b, a)
    return (fe.fe_mul_kernel(e, f), fe.fe_mul_kernel(g, h),
            fe.fe_mul_kernel(f, g), fe.fe_mul_kernel(e, h))


def _identity4(lanes):
    one = (jax.lax.broadcasted_iota(jnp.int32, (NLIMBS, lanes), 0) == 0)
    one = one.astype(jnp.int32)
    zero = jnp.zeros((NLIMBS, lanes), jnp.int32)
    return (zero, one, one, zero)


def fill_buckets_pallas(yp, ym, t2d, lane_tile: int = 2048,
                        interpret: bool = False):
    """Accumulate staged niels rounds into bucket points.

    yp/ym/t2d: (R, 32, L) int32 — round r's point for every
    (window, bucket) lane, identity-staged where the slot is empty.
    Returns extended bucket points (x, y, z, t), each (32, L).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_rounds, _, n_lanes = yp.shape
    if n_lanes % lane_tile:
        # fd_msm2 plan grids (windows x buckets, buckets not a power of
        # two for signed-magnitude plans) are staged to a multiple of
        # 256 lanes but rarely divide 2048: pick the largest divisor of
        # n_lanes that is a multiple of 128 and <= the requested tile,
        # falling back to the whole array as one tile (interpret/CPU).
        lane_tile = max(
            (t for t in range(128, min(lane_tile, n_lanes) + 1, 128)
             if n_lanes % t == 0),
            default=n_lanes,
        )
    n_tiles = n_lanes // lane_tile

    def kern(ypr, ymr, t2dr, ox, oy, oz, ot, xs, ys, zs, ts):
        ri = pl.program_id(1)

        @pl.when(ri == 0)
        def _init():
            x0, y0, z0, t0 = _identity4(lane_tile)
            xs[...] = x0
            ys[...] = y0
            zs[...] = z0
            ts[...] = t0

        p = (xs[...], ys[...], zs[...], ts[...])
        q = (ypr[0].astype(jnp.int32), ymr[0].astype(jnp.int32),
             t2dr[0].astype(jnp.int32))   # staged rounds ride HBM as int16
        x, y, z, t = _madd_niels(p, q)
        xs[...] = x
        ys[...] = y
        zs[...] = z
        ts[...] = t

        @pl.when(ri == n_rounds - 1)
        def _emit():
            ox[...] = x
            oy[...] = y
            oz[...] = z
            ot[...] = t

    spec_in = pl.BlockSpec((1, NLIMBS, lane_tile), lambda i, r: (r, 0, i))
    spec_out = pl.BlockSpec((NLIMBS, lane_tile), lambda i, r: (0, i))
    out_shape = jax.ShapeDtypeStruct((NLIMBS, n_lanes), jnp.int32)
    return pl.pallas_call(
        kern,
        grid=(n_tiles, n_rounds),
        in_specs=[spec_in] * 3,
        out_specs=[spec_out] * 4,
        out_shape=[out_shape] * 4,
        scratch_shapes=[
            pltpu.VMEM((NLIMBS, lane_tile), jnp.int32) for _ in range(4)
        ],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(yp, ym, t2d)


def mul_by_group_order_pallas(pt, d2_col, bits_col, interpret: bool = False):
    """[L]P over a (32, K)-lane point batch, fully in VMEM.

    The XLA version (msm._mul_by_group_order) is a 252-step lax.scan
    whose per-step while-loop overhead dwarfs its (32, K) arithmetic;
    here the double/conditional-add ladder runs inside one kernel with
    the point state resident in VMEM. L is public (vartime is fine) but
    the ladder is still branch-free: the conditional add is an
    arithmetic select so every lane runs the identical op stream.

    pt: (X, Y, Z, T) of (32, K) limbs. d2_col: (32, 1) limbs of 2d.
    bits_col: (256, 1) int32 — bits of L, MSB-first starting at index 0
    (bits_col[0] is the leading 1 bit), zero-padded after index
    n_bits-1 (the padding is never read; the loop bound is static).
    Returns (X, Y, Z, T) of (32, K) limbs of [L]P.
    """
    from jax.experimental import pallas as pl
    from firedancer_tpu.ops import sc25519 as sc

    n_bits = sc.L.bit_length()
    k = pt[0].shape[1]
    kpad = (-k) % 128
    if kpad:
        pt = tuple(jnp.pad(c, ((0, 0), (0, kpad))) for c in pt)
    lanes = k + kpad

    def kern(px, py, pz, pt_, d2r, bits, ox, oy, oz, ot):
        d2 = d2r[...]
        base = (px[...], py[...], pz[...], pt_[...])

        def body(i, r):
            r = _point_double_ext(r)
            added = _point_add_ext(r, base, d2)
            # Scalar select from SMEM: a vector (1, 1) bit would need a
            # sublane+lane broadcast, which Mosaic rejects; a scalar
            # broadcasts freely into the arithmetic select.
            sel = bits[i]
            return tuple(sel * a + (1 - sel) * c
                         for a, c in zip(added, r))

        # bits[0] is the leading 1: init = P, then n_bits-1 = 252
        # double/(conditional-)add steps.
        r = jax.lax.fori_loop(1, n_bits, body, base)
        ox[...] = r[0]
        oy[...] = r[1]
        oz[...] = r[2]
        ot[...] = r[3]

    from jax.experimental.pallas import tpu as pltpu

    spec_fe = pl.BlockSpec((NLIMBS, lanes), lambda: (0, 0))
    spec_d2 = pl.BlockSpec((NLIMBS, 1), lambda: (0, 0))
    spec_bits = pl.BlockSpec(memory_space=pltpu.SMEM)
    out_shape = jax.ShapeDtypeStruct((NLIMBS, lanes), jnp.int32)
    x, y, z, t = pl.pallas_call(
        kern,
        in_specs=[spec_fe] * 4 + [spec_d2, spec_bits],
        out_specs=[spec_fe] * 4,
        out_shape=[out_shape] * 4,
        interpret=interpret,
    )(*pt, d2_col, bits_col.reshape(-1))
    if kpad:
        x, y, z, t = (c[:, :k] for c in (x, y, z, t))
    return (x, y, z, t)


def _point_double_ext(p):
    """dbl-2008-hwcd a=-1 with fe_mul_unrolled (kernel-safe)."""
    from .pow_pallas import _sq

    x1, y1, z1, _ = p
    a = _sq(x1)
    b = _sq(y1)
    zz = _sq(z1)
    c = fe.fe_add(zz, zz)
    d_ = fe.fe_neg(a)
    e = fe.fe_sub(fe.fe_sub(_sq(fe.fe_add(x1, y1)), a), b)
    g = fe.fe_add(d_, b)
    f = fe.fe_sub(g, c)
    h = fe.fe_sub(d_, b)
    return (fe.fe_mul_kernel(e, f), fe.fe_mul_kernel(g, h),
            fe.fe_mul_kernel(f, g), fe.fe_mul_kernel(e, h))


def window_horner_pallas(w_res, d2_col, n_windows: int,
                         interpret: bool = False, w_bits: int = 7):
    """Cross-window Horner combine, fully in VMEM: the 2^(7t)-weighted
    sum of the per-window points, MSB-first (msm._window_horner is the
    XLA reference — an (n_windows-1)-step lax.scan whose per-step
    overhead on TPU dwarfs its (32, 1)-lane arithmetic).

    w_res: (X, Y, Z, T) of (32, nw) limbs, window t in column t.
    Returns (32, 1)-column points. Window columns are pre-broadcast in
    XLA to (nw*32, 128) row blocks so the in-kernel loop reads window t
    with one dynamic sublane-block slice (the dsm window-read pattern;
    dynamic LANE slicing is what Mosaic cannot do).
    """
    from jax.experimental import pallas as pl

    nw = n_windows

    def prep(c):
        # (32, nw) -> (nw*32, 128): window-major rows, lane-broadcast.
        return jnp.broadcast_to(
            jnp.transpose(c[:, :nw], (1, 0)).reshape(nw * NLIMBS, 1),
            (nw * NLIMBS, 128),
        )

    def kern(wx, wy, wz, wt, d2r, ox, oy, oz, ot):
        d2 = d2r[...]

        def col(j):
            return tuple(
                w[pl.ds(j * NLIMBS, NLIMBS), :] for w in (wx, wy, wz, wt)
            )

        def body(i, r):
            for _ in range(w_bits):
                r = _point_double_ext(r)
            return _point_add_ext(r, col(nw - 2 - i), d2)

        r = jax.lax.fori_loop(0, nw - 1, body, col(nw - 1))
        ox[...] = r[0]
        oy[...] = r[1]
        oz[...] = r[2]
        ot[...] = r[3]

    spec_w = pl.BlockSpec((nw * NLIMBS, 128), lambda: (0, 0))
    spec_d2 = pl.BlockSpec((NLIMBS, 1), lambda: (0, 0))
    spec_out = pl.BlockSpec((NLIMBS, 128), lambda: (0, 0))
    out_shape = jax.ShapeDtypeStruct((NLIMBS, 128), jnp.int32)
    x, y, z, t = pl.pallas_call(
        kern,
        in_specs=[spec_w] * 4 + [spec_d2],
        out_specs=[spec_out] * 4,
        out_shape=[out_shape] * 4,
        interpret=interpret,
    )(*(prep(c) for c in w_res), d2_col)
    return (x[:, :1], y[:, :1], z[:, :1], t[:, :1])


def aggregate_buckets_pallas(buckets, d2_col, interpret: bool = False):
    """sum_b b * S_b per window, running-sums walk (b = 255 .. 1).

    buckets: (x, y, z, t) each (n_buckets, 32, nw_pad) — bucket-major;
    the grid walks buckets top-down, streaming one (32, nw_pad) slice
    per step (auto double-buffered), with the two running sums (S =
    suffix bucket sum, T = the weighted answer) in VMEM scratch. Bucket
    0 is never visited (digit 0 contributes identity by construction).
    d2_col: (32, 1) int32 limbs of 2d (kernels can't capture constants).
    Returns (x, y, z, t) each (32, nw_pad).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_buckets, _, nw = buckets[0].shape
    n_steps = n_buckets - 1                    # buckets top .. 1

    def kern(bx, by, bz, bt, d2r, ox, oy, oz, ot, *scr):
        g = pl.program_id(0)
        d2 = d2r[...]
        q = (bx[0], by[0], bz[0], bt[0])
        sx, sy, sz, st_, tx, ty, tz, tt = scr

        @pl.when(g == 0)
        def _init():
            for r, v in zip(scr, q + q):
                r[...] = v

        @pl.when(g > 0)
        def _step():
            s = _point_add_ext((sx[...], sy[...], sz[...], st_[...]), q, d2)
            t_ = _point_add_ext((tx[...], ty[...], tz[...], tt[...]), s, d2)
            for r, v in zip(scr, s + t_):
                r[...] = v

        @pl.when(g == n_steps - 1)
        def _emit():
            ox[...] = tx[...]
            oy[...] = ty[...]
            oz[...] = tz[...]
            ot[...] = tt[...]

    spec_b = pl.BlockSpec(
        (1, NLIMBS, nw), lambda g: (n_buckets - 1 - g, 0, 0)
    )
    spec_d2 = pl.BlockSpec((NLIMBS, 1), lambda g: (0, 0))
    spec_out = pl.BlockSpec((NLIMBS, nw), lambda g: (0, 0))
    out_shape = jax.ShapeDtypeStruct((NLIMBS, nw), jnp.int32)
    return pl.pallas_call(
        kern,
        grid=(n_steps,),
        in_specs=[spec_b] * 4 + [spec_d2],
        out_specs=[spec_out] * 4,
        out_shape=[out_shape] * 4,
        scratch_shapes=[
            pltpu.VMEM((NLIMBS, nw), jnp.int32) for _ in range(8)
        ],
        compiler_params=_tpu_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(*buckets, d2_col)
