"""Batched Curve25519 (edwards25519) group operations for TPU.

Replaces the reference's ge (group element) layer
(/root/reference/src/ballet/ed25519/ref/fd_ed25519_ge.c, avx/fd_ed25519_ge.c)
with batch-uniform JAX: every lane executes the same instruction stream;
data-dependent branches (square-root failure, sign fix-up) become masks.

Representation: extended twisted-Edwards coordinates (X:Y:Z:T), T = XY/Z,
on -x^2 + y^2 = 1 + d x^2 y^2. Each coordinate is a (32, *batch) fe25519
limb array. The unified Hisil-Wong-Carter-Dawson a=-1 formulas are complete
(d nonsquare), so a single add routine covers doubling-adjacent cases for
arbitrary curve points, including the torsion points donna-style
decompression can produce — no per-lane special cases.

Scalar multiplication uses fixed 4-bit windows with one-hot table lookups
(a (16,B) one-hot contraction — the TPU analog of the reference's
constant-size precomp tables with CMOV selection), giving batch-uniform
control flow where the reference uses vartime sliding windows
(ref/fd_ed25519_ge.c:468).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ballet.ed25519 import oracle as _oracle
from . import fe25519 as fe

P = fe.P
D_INT = fe.D_INT


def _pow_auto():
    """Backend-select the field power chains (invert, pow22523): the
    VMEM-resident Pallas kernels on TPU (~5x the XLA graph's per-mul
    rate, see ops/pow_pallas.py), the XLA chain elsewhere."""
    from .backend import use_pallas

    if use_pallas("FD_POW_IMPL"):
        from .pow_pallas import fe_invert_pallas, fe_pow22523_pallas

        return fe_invert_pallas, fe_pow22523_pallas
    return fe.fe_invert, fe.fe_pow22523


def decompress_xla(y_bytes: jnp.ndarray, want_x_zero: bool = False):
    """XLA decompress with the optional x==0-mod-p mask — the shared
    fallback for decompress_auto and decompress_pallas's sub-tile path.
    The mask is computed on the PRE-poison decompressed x (failed lanes
    report that candidate x, not the identity's 0), bit-identical to the
    kernel path, so callers see one semantics across FD_DECOMPRESS_IMPL."""
    if want_x_zero:
        pt, ok, x_pre = decompress(y_bytes, want_x_pre=True)
        return pt, ok, fe.fe_is_zero(x_pre)
    pt, ok = decompress(y_bytes)
    return pt, ok


def decompress_auto(y_bytes: jnp.ndarray, want_x_zero: bool = False,
                    want_niels: bool = False):
    """Backend-dispatched decompress — since PR 14 a thin delegate to
    decompress_pallas.decompress_batched_auto (FD_DECOMPRESS_IMPL =
    auto|pallas|xla|interpret): the Montgomery-batched kernels/graph
    on eligible shapes, the staged per-lane-chain composition
    otherwise, bit-exact. want_x_zero appends the x==0-mod-p lane
    mask; want_niels (kernel path only) appends the (yp, ym, t2d,
    t2dn) niels-form arrays for the MSM fills."""
    from .decompress_pallas import decompress_batched_auto

    return decompress_batched_auto(y_bytes, want_x_zero=want_x_zero,
                                   want_niels=want_niels)


def small_order_mask(p):
    """Lane mask: 8*P == identity (order divides the cofactor), the
    reference's fd_ed25519_ge_p3_is_small_order (fd_ed25519_ge.c:62-66)
    as 3 batched doublings + projective identity test."""
    t = p
    for _ in range(3):
        t = point_double(t, need_t=False)
    x8, y8, z8, _ = t
    return fe.fe_is_zero(x8) & fe.fe_is_zero(fe.fe_sub(y8, z8))


def point_eq_affine_xla(aff, proj):
    """Lane mask: affine point (ax, ay) equals projective (X:Y:Z).
    The reference verify's final compare (fd_ed25519_user.c:424-430):
    ax*Z == X and ay*Z == Y — no inversion."""
    ax, ay = aff
    x, y, z, _ = proj
    return (fe.fe_is_zero(fe.fe_sub(fe.fe_mul(ax, z), x))
            & fe.fe_is_zero(fe.fe_sub(fe.fe_mul(ay, z), y)))


def decompress_so_auto(y_bytes: jnp.ndarray):
    """Decompress + small-order lane mask, backend-dispatched (the
    batched engines compute the mask on the just-decompressed point
    while it is VMEM/cache-resident). Failed lanes carry the identity
    poison and so read small_order=True — callers must gate on ok
    first (the verify status ladder does)."""
    from .decompress_pallas import decompress_batched_auto

    return decompress_batched_auto(y_bytes, want_small_order=True)


def point_eq_affine_auto(aff, proj):
    """Backend-dispatched affine-vs-projective point equality."""
    from .backend import use_pallas

    if use_pallas("FD_COMPRESS_IMPL"):
        from .curve_pallas import point_eq_affine_pallas

        return point_eq_affine_pallas(aff, proj)
    return point_eq_affine_xla(aff, proj)


def compress_auto(p) -> jnp.ndarray:
    """Backend-dispatched compress: fused Pallas kernel on TPU."""
    from .backend import use_pallas

    if use_pallas("FD_COMPRESS_IMPL"):
        from .curve_pallas import compress_pallas

        return compress_pallas(p)
    return compress(p)


def identity(batch_shape):
    return (
        fe.fe_zero(batch_shape),
        fe.fe_one(batch_shape),
        fe.fe_one(batch_shape),
        fe.fe_zero(batch_shape),
    )


def point_add(p, q, need_t: bool = True):
    """Unified extended-coordinates addition (complete for a=-1, d nonsq).

    need_t=False skips the T-coordinate product (one fe_mul) when the
    consumer is a doubling or compress — the same elision wiredancer's
    fixed pipeline hardwires and the reference's p1p1->p2 conversions get
    for free (fd_ed25519_private.h reprs).
    """
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    # 2d constant, rank-adapted so multi-dim batch shapes (e.g. the MSM's
    # (windows, buckets) lanes) broadcast correctly.
    d2 = fe.FE_D2.reshape((fe.NLIMBS,) + (1,) * (x1.ndim - 1))
    a = fe.fe_mul(fe.fe_sub(y1, x1), fe.fe_sub(y2, x2))
    b = fe.fe_mul(fe.fe_add(y1, x1), fe.fe_add(y2, x2))
    c = fe.fe_mul(fe.fe_mul(t1, t2), d2)
    d_ = fe.fe_add(fe.fe_mul(z1, z2), fe.fe_mul(z1, z2))
    e = fe.fe_sub(b, a)
    f = fe.fe_sub(d_, c)
    g = fe.fe_add(d_, c)
    h = fe.fe_add(b, a)
    t = fe.fe_mul(e, h) if need_t else None
    return fe.fe_mul(e, f), fe.fe_mul(g, h), fe.fe_mul(f, g), t


def point_double(p, need_t: bool = True):
    """dbl-2008-hwcd with a=-1. Input T is never read; need_t=False skips
    producing it (doubling chains only need T on the last step)."""
    x1, y1, z1, _ = p
    a = fe.fe_sq(x1)
    b = fe.fe_sq(y1)
    c = fe.fe_add(fe.fe_sq(z1), fe.fe_sq(z1))
    d_ = fe.fe_neg(a)
    e = fe.fe_sub(fe.fe_sub(fe.fe_sq(fe.fe_add(x1, y1)), a), b)
    g = fe.fe_add(d_, b)
    f = fe.fe_sub(g, c)
    h = fe.fe_sub(d_, b)
    t = fe.fe_mul(e, h) if need_t else None
    return fe.fe_mul(e, f), fe.fe_mul(g, h), fe.fe_mul(f, g), t


def point_neg(p):
    x, y, z, t = p
    return fe.fe_neg(x), y, z, fe.fe_neg(t)


def point_select(mask, p, q):
    """Lane-wise select between two points (mask shape = batch)."""
    return tuple(fe.fe_select(mask, a, b) for a, b in zip(p, q))


def decompress(y_bytes: jnp.ndarray, want_x_pre: bool = False):
    """Batch point decompression, donna semantics (ref fd_ed25519_ge.c:242).

    y_bytes: (*batch, 32) uint8 encodings.
    Returns ((X, Y, Z, T), ok_mask). Failed lanes carry the identity point
    (harmless poison) with ok=False. Accepts non-canonical y and x==0 with
    either sign, exactly like the reference. want_x_pre=True appends the
    pre-poison x limbs (what the Pallas kernel's x==0 mask is computed on).
    """
    sign = (y_bytes[..., 31] >> 7).astype(jnp.int32)          # (*batch,)
    y = fe.fe_from_bytes(y_bytes, mask_high_bit=True)
    z = fe.fe_one(y.shape[1:])
    u = fe.fe_sub(fe.fe_sq(y), z)                              # y^2 - 1
    v = fe.fe_add(fe.fe_mul(fe.fe_sq(y), fe.FE_D), z)          # d y^2 + 1

    _, pow22523 = _pow_auto()
    v3 = fe.fe_mul(fe.fe_sq(v), v)
    uv7 = fe.fe_mul(fe.fe_mul(fe.fe_sq(v3), v), u)             # u v^7
    x = fe.fe_mul(fe.fe_mul(pow22523(uv7), v3), u)             # u v^3 (uv^7)^((p-5)/8)

    vxx = fe.fe_mul(fe.fe_sq(x), v)
    root_ok = fe.fe_eq(vxx, u)                                 # vx^2 == u
    neg_ok = fe.fe_eq(vxx, fe.fe_neg(u))                       # vx^2 == -u
    x = fe.fe_select(root_ok, x, fe.fe_mul(x, fe.FE_SQRT_M1))
    ok = root_ok | neg_ok

    # Match requested sign (parity of canonical x); for x==0 this is a no-op
    # in effect because -0 == 0.
    flip = fe.fe_is_negative(x) != (sign == 1)
    x = fe.fe_select(flip, fe.fe_neg(x), x)

    t = fe.fe_mul(x, y)
    pt = (x, y, z, t)
    sel = point_select(ok, pt, identity(y.shape[1:]))
    if want_x_pre:
        return sel, ok, x
    return sel, ok


def compress(p) -> jnp.ndarray:
    """(X:Y:Z:T) -> canonical 32-byte encoding (*batch, 32) uint8."""
    x, y, z, _ = p
    invert, _ = _pow_auto()
    if z.ndim == 2 and z.shape[1] >= 256:
        # Grouped Montgomery trick: ~3 muls/lane + one power chain per
        # 64 lanes (Z != 0 mod p always holds for group elements).
        zinv = fe.fe_invert_batch(z, invert_fn=invert)
    else:
        zinv = invert(z)
    ax = fe.fe_mul(x, zinv)
    ay = fe.fe_mul(y, zinv)
    out = fe.fe_to_bytes(ay)
    signbit = fe.fe_is_negative(ax).astype(jnp.uint8) << 7
    return out.at[..., 31].set(out[..., 31] | signbit)


def _windows_from_bytes(scalar_bytes: jnp.ndarray) -> jnp.ndarray:
    """(*batch, 32) uint8 -> (64, *batch) int32 4-bit windows, LSB first."""
    b = jnp.moveaxis(scalar_bytes.astype(jnp.int32), -1, 0)   # (32, *batch)
    lo = b & 0xF
    hi = (b >> 4) & 0xF
    return jnp.stack([lo, hi], axis=1).reshape((64,) + b.shape[1:])


def _table_lookup(table, onehot):
    """table: tuple of 4 arrays (16, 32, B); onehot: (16, B) int32."""
    return tuple(
        jnp.einsum("tb,tlb->lb", onehot, coord,
                   preferred_element_type=jnp.int32)
        for coord in table
    )


def _build_table(p):
    """[0..15]*P as stacked coordinates: 4 arrays of (16, 32, B)."""
    batch = p[0].shape[1:]
    pts = [identity(batch), p]
    for j in range(2, 16):
        if j % 2 == 0:
            pts.append(point_double(pts[j // 2]))
        else:
            pts.append(point_add(pts[j - 1], p))
    return tuple(
        jnp.stack([pt[c] for pt in pts], axis=0) for c in range(4)
    )


def _base_point_table() -> tuple:
    """[0..15]*B as numpy constants, shape (16, 32, 1) each coordinate.

    Built with the oracle's affine arithmetic (one source of curve truth).
    """
    pts = [(0, 1), _oracle.B]
    for _ in range(14):
        pts.append(_oracle.point_add(pts[-1], _oracle.B))
    coords = []
    for c in range(4):
        rows = []
        for (x, y) in pts:
            val = [x, y, 1, x * y % P][c]
            rows.append([(val >> (8 * i)) & 0xFF for i in range(32)])
        coords.append(jnp.asarray(np.asarray(rows, np.int32)[:, :, None]))
    return tuple(coords)


_B_TABLE = _base_point_table()


def double_scalarmult(h_bytes, a_point, s_bytes, n_windows: int = 64):
    """R = h*A + s*Base, batch-uniform fixed windows.

    h_bytes, s_bytes: (*batch, 32) uint8 little-endian scalars (< 2^256; for
    verify they are canonical mod L). a_point: decompressed batch point.
    Replaces ge_double_scalarmult_vartime (ref/fd_ed25519_ge.c:468) with a
    fixed schedule: 64 windows x (4 doublings + 2 table adds).
    n_windows < 64 processes only the MSB-side windows (test harness knob
    for cross-checking the Pallas kernel without 64 interpreted rounds).
    """
    batch = a_point[0].shape[1:]
    hw = _windows_from_bytes(h_bytes)                         # (64, *batch)
    sw = _windows_from_bytes(s_bytes)
    a_table = _build_table(a_point)
    b_table = tuple(jnp.broadcast_to(c, (16, 32) + batch).astype(jnp.int32)
                    for c in _B_TABLE)

    idx16 = jnp.arange(16, dtype=jnp.int32)

    def step(r3, wins):
        whi, wsi = wins
        r = (*r3, None)  # T is never read by doublings
        for _ in range(3):
            r = point_double(r, need_t=False)
        r = point_double(r, need_t=True)
        oh_h = (idx16[:, None] == whi[None, :]).astype(jnp.int32)
        r = point_add(r, _table_lookup(a_table, oh_h), need_t=True)
        oh_s = (idx16[:, None] == wsi[None, :]).astype(jnp.int32)
        x, y, z, _ = point_add(r, _table_lookup(b_table, oh_s), need_t=False)
        return (x, y, z), None

    # MSB-first over the 64 windows.
    ident = identity(batch)
    r3, _ = jax.lax.scan(
        step, ident[:3], (hw[::-1][:n_windows], sw[::-1][:n_windows])
    )
    # T of the result is never computed (compress reads X/Y/Z only).
    # Return None as a sentinel rather than a plausible-looking zero so any
    # future consumer that feeds this into point_add (which reads T) fails
    # loudly instead of silently computing a wrong point.
    return (*r3, None)
