"""Batched multi-scalar multiplication (Pippenger) on TPU.

Computes sum_i c_i * P_i for a batch of per-lane scalars and points with
ONE shared doubling chain — the structural cost that per-lane
double-scalar-mult (ops/curve25519.double_scalarmult) cannot amortize.
This is the engine behind RLC batch verification (ops/verify_rlc.py).

Shape of the computation (w = 8-bit windows, byte-aligned so digit
extraction is free):

1. **Bucket fill.** For every window t, lane digits d_i route point P_i
   into bucket (t, d_i). The fill is batch-uniform: a static number of
   ROUNDS, each adding one gathered point per (window, bucket) lane —
   lanes are (n_windows x 256) wide, so every round is one unified
   point_add across all windows at once. Slot indices are built by a
   stable argsort per window + rank-within-bucket arithmetic (gathers
   only, no scatters — TPU-friendly).
2. **Bucket aggregation.** sum_b b * S_b via bit decomposition:
   sum_k 2^k (sum over buckets with bit k set), each inner sum a
   pairwise tree-reduce over the bucket axis — log-depth, batch-uniform.
3. **Cross-window Horner.** S = 2^8 * S + W_t, MSB-first; (32, 1)-lane
   elementwise chains that XLA fuses.

Data-dependence escape hatch: the fill uses a STATIC round count
(max_rounds). If any bucket receives more points (Poisson tail, or
adversarially-biased digits of h), the fill would be incomplete — the
function detects this and reports ok=False so the caller falls back to
the exact per-lane path. Never a wrong result, only a slow path.

Reference basis: Pippenger's algorithm (public-domain technique; cf.
the batched bucket MSMs in GPU ZK provers), re-shaped for TPU: no
atomics, no scatters, unified complete adds, one-hot-free gathers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import curve25519 as ge
from . import fe25519 as fe
from .msm_recode import madd_niels_lazy, recode_signed
from firedancer_tpu.msm_plan import (
    BASELINE_PLAN, MsmPlan, PLAN_WIDTHS, parse_plan, plan_buckets,
    plan_windows,
)

W_BITS = 7
N_BUCKETS = 1 << W_BITS
# Window counts are chosen so EVERY window of the scalar distribution is
# either uniform or almost-always-zero — a top window whose digits
# concentrate on a few NONZERO values overloads those buckets and forces
# the static-round fill into its fallback (zero digits are free: bucket
# 0 is never accumulated). 252 = 36*7, so scalars mod L (< 2^252 + eps)
# are uniform in windows 0..35 and ~always 0 in window 36; RLC z weights
# are drawn < 2^126 = 2^(18*7) so all 18 windows are uniform.
WINDOWS_128 = 19   # any 128-bit scalar (window 18 in {0..3})
WINDOWS_Z = 18     # RLC z weights: uniform < 2^126
WINDOWS_253 = 37   # scalars mod L

# Scalar bit-widths keyed by the BASELINE (w=7) window count callers
# pass — the public n_windows argument stays the u7 vocabulary
# (WINDOWS_Z / WINDOWS_128 / WINDOWS_253) and a non-default MsmPlan
# re-derives its own window count from the underlying scalar width via
# msm_plan.plan_windows. Unknown counts fall back to 7 * n_windows.
SCALAR_BITS = {WINDOWS_Z: 126, WINDOWS_128: 128, WINDOWS_253: 253}


def active_plan() -> MsmPlan:
    """The MsmPlan selected by the FD_MSM_* flags — the device-ops
    alias for msm_plan.plan_from_flags (one resolution rule; the
    jax-free engine registry calls the msm_plan spelling)."""
    from firedancer_tpu.msm_plan import plan_from_flags

    return plan_from_flags()


def _digits(scalars_bytes: jnp.ndarray, n_windows: int,
            w_bits: int = W_BITS) -> jnp.ndarray:
    """(B, 32) uint8 -> (n_windows, B) int32 w_bits-wide windows, LSB
    first. Any w_bits <= 8 works: a window spans at most two bytes
    (sh + w_bits <= 15), so the two-byte splice below covers it."""
    b = jnp.moveaxis(scalars_bytes.astype(jnp.int32), -1, 0)  # (32, B)
    zero = jnp.zeros_like(b[0])
    outs = []
    for w in range(n_windows):
        bit = w_bits * w
        i, sh = bit >> 3, bit & 7
        lo = b[i] if i < 32 else zero
        hi = b[i + 1] if i + 1 < 32 else zero
        outs.append(((lo + (hi << 8)) >> sh) & ((1 << w_bits) - 1))
    return jnp.stack(outs)


def _reduce_pairs(pt, n):
    """Tree-reduce a (..., n) bucket axis by pairwise point_add. Odd
    widths (signed-magnitude grids are 2^(w-1)+1 wide) split off their
    leading element into a carry folded back at the end — for powers of
    two the op sequence is exactly the historical halving tree."""
    carry = None
    while n > 1:
        if n % 2:
            head = tuple(c[..., :1] for c in pt)
            carry = head if carry is None else ge.point_add(carry, head)
            pt = tuple(c[..., 1:] for c in pt)
            n -= 1
        a = tuple(c[..., 0::2] for c in pt)
        b = tuple(c[..., 1::2] for c in pt)
        pt = ge.point_add(a, b)
        n //= 2
    return pt if carry is None else ge.point_add(pt, carry)


def _default_rounds(bsz: int, n_buckets: int = N_BUCKETS,
                    signed: bool = False) -> int:
    # Poisson tail bound: with uniform digits each nonzero bucket holds
    # ~lam = B/(n_buckets-1) points; lam + 7*sqrt(lam) + 8 puts the
    # per-batch overflow probability below ~1e-7 even across thousands
    # of buckets. Adversarially-biased digits only cost the fallback.
    # Signed callers pass the LIVE magnitude count 2^(w-1) (bucket 0 is
    # dead; each live bucket catches digit rate 2/2^w). The formula
    # lives in firedancer_tpu/msm_plan.py (stdlib-only) so the bench
    # orchestrator's fill-efficiency predictions can never drift from
    # the engine's actual round count.
    from firedancer_tpu.msm_plan import default_rounds

    return default_rounds(bsz, n_buckets, signed=signed)


def _plan_dims(n_windows: int, bsz: int, plan: MsmPlan,
               _force_windows: int | None = None):
    """(nw, n_buckets, default max_rounds) for a non-baseline plan,
    re-derived from the scalar width behind the caller's baseline
    window count. _force_windows is the search harness's parity-control
    knob ONLY (a signed plan at the unsigned count drops the carry
    window — the negative control the gate must catch)."""
    scalar_bits = SCALAR_BITS.get(n_windows, W_BITS * n_windows)
    nw = plan_windows(scalar_bits, plan.w, plan.signed)
    if _force_windows is not None:
        nw = _force_windows
    nb = plan_buckets(plan)
    live = (1 << (plan.w - 1)) if plan.signed else nb
    return nw, nb, _default_rounds(bsz, live, signed=plan.signed)


def _neg_table(neg_flags: jnp.ndarray, idx: jnp.ndarray,
               bsz: int) -> jnp.ndarray:
    """Gather per-lane sign flags through the slot table: neg[t, b, r]
    is True iff slot (t, b, r) holds a lane whose signed digit was
    negative (empty slots are False — identity has no sign)."""
    nw, nb, rounds = idx.shape
    safe = jnp.clip(idx.reshape(nw, -1), 0, bsz - 1)
    neg = jnp.take_along_axis(neg_flags, safe, axis=1).reshape(
        nw, nb, rounds
    )
    return neg & (idx >= 0)


def _top_tree_planes(n_windows: int, nw: int, plan: MsmPlan) -> int:
    """Bit planes for the plan's TOP window when it must bypass the
    bucket grid, else 0. The static-round Poisson bound prices UNIFORM
    w-bit digits; a top window covering r < w significant scalar bits
    concentrates its mass on 2^r values (signed recode is the worst
    case: the final borrow lands ~B/2 lanes on magnitude 1), so at
    production B that one window deterministically overflows a round
    count the other windows never approach. Such windows are instead
    summed directly (_top_window_sum) — digits there are in [0, 2^r]
    (signed; the borrow can add 1) or [0, 2^r) (unsigned), so r+1 / r
    bit planes suffice. r >= w means the top window is a full uniform
    digit and the grid handles it (the baseline geometry); r < 0 only
    under the search harness's _force_windows truncation control,
    which must keep the plain (wrong-by-construction) grid path."""
    scalar_bits = SCALAR_BITS.get(n_windows, W_BITS * n_windows)
    r = scalar_bits - plan.w * (nw - 1)
    if r < 0 or r >= plan.w:
        return 0
    return r + 1 if plan.signed else r


def _top_window_sum(top_digits, points, planes: int):
    """W_top = sum_i top_i * P_i by MSB-first bit-plane masked tree
    reduction over the LANE axis: planes x (select + pairwise point_add
    tree) + one tiny doubling ladder — exact for any digit values, no
    round bound to overflow. O(planes * B) add-lanes in O(planes *
    log B) sequential depth; at the shapes that need it (planes <= 7)
    this is ~1% of the bucket fill's lane count."""
    bsz = points[0].shape[1]
    ident_b = ge.identity((bsz,))
    acc = ge.identity((1,))
    for k in range(planes - 1, -1, -1):
        m = ((top_digits >> k) & 1) == 1
        masked = ge.point_select(m, points, ident_b)
        t_k = _reduce_pairs(masked, bsz)
        acc = ge.point_add(ge.point_double(acc), t_k)
    return acc                                             # (32, 1)


def _plan_staging(scalars_bytes, bsz: int, max_rounds: int, nw: int,
                  n_buckets: int, plan: MsmPlan, tree_planes: int = 0):
    """Digit extraction + (for signed plans) balanced recode + magnitude
    bucketing: returns (idx, neg, ok, top) with neg None on unsigned
    plans. Signed digits route |d| into bucket |d| (dead bucket 0, live
    magnitudes 1..2^(w-1)) and fold the sign into the gather — the
    certified recode (ops/msm_recode.py) guarantees |d| <= 2^(w-1), so
    the magnitude grid is exactly plan_buckets wide. When tree_planes >
    0 the top window's digit row is split off for _top_window_sum (top)
    and the grid stages only the nw-1 uniform windows."""
    d = _digits(scalars_bytes, nw, plan.w)
    s = recode_signed(d, plan.w) if plan.signed else d
    top = None
    if tree_planes:
        top, s = s[nw - 1], s[:nw - 1]
    if not plan.signed:
        idx, ok = _staging_from_digits(s, bsz, max_rounds, n_buckets)
        return idx, None, ok, top
    idx, ok = _staging_from_digits(jnp.abs(s), bsz, max_rounds, n_buckets)
    return idx, _neg_table(s < 0, idx, bsz), ok, top


def combine_stacked(pt):
    """Fold a leading-axis stack of point partials ((N, ...) limb
    arrays per coordinate) into their group sum with unified adds, in
    stack order — the one folding rule every cross-shard combine path
    (monolithic all_gather and the fd_pod split tail alike) goes
    through, so the two compositions can never drift bit-wise."""
    n = pt[0].shape[0]
    acc = tuple(c[0] for c in pt)
    for d in range(1, n):
        acc = ge.point_add(acc, tuple(c[d] for c in pt))
    return acc


def _gather_point_sum(pt, axis_name: str):
    """Combine per-device point partials into the global sum, on every
    device: all_gather the (X, Y, Z, T) limb arrays over the mesh axis
    and point_add the device slices. Point addition is the GROUP
    operation, so a raw psum cannot combine partials — but the partials
    are tiny ((32, nw) limbs per coordinate), so gather + a handful of
    unified adds costs microseconds against the milliseconds of bucket
    work they summarize. This is the only cross-device traffic in the
    sharded MSM."""
    g = tuple(jax.lax.all_gather(c, axis_name) for c in pt)  # (N, ...)
    return combine_stacked(g)


def _all_shards_ok(ok, axis_name: str):
    """Global AND of a per-shard () bool (fill-overflow flags: ONE
    overflowing shard invalidates the whole batch result)."""
    return jnp.all(jax.lax.all_gather(ok, axis_name))


def _staging_indices(scalars_bytes, n_windows: int, bsz: int,
                     max_rounds: int):
    """Slot table for the bucket fill: (idx, ok) where idx[t, b, r] is
    the lane of the r-th point in bucket (t, b) or -1, and ok is False
    iff some bucket overflowed max_rounds."""
    d = _digits(scalars_bytes, n_windows)                 # (nw, B)
    return _staging_from_digits(d, bsz, max_rounds)


def _staging_from_digits(d: jnp.ndarray, bsz: int, max_rounds: int,
                         n_buckets: int = N_BUCKETS):
    """As _staging_indices, but from an explicit (nw, B) int32 digit
    array in [0, n_buckets) — each row an independent weighting of the
    same points (used by the torsion subgroup check, where rows are
    independent random trials rather than positional windows)."""
    nw = d.shape[0]
    order = jnp.argsort(d, axis=1, stable=True)           # (nw, B)
    sorted_d = jnp.take_along_axis(d, order, axis=1)

    # starts[t, b] = first sorted position of digit b in window t.
    buckets = jnp.arange(n_buckets, dtype=jnp.int32)
    starts = jax.vmap(
        lambda row: jnp.searchsorted(row, buckets, side="left")
    )(sorted_d)                                           # (nw, n_buckets)
    ends = jnp.concatenate(
        [starts[:, 1:], jnp.full((nw, 1), bsz, starts.dtype)], axis=1
    )
    counts = ends - starts                                # (nw, n_buckets)
    ok = jnp.max(jnp.where(buckets[None, :] > 0, counts, 0)) <= max_rounds

    # Slot table: idx[t, b, r] = lane index of the r-th point in bucket
    # (t, b), or -1. Bucket 0 contributes nothing (digit 0 == identity).
    r_iota = jnp.arange(max_rounds, dtype=jnp.int32)
    pos = starts[:, :, None] + r_iota[None, None, :]      # (nw, nb, R)
    valid = (r_iota[None, None, :] < counts[:, :, None]) & (
        buckets[None, :, None] > 0
    )
    pos_flat = jnp.clip(pos.reshape(nw, -1), 0, bsz - 1)
    idx = jnp.take_along_axis(order, pos_flat, axis=1).reshape(
        nw, n_buckets, max_rounds
    )
    idx = jnp.where(valid, idx, -1)                       # (nw, nb, R)
    return idx, ok


def msm(scalars_bytes: jnp.ndarray, points, n_windows: int,
        max_rounds: int | None = None, axis_name: str | None = None,
        plan: MsmPlan | None = None):
    """sum_i scalars_i * P_i (XLA reference path).

    scalars_bytes: (B, 32) uint8 little-endian (windows beyond
      n_windows must be zero). points: (X, Y, Z, T) of (32, B) limbs.
    axis_name (under shard_map): B is the LOCAL lane count; the
      per-window bucket sums are combined across the mesh before the
      Horner tail, so the returned point is the global MSM over all
      shards' lanes (replicated), and ok is the global fill verdict.
    plan (None = active_plan()): the fd_msm2 schedule. BASELINE_PLAN
      runs the historical u7 path bit-identically; lazy plans require
      points with Z == 1 (decompress output / affine constants — the
      niels fill's mixed add assumes it, exactly like msm_fast).
    Returns (point, ok): point is (X, Y, Z, T) of (32, 1) limbs; ok is a
      () bool — False iff a bucket overflowed max_rounds (result then
      invalid; caller must use the exact path).
    """
    if plan is None:
        plan = active_plan()
    w_res, ok = msm_partial(scalars_bytes, points, n_windows,
                            max_rounds=max_rounds, plan=plan)
    return msm_combine(w_res, ok, n_windows, axis_name=axis_name,
                       plan=plan)


def msm_partial(scalars_bytes: jnp.ndarray, points, n_windows: int,
                max_rounds: int | None = None,
                plan: MsmPlan | None = None,
                _force_windows: int | None = None):
    """The LOCAL half of msm(): digit staging + bucket fill + per-window
    bucket aggregation over this shard's lanes only — no collectives, no
    doubling-chain tails. Returns (w_res, ok): w_res a (32, nw)-limb
    point per window (W_t = sum over local lanes; nw is the PLAN's
    window count — n_windows for the baseline), ok the local fill
    verdict. msm_combine finishes the job; fd_pod's split-step
    dispatcher jits the two halves separately so batch k's combine can
    execute while batch k+1's fill is already dispatched."""
    if plan is None:
        plan = active_plan()
    bsz = points[0].shape[1]
    if plan == BASELINE_PLAN and _force_windows is None:
        if max_rounds is None:
            max_rounds = _default_rounds(bsz)
        idx, ok = _staging_indices(scalars_bytes, n_windows, bsz,
                                   max_rounds)
        return _fill_and_aggregate(idx, points, max_rounds,
                                   n_windows), ok
    nw, nb, rounds = _plan_dims(n_windows, bsz, plan, _force_windows)
    if max_rounds is None:
        max_rounds = rounds
    planes = _top_tree_planes(n_windows, nw, plan)
    idx, neg, ok, top = _plan_staging(scalars_bytes, bsz, max_rounds, nw,
                                      nb, plan, tree_planes=planes)
    nw_grid = nw - 1 if planes else nw
    if plan.lazy:
        w_res = _fill_and_aggregate_lazy(idx, neg, points, max_rounds,
                                         nw_grid, nb, plan.w)
    else:
        w_res = _fill_and_aggregate(idx, points, max_rounds, nw_grid,
                                    n_buckets=nb, w_bits=plan.w)
    if planes:
        w_top = _top_window_sum(top, points, planes)
        w_res = tuple(jnp.concatenate([c, ct], axis=1)
                      for c, ct in zip(w_res, w_top))
    return w_res, ok


def msm_combine(w_res, ok, n_windows: int, axis_name: str | None = None,
                plan: MsmPlan | None = None):
    """The TAIL half of msm(): combine per-shard window partials across
    the mesh (axis_name; identity when None) and run the cross-window
    Horner doubling chain (plan.w doublings per window — the window
    count itself is read off w_res, so both halves agree by shape).
    msm() == msm_combine(*msm_partial(...)) by construction — the
    composition is the exact op sequence the monolithic path always
    ran, so the split is bit-exact."""
    if plan is None:
        plan = active_plan()
    if axis_name is not None:
        w_res = _gather_point_sum(w_res, axis_name)
        ok = _all_shards_ok(ok, axis_name)
    return _window_horner(w_res, w_res[0].shape[1], w_bits=plan.w), ok


def _aggregate_windows(acc, nw: int, n_buckets: int, w_bits: int):
    """Per-window bucket aggregation over a filled (32, nw*nb) lane
    accumulator: W_t = sum_b b * S_{t,b} = sum_k 2^k * (sum_{b: bit k
    set} S_b). A lax.scan over the bit masks keeps the traced graph
    ~w_bits x smaller than unrolling (this path must stay compilable on
    CPU test hosts). Works for any bucket-index range < 2^w_bits —
    signed-magnitude grids (max index 2^(w-1)) included."""
    s_buckets = tuple(
        c.reshape(fe.NLIMBS, nw, n_buckets) for c in acc
    )
    buckets = jnp.arange(n_buckets, dtype=jnp.int32)
    ident_nb = ge.identity((nw, n_buckets))
    bit_masks = jnp.stack([
        jnp.broadcast_to((((buckets >> k) & 1) == 1)[None, :],
                         (nw, n_buckets))
        for k in range(w_bits - 1, -1, -1)
    ])                                                     # (w_bits, nw, nb)

    def agg_step(carry, bit):
        masked = ge.point_select(bit, s_buckets, ident_nb)
        t_k = _reduce_pairs(masked, n_buckets)             # (32, nw, 1)
        t_k = tuple(c[..., 0] for c in t_k)                # (32, nw)
        out = ge.point_add(ge.point_double(carry), t_k)
        return out, None

    w_res, _ = jax.lax.scan(agg_step, ge.identity((nw,)), bit_masks)
    return w_res


def _fill_and_aggregate(idx, points, max_rounds: int, nw: int,
                        n_buckets: int = N_BUCKETS,
                        w_bits: int = W_BITS):
    """Bucket fill + per-window bucket aggregation (XLA path): returns
    w_res, a (32, nw)-limb point per window, W_t = sum_b b * S_{t,b}.
    Defaults are the historical u7 grid — bit-identical graph."""
    bsz = points[0].shape[1]
    lanes = nw * n_buckets
    ident = ge.identity((lanes,))

    def fill_round(r, acc):
        sel = jax.lax.dynamic_index_in_dim(
            idx, r, axis=2, keepdims=False
        ).reshape(lanes)                                   # (L,)
        m = sel >= 0
        safe = jnp.clip(sel, 0, bsz - 1)
        q = tuple(c[:, safe] for c in points)
        q = ge.point_select(m, q, ident)
        # Adding the identity is exact under the unified formulas, so a
        # plain add-then-keep is fine; select keeps masked lanes stable.
        return ge.point_select(m, ge.point_add(acc, q), acc)

    acc = jax.lax.fori_loop(0, max_rounds, fill_round, ident)
    return _aggregate_windows(acc, nw, n_buckets, w_bits)


def _fill_and_aggregate_lazy(idx, neg, points, max_rounds: int, nw: int,
                             n_buckets: int, w_bits: int):
    """The fd_msm2 lazy niels fill (XLA path): 7-mul mixed adds through
    the certified madd_niels_lazy (ops/msm_recode.py) instead of the
    9-mul unified extended add, with the sign of a signed digit folded
    into the gather (yp <-> ym swap + t2d negation — one elementwise
    select, no extra field ops). Empty slots gather the identity niels
    (1, 1, 0), which scales the accumulator's representation
    projectively (same group element) — NO per-round point_select, so
    the whole round is madd-only. REQUIRES points with Z == 1 (the
    mixed add assumes it). neg: (nw, nb, R) bool from _neg_table, or
    None for unsigned plans."""
    bsz = points[0].shape[1]
    lanes = nw * n_buckets
    x, y, z, t = points
    yp = fe.fe_add(y, x)
    ym = fe.fe_sub(y, x)
    t2d = fe.fe_mul(t, fe.FE_D2)
    one0 = (jnp.arange(fe.NLIMBS, dtype=jnp.int32) == 0)[:, None]
    one0 = one0.astype(jnp.int32)

    idx_r = jnp.transpose(idx, (2, 0, 1)).reshape(max_rounds, lanes)
    neg_r = (jnp.transpose(neg, (2, 0, 1)).reshape(max_rounds, lanes)
             if neg is not None else None)

    def fill_round(r, acc):
        sel = jax.lax.dynamic_index_in_dim(idx_r, r, axis=0,
                                           keepdims=False)
        m = (sel >= 0)[None, :]
        safe = jnp.clip(sel, 0, bsz - 1)
        gyp = jnp.where(m, yp[:, safe], one0)
        gym = jnp.where(m, ym[:, safe], one0)
        gtd = jnp.where(m, t2d[:, safe], 0)
        if neg_r is not None:
            ng = jax.lax.dynamic_index_in_dim(
                neg_r, r, axis=0, keepdims=False
            )[None, :]
            gyp, gym = (jnp.where(ng, gym, gyp),
                        jnp.where(ng, gyp, gym))
            gtd = jnp.where(ng, -gtd, gtd)
        return madd_niels_lazy(*acc, gyp, gym, gtd)

    acc = jax.lax.fori_loop(0, max_rounds, fill_round,
                            ge.identity((lanes,)))
    return _aggregate_windows(acc, nw, n_buckets, w_bits)


def _window_horner(w_res, nw: int, w_bits: int = W_BITS):
    """Combine per-window sums: sum_t 2^(w t) W_t, MSB-first Horner as a
    lax.scan over windows (graph stays small; lanes are (32, 1))."""
    res = tuple(c[:, nw - 1:nw] for c in w_res)            # (32, 1)
    if nw == 1:
        return res
    stacked = tuple(
        jnp.moveaxis(c[:, :nw - 1], 1, 0)[::-1][:, :, None]  # (nw-1, 32, 1)
        for c in w_res
    )

    def horner_step(carry, wt):
        for _ in range(w_bits):
            carry = ge.point_double(carry)
        return ge.point_add(carry, wt), None

    res, _ = jax.lax.scan(horner_step, res, stacked)
    return res


def _mul_by_group_order(pt):
    """[L]P over a (32, K)-lane point batch, L the prime group order
    (sc25519.L). L is a fixed PUBLIC scalar, so this is a lax.scan over
    its bit pattern — double always, add where the bit is set; batch-
    uniform, no per-lane tables, one traced body."""
    from . import sc25519 as sc

    bits = [int(b) for b in bin(sc.L)[2:]]
    k = pt[0].shape[-1]
    bits_arr = jnp.asarray(bits[1:], dtype=jnp.bool_)

    def step(carry, bit):
        carry = ge.point_double(carry)
        added = ge.point_add(carry, pt)
        return ge.point_select(jnp.broadcast_to(bit, (k,)), added, carry), None

    out, _ = jax.lax.scan(step, pt, bits_arr)              # init = leading 1
    return out


def subgroup_check(points, u_digits: jnp.ndarray,
                   max_rounds: int | None = None,
                   axis_name: str | None = None,
                   bucket_bits: int = W_BITS, lazy: bool = False):
    """Randomized prime-subgroup (torsion-freeness) certification.

    points: (X, Y, Z, T) of (32, B) limbs. u_digits: (K, B) int32 in
    [0, N_BUCKETS) — K independent uniform random weightings, drawn
    AFTER the points are known (verify_rlc.fresh_u). Trial j computes
    Agg_j = sum_i u_{j,i} P_i through the shared bucket machinery (rows
    act as windows, so all K trials fill in one pass), then checks
    [L]Agg_j == identity. Points weighted zero in a trial are unchecked
    by that trial.

    Why this certifies: P_i = P0_i + T_i with P0_i in the prime subgroup
    and T_i in the 8-torsion (cyclic, order 8). [L]Agg_j kills every
    prime component, leaving [L * sum_i u_{j,i} t_i mod 8] * T8 with L
    odd — identity iff sum u_ji t_i = 0 mod 8. If any T_i != 0 that
    survives one trial with probability <= 1/2 (= order-2 defects; 1/4
    order-4, 1/8 order-8), so K trials miss with probability <= 2^-K.
    Honest (torsion-free) points always pass.

    axis_name (under shard_map): the K trial rows weight the GLOBAL
    point set; each shard fills its local lanes' contributions and the
    per-trial aggregates combine across the mesh before the [L] ladder
    (Agg_j = sum over all shards' lanes), so the certification is over
    every live point, not per-shard.

    Returns (ok_subgroup, ok_fill): ok_subgroup () bool — every trial
    aggregated to the identity; ok_fill () bool — False iff a bucket
    overflowed max_rounds (trials then unusable; the caller must treat
    the set as uncertified and take its exact path).
    """
    agg, ok_fill = subgroup_partial(points, u_digits,
                                    max_rounds=max_rounds,
                                    bucket_bits=bucket_bits, lazy=lazy)
    return subgroup_combine(agg, ok_fill, axis_name=axis_name)


def subgroup_partial(points, u_digits: jnp.ndarray,
                     max_rounds: int | None = None,
                     bucket_bits: int = W_BITS, lazy: bool = False):
    """Local half of subgroup_check: the K per-trial aggregates over
    THIS shard's lanes only ((32, K)-limb coords) + the local fill
    verdict — no collectives, no [L] ladder.

    bucket_bits < W_BITS masks the trial digits (soundness preserved —
    subgroup_check_fast's 5-bit argument: the catch probability is
    governed by the digit distribution mod 8) and shrinks the lane
    grid; lazy routes the fill through the certified 7-mul niels madd
    (REQUIRES Z == 1 points, like msm_fast). Defaults are the
    historical 7-bit unified-add path, bit-identical."""
    bsz = points[0].shape[1]
    n_buckets = 1 << bucket_bits
    if max_rounds is None:
        max_rounds = _default_rounds(bsz, n_buckets)
    k = u_digits.shape[0]
    d = u_digits.astype(jnp.int32)
    if bucket_bits != W_BITS:
        d = d & (n_buckets - 1)
    idx, ok_fill = _staging_from_digits(d, bsz, max_rounds, n_buckets)
    if lazy:
        agg = _fill_and_aggregate_lazy(idx, None, points, max_rounds, k,
                                       n_buckets, bucket_bits)
    else:
        agg = _fill_and_aggregate(idx, points, max_rounds, k,
                                  n_buckets=n_buckets,
                                  w_bits=bucket_bits)
    return agg, ok_fill                                    # (32, K) coords


def subgroup_combine(agg, ok_fill, axis_name: str | None = None):
    """Tail half of subgroup_check: cross-mesh per-trial combine (when
    axis_name), the [L] doubling ladder, and the identity test.
    subgroup_check == subgroup_combine(*subgroup_partial(...)) — same
    op sequence, so the split is bit-exact."""
    if axis_name is not None:
        agg = _gather_point_sum(agg, axis_name)
        ok_fill = _all_shards_ok(ok_fill, axis_name)
    la = _mul_by_group_order(agg)
    ok = fe.fe_is_zero(la[0]) & fe.fe_eq(la[1], la[2])     # (K,) identity
    return jnp.all(ok), ok_fill


# Staged niels rounds are cast to int16 for the HBM round buffers: every
# staged limb obeys the |limb| <= 1024 lazy-carry invariant (fe_add /
# fe_sub / fe_mul outputs), far inside int16 range, and the fill kernel
# widens back to int32 on load — halving the fill's HBM traffic, which
# is the dominant byte stream of the whole MSM.
_STAGE_DTYPE = jnp.int16


def _stage_niels(points, idx, max_rounds: int, lanes: int, bsz: int,
                 niels=None, neg=None, lane_pad: int = 0):
    """Gather per-round niels operands: (R, 32, L + lane_pad) x3,
    identity-staged ((1, 1, 0) niels form) where a slot is empty.
    points must have Z == 1 (decompress output / affine constants).
    niels, if given, is the precomputed (yp, ym, t2d) from the
    decompress kernel — skips three XLA field ops over the whole point
    set. neg ((nw, nb, R) bool, signed plans) folds each negative
    digit's point negation into the gather: -P in niels form is just
    (ym, yp, -t2d), one elementwise select. lane_pad appends identity
    columns so non-power-of-two signed grids meet the kernel's lane
    alignment."""
    if niels is not None:
        yp, ym, t2d = niels
    else:
        x, y, z, t = points
        yp = fe.fe_add(y, x)
        ym = fe.fe_sub(y, x)
        t2d = fe.fe_mul(t, fe.FE_D2)

    sel = jnp.transpose(idx, (2, 0, 1)).reshape(max_rounds * lanes)
    m = (sel >= 0)[None, :]
    safe = jnp.clip(sel, 0, bsz - 1)
    one0 = (jnp.arange(fe.NLIMBS, dtype=jnp.int32) == 0)[:, None]

    gyp = jnp.where(m, yp[:, safe], one0.astype(jnp.int32))
    gym = jnp.where(m, ym[:, safe], one0.astype(jnp.int32))
    gtd = jnp.where(m, t2d[:, safe], 0)                    # (32, R*L)
    if neg is not None:
        ng = jnp.transpose(neg, (2, 0, 1)).reshape(
            max_rounds * lanes
        )[None, :]
        gyp, gym = jnp.where(ng, gym, gyp), jnp.where(ng, gyp, gym)
        gtd = jnp.where(ng, -gtd, gtd)

    def stage(g, ident_one):
        g = jnp.transpose(
            g.reshape(fe.NLIMBS, max_rounds, lanes), (1, 0, 2)
        ).astype(_STAGE_DTYPE)                             # (R, 32, L)
        if lane_pad:
            g = jnp.pad(g, ((0, 0), (0, 0), (0, lane_pad)))
            if ident_one:
                g = g.at[:, 0, lanes:].set(1)
        return g

    return stage(gyp, True), stage(gym, True), stage(gtd, False)


def msm_fast(scalars_bytes: jnp.ndarray, points, n_windows: int,
             max_rounds: int | None = None, interpret: bool = False,
             niels=None, axis_name: str | None = None,
             plan: MsmPlan | None = None):
    """Kernel-backed msm (same contract as msm(), including axis_name's
    cross-mesh window-partial combine before the Horner tail and the
    plan argument's schedule selection).

    REQUIRES points with Z == 1 (decompress output / affine constants) —
    the bucket fill uses precomputed niels form (y+x, y-x, 2d*t) with
    mixed adds, 7 muls instead of 9. Bucket accumulators and the
    aggregation running sums live in VMEM (ops/msm_pallas.py); the
    sort/gather staging and final Horner remain XLA.
    """
    if plan is None:
        plan = active_plan()
    w_res, ok = msm_fast_partial(scalars_bytes, points, n_windows,
                                 max_rounds=max_rounds,
                                 interpret=interpret, niels=niels,
                                 plan=plan)
    return msm_fast_combine(w_res, ok, n_windows, interpret=interpret,
                            axis_name=axis_name, plan=plan)


def msm_fast_partial(scalars_bytes: jnp.ndarray, points, n_windows: int,
                     max_rounds: int | None = None,
                     interpret: bool = False, niels=None,
                     plan: MsmPlan | None = None,
                     _force_windows: int | None = None):
    """Local half of msm_fast: niels staging + VMEM bucket fill +
    running-sum aggregation over this shard's lanes — no collectives,
    no Horner. Returns (w_res, ok) exactly like msm_partial (the kernel
    aggregation's nw padding is trimmed here, so the partial's shape is
    engine-independent and the fd_pod split tail can gather it). A
    signed plan folds digit signs into the niels staging (yp <-> ym
    swap + t2d negation), so the kernels themselves are untouched —
    magnitude grids just change the lane count, padded to the kernel's
    lane alignment with identity slots."""
    from . import msm_pallas as mp

    if plan is None:
        plan = active_plan()
    bsz = points[0].shape[1]
    if plan == BASELINE_PLAN and _force_windows is None:
        if max_rounds is None:
            max_rounds = _default_rounds(bsz)
        nw, nb = n_windows, N_BUCKETS
        idx, ok = _staging_indices(scalars_bytes, nw, bsz, max_rounds)
        neg, top, planes = None, None, 0
    else:
        nw, nb, rounds = _plan_dims(n_windows, bsz, plan, _force_windows)
        if max_rounds is None:
            max_rounds = rounds
        planes = _top_tree_planes(n_windows, nw, plan)
        idx, neg, ok, top = _plan_staging(scalars_bytes, bsz, max_rounds,
                                          nw, nb, plan,
                                          tree_planes=planes)
    nw_grid = nw - 1 if planes else nw

    lanes = nw_grid * nb
    lane_pad = (-lanes) % 256 if nb != N_BUCKETS else 0
    s_yp, s_ym, s_t2d = _stage_niels(points, idx, max_rounds, lanes, bsz,
                                     niels=niels, neg=neg,
                                     lane_pad=lane_pad)

    bx, by, bz, bt = mp.fill_buckets_pallas(
        s_yp, s_ym, s_t2d, interpret=interpret
    )
    if lane_pad:
        bx, by, bz, bt = (c[:, :lanes] for c in (bx, by, bz, bt))

    # (32, L) -> bucket-major (nb, 32, nw_pad) for the aggregation walk.
    nw_pad = max(128, nw_grid)
    def to_bucket_major(c):
        c = jnp.transpose(
            c.reshape(fe.NLIMBS, nw_grid, nb), (2, 0, 1)
        )
        if nw_pad != nw_grid:
            c = jnp.pad(c, ((0, 0), (0, 0), (0, nw_pad - nw_grid)))
        return c

    w_res = mp.aggregate_buckets_pallas(
        tuple(to_bucket_major(c) for c in (bx, by, bz, bt)),
        fe.FE_D2.astype(jnp.int32),
        interpret=interpret,
    )
    w_res = tuple(c[:, :nw_grid] for c in w_res)
    if planes:
        # The tree-summed top window is XLA-side on both engines — it is
        # ~1% of the fill's lane count and keeps the kernels untouched.
        w_top = _top_window_sum(top, points, planes)
        w_res = tuple(jnp.concatenate([c, ct], axis=1)
                      for c, ct in zip(w_res, w_top))
    return w_res, ok


def msm_fast_combine(w_res, ok, n_windows: int, interpret: bool = False,
                     axis_name: str | None = None,
                     plan: MsmPlan | None = None):
    """Tail half of msm_fast: cross-mesh window-partial combine + the
    VMEM Horner doubling chain (plan.w doublings per window; the window
    count is read off w_res so both halves agree by shape). msm_fast ==
    the composition, bit-exact (same op order the monolithic path
    always ran)."""
    from . import msm_pallas as mp

    if plan is None:
        plan = active_plan()
    if axis_name is not None:
        w_res = _gather_point_sum(w_res, axis_name)
        ok = _all_shards_ok(ok, axis_name)
    res = mp.window_horner_pallas(
        w_res, fe.FE_D2.astype(jnp.int32), w_res[0].shape[1],
        interpret=interpret, w_bits=plan.w,
    )
    return res, ok


def _l_bits_col() -> jnp.ndarray:
    """(256, 1) int32: bits of the group order L, MSB-first from row 0,
    zero-padded (kernel input for mul_by_group_order_pallas)."""
    from . import sc25519 as sc

    bits = [int(b) for b in bin(sc.L)[2:]]
    out = np.zeros((256, 1), np.int32)
    out[: len(bits), 0] = bits
    return jnp.asarray(out)


def subgroup_check_fast(points, u_digits: jnp.ndarray,
                        bucket_bits: int = 5,
                        max_rounds: int | None = None,
                        interpret: bool = False,
                        niels=None, axis_name: str | None = None):
    """Kernel-backed subgroup_check (same contract and soundness,
    including axis_name's cross-mesh per-trial aggregate combine).

    REQUIRES points with Z == 1 (decompress output), like msm_fast.

    Two changes versus the XLA path, neither affecting soundness:
    - Trial digits are masked to `bucket_bits` (< 7) bits. Uniform
      digits stay uniform under the mask, and the per-trial catch
      probability is governed by the digit distribution mod 8, which
      5-bit digits preserve — but the bucket grid shrinks from
      (K, 128) to (K, 32), cutting the staged round buffers' HBM
      footprint ~4x (the fill is HBM-bound; tail efficiency
      lam/(lam + 7*sqrt(lam)) improves with larger lam per bucket).
    - The fill, aggregation, and the [L]-ladder all run in VMEM Pallas
      kernels (the XLA ladder alone cost more than the entire direct
      verify at production batch sizes).
    """
    agg, ok_fill = subgroup_fast_partial(
        points, u_digits, bucket_bits=bucket_bits, max_rounds=max_rounds,
        interpret=interpret, niels=niels,
    )
    return subgroup_fast_combine(agg, ok_fill, k=u_digits.shape[0],
                                 interpret=interpret, axis_name=axis_name)


def subgroup_fast_partial(points, u_digits: jnp.ndarray,
                          bucket_bits: int = 5,
                          max_rounds: int | None = None,
                          interpret: bool = False, niels=None):
    """Local half of subgroup_check_fast: masked-digit staging + VMEM
    fill + per-trial aggregation over this shard's lanes. Returns
    (agg, ok_fill) with agg at the kernel's Mosaic-padded trial width
    (k_pad = k rounded up to 128); the pad lanes are ZERO coordinate
    limbs, which every downstream group op maps to zero and the final
    identity test trivially passes — so a combine that does not know k
    can evaluate all k_pad lanes and reach the same verdict."""
    from . import msm_pallas as mp

    bsz = points[0].shape[1]
    n_buckets = 1 << bucket_bits
    if max_rounds is None:
        max_rounds = _default_rounds(bsz, n_buckets)
    d = u_digits.astype(jnp.int32) & (n_buckets - 1)
    k = d.shape[0]
    idx, ok_fill = _staging_from_digits(d, bsz, max_rounds, n_buckets)

    lanes = k * n_buckets
    s_yp, s_ym, s_t2d = _stage_niels(points, idx, max_rounds, lanes, bsz,
                                     niels=niels)
    bx, by, bz, bt = mp.fill_buckets_pallas(
        s_yp, s_ym, s_t2d, interpret=interpret
    )

    k_pad = k + (-k) % 128                 # Mosaic lane-width alignment

    def to_bucket_major(c):
        c = jnp.transpose(c.reshape(fe.NLIMBS, k, n_buckets), (2, 0, 1))
        if k_pad != k:
            c = jnp.pad(c, ((0, 0), (0, 0), (0, k_pad - k)))
        return c

    agg = mp.aggregate_buckets_pallas(
        tuple(to_bucket_major(c) for c in (bx, by, bz, bt)),
        fe.FE_D2.astype(jnp.int32),
        interpret=interpret,
    )
    return agg, ok_fill


def subgroup_fast_combine(agg, ok_fill, k: int | None = None,
                          interpret: bool = False,
                          axis_name: str | None = None):
    """Tail half of subgroup_check_fast: cross-mesh per-trial combine,
    the VMEM [L] ladder, and the identity test over the first k trial
    lanes (k=None evaluates every padded lane — sound, see
    subgroup_fast_partial's zero-pad note)."""
    from . import msm_pallas as mp

    if axis_name is not None:
        agg = _gather_point_sum(agg, axis_name)
        ok_fill = _all_shards_ok(ok_fill, axis_name)
    la = mp.mul_by_group_order_pallas(
        agg, fe.FE_D2.astype(jnp.int32), _l_bits_col(), interpret=interpret
    )
    if k is not None:
        la = tuple(c[:, :k] for c in la)
    ok = fe.fe_is_zero(la[0]) & fe.fe_eq(la[1], la[2])     # (K,) identity
    return jnp.all(ok), ok_fill


# --------------------------------------------------------------------- #
# fdlint pass 7 (graph-audit) contracts — literals, read with
# ast.literal_eval by firedancer_tpu/lint/graphs.py, never imported.
# The msm_stage graphs are the three fill partials of one RLC verify
# (z-MSM, 253-bit MSM, torsion certification) traced standalone at
# EVERY ladder rung; their walked fill madds must reconcile with
# msm_plan's analytic executed-madd count within the tolerance.
# --------------------------------------------------------------------- #

GRAPH_CONTRACTS = {
    "msm_stage_xla": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
        "madds": {"engine": "xla", "tolerance_pct": 2.0},
    },
    "msm_stage_kernel": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int16", "int32", "uint32", "uint8"],
        "madds": {"engine": "kernel", "tolerance_pct": 2.0},
        "vmem_mb": 64.0,
    },
}
