"""Batched multi-scalar multiplication (Pippenger) on TPU.

Computes sum_i c_i * P_i for a batch of per-lane scalars and points with
ONE shared doubling chain — the structural cost that per-lane
double-scalar-mult (ops/curve25519.double_scalarmult) cannot amortize.
This is the engine behind RLC batch verification (ops/verify_rlc.py).

Shape of the computation (w = 8-bit windows, byte-aligned so digit
extraction is free):

1. **Bucket fill.** For every window t, lane digits d_i route point P_i
   into bucket (t, d_i). The fill is batch-uniform: a static number of
   ROUNDS, each adding one gathered point per (window, bucket) lane —
   lanes are (n_windows x 256) wide, so every round is one unified
   point_add across all windows at once. Slot indices are built by a
   stable argsort per window + rank-within-bucket arithmetic (gathers
   only, no scatters — TPU-friendly).
2. **Bucket aggregation.** sum_b b * S_b via bit decomposition:
   sum_k 2^k (sum over buckets with bit k set), each inner sum a
   pairwise tree-reduce over the bucket axis — log-depth, batch-uniform.
3. **Cross-window Horner.** S = 2^8 * S + W_t, MSB-first; (32, 1)-lane
   elementwise chains that XLA fuses.

Data-dependence escape hatch: the fill uses a STATIC round count
(max_rounds). If any bucket receives more points (Poisson tail, or
adversarially-biased digits of h), the fill would be incomplete — the
function detects this and reports ok=False so the caller falls back to
the exact per-lane path. Never a wrong result, only a slow path.

Reference basis: Pippenger's algorithm (public-domain technique; cf.
the batched bucket MSMs in GPU ZK provers), re-shaped for TPU: no
atomics, no scatters, unified complete adds, one-hot-free gathers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import curve25519 as ge
from . import fe25519 as fe

W_BITS = 7
N_BUCKETS = 1 << W_BITS
# Window counts are chosen so EVERY window of the scalar distribution is
# either uniform or almost-always-zero — a top window whose digits
# concentrate on a few NONZERO values overloads those buckets and forces
# the static-round fill into its fallback (zero digits are free: bucket
# 0 is never accumulated). 252 = 36*7, so scalars mod L (< 2^252 + eps)
# are uniform in windows 0..35 and ~always 0 in window 36; RLC z weights
# are drawn < 2^126 = 2^(18*7) so all 18 windows are uniform.
WINDOWS_128 = 19   # any 128-bit scalar (window 18 in {0..3})
WINDOWS_Z = 18     # RLC z weights: uniform < 2^126
WINDOWS_253 = 37   # scalars mod L


def _digits(scalars_bytes: jnp.ndarray, n_windows: int) -> jnp.ndarray:
    """(B, 32) uint8 -> (n_windows, B) int32 7-bit windows, LSB first."""
    b = jnp.moveaxis(scalars_bytes.astype(jnp.int32), -1, 0)  # (32, B)
    zero = jnp.zeros_like(b[0])
    outs = []
    for w in range(n_windows):
        bit = 7 * w
        i, sh = bit >> 3, bit & 7
        lo = b[i] if i < 32 else zero
        hi = b[i + 1] if i + 1 < 32 else zero
        outs.append(((lo + (hi << 8)) >> sh) & (N_BUCKETS - 1))
    return jnp.stack(outs)


def _reduce_pairs(pt, n):
    """Tree-reduce a (..., n) bucket axis by pairwise point_add."""
    while n > 1:
        half = n // 2
        a = tuple(c[..., 0::2] for c in pt)
        b = tuple(c[..., 1::2] for c in pt)
        pt = ge.point_add(a, b)
        n = half
    return pt


def _default_rounds(bsz: int, n_buckets: int = N_BUCKETS) -> int:
    # Poisson tail bound: with uniform digits each nonzero bucket holds
    # ~lam = B/(n_buckets-1) points; lam + 7*sqrt(lam) + 8 puts the
    # per-batch overflow probability below ~1e-7 even across thousands
    # of buckets. Adversarially-biased digits only cost the fallback.
    # The formula lives in firedancer_tpu/msm_plan.py (stdlib-only) so
    # the bench orchestrator's fill-efficiency predictions can never
    # drift from the engine's actual round count.
    from firedancer_tpu.msm_plan import default_rounds

    return default_rounds(bsz, n_buckets)


def combine_stacked(pt):
    """Fold a leading-axis stack of point partials ((N, ...) limb
    arrays per coordinate) into their group sum with unified adds, in
    stack order — the one folding rule every cross-shard combine path
    (monolithic all_gather and the fd_pod split tail alike) goes
    through, so the two compositions can never drift bit-wise."""
    n = pt[0].shape[0]
    acc = tuple(c[0] for c in pt)
    for d in range(1, n):
        acc = ge.point_add(acc, tuple(c[d] for c in pt))
    return acc


def _gather_point_sum(pt, axis_name: str):
    """Combine per-device point partials into the global sum, on every
    device: all_gather the (X, Y, Z, T) limb arrays over the mesh axis
    and point_add the device slices. Point addition is the GROUP
    operation, so a raw psum cannot combine partials — but the partials
    are tiny ((32, nw) limbs per coordinate), so gather + a handful of
    unified adds costs microseconds against the milliseconds of bucket
    work they summarize. This is the only cross-device traffic in the
    sharded MSM."""
    g = tuple(jax.lax.all_gather(c, axis_name) for c in pt)  # (N, ...)
    return combine_stacked(g)


def _all_shards_ok(ok, axis_name: str):
    """Global AND of a per-shard () bool (fill-overflow flags: ONE
    overflowing shard invalidates the whole batch result)."""
    return jnp.all(jax.lax.all_gather(ok, axis_name))


def _staging_indices(scalars_bytes, n_windows: int, bsz: int,
                     max_rounds: int):
    """Slot table for the bucket fill: (idx, ok) where idx[t, b, r] is
    the lane of the r-th point in bucket (t, b) or -1, and ok is False
    iff some bucket overflowed max_rounds."""
    d = _digits(scalars_bytes, n_windows)                 # (nw, B)
    return _staging_from_digits(d, bsz, max_rounds)


def _staging_from_digits(d: jnp.ndarray, bsz: int, max_rounds: int,
                         n_buckets: int = N_BUCKETS):
    """As _staging_indices, but from an explicit (nw, B) int32 digit
    array in [0, n_buckets) — each row an independent weighting of the
    same points (used by the torsion subgroup check, where rows are
    independent random trials rather than positional windows)."""
    nw = d.shape[0]
    order = jnp.argsort(d, axis=1, stable=True)           # (nw, B)
    sorted_d = jnp.take_along_axis(d, order, axis=1)

    # starts[t, b] = first sorted position of digit b in window t.
    buckets = jnp.arange(n_buckets, dtype=jnp.int32)
    starts = jax.vmap(
        lambda row: jnp.searchsorted(row, buckets, side="left")
    )(sorted_d)                                           # (nw, n_buckets)
    ends = jnp.concatenate(
        [starts[:, 1:], jnp.full((nw, 1), bsz, starts.dtype)], axis=1
    )
    counts = ends - starts                                # (nw, n_buckets)
    ok = jnp.max(jnp.where(buckets[None, :] > 0, counts, 0)) <= max_rounds

    # Slot table: idx[t, b, r] = lane index of the r-th point in bucket
    # (t, b), or -1. Bucket 0 contributes nothing (digit 0 == identity).
    r_iota = jnp.arange(max_rounds, dtype=jnp.int32)
    pos = starts[:, :, None] + r_iota[None, None, :]      # (nw, nb, R)
    valid = (r_iota[None, None, :] < counts[:, :, None]) & (
        buckets[None, :, None] > 0
    )
    pos_flat = jnp.clip(pos.reshape(nw, -1), 0, bsz - 1)
    idx = jnp.take_along_axis(order, pos_flat, axis=1).reshape(
        nw, n_buckets, max_rounds
    )
    idx = jnp.where(valid, idx, -1)                       # (nw, nb, R)
    return idx, ok


def msm(scalars_bytes: jnp.ndarray, points, n_windows: int,
        max_rounds: int | None = None, axis_name: str | None = None):
    """sum_i scalars_i * P_i (XLA reference path).

    scalars_bytes: (B, 32) uint8 little-endian (windows beyond
      n_windows must be zero). points: (X, Y, Z, T) of (32, B) limbs.
    axis_name (under shard_map): B is the LOCAL lane count; the
      per-window bucket sums are combined across the mesh before the
      Horner tail, so the returned point is the global MSM over all
      shards' lanes (replicated), and ok is the global fill verdict.
    Returns (point, ok): point is (X, Y, Z, T) of (32, 1) limbs; ok is a
      () bool — False iff a bucket overflowed max_rounds (result then
      invalid; caller must use the exact path).
    """
    w_res, ok = msm_partial(scalars_bytes, points, n_windows,
                            max_rounds=max_rounds)
    return msm_combine(w_res, ok, n_windows, axis_name=axis_name)


def msm_partial(scalars_bytes: jnp.ndarray, points, n_windows: int,
                max_rounds: int | None = None):
    """The LOCAL half of msm(): digit staging + bucket fill + per-window
    bucket aggregation over this shard's lanes only — no collectives, no
    doubling-chain tails. Returns (w_res, ok): w_res a (32, n_windows)-
    limb point per window (W_t = sum over local lanes), ok the local
    fill verdict. msm_combine finishes the job; fd_pod's split-step
    dispatcher jits the two halves separately so batch k's combine can
    execute while batch k+1's fill is already dispatched."""
    bsz = points[0].shape[1]
    if max_rounds is None:
        max_rounds = _default_rounds(bsz)
    idx, ok = _staging_indices(scalars_bytes, n_windows, bsz, max_rounds)
    return _fill_and_aggregate(idx, points, max_rounds, n_windows), ok


def msm_combine(w_res, ok, n_windows: int, axis_name: str | None = None):
    """The TAIL half of msm(): combine per-shard window partials across
    the mesh (axis_name; identity when None) and run the cross-window
    Horner doubling chain. msm() == msm_combine(*msm_partial(...)) by
    construction — the composition is the exact op sequence the
    monolithic path always ran, so the split is bit-exact."""
    if axis_name is not None:
        w_res = _gather_point_sum(w_res, axis_name)
        ok = _all_shards_ok(ok, axis_name)
    return _window_horner(w_res, n_windows), ok


def _fill_and_aggregate(idx, points, max_rounds: int, nw: int):
    """Bucket fill + per-window bucket aggregation (XLA path): returns
    w_res, a (32, nw)-limb point per window, W_t = sum_b b * S_{t,b}."""
    bsz = points[0].shape[1]
    lanes = nw * N_BUCKETS
    ident = ge.identity((lanes,))

    def fill_round(r, acc):
        sel = jax.lax.dynamic_index_in_dim(
            idx, r, axis=2, keepdims=False
        ).reshape(lanes)                                   # (L,)
        m = sel >= 0
        safe = jnp.clip(sel, 0, bsz - 1)
        q = tuple(c[:, safe] for c in points)
        q = ge.point_select(m, q, ident)
        # Adding the identity is exact under the unified formulas, so a
        # plain add-then-keep is fine; select keeps masked lanes stable.
        return ge.point_select(m, ge.point_add(acc, q), acc)

    acc = jax.lax.fori_loop(0, max_rounds, fill_round, ident)
    s_buckets = tuple(
        c.reshape(fe.NLIMBS, nw, N_BUCKETS) for c in acc
    )

    # sum_b b * S_b = sum_k 2^k * (sum_{b: bit k set} S_b). A lax.scan
    # over the bit masks keeps the traced graph ~W_BITS x smaller than
    # unrolling (this path must stay compilable on CPU test hosts).
    buckets = jnp.arange(N_BUCKETS, dtype=jnp.int32)
    ident_nb = ge.identity((nw, N_BUCKETS))
    bit_masks = jnp.stack([
        jnp.broadcast_to((((buckets >> k) & 1) == 1)[None, :],
                         (nw, N_BUCKETS))
        for k in range(W_BITS - 1, -1, -1)
    ])                                                     # (W_BITS, nw, 256)

    def agg_step(carry, bit):
        masked = ge.point_select(bit, s_buckets, ident_nb)
        t_k = _reduce_pairs(masked, N_BUCKETS)             # (32, nw, 1)
        t_k = tuple(c[..., 0] for c in t_k)                # (32, nw)
        out = ge.point_add(ge.point_double(carry), t_k)
        return out, None

    w_res, _ = jax.lax.scan(agg_step, ge.identity((nw,)), bit_masks)
    return w_res


def _window_horner(w_res, nw: int):
    """Combine per-window sums: sum_t 2^(w t) W_t, MSB-first Horner as a
    lax.scan over windows (graph stays small; lanes are (32, 1))."""
    res = tuple(c[:, nw - 1:nw] for c in w_res)            # (32, 1)
    if nw == 1:
        return res
    stacked = tuple(
        jnp.moveaxis(c[:, :nw - 1], 1, 0)[::-1][:, :, None]  # (nw-1, 32, 1)
        for c in w_res
    )

    def horner_step(carry, wt):
        for _ in range(W_BITS):
            carry = ge.point_double(carry)
        return ge.point_add(carry, wt), None

    res, _ = jax.lax.scan(horner_step, res, stacked)
    return res


def _mul_by_group_order(pt):
    """[L]P over a (32, K)-lane point batch, L the prime group order
    (sc25519.L). L is a fixed PUBLIC scalar, so this is a lax.scan over
    its bit pattern — double always, add where the bit is set; batch-
    uniform, no per-lane tables, one traced body."""
    from . import sc25519 as sc

    bits = [int(b) for b in bin(sc.L)[2:]]
    k = pt[0].shape[-1]
    bits_arr = jnp.asarray(bits[1:], dtype=jnp.bool_)

    def step(carry, bit):
        carry = ge.point_double(carry)
        added = ge.point_add(carry, pt)
        return ge.point_select(jnp.broadcast_to(bit, (k,)), added, carry), None

    out, _ = jax.lax.scan(step, pt, bits_arr)              # init = leading 1
    return out


def subgroup_check(points, u_digits: jnp.ndarray,
                   max_rounds: int | None = None,
                   axis_name: str | None = None):
    """Randomized prime-subgroup (torsion-freeness) certification.

    points: (X, Y, Z, T) of (32, B) limbs. u_digits: (K, B) int32 in
    [0, N_BUCKETS) — K independent uniform random weightings, drawn
    AFTER the points are known (verify_rlc.fresh_u). Trial j computes
    Agg_j = sum_i u_{j,i} P_i through the shared bucket machinery (rows
    act as windows, so all K trials fill in one pass), then checks
    [L]Agg_j == identity. Points weighted zero in a trial are unchecked
    by that trial.

    Why this certifies: P_i = P0_i + T_i with P0_i in the prime subgroup
    and T_i in the 8-torsion (cyclic, order 8). [L]Agg_j kills every
    prime component, leaving [L * sum_i u_{j,i} t_i mod 8] * T8 with L
    odd — identity iff sum u_ji t_i = 0 mod 8. If any T_i != 0 that
    survives one trial with probability <= 1/2 (= order-2 defects; 1/4
    order-4, 1/8 order-8), so K trials miss with probability <= 2^-K.
    Honest (torsion-free) points always pass.

    axis_name (under shard_map): the K trial rows weight the GLOBAL
    point set; each shard fills its local lanes' contributions and the
    per-trial aggregates combine across the mesh before the [L] ladder
    (Agg_j = sum over all shards' lanes), so the certification is over
    every live point, not per-shard.

    Returns (ok_subgroup, ok_fill): ok_subgroup () bool — every trial
    aggregated to the identity; ok_fill () bool — False iff a bucket
    overflowed max_rounds (trials then unusable; the caller must treat
    the set as uncertified and take its exact path).
    """
    agg, ok_fill = subgroup_partial(points, u_digits,
                                    max_rounds=max_rounds)
    return subgroup_combine(agg, ok_fill, axis_name=axis_name)


def subgroup_partial(points, u_digits: jnp.ndarray,
                     max_rounds: int | None = None):
    """Local half of subgroup_check: the K per-trial aggregates over
    THIS shard's lanes only ((32, K)-limb coords) + the local fill
    verdict — no collectives, no [L] ladder."""
    bsz = points[0].shape[1]
    if max_rounds is None:
        max_rounds = _default_rounds(bsz)
    k = u_digits.shape[0]
    idx, ok_fill = _staging_from_digits(
        u_digits.astype(jnp.int32), bsz, max_rounds
    )
    agg = _fill_and_aggregate(idx, points, max_rounds, k)  # (32, K) coords
    return agg, ok_fill


def subgroup_combine(agg, ok_fill, axis_name: str | None = None):
    """Tail half of subgroup_check: cross-mesh per-trial combine (when
    axis_name), the [L] doubling ladder, and the identity test.
    subgroup_check == subgroup_combine(*subgroup_partial(...)) — same
    op sequence, so the split is bit-exact."""
    if axis_name is not None:
        agg = _gather_point_sum(agg, axis_name)
        ok_fill = _all_shards_ok(ok_fill, axis_name)
    la = _mul_by_group_order(agg)
    ok = fe.fe_is_zero(la[0]) & fe.fe_eq(la[1], la[2])     # (K,) identity
    return jnp.all(ok), ok_fill


# Staged niels rounds are cast to int16 for the HBM round buffers: every
# staged limb obeys the |limb| <= 1024 lazy-carry invariant (fe_add /
# fe_sub / fe_mul outputs), far inside int16 range, and the fill kernel
# widens back to int32 on load — halving the fill's HBM traffic, which
# is the dominant byte stream of the whole MSM.
_STAGE_DTYPE = jnp.int16


def _stage_niels(points, idx, max_rounds: int, lanes: int, bsz: int,
                 niels=None):
    """Gather per-round niels operands: (R, 32, L) x3, identity-staged
    ((1, 1, 0) niels form) where a slot is empty. points must have
    Z == 1 (decompress output / affine constants). niels, if given, is
    the precomputed (yp, ym, t2d) from the decompress kernel — skips
    three XLA field ops over the whole point set."""
    if niels is not None:
        yp, ym, t2d = niels
    else:
        x, y, z, t = points
        yp = fe.fe_add(y, x)
        ym = fe.fe_sub(y, x)
        t2d = fe.fe_mul(t, fe.FE_D2)

    sel = jnp.transpose(idx, (2, 0, 1)).reshape(max_rounds * lanes)
    m = (sel >= 0)[None, :]
    safe = jnp.clip(sel, 0, bsz - 1)
    one0 = (jnp.arange(fe.NLIMBS, dtype=jnp.int32) == 0)[:, None]

    def stage(src, ident_col):
        g = jnp.where(m, src[:, safe], ident_col)          # (32, R*L)
        return jnp.transpose(
            g.reshape(fe.NLIMBS, max_rounds, lanes), (1, 0, 2)
        ).astype(_STAGE_DTYPE)                             # (R, 32, L)

    return (stage(yp, one0.astype(jnp.int32)),
            stage(ym, one0.astype(jnp.int32)),
            stage(t2d, 0))


def msm_fast(scalars_bytes: jnp.ndarray, points, n_windows: int,
             max_rounds: int | None = None, interpret: bool = False,
             niels=None, axis_name: str | None = None):
    """Kernel-backed msm (same contract as msm(), including axis_name's
    cross-mesh window-partial combine before the Horner tail).

    REQUIRES points with Z == 1 (decompress output / affine constants) —
    the bucket fill uses precomputed niels form (y+x, y-x, 2d*t) with
    mixed adds, 7 muls instead of 9. Bucket accumulators and the
    aggregation running sums live in VMEM (ops/msm_pallas.py); the
    sort/gather staging and final Horner remain XLA.
    """
    w_res, ok = msm_fast_partial(scalars_bytes, points, n_windows,
                                 max_rounds=max_rounds,
                                 interpret=interpret, niels=niels)
    return msm_fast_combine(w_res, ok, n_windows, interpret=interpret,
                            axis_name=axis_name)


def msm_fast_partial(scalars_bytes: jnp.ndarray, points, n_windows: int,
                     max_rounds: int | None = None,
                     interpret: bool = False, niels=None):
    """Local half of msm_fast: niels staging + VMEM bucket fill +
    running-sum aggregation over this shard's lanes — no collectives,
    no Horner. Returns (w_res, ok) exactly like msm_partial (the kernel
    aggregation's nw padding is trimmed here, so the partial's shape is
    engine-independent and the fd_pod split tail can gather it)."""
    from . import msm_pallas as mp

    bsz = points[0].shape[1]
    if max_rounds is None:
        max_rounds = _default_rounds(bsz)
    nw = n_windows
    idx, ok = _staging_indices(scalars_bytes, nw, bsz, max_rounds)

    lanes = nw * N_BUCKETS
    s_yp, s_ym, s_t2d = _stage_niels(points, idx, max_rounds, lanes, bsz,
                                     niels=niels)

    bx, by, bz, bt = mp.fill_buckets_pallas(
        s_yp, s_ym, s_t2d, interpret=interpret
    )

    # (32, L) -> bucket-major (256, 32, nw_pad) for the aggregation walk.
    nw_pad = max(128, nw)
    def to_bucket_major(c):
        c = jnp.transpose(
            c.reshape(fe.NLIMBS, nw, N_BUCKETS), (2, 0, 1)
        )
        if nw_pad != nw:
            c = jnp.pad(c, ((0, 0), (0, 0), (0, nw_pad - nw)))
        return c

    w_res = mp.aggregate_buckets_pallas(
        tuple(to_bucket_major(c) for c in (bx, by, bz, bt)),
        fe.FE_D2.astype(jnp.int32),
        interpret=interpret,
    )
    return tuple(c[:, :nw] for c in w_res), ok


def msm_fast_combine(w_res, ok, n_windows: int, interpret: bool = False,
                     axis_name: str | None = None):
    """Tail half of msm_fast: cross-mesh window-partial combine + the
    VMEM Horner doubling chain. msm_fast == the composition, bit-exact
    (same op order the monolithic path always ran)."""
    from . import msm_pallas as mp

    if axis_name is not None:
        w_res = _gather_point_sum(w_res, axis_name)
        ok = _all_shards_ok(ok, axis_name)
    res = mp.window_horner_pallas(
        w_res, fe.FE_D2.astype(jnp.int32), n_windows, interpret=interpret,
        w_bits=W_BITS,
    )
    return res, ok


def _l_bits_col() -> jnp.ndarray:
    """(256, 1) int32: bits of the group order L, MSB-first from row 0,
    zero-padded (kernel input for mul_by_group_order_pallas)."""
    from . import sc25519 as sc

    bits = [int(b) for b in bin(sc.L)[2:]]
    out = np.zeros((256, 1), np.int32)
    out[: len(bits), 0] = bits
    return jnp.asarray(out)


def subgroup_check_fast(points, u_digits: jnp.ndarray,
                        bucket_bits: int = 5,
                        max_rounds: int | None = None,
                        interpret: bool = False,
                        niels=None, axis_name: str | None = None):
    """Kernel-backed subgroup_check (same contract and soundness,
    including axis_name's cross-mesh per-trial aggregate combine).

    REQUIRES points with Z == 1 (decompress output), like msm_fast.

    Two changes versus the XLA path, neither affecting soundness:
    - Trial digits are masked to `bucket_bits` (< 7) bits. Uniform
      digits stay uniform under the mask, and the per-trial catch
      probability is governed by the digit distribution mod 8, which
      5-bit digits preserve — but the bucket grid shrinks from
      (K, 128) to (K, 32), cutting the staged round buffers' HBM
      footprint ~4x (the fill is HBM-bound; tail efficiency
      lam/(lam + 7*sqrt(lam)) improves with larger lam per bucket).
    - The fill, aggregation, and the [L]-ladder all run in VMEM Pallas
      kernels (the XLA ladder alone cost more than the entire direct
      verify at production batch sizes).
    """
    agg, ok_fill = subgroup_fast_partial(
        points, u_digits, bucket_bits=bucket_bits, max_rounds=max_rounds,
        interpret=interpret, niels=niels,
    )
    return subgroup_fast_combine(agg, ok_fill, k=u_digits.shape[0],
                                 interpret=interpret, axis_name=axis_name)


def subgroup_fast_partial(points, u_digits: jnp.ndarray,
                          bucket_bits: int = 5,
                          max_rounds: int | None = None,
                          interpret: bool = False, niels=None):
    """Local half of subgroup_check_fast: masked-digit staging + VMEM
    fill + per-trial aggregation over this shard's lanes. Returns
    (agg, ok_fill) with agg at the kernel's Mosaic-padded trial width
    (k_pad = k rounded up to 128); the pad lanes are ZERO coordinate
    limbs, which every downstream group op maps to zero and the final
    identity test trivially passes — so a combine that does not know k
    can evaluate all k_pad lanes and reach the same verdict."""
    from . import msm_pallas as mp

    bsz = points[0].shape[1]
    n_buckets = 1 << bucket_bits
    if max_rounds is None:
        max_rounds = _default_rounds(bsz, n_buckets)
    d = u_digits.astype(jnp.int32) & (n_buckets - 1)
    k = d.shape[0]
    idx, ok_fill = _staging_from_digits(d, bsz, max_rounds, n_buckets)

    lanes = k * n_buckets
    s_yp, s_ym, s_t2d = _stage_niels(points, idx, max_rounds, lanes, bsz,
                                     niels=niels)
    bx, by, bz, bt = mp.fill_buckets_pallas(
        s_yp, s_ym, s_t2d, interpret=interpret
    )

    k_pad = k + (-k) % 128                 # Mosaic lane-width alignment

    def to_bucket_major(c):
        c = jnp.transpose(c.reshape(fe.NLIMBS, k, n_buckets), (2, 0, 1))
        if k_pad != k:
            c = jnp.pad(c, ((0, 0), (0, 0), (0, k_pad - k)))
        return c

    agg = mp.aggregate_buckets_pallas(
        tuple(to_bucket_major(c) for c in (bx, by, bz, bt)),
        fe.FE_D2.astype(jnp.int32),
        interpret=interpret,
    )
    return agg, ok_fill


def subgroup_fast_combine(agg, ok_fill, k: int | None = None,
                          interpret: bool = False,
                          axis_name: str | None = None):
    """Tail half of subgroup_check_fast: cross-mesh per-trial combine,
    the VMEM [L] ladder, and the identity test over the first k trial
    lanes (k=None evaluates every padded lane — sound, see
    subgroup_fast_partial's zero-pad note)."""
    from . import msm_pallas as mp

    if axis_name is not None:
        agg = _gather_point_sum(agg, axis_name)
        ok_fill = _all_shards_ok(ok_fill, axis_name)
    la = mp.mul_by_group_order_pallas(
        agg, fe.FE_D2.astype(jnp.int32), _l_bits_col(), interpret=interpret
    )
    if k is not None:
        la = tuple(c[:, :k] for c in la)
    ok = fe.fe_is_zero(la[0]) & fe.fe_eq(la[1], la[2])     # (K,) identity
    return jnp.all(ok), ok_fill
