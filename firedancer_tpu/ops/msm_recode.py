"""fd_msm2 certified core: signed-window recoding + the lazy niels madd.

Two pieces of the signed-digit Pippenger engine live here, OUTSIDE the
(uncertifiable) gather/argsort staging of ops/msm.py, precisely so the
fdcert abstract interpreter can prove them (lint/bounds.py pass 5 —
this module is a CERT_MODULE):

- ``recode_signed`` — the borrow-propagating balanced recode. Unsigned
  w-bit window digits d_t (LSB-first along axis 0) become signed
  digits in [-(2^(w-1)-1), 2^(w-1)] with sum(digit_t * 2^(w*t)) equal
  to the original scalar, provided the window count follows
  msm_plan.plan_windows (an extra all-carry window when w divides the
  scalar width; otherwise the top partial window absorbs the borrow).
  The per-step wrap routes through ``_recode_step``, which the
  certifier replaces by name with a precise hull transfer
  (lint/bounds.py ``_transfer_recode_step``): the plain interval
  product would book digits in [-2^w, 2^w] and fail the contract,
  while the branch hull proves the tight [-(2^(w-1)-1), 2^(w-1)]
  bound the magnitude-bucket staging indexes with. The carry chain
  itself is a Python loop over a static window count, so every
  iterate's interval is checked int32-wrap-free.

- ``madd_niels_lazy`` — the 7-mul extended+niels point add with
  lazy-reduction depth 3: the six cross sums (y1-x1, y1+x1, e, f, g,
  h) stay UNCARRIED limb sums feeding fe_mul's generic |limb| <= 1024
  contract; only d = z1+z1 takes fe_add's carry pass (without it,
  f = d - c reaches 1536 and the product conv row escapes int32 —
  exactly the bound this module's cert entry pins). All four outputs
  are fe_mul results, so the accumulator contract |limb| <= 512 is
  closed under iteration: the whole static-round fill is proven by
  proving one round.

Adding the identity niels (yp, ym, t2d) = (1, 1, 0) scales the
accumulator's representation projectively ((X:Y:Z:T) -> (2XZ : 2YZ :
2Z^2 : 2XY), the same group element), which is why the lazy fill needs
NO output point_select for empty slots — ops/msm_pallas.py's kernel
fill rides the identical argument.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import fe25519 as fe

# fdcert entry contracts (fdlint pass 5 — grammar in lint/bounds.py).
# The recode entries prove the carry chain at every shippable width
# (msm_plan.PLAN_WIDTHS); the madd entry proves one fill round at the
# accumulator/niels bounds the staging feeds it, closed under
# iteration because every output coordinate is an fe_mul result.
FDCERT_CONTRACTS = {
    "_recode_step": {
        "inputs": ["blocks:1:256", "int:8"], "out_abs": 128,
        "doc": "one borrow step: v in [0, 2^w] -> digit in "
               "[-(2^(w-1)-1), 2^(w-1)] (precise hull transfer)"},
    "recode_signed_w6": {
        "inputs": ["bytes2:43:8"], "out_abs": 32,
        "doc": "43-window (253-bit) balanced recode at w=6"},
    "recode_signed_w7": {
        "inputs": ["bytes2:37:8"], "out_abs": 64,
        "doc": "37-window (253-bit) balanced recode at w=7"},
    "recode_signed_w8": {
        "inputs": ["bytes2:32:8"], "out_abs": 128,
        "doc": "32-window (253-bit) balanced recode at w=8"},
    "madd_niels_lazy": {
        "inputs": ["limbs:32:512:2", "limbs:32:512:2", "limbs:32:512:2",
                   "limbs:32:512:2", "limbs:32:1024:2", "limbs:32:1024:2",
                   "limbs:32:512:2"],
        "out_abs": 512,
        "doc": "7-mul extended+niels add, lazy depth 3; accumulator "
               "contract closed under iteration"},
}


def _recode_step(v, w_bits):
    """One borrow-propagating step: v = d_t + c_in in [0, 2^w] maps to
    (digit, c_out) with digit = v - c_out * 2^w and c_out = (v > 2^(w-1)).
    The certifier swaps this for its precise hull transfer by name."""
    half = 1 << (w_bits - 1)
    borrow = (v > half).astype(jnp.int32)
    return v - (borrow << w_bits), borrow


def recode_signed(d, w_bits):
    """Balanced signed-window recode of unsigned w_bits-wide digits.

    d: (n_windows, ...) int-like, LSB-first windows, each in
    [0, 2^w - 1] (masked on entry so the proof covers byte inputs).
    Returns int32 signed digits of the same shape, each in
    [-(2^(w-1)-1), 2^(w-1)]. The final borrow is 0 whenever the window
    count follows msm_plan.plan_windows for the scalar width — the top
    window's raw digit never exceeds 2^(w-1) - 1, so it absorbs the
    incoming carry without wrapping."""
    d = jnp.asarray(d).astype(jnp.int32) & ((1 << w_bits) - 1)
    c = jnp.zeros(d.shape[1:], jnp.int32)
    outs = []
    for t in range(d.shape[0]):
        digit, c = _recode_step(d[t] + c, w_bits)
        outs.append(digit)
    return jnp.stack(outs, axis=0)


def recode_signed_w6(d):
    return recode_signed(d, 6)


def recode_signed_w7(d):
    return recode_signed(d, 7)


def recode_signed_w8(d):
    return recode_signed(d, 8)


def madd_niels_lazy(x1, y1, z1, t1, yp2, ym2, t2d2):
    """Extended (x1, y1, z1, t1) + niels (yp2, ym2, t2d2) -> extended,
    7 field muls, lazy-reduction depth 3 (see module docstring for the
    bound closure). With the identity niels (1, 1, 0) the result is
    the same group element, representation scaled — the fill's
    select-free empty-slot trick."""
    a = fe.fe_mul(y1 - x1, ym2)
    b = fe.fe_mul(y1 + x1, yp2)
    c = fe.fe_mul(t1, t2d2)
    d = fe.fe_add(z1, z1)
    e = b - a
    f = d - c
    g = d + c
    h = b + a
    return (fe.fe_mul(e, f), fe.fe_mul(g, h),
            fe.fe_mul(f, g), fe.fe_mul(e, h))
