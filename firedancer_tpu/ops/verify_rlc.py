"""Batch Ed25519 verification by random linear combination (RLC).

The TPU-first answer to the per-signature cost wall: a single-lane
verify pays ~256 doublings + 128 table adds in the double-scalar-mult
(ops/verify.py), and no amount of kernel tuning removes that work. RLC
batch verification (the standard ed25519 batch equation, e.g.
ed25519-dalek / RFC 8032 §8.2 discussion) replaces B per-lane
scalar-mults with ONE multi-scalar-multiplication whose doubling chain
is shared across the whole batch:

    T = (sum_i z_i s_i mod L) * B  +  sum_i z_i * (-R_i)  +  sum_i (z_i h_i mod L) * (-A_i)
    batch valid  <=>  T == identity  AND  all live R_i, A_i torsion-free

with z_i fresh random 126-bit scalars chosen AFTER the signatures are
known. The MSM is computed with Pippenger bucket accumulation
(ops/msm.py) — bucket fill cost amortizes the doublings over all lanes.

Soundness (why the torsion condition is load-bearing): the RLC equation
alone is only sound against defects in the PRIME-ORDER component. An
adversary can craft lanes whose per-lane defect D_i = s_i*B - h_i*A_i
- R_i lies entirely in the 8-torsion subgroup (e.g. R_i = r_i*B + T
with T the order-2 point): each such lane fails the per-lane
byte-compare, but the combined torsion defect sum z_i t_i mod 8 cancels
with probability up to 1/2 per batch — catastrophic for a consensus
path. The fix is a randomized subgroup certification
(msm.subgroup_check) over all live lanes' R_i and A_i: K independent
random aggregates, each multiplied by the group order and compared to
the identity. Torsion-free R and A (plus torsion-free B) make every
D_i torsion-free, restoring the RLC bound. Combined soundness per
batch accept: <= 2^-126 for prime-order defects + <= 2^-K for
torsion defects (K = FD_RLC_TORSION_K, default 64). Honest traffic
(real keys and nonces are prime-order) never trips the check; a
tripped check only routes the batch to the exact per-lane path.

Semantics parity with the reference's DEFAULT (2-point) verify
(fd_ed25519_user.c:346-433, FD_ED25519_VERIFY_USE_2POINT=1; round-5,
pinned by the 396 Zcash malleability vectors — see ops/verify.py):
- s range check (ERR_SIG) exactly as the per-lane path.
- A or R failing decompression is definite ERR_PUBKEY (the reference's
  frombytes_vartime_2 reports both with the shared code); small-order A
  is definite ERR_PUBKEY, small-order R definite ERR_SIG. These lanes
  are excluded from the combination (z_i = 0).
- The per-lane compare is on GROUP ELEMENTS (projective cross-multiply
  against the decoded R), so a non-canonical-but-decodable r encoding
  stays LIVE — the RLC equation on points is exactly the right test.

Failure handling is the caller's job (disco/tiles.py): if the batch
equation fails, at least one lane is bad — re-dispatch the batch on the
per-lane path. Worst case (adversary salts every batch) costs one extra
RLC pass (~0.4x a direct pass); the clean-traffic common case runs
~2-3x faster than per-lane verify.

Engine selection (round-6 un-park): RLC is the PRIMARY device verify
mode, and on TPU its MSM runs on the VMEM Pallas Pippenger kernels
(ops/msm_pallas.py) — bucket state resident in VMEM across the fill
rounds, the running-sum aggregation, and the cross-window Horner, so
the doubling chain is paid once per batch. The round-4 parking decision
was made on the XLA-graph MSM only (VERDICT.md r5 weak #4: "parked on
the wrong evidence"); the kernel engine had never run as the RLC
backend. FD_MSM_IMPL picks explicitly: 'pallas' | 'xla' |
'interpret' (the production kernels under the Pallas interpreter, so
CPU CI can parity-test the exact engine that ships); 'auto' resolves
to pallas on TPU platforms. docs/ROOFLINE.md carries the op-count
analysis that motivates the promotion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from firedancer_tpu import flags

from . import curve25519 as ge
from . import fe25519 as fe
from . import msm as msm_mod
from . import sc25519 as sc
# Top-level, not trace-time: frontend_pallas transitively materializes
# sha512/sign's module-scope jnp constants; importing inside the traced
# body would leak tracers into those globals on the first call.
from .frontend_pallas import frontend_decompress_auto, frontend_rlc_auto
from .verify import (
    FD_ED25519_ERR_PUBKEY,
    FD_ED25519_ERR_SIG,
    FD_ED25519_SUCCESS,
)

def msm_engine() -> str:
    """Trace-time MSM engine for the RLC pass: 'pallas' (VMEM Pippenger
    kernels — the production TPU engine), 'xla' (graph MSM, CPU hosts),
    or 'interpret' (the Pallas kernels under the interpreter: slow, but
    it exercises the production engine's exact staging/fill/aggregation
    code on CPU CI). FD_MSM_IMPL forces any of the three; 'auto' (the
    default) resolves to pallas exactly when the attached backend is a
    TPU family (ops.backend.use_pallas). An unrecognized value is an
    error — a typo'd force must never quietly test the wrong engine."""
    impl = flags.get_str("FD_MSM_IMPL")
    if impl == "interpret":
        return "interpret"
    if impl not in ("", "auto", "xla", "pallas"):
        raise ValueError(
            f"unknown FD_MSM_IMPL {impl!r} "
            "(want auto|xla|pallas|interpret)"
        )
    from .backend import use_pallas

    return "pallas" if use_pallas("FD_MSM_IMPL") else "xla"


def fresh_z(batch: int, rng: np.random.Generator | None = None) -> np.ndarray:
    """(B, 32) uint8: uniform random 126-bit scalars (top 16 bytes zero).

    Host-side entropy: z must be unpredictable to whoever crafted the
    signatures, so it is drawn per batch, never fixed in the graph.
    126 bits = 18 exact 7-bit MSM windows (msm.WINDOWS_Z), keeping every
    window's digit distribution uniform.

    z is FULLY uniform — no bit is forced. (An earlier revision forced
    z_i odd to avoid zero weights; all-odd z makes order-2 torsion
    defects cancel pairwise DETERMINISTICALLY, z_i + z_j always even —
    part of the torsion unsoundness fixed by msm.subgroup_check.) A
    zero z_i drops lane i's check with probability 2^-126 per lane,
    which is absorbed by the stated soundness bound.

    Default entropy is os.urandom (CSPRNG) — the soundness claim rests
    on z being unpredictable, which a statistical PRNG does not provide.
    The rng parameter exists for deterministic tests only.
    """
    import os

    z = np.zeros((batch, 32), np.uint8)
    if rng is None:
        z[:, :16] = np.frombuffer(
            os.urandom(batch * 16), np.uint8
        ).reshape(batch, 16)
    else:
        z[:, :16] = rng.integers(0, 256, (batch, 16), dtype=np.uint8)
    z[:, 15] &= 0x3F
    return z


def fresh_u(k: int, batch: int,
            rng: np.random.Generator | None = None) -> np.ndarray:
    """(K, batch) int32 digits uniform in [0, 128): trial weights for the
    torsion subgroup certification (msm.subgroup_check). 7-bit digits
    load the fill buckets exactly like one MSM window, so the overflow
    analysis (msm._default_rounds) carries over unchanged. Same
    entropy requirements as fresh_z: os.urandom in production, the rng
    parameter for deterministic tests only."""
    import os

    if rng is None:
        raw = np.frombuffer(os.urandom(k * batch), np.uint8)
    else:
        raw = rng.integers(0, 256, k * batch, dtype=np.uint8)
    return (raw.astype(np.int32) & 0x7F).reshape(k, batch)




def verify_batch_rlc(msgs, msg_lengths, sigs, pubkeys, z_bytes, u_digits,
                     axis_name: str | None = None, plan=None):
    """One RLC pass over a batch.

    Args are as ops.verify.verify_batch, plus z_bytes (B, 32) uint8
    126-bit random weights (from fresh_z) and u_digits (K, 2B) int32
    trial weights for the torsion certification (from fresh_u; columns
    0..B-1 weight the pubkey points, B..2B-1 the R points).

    axis_name shards the batch over a device mesh (round-10): called
    under shard_map with per-device lane slices, the per-lane stages
    run locally and the MSMs combine per-window PARTIALS across the
    mesh (ops/msm.py axis_name plumbing) before the doubling-chain
    tails — the u*B term folds per shard (sum_d u_d*B == (sum_d u_d)*B
    in the group, so no scalar collective is needed), and batch_ok is
    the replicated global verdict. parallel/mesh.verify_rlc_step_sharded
    is the tile-facing builder.

    Returns (status, definite, batch_ok):
      status:   (B,) int32 — correct for lanes where definite is True;
                provisionally SUCCESS elsewhere.
      definite: (B,) bool — lanes whose status is final regardless of
                the batch equation (s-range / pubkey / R-encoding fails).
      batch_ok: () bool — True iff the combined equation holds AND every
                live lane's A and R are certified torsion-free, i.e.
                every non-definite lane is genuinely SUCCESS. On False
                the caller re-runs the per-lane path.

    fd_pod split (round-18): the body is verify_rlc_local (per-lane
    stages + local bucket fills, no collectives) composed with
    verify_rlc_combine (the cross-mesh gathers + doubling-chain tails)
    — the exact op sequence the monolithic step always ran, so this
    single-graph path stays bit-exact while parallel/mesh.py can jit
    the two halves separately and double-buffer them.

    plan (None = msm.active_plan()): the fd_msm2 MSM schedule, threaded
    to both halves so a (local, combine) pair always agrees on window
    counts and Horner stride (disco/engine.py resolves the per-rung
    winner from the EngineRegistry).
    """
    if plan is None:
        plan = msm_mod.active_plan()
    status, definite, parts = verify_rlc_local(
        msgs, msg_lengths, sigs, pubkeys, z_bytes, u_digits, plan=plan)
    batch_ok = verify_rlc_combine(parts, axis_name=axis_name, plan=plan)
    return status, definite, batch_ok


def verify_rlc_local(msgs, msg_lengths, sigs, pubkeys, z_bytes, u_digits,
                     plan=None, engine=None):
    """The LOCAL half of one RLC pass: s-range, stacked decompression,
    the fused SHA/mod-L front half, the status ladder, and the three
    Pippenger bucket fills/aggregations over THIS shard's lanes — no
    collectives, no doubling-chain tails.

    Returns (status, definite, parts): status/definite as
    verify_batch_rlc; parts the pytree of per-shard partials
    verify_rlc_combine consumes —
      w_r / ok_r    window partials + fill verdict of the z*(-R) MSM
      w_m / ok_m    same for the [m*(-A), u*B] 253-bit MSM
      sub / sub_ok  per-trial torsion aggregates + fill verdict
    Every leaf is a small array ((32, nw)-limb coords, () bools), so
    shipping parts between two jitted graphs costs microseconds.

    plan: the fd_msm2 schedule for all three fills. A lazy plan routes
    the XLA torsion fill through the 5-bit masked-digit grid (the same
    soundness argument subgroup_check_fast has always shipped) — the
    baseline keeps the historical 7-bit unified-add fill bit-identical.

    engine (None = msm_engine(), i.e. the trace-time flag): explicit
    MSM engine override. fdlint pass 7 traces the kernel-schedule graph
    on CPU by passing 'interpret' here — same dispatch the flag drives,
    no environment mutation inside the auditor.
    """
    if plan is None:
        plan = msm_mod.active_plan()
    r_bytes = sigs[:, :32]
    s_bytes = sigs[:, 32:]

    s_ok = sc.sc_check_range(s_bytes)

    # One decompression pass over A and R stacked: same lane-work, half
    # the traced graph (the power chain appears once). The niels forms
    # for the MSM fills ride along from the kernel (free in-VMEM vs
    # multi-ms XLA chains).
    from .backend import use_pallas

    bsz = pubkeys.shape[0]
    if engine is None:
        engine = msm_engine()
    on_tpu = engine == "pallas"
    # niels outputs are only consumed by the kernel MSM path, so both
    # backends must be on (a split config would compute and drop them).
    from .curve_pallas import MIN_KERNEL_BATCH

    want_niels = (on_tpu and use_pallas("FD_DECOMPRESS_IMPL")
                  and 2 * bsz >= MIN_KERNEL_BATCH)
    # Engine dispatch lives with the rest of the front half
    # (frontend_pallas): the Montgomery-batched decompress on eligible
    # shapes, staged composition otherwise — bit-exact either way.
    dec = frontend_decompress_auto(
        jnp.concatenate([pubkeys, r_bytes], axis=0),
        want_niels=want_niels,
    )
    both, both_ok = dec[:2]
    both_niels = dec[2] if want_niels else None
    a_point = tuple(c[:, :bsz] for c in both)
    r_point = tuple(c[:, bsz:] for c in both)
    pub_ok = both_ok[:bsz]
    r_dec_ok = both_ok[bsz:]

    # 2-point semantics (round-5, pinned by the Zcash malleability
    # vectors — see ops/verify.py): the per-lane path compares group
    # ELEMENTS, so a non-canonical-but-decodable r encoding is LIVE
    # (the RLC equation on points is exactly the right test), an
    # undecodable r is ERR_PUBKEY (frombytes_vartime_2's shared code),
    # and small-order A (ERR_PUBKEY) / R (ERR_SIG) are definite fails.
    so_both = ge.small_order_mask(both)
    a_small = so_both[:bsz]
    r_small = so_both[bsz:]

    status = jnp.where(
        ~s_ok,
        FD_ED25519_ERR_SIG,
        jnp.where(
            ~pub_ok | ~r_dec_ok | a_small,
            FD_ED25519_ERR_PUBKEY,
            jnp.where(r_small, FD_ED25519_ERR_SIG, FD_ED25519_SUCCESS),
        ),
    ).astype(jnp.int32)
    definite = ~(s_ok & pub_ok & r_dec_ok & ~a_small & ~r_small)

    # Zero out excluded lanes' weights; z=0 contributes the identity.
    live = ~definite
    z_live = jnp.where(live[:, None], z_bytes, 0).astype(jnp.uint8)

    # h = SHA-512(r||pub||msg) mod L, m = z*h mod L, zs = z*s mod L —
    # the fused front-end (ops/frontend_pallas.py) runs all three as
    # one VMEM kernel chained onto the compression when active and the
    # shape is eligible; the staged fallback keeps the historical
    # per-stage dispatch (FD_SHA_IMPL / FD_SC_IMPL, registry reads at
    # trace time — fdlint pass 1 sanctions exactly that). The z-live
    # masking rides INTO the fused muls (dead lanes: z = 0 -> m = zs =
    # 0, bit-identical to the staged path). u = sum zs mod L.
    _h_bytes, m_bytes, zs = frontend_rlc_auto(
        jnp.concatenate([r_bytes, pubkeys, msgs], axis=1),
        msg_lengths.astype(jnp.int32) + 64,
        z_live, s_bytes,
    )
    u_bytes = sc.sc_sum(zs)

    neg_r = ge.point_neg(r_point)
    neg_a = ge.point_neg(a_point)

    # Fold the u*B term into the 253-bit MSM as one extra lane (point B,
    # scalar u) — one engine, no separate fixed-base path.
    from .sign import _b_point

    b_pt, _ = _b_point(1)
    m_all = jnp.concatenate([m_bytes, u_bytes], axis=0)
    pts_all = tuple(
        jnp.concatenate([c_a, c_b], axis=1)
        for c_a, c_b in zip(neg_a, b_pt)
    )
    # niels forms from the decompress kernel: the negated point's form
    # is the coordinate swap (ym, yp, t2dn); the single B lane's form
    # is three tiny XLA ops.
    # Separate dict literals: chained assignment would alias one object
    # and let a future in-place mutation leak across the three kwargs.
    kw_r = {}
    kw_m = {}
    kw_sub = {}
    if both_niels is not None and on_tpu:
        yp, ym, t2d, t2dn = both_niels
        kw_r = {"niels": (ym[:, bsz:], yp[:, bsz:], t2dn[:, bsz:])}
        b_niels = (fe.fe_add(b_pt[1], b_pt[0]),
                   fe.fe_sub(b_pt[1], b_pt[0]),
                   fe.fe_mul(b_pt[3], fe.FE_D2))
        kw_m = {"niels": tuple(
            jnp.concatenate([na, nb], axis=1)
            for na, nb in zip(
                (ym[:, :bsz], yp[:, :bsz], t2dn[:, :bsz]), b_niels
            )
        )}
        kw_sub = {"niels": (yp, ym, t2d)}
    # Decompressed points have Z == 1, so the niels fast path applies.
    # Torsion certification is over the live lanes' A and R (the
    # stacked decompression output `both` is already in that column
    # order); dead lanes get zero trial weights — unweighted, identity
    # contribution.
    live2 = jnp.concatenate([live, live], axis=0)
    u_live = jnp.where(live2[None, :], u_digits, 0)
    if engine == "xla":
        w_r, ok_r = msm_mod.msm_partial(
            z_live, neg_r, msm_mod.WINDOWS_Z, plan=plan)
        w_m, ok_m = msm_mod.msm_partial(
            m_all, pts_all, msm_mod.WINDOWS_253, plan=plan)
        if plan.lazy:
            # The lazy engine's torsion grid: 5-bit masked trial digits
            # (subgroup_check_fast's shipping soundness argument) over
            # the certified niels madd — the fill that dominates the
            # whole MSM stage's lane count at production batch sizes.
            from firedancer_tpu.msm_plan import TORSION_BUCKET_BITS

            sub_agg, sub_okf = msm_mod.subgroup_partial(
                both, u_live, bucket_bits=TORSION_BUCKET_BITS,
                lazy=True)
        else:
            sub_agg, sub_okf = msm_mod.subgroup_partial(both, u_live)
    else:
        interp = engine == "interpret"
        w_r, ok_r = msm_mod.msm_fast_partial(
            z_live, neg_r, msm_mod.WINDOWS_Z, interpret=interp,
            plan=plan, **kw_r)
        w_m, ok_m = msm_mod.msm_fast_partial(
            m_all, pts_all, msm_mod.WINDOWS_253, interpret=interp,
            plan=plan, **kw_m)
        sub_agg, sub_okf = msm_mod.subgroup_fast_partial(
            both, u_live, interpret=interp, **kw_sub)
    parts = {
        "w_r": w_r, "ok_r": ok_r,
        "w_m": w_m, "ok_m": ok_m,
        "sub": sub_agg, "sub_ok": sub_okf,
    }
    return status, definite, parts


def _gather_parts(parts, axis_name: str):
    """ONE all_gather for the whole combine tail (round-17).

    The per-leaf gather path (msm._gather_point_sum + _all_shards_ok,
    once per grid) issued 15 collectives per combine; every partial
    leaf is tiny ((32, nw) limb planes, () verdicts), so the tail was
    latency-bound on collective COUNT, not bytes. Ravel every leaf
    into one flat int32 vector (verdict bools widen to int32), gather
    the (n_shards, N) table once, then rebuild the per-leaf shard
    stacks and fold them through the SAME rules the per-leaf path
    always used — combine_stacked in mesh order for coordinate stacks,
    AND across shards for verdicts — so every folded value is
    bit-identical to the historical path and only the data movement is
    fused. Returns GLOBAL parts; the caller runs the per-grid combines
    with axis_name=None. fdlint pass 7 proves the 'exactly one
    all_gather in the combine tail' contract against this graph."""
    leaves, treedef = jax.tree_util.tree_flatten(parts)
    flat = jnp.concatenate(
        [jnp.ravel(leaf).astype(jnp.int32) for leaf in leaves])
    table = jax.lax.all_gather(flat, axis_name)          # (n_shards, N)
    stacks = []
    off = 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape, dtype=np.int64))
        stacks.append(
            table[:, off:off + n].reshape((-1,) + leaf.shape)
            .astype(leaf.dtype))
        off += n
    stacked = jax.tree_util.tree_unflatten(treedef, stacks)
    out = {k: msm_mod.combine_stacked(stacked[k])
           for k in ("w_r", "w_m", "sub")}
    out.update({k: jnp.all(stacked[k], axis=0)
                for k in ("ok_r", "ok_m", "sub_ok")})
    return out


def verify_rlc_combine(parts, axis_name: str | None = None, plan=None,
                       engine=None):
    """The TAIL half of one RLC pass: combine the per-shard partials
    across the mesh (ONE fused all_gather via _gather_parts when
    axis_name; identity when None), run the three doubling-chain tails
    (two window Horners + the [L] torsion ladder), and fold the global
    batch verdict.

    The engine is re-resolved from the same trace-time flag the local
    half read (or forced via the engine parameter, as verify_rlc_local),
    so a (local, combine) pair traced under one environment always
    agrees on partial shapes. The kernel-path torsion combine
    evaluates every Mosaic-padded trial lane — sound, because the pad
    lanes carry zero coordinates that trivially pass the identity test
    (msm.subgroup_fast_partial documents the argument)."""
    if plan is None:
        plan = msm_mod.active_plan()
    if engine is None:
        engine = msm_engine()
    if axis_name is not None:
        parts = _gather_parts(parts, axis_name)
        axis_name = None
    if engine == "xla":
        t1, ok1 = msm_mod.msm_combine(
            parts["w_r"], parts["ok_r"], msm_mod.WINDOWS_Z,
            axis_name=axis_name, plan=plan)
        t2, ok2 = msm_mod.msm_combine(
            parts["w_m"], parts["ok_m"], msm_mod.WINDOWS_253,
            axis_name=axis_name, plan=plan)
        sub_ok, sub_fill_ok = msm_mod.subgroup_combine(
            parts["sub"], parts["sub_ok"], axis_name=axis_name)
    else:
        interp = engine == "interpret"
        t1, ok1 = msm_mod.msm_fast_combine(
            parts["w_r"], parts["ok_r"], msm_mod.WINDOWS_Z,
            interpret=interp, axis_name=axis_name, plan=plan)
        t2, ok2 = msm_mod.msm_fast_combine(
            parts["w_m"], parts["ok_m"], msm_mod.WINDOWS_253,
            interpret=interp, axis_name=axis_name, plan=plan)
        sub_ok, sub_fill_ok = msm_mod.subgroup_fast_combine(
            parts["sub"], parts["sub_ok"], interpret=interp,
            axis_name=axis_name)
    # T = u*B + sum z(-R) + sum m(-A); identity <=> X == 0 and Y == Z.
    t = ge.point_add(t1, t2, need_t=False)
    batch_ok = (
        fe.fe_is_zero(t[0]) & fe.fe_eq(t[1], t[2]) & ok1 & ok2
        & sub_ok & sub_fill_ok
    )
    return batch_ok


class RlcAsyncResult:
    """Duck-types the slice of the jax.Array surface the verify tile's
    completion shim uses (`is_ready()`, `np.asarray`) over an RLC pass
    with lazy per-lane fallback.

    The RLC pass and the fallback both dispatch asynchronously; the
    fallback is only ever dispatched once the RLC verdict is known to be
    False, so clean batches cost one pass and dirty batches two — the
    shim's in-flight accounting and ordering are untouched.
    """

    def __init__(self, rlc_out, fallback_fn, args):
        self._status, self._definite, self._ok = rlc_out
        self._fallback_fn = fallback_fn
        self._args = args
        self._fb = None
        self._resolved = None
        self.used_fallback = False

    def _start_fallback(self):
        self._fb = self._fallback_fn(*self._args)
        self._args = None
        self.used_fallback = True

    def is_ready(self) -> bool:
        if self._resolved is not None:
            return True
        if self._fb is not None:
            return self._fb.is_ready()
        if not self._ok.is_ready():
            return False
        if bool(self._ok):
            self._resolved = np.asarray(self._status)
            return True
        self._start_fallback()
        return self._fb.is_ready()

    def __array__(self, dtype=None, copy=None):
        if self._resolved is None:
            if self._fb is None:
                if bool(self._ok):          # blocks on the RLC pass
                    self._resolved = np.asarray(self._status)
                else:
                    self._start_fallback()
            if self._resolved is None:
                self._resolved = np.asarray(self._fb)  # blocks on fallback
        out = self._resolved
        return out.astype(dtype) if dtype is not None else out


def make_async_verifier(fallback_fn, rng: np.random.Generator | None = None,
                        rlc_fn=None, torsion_k: int | None = None):
    """A drop-in for jit(verify_batch) with RLC fast-pass semantics.

    Returns fn(msgs, lens, sigs, pubs) -> RlcAsyncResult. fallback_fn is
    the compiled per-lane verifier used when the batch equation fails;
    rlc_fn overrides the jitted RLC pass (e.g. a shared compiled
    instance in tests). Fresh z and torsion-trial u weights are drawn
    per call (never baked into the graph), from os.urandom by default —
    the soundness contract (module docstring) requires CSPRNG entropy
    in production; pass rng only for deterministic tests. torsion_k is
    the subgroup-check trial count (default FD_RLC_TORSION_K or 64).
    """
    import jax

    rlc = rlc_fn if rlc_fn is not None else jax.jit(verify_batch_rlc)
    if torsion_k is None:
        torsion_k = flags.get_int("FD_RLC_TORSION_K")

    def fn(msgs, lens, sigs, pubs):
        bsz = msgs.shape[0]
        z = jnp.asarray(fresh_z(bsz, rng))
        u = jnp.asarray(fresh_u(torsion_k, 2 * bsz, rng))
        out = rlc(msgs, lens, sigs, pubs, z, u)
        return RlcAsyncResult(out, fallback_fn, (msgs, lens, sigs, pubs))

    return fn


# --------------------------------------------------------------------- #
# fdlint pass 7 (graph-audit) contracts — literals, read with
# ast.literal_eval by firedancer_tpu/lint/graphs.py, never imported.
# Grammar + rules: docs/GRAPHS.md.  `rlc_mono`/`pod_local`/`rlc_sharded`
# are derived graphs: thin wrappers proved by AST witness over the
# traced halves (lint/graphs.py:DERIVED_WITNESS), so their collective
# inventory is the composition of the halves' inventories.
# --------------------------------------------------------------------- #

GRAPH_CONTRACTS = {
    "rlc_local": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
        "madds": {"engine": "xla", "tolerance_pct": 2.0},
    },
    "rlc_tail": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
    },
    "pod_tail": {
        "collectives": {"all_gather": 1},
        "axes": ["dp"],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
    },
    "kernel_tail": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int16", "int32", "uint32", "uint8"],
        "vmem_mb": 64.0,
    },
    "rlc_mono": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
        "derived_from": ["rlc_local", "rlc_tail"],
    },
    "rlc_sharded": {
        "collectives": {"all_gather": 1},
        "axes": ["dp"],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
        "derived_from": ["rlc_local", "pod_tail"],
    },
    "pod_local": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
        "derived_from": ["rlc_local"],
    },
}
