"""Batched scalar arithmetic mod L = 2^252 + 27742... for TPU.

Replaces the reference's fd_ed25519_sc_reduce
(/root/reference/src/ballet/ed25519/fd_ed25519_user.c:414, impl in
fd_curve25519_scalar.c-style code) with a batch Barrett reduction in
radix-2^8 int32 limbs — byte-aligned shifts only, no 64-bit arithmetic,
sequential exactness confined to short lax.scan carry chains.

Barrett with b = 2^8, k = 32 (b^k = 2^256 > L):
    mu = floor(b^(2k) / L)            (33 limbs, precomputed)
    q1 = floor(x / b^(k-1))           (drop 31 limbs)
    q3 = floor(q1 * mu / b^(k+1))     (conv + drop 33 limbs)
    r  = (x - q3*L) mod b^(k+1)       in [0, 3L)
then two conditional subtractions of L. Valid for any x < b^(2k) = 2^512,
which covers the 64-byte SHA-512 output.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from . import fe25519

L = 2**252 + 27742317777372353535851937790883648493
_MU = (2**512) // L  # 259-bit

# fdcert entry contracts (fdlint pass 5 — see ops/fe25519.py's table
# for the grammar). The Barrett body is the CPU/test reference the
# fused front-end mirrors bit-exactly, so its proof is the anchor for
# frontend_pallas's folded twin.
FDCERT_CONTRACTS = {
    "sc_reduce64": {"inputs": ["bytes:64"], "out_abs": 255,
                    "doc": "Barrett b=2^8 k=32; q2 rows < 2^21"},
    "sc_sum": {"inputs": ["bytes2:32768:32"], "out_abs": 255,
               "doc": "batch scalar sum at the max shipping batch "
                      "(32768 lanes; limb sums < 2^23)"},
    "sc_check_range": {"inputs": ["bytes2:1:32"], "out_abs": 1,
                       "doc": "lexicographic s < L compare"},
}

_L_LIMBS33 = jnp.asarray(
    [(L >> (8 * i)) & 0xFF for i in range(33)], jnp.int32
).reshape(33, 1)
_MU_LIMBS = np.asarray([( _MU >> (8 * i)) & 0xFF for i in range(33)], np.int32)


def _conv_matrix(n_in: int, n_out: int, weights: np.ndarray) -> jnp.ndarray:
    """T[k, i] = weights[k - i] — contraction computes conv(x, weights)."""
    t = np.zeros((n_out, n_in), np.int32)
    for i in range(n_in):
        for j in range(len(weights)):
            if i + j < n_out:
                t[i + j, i] = weights[j]
    return jnp.asarray(t)


_T_MU = _conv_matrix(33, 66, _MU_LIMBS)                 # q1(33) -> q1*mu(66)
_T_L = _conv_matrix(33, 33, np.asarray(
    [(L >> (8 * i)) & 0xFF for i in range(33)], np.int32))  # q3*L mod b^33


# Exact base-256 carry chain shared with the field module (one impl).
_seq_carry = fe25519._seq_carry


def sc_reduce64(hash_bytes: jnp.ndarray) -> jnp.ndarray:
    """(*batch, 64) uint8 little-endian -> canonical (*batch, 32) uint8 mod L."""
    x = jnp.moveaxis(hash_bytes.astype(jnp.int32), -1, 0)   # (64, B) canonical
    q1 = x[31:]                                              # (33, B)
    q2 = jnp.tensordot(_T_MU, q1, axes=1)                    # (66, B), <= 2^21.1
    q2, _ = _seq_carry(q2)                                   # canonical
    q3 = q2[33:]                                             # (33, B) = floor(q1*mu/b^33)
    q3l = jnp.tensordot(_T_L, q3, axes=1)                    # (33, B) mod b^33
    q3l, _ = _seq_carry(q3l)
    # r = (x - q3*L) mod b^33: borrow-propagating subtract, final borrow
    # discarded (that IS the mod-b^33 wrap).
    r, _ = _seq_carry(x[:33] - q3l)
    # r in [0, 3L): subtract L at most twice.
    for _ in range(2):
        d, borrow = _seq_carry(r - _L_LIMBS33)
        r = jnp.where(borrow[None] < 0, r, d)
    return jnp.moveaxis(r[:32], 0, -1).astype(jnp.uint8)


def sc_reduce64_auto(hash_bytes: jnp.ndarray) -> jnp.ndarray:
    """Backend-dispatched sc_reduce64. Round-4 measurement on v5e:
    the XLA graph (5.3 ms @8192) beats the VMEM Barrett kernel
    (14.7 ms — the scalar path is short and fuses well in XLA), so XLA
    is the default everywhere; FD_SC_IMPL=pallas opts back in."""
    from firedancer_tpu import flags

    if flags.get_raw("FD_SC_IMPL") == "pallas":
        from .sc_pallas import sc_reduce64_pallas

        return sc_reduce64_pallas(hash_bytes)
    return sc_reduce64(hash_bytes)


def sc_sum(s_bytes: jnp.ndarray) -> jnp.ndarray:
    """Sum of a batch of scalars mod L: (B, 32) uint8 -> (1, 32) uint8.

    Limb-wise int32 sum (exact for B < 2^23), exact carry to a 64-byte
    integer (< B * L < 2^512 for any practical batch), then the shared
    Barrett reduction.
    """
    x = jnp.sum(s_bytes.astype(jnp.int32), axis=0)[:, None]  # (32, 1)
    limbs, carry = _seq_carry(x)
    out = jnp.zeros((64, 1), jnp.int32)
    out = out.at[:32].set(limbs)
    out = out.at[32].set(carry & 0xFF)
    out = out.at[33].set((carry >> 8) & 0xFF)
    out = out.at[34].set((carry >> 16) & 0xFF)
    return sc_reduce64(jnp.moveaxis(out, 0, -1).astype(jnp.uint8))


def sc_check_range(s_bytes: jnp.ndarray) -> jnp.ndarray:
    """Vectorized s < L check on (*batch, 32) uint8 little-endian scalars.

    Upstream semantics (reject s >= L) — see the oracle module docstring for
    the documented divergence from the fork's quirk at
    fd_ed25519_user.c:379.
    """
    l_bytes = jnp.asarray([(L >> (8 * i)) & 0xFF for i in range(32)],
                          jnp.int32)
    s = s_bytes.astype(jnp.int32)
    # Lexicographic compare from the most significant byte down.
    lt = jnp.zeros(s.shape[:-1], jnp.bool_)
    decided = jnp.zeros(s.shape[:-1], jnp.bool_)
    for i in range(31, -1, -1):
        b = s[..., i]
        lb = l_bytes[i]
        lt = jnp.where(~decided & (b < lb), True, lt)
        decided = decided | (b != lb)
    return lt
