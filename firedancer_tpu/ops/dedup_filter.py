"""fd_drain dedup pre-filter — device-resident tag-hash membership test.

One jitted graph answers, for a batch of 64-bit dedup tags (the
`meta_sig` of each staged txn), "is this tag DEFINITELY novel, or only
MAYBE a duplicate?" against a sliding window of recently published
tags.  The window is two bitset banks (uint32 lanes) resident on the
device; the host rotates banks (B <- A, A <- 0) only after enough
confirmed-novel publishes that nothing still tracked by the host
TCache can fall out of A | B (see disco/drain.py for the rotation
proof obligation).

The verdict is one-sided BY CONSTRUCTION:

  * "novel"     -> the tag's bucket bit is clear in A | B AND the tag
                   is the first occurrence of its value inside the
                   batch.  Because every tag the host TCache holds had
                   its bucket bit set when it was published (and bank
                   rotation never drops a bit before the TCache has
                   provably evicted every tag that set it), a clear
                   bit proves TCache membership is impossible.
                   DedupTile may skip the probe and blind-insert.
  * "maybe dup" -> anything else: bucket occupied (real dup OR hash
                   collision), in-batch repeat, invalid lane.  The
                   host TCache stays the exact authority; a collision
                   costs one probe, never a wrong answer.

In-batch first-occurrence collapse rides the same graph: a stable sort
over the (hi, lo) tag pair spots equal neighbours, so two copies of
one tag inside a single batch can never both claim novelty (the first
claims, the repeat probes and the TCache — updated by the first's
blind insert — catches it).

Everything is uint32/int32/bool: 64-bit tags travel as (hi, lo)
uint32 pairs because the hot graphs run with the x64 lattice disabled
(fdlint pass 7 forbids int64/uint64 outright).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

#: Default sliding-window size in bits (FD_DRAIN_FILTER_BITS).  Must be
#: a power of two; 1 << 17 bits = 16 KiB per bank, comfortably
#: device-resident while keeping the false-maybe-dup rate ~ B / 2^17
#: per batch lane.
DEFAULT_FILTER_BITS = 1 << 17

#: Odd 32-bit mix constants (Knuth / xxhash finalizer family).
_MIX_A = 0x9E3779B1
_MIX_B = 0x85EBCA77


def filter_words(h_bits: int) -> int:
    """uint32 words per bank for an `h_bits`-bit window."""
    if h_bits <= 0 or (h_bits & (h_bits - 1)) != 0 or h_bits % 32:
        raise ValueError(f"h_bits must be a power of two >= 32: {h_bits}")
    return h_bits // 32


def split_tags(tags_u64):
    """numpy uint64 tag vector -> (hi, lo) uint32 pair (host helper)."""
    import numpy as np

    t = np.asarray(tags_u64, dtype=np.uint64)
    lo = (t & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (t >> np.uint64(32)).astype(np.uint32)
    return hi, lo


def _bucket(tags_hi, tags_lo, h_bits: int):
    """Per-lane bucket index in [0, h_bits): a cheap avalanche mix of
    the 64-bit tag.  Same tag -> same bucket always (the one-sided
    contract needs determinism, not uniformity; uniformity only sets
    the collision -> probe rate)."""
    mix = tags_lo ^ (tags_hi * jnp.uint32(_MIX_A))
    mix = (mix ^ (mix >> 15)) * jnp.uint32(_MIX_B)
    mix = mix ^ (mix >> 13)
    return (mix & jnp.uint32(h_bits - 1)).astype(jnp.int32)


def dedup_filter(tags_hi, tags_lo, valid, bits_a, bits_b):
    """One drain-filter round.

    Args:
      tags_hi, tags_lo: (B,) uint32 — 64-bit dedup tags, split.
      valid:            (B,) bool   — lane carries a real staged txn.
      bits_a:           (W,) uint32 — current bank (receives inserts).
      bits_b:           (W,) uint32 — previous bank (read-only here).

    Returns (novel, bits_a_new, novel_cnt):
      novel:      (B,) bool  — definitely-novel verdict per lane.
      bits_a_new: (W,) uint32 — bank A with every valid first-occurrence
                  bucket bit set (novel or not: maybe-dups are inserted
                  too, so the window over-approximates — the safe
                  direction).
      novel_cnt:  () int32   — popcount of `novel`.
    """
    n = tags_hi.shape[0]
    n_words = bits_a.shape[0]
    h_bits = n_words * 32

    bucket = _bucket(tags_hi, tags_lo, h_bits)
    word = bucket >> 5
    bit = (bucket & 31).astype(jnp.uint32)
    window = bits_a[word] | bits_b[word]
    window_hit = ((window >> bit) & jnp.uint32(1)) != 0

    # In-batch first-occurrence collapse: stable sort on the tag pair;
    # invalid lanes are forced onto an all-ones sentinel key so they
    # sort to the end.  A real tag equal to the sentinel simply loses
    # first-occurrence and goes maybe-dup — the safe direction.
    sentinel = jnp.uint32(0xFFFFFFFF)
    k_hi = jnp.where(valid, tags_hi, sentinel)
    k_lo = jnp.where(valid, tags_lo, sentinel)
    idx = jax.lax.iota(jnp.int32, n)
    s_hi, s_lo, s_idx = jax.lax.sort((k_hi, k_lo, idx), num_keys=3)
    rep = jnp.concatenate([
        jnp.zeros((1,), jnp.bool_),
        (s_hi[1:] == s_hi[:-1]) & (s_lo[1:] == s_lo[:-1]),
    ])
    first = jnp.zeros((n,), jnp.bool_).at[s_idx].set(~rep)
    first = first & valid

    novel = first & ~window_hit

    # Insert EVERY valid first occurrence into bank A (duplicate
    # buckets collapse via scatter-set of True; out-of-range sentinel
    # drops the masked-off lanes).
    ins_bucket = jnp.where(first, bucket, jnp.int32(h_bits))
    occ = jnp.zeros((h_bits,), jnp.bool_).at[ins_bucket].set(
        True, mode="drop")
    lane_bits = jnp.where(
        occ.reshape(n_words, 32),
        jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32),
        jnp.uint32(0))
    # The 32 columns are distinct powers of two, so sum == bitwise-or
    # (and reduce_sum is in pass 7's blessed primitive table).
    packed = jnp.sum(lane_bits, axis=1, dtype=jnp.uint32)
    bits_a_new = bits_a | packed

    novel_cnt = jnp.sum(novel.astype(jnp.int32))
    return novel, bits_a_new, novel_cnt


#: Jitted entry point (shapes are the only static state).
dedup_filter_jit = jax.jit(dedup_filter)


@partial(jax.jit, static_argnames=("h_bits",))
def empty_banks(h_bits: int = DEFAULT_FILTER_BITS):
    """Fresh (bits_a, bits_b) pair — all-clear window (everything goes
    maybe-dup until bits accumulate; safe by construction)."""
    w = filter_words(h_bits)
    z = jnp.zeros((w,), jnp.uint32)
    return z, z


# --------------------------------------------------------------------- #
# fdlint pass 7 (graph-audit) contracts — literals, read with
# ast.literal_eval by firedancer_tpu/lint/graphs.py, never imported.
# `drain_filter` is the traced filter round above; `drain_pair` is the
# composed verify+filter drain step (disco/drain.py::drain_pair), an
# AST-witnessed derivation over the traced `direct` verify graph and
# `drain_filter` — both collective-free by contract, so the fused
# drain step can never smuggle a collective or an x64 dtype into the
# hot path.
# --------------------------------------------------------------------- #

GRAPH_CONTRACTS = {
    "drain_filter": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32"],
    },
    "drain_pair": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
        "derived_from": ["direct", "drain_filter"],
    },
}
