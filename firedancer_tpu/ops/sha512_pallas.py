"""Pallas TPU kernel for the batched SHA-512 compression loop.

The XLA sha512_batch costs ~15.6 ms at B=8192 on v5e — the 80-round
compression and 64-step schedule extension become hundreds of small
HBM-streamed elementwise kernels. Here the whole multi-block absorb
runs in one kernel with the working state in VMEM.

Layout: every 64-bit word is an (hi, lo) uint32 pair (TPU has no
64-bit integers — same decision as ops/sha512.py), and the batch axis
is folded to (8, B/8) so each word occupies a FULL (8, 128)-tile VPU
vreg instead of a single sublane row — 8x the lane utilization of the
naive (1, B) layout. The byte->word packing, padding arithmetic, and
digest assembly stay in XLA (cheap elementwise + transposes); the
kernel consumes pre-packed schedule words.

Round structure and constants follow FIPS 180-4 via ops/sha512.py's
helpers (one implementation of rotr/add64/sigma shared by both paths —
the XLA path remains the CPU/test reference).

The compression body (`_sha512_rounds`) and the XLA-side schedule
packing (`_pack_schedule`) are module-level so the fused verify
front-end (ops/frontend_pallas.py) can chain the mod-L reduction and
the RLC coefficient muls onto the digest while it still sits in VMEM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import sha512 as s

SUB = 8  # sublane fold of the batch axis


def _sha512_rounds(win_hi, win_lo, nblocks, *, max_blocks: int):
    """The multi-block SHA-512 absorb on folded VMEM tiles.

    win_hi/lo: (max_blocks*16*SUB, Lb) uint32 message words, word w of
    block b at rows [(b*16+w)*SUB : +SUB]. nblocks: (SUB, Lb) int32
    per-lane block counts. Returns the final state as a list of 8
    (hi, lo) pairs, each (SUB, Lb) uint32.

    The 80-round loop is statically unrolled, so the round constants
    are Python int literals folded into the instruction stream — no
    constant-array input needed (Pallas forbids captured arrays, and a
    (1, 1) VMEM scalar read would need a both-axes broadcast Mosaic
    does not implement)."""
    lanes = win_hi.shape[1]

    def rotr(h, l, n):
        return s._rotr64(h, l, n)

    def shr(h, l, n):
        return s._shr64(h, l, n)

    def add64(ah, al, bh, bl):
        lo = al + bl
        carry = (lo < al).astype(jnp.uint32)
        return ah + bh + carry, lo

    def xor3p(p0, p1, p2):
        return (p0[0] ^ p1[0] ^ p2[0], p0[1] ^ p1[1] ^ p2[1])

    # state: 8 (hi, lo) pairs, (SUB, lanes) each.
    state = []
    for i in range(8):
        hi = jnp.full((SUB, lanes), np.uint32(s._IV[i] >> 32), jnp.uint32)
        lo = jnp.full((SUB, lanes), np.uint32(s._IV[i] & 0xFFFFFFFF),
                      jnp.uint32)
        state.append((hi, lo))

    for b in range(max_blocks):
        # load the 16 message words of block b
        wh = [win_hi[(b * 16 + w) * SUB:(b * 16 + w + 1) * SUB]
              for w in range(16)]
        wl = [win_lo[(b * 16 + w) * SUB:(b * 16 + w + 1) * SUB]
              for w in range(16)]
        # schedule extension 16 -> 80 (rolling window, fully unrolled)
        for t in range(16, 80):
            s0 = xor3p(rotr(wh[t - 15], wl[t - 15], 1),
                       rotr(wh[t - 15], wl[t - 15], 8),
                       shr(wh[t - 15], wl[t - 15], 7))
            s1 = xor3p(rotr(wh[t - 2], wl[t - 2], 19),
                       rotr(wh[t - 2], wl[t - 2], 61),
                       shr(wh[t - 2], wl[t - 2], 6))
            nh, nl = add64(wh[t - 16], wl[t - 16], s0[0], s0[1])
            nh, nl = add64(nh, nl, wh[t - 7], wl[t - 7])
            nh, nl = add64(nh, nl, s1[0], s1[1])
            wh.append(nh)
            wl.append(nl)

        a, bb, c, d, e, f, g, h = state
        for t in range(80):
            s1 = xor3p(rotr(e[0], e[1], 14), rotr(e[0], e[1], 18),
                       rotr(e[0], e[1], 41))
            ch = (e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1])
            kh = np.uint32(s._K[t] >> 32)
            kl = np.uint32(s._K[t] & 0xFFFFFFFF)
            t1h, t1l = add64(h[0], h[1], s1[0], s1[1])
            t1h, t1l = add64(t1h, t1l, ch[0], ch[1])
            t1h, t1l = add64(t1h, t1l, kh, kl)
            t1h, t1l = add64(t1h, t1l, wh[t], wl[t])
            s0 = xor3p(rotr(a[0], a[1], 28), rotr(a[0], a[1], 34),
                       rotr(a[0], a[1], 39))
            maj = ((a[0] & bb[0]) ^ (a[0] & c[0]) ^ (bb[0] & c[0]),
                   (a[1] & bb[1]) ^ (a[1] & c[1]) ^ (bb[1] & c[1]))
            t2h, t2l = add64(s0[0], s0[1], maj[0], maj[1])
            ne = add64(d[0], d[1], t1h, t1l)
            na = add64(t1h, t1l, t2h, t2l)
            a, bb, c, d, e, f, g, h = (na, a, bb, c, ne, e, f, g)

        # feed-forward + per-lane active masking (lane done once
        # b >= its block count)
        active = (nblocks > b).astype(jnp.uint32)
        new_state = []
        for i, (sh_, sl_) in enumerate(state):
            vh, vl = add64(sh_, sl_, *( (a, bb, c, d, e, f, g, h)[i] ))
            new_state.append((active * vh + (1 - active) * sh_,
                              active * vl + (1 - active) * sl_))
        state = new_state
    return state


def _sha512_kernel(win_hi, win_lo, nblk, out, *, max_blocks: int):
    """win_hi/lo, nblk as _sha512_rounds. out: (16*SUB, Lb) uint32
    digest words, word w's hi at rows [2w*SUB : +SUB], its lo at the
    following SUB rows."""
    state = _sha512_rounds(win_hi[...], win_lo[...], nblk[...],
                           max_blocks=max_blocks)
    rows = []
    for i in range(8):
        rows.append(state[i][0])
        rows.append(state[i][1])
    out[...] = jnp.concatenate(rows, axis=0)


def _pack_schedule(msgs: jnp.ndarray, lengths: jnp.ndarray):
    """XLA-side staging shared by the plain kernel and the fused
    front-end: padded buffer construction + byte->word packing + the
    sublane fold. msgs (B, max_len) uint8, lengths (B,) int32 ->
    (hi, lo, nblk, lb, max_blocks) with hi/lo (max_blocks*16*SUB, lb)
    uint32 and nblk (SUB, lb) int32. Requires B % (SUB*128) == 0
    (callers gate on that before packing)."""
    bsz, max_len = msgs.shape
    lb = bsz // SUB
    max_blocks = (max_len + 17 + 127) // 128
    lengths = lengths.astype(jnp.int32)

    # Padded buffer (total, B) — identical construction to the XLA path.
    total = max_blocks * 128
    data = jnp.moveaxis(msgs.astype(jnp.uint32), -1, 0)
    if total > max_len:
        data = jnp.concatenate(
            [data, jnp.zeros((total - max_len, bsz), jnp.uint32)], axis=0
        )
    pos = jnp.arange(total, dtype=jnp.int32)[:, None]
    ln = lengths[None, :]
    data = jnp.where(pos < ln, data, 0)
    data = jnp.where(pos == ln, 0x80, data)
    nblocks = (lengths + 17 + 127) // 128
    len_start = nblocks * 128 - 8
    bitlen_lo = lengths.astype(jnp.uint32) << 3
    bitlen_hi = lengths.astype(jnp.uint32) >> 29
    k = pos - len_start[None, :]
    word = jnp.where(k < 4, bitlen_hi[None, :], bitlen_lo[None, :])
    shift = (3 - (k & 3)) * 8
    lenbyte = jnp.where(
        (k >= 0) & (k < 8),
        (word >> jnp.clip(shift, 0, 31)) & 0xFF,
        0,
    ).astype(jnp.uint32)
    data = data | lenbyte                                   # (total, B)

    # bytes -> big-endian 64-bit (hi, lo) words: (16*max_blocks, B) each.
    by = data.reshape(16 * max_blocks, 8, bsz)
    hi = (by[:, 0] << 24) | (by[:, 1] << 16) | (by[:, 2] << 8) | by[:, 3]
    lo = (by[:, 4] << 24) | (by[:, 5] << 16) | (by[:, 6] << 8) | by[:, 7]
    # fold batch into sublanes: (W, B) -> (W*SUB, B/SUB)
    hi = hi.reshape(16 * max_blocks, SUB, lb).reshape(-1, lb)
    lo = lo.reshape(16 * max_blocks, SUB, lb).reshape(-1, lb)
    nblk = nblocks.reshape(SUB, lb)
    return hi, lo, nblk, lb, max_blocks


def _vmem_estimate(bsz: int, max_blocks: int) -> int:
    """VMEM footprint estimate of the single-launch compression: all
    max_blocks*16 (hi, lo) message word pairs plus the fully unrolled
    80-entry schedule per block, state, x2 for Mosaic temporaries."""
    return (2 * 16 * max_blocks * bsz * 4      # hi + lo inputs
            + 80 * 2 * bsz * 4                 # unrolled schedule
            + 16 * 2 * bsz * 4) * 2            # state + slack


VMEM_BUDGET = 64 * 1024 * 1024


def sha512_batch_pallas(msgs: jnp.ndarray, lengths: jnp.ndarray,
                        interpret: bool = False) -> jnp.ndarray:
    """Drop-in for sha512_batch on TPU: (B, max_len) uint8 + (B,) int32
    -> (B, 64) uint8 digests. B must be a multiple of 8*128 for the
    folded layout; smaller/odd batches take the XLA path."""
    from jax.experimental import pallas as pl

    bsz, max_len = msgs.shape
    if bsz % (SUB * 128) != 0:
        return s.sha512_batch(msgs, lengths)
    max_blocks = (max_len + 17 + 127) // 128
    # VMEM guard: fall back to the XLA path rather than die with an
    # opaque Mosaic OOM on large (batch, max_msg_len) combinations.
    if _vmem_estimate(bsz, max_blocks) > VMEM_BUDGET:
        return s.sha512_batch(msgs, lengths)
    hi, lo, nblk, lb, max_blocks = _pack_schedule(msgs, lengths)

    spec_w = pl.BlockSpec((16 * max_blocks * SUB, lb), lambda: (0, 0))
    spec_n = pl.BlockSpec((SUB, lb), lambda: (0, 0))
    out = pl.pallas_call(
        functools.partial(_sha512_kernel, max_blocks=max_blocks),
        in_specs=[spec_w, spec_w, spec_n],
        out_specs=pl.BlockSpec((16 * SUB, lb), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((16 * SUB, lb), jnp.uint32),
        interpret=interpret,
    )(hi, lo, nblk)

    # (16*SUB, lb): rows [2w*SUB:+SUB] = hi of word w, next SUB = lo.
    words = out.reshape(8, 2, SUB, lb).reshape(8, 2, bsz)   # (8, 2, B)
    words = jnp.transpose(words, (2, 0, 1))                 # (B, 8, 2)
    shifts = jnp.asarray([24, 16, 8, 0], jnp.uint32)
    hi_b = (words[:, :, 0:1] >> shifts[None, None, :]) & 0xFF
    lo_b = (words[:, :, 1:2] >> shifts[None, None, :]) & 0xFF
    return jnp.concatenate([hi_b, lo_b], axis=-1).reshape(
        bsz, 64).astype(jnp.uint8)
