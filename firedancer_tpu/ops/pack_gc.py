"""Account-conflict transaction scheduling as batched XLA graph coloring.

The device analog of ballet.pack (reference fd_pack.c:446-461,520-545):
given a block of pending transactions with account read/write locks,
partition them into parallel waves ("colors") such that no two
transactions in a wave conflict — a writer conflicts with any other use
of the account; readers conflict only with writers — while higher
rewards-per-CU transactions land in earlier waves (the reference's
max-heap order) and each wave respects a CU budget (the per-bank
fd_pack budget).

TPU-first design (this is NOT how the C code works — fd_pack walks a
heap with hash-table lock lookups, which is unvectorizable):

  * Account keys are hashed into a fixed bucket space of H bits,
    bitpacked into H/32 uint32 lanes. A transaction's write/read sets
    become two H-bit masks. Hash collisions only create FALSE conflicts
    — the schedule stays admissible, never violates a real lock.
  * Transactions are sorted by score (rewards/CU) descending with one
    argsort — the whole-batch analog of heap pops.
  * One `lax.scan` in sorted order carries the per-color lock state
    (used_w, used_r: (C, H/32) uint32) and per-color CU fill. Each step
    computes the conflict vector against ALL colors at once with
    bitwise AND + any-reduce (batch-uniform control flow, no branches),
    picks the first conflict-free color within budget, and ORs the
    txn's masks into that color's state. Unschedulable txns (all C
    colors conflict or over budget) get color -1 and stay pending —
    exactly like a txn that fd_pack leaves on the heap.

The CPU `ballet.pack.Pack`/`validate_schedule` is the admissibility
oracle: any schedule emitted here must pass it (tests/test_pack_gc.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

H_BITS_DEFAULT = 4096           # lock-bucket space; 128 uint32 words
MAX_COLORS_DEFAULT = 64         # parallel waves per scheduling round


def _masks_from_idx(idx: jnp.ndarray, n_words: int) -> jnp.ndarray:
    """(A,) int32 bucket indices (-1 pad) -> (n_words,) uint32 bitmask."""
    word = idx >> 5                                   # (A,)
    bit = (idx & 31).astype(jnp.uint32)
    valid = idx >= 0
    lanes = jax.lax.broadcasted_iota(jnp.int32, (idx.shape[0], n_words), 1)
    onehot = (lanes == word[:, None]) & valid[:, None]
    bits = jnp.where(
        onehot, jnp.left_shift(jnp.uint32(1), bit[:, None]), jnp.uint32(0)
    )
    return jax.lax.reduce(
        bits, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(0,)
    )


@functools.partial(
    jax.jit, static_argnames=("n_colors", "h_bits", "cu_cap")
)
def pack_schedule(
    w_idx: jnp.ndarray,
    r_idx: jnp.ndarray,
    scores: jnp.ndarray,
    cus: jnp.ndarray,
    *,
    n_colors: int = MAX_COLORS_DEFAULT,
    h_bits: int = H_BITS_DEFAULT,
    cu_cap: int = 12_000_000,
) -> jnp.ndarray:
    """Color a block of transactions on device.

    Args:
      w_idx: (N, AW) int32 hashed bucket indices of write-locked accounts,
        -1 padded.
      r_idx: (N, AR) int32, read-locked accounts, -1 padded.
      scores: (N,) float32 rewards-per-CU priority (higher = earlier).
      cus: (N,) int32 estimated compute units.

    Returns:
      (N,) int32 color per transaction in the ORIGINAL order; -1 means
      unscheduled (left pending for the next round).
    """
    n, _ = w_idx.shape
    n_words = h_bits // 32
    order = jnp.argsort(-scores)                      # heap-pop order
    w_sorted = w_idx[order]
    r_sorted = r_idx[order]
    cu_sorted = cus[order]

    def step(carry, inp):
        used_w, used_r, cu_used = carry
        wi, ri, cu = inp
        w_mask = _masks_from_idx(wi, n_words)         # (W,) uint32
        r_mask = _masks_from_idx(ri, n_words)
        wr_mask = w_mask | r_mask
        # Conflict rule (fd_pack.c:446-461): my writes vs their anything,
        # my reads vs their writes. Plus the per-wave CU budget.
        conflict = (
            jnp.any((used_w & wr_mask[None, :]) != 0, axis=1)
            | jnp.any((used_r & w_mask[None, :]) != 0, axis=1)
            | (cu_used + cu > cu_cap)
        )                                             # (C,)
        free = ~conflict
        any_free = jnp.any(free)
        color = jnp.where(any_free, jnp.argmax(free), -1).astype(jnp.int32)
        sel = (
            jax.lax.broadcasted_iota(jnp.int32, (n_colors,), 0) == color
        )                                             # (C,) one-hot (or none)
        used_w = jnp.where(sel[:, None], used_w | w_mask[None, :], used_w)
        used_r = jnp.where(sel[:, None], used_r | r_mask[None, :], used_r)
        cu_used = jnp.where(sel, cu_used + cu, cu_used)
        return (used_w, used_r, cu_used), color

    init = (
        jnp.zeros((n_colors, n_words), jnp.uint32),
        jnp.zeros((n_colors, n_words), jnp.uint32),
        jnp.zeros((n_colors,), jnp.int32),
    )
    _, colors_sorted = jax.lax.scan(
        step, init, (w_sorted, r_sorted, cu_sorted)
    )
    # Scatter back to input order.
    colors = jnp.zeros((n,), jnp.int32).at[order].set(colors_sorted)
    return colors


def hash_account(key: bytes, h_bits: int = H_BITS_DEFAULT) -> int:
    """Stable account-key -> bucket hash (host side).

    FNV-1a over the 32-byte key; stability matters only within one
    scheduling round, but a fixed fn keeps schedules reproducible.
    """
    h = 0xCBF29CE484222325
    for b in key:
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h % h_bits


class _PadTxn:
    """Shape-padding placeholder: no locks, zero priority, 1 CU."""

    txn_id = -1
    rewards = 0
    est_cus = 1
    writable = frozenset()
    readonly = frozenset()
    score = 0.0


PackTxnPad = _PadTxn()


def build_arrays(
    txns,
    h_bits: int = H_BITS_DEFAULT,
    max_w: int | None = None,
    max_r: int | None = None,
):
    """PackTxn list -> (w_idx, r_idx, scores, cus) numpy arrays.

    Hashing note: within one round, DISTINCT accounts may share a bucket
    (false conflict, safe); the SAME account always maps to the same
    bucket, so every true conflict is preserved.
    """
    n = len(txns)
    max_w = max_w or max((len(t.writable) for t in txns), default=1) or 1
    max_r = max_r or max((len(t.readonly) for t in txns), default=1) or 1
    w_idx = np.full((n, max_w), -1, np.int32)
    r_idx = np.full((n, max_r), -1, np.int32)
    scores = np.zeros((n,), np.float32)
    cus = np.zeros((n,), np.int32)
    for i, t in enumerate(txns):
        for j, k in enumerate(sorted(t.writable)):
            w_idx[i, j] = hash_account(k, h_bits)
        for j, k in enumerate(sorted(t.readonly)):
            r_idx[i, j] = hash_account(k, h_bits)
        scores[i] = t.score
        cus[i] = t.est_cus
    return w_idx, r_idx, scores, cus


def schedule_block(
    txns,
    n_colors: int = MAX_COLORS_DEFAULT,
    h_bits: int = H_BITS_DEFAULT,
    cu_cap: int = 12_000_000,
    pad_to: int | None = None,
    max_w: int | None = None,
    max_r: int | None = None,
):
    """End-to-end host API: PackTxn list -> (waves, leftover).

    waves: list of lists of PackTxn, wave k = color k (parallel batch);
    leftover: txns the device left unscheduled this round.

    pad_to / max_w / max_r pin the jitted program's shapes: a streaming
    caller (the pack tile) feeds ever-varying block sizes and per-block
    account maxima, and without pinning each new (n, AW, AR) shape costs
    a fresh XLA compile of the 1000+-step scan. pad_to rounds n up to a
    multiple (dummy txns have no accounts and zero score, so they color
    freely and are sliced off the result).
    """
    if not txns:
        return [], []
    n_real = len(txns)
    if pad_to:
        pad = (-n_real) % pad_to
        if pad:
            txns = list(txns) + [
                PackTxnPad for _ in range(pad)
            ]
    w_idx, r_idx, scores, cus = build_arrays(txns, h_bits,
                                             max_w=max_w, max_r=max_r)
    colors = np.asarray(
        pack_schedule(
            jnp.asarray(w_idx),
            jnp.asarray(r_idx),
            jnp.asarray(scores),
            jnp.asarray(cus),
            n_colors=n_colors,
            h_bits=h_bits,
            cu_cap=cu_cap,
        )
    )
    waves = [[] for _ in range(n_colors)]
    leftover = []
    for t, c in zip(txns[:n_real], colors[:n_real]):
        if c < 0:
            leftover.append(t)
        else:
            waves[int(c)].append(t)
    return [w for w in waves if w], leftover
