"""Fused verify front-end: SHA-512 -> mod-L reduce -> RLC coefficient
muls in ONE Pallas kernel, intermediates resident in VMEM.

Why this exists (round-10; docs/ROOFLINE.md): with the MSM on the VMEM
Pippenger engine, the verify batch is floored by the NON-DSM stages
(~33 ms/8192 measured by subtraction on v5e). A large share of that is
not stage arithmetic but the XLA glue BETWEEN per-stage kernels: the
digest unpacks to (B, 64) bytes in HBM, transposes to (64, B) limbs for
sc_reduce64 (five sequential carry scans as multi-kernel elementwise
chains), re-packs to bytes, transposes again for each _sc_muladd, and
every hop streams the full batch through HBM. This module chains the
whole scalar front half onto the SHA compression while the digest still
sits in VMEM:

    schedule words -> 80-round compression -> digest byte limbs
      -> Barrett reduce mod L (h)
      -> m = z*h mod L, zs = z*s mod L     (the RLC coefficient muls)

The scalar stages run in the SAME folded (SUB, B/SUB) lane layout as
the SHA kernel (ops/sha512_pallas.py): each byte limb occupies a full
(8, 128)-tile block instead of a single sublane row, so the Barrett
carry chains and the 32x32 schoolbook convolutions are full-width VPU
ops — and no byte<->limb transpose ever materializes between stages.

Algorithm parity: the Barrett body mirrors sc25519.sc_reduce64 (b=2^8,
k=32; mu and L folded in as Python int literals) and the muls mirror
sign._sc_muladd — both remain the CPU/test reference the interpret-mode
parity tests compare against, bit-exact.

Engine selection: FD_FRONTEND_IMPL = auto | pallas | xla | interpret.
'auto' resolves to the fused kernels exactly when the attached backend
is a TPU family; 'xla' pins the staged composition (where FD_SHA_IMPL /
FD_SC_IMPL still dispatch each stage individually — the escape hatch if
a Mosaic version rejects the fused construction); 'interpret' runs the
production kernels under the Pallas interpreter so CPU CI parity-tests
the exact shipping engine (same contract as FD_MSM_IMPL=interpret).
Ineligible shapes (batch not a multiple of 8*128, or VMEM overflow)
always fall back to the staged composition — never a wrong result.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from firedancer_tpu import flags

from . import sc25519 as sc
# Module-level, NOT lazy: sha512.py and sign.py create jnp constants at
# module scope (_K_HI, the basepoint tables). A first import that
# happens INSIDE a jit trace would turn those into leaked tracers that
# poison every later trace (UnexpectedTracerError) — importing here
# guarantees they materialize as concrete arrays at import time.
from .sha512 import sha512_batch_auto
from .sign import _sc_muladd
from .sha512_pallas import (
    SUB,
    VMEM_BUDGET,
    _pack_schedule,
    _sha512_rounds,
    _vmem_estimate,
)

_MU_W = [(sc._MU >> (8 * i)) & 0xFF for i in range(33)]
_L_W = [(sc.L >> (8 * i)) & 0xFF for i in range(33)]

# fdcert entry contracts (fdlint pass 5 — grammar in ops/fe25519.py).
# These are the folded-layout Barrett/schoolbook mirrors of
# sc25519.sc_reduce64 / sign._sc_muladd; the certifier re-proves them
# independently so a divergence that widens an intermediate fails CI
# even if the bit-exact parity tests are skipped. The final
# conditional-subtract lane select is arithmetic (keep*r + (1-keep)*d),
# which the interval domain over-approximates to [0, 765]; runtime
# digits are canonical [0, 255]. _mul_mod_l_f is certified at the
# wider [0, 765] input so the kernel composition h = _barrett_f(...)
# -> _mul_mod_l_f(z, h) is covered by the proof chain.
FDCERT_CONTRACTS = {
    "_carry_f": {"inputs": ["blocks:64:255"], "out_abs": 255,
                 "doc": "exact folded base-256 carry"},
    "_barrett_f": {"inputs": ["blocks:64:255"], "out_abs": 765,
                   "doc": "folded Barrett mod L; conv rows < 2^21"},
    "_mul_mod_l_f": {"inputs": ["blocks:32:765", "blocks:32:765"],
                     "out_abs": 765,
                     "doc": "folded schoolbook mul mod L"},
    "_digest_limbs": {"inputs": ["digest_state"], "out_abs": 255,
                      "doc": "uint32 state -> byte limbs, shifts only"},
}


def frontend_impl() -> str:
    """Trace-time front-end engine: 'pallas' (the fused VMEM kernels),
    'xla' (staged composition, per-stage flags apply), or 'interpret'
    (fused kernels under the interpreter — CPU CI runs the exact
    shipping engine). An unrecognized value is an error: a typo'd force
    must never quietly measure the wrong engine."""
    impl = flags.get_str("FD_FRONTEND_IMPL")
    if impl == "interpret":
        return "interpret"
    if impl not in ("", "auto", "xla", "pallas"):
        raise ValueError(
            f"unknown FD_FRONTEND_IMPL {impl!r} "
            "(want auto|xla|pallas|interpret)"
        )
    from .backend import use_pallas

    return "pallas" if use_pallas("FD_FRONTEND_IMPL") else "xla"


def frontend_eligible(bsz: int, max_len: int, with_rlc: bool) -> bool:
    """Whether the fused kernel launch handles this shape: the folded
    layout needs bsz % (8*128) == 0, and the combined SHA + scalar
    footprint must fit the VMEM guard (the scalar stage adds the z/s
    inputs, h/m/zs outputs, and the 64-limb convolution accumulators on
    top of the compression's schedule words)."""
    if bsz % (SUB * 128) != 0:
        return False
    max_blocks = (max_len + 17 + 127) // 128
    if with_rlc:
        # z + s inputs, h + m + zs outputs (32 limb blocks each), plus
        # ~8 64-limb-block temporaries (conv accumulators, Barrett q2).
        extra = (5 * 32 + 8 * 64) * bsz * 4
    else:
        extra = (1 * 32 + 4 * 64) * bsz * 4
    return _vmem_estimate(bsz, max_blocks) + 2 * extra <= VMEM_BUDGET


# --------------------------------------------------------------------------
# Fold-layout scalar arithmetic (kernel-safe): limb i of every lane
# occupies rows [i*SUB : (i+1)*SUB], lanes ride (SUB, B/SUB) tiles.
# Mirrors sc25519 / sc_pallas semantics exactly; carries propagate
# across limb BLOCKS (each (row, col) within a block is an independent
# lane).
# --------------------------------------------------------------------------


def _carry_f(x):
    """Exact sequential base-256 carry over limb blocks: (n*SUB, L)
    int32 (signed ok — arithmetic shift floors) -> (digits in [0, 255],
    top carry (SUB, L), possibly negative)."""
    n = x.shape[0] // SUB
    carry = jnp.zeros((SUB,) + x.shape[1:], jnp.int32)
    outs = []
    for i in range(n):
        t = x[i * SUB:(i + 1) * SUB] + carry
        outs.append(t & 0xFF)
        carry = t >> 8
    return jnp.concatenate(outs, axis=0), carry


def _pad_blocks(x, lo: int, hi: int):
    """Place x's limb blocks at block offset lo inside lo+blocks+hi
    total blocks (zeros + concatenate; static shapes only)."""
    parts = []
    if lo:
        parts.append(jnp.zeros((lo * SUB,) + x.shape[1:], jnp.int32))
    parts.append(x)
    if hi:
        parts.append(jnp.zeros((hi * SUB,) + x.shape[1:], jnp.int32))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


def _conv_w(x, weights, n_out: int):
    """conv(x, weights) truncated to n_out limb blocks; weights are
    Python ints (static). Partial sums <= 33*255^2 < 2^21: int32-safe
    (same bound as sc_pallas._conv_const)."""
    n_in = x.shape[0] // SUB
    acc = jnp.zeros((n_out * SUB,) + x.shape[1:], jnp.int32)
    for j, w in enumerate(weights):
        if w == 0:
            continue
        blocks = min(n_in, n_out - j)
        if blocks <= 0:
            break
        term = x[:blocks * SUB] * np.int32(w)
        acc = acc + _pad_blocks(term, j, n_out - j - blocks)
    return acc


def _barrett_f(x):
    """(64*SUB, L) canonical byte-limb blocks of x < 2^512 ->
    (32*SUB, L) canonical limb blocks of x mod L (sc_reduce64's Barrett
    with b = 2^8, k = 32, in the folded layout)."""
    lanes = x.shape[1]
    q1 = x[31 * SUB:]                              # 33 blocks
    q2 = _conv_w(q1, _MU_W, 66)
    q2, _ = _carry_f(q2)
    q3 = q2[33 * SUB:]                             # 33 blocks
    q3l = _conv_w(q3, _L_W, 33)
    q3l, _ = _carry_f(q3l)
    # r = (x - q3*L) mod b^33: final borrow discarded (= the wrap).
    r, _ = _carry_f(x[:33 * SUB] - q3l)
    i = jax.lax.broadcasted_iota(jnp.int32, (33 * SUB, lanes), 0) // SUB
    l_col = jnp.zeros((33 * SUB, lanes), jnp.int32)
    for j, w in enumerate(_L_W):
        if w:
            l_col = l_col + jnp.where(i == j, w, 0)
    # r in [0, 3L): subtract L at most twice (arithmetic lane select —
    # the borrow is per lane, broadcast over the 33 limb blocks).
    for _ in range(2):
        d, borrow = _carry_f(r - l_col)
        keep = (borrow < 0).astype(jnp.int32)      # (SUB, L)
        keep_b = jnp.concatenate([keep] * 33, axis=0)
        r = keep_b * r + (1 - keep_b) * d
    return r[:32 * SUB]


def _mul_mod_l_f(a, b):
    """(32*SUB, L) x (32*SUB, L) canonical byte-limb blocks ->
    (32*SUB, L) canonical a*b mod L (sign._sc_muladd's c=0 case:
    schoolbook conv, partial sums <= 32*255^2 < 2^21, exact carry to a
    64-limb integer, shared Barrett)."""
    acc = jnp.zeros((64 * SUB,) + a.shape[1:], jnp.int32)
    for i in range(32):
        ai = a[i * SUB:(i + 1) * SUB]
        ai_b = jnp.concatenate([ai] * 32, axis=0)  # broadcast over b's blocks
        acc = acc + _pad_blocks(ai_b * b, i, 32 - i)
    x, _ = _carry_f(acc)                           # < 2^512: exact
    return _barrett_f(x)


def _digest_limbs(state):
    """SHA final state (8 (hi, lo) pairs of (SUB, L) uint32) ->
    (64*SUB, L) int32 byte-limb blocks of the digest read as a
    little-endian integer (limb j = digest byte j — the sc_reduce64
    input convention), extracted with shifts only: the byte<->word
    transpose this kernel exists to delete."""
    limbs = []
    for w in range(8):
        hi, lo = state[w]
        for c in range(4):
            limbs.append(((hi >> (24 - 8 * c)) & 0xFF).astype(jnp.int32))
        for c in range(4):
            limbs.append(((lo >> (24 - 8 * c)) & 0xFF).astype(jnp.int32))
    return jnp.concatenate(limbs, axis=0)


# --------------------------------------------------------------------------
# Kernels + launch wrappers.
# --------------------------------------------------------------------------


def _sha_mod_l_kernel(win_hi, win_lo, nblk, oh, *, max_blocks: int):
    state = _sha512_rounds(win_hi[...], win_lo[...], nblk[...],
                           max_blocks=max_blocks)
    oh[...] = _barrett_f(_digest_limbs(state))


def _frontend_rlc_kernel(win_hi, win_lo, nblk, zin, sin, oh, om, ozs, *,
                         max_blocks: int):
    state = _sha512_rounds(win_hi[...], win_lo[...], nblk[...],
                           max_blocks=max_blocks)
    h = _barrett_f(_digest_limbs(state))
    z = zin[...]
    oh[...] = h
    om[...] = _mul_mod_l_f(z, h)
    ozs[...] = _mul_mod_l_f(z, sin[...])


def _fold_scalar(b_bytes, lb: int):
    """(B, 32) uint8 -> (32*SUB, lb) int32 folded limb blocks, the same
    b = row*lb + col lane order as _pack_schedule's fold."""
    x = jnp.moveaxis(b_bytes.astype(jnp.int32), -1, 0)       # (32, B)
    return x.reshape(32, SUB, lb).reshape(32 * SUB, lb)


def _unfold_scalar(x, bsz: int):
    """(32*SUB, lb) int32 -> (B, 32) uint8 (inverse of _fold_scalar)."""
    lb = bsz // SUB
    return jnp.moveaxis(
        x.reshape(32, SUB, lb).reshape(32, bsz), 0, -1
    ).astype(jnp.uint8)


def sha512_mod_l_pallas(msgs: jnp.ndarray, lengths: jnp.ndarray,
                        interpret: bool = False) -> jnp.ndarray:
    """Fused h = SHA-512(msgs) mod L: (B, max_len) uint8 + (B,) int32
    -> (B, 32) uint8 canonical scalars. Callers gate on
    frontend_eligible first."""
    from jax.experimental import pallas as pl

    bsz = msgs.shape[0]
    hi, lo, nblk, lb, max_blocks = _pack_schedule(
        msgs, lengths.astype(jnp.int32))
    spec_w = pl.BlockSpec((16 * max_blocks * SUB, lb), lambda: (0, 0))
    spec_n = pl.BlockSpec((SUB, lb), lambda: (0, 0))
    spec_s = pl.BlockSpec((32 * SUB, lb), lambda: (0, 0))
    out = pl.pallas_call(
        functools.partial(_sha_mod_l_kernel, max_blocks=max_blocks),
        in_specs=[spec_w, spec_w, spec_n],
        out_specs=spec_s,
        out_shape=jax.ShapeDtypeStruct((32 * SUB, lb), jnp.int32),
        interpret=interpret,
    )(hi, lo, nblk)
    return _unfold_scalar(out, bsz)


def frontend_rlc_pallas(msgs: jnp.ndarray, lengths: jnp.ndarray,
                        z_bytes: jnp.ndarray, s_bytes: jnp.ndarray,
                        interpret: bool = False):
    """Fused RLC scalar front half: returns (h, m, zs) as (B, 32) uint8
    with h = SHA-512(msgs) mod L, m = z*h mod L, zs = z*s mod L — one
    kernel, digest never leaves VMEM between the stages. z carries the
    caller's live-lane masking (dead lanes: z = 0 -> m = zs = 0,
    identical to the staged path). Callers gate on frontend_eligible."""
    from jax.experimental import pallas as pl

    bsz = msgs.shape[0]
    hi, lo, nblk, lb, max_blocks = _pack_schedule(
        msgs, lengths.astype(jnp.int32))
    z = _fold_scalar(z_bytes, lb)
    ss = _fold_scalar(s_bytes, lb)
    spec_w = pl.BlockSpec((16 * max_blocks * SUB, lb), lambda: (0, 0))
    spec_n = pl.BlockSpec((SUB, lb), lambda: (0, 0))
    spec_s = pl.BlockSpec((32 * SUB, lb), lambda: (0, 0))
    out_s = jax.ShapeDtypeStruct((32 * SUB, lb), jnp.int32)
    h, m, zs = pl.pallas_call(
        functools.partial(_frontend_rlc_kernel, max_blocks=max_blocks),
        in_specs=[spec_w, spec_w, spec_n, spec_s, spec_s],
        out_specs=[spec_s] * 3,
        out_shape=[out_s] * 3,
        interpret=interpret,
    )(hi, lo, nblk, z, ss)
    return (_unfold_scalar(h, bsz), _unfold_scalar(m, bsz),
            _unfold_scalar(zs, bsz))


# --------------------------------------------------------------------------
# Auto dispatchers (the verify paths call these).
# --------------------------------------------------------------------------


def sha512_mod_l_auto(msgs: jnp.ndarray,
                      lengths: jnp.ndarray) -> jnp.ndarray:
    """h = SHA-512(msgs) mod L: the fused kernel when the front-end is
    active and the shape is eligible, else the staged composition
    (sha512_batch_auto + sc_reduce64_auto, each stage's own flag
    dispatching as before)."""
    impl = frontend_impl()
    bsz, max_len = msgs.shape
    if impl != "xla" and frontend_eligible(bsz, max_len, with_rlc=False):
        return sha512_mod_l_pallas(msgs, lengths,
                                   interpret=impl == "interpret")
    return sc.sc_reduce64_auto(sha512_batch_auto(msgs, lengths))


def staged_coeff_muls(z_bytes: jnp.ndarray, h_bytes: jnp.ndarray,
                      s_bytes: jnp.ndarray):
    """The staged path's RLC coefficient muls: (m, zs) = (z*h, z*s)
    mod L, with verify_batch_rlc's HISTORICAL dispatch — the stacked
    VMEM Barrett kernels iff FD_SC_IMPL=pallas is EXPLICIT and the
    platform is a TPU (round-4 v5e: the Barrett kernel loses ~3x to
    XLA on short scalar chains, so auto stays XLA), independent of
    FD_FRONTEND_IMPL: forcing the front-end to 'xla' must reproduce
    the pre-fusion staged composition, not a third configuration.
    Registry reads at trace time (fdlint pass 1 sanctions both).
    profile_stages times this exact function for `rlc_combine`."""
    from .backend import _platform_is_tpu

    if flags.get_raw("FD_SC_IMPL") == "pallas" and _platform_is_tpu():
        from .sc_pallas import sc_mul_pallas

        bsz = z_bytes.shape[0]
        both_m = sc_mul_pallas(
            jnp.concatenate([z_bytes, z_bytes], axis=0),
            jnp.concatenate([h_bytes, s_bytes], axis=0),
        )
        return both_m[:bsz], both_m[bsz:]
    return (_sc_muladd(z_bytes, h_bytes, jnp.zeros_like(h_bytes)),
            _sc_muladd(z_bytes, s_bytes, jnp.zeros_like(s_bytes)))


def frontend_rlc_auto(msgs: jnp.ndarray, lengths: jnp.ndarray,
                      z_bytes: jnp.ndarray, s_bytes: jnp.ndarray):
    """(h, m, zs) for the RLC pass: the fused kernel when active and
    eligible, else the staged composition (sha512_batch_auto +
    sc_reduce64_auto + staged_coeff_muls, each stage's own flag
    dispatching exactly as verify_batch_rlc historically did)."""
    impl = frontend_impl()
    bsz, max_len = msgs.shape
    if impl != "xla" and frontend_eligible(bsz, max_len, with_rlc=True):
        return frontend_rlc_pallas(msgs, lengths, z_bytes, s_bytes,
                                   interpret=impl == "interpret")
    h_bytes = sc.sc_reduce64_auto(sha512_batch_auto(msgs, lengths))
    m_bytes, zs = staged_coeff_muls(z_bytes, h_bytes, s_bytes)
    return h_bytes, m_bytes, zs


# PR 14: the stacked (A, R) point decompression is part of the verify
# front half — bytes -> validated extended coordinates, Montgomery-
# batched, VMEM-resident on the kernel path — so its engine dispatch
# lives behind this module's surface next to the scalar dispatch
# (FD_DECOMPRESS_IMPL mirrors FD_FRONTEND_IMPL's auto|pallas|xla|
# interpret shape). verify_batch_rlc routes its decompress here; the
# direct path takes the whole-front-half composition below.
from .decompress_pallas import (  # noqa: E402  (re-export, post-defs)
    decompress_batched_auto as frontend_decompress_auto,
)


def frontend_direct_auto(msgs: jnp.ndarray, lengths: jnp.ndarray,
                         ar_bytes: jnp.ndarray):
    """The ENTIRE direct-mode verify front half in one dispatch:
    h = SHA-512(msgs) mod L through the fused kernel when active and
    eligible, plus the stacked (A, R) Montgomery-batched decompress
    with its in-engine small-order mask. Returns (h_bytes, ar_pt,
    ar_ok, ar_so) — everything verify_batch needs before the DSM."""
    h_bytes = sha512_mod_l_auto(msgs, lengths)
    ar_pt, ar_ok, ar_so = frontend_decompress_auto(
        ar_bytes, want_small_order=True)
    return h_bytes, ar_pt, ar_ok, ar_so
