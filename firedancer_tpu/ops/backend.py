"""Backend selection for ops with both Pallas-TPU and XLA implementations.

One policy, used by verify (DSM kernel) and curve25519 (pow chains):
an env var forces "xla" or "pallas"; otherwise the Pallas kernel is used
exactly when the attached backend is a TPU family ("tpu", or this
image's "axon" tunnel plugin). Pallas kernels here are built on
pallas.tpu BlockSpecs/VMEM, so every other platform takes the XLA graph.
"""

from __future__ import annotations

import jax

from firedancer_tpu import flags

TPU_PLATFORMS = ("tpu", "axon")


def use_specialized_square() -> bool:
    """FD_SQ_IMPL=mul swaps the specialized fe_sq inside Pallas kernels
    for a plain multiply — the escape hatch the bench ladder retries
    with if a Mosaic version rejects fe_sq's slice/concat construction.
    Centralized here so dsm_pallas and pow_pallas cannot drift."""
    return flags.get_str("FD_SQ_IMPL") != "mul"


def _platform_is_tpu() -> bool:
    """Whether the attached jax backend is a TPU family (shared probe:
    the pallas-kernel dispatch and the verify-mode default must never
    disagree about what the device is)."""
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "cpu"
    return platform in TPU_PLATFORMS


def use_pallas(env_var: str) -> bool:
    """Decide at trace time whether to use the Pallas implementation.
    env_var names a registered *_IMPL flag (firedancer_tpu/flags.py)."""
    impl = flags.get_str(env_var, "auto")
    if impl == "xla":
        return False
    if impl == "pallas":
        return True
    return _platform_is_tpu()


def default_verify_mode() -> str:
    """Verify-tile mode when the config says 'auto': a pure fd_engine
    registry lookup since PR 13 — disco/engine.py owns every
    engine-resolution decision (this delegation stays because ops-layer
    callers spell it backend.default_verify_mode, and the platform
    probe itself still lives here as _platform_is_tpu)."""
    from firedancer_tpu.disco.engine import (
        default_verify_mode as _engine_default,
    )

    return _engine_default()


def kernel_mul_impl() -> str:
    """In-kernel field-multiply schedule, decided at trace time:
    'schoolbook' (int32, the r3 baseline), 'karatsuba' (576 vs 1024
    VPU products, more adds), or 'f32' (exact-f32-product convolution —
    wins when the VPU's int32 multiply is emulated multi-pass while f32
    multiply is single-pass; products bounded < 2^24 stay exact)."""
    impl = flags.get_str("FD_MUL_IMPL")
    if impl not in ("schoolbook", "karatsuba", "f32", "rolled", "factored"):
        impl = "schoolbook"
    return impl


def use_karatsuba() -> bool:
    return kernel_mul_impl() == "karatsuba"
