"""Backend selection for ops with both Pallas-TPU and XLA implementations.

One policy, used by verify (DSM kernel) and curve25519 (pow chains):
an env var forces "xla" or "pallas"; otherwise the Pallas kernel is used
exactly when the attached backend is a TPU family ("tpu", or this
image's "axon" tunnel plugin). Pallas kernels here are built on
pallas.tpu BlockSpecs/VMEM, so every other platform takes the XLA graph.
"""

from __future__ import annotations

import jax

from firedancer_tpu import flags

TPU_PLATFORMS = ("tpu", "axon")


def use_specialized_square() -> bool:
    """FD_SQ_IMPL=mul swaps the specialized fe_sq inside Pallas kernels
    for a plain multiply — the escape hatch the bench ladder retries
    with if a Mosaic version rejects fe_sq's slice/concat construction.
    Centralized here so dsm_pallas and pow_pallas cannot drift."""
    return flags.get_str("FD_SQ_IMPL") != "mul"


def _platform_is_tpu() -> bool:
    """Whether the attached jax backend is a TPU family (shared probe:
    the pallas-kernel dispatch and the verify-mode default must never
    disagree about what the device is)."""
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "cpu"
    return platform in TPU_PLATFORMS


def use_pallas(env_var: str) -> bool:
    """Decide at trace time whether to use the Pallas implementation.
    env_var names a registered *_IMPL flag (firedancer_tpu/flags.py)."""
    impl = flags.get_str(env_var, "auto")
    if impl == "xla":
        return False
    if impl == "pallas":
        return True
    return _platform_is_tpu()


def default_verify_mode() -> str:
    """Verify-tile mode when the config says 'auto' (round-6 RLC
    promotion): 'rlc' — batch RLC verification over the VMEM Pallas
    Pippenger MSM (ops/verify_rlc.py), one shared doubling chain per
    batch with exact per-lane fallback — on TPU platforms; 'direct'
    per-lane on host-jax backends (no VMEM engine to amortize, and the
    CPU-jax RLC graph is a CI/parity path, not a production one).
    FD_VERIFY_MODE forces either explicitly; an unrecognized value is
    an error, not a silent fall-through to the platform default (a
    typo'd force must never masquerade as a measurement of the mode
    the operator asked for)."""
    forced = flags.get_raw("FD_VERIFY_MODE")
    if forced:
        if forced not in ("rlc", "direct"):
            raise ValueError(
                f"unknown FD_VERIFY_MODE {forced!r} (want rlc|direct)"
            )
        return forced
    return "rlc" if _platform_is_tpu() else "direct"


def kernel_mul_impl() -> str:
    """In-kernel field-multiply schedule, decided at trace time:
    'schoolbook' (int32, the r3 baseline), 'karatsuba' (576 vs 1024
    VPU products, more adds), or 'f32' (exact-f32-product convolution —
    wins when the VPU's int32 multiply is emulated multi-pass while f32
    multiply is single-pass; products bounded < 2^24 stay exact)."""
    impl = flags.get_str("FD_MUL_IMPL")
    if impl not in ("schoolbook", "karatsuba", "f32", "rolled", "factored"):
        impl = "schoolbook"
    return impl


def use_karatsuba() -> bool:
    return kernel_mul_impl() == "karatsuba"
