"""Pallas TPU kernels for the GF(2^255-19) power chains.

fe_invert (z^(p-2), used by compress) and fe_pow22523 (z^((p-5)/8), used
by decompress's square root) are ~265-multiply sequential addition
chains. In the XLA graph each fe_mul streams its (32, B) operands
through HBM (~45 us/mul at B=8192 measured on v5e); pinned in VMEM the
same multiply costs ~9 us. These kernels run the whole chain on one
VMEM-resident tile of lanes, mirroring dsm_pallas's layout.

Chain structure: the classic curve25519 ladder (RFC 7748 style), same as
fe25519._pow_ladder — which remains the XLA/CPU reference the tests
compare against.
"""

from __future__ import annotations

import functools
import math
import jax
import jax.numpy as jnp

from . import fe25519 as fe


def np_prod(shape) -> int:
    return math.prod(shape)

NLIMBS = fe.NLIMBS
LANES = 512


def _mul(a, b):
    return fe.fe_mul_kernel(a, b)


def _sq(x):
    """Kernel squaring (f32-product variant under FD_MUL_IMPL=f32) with
    the FD_SQ_IMPL=mul escape hatch (backend.use_specialized_square)."""
    from .backend import kernel_mul_impl, use_specialized_square

    impl = kernel_mul_impl()
    if impl == "rolled" and not use_specialized_square():
        # Movement-bound squaring: rolled(x, x) vs fe_sq is decided by
        # FD_SQ_IMPL (see dsm_pallas._fe_sq).
        return fe.fe_mul_rolled(x, x)
    if use_specialized_square():
        if impl == "f32":
            return fe.fe_sq_f32(x)
        return fe.fe_sq(x)
    return _mul(x, x)


def _sqn(x, n):
    """n successive squarings, BLOCK-unrolled inside lax.fori_loop.

    Round-4 put the long runs in a per-squaring fori_loop to shrink
    compile time, asserting the per-step loop overhead was noise — an
    assumption that was never re-measured on chip (the tunnel was down
    the whole round). Round-5 hedges both ways: FD_POW_BLOCK squarings
    (default 10) are unrolled per loop iteration, cutting the loop-step
    count ~10x while the traced body stays ~1k ops. FD_POW_BLOCK=1
    reproduces the round-4 shape for A/B timing; a block >= n fully
    unrolls."""
    if n <= 8:
        for _ in range(n):
            x = _sq(x)
        return x
    from firedancer_tpu import flags

    block = max(1, flags.get_int("FD_POW_BLOCK"))
    nb, rem = divmod(n, block)

    def body(i, v):
        for _ in range(block):
            v = _sq(v)
        return v

    if nb:
        x = jax.lax.fori_loop(0, nb, body, x)
    for _ in range(rem):
        x = _sq(x)
    return x


def _ladder(z):
    """(z^(2^250 - 1), z^11) per fe25519._pow_ladder."""
    z2 = _sq(z)
    z9 = _mul(_sqn(z2, 2), z)
    z11 = _mul(z9, z2)
    z_5_0 = _mul(_sq(z11), z9)
    z_10_0 = _mul(_sqn(z_5_0, 5), z_5_0)
    z_20_0 = _mul(_sqn(z_10_0, 10), z_10_0)
    z_40_0 = _mul(_sqn(z_20_0, 20), z_20_0)
    z_50_0 = _mul(_sqn(z_40_0, 10), z_10_0)
    z_100_0 = _mul(_sqn(z_50_0, 50), z_50_0)
    z_200_0 = _mul(_sqn(z_100_0, 100), z_100_0)
    z_250_0 = _mul(_sqn(z_200_0, 50), z_50_0)
    return z_250_0, z11


def invert_chain(z):
    """z^(p-2) = z^(2^255 - 21), kernel-safe (shared chain tail)."""
    z_250_0, z11 = _ladder(z)
    return _mul(_sqn(z_250_0, 5), z11)


def pow22523_chain(z):
    """z^((p-5)/8) = z^(2^252 - 3), kernel-safe (shared chain tail)."""
    z_250_0, _ = _ladder(z)
    return _mul(_sqn(z_250_0, 2), z)


def _pow_kernel(zin, out, *, kind: str):
    z = zin[...]
    if kind == "invert":
        out[...] = invert_chain(z)
    elif kind == "pow22523":
        out[...] = pow22523_chain(z)
    else:  # pragma: no cover
        raise ValueError(kind)


def _fe_pow_pallas(z_limbs: jnp.ndarray, kind: str) -> jnp.ndarray:
    """(32, *batch) int32 limbs -> same-shape limbs of z^e on a VMEM tile
    grid. Arbitrary batch shapes (incl. none) are flattened to one lane
    axis for the kernel and restored after — matching the fe25519 chains'
    shape-polymorphic contract."""
    from jax.experimental import pallas as pl

    batch_shape = z_limbs.shape[1:]
    if batch_shape != (int(np_prod(batch_shape)),):
        z_limbs = z_limbs.reshape(NLIMBS, -1)
    bsz = z_limbs.shape[1]
    if bsz == 0:
        return z_limbs.reshape((NLIMBS,) + batch_shape)
    if bsz < 128:
        # Sub-tile batches (single-point helpers): the XLA chain beats a
        # padded-to-128-lane kernel launch.
        out = (fe.fe_invert if kind == "invert" else fe.fe_pow22523)(z_limbs)
        return out.reshape((NLIMBS,) + batch_shape)
    lanes = min(LANES, bsz)
    pad = (-bsz) % lanes
    if pad:
        z_limbs = jnp.pad(z_limbs, ((0, 0), (0, pad)))
    n = (bsz + pad) // lanes

    spec = pl.BlockSpec((NLIMBS, lanes), lambda i: (0, i))
    out = pl.pallas_call(
        functools.partial(_pow_kernel, kind=kind),
        grid=(n,),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((NLIMBS, bsz + pad), jnp.int32),
    )(z_limbs)
    if pad:
        out = out[:, :bsz]
    return out.reshape((NLIMBS,) + batch_shape)


def fe_invert_pallas(z: jnp.ndarray) -> jnp.ndarray:
    return _fe_pow_pallas(z, "invert")


def fe_pow22523_pallas(z: jnp.ndarray) -> jnp.ndarray:
    return _fe_pow_pallas(z, "pow22523")
