"""Montgomery-batched point decompression, fused behind the front-end.

ROOFLINE prediction 7 named decompress the next head-of-queue after the
fused front half: the staged path spends one ~265-multiply power chain
PER LANE on the sqrt-ratio (2B stacked lanes per verify batch) plus
three canonicalize-based masks. This module restructures the donna
square root so that everything except an irreducible pure-squaring
ladder rides a grouped Montgomery inversion tree:

    u = y^2 - 1,  v = d y^2 + 1,  w = u v
    x_cand = (u v)^((p+3)/8) / v
           = w^(2^252) * inv(u^2 v^3)

  * ``w^(2^252)`` is 252 repeated squarings — no multiplies, and the
    only per-lane chain left (a square root has no multiplicative
    shortcut: sqrt(ab) does not split into sqrt(a)*sqrt(b) without one
    new chain per split, so the ladder is the floor).
  * ``inv(u^2 v^3)`` batches through a prefix-product tree: ONE
    fe_invert chain per 2^FD_DECOMPRESS_BATCH lanes (default 64) plus
    ~3 tree multiplies per lane — the analytic inversion count drops
    from 2B per batch to 2B/64 (`inversion_count`, recorded in bench
    artifacts).
  * The old candidate u v^3 (u v^7)^((p-5)/8) and this one differ by a
    fourth root of unity chi_v = v^((p-1)/4); both flow through the
    SAME root checks (v x^2 == +-u) and sign fix-up, which collapse
    either candidate to the unique canonical x — bit-exact, including
    the ok mask (both fail iff u v is a non-square) and the x==0 mask
    (x == 0 iff u == 0 iff y == +-1, tested directly on the byte limbs).

Zero lanes (y == +-1 -> w == 0) would poison their whole inversion
group (the group product is 0 and 0^(p-2) = 0 spreads on the backward
sweep), so they are masked to 1 before the tree; their x is forced by
the ladder (0^(2^252) == 0) regardless of the inverse.

Engine selection (FD_DECOMPRESS_IMPL = auto | pallas | xla |
interpret): 'pallas' routes curve_pallas's kernels, whose shared body
now runs this batched math in-VMEM (half-split lane tree + the
pow_pallas squaring ladder) so bytes -> validated extended coordinates
never leave VMEM behind the fused front-end; 'xla' is the host graph
below, cache-blocked with lax.map over FD_DECOMPRESS_CHUNK-lane blocks
(the CPU analog of the VMEM tile — the Versal point-add pipeline's
"operands stay resident" shape); 'interpret' runs the production
kernels under the Pallas interpreter for CI parity. Shapes an engine
cannot serve fall back bit-exactly to the staged per-lane-chain
composition: the host graph needs whole 1024-lane blocks
(batch_eligible), the kernel path folds whole padded LANES-wide tiles
whenever the tile reaches the full Montgomery group
(use_batched_kernel; sub-tile batches take curve25519.decompress_xla),
and FD_DECOMPRESS_BATCH=0 disables the batched math everywhere.
batched_active/inversion_count are the engine-aware attribution
answers the bench artifacts record.

The ladder squaring schedule is certifier-gated search output
(FD_DECOMPRESS_SQ_SCHED; scripts/fe_schedule_search.py): every
registered choice is proved int32-wrap-free by fdcert — including the
fori_loop inductive-invariant transfer for the ladder itself — and
oracle-parity-checked; lazy depths the interval domain cannot close
(int32x2, f32x3) are rejected candidates, not flag values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from firedancer_tpu import flags

from . import fe25519 as fe

NLIMBS = fe.NLIMBS

# The pure-squaring exponent: (uv)^(2^252) realizes (uv)^((p+3)/8) up
# to the batched inverse, p = 2^255 - 19.
LADDER_SQUARINGS = 252

# Batched eligibility quantum: the chunked graph and the folded kernel
# tiles both want whole 1024-lane blocks; anything else falls back to
# the staged composition (the B=1 / odd-batch path of the tests).
ELIGIBLE_MULTIPLE = 1024

# fdcert entry contracts (fdlint pass 5 — grammar in lint/bounds.py).
# _decompress_block is the WHOLE per-chunk graph at byte-limb inputs:
# every intermediate of the ladder (via the fori inductive transfer),
# the prefix-product tree, the root checks and the sign fix-up proves
# int32-wrap-free in one certificate entry.
FDCERT_CONTRACTS = {
    "_y_pm1_mask": {"inputs": ["limbs:32:255:4"], "out_abs": 1,
                    "doc": "y == +-1 mod p as three byte compares"},
    "_mont_inv_tree": {"inputs": ["limbs:32:512:8", "int:3"],
                       "out_abs": 512,
                       "doc": "grouped prefix-product inversion "
                              "(wraps fe_invert_batch)"},
    "_decompress_block": {"inputs": ["limbs:32:255:8", "mask:1:8"],
                          "out_abs": 512,
                          "doc": "full batched decompress block: "
                                 "ladder + tree + checks + fix-ups"},
}


# --------------------------------------------------------------------------
# Flag plumbing.
# --------------------------------------------------------------------------


def decompress_impl() -> str:
    """Trace-time decompress engine: 'pallas' (the VMEM kernels),
    'xla' (the host graph), or 'interpret' (kernels under the Pallas
    interpreter — CPU CI runs the exact shipping engine). Same shape
    as frontend_pallas.frontend_impl; an unrecognized value raises at
    the registry (choices are validated)."""
    impl = flags.get_str("FD_DECOMPRESS_IMPL", "auto")
    if impl in ("interpret", "pallas", "xla"):
        return impl
    if impl not in ("", "auto", None):
        # A typo'd force must never quietly measure the wrong engine
        # (frontend_impl's contract).
        raise ValueError(
            f"unknown FD_DECOMPRESS_IMPL {impl!r} "
            "(want auto|xla|pallas|interpret)"
        )
    from .backend import _platform_is_tpu

    return "pallas" if _platform_is_tpu() else "xla"


def group_log2() -> int:
    """log2 of the Montgomery inversion group (lanes per fe_invert
    chain). 0 disables the batched math everywhere — the staged
    per-lane-chain composition runs instead (the A/B hatch)."""
    return max(0, flags.get_int("FD_DECOMPRESS_BATCH"))


def chunk_lanes() -> int:
    """Lane-block width for the cache-blocked host graph (lax.map
    body size). 0 = unchunked (one block over the whole batch)."""
    return max(0, flags.get_int("FD_DECOMPRESS_CHUNK"))


def batch_eligible(bsz: int) -> bool:
    """Whether the batched HOST graph handles this batch: whole
    1024-lane blocks only, and the Montgomery group enabled.
    Everything else takes the staged composition on the xla path —
    never a wrong result. The kernel path has its own per-tile gate
    (use_batched_kernel over padded LANES-wide tiles); batched_active
    is the engine-aware answer."""
    return (bsz > 0 and bsz % ELIGIBLE_MULTIPLE == 0
            and group_log2() > 0)


def batched_active(bsz: int, impl: str | None = None) -> bool:
    """Engine-aware: does the Montgomery-batched math actually serve a
    bsz-lane decompress under the current flags? The host graph
    requires batch_eligible (whole 1024-lane blocks); the kernel path
    folds whole padded LANES-wide tiles whenever the tile reaches the
    full flag group (use_batched_kernel), independent of the host
    quantum. This — not batch_eligible — is what bench artifacts
    record as `decompress_batched`."""
    if bsz <= 0 or group_log2() == 0:
        return False
    if impl is None:
        impl = decompress_impl()
    if impl in ("pallas", "interpret"):
        from .curve_pallas import LANES, MIN_KERNEL_BATCH

        return (bsz >= MIN_KERNEL_BATCH
                and use_batched_kernel(min(LANES, bsz)))
    return batch_eligible(bsz)


def inversion_count(bsz: int, impl: str | None = None) -> int:
    """Analytic fe_invert-chain-LANE count for a bsz-lane decompress
    under the current flags: one chain lane per 2^FD_DECOMPRESS_BATCH
    lanes on the batched path, one per lane on the staged path.
    Engine-aware like batched_active: the kernel path pads to whole
    LANES-wide tiles, so its count runs over the padded width.
    Recorded in bench artifacts (`decompress_inversions`) so the
    2B -> 2B/64 drop is a checkable number, not prose."""
    if impl is None:
        impl = decompress_impl()
    if not batched_active(bsz, impl):
        return max(0, bsz)
    if impl in ("pallas", "interpret"):
        from .curve_pallas import LANES

        lanes = min(LANES, bsz)
        padded = -(-bsz // lanes) * lanes
        return padded >> group_log2()
    # Host graph: fe_invert_batch runs once per chunk_lanes() block and
    # DEGRADES the group until it divides the block (fe25519.py) —
    # mirror that here so the artifact number is exact for any flag
    # combo (e.g. FD_DECOMPRESS_BATCH > log2(FD_DECOMPRESS_CHUNK)).
    ck = chunk_lanes() or bsz
    if ck > bsz or bsz % ck:
        ck = bsz
    g = group_log2()
    while g > 0 and (ck % (1 << g) or ck >> g < 1):
        g -= 1
    return (bsz // ck) * (ck >> g)


# --------------------------------------------------------------------------
# Shared block math (XLA graph; the kernel body below mirrors it with
# the Mosaic-safe primitive set). Everything is (32, L) limb-major.
# --------------------------------------------------------------------------


def _iota_col(ndim: int):
    return jax.lax.broadcasted_iota(
        jnp.int32, (NLIMBS,) + (1,) * (ndim - 1), 0)


def _y_pm1_mask(y: jnp.ndarray) -> jnp.ndarray:
    """(1, *batch) mask: y == +-1 mod p, tested directly on the raw
    byte limbs (y < 2^255 after the sign-bit mask, so the residues'
    only representations are 1, p-1 and p+1 — three constant
    compares instead of a canonicalize chain). Equivalent to
    u == 0 mod p, which is exactly the lanes whose w = u*v would
    poison a Montgomery group, and exactly the x == 0 mask."""
    i = _iota_col(y.ndim)
    one_c = jnp.where(i == 0, 1, 0)
    pm1_c = jnp.where(i == 0, 0xEC,
                      jnp.where(i == NLIMBS - 1, 0x7F, 0xFF))
    pp1_c = jnp.where(i == 0, 0xEE,
                      jnp.where(i == NLIMBS - 1, 0x7F, 0xFF))
    hit = None
    for c in (one_c, pm1_c, pp1_c):
        m = (jnp.sum(jnp.abs(y - c), axis=0, keepdims=True)
             == 0).astype(jnp.int32)
        hit = m if hit is None else hit | m
    return hit


def _mont_inv_tree(m: jnp.ndarray, g: int) -> jnp.ndarray:
    """Per-lane inverses of m (every lane nonzero mod p) via the
    grouped prefix-product tree: one fe_invert chain per 2^g lanes
    plus ~3 multiplies per lane (fe25519.fe_invert_batch, the same
    tree compress has used since round 5 — now the decompress
    workhorse)."""
    return fe.fe_invert_batch(m, group_log2=g, invert_fn=fe.fe_invert)


def _decompress_block(y: jnp.ndarray, sign: jnp.ndarray):
    """One cache-resident block of the batched decompress.

    y: (32, L) raw byte limbs (high bit already masked);
    sign: (1, L) int32 in {0, 1}.
    Returns (x, y, z, t, ok, xz) with ok/xz as (1, L) int32 masks and
    failed lanes carrying the identity poison (0, 1, 1, 0) — the
    contract of curve_pallas._decompress_body, bit-exact.
    """
    lanes_nd = y.ndim
    i = _iota_col(lanes_nd)
    one = (i == 0).astype(jnp.int32)
    d_c = fe.int_to_limbs(fe.D_INT, y.shape[1:])
    sqrtm1 = fe.int_to_limbs(fe.SQRT_M1_INT, y.shape[1:])

    yy = fe.fe_sq_l4(y)
    u = fe.fe_sub(yy, one)                      # y^2 - 1
    v = fe.fe_add(fe.fe_mul(yy, d_c), one)      # d y^2 + 1
    w = fe.fe_mul(u, v)

    # Zero lanes (u == 0 mod p): mask their group contribution to 1 so
    # the tree stays invertible; their x is pinned to 0 by the ladder.
    uz = _y_pm1_mask(y)
    m = fe.fe_mul(fe.fe_sq_l4(w), v)            # u^2 v^3
    m_safe = fe._sel01(uz, one, m)

    inv_m = _mont_inv_tree(m_safe, group_log2() or 6)
    s = fe.fe_sqn_sched(w, LADDER_SQUARINGS)    # w^(2^252)
    x = fe.fe_mul(s, inv_m)                     # the sqrt-ratio candidate

    vxx = fe.fe_mul(fe.fe_sq_l4(x), v)
    root_ok = fe.fe_is_zero_k(fe.fe_sub(vxx, u))
    neg_ok = fe.fe_is_zero_k(fe.fe_add(vxx, u))
    x = fe._sel01(root_ok, x, fe.fe_mul(x, sqrtm1))
    ok = root_ok | neg_ok

    flip = fe.fe_parity_k(x) ^ sign
    x = fe._sel01(flip, fe.fe_neg(x), x)

    t = fe.fe_mul(x, y)
    zero = jnp.zeros_like(x)
    return (fe._sel01(ok, x, zero), fe._sel01(ok, y, one),
            jnp.broadcast_to(one, x.shape), fe._sel01(ok, t, zero),
            ok, uz)


def _double_block(x, y, z):
    """dbl-2008-hwcd a=-1, T-free, lean ops (the small-order chain)."""
    a = fe.fe_sq_l4(x)
    b = fe.fe_sq_l4(y)
    zz = fe.fe_sq_l4(z)
    c = fe.fe_add(zz, zz)
    d_ = fe.fe_neg(a)
    e = fe.fe_sub(fe.fe_sub(fe.fe_sq_l4(fe.fe_add(x, y)), a), b)
    g = fe.fe_add(d_, b)
    f = fe.fe_sub(g, c)
    h = fe.fe_sub(d_, b)
    return fe.fe_mul(e, f), fe.fe_mul(g, h), fe.fe_mul(f, g)


def _small_order_block(x, y, z):
    """(1, L) mask: 8*P == identity, on the (possibly poisoned) block
    output — failed lanes hold the identity and read small_order=1,
    matching the staged path (callers gate on ok first)."""
    for _ in range(3):
        x, y, z = _double_block(x, y, z)
    return fe.fe_is_zero_k(x) * fe.fe_is_zero_k(fe.fe_sub(y, z))


# --------------------------------------------------------------------------
# Cache-blocked host graph.
# --------------------------------------------------------------------------


def decompress_batched_xla(y_bytes: jnp.ndarray,
                           want_x_zero: bool = False,
                           want_small_order: bool = False):
    """The batched decompress as a host XLA graph: (B, 32) uint8 ->
    ((X, Y, Z, T) limbs, ok bool[, x_zero][, small_order]). Callers
    gate on batch_eligible first. lax.map serializes
    FD_DECOMPRESS_CHUNK-lane blocks so the ~252-squaring ladder's
    working set stays cache-resident — measured 2.9x the flat graph's
    per-squaring rate on the CI host (scripts/kernel_probe.py
    --suspect decompress keeps the sweep)."""
    bsz = y_bytes.shape[0]
    sign = (y_bytes[:, 31] >> 7).astype(jnp.int32)[None, :]   # (1, B)
    y = fe.fe_from_bytes(y_bytes, mask_high_bit=True)         # (32, B)

    ck = chunk_lanes() or bsz
    if ck > bsz or bsz % ck:
        ck = bsz
    n = bsz // ck

    def block(args):
        yb, sb = args
        out = _decompress_block(yb, sb)
        if want_small_order:
            out = out + (_small_order_block(out[0], out[1], out[2]),)
        return out

    if n == 1:
        outs = block((y, sign))
    else:
        yc = jnp.moveaxis(y.reshape(NLIMBS, n, ck), 1, 0)
        sc_ = jnp.moveaxis(sign.reshape(1, n, ck), 1, 0)
        stacked = jax.lax.map(block, (yc, sc_))
        # (n, rows, ck) -> (rows, B): blocks are contiguous lane runs.
        outs = tuple(
            jnp.moveaxis(o, 0, 1).reshape(o.shape[1], bsz)
            for o in stacked
        )

    x, yy, z, t = outs[:4]
    ok, xz = outs[4], outs[5]
    ret = [(x, yy, z, t), ok[0] != 0]
    if want_x_zero:
        ret.append(xz[0] != 0)
    if want_small_order:
        ret.append(outs[6][0] != 0)
    return tuple(ret)


# --------------------------------------------------------------------------
# Kernel-side mirror (called from curve_pallas._decompress_body while
# the tile sits in VMEM; Mosaic-safe primitive set only).
# --------------------------------------------------------------------------


def use_batched_kernel(lanes: int) -> bool:
    """Whether the kernel body runs the batched math on this tile: the
    Montgomery group must be enabled AND the tile must fold to the
    FULL flag group (_tree_levels == group_log2), so the in-tile tree
    realizes exactly one invert lane per 2^FD_DECOMPRESS_BATCH lanes
    and the analytic inversion_count is never a lie. Narrow/odd test
    tiles that cannot reach the group keep the per-lane chain body."""
    return group_log2() > 0 and _tree_levels(lanes) == group_log2()


def _tree_levels(lanes: int) -> int:
    """Half-split depth for the in-tile tree: halve while even, down
    to >= 8-lane roots, capped by the flag group (lanes=512, g=6 ->
    8-lane roots = 64 lanes per chain, the 2B/64 analytic count)."""
    g = group_log2()
    levels = 0
    width = lanes
    while levels < g and width % 2 == 0 and width > 8:
        width //= 2
        levels += 1
    return levels


def _mont_inv_tree_k(m: jnp.ndarray, levels: int) -> jnp.ndarray:
    """In-VMEM prefix-product tree: contiguous half-split products
    down the levels, ONE invert_chain on the root tile, then the
    backward sweep — lane-axis concats/slices only (no strided
    pairing; Mosaic keeps every slice a static lane window)."""
    from .pow_pallas import _mul
    from .pow_pallas import invert_chain as _invert

    stack = []
    cur = m
    for _ in range(levels):
        half = cur.shape[1] // 2
        a, b = cur[:, :half], cur[:, half:]
        stack.append((a, b))
        cur = _mul(a, b)
    inv = _invert(cur)
    for a, b in reversed(stack):
        inv = jnp.concatenate([_mul(inv, b), _mul(inv, a)], axis=1)
    return inv


def _decompress_batched_body(y, sign, consts):
    """The batched math on one VMEM tile — mirror of
    _decompress_block with the kernel-dispatched field ops (returns
    (x, y, z, t, ok, xz); curve_pallas._decompress_body writes the
    refs and layers niels / small-order outputs on top)."""
    from .pow_pallas import _mul, _sq, _sqn

    lanes = y.shape[1]
    d_c = jnp.broadcast_to(consts[:, 0:1], (NLIMBS, lanes))
    sqrtm1 = jnp.broadcast_to(consts[:, 1:2], (NLIMBS, lanes))
    one = (jax.lax.broadcasted_iota(jnp.int32, (NLIMBS, lanes), 0) == 0)
    one = one.astype(jnp.int32)

    yy = _sq(y)
    u = fe.fe_sub(yy, one)
    v = fe.fe_add(_mul(yy, d_c), one)
    w = _mul(u, v)

    uz = _y_pm1_mask(y)
    m = _mul(_sq(w), v)
    m_safe = fe._sel01(uz, one, m)

    inv_m = _mont_inv_tree_k(m_safe, _tree_levels(lanes))
    s = _sqn(w, LADDER_SQUARINGS)
    x = _mul(s, inv_m)

    vxx = _mul(_sq(x), v)
    root_ok = fe.fe_is_zero_k(fe.fe_sub(vxx, u))
    neg_ok = fe.fe_is_zero_k(fe.fe_add(vxx, u))
    x = fe._sel01(root_ok, x, _mul(x, sqrtm1))
    ok = root_ok | neg_ok

    flip = fe.fe_parity_k(x) ^ sign
    x = fe._sel01(flip, fe.fe_neg(x), x)

    t = _mul(x, y)
    zero = jnp.zeros((NLIMBS, lanes), jnp.int32)
    return (fe._sel01(ok, x, zero), fe._sel01(ok, y, one), one,
            fe._sel01(ok, t, zero), ok, uz)


# --------------------------------------------------------------------------
# Dispatch (the decompress_auto / decompress_so_auto entry point).
# --------------------------------------------------------------------------


def decompress_batched_auto(y_bytes: jnp.ndarray,
                            want_x_zero: bool = False,
                            want_niels: bool = False,
                            want_small_order: bool = False):
    """Backend- and shape-dispatched decompress — the one entry the
    verify paths (and profile_stages' decompress stage) route through
    since PR 14. Return shape matches the historical
    curve25519.decompress_auto / decompress_so_auto contracts."""
    if want_niels and want_small_order:
        raise ValueError("want_niels and want_small_order are exclusive")
    impl = decompress_impl()
    if impl in ("pallas", "interpret"):
        # curve_pallas's kernels share _decompress_batched_body via
        # _decompress_body when use_batched_kernel says so; the
        # sub-tile fallback inside decompress_pallas stays intact.
        from .curve_pallas import decompress_pallas

        return decompress_pallas(
            y_bytes, interpret=impl == "interpret",
            want_x_zero=want_x_zero, want_niels=want_niels,
            want_small_order=want_small_order,
        )
    if want_niels:
        raise ValueError("want_niels requires the kernel backend")
    bsz = y_bytes.shape[0]
    if batch_eligible(bsz):
        out = decompress_batched_xla(
            y_bytes, want_x_zero=want_x_zero,
            want_small_order=want_small_order)
        return out
    # Staged composition: the per-lane-chain XLA graph (bit-exact,
    # same return shape for every mask combination as the batched
    # engines — no shape-dependent API cliffs).
    from . import curve25519 as ge

    if want_small_order:
        if want_x_zero:
            pt, ok, xz = ge.decompress_xla(y_bytes, True)
            return pt, ok, xz, ge.small_order_mask(pt)
        pt, ok = ge.decompress_xla(y_bytes)
        return pt, ok, ge.small_order_mask(pt)
    return ge.decompress_xla(y_bytes, want_x_zero)
