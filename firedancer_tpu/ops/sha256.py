"""Batched SHA-256 and PoH chains on TPU (JAX/XLA).

Role: TPU analog of the reference's 8-way AVX SHA-256 batch API
(/root/reference/src/ballet/sha256/fd_sha256_batch_avx.c) and of the PoH
hashchain (/root/reference/src/ballet/poh/fd_poh.h). SHA-256 words are
native uint32, so unlike the SHA-512 kernel no hi/lo pairing is needed —
everything is elementwise uint32 on the VPU with the batch riding the
128-wide lane axis (lane-major (..., B) layout).

PoH is serial within a chain but embarrassingly parallel across chains:
poh_append_batch runs B independent hashchains in lockstep, which is how a
slot's entry hashes are verified in parallel (each entry's segment is one
lane; the per-lane `n` masks shorter segments).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

U32 = jnp.uint32

# FIPS 180-4 SHA-256 round constants / IV.
_K = [
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
    0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
    0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
    0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
    0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
]
_IV = [
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
]
_K_ARR = jnp.asarray(np.asarray(_K, np.uint32))
_IV_ARR = np.asarray(_IV, np.uint32)


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def _compress_block(state, w):
    """One SHA-256 compression. state: (8, B) uint32, w: (16, B) uint32."""

    def extend(window, _):
        s0 = _rotr(window[1], 7) ^ _rotr(window[1], 18) ^ (window[1] >> 3)
        s1 = _rotr(window[14], 17) ^ _rotr(window[14], 19) ^ (window[14] >> 10)
        nw = window[0] + s0 + window[9] + s1
        return jnp.concatenate([window[1:], nw[None]], axis=0), nw

    _, ext = jax.lax.scan(extend, w, None, length=48)
    sched = jnp.concatenate([w, ext], axis=0)  # (64, B)

    def round_fn(vs, inputs):
        k, wt = inputs
        a, b, c, d, e, f, g, h = vs
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k + wt
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    batch = w.shape[1:]
    init = tuple(state[i] for i in range(8))
    k_b = jnp.broadcast_to(_K_ARR[:, None], (64,) + batch) if batch else _K_ARR
    final, _ = jax.lax.scan(round_fn, init, (k_b, sched))
    return jnp.stack([state[i] + final[i] for i in range(8)])


def _bytes_to_words(block_bytes):
    """(64, B) uint8 big-endian -> (16, B) uint32."""
    b = block_bytes.astype(U32).reshape((16, 4) + block_bytes.shape[1:])
    return (b[:, 0] << 24) | (b[:, 1] << 16) | (b[:, 2] << 8) | b[:, 3]


def _state_to_bytes(state):
    """(8, B) uint32 -> (B, 32) uint8 big-endian."""
    words = jnp.moveaxis(state, 0, -1)  # (B, 8)
    shifts = jnp.asarray([24, 16, 8, 0], U32)
    by = (words[..., None] >> shifts[None, None, :]) & 0xFF
    return by.reshape(words.shape[:-1] + (32,)).astype(jnp.uint8)


def _bytes_to_state(digests):
    """(B, 32) uint8 -> (8, B) uint32 big-endian words."""
    b = digests.astype(U32).reshape(digests.shape[:-1] + (8, 4))
    words = (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]
    return jnp.moveaxis(words, -1, 0)


def sha256_batch(msgs: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-256 of variable-length rows (same contract as
    ops.sha512.sha512_batch: (B, max_len) uint8 + (B,) lengths -> (B, 32))."""
    bsz, max_len = msgs.shape
    max_blocks = (max_len + 9 + 63) // 64
    total = max_blocks * 64
    lengths = lengths.astype(jnp.int32)

    data = jnp.moveaxis(msgs.astype(U32), -1, 0)  # (max_len, B)
    if total > max_len:
        data = jnp.concatenate([data, jnp.zeros((total - max_len, bsz), U32)], 0)
    pos = jnp.arange(total, dtype=jnp.int32)[:, None]
    ln = lengths[None, :]
    data = jnp.where(pos < ln, data, 0)
    data = jnp.where(pos == ln, 0x80, data)
    nblocks = (lengths + 9 + 63) // 64
    len_start = nblocks * 64 - 8
    bitlen_lo = lengths.astype(U32) << 3
    bitlen_hi = lengths.astype(U32) >> 29
    k = pos - len_start[None, :]
    word = jnp.where(k < 4, bitlen_hi[None, :], bitlen_lo[None, :])
    shift = (3 - (k & 3)) * 8
    lenbyte = jnp.where(
        (k >= 0) & (k < 8), (word >> jnp.clip(shift, 0, 31)) & 0xFF, 0
    ).astype(U32)
    data = data | lenbyte

    state = jnp.broadcast_to(_IV_ARR[:, None], (8, bsz)).astype(U32)

    def per_block(state, i):
        block = jax.lax.dynamic_slice_in_dim(data, i * 64, 64, axis=0)
        new_state = _compress_block(state, _bytes_to_words(block))
        active = (i < nblocks)[None, :]
        return jnp.where(active, new_state, state), None

    state, _ = jax.lax.scan(per_block, state, jnp.arange(max_blocks))
    return _state_to_bytes(state)


# --- PoH on TPU ------------------------------------------------------------
# A PoH step hashes a fixed 32-byte state: exactly one padded block
# (state | 0x80 | zeros | bitlen=256), so the padding is a compile-time
# constant and each step is a single compression.

_PAD32 = np.zeros((8,), np.uint32)
_PAD32[0] = 0x80000000
_PAD32_TAIL = np.concatenate([_PAD32[:7], np.asarray([256], np.uint32)])


def _poh_step(state):
    """(8, B) -> (8, B): one sha256(state) iteration."""
    bsz = state.shape[1]
    pad = jnp.broadcast_to(
        jnp.asarray(_PAD32_TAIL)[:, None], (8, bsz)
    ).astype(U32)
    w = jnp.concatenate([state, pad], axis=0)  # (16, B)
    return _compress_block(
        jnp.broadcast_to(_IV_ARR[:, None], (8, bsz)).astype(U32), w
    )


def poh_append_batch(states: jnp.ndarray, n: jnp.ndarray, max_n: int) -> jnp.ndarray:
    """Advance B independent PoH chains by n[b] hashes each.

    states: (B, 32) uint8; n: (B,) int32 (n[b] <= max_n, static bound).
    Returns (B, 32) uint8. All lanes run max_n steps; lanes stop updating
    once their count is reached (batch-uniform control flow).
    """
    st = _bytes_to_state(states)
    n = n.astype(jnp.int32)

    def step(st, i):
        new = _poh_step(st)
        return jnp.where((i < n)[None, :], new, st), None

    st, _ = jax.lax.scan(step, st, jnp.arange(max_n))
    return _state_to_bytes(st)


def poh_mixin_batch(states: jnp.ndarray, mixins: jnp.ndarray) -> jnp.ndarray:
    """state' = sha256(state || mixin) per lane.

    states, mixins: (B, 32) uint8 -> (B, 32) uint8. The 64-byte message
    fills one block; padding is a second, constant block.
    """
    bsz = states.shape[0]
    w1 = jnp.concatenate(
        [_bytes_to_state(states), _bytes_to_state(mixins)], axis=0
    )  # (16, B)
    iv = jnp.broadcast_to(_IV_ARR[:, None], (8, bsz)).astype(U32)
    mid = _compress_block(iv, w1)
    pad = np.zeros((16,), np.uint32)
    pad[0] = 0x80000000
    pad[15] = 512
    w2 = jnp.broadcast_to(jnp.asarray(pad)[:, None], (16, bsz)).astype(U32)
    return _state_to_bytes(_compress_block(mid, w2))
