"""Pallas TPU kernel for the mod-L Barrett reduction (sc25519).

sc_reduce64's XLA graph is five sequential base-256 carry chains plus
two small convolutions — ~8.7 ms at B=8192 on v5e, almost all of it
multi-kernel elementwise launch cost. In VMEM the same reduction is a
few hundred fused vector ops.

Identical algorithm to sc25519.sc_reduce64 (the CPU/test reference):
Barrett with b = 2^8, k = 32; mu and L enter as Python int literals
folded into the instruction stream (the round structure is static), so
the kernel needs no constant-array inputs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import fe25519 as fe
from . import sc25519 as sc

LANES = 2048


def _seq_carry_k(x):
    return fe._seq_carry_k(x)


def _conv_const(x, weights, n_out: int):
    """conv(x, weights) truncated to n_out rows; weights are Python
    ints (static), x is (n_in, L). Static-slice shifts + scalar muls."""
    n_in = x.shape[0]
    lanes = x.shape[1]
    acc = jnp.zeros((n_out, lanes), jnp.int32)
    for j, w in enumerate(weights):
        if w == 0:
            continue
        rows = min(n_in, n_out - j)
        if rows <= 0:
            break
        term = x[:rows] * np.int32(w)
        acc = acc + fe._pad_rows_k(term, j, n_out - j - rows, (lanes,))
    return acc


def _barrett_body(x):
    """(64, L) int32 canonical byte limbs of x < 2^512 -> (32, L)
    canonical limbs of x mod L (kernel-safe; shared by the reduce and
    mul kernels)."""
    mu = [(sc._MU >> (8 * i)) & 0xFF for i in range(33)]
    l_limbs = [(sc.L >> (8 * i)) & 0xFF for i in range(33)]

    q1 = x[31:]                                   # (33, L)
    q2 = _conv_const(q1, mu, 66)
    q2, _ = _seq_carry_k(q2)
    q3 = q2[33:]                                  # (33, L)
    q3l = _conv_const(q3, l_limbs, 33)
    q3l, _ = _seq_carry_k(q3l)
    r, _ = _seq_carry_k(x[:33] - q3l)
    i = jax.lax.broadcasted_iota(jnp.int32, (33, 1), 0)
    l_col = jnp.zeros((33, 1), jnp.int32)
    for j, w in enumerate(l_limbs):
        l_col = l_col + jnp.where(i == j, w, 0)
    for _ in range(2):
        d, borrow = _seq_carry_k(r - l_col)
        keep = (borrow < 0).astype(jnp.int32)
        r = keep * r + (1 - keep) * d
    return r[:32]


def _sc_reduce_kernel(xin, out):
    out[...] = _barrett_body(xin[...])


def _sc_mul_kernel(ain, bin_, out):
    """a, b: (32, L) int32 canonical byte limbs -> (32, L) canonical
    limbs of a*b mod L. Schoolbook conv (products <= 32*255^2 < 2^21,
    inside int32) -> exact carry -> Barrett."""
    a = ain[...]
    b = bin_[...]
    lanes = a.shape[1]
    acc = jnp.zeros((64, lanes), jnp.int32)
    for i in range(32):
        acc = acc + fe._pad_rows_k(a[i:i + 1] * b, i, 32 - i, (lanes,))
    x, _ = _seq_carry_k(acc)                      # < 2^512 exactly
    out[...] = _barrett_body(x)


def sc_mul_pallas(a_bytes: jnp.ndarray, b_bytes: jnp.ndarray,
                  interpret: bool = False) -> jnp.ndarray:
    """(B, 32) x (B, 32) uint8 -> (B, 32) uint8, a*b mod L per lane
    (the c=0 case of sign._sc_muladd, in VMEM). Sub-tile batches fall
    back to the XLA path."""
    from jax.experimental import pallas as pl

    from .sign import _sc_muladd

    if a_bytes.ndim != 2 or a_bytes.shape[0] < 128:
        return _sc_muladd(a_bytes, b_bytes, jnp.zeros_like(a_bytes))
    bsz = a_bytes.shape[0]
    a = jnp.moveaxis(a_bytes.astype(jnp.int32), -1, 0)      # (32, B)
    b = jnp.moveaxis(b_bytes.astype(jnp.int32), -1, 0)
    lanes = min(LANES, bsz)
    pad = (-bsz) % lanes
    if pad:
        a = jnp.pad(a, ((0, 0), (0, pad)))
        b = jnp.pad(b, ((0, 0), (0, pad)))
    n = (bsz + pad) // lanes

    out = pl.pallas_call(
        _sc_mul_kernel,
        grid=(n,),
        in_specs=[pl.BlockSpec((32, lanes), lambda i: (0, i))] * 2,
        out_specs=pl.BlockSpec((32, lanes), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((32, bsz + pad), jnp.int32),
        interpret=interpret,
    )(a, b)
    if pad:
        out = out[:, :bsz]
    return jnp.moveaxis(out, 0, -1).astype(jnp.uint8)


def sc_reduce64_pallas(hash_bytes: jnp.ndarray,
                       interpret: bool = False) -> jnp.ndarray:
    """Drop-in for sc25519.sc_reduce64 on TPU: (B, 64) uint8 -> (B, 32)
    uint8 canonical mod L. Batches below one lane tile (or with extra
    leading dims) take the XLA path."""
    from jax.experimental import pallas as pl

    if hash_bytes.ndim != 2 or hash_bytes.shape[0] < 128:
        return sc.sc_reduce64(hash_bytes)
    bsz = hash_bytes.shape[0]
    x = jnp.moveaxis(hash_bytes.astype(jnp.int32), -1, 0)   # (64, B)
    lanes = min(LANES, bsz)
    pad = (-bsz) % lanes
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)))
    n = (bsz + pad) // lanes

    out = pl.pallas_call(
        _sc_reduce_kernel,
        grid=(n,),
        in_specs=[pl.BlockSpec((64, lanes), lambda i: (0, i))],
        out_specs=pl.BlockSpec((32, lanes), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((32, bsz + pad), jnp.int32),
        interpret=interpret,
    )(x)
    if pad:
        out = out[:, :bsz]
    return jnp.moveaxis(out, 0, -1).astype(jnp.uint8)
