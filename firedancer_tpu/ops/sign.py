"""Batched Ed25519 keygen + signing on TPU (JAX/XLA).

The device analog of the reference's fd_ed25519_sign / public_from_private
(/root/reference/src/ballet/ed25519/fd_ed25519_user.c:305-344 and
fd_ed25519.h:40-70) — but batched: one fused XLA program signs B messages
at once, reusing the verify stack's primitives (sha512_batch, the
fixed-window double-scalarmult with a zero h-scalar as a base-point
multiply, and Barrett scalar arithmetic mod L).

RFC 8032 signing is deterministic, so outputs are bit-exact against the
CPU oracle (ballet.ed25519.oracle.sign) — pinned by tests. Main consumer:
mainnet-scale corpus generation (the 100k-tx replay gate), where the
pure-Python oracle's ~0.5 s/signature is unusable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import curve25519 as ge
from . import sc25519 as sc
from .sha512 import sha512_batch

NLIMBS = 32


def _b_point(batch: int):
    """The Ed25519 base point, broadcast to the batch as limb arrays."""
    from firedancer_tpu.ballet.ed25519 import oracle

    enc = np.frombuffer(oracle.point_compress(oracle.B), np.uint8)
    enc_b = jnp.broadcast_to(jnp.asarray(enc)[None, :], (batch, 32))
    pt, ok = ge.decompress(enc_b)
    return pt, ok


def scalarmult_base(s_bytes: jnp.ndarray) -> tuple:
    """s*B for (B, 32) uint8 scalars (any 256-bit value).

    Runs the double-scalarmult with h = 0 so the A-term contributes only
    identity lookups; the result is the s*B table walk alone. Uses the
    backend-selected implementation (Pallas on TPU, XLA elsewhere), same
    as verify_batch.
    """
    from .verify import _dsm_auto

    bsz = s_bytes.shape[0]
    b_pt, _ = _b_point(bsz)
    zero = jnp.zeros_like(s_bytes)
    return _dsm_auto()(zero, b_pt, s_bytes)


def _clamp(a_bytes: jnp.ndarray) -> jnp.ndarray:
    """RFC 8032 secret-scalar clamp on (B, 32) uint8."""
    a = a_bytes
    a = a.at[:, 0].set(a[:, 0] & 248)
    a = a.at[:, 31].set((a[:, 31] & 63) | 64)
    return a


def _sc_muladd(h_bytes: jnp.ndarray, a_bytes: jnp.ndarray,
               r_bytes: jnp.ndarray) -> jnp.ndarray:
    """(h*a + r) mod L on (B, 32) uint8 scalars.

    Schoolbook limb convolution (63 limbs, partial sums < 32*255^2 + 255
    so int32 is safe), exact carry to a 64-byte integer, then the shared
    Barrett sc_reduce64. Reference: fd_ed25519_sc_muladd.
    """
    h = jnp.moveaxis(h_bytes.astype(jnp.int32), -1, 0)   # (32, B)
    a = jnp.moveaxis(a_bytes.astype(jnp.int32), -1, 0)
    r = jnp.moveaxis(r_bytes.astype(jnp.int32), -1, 0)
    bsz = h.shape[1]
    acc = jnp.zeros((64, bsz), jnp.int32)
    for i in range(NLIMBS):
        acc = acc.at[i:i + NLIMBS].add(h[i:i + 1] * a)
    acc = acc.at[:NLIMBS].add(r)
    limbs, _carry = sc._seq_carry(acc)                   # < 2^512: carry 0
    return sc.sc_reduce64(jnp.moveaxis(limbs, 0, -1).astype(jnp.uint8))


def keygen_batch(seeds: jnp.ndarray):
    """(B, 32) uint8 seeds -> (a_clamped, prefix, pub) per RFC 8032.

    a_clamped/prefix/pub are (B, 32) uint8; pub is the compressed A = a*B.
    """
    az = sha512_batch(seeds, jnp.full(seeds.shape[0], 32, jnp.int32))
    a = _clamp(az[:, :32])
    prefix = az[:, 32:]
    pub = ge.compress(scalarmult_base(a))
    return a, prefix, pub


def sign_batch(msgs: jnp.ndarray, lens: jnp.ndarray,
               seeds: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sign a batch of messages. Returns (sigs (B, 64), pubs (B, 32)).

    msgs: (B, max_len) uint8; lens: (B,) int32; seeds: (B, 32) uint8.
    """
    lens = lens.astype(jnp.int32)
    a, prefix, pub = keygen_batch(seeds)

    # r = SHA-512(prefix || msg) mod L
    r64 = sha512_batch(jnp.concatenate([prefix, msgs], axis=1), lens + 32)
    r_sc = sc.sc_reduce64(r64)
    r_enc = ge.compress(scalarmult_base(r_sc))

    # h = SHA-512(R || pub || msg) mod L  (same layout as verify)
    h64 = sha512_batch(
        jnp.concatenate([r_enc, pub, msgs], axis=1), lens + 64
    )
    h_sc = sc.sc_reduce64(h64)

    s = _sc_muladd(h_sc, a, r_sc)
    return jnp.concatenate([r_enc, s], axis=1), pub


sign_batch_jit = jax.jit(sign_batch)
