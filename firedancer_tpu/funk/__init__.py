"""funk — fork-aware record database for accounts/ledger state.

Role parity with the reference's fd_funk (/root/reference/src/funk/
fd_funk.h:28-90): a key→value store whose updates are staged in a tree of
in-preparation *transactions* forking off the last-published root. A
transaction can fork children (speculative forks of a fork), be cancelled
(dropping it and all descendants), or be published (folding it — and all
its ancestors — into the root, cancelling every competing sibling fork).
Reads inside a transaction fall through to the nearest ancestor holding
the record, ending at the published root.

The reference backs everything with a workspace so the wksp file doubles
as an on-disk checkpoint (fd_funk.h:136-140). Here the same contract is
kept with an explicit checkpoint/restore pair over a compact binary image
(length-prefixed records; the published root only — in-preparation
transactions are by definition speculative and are not checkpointed,
matching the reference where unpublished txns are lost on crash).

Records are (xid, key) → val as in fd_funk_rec; vals are opaque bytes
(fd_funk_val). Keys are bytes up to 64 B (FD_FUNK_REC_KEY_FOOTPRINT
analog); xids are caller-chosen opaque ints (the reference uses 32-byte
xids; Solana uses slot numbers — an int is the idiomatic form here).
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

ROOT_XID = 0  # the last-published root (fd_funk_txn_xid root analog)

_TOMBSTONE = None  # staged removal marker


class FunkError(Exception):
    pass


@dataclass
class _Txn:
    """One in-preparation transaction (fd_funk_txn_t analog)."""

    xid: int
    parent: int  # parent xid (ROOT_XID if forked off the root)
    children: List[int] = field(default_factory=list)
    # staged writes: key -> bytes, or _TOMBSTONE for staged removal
    recs: Dict[bytes, Optional[bytes]] = field(default_factory=dict)
    frozen: bool = False  # True once it has children (fd_funk freezes parents)


class Funk:
    """Fork-aware record database.

    Lifecycle mirrors fd_funk_txn_{prepare,cancel,publish} and
    fd_funk_rec_{query,insert,remove} + fd_funk_val_{read,write}.
    """

    MAX_KEY = 64

    def __init__(self) -> None:
        self._root: Dict[bytes, bytes] = {}
        self._txns: Dict[int, _Txn] = {}
        self._next_auto_xid = 1

    # -- transaction tree ----------------------------------------------

    def txn_prepare(self, parent: int = ROOT_XID, xid: Optional[int] = None) -> int:
        """Fork a new in-preparation txn off `parent`. Returns its xid."""
        if parent != ROOT_XID and parent not in self._txns:
            raise FunkError(f"unknown parent xid {parent}")
        if xid is None:
            while self._next_auto_xid in self._txns or self._next_auto_xid == ROOT_XID:
                self._next_auto_xid += 1
            xid = self._next_auto_xid
        if xid == ROOT_XID or xid in self._txns:
            raise FunkError(f"xid {xid} already in use")
        self._txns[xid] = _Txn(xid=xid, parent=parent)
        if parent != ROOT_XID:
            p = self._txns[parent]
            p.children.append(xid)
            p.frozen = True  # writes to a forked-from txn are disallowed
        return xid

    def txn_cancel(self, xid: int) -> int:
        """Cancel a txn and all its descendants. Returns count cancelled."""
        txn = self._txns.get(xid)
        if txn is None:
            raise FunkError(f"unknown xid {xid}")
        n = self._cancel_subtree(xid)
        if txn.parent != ROOT_XID and txn.parent in self._txns:
            p = self._txns[txn.parent]
            p.children.remove(xid)
            if not p.children:
                p.frozen = False
        return n

    def _cancel_subtree(self, xid: int) -> int:
        n = 0
        stack = [xid]
        while stack:
            txn = self._txns.pop(stack.pop())
            stack.extend(txn.children)
            n += 1
        return n

    def txn_publish(self, xid: int) -> int:
        """Publish `xid` and its unpublished ancestors into the root.

        Every txn that is not a descendant of `xid` (i.e. every competing
        history — siblings of `xid` and of each folded ancestor, plus their
        subtrees) is cancelled; `xid`'s own descendants survive, re-parented
        to the new root, exactly as fd_funk_txn_publish documents
        (fd_funk.h:60-78). Returns the number of txns published.
        """
        if xid not in self._txns:
            raise FunkError(f"unknown xid {xid}")
        chain = list(reversed(self.txn_ancestry(xid)[:-1]))  # root-side first
        survivors = self._descendants(xid)
        # Fold the chain into the root, oldest first.
        for level in chain:
            for key, val in self._txns[level].recs.items():
                if val is _TOMBSTONE:
                    self._root.pop(key, None)
                else:
                    self._root[key] = val
        # xid's children fork off now-published state: re-parent to root.
        for c in self._txns[xid].children:
            self._txns[c].parent = ROOT_XID
        # Drop the published chain and cancel all competing histories.
        for level in chain:
            del self._txns[level]
        for t in [t for t in self._txns if t not in survivors]:
            del self._txns[t]
        return len(chain)

    def _descendants(self, xid: int) -> set:
        """xids of `xid`'s strict descendants (subtree minus `xid`)."""
        out: set = set()
        stack = list(self._txns[xid].children)
        while stack:
            c = stack.pop()
            out.add(c)
            stack.extend(self._txns[c].children)
        return out

    def txn_is_frozen(self, xid: int) -> bool:
        if xid == ROOT_XID:
            return bool(self._txns)  # root is frozen while any txn is in prep
        txn = self._txns.get(xid)
        if txn is None:
            raise FunkError(f"unknown xid {xid}")
        return txn.frozen

    def txn_ancestry(self, xid: int) -> List[int]:
        """xid's ancestor chain, nearest first, ending at ROOT_XID."""
        out = []
        cur = xid
        while cur != ROOT_XID:
            t = self._txns.get(cur)
            if t is None:
                raise FunkError(f"unknown xid {cur}")
            out.append(cur)
            cur = t.parent
        out.append(ROOT_XID)
        return out

    @property
    def txn_cnt(self) -> int:
        return len(self._txns)

    # -- records ---------------------------------------------------------

    def _check_key(self, key: bytes) -> None:
        if not isinstance(key, bytes) or not key or len(key) > self.MAX_KEY:
            raise FunkError(f"bad key (1..{self.MAX_KEY} bytes required)")

    def write(self, xid: int, key: bytes, val: bytes) -> None:
        """Stage (xid==ROOT_XID: apply directly) a record write."""
        self._check_key(key)
        if xid == ROOT_XID:
            if self._txns:
                raise FunkError("root is frozen while txns are in preparation")
            self._root[key] = bytes(val)
            return
        txn = self._txns.get(xid)
        if txn is None:
            raise FunkError(f"unknown xid {xid}")
        if txn.frozen:
            raise FunkError(f"xid {xid} is frozen (has children)")
        txn.recs[key] = bytes(val)

    def remove(self, xid: int, key: bytes) -> None:
        """Stage a record removal (tombstone), or remove from root."""
        self._check_key(key)
        if xid == ROOT_XID:
            if self._txns:
                raise FunkError("root is frozen while txns are in preparation")
            self._root.pop(key, None)
            return
        txn = self._txns.get(xid)
        if txn is None:
            raise FunkError(f"unknown xid {xid}")
        if txn.frozen:
            raise FunkError(f"xid {xid} is frozen (has children)")
        txn.recs[key] = _TOMBSTONE

    def read(self, xid: int, key: bytes) -> Optional[bytes]:
        """Read a record as seen from `xid`: falls through the ancestor
        chain to the published root (fd_funk_rec_query_global analog)."""
        self._check_key(key)
        if xid != ROOT_XID:
            for a in self.txn_ancestry(xid)[:-1]:
                txn = self._txns[a]
                if key in txn.recs:
                    v = txn.recs[key]
                    return None if v is _TOMBSTONE else v
        return self._root.get(key)

    def keys(self, xid: int = ROOT_XID) -> Iterator[bytes]:
        """All live keys as seen from `xid`, in sorted order."""
        staged: Dict[bytes, Optional[bytes]] = {}
        if xid != ROOT_XID:
            for a in reversed(self.txn_ancestry(xid)[:-1]):
                staged.update(self._txns[a].recs)
        live = dict(self._root)
        for k, v in staged.items():
            if v is _TOMBSTONE:
                live.pop(k, None)
            else:
                live[k] = v
        return iter(sorted(live))

    @property
    def rec_cnt(self) -> int:
        return len(self._root)

    # -- checkpoint / restore --------------------------------------------
    # Image format: magic, rec_cnt, then per record: klen u16, key, vlen
    # u32, val. Only the published root is persisted (speculative state is
    # crash-discardable by design, fd_funk.h:136-140).

    _MAGIC = b"FDFUNK01"

    def checkpoint(self, path: str) -> int:
        """Write the published root to `path`. Returns records written."""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(self._MAGIC)
            f.write(struct.pack("<Q", len(self._root)))
            for k in sorted(self._root):
                v = self._root[k]
                f.write(struct.pack("<H", len(k)))
                f.write(k)
                f.write(struct.pack("<I", len(v)))
                f.write(v)
        os.replace(tmp, path)
        return len(self._root)

    @classmethod
    def restore(cls, path: str) -> "Funk":
        def must_read(f, n: int) -> bytes:
            b = f.read(n)
            if len(b) != n:
                raise FunkError(f"{path}: truncated checkpoint image")
            return b

        funk = cls()
        with open(path, "rb") as f:
            if f.read(8) != cls._MAGIC:
                raise FunkError(f"{path}: bad magic")
            (n,) = struct.unpack("<Q", must_read(f, 8))
            for _ in range(n):
                (klen,) = struct.unpack("<H", must_read(f, 2))
                if not 1 <= klen <= cls.MAX_KEY:
                    raise FunkError(f"{path}: bad key length {klen}")
                k = must_read(f, klen)
                (vlen,) = struct.unpack("<I", must_read(f, 4))
                funk._root[k] = must_read(f, vlen)
        return funk
