"""fctl — credit-based flow control for reliable consumers.

Role parity with the reference's fd_fctl
(/root/reference/src/tango/fctl/fd_fctl.h:4-60): a producer serving a mix
of reliable and unreliable consumers keeps `cr_avail` credits; each
publish spends one. Credits are lazily refreshed from every reliable
consumer's fseq: the slowest reliable consumer bounds how far the
producer may run ahead (cr_max at most the ring depth), and slow
consumers are attributed via their fseq's SLOW_CNT diag.

Parameters (fd_fctl semantics):
  cr_burst  max credits a single publish burst needs (>=1)
  cr_max    max credits the producer can bank (<= min rx depth)
  cr_resume if cr_avail falls below cr_burst, wait until refresh yields
            at least cr_resume before resuming (hysteresis)
  cr_refill only refresh from fseqs when cr_avail < cr_refill (limits
            cache-line bouncing on the fseqs)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

from .rings import DIAG_SLOW_CNT


@dataclass
class _Rx:
    seq_query: Callable[[], int]         # consumer progress (fseq read)
    slow_attr: Optional[Callable[[int], None]] = None  # add to SLOW_CNT


def _seq_diff(a: int, b: int) -> int:
    """Signed distance a-b in 64-bit sequence space."""
    d = (a - b) & ((1 << 64) - 1)
    return d - (1 << 64) if d >= (1 << 63) else d


@dataclass
class Fctl:
    depth: int
    cr_burst: int = 1
    cr_max: int = 0
    cr_resume: int = 0
    cr_refill: int = 0
    _rx: List[_Rx] = field(default_factory=list)
    cr_avail: int = 0
    in_backpressure: bool = False
    backp_cnt: int = 0

    def __post_init__(self) -> None:
        if self.cr_max <= 0:
            self.cr_max = self.depth
        self.cr_max = min(self.cr_max, self.depth)
        if self.cr_resume <= 0:
            # Default hysteresis: resume at ~2/3 of cr_max (fd_fctl default
            # shape: resume >= burst, well below max to amortize refresh).
            self.cr_resume = max(self.cr_burst, (2 * self.cr_max) // 3)
        if self.cr_refill <= 0:
            self.cr_refill = max(self.cr_burst, self.cr_resume // 2)

    def rx_add(
        self,
        seq_query: Callable[[], int],
        slow_attr: Optional[Callable[[int], None]] = None,
    ) -> "Fctl":
        """Register a reliable consumer (its fseq query fn)."""
        self._rx.append(_Rx(seq_query, slow_attr))
        return self

    def probe(self, tx_seq: int) -> int:
        """Side-effect-free credit query: how many credits a refresh at
        `tx_seq` would yield right now. Unlike tx_cr_update this neither
        mutates hysteresis state nor attributes slow consumers — it is
        the read-only signal fd_feed's flush policy uses ("is the out
        link backpressured?") without perturbing the producer's own
        credit accounting from another thread."""
        cr_query = self.cr_max
        for rx in self._rx:
            cr = self.cr_max - _seq_diff(tx_seq, rx.seq_query())
            cr_query = min(cr_query, max(0, min(self.cr_max, cr)))
        return cr_query

    def tx_cr_update(self, cr_avail: int, tx_seq: int) -> int:
        """Housekeeping refresh (fd_fctl_tx_cr_update): recompute credits
        from the slowest reliable consumer. Returns new cr_avail."""
        if cr_avail >= self.cr_refill and not self.in_backpressure:
            self.cr_avail = cr_avail
            return cr_avail
        cr_query = self.cr_max
        slowest = None
        for rx in self._rx:
            rx_seq = rx.seq_query()
            # Consumer has processed up to rx_seq; producer at tx_seq may
            # run ahead at most cr_max.
            cr = self.cr_max - _seq_diff(tx_seq, rx_seq)
            cr = max(0, min(self.cr_max, cr))
            if cr < cr_query:
                cr_query = cr
                slowest = rx
        if self.in_backpressure:
            if cr_query >= self.cr_resume:
                self.in_backpressure = False
                cr_avail = cr_query
            # else stay backpressured with old (insufficient) credits
            elif slowest is not None and slowest.slow_attr:
                slowest.slow_attr(1)
        else:
            cr_avail = cr_query
            if cr_avail < self.cr_burst:
                self.in_backpressure = True
                self.backp_cnt += 1
                if slowest is not None and slowest.slow_attr:
                    slowest.slow_attr(1)
        self.cr_avail = cr_avail
        return cr_avail


def make_fctl_for_fseqs(depth: int, fseqs, cr_burst: int = 1) -> Fctl:
    """Convenience: flow control over tango FSeq objects, attributing
    slow consumers to their DIAG_SLOW_CNT slot."""
    f = Fctl(depth=depth, cr_burst=cr_burst)
    for fs in fseqs:
        f.rx_add(fs.query, lambda d, fs=fs: fs.diag_add(DIAG_SLOW_CNT, d))
    return f
