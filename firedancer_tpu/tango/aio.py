"""aio — abstract async packet-burst IO.

Role parity with /root/reference/src/tango/aio/fd_aio.h (fd_aio_send
callback interface decoupling QUIC from XDP/sockets/pcap, aio/fd_aio.h:6-14).
An Aio endpoint is just a send callback taking a burst of (addr, payload)
packets; backends are UDP sockets (tango/udpsock), in-process wire pairs
(tests), or pcap writers.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

Packet = Tuple[object, bytes]  # (opaque peer address, datagram payload)


class Aio:
    """A packet sink: send_func receives a burst, returns #consumed."""

    def __init__(self, send_func: Callable[[List[Packet]], int]):
        self._send = send_func

    def send(self, batch: List[Packet]) -> int:
        return self._send(batch)

    def send_one(self, addr, payload: bytes) -> bool:
        return self._send([(addr, payload)]) == 1


class AioWirePair:
    """Two aio endpoints cross-wired through in-memory queues — the test
    fixture the reference builds in tango/quic/tests/fd_quic_test_helpers.c
    (virtual paired wires), with optional deterministic loss injection."""

    def __init__(self, drop_filter: Optional[Callable[[int, bytes], bool]] = None):
        self.a_to_b: List[Packet] = []
        self.b_to_a: List[Packet] = []
        self._n_sent = 0
        self._drop = drop_filter

    def _mk_send(self, queue: List[Packet]):
        def send(batch: List[Packet]) -> int:
            for addr, payload in batch:
                idx = self._n_sent
                self._n_sent += 1
                if self._drop is not None and self._drop(idx, payload):
                    continue  # deterministic loss injection
                queue.append((addr, payload))
            return len(batch)

        return send

    def endpoint_a(self) -> Aio:
        return Aio(self._mk_send(self.a_to_b))

    def endpoint_b(self) -> Aio:
        return Aio(self._mk_send(self.b_to_a))

    def drain_to_b(self) -> List[Packet]:
        out, self.a_to_b = self.a_to_b, []
        return out

    def drain_to_a(self) -> List[Packet]:
        out, self.b_to_a = self.b_to_a, []
        return out
