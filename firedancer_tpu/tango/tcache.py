"""tcache — recent-tag dedup cache (ring + map of last `depth` unique tags).

Role of the reference's tango/tcache (fd_tcache.h:344-414): O(1) duplicate
detection over the most recent `depth` unique 64-bit tags. The ring evicts
oldest-inserted (not LRU: a duplicate hit does not refresh age), exactly the
reference's semantics — the map tracks membership, the ring tracks age.
"""

from __future__ import annotations


class TCache:
    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("depth >= 1")
        self.depth = depth
        self._ring: list[int | None] = [None] * depth
        self._next = 0
        self._map: set[int] = set()
        self.hit_cnt = 0
        self.miss_cnt = 0

    def insert(self, tag: int) -> bool:
        """Returns True if tag was a duplicate (already among last depth)."""
        if tag in self._map:
            self.hit_cnt += 1
            return True
        self.miss_cnt += 1
        old = self._ring[self._next]
        if old is not None:
            self._map.discard(old)
        self._ring[self._next] = tag
        self._next = (self._next + 1) % self.depth
        self._map.add(tag)
        return False

    def reset(self):
        self._ring = [None] * self.depth
        self._next = 0
        self._map.clear()
