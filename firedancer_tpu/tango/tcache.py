"""tcache — recent-tag dedup cache (ring + map of last `depth` unique tags).

Role of the reference's tango/tcache (fd_tcache.h:344-414): O(1) duplicate
detection over the most recent `depth` unique 64-bit tags. The ring evicts
oldest-inserted (not LRU: a duplicate hit does not refresh age), exactly the
reference's semantics — the map tracks membership, the ring tracks age.
"""

from __future__ import annotations


class TCache:
    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("depth >= 1")
        self.depth = depth
        self._ring: list[int | None] = [None] * depth
        self._next = 0
        self._map: set[int] = set()
        self.hit_cnt = 0
        self.miss_cnt = 0
        # fd_drain tripwire ledger: lanes a device pre-filter claimed
        # DEFINITELY novel that the authoritative map contradicted.
        self.false_novel_cnt = 0

    def insert(self, tag: int) -> bool:
        """Returns True if tag was a duplicate (already among last depth)."""
        if tag in self._map:
            self.hit_cnt += 1
            return True
        self.miss_cnt += 1
        old = self._ring[self._next]
        if old is not None:
            self._map.discard(old)
        self._ring[self._next] = tag
        self._next = (self._next + 1) % self.depth
        self._map.add(tag)
        return False

    def insert_batch(self, tags, novel=None) -> "object":
        """Vectorized insert over a drain round's tag array: returns a
        numpy bool array, True where the tag was a duplicate —
        BIT-IDENTICAL to calling insert() per tag in order (the bulk
        dedup paths are gated on content parity with the per-frag
        loop).

        ``novel`` (optional bool array, same length) marks lanes a
        one-sided device pre-filter (fd_drain's dedup_filter) already
        ruled DEFINITELY novel: their dup verdict is owed to the
        filter, not this map, so the caller ledgers them as probe
        skips. The map lookup still runs for those lanes — but as the
        contract TRIPWIRE, not the decision authority: a novel claim
        the map contradicts increments ``false_novel_cnt`` and keeps
        the exact (duplicate → dropped) verdict, so a violated filter
        contract is observable and harmless rather than silently
        double-inserting a member (which would leave a stale map entry
        behind at eviction). Verdicts are therefore bit-identical with
        and without ``novel``.

        Fast path: one np.unique collapses in-batch repeats, membership
        is probed once per unique tag, and the verdict scatters back
        through the inverse index — O(uniq) Python instead of O(frags).
        The one sequential behavior this cannot express is a MID-BATCH
        EVICTION changing a later probe's verdict (a member among the
        next len(tags) ring slots gets evicted by this batch's inserts
        and then probed again); the guard detects exactly that overlap
        (two tiny set ops) and falls back to the exact loop, so the
        fast path is bit-identical whenever it runs."""
        import numpy as np

        tags = np.asarray(tags, np.uint64)
        n = len(tags)
        out = np.zeros(n, np.bool_)
        if n == 0:
            return out
        probe = set(int(t) for t in tags.tolist())
        # Eviction window: the next n ring slots (an upper bound on
        # this batch's inserts). Overlap with the probe set means a
        # verdict could depend on mid-batch eviction order.
        window = set()
        for i in range(min(n, self.depth)):
            t = self._ring[(self._next + i) % self.depth]
            if t is not None:
                window.add(t)
        if window & probe or n >= self.depth:
            for i, t in enumerate(tags.tolist()):
                out[i] = self.insert(int(t))
            if novel is not None:
                self.false_novel_cnt += int(
                    (np.asarray(novel, np.bool_) & out).sum())
            return out
        uniq, first_idx, inverse = np.unique(
            tags, return_index=True, return_inverse=True)
        m = self._map
        hit_u = np.fromiter((int(t) in m for t in uniq.tolist()),
                            np.bool_, len(uniq))
        out = hit_u[inverse]
        # A repeat of ANY tag is a duplicate (its first occurrence
        # either already was one or just inserted it).
        out |= np.arange(n) != first_idx[inverse]
        # Ring/map surgery only for the genuinely new tags, in
        # first-occurrence order so ring age matches the loop.
        new = uniq[~hit_u]
        new_first = first_idx[~hit_u]
        for t in new[np.argsort(new_first, kind="stable")].tolist():
            t = int(t)
            old = self._ring[self._next]
            if old is not None:
                m.discard(old)
            self._ring[self._next] = t
            self._next = (self._next + 1) % self.depth
            m.add(t)
        hits = int(out.sum())
        self.hit_cnt += hits
        self.miss_cnt += n - hits
        if novel is not None:
            self.false_novel_cnt += int(
                (np.asarray(novel, np.bool_) & out).sum())
        return out

    def insert_novel_batch(self, tags) -> "object":
        """Insert for tags a one-sided pre-filter (fd_drain's
        dedup_filter) proved DEFINITELY novel: the dup-verdict
        machinery of insert_batch (np.unique, eviction-window overlap
        guard, verdict scatter) is skipped entirely — just ring/map
        surgery in order, bit-identical to insert() for genuinely-new
        tags. One O(1) map check per tag remains as a tripwire: it
        returns a bool array, True where a "novel" tag was unexpectedly
        already a member — all-False whenever the filter's one-sided
        contract holds. A violated contract is thereby OBSERVABLE (the
        caller ledgers it and drops the frag as a duplicate, restoring
        exact semantics) instead of silently corrupting the ring (a
        double-inserted tag would leave a stale map entry behind at
        eviction)."""
        import numpy as np

        tl = [int(x) for x in
              (tags if isinstance(tags, list) else tags.tolist())]
        false_novel = np.zeros(len(tl), np.bool_)
        m = self._map
        for i, t in enumerate(tl):
            if t in m:
                # Contract breach: flag it, keep exact insert()
                # semantics (a member stays a member, age unchanged).
                false_novel[i] = True
                self.hit_cnt += 1
                continue
            self.miss_cnt += 1
            old = self._ring[self._next]
            if old is not None:
                m.discard(old)
            self._ring[self._next] = t
            self._next = (self._next + 1) % self.depth
            m.add(t)
        return false_novel

    def reset(self):
        self._ring = [None] * self.depth
        self._next = 0
        self._map.clear()
        self.false_novel_cnt = 0
