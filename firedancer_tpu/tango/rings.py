"""Python bindings for the native tango rings (ctypes over libfdtango.so).

Python tiles (the TPU shim, monitors, tests) join the same shared-memory
workspace files the native tiles use. The native library implements the
actual publish/consume protocols (seqlock discipline lives in C++,
native/tango.cc); Python calls through ctypes, which is fine off the
nanosecond path — the hot Python-side consumer is the TPU batch shim, which
drains frags in batches.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "build",
    "libfdtango.so",
)

POLL_EMPTY = 0
POLL_FRAG = 1
POLL_OVERRUN = 2

CTL_SOM = 1
CTL_EOM = 2
CTL_ERR = 4

CNC_BOOT = 0
CNC_RUN = 1
CNC_HALT = 2
CNC_FAIL = 3

# fseq diag slots (fd_fseq.h:57-63 ABI analog)
DIAG_PUB_CNT = 0
DIAG_PUB_SZ = 1
DIAG_FILT_CNT = 2
DIAG_FILT_SZ = 3
DIAG_OVRNP_CNT = 4
DIAG_OVRNR_CNT = 5
DIAG_SLOW_CNT = 6


def ensure_native_built(lib_path: str = _LIB_PATH) -> None:
    """Build the native tree if lib_path is missing; flock-serialized so
    concurrent processes can't race partially-written .so files."""
    if os.path.exists(lib_path):
        return
    import fcntl

    build_dir = os.path.dirname(lib_path)
    os.makedirs(build_dir, exist_ok=True)
    native_dir = os.path.abspath(
        os.path.join(build_dir, os.pardir, "native"))
    with open(os.path.join(build_dir, ".build.lock"), "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        if not os.path.exists(lib_path):
            subprocess.run(["make", "-s"], cwd=native_dir, check=True)


def load_lib() -> ctypes.CDLL:
    ensure_native_built(_LIB_PATH)
    lib = ctypes.CDLL(_LIB_PATH)
    lib.fd_wksp_create.restype = ctypes.c_void_p
    lib.fd_wksp_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    if hasattr(lib, "fd_wksp_page_probe"):  # absent in a stale build
        lib.fd_wksp_page_probe.restype = ctypes.c_uint64
        lib.fd_wksp_page_probe.argtypes = []
    lib.fd_wksp_join.restype = ctypes.c_void_p
    lib.fd_wksp_join.argtypes = [ctypes.c_char_p]
    lib.fd_wksp_leave.argtypes = [ctypes.c_void_p]
    lib.fd_wksp_alloc.restype = ctypes.c_uint64
    lib.fd_wksp_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint64, ctypes.c_uint64]
    lib.fd_wksp_query.restype = ctypes.c_uint64
    lib.fd_wksp_query.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_uint64)]
    lib.fd_wksp_laddr.restype = ctypes.c_void_p
    lib.fd_wksp_laddr.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.fd_mcache_footprint.restype = ctypes.c_uint64
    lib.fd_mcache_footprint.argtypes = [ctypes.c_uint64]
    lib.fd_mcache_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.fd_mcache_depth.restype = ctypes.c_uint64
    lib.fd_mcache_depth.argtypes = [ctypes.c_void_p]
    lib.fd_mcache_seq_next.restype = ctypes.c_uint64
    lib.fd_mcache_seq_next.argtypes = [ctypes.c_void_p]
    lib.fd_mcache_publish.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
        ctypes.c_uint16, ctypes.c_uint16, ctypes.c_uint32, ctypes.c_uint32]
    lib.fd_mcache_poll.restype = ctypes.c_int
    lib.fd_mcache_poll.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.POINTER(ctypes.c_uint64 * 4)]
    lib.fd_fseq_footprint.restype = ctypes.c_uint64
    lib.fd_fseq_init.argtypes = [ctypes.c_void_p]
    lib.fd_fseq_update.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.fd_fseq_query.restype = ctypes.c_uint64
    lib.fd_fseq_query.argtypes = [ctypes.c_void_p]
    lib.fd_fseq_diag_add.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                     ctypes.c_uint64]
    lib.fd_fseq_diag_get.restype = ctypes.c_uint64
    lib.fd_fseq_diag_get.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.fd_cnc_footprint.restype = ctypes.c_uint64
    lib.fd_cnc_init.argtypes = [ctypes.c_void_p]
    lib.fd_cnc_signal.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.fd_cnc_signal_query.restype = ctypes.c_uint64
    lib.fd_cnc_signal_query.argtypes = [ctypes.c_void_p]
    lib.fd_cnc_heartbeat.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.fd_cnc_heartbeat_query.restype = ctypes.c_uint64
    lib.fd_cnc_heartbeat_query.argtypes = [ctypes.c_void_p]
    lib.fd_cnc_diag_add.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                    ctypes.c_uint64]
    lib.fd_cnc_diag_get.restype = ctypes.c_uint64
    lib.fd_cnc_diag_get.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.fd_dcache_next_chunk.restype = ctypes.c_uint32
    lib.fd_dcache_next_chunk.argtypes = [ctypes.c_uint32, ctypes.c_uint32,
                                         ctypes.c_uint32, ctypes.c_uint32]
    lib.fd_wksp_free.restype = ctypes.c_int
    lib.fd_wksp_free.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.fd_wksp_alloc_cnt.restype = ctypes.c_uint32
    lib.fd_wksp_alloc_cnt.argtypes = [ctypes.c_void_p]
    lib.fd_wksp_stat.restype = ctypes.c_int
    lib.fd_wksp_stat.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                 ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_uint64),
                                 ctypes.POINTER(ctypes.c_uint64)]
    lib.fd_wksp_usage.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.fd_txn_parse_check.restype = ctypes.c_int
    lib.fd_txn_parse_check.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                       ctypes.c_void_p]
    lib.fd_verify_drain.restype = ctypes.c_int
    _vd_argt = [
        ctypes.c_void_p, ctypes.c_void_p,                   # mcache, dcache
        ctypes.POINTER(ctypes.c_uint64),                    # seq_io
        ctypes.c_uint32, ctypes.c_uint32,                   # txns, room
        ctypes.c_uint32, ctypes.c_uint32,                   # hard_lanes, mtu
        ctypes.c_void_p, ctypes.c_void_p,                   # msgs, lens
        ctypes.c_void_p, ctypes.c_void_p,                   # sigs, pubs
        ctypes.c_void_p, ctypes.c_uint32,                   # payloads, cap
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,  # offs/lens/sigs
        ctypes.c_void_p, ctypes.c_void_p,                   # lanes, tsorig
        ctypes.c_void_p,                                    # counters
    ]
    if hasattr(lib, "fd_verify_drain_abi2"):
        # Current ABI: the drain exports the producer's publish stamp
        # (fd_feed's ring-dwell gauge) and the FNV-1a payload hash (the
        # HA-dedup tag) per staged txn. A stale .so keeps the v1 call
        # shape.
        _vd_argt.insert(len(_vd_argt) - 1, ctypes.c_void_p)  # tspubs
        _vd_argt.insert(len(_vd_argt) - 1, ctypes.c_void_p)  # hashes
    lib.fd_verify_drain.argtypes = _vd_argt
    if hasattr(lib, "fd_frag_publish_bulk"):
        lib.fd_frag_publish_bulk.restype = ctypes.c_int
        lib.fd_frag_publish_bulk.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,               # mcache, dcache
            ctypes.c_uint32, ctypes.c_uint32,               # chunks, mtu
            ctypes.POINTER(ctypes.c_uint64),                # seq_io
            ctypes.POINTER(ctypes.c_uint32),                # chunk_io
            ctypes.c_void_p, ctypes.c_void_p,               # payloads, offs
            ctypes.c_void_p, ctypes.c_void_p,               # lens, sigs
            ctypes.c_void_p, ctypes.c_void_p,               # tsorigs, mask
            ctypes.POINTER(ctypes.c_uint32),                # txn_io
            ctypes.c_uint32, ctypes.c_uint32,               # n_txn, max_pub
            ctypes.c_uint32,                                # now32
            ctypes.c_void_p,                                # bytes_out
        ]
    if hasattr(lib, "fd_frag_publish_bulk_ctl"):
        # Current ABI: the bulk publisher grew a per-frag ctl variant
        # (fd_drain rides novel/color/block hints in the ctl word). A
        # stale .so keeps the ctl-less publisher only; callers probe
        # frag_publish_has_ctl() and fall back to the hardwired-ctl
        # call, exactly the pre-drain behavior.
        lib.fd_frag_publish_bulk_ctl.restype = ctypes.c_int
        lib.fd_frag_publish_bulk_ctl.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,               # mcache, dcache
            ctypes.c_uint32, ctypes.c_uint32,               # chunks, mtu
            ctypes.POINTER(ctypes.c_uint64),                # seq_io
            ctypes.POINTER(ctypes.c_uint32),                # chunk_io
            ctypes.c_void_p, ctypes.c_void_p,               # payloads, offs
            ctypes.c_void_p, ctypes.c_void_p,               # lens, sigs
            ctypes.c_void_p, ctypes.c_void_p,               # tsorigs, ctls
            ctypes.c_void_p,                                # mask
            ctypes.POINTER(ctypes.c_uint32),                # txn_io
            ctypes.c_uint32, ctypes.c_uint32,               # n_txn, max_pub
            ctypes.c_uint32,                                # now32
            ctypes.c_void_p,                                # bytes_out
        ]
    if hasattr(lib, "fd_frag_drain"):  # absent in a stale build
        lib.fd_frag_drain.restype = ctypes.c_int
        argt = [
            ctypes.c_void_p, ctypes.c_void_p,               # mcache, dcache
            ctypes.POINTER(ctypes.c_uint64),                # seq_io
            ctypes.c_uint32, ctypes.c_uint32,               # max_n, mtu
            ctypes.c_void_p, ctypes.c_uint32,               # payloads, cap
            ctypes.c_void_p, ctypes.c_void_p,               # offs, lens
            ctypes.c_void_p, ctypes.c_void_p,               # sigs, tsorigs
            ctypes.c_void_p,                                # seqs
            ctypes.c_void_p,                                # counters
        ]
        if hasattr(lib, "fd_frag_drain_has_ctl"):
            # Current ABI: the drain exports the meta ctl word (one
            # more output array, before counters) so a producer's
            # CTL_ERR is not laundered into a normal frag on the bulk
            # path. A stale .so keeps the pre-ctl call shape.
            argt.insert(len(argt) - 1, ctypes.c_void_p)     # ctls
        if hasattr(lib, "fd_frag_drain_has_tspub"):
            # Current ABI: the drain also exports the producer publish
            # stamp per frag — fd_xray's per-edge queue-dwell (ring
            # wait) attribution on the bulk path. Probe discipline as
            # above: a stale .so keeps the pre-tspub call shape.
            argt.insert(len(argt) - 1, ctypes.c_void_p)     # tspubs
        lib.fd_frag_drain.argtypes = argt
    return lib


_lib = None


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = load_lib()
    return _lib


# Names whose C bodies are nanosecond-scale (one atomic or a handful of
# word ops). These are called per frag on every hot path, and a CDLL
# call RELEASES the GIL around the C body: with several pipeline
# threads in one interpreter, every release is an invitation for the
# scheduler to hand the GIL elsewhere and make the caller wait a full
# switch quantum to continue — measured ~100-700 us per ring op under
# contention, ~1000x the op itself, and the dominant cost of the whole
# host pipeline. Routing them through PyDLL (C body runs WITH the GIL
# held) makes a ring op cost a ring op again. Long-running calls (the
# bulk drains, wksp create) stay on the CDLL handle so they genuinely
# overlap with other threads.
_HOT_FUNCS = (
    "fd_mcache_depth", "fd_mcache_seq_next", "fd_mcache_publish",
    "fd_mcache_poll", "fd_fseq_update", "fd_fseq_query",
    "fd_fseq_diag_add", "fd_fseq_diag_get", "fd_cnc_signal",
    "fd_cnc_signal_query", "fd_cnc_heartbeat", "fd_cnc_heartbeat_query",
    "fd_cnc_diag_add", "fd_cnc_diag_get", "fd_dcache_next_chunk",
)

_pylib = None


def pylib() -> ctypes.CDLL:
    """GIL-holding handle for the fine-grained ring ops (see
    _HOT_FUNCS). Prototypes are copied from the CDLL handle so the two
    cannot drift. FD_RINGS_PYDLL=0 hands back the GIL-releasing CDLL
    handle — the seed behavior — for A/B and bisection."""
    global _pylib
    if _pylib is None:
        L = lib()  # ensures the .so is built + prototypes configured
        from firedancer_tpu import flags

        if not flags.get_bool("FD_RINGS_PYDLL"):
            _pylib = L
            return _pylib
        pl = ctypes.PyDLL(_LIB_PATH)
        for name in _HOT_FUNCS:
            if not hasattr(L, name):
                continue
            src = getattr(L, name)
            dst = getattr(pl, name)
            dst.restype = src.restype
            dst.argtypes = src.argtypes
        _pylib = pl
    return _pylib


_native_ok: bool | None = None


def native_available() -> bool:
    """True when the native ring library loads AND carries the bulk
    drain entry (a stale .so keeps the pure-Python poll path)."""
    global _native_ok
    if _native_ok is None:
        try:
            _native_ok = hasattr(lib(), "fd_frag_drain")
        except Exception:
            _native_ok = False
    return _native_ok


def frag_drain_has_ctl() -> bool:
    """True when fd_frag_drain exports the meta ctl word (current ABI).
    A stale .so without the marker keeps the old call shape; callers
    synthesize CTL_SOM_EOM for it, exactly the pre-ctl behavior."""
    try:
        return hasattr(lib(), "fd_frag_drain_has_ctl")
    except Exception:
        return False


def frag_drain_has_tspub() -> bool:
    """True when fd_frag_drain exports the producer publish stamp per
    frag (current ABI) — the fd_xray queue-dwell input on the bulk
    drain path. A stale .so keeps the pre-tspub call shape; callers
    then skip dwell attribution for bulk-drained edges (the sampled
    telemetry degrades, nothing corrupts)."""
    try:
        return hasattr(lib(), "fd_frag_drain_has_tspub")
    except Exception:
        return False


def frag_publish_has_ctl() -> bool:
    """True when the bulk publisher carries a per-frag ctl word
    (current ABI) — the fd_drain transport for novel/color/block hints.
    A stale .so keeps the ctl-less publisher; the drain then claims
    nothing (every frag goes maybe-dup, PackTile keeps CPU greedy) and
    behavior is bit-identical to FD_DRAIN=off."""
    try:
        return hasattr(lib(), "fd_frag_publish_bulk_ctl")
    except Exception:
        return False


def verify_drain_abi2() -> bool:
    """True when fd_verify_drain exports the per-txn publish stamp and
    FNV payload hash (current ABI). A stale .so keeps the v1 call
    shape; the legacy native staging path degrades gracefully and the
    fd_feed runtime routing falls back to the legacy runner."""
    try:
        return hasattr(lib(), "fd_verify_drain_abi2")
    except Exception:
        return False


def verify_drain_ctl_err() -> bool:
    """True when fd_verify_drain drops CTL_ERR frags at the ctl word
    (counters[6]/[7], current ABI). A stale .so stages err frags like
    any other — their payloads then fail at parse, so nothing poisoned
    verifies, but the chaos ring_ctl_err audit needs the typed drop
    counter and refuses to run without it."""
    try:
        return hasattr(lib(), "fd_verify_drain_ctl_err")
    except Exception:
        return False


def feed_abi_ok() -> bool:
    """The fd_feed runtime's native surface: drain ABI v2 (tspub + HA
    hash outputs) plus the bulk completion publisher. Absent on a stale
    .so — run_pipeline then keeps the legacy step loop."""
    try:
        return verify_drain_abi2() and hasattr(lib(), "fd_frag_publish_bulk")
    except Exception:
        return False


def cnc_diag_cap() -> int:
    """Diag slots carried by the native cnc object: 16 on the current
    ABI (fd_cnc_diag_cap marker), 8 on a stale .so. Writers of the
    fd_feed feeder gauges (slots 8..) MUST check this — on an 8-slot
    build those indices land out of bounds in the workspace, which is
    shared-memory corruption, not a miscounted gauge."""
    try:
        L = lib()
        if hasattr(L, "fd_cnc_diag_cap"):
            L.fd_cnc_diag_cap.restype = ctypes.c_uint64
            return int(L.fd_cnc_diag_cap())
    except Exception:
        pass
    return 8


class Alloc:
    """Concurrent sizeclass allocator inside a wksp region (fd_alloc
    analog; native/alloc.cc). malloc/free return/take workspace offsets
    so any process sharing the file can pass allocations around."""

    def __init__(self, wksp: "Workspace", name: str,
                 heap_sz: int | None = None, create: bool = False):
        L = lib()
        L.fd_alloc_footprint.restype = ctypes.c_uint64
        L.fd_alloc_footprint.argtypes = [ctypes.c_uint64]
        L.fd_alloc_init.restype = ctypes.c_int
        L.fd_alloc_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        L.fd_alloc_malloc.restype = ctypes.c_uint64
        L.fd_alloc_malloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        L.fd_alloc_free.restype = ctypes.c_int
        L.fd_alloc_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        L.fd_alloc_in_use.restype = ctypes.c_uint64
        L.fd_alloc_in_use.argtypes = [ctypes.c_void_p]
        L.fd_alloc_max_alloc.restype = ctypes.c_uint64
        if create:
            # Typed raises, not asserts, throughout this module: python
            # -O strips asserts, and these values define the shared-
            # memory layout every OTHER process maps — a bad one is IPC
            # corruption, not a local bug.
            if heap_sz is None:
                raise ValueError("Alloc(create=True) requires heap_sz")
            fp = L.fd_alloc_footprint(heap_sz)
            off = wksp.alloc(name, fp)
            self._mem = wksp.laddr(off)
            if L.fd_alloc_init(self._mem, heap_sz) != 0:
                raise MemoryError("fd_alloc_init failed")
        else:
            off, _ = wksp.query(name)
            self._mem = wksp.laddr(off)
        self._wksp = wksp
        self._region_off = off

    def malloc(self, sz: int) -> int:
        """-> region-relative offset (0 on exhaustion/oversize)."""
        return lib().fd_alloc_malloc(self._mem, sz)

    def free(self, gaddr: int) -> None:
        if lib().fd_alloc_free(self._mem, gaddr) != 0:
            raise ValueError(f"bad free: {gaddr}")

    def in_use(self) -> int:
        return lib().fd_alloc_in_use(self._mem)

    def max_alloc(self) -> int:
        return lib().fd_alloc_max_alloc()

    def view(self, gaddr: int, sz: int):
        """Writable ctypes view of an allocation (slice-assignable)."""
        return (ctypes.c_ubyte * sz).from_address(self._mem + gaddr)


@dataclass
class Frag:
    seq: int
    sig: int
    chunk: int
    sz: int
    ctl: int
    tsorig: int
    tspub: int


class Workspace:
    """A named-allocation shared-memory file (wksp + pod-lite)."""

    def __init__(self, handle: int):
        self._h = handle

    @classmethod
    def create(cls, path: str, size: int) -> "Workspace":
        h = lib().fd_wksp_create(path.encode(), size)
        if not h:
            raise OSError(f"wksp create failed: {path}")
        return cls(h)

    @classmethod
    def join(cls, path: str) -> "Workspace":
        h = lib().fd_wksp_join(path.encode())
        if not h:
            raise OSError(f"wksp join failed: {path}")
        return cls(h)

    def leave(self):
        lib().fd_wksp_leave(self._h)
        self._h = None

    def alloc(self, name: str, sz: int, align: int = 64) -> int:
        off = lib().fd_wksp_alloc(self._h, name.encode(), sz, align)
        if not off:
            raise MemoryError(f"wksp alloc failed: {name}")
        return off

    def free(self, name: str) -> None:
        """Release a named allocation for first-fit reuse (fd_wksp_free).

        Caller discipline: nothing may still hold a pointer/view into
        the region (same contract as the reference)."""
        if lib().fd_wksp_free(self._h, name.encode()) != 0:
            raise KeyError(name)

    def query(self, name: str) -> tuple[int, int]:
        sz = ctypes.c_uint64()
        off = lib().fd_wksp_query(self._h, name.encode(), ctypes.byref(sz))
        if not off:
            raise KeyError(name)
        return off, sz.value

    def alloc_list(self):
        """[(name, off, sz)] of every named alloc (fd_wksp_ctl query)."""
        import ctypes as ct

        n = lib().fd_wksp_alloc_cnt(self._h)
        out = []
        name = ct.create_string_buffer(64)
        off = ct.c_uint64()
        sz = ct.c_uint64()
        for i in range(n):
            if lib().fd_wksp_stat(self._h, i, name, ct.byref(off),
                                  ct.byref(sz)) == 0:
                out.append((name.value.decode(), off.value, sz.value))
        return out

    def usage(self):
        """{total_sz, used, alloc_cnt} summary."""
        import ctypes as ct

        buf = (ct.c_uint64 * 3)()
        lib().fd_wksp_usage(self._h, buf)
        return {"total_sz": buf[0], "used": buf[1], "alloc_cnt": buf[2]}

    def laddr(self, off: int) -> int:
        return lib().fd_wksp_laddr(self._h, off)

    def view(self, name: str) -> memoryview:
        off, sz = self.query(name)
        addr = self.laddr(off)
        return (ctypes.c_char * sz).from_address(addr)


class MCache:
    def __init__(self, wksp: Workspace, name: str, depth: int | None = None,
                 create: bool = False):
        if create:
            if depth is None or depth <= 0 or depth & (depth - 1) != 0:
                # The line index is seq & (depth-1): a non-power-of-two
                # depth silently aliases mcache lines for every joiner.
                raise ValueError(
                    f"mcache depth must be a positive power of two, "
                    f"got {depth!r}"
                )
            fp = lib().fd_mcache_footprint(depth)
            off = wksp.alloc(name, fp)
            self._mem = wksp.laddr(off)
            lib().fd_mcache_init(self._mem, depth)
        else:
            off, _ = wksp.query(name)
            self._mem = wksp.laddr(off)
        self.depth = pylib().fd_mcache_depth(self._mem)

    def seq_next(self) -> int:
        return pylib().fd_mcache_seq_next(self._mem)

    def publish(self, seq: int, sig: int, chunk: int, sz: int, ctl: int,
                tsorig: int = 0, tspub: int = 0):
        pylib().fd_mcache_publish(self._mem, seq, sig, chunk, sz, ctl,
                                tsorig, tspub)

    def poll(self, seq: int) -> tuple[int, Frag | None]:
        out = (ctypes.c_uint64 * 4)()
        r = pylib().fd_mcache_poll(self._mem, seq, ctypes.byref(out))
        if r != POLL_FRAG:
            return r, None
        sig, b, ts, s = out
        return r, Frag(seq=s, sig=sig, chunk=(b >> 32) & 0xFFFFFFFF,
                       sz=(b >> 16) & 0xFFFF, ctl=b & 0xFFFF,
                       tsorig=(ts >> 32) & 0xFFFFFFFF, tspub=ts & 0xFFFFFFFF)


class DCache:
    """Payload region; numpy/memoryview access by chunk index."""

    def __init__(self, wksp: Workspace, name: str, data_sz: int | None = None,
                 create: bool = False):
        if create:
            if data_sz is None or data_sz <= 0 or data_sz % 64 != 0:
                # Chunk indices address 64-byte units; an unaligned size
                # breaks the chunk walk for every process on the link.
                raise ValueError(
                    f"dcache data_sz must be a positive multiple of 64, "
                    f"got {data_sz!r}"
                )
            off = wksp.alloc(name, data_sz)
        else:
            off, data_sz = wksp.query(name)
        self._buf = (ctypes.c_char * data_sz).from_address(wksp.laddr(off))
        self.data_sz = data_sz
        self.chunk_cnt = data_sz // 64

    def write(self, chunk: int, data: bytes):
        o = chunk * 64
        self._buf[o : o + len(data)] = data

    def read(self, chunk: int, sz: int) -> bytes:
        o = chunk * 64
        return bytes(self._buf[o : o + sz])

    def next_chunk(self, chunk: int, sz: int, mtu: int) -> int:
        return pylib().fd_dcache_next_chunk(chunk, sz, (mtu + 63) // 64,
                                          self.chunk_cnt)


class FSeq:
    def __init__(self, wksp: Workspace, name: str, create: bool = False):
        if create:
            off = wksp.alloc(name, lib().fd_fseq_footprint())
            self._mem = wksp.laddr(off)
            lib().fd_fseq_init(self._mem)
        else:
            off, _ = wksp.query(name)
            self._mem = wksp.laddr(off)

    def update(self, seq: int):
        pylib().fd_fseq_update(self._mem, seq)

    def query(self) -> int:
        return pylib().fd_fseq_query(self._mem)

    def diag_add(self, idx: int, delta: int):
        pylib().fd_fseq_diag_add(self._mem, idx, delta)

    def diag(self, idx: int) -> int:
        return pylib().fd_fseq_diag_get(self._mem, idx)


class Cnc:
    def __init__(self, wksp: Workspace, name: str, create: bool = False):
        if create:
            off = wksp.alloc(name, lib().fd_cnc_footprint())
            self._mem = wksp.laddr(off)
            lib().fd_cnc_init(self._mem)
        else:
            off, _ = wksp.query(name)
            self._mem = wksp.laddr(off)

    def signal(self, sig: int):
        pylib().fd_cnc_signal(self._mem, sig)

    def signal_query(self) -> int:
        return pylib().fd_cnc_signal_query(self._mem)

    def heartbeat(self, now: int):
        pylib().fd_cnc_heartbeat(self._mem, now)

    def heartbeat_query(self) -> int:
        return pylib().fd_cnc_heartbeat_query(self._mem)

    def diag_add(self, idx: int, delta: int):
        pylib().fd_cnc_diag_add(self._mem, idx, delta)

    def diag(self, idx: int) -> int:
        return pylib().fd_cnc_diag_get(self._mem, idx)
