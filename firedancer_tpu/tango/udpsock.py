"""udpsock — plain UDP socket aio backend.

Role parity with /root/reference/src/tango/udpsock/fd_udpsock.{h,c}: the
development fallback for the XDP kernel-bypass path. A nonblocking UDP
socket drained in bursts into an rx callback, with an Aio-shaped tx side.
(The reference's AF_XDP path, tango/xdp/fd_xsk.*, has no TPU-host
equivalent here: kernel bypass NICs are out of scope for the dev loop; the
architecture keeps the same aio seam so one can be slotted in.)
"""

from __future__ import annotations

import socket
from typing import Callable, List, Optional, Tuple

from firedancer_tpu.tango.aio import Aio, Packet

MTU = 2048
RX_BURST = 64


class UdpSock:
    """Nonblocking UDP socket with aio-style burst service."""

    def __init__(self, bind_addr: Tuple[str, int] = ("127.0.0.1", 0)):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind(bind_addr)
        self.local_addr = self._sock.getsockname()
        self.metrics = {"rx_pkts": 0, "tx_pkts": 0, "tx_fails": 0}

    def aio_tx(self) -> Aio:
        def send(batch: List[Packet]) -> int:
            n = 0
            for addr, payload in batch:
                try:
                    self._sock.sendto(payload, addr)
                    self.metrics["tx_pkts"] += 1
                    n += 1
                except (BlockingIOError, OSError):
                    self.metrics["tx_fails"] += 1
            return n

        return Aio(send)

    def service_rx(
        self, on_packet: Callable[[Tuple[str, int], bytes], None]
    ) -> int:
        """Drain up to RX_BURST datagrams into on_packet. -> count."""
        n = 0
        for _ in range(RX_BURST):
            try:
                data, addr = self._sock.recvfrom(MTU)
            except BlockingIOError:
                break
            except OSError:
                break
            self.metrics["rx_pkts"] += 1
            on_packet(addr, data)
            n += 1
        return n

    def close(self) -> None:
        self._sock.close()
