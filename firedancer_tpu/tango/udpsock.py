"""udpsock — plain UDP socket aio backend.

Role parity with /root/reference/src/tango/udpsock/fd_udpsock.{h,c}: the
development fallback for the XDP kernel-bypass path. A nonblocking UDP
socket drained in bursts into an rx callback, with an Aio-shaped tx side.
(The reference's AF_XDP path, tango/xdp/fd_xsk.*, has no TPU-host
equivalent here: kernel bypass NICs are out of scope for the dev loop; the
architecture keeps the same aio seam so one can be slotted in.)
"""

from __future__ import annotations

import socket
from typing import Callable, List, Optional, Tuple

from firedancer_tpu.tango.aio import Aio, Packet

MTU = 2048
RX_BURST = 64


class UdpSock:
    """Nonblocking UDP socket with aio-style burst service."""

    def __init__(self, bind_addr: Tuple[str, int] = ("127.0.0.1", 0)):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self._sock.bind(bind_addr)
        self.local_addr = self._sock.getsockname()
        self.metrics = {"rx_pkts": 0, "tx_pkts": 0, "tx_fails": 0}

    def aio_tx(self) -> Aio:
        def send(batch: List[Packet]) -> int:
            n = 0
            for addr, payload in batch:
                try:
                    self._sock.sendto(payload, addr)
                    self.metrics["tx_pkts"] += 1
                    n += 1
                except (BlockingIOError, OSError):
                    self.metrics["tx_fails"] += 1
            return n

        return Aio(send)

    def service_rx(
        self, on_packet: Callable[[Tuple[str, int], bytes], None]
    ) -> int:
        """Drain up to RX_BURST datagrams into on_packet. -> count."""
        n = 0
        for _ in range(RX_BURST):
            try:
                data, addr = self._sock.recvfrom(MTU)
            except BlockingIOError:
                break
            except OSError:
                break
            self.metrics["rx_pkts"] += 1
            on_packet(addr, data)
            n += 1
        return n

    def close(self) -> None:
        self._sock.close()


class UdpBatchSock:
    """Batched UDP socket: recvmmsg/sendmmsg via the native helper.

    The environment-appropriate analog of the reference's AF_XDP stack
    (tango/xdp/fd_xsk.h:8-60): where fd_xsk amortizes kernel crossings
    with UMEM descriptor rings, this backend amortizes them with
    one-syscall batches (native/udp_batch.cc). Same aio seam as UdpSock,
    so QuicTile/clients swap backends without change; falls back is the
    caller's choice (UdpSock) if the native library is unavailable.
    """

    BATCH = 256

    def __init__(self, bind_addr: Tuple[str, int] = ("127.0.0.1", 0),
                 mtu: int = MTU, rcvbuf: int = 1 << 22):
        import ctypes
        import os

        import numpy as np

        from firedancer_tpu.tango.rings import ensure_native_built

        lib_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "build", "libfdudp.so")
        ensure_native_built(lib_path)
        self._lib = ctypes.CDLL(lib_path)
        self._lib.fd_udp_recv_batch.restype = ctypes.c_int
        self._lib.fd_udp_recv_batch.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.c_void_p]
        self._lib.fd_udp_send_batch.restype = ctypes.c_int
        self._lib.fd_udp_send_batch.argtypes = [
            ctypes.c_int, ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_uint32]

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
        except OSError:
            pass
        self._sock.bind(bind_addr)
        self.local_addr = self._sock.getsockname()
        self.mtu = mtu
        self._np = np
        self._rx_buf = np.zeros((self.BATCH, mtu), np.uint8)
        self._rx_lens = np.zeros(self.BATCH, np.uint32)
        self._rx_addrs = np.zeros(2 * self.BATCH, np.uint32)
        self._tx_buf = np.zeros((self.BATCH, mtu), np.uint8)
        self._tx_lens = np.zeros(self.BATCH, np.uint32)
        self._tx_addrs = np.zeros(2 * self.BATCH, np.uint32)
        self.metrics = {"rx_pkts": 0, "tx_pkts": 0, "tx_fails": 0,
                        "rx_batches": 0}

    def aio_tx(self) -> Aio:
        import socket as _socket
        import struct as _struct

        def send(batch: List[Packet]) -> int:
            sent_total = 0
            for start in range(0, len(batch), self.BATCH):
                chunk = batch[start : start + self.BATCH]
                n = 0
                for addr, payload in chunk:
                    if len(payload) > self.mtu:
                        self.metrics["tx_fails"] += 1
                        continue
                    ip, port = addr
                    try:
                        packed = _struct.unpack(
                            "<I", _socket.inet_aton(ip))[0]
                    except OSError:
                        # An unroutable/synthetic peer address (e.g. a
                        # fault-injection placeholder) must cost one
                        # tx_fail, never kill the sending tile.
                        self.metrics["tx_fails"] += 1
                        continue
                    self._tx_buf[n, : len(payload)] = bytearray(payload)
                    self._tx_lens[n] = len(payload)
                    self._tx_addrs[2 * n] = packed
                    self._tx_addrs[2 * n + 1] = port
                    n += 1
                if not n:
                    continue
                rc = self._lib.fd_udp_send_batch(
                    self._sock.fileno(),
                    self._tx_buf.ctypes.data, self.mtu,
                    self._tx_lens.ctypes.data, self._tx_addrs.ctypes.data,
                    n)
                if rc < 0:
                    self.metrics["tx_fails"] += n
                    continue
                self.metrics["tx_pkts"] += rc
                self.metrics["tx_fails"] += n - rc
                sent_total += rc
            return sent_total

        return Aio(send)

    def service_rx(
        self, on_packet: Callable[[Tuple[str, int], bytes], None]
    ) -> int:
        """Drain one recvmmsg batch into on_packet. -> count."""
        import socket as _socket
        import struct as _struct

        rc = self._lib.fd_udp_recv_batch(
            self._sock.fileno(), self._rx_buf.ctypes.data, self.mtu,
            self.BATCH, self._rx_lens.ctypes.data,
            self._rx_addrs.ctypes.data)
        if rc <= 0:
            return 0
        self.metrics["rx_pkts"] += rc
        self.metrics["rx_batches"] += 1
        for i in range(rc):
            ln = int(self._rx_lens[i])
            ip = _socket.inet_ntoa(
                _struct.pack("<I", int(self._rx_addrs[2 * i])))
            port = int(self._rx_addrs[2 * i + 1])
            on_packet((ip, port), self._rx_buf[i, :ln].tobytes())
        return rc

    def close(self) -> None:
        self._sock.close()
