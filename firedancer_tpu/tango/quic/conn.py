"""QUIC connection state machine: packet numbers, ACKs, CRYPTO + streams.

Role parity with /root/reference/src/tango/quic/fd_quic_conn.{h,c},
fd_quic_stream.*, and the ack/loss tracking of fd_quic_pkt_meta.*: three
packet-number spaces (initial/handshake/app) each with their own keys, ACK
range tracking, CRYPTO-stream reassembly feeding the TLS engine, stream
reassembly delivering completed unidirectional streams (one Solana txn per
stream, the TPU convention), simple PTO-style retransmission, and datagram
assembly with long-header coalescing + client-Initial padding.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from firedancer_tpu.tango.quic import wire
from firedancer_tpu.tango.quic.crypto_suites import (
    AEAD_OVERHEAD,
    PacketKeys,
    QuicCryptoError,
    initial_secrets,
    protect_packet,
    unprotect_header,
)
from firedancer_tpu.tango.quic.tls import (
    LEVEL_APP,
    LEVEL_HANDSHAKE,
    LEVEL_INITIAL,
    TlsConfig,
    TlsEndpoint,
    TlsError,
)

MAX_DATAGRAM = 1200  # conservative pre-PMTUD budget (RFC 9000 §14.1)
CID_LEN = 8

# transport parameter ids (RFC 9000 §18.2)
TP_ORIGINAL_DCID = 0x00
TP_MAX_IDLE_TIMEOUT = 0x01
TP_MAX_UDP_PAYLOAD = 0x03
TP_INITIAL_MAX_DATA = 0x04
TP_INITIAL_MAX_STREAM_DATA_BIDI_LOCAL = 0x05
TP_INITIAL_MAX_STREAM_DATA_BIDI_REMOTE = 0x06
TP_INITIAL_MAX_STREAM_DATA_UNI = 0x07
TP_INITIAL_MAX_STREAMS_BIDI = 0x08
TP_INITIAL_MAX_STREAMS_UNI = 0x09
TP_INITIAL_SCID = 0x0F
TP_STATELESS_RESET_TOKEN = 0x02
TP_RETRY_SCID = 0x10

# RFC 9000 §8.1: a server may send at most 3x the bytes received from an
# address it has not yet validated (anti-amplification limit).
AMP_LIMIT = 3

_LEVEL_TO_PKT = {
    LEVEL_INITIAL: wire.PKT_INITIAL,
    LEVEL_HANDSHAKE: wire.PKT_HANDSHAKE,
}


def encode_transport_params(params: Dict[int, object]) -> bytes:
    out = bytearray()
    for tid, val in params.items():
        out += wire.varint_encode(tid)
        if isinstance(val, bytes):
            out += wire.varint_encode(len(val))
            out += val
        else:
            body = wire.varint_encode(int(val))
            out += wire.varint_encode(len(body))
            out += body
    return bytes(out)


def parse_transport_params(buf: bytes) -> Dict[int, bytes]:
    out: Dict[int, bytes] = {}
    off = 0
    while off < len(buf):
        tid, off = wire.varint_decode(buf, off)
        ln, off = wire.varint_decode(buf, off)
        out[tid] = bytes(buf[off : off + ln])
        off += ln
    return out


def tp_varint(params: Dict[int, bytes], tid: int, default: int = 0) -> int:
    v = params.get(tid)
    if v is None:
        return default
    return wire.varint_decode(v, 0)[0]


class RttEstimator:
    """RFC 9002 RTT estimation + PTO computation (§5.3, §6.2).

    Replaces the fixed 0.25 s probe timeout: smoothed_rtt/rttvar are EWMAs
    of ack-derived samples (ack_delay-adjusted once min_rtt is known) and
    the PTO backs off exponentially per probe event. Loss detection (all
    wired in the ACK handler) uses the packet threshold
    (kPacketThreshold=3), the time threshold (kTimeThreshold=9/8 of
    max(srtt, latest_rtt), RFC 9002 §6.1.2), and the PTO.
    Reference behavior: src/tango/quic/fd_quic_pkt_meta.c + RFC defaults.
    """

    K_GRANULARITY = 0.001          # kGranularity, seconds
    MAX_ACK_DELAY = 0.025          # default peer max_ack_delay
    PTO_BACKOFF_CAP = 6            # 64x max backoff

    def __init__(self, initial_rtt: float = 0.125):
        self.initial_rtt = initial_rtt
        self.latest_rtt = 0.0
        self.smoothed_rtt: Optional[float] = None
        self.rttvar = 0.0
        self.min_rtt = 0.0
        self.pto_count = 0

    def on_sample(self, rtt: float, ack_delay: float = 0.0) -> None:
        if rtt <= 0:
            return
        self.latest_rtt = rtt
        if self.smoothed_rtt is None:
            self.smoothed_rtt = rtt
            self.rttvar = rtt / 2
            self.min_rtt = rtt
        else:
            self.min_rtt = min(self.min_rtt, rtt)
            adj = rtt
            if rtt - ack_delay >= self.min_rtt:
                adj = rtt - ack_delay
            self.rttvar = 0.75 * self.rttvar + 0.25 * abs(self.smoothed_rtt - adj)
            self.smoothed_rtt = 0.875 * self.smoothed_rtt + 0.125 * adj
        self.pto_count = 0

    def pto(self) -> float:
        if self.smoothed_rtt is None:
            base = 2 * self.initial_rtt
        else:
            base = (self.smoothed_rtt
                    + max(4 * self.rttvar, self.K_GRANULARITY)
                    + self.MAX_ACK_DELAY)
        return base * (1 << min(self.pto_count, self.PTO_BACKOFF_CAP))

@dataclass
class _SentPacket:
    time: float
    ack_eliciting: bool
    crypto: List[Tuple[int, bytes]] = field(default_factory=list)
    streams: List[Tuple[int, int, bytes, bool]] = field(default_factory=list)
    handshake_done: bool = False
    pmtu_probe: int = 0   # DPLPMTUD probe datagram size (0 = not a probe)


class _PnSpace:
    """One packet-number space: keys, ACK state, CRYPTO buffers, loss."""

    def __init__(self) -> None:
        self.keys_tx: Optional[PacketKeys] = None
        self.keys_rx: Optional[PacketKeys] = None
        self.next_pn = 0
        self.largest_rx = -1
        self.largest_acked = -1
        # received pn ranges as a sorted (desc) list of [lo, hi]
        self.rx_ranges: List[List[int]] = []
        self.ack_needed = False
        # crypto stream tx: queue of (offset, bytes) not yet sent
        self.crypto_tx: List[Tuple[int, bytes]] = []
        self.crypto_tx_off = 0
        # crypto stream rx reassembly
        self.crypto_rx: Dict[int, bytes] = {}
        self.crypto_rx_off = 0
        self.sent: Dict[int, _SentPacket] = {}
        self.dropped = False

    def record_rx(self, pn: int) -> bool:
        """Track a received pn. -> False if duplicate."""
        for r in self.rx_ranges:
            if r[0] <= pn <= r[1]:
                return False
        self.largest_rx = max(self.largest_rx, pn)
        self.rx_ranges.append([pn, pn])
        self.rx_ranges.sort(key=lambda r: -r[1])
        # merge adjacent
        merged: List[List[int]] = []
        for r in self.rx_ranges:
            if merged and r[1] >= merged[-1][0] - 1:
                merged[-1][0] = min(merged[-1][0], r[0])
            else:
                merged.append(r)
        self.rx_ranges = merged[:32]  # bound state like the reference
        return True

    def ack_frame(self) -> Optional[bytes]:
        if not self.rx_ranges:
            return None
        first = self.rx_ranges[0]
        ranges: List[Tuple[int, int]] = []
        prev_lo = first[0]
        for r in self.rx_ranges[1:]:
            gap = prev_lo - r[1] - 2
            ranges.append((gap, r[1] - r[0]))
            prev_lo = r[0]
        return wire.encode_ack(first[1], 0, first[1] - first[0], ranges)

    def queue_crypto(self, data: bytes) -> None:
        self.crypto_tx.append((self.crypto_tx_off, data))
        self.crypto_tx_off += len(data)

    def on_ack(self, f: wire.Frame):
        """Remove acked packets from the sent map; -> [(pn, _SentPacket)]."""
        acked = []
        hi = f.fields["largest"]
        lo = hi - f.fields["first_range"]
        spans = [(lo, hi)]
        for gap, rng in f.ack_ranges:
            hi = lo - gap - 2
            lo = hi - rng
            spans.append((lo, hi))
        for lo, hi in spans:
            for pn in list(self.sent.keys()):
                if lo <= pn <= hi:
                    acked.append((pn, self.sent.pop(pn)))
            self.largest_acked = max(self.largest_acked, hi)
        return acked

    def drop_keys(self) -> None:
        self.keys_tx = None
        self.keys_rx = None
        self.sent.clear()
        self.crypto_tx.clear()
        self.dropped = True


class _RecvStream:
    __slots__ = ("chunks", "fin_size", "delivered")

    def __init__(self) -> None:
        self.chunks: Dict[int, bytes] = {}
        self.fin_size: Optional[int] = None
        self.delivered = False

    def add(self, off: int, data: bytes, fin: bool) -> None:
        if data:
            self.chunks[off] = data
        if fin:
            self.fin_size = off + len(data)

    def complete(self) -> Optional[bytes]:
        if self.fin_size is None or self.delivered:
            return None
        out = bytearray()
        off = 0
        while off < self.fin_size:
            chunk = self.chunks.get(off)
            if chunk is None:
                # tolerate overlapping retransmits: scan for a covering chunk
                found = None
                for o, c in self.chunks.items():
                    if o <= off < o + len(c):
                        found = c[off - o :]
                        break
                if found is None:
                    return None
                chunk = found
            out += chunk
            off += len(chunk)
        self.delivered = True
        return bytes(out[: self.fin_size])


class QuicConn:
    """A single QUIC connection (client or server role)."""

    def __init__(
        self,
        is_server: bool,
        identity_seed: bytes,
        peer_addr,
        alpns: Tuple[bytes, ...] = (b"solana-tpu",),
        orig_dcid: Optional[bytes] = None,
        idle_timeout: float = 10.0,
        on_stream: Optional[Callable[[int, bytes], None]] = None,
        now: float = 0.0,
        initial_max_streams_uni: int = 2048,
        initial_max_data: int = 1 << 24,
        scid: Optional[bytes] = None,
        reset_token: Optional[bytes] = None,
        retry_odcid: Optional[bytes] = None,
        addr_validated: Optional[bool] = None,
    ):
        self.is_server = is_server
        self.peer_addr = peer_addr
        self.scid = scid if scid is not None else os.urandom(CID_LEN)
        self.on_stream = on_stream
        self.established = False
        self.closed = False
        self.close_reason: Optional[str] = None
        self.idle_timeout = idle_timeout
        self._last_activity = now
        self._hs_done_pending = False
        self._hs_done_sent = False
        self._max_streams_uni = initial_max_streams_uni
        self._streams_consumed = 0
        self._max_data = initial_max_data
        self._rx_data_total = 0

        self.rtt = RttEstimator()
        # Key update state (RFC 9001 §6): per-direction phase bits on the
        # 1-RTT keys; old rx keys are retained one generation for packets
        # reordered across the update.
        self.tx_key_phase = 0
        self.rx_key_phase = 0
        self._prev_keys_rx: Optional[PacketKeys] = None
        self._prev_keys_deadline = 0.0   # drop old read keys after ~3 PTO
        self._next_keys_rx: Optional[PacketKeys] = None  # precomputed (§6.3)
        self._rx_phase_start_pn = 0      # first pn of the current rx phase
        # §6.2 MUST NOT initiate again until a packet sent under the
        # current-phase keys has been ACKNOWLEDGED (tx==rx is not enough:
        # a responder flips both at once and could re-roll within the
        # same round trip, desynchronizing generations).
        self._ku_pending = False
        self._ku_min_ack_pn = 0
        self.stat_key_updates = 0
        # Path migration (RFC 9000 §9): a new source address is adopted
        # only after a PATH_CHALLENGE round trip to it succeeds. One
        # probe at a time, and an in-flight probe is never clobbered by
        # a new candidate (§9.3; see on_peer_address_change).
        self._probe_addr = None
        self._probe_data: Optional[bytes] = None
        self._probe_expire = 0.0
        self._probe_next_tx = 0.0
        self._path_frames: List[bytes] = []   # queued PATH_RESPONSEs
        self._last_rx_addr = None
        self._highest_rx_pn = -1   # §9.3: migrate on newest packet only
        self.stat_migrations = 0
        # Anti-amplification (RFC 9000 §8.1; reference fd_quic.h:110 names
        # this mitigation, enforcement fd_quic.c:1198): a server must not
        # send more than AMP_LIMIT x the bytes received from an address
        # until that address is validated — by a token-validated Initial
        # (retry_odcid path) or by the client proving receipt of the
        # server's Initial (a packet decrypted with handshake keys).
        # Clients are born validated (they chose to talk to the server).
        self.addr_validated = (
            addr_validated if addr_validated is not None else not is_server
        )
        self._amp_rx_bytes = 0
        self._amp_tx_bytes = 0
        self.stat_amp_blocked = 0
        # Retry state (RFC 9000 §8.1.2 / 17.2.5): the client echoes the
        # server's token in every subsequent Initial; one Retry max.
        self._retry_token = b""
        self._retry_used = False
        self.stat_retries = 0
        # Stateless reset (RFC 9000 §10.3): the peer's token arrives in
        # its transport parameters; an undecryptable short packet whose
        # tail matches it kills the connection.
        self.peer_reset_token: Optional[bytes] = None
        self.stat_stateless_reset = 0
        self._peer_cid_adopted = False  # client: server scid adopted (§7.2)
        # DPLPMTUD (RFC 8899 / RFC 9000 §14.3): datagram budget starts at
        # the conservative 1200 and is raised only after a padded probe
        # of the candidate size is ACKNOWLEDGED; a lost probe ends the
        # search at the last validated size. One probe in flight at most.
        self.max_datagram = MAX_DATAGRAM
        self._pmtu_rungs = [1350, 1452]
        self._pmtu_inflight = 0     # probe size awaiting ack (0 = none)
        self._pmtu_done = False
        self.stat_pmtu_probes = 0
        self.spaces = [_PnSpace(), _PnSpace(), _PnSpace()]
        # Creation stamp: the server-side handshake-deadline reaper
        # (Quic.service, hs_timeout) measures half-open lifetime from
        # here — a junk Initial buys bounded state, not a 10 s idle slot.
        self.created = now
        if is_server:
            if orig_dcid is None:
                raise ValueError(
                    "server QuicConn requires orig_dcid (the client "
                    "Initial's destination cid derives the Initial keys)"
                )
            self.dcid = b""  # learned from the client's first Initial (scid)
            self.orig_dcid = orig_dcid
            ckeys, skeys = initial_secrets(orig_dcid)
            self.spaces[LEVEL_INITIAL].keys_rx = ckeys
            self.spaces[LEVEL_INITIAL].keys_tx = skeys
        else:
            self.dcid = os.urandom(CID_LEN)
            self.orig_dcid = self.dcid
            ckeys, skeys = initial_secrets(self.dcid)
            self.spaces[LEVEL_INITIAL].keys_tx = ckeys
            self.spaces[LEVEL_INITIAL].keys_rx = skeys

        tp: Dict[int, object] = {
            TP_MAX_IDLE_TIMEOUT: int(idle_timeout * 1000),
            TP_MAX_UDP_PAYLOAD: 1452,
            TP_INITIAL_MAX_DATA: initial_max_data,
            TP_INITIAL_MAX_STREAM_DATA_BIDI_LOCAL: 1 << 20,
            TP_INITIAL_MAX_STREAM_DATA_BIDI_REMOTE: 1 << 20,
            TP_INITIAL_MAX_STREAM_DATA_UNI: 1 << 20,
            TP_INITIAL_MAX_STREAMS_BIDI: 128,
            TP_INITIAL_MAX_STREAMS_UNI: initial_max_streams_uni,
            TP_INITIAL_SCID: self.scid,
        }
        if is_server:
            if retry_odcid is not None:
                # Post-retry handshake (RFC 9000 §18.2): original dcid is
                # the one from the FIRST Initial (recovered from the
                # token); retry_source_connection_id is the cid the Retry
                # chose, which the client now addresses us by.
                tp[TP_ORIGINAL_DCID] = retry_odcid
                tp[TP_RETRY_SCID] = orig_dcid
            else:
                tp[TP_ORIGINAL_DCID] = orig_dcid
            if reset_token is not None:
                tp[TP_STATELESS_RESET_TOKEN] = reset_token
        self.tls = TlsEndpoint(
            TlsConfig(
                is_server=is_server,
                identity_seed=identity_seed,
                alpns=alpns,
                transport_params=encode_transport_params(tp),
            )
        )
        self.peer_tp: Dict[int, bytes] = {}
        # streams
        self._recv_streams: Dict[int, _RecvStream] = {}
        self._next_uni_stream = 2 if not is_server else 3
        self._send_queue: List[Tuple[int, int, bytes, bool]] = []

        if not is_server:
            self.tls.start()
            self._drain_tls()

    # ---------------------------------------------------------------- rx ---

    def recv_datagram(self, data: bytes, now: float, from_addr=None) -> None:
        self._last_activity = now
        if from_addr is not None:
            self._last_rx_addr = from_addr
        if not self.addr_validated and (
            from_addr is None or from_addr == self.peer_addr
        ):
            # Bytes from the handshake address buy 3x send budget (§8.1).
            self._amp_rx_bytes += len(data)
        off = 0
        while off < len(data) and not self.closed:
            first = data[off]
            if wire.is_long_header(first):
                try:
                    hdr = wire.parse_long_header(data, off)
                except wire.QuicWireError:
                    return
                if hdr.pkt_type == wire.PKT_RETRY:
                    self._on_retry(data[off:], hdr, now)
                    return  # a Retry is never coalesced (§12.2)
                pkt_end = hdr.hdr_end + hdr.length
                if hdr.version != wire.QUIC_VERSION_1 or pkt_end > len(data):
                    return
                if hdr.pkt_type == wire.PKT_INITIAL:
                    level = LEVEL_INITIAL
                elif hdr.pkt_type == wire.PKT_HANDSHAKE:
                    level = LEVEL_HANDSHAKE
                else:
                    off = pkt_end  # 0-RTT unsupported: skip
                    continue
                if not self.dcid:
                    self.dcid = hdr.scid  # server: learn the client's cid
                self._decrypt_and_process(
                    data, off, hdr.hdr_end, pkt_end, level, now,
                    peer_scid=hdr.scid,
                )
                off = pkt_end
            else:
                level = LEVEL_APP
                try:
                    hdr_s = wire.parse_short_header(data, CID_LEN, off)
                except wire.QuicWireError:
                    return
                self._decrypt_and_process(
                    data, off, hdr_s.hdr_end, len(data), level, now
                )
                off = len(data)

    def _decrypt_and_process(
        self, data: bytes, pkt_start: int, pn_off: int, pkt_end: int,
        level: int, now: float, peer_scid: Optional[bytes] = None,
    ) -> None:
        space = self.spaces[level]
        if space.keys_rx is None:
            return  # keys not yet available (or dropped); packet is lost
        pkt = bytearray(data[pkt_start:pkt_end])
        rel_pn_off = pn_off - pkt_start
        try:
            pn_len, tpn = unprotect_header(space.keys_rx, pkt, rel_pn_off)
            pn = wire.pn_decode(tpn, pn_len, space.largest_rx)
            header = bytes(pkt[: rel_pn_off + pn_len])
            ciphertext = bytes(pkt[rel_pn_off + pn_len:])
            # Key update (RFC 9001 §6): the Key Phase bit (0x04, header-
            # protected) selects the key generation for short packets.
            phase = (pkt[0] >> 2) & 1
            if level == LEVEL_APP and now > self._prev_keys_deadline:
                self._prev_keys_rx = None  # §6.5: old read keys expire
            if level == LEVEL_APP and phase != self.rx_key_phase:
                # §6.3: pick the candidate generation by packet number —
                # below the current phase's first pn it can only be a
                # reordered pre-update packet (old keys); at or above, a
                # peer-initiated update (precomputed next keys, derived
                # once per generation, not per packet).
                if pn < self._rx_phase_start_pn and self._prev_keys_rx:
                    payload = self._prev_keys_rx.open(header, pn, ciphertext)
                else:
                    if self._next_keys_rx is None:
                        self._next_keys_rx = space.keys_rx.next_generation()
                    payload = self._next_keys_rx.open(header, pn, ciphertext)
                    # Install the new generation; respond in kind on the
                    # tx side unless we already initiated this update.
                    self._prev_keys_rx = space.keys_rx
                    self._prev_keys_deadline = now + 3 * self.rtt.pto()
                    space.keys_rx = self._next_keys_rx
                    self._next_keys_rx = None
                    self._rx_phase_start_pn = pn
                    self.rx_key_phase ^= 1
                    self.stat_key_updates += 1
                    if self.tx_key_phase != self.rx_key_phase:
                        space.keys_tx = space.keys_tx.next_generation()
                        self.tx_key_phase ^= 1
                        self._ku_pending = True
                        self._ku_min_ack_pn = space.next_pn
            else:
                payload = space.keys_rx.open(header, pn, ciphertext)
        except QuicCryptoError:
            # Undecryptable: drop silently (RFC 9001 §9.3) — unless it is
            # a stateless reset: a short-header datagram whose last 16
            # bytes equal the peer's advertised reset token (RFC 9000
            # §10.3.1; checked only AFTER AEAD failure, so a valid packet
            # can never be misread as a reset).
            if (level == LEVEL_APP and self.peer_reset_token is not None
                    and pkt_end - pkt_start >= 21
                    and data[pkt_end - 16:pkt_end] == self.peer_reset_token):
                self.closed = True
                self.close_reason = "stateless reset"
                self.stat_stateless_reset += 1
            return
        if not space.record_rx(pn):
            return  # duplicate
        if self.is_server and level == LEVEL_HANDSHAKE:
            # The client can only have handshake keys if it received our
            # Initial at the address it claims: address validated (§8.1).
            self.addr_validated = True
        if (not self.is_server and peer_scid is not None
                and not self._peer_cid_adopted):
            # RFC 9000 §7.2: the client MUST switch its dcid to the
            # server's chosen scid once a packet from the server is
            # processed — adopted here, after AEAD authentication, so an
            # off-path injector cannot redirect the connection. (The
            # stateless-reset design depends on this: the server's reset
            # token is minted for ITS cid.)
            self.dcid = peer_scid
            self._peer_cid_adopted = True
        if level == LEVEL_APP and pn > self._highest_rx_pn:
            self._highest_rx_pn = pn
            # Authenticated, newest packet from a non-current address:
            # start path validation (RFC 9000 §9.3 — spoofed packets die
            # at the AEAD above; reordered old-path packets have lower
            # pn and must not clobber an in-flight probe).
            if (self.established and self._last_rx_addr is not None
                    and self._last_rx_addr != self.peer_addr):
                self.on_peer_address_change(self._last_rx_addr, now)
        try:
            frames = wire.parse_frames(payload)
        except wire.QuicWireError:
            self.abort(0x0A, "frame encoding error")
            return
        ack_eliciting = False
        for f in frames:
            if f.ftype not in (wire.FRAME_ACK,):
                ack_eliciting = True
            self._on_frame(level, f, now)
        if ack_eliciting:
            space.ack_needed = True

    def _on_frame(self, level: int, f: wire.Frame, now: float) -> None:
        space = self.spaces[level]
        t = f.ftype
        if t == wire.FRAME_ACK:
            acked = space.on_ack(f)
            if (level == LEVEL_APP and self._ku_pending
                    and any(pn >= self._ku_min_ack_pn for pn, _ in acked)):
                self._ku_pending = False  # current phase confirmed (§6.2)
            for _pn, sp in acked:
                if sp.pmtu_probe and sp.pmtu_probe == self._pmtu_inflight:
                    # Probe delivered: the path carries this size (§14.3).
                    self.max_datagram = max(self.max_datagram,
                                            sp.pmtu_probe)
                    self._pmtu_inflight = 0
            # RTT sample ONLY when the frame's largest-acknowledged packet
            # is itself newly acked and ack-eliciting (RFC 9002 §5.1) — a
            # reordered ACK re-listing old ranges must not fold its own
            # delivery delay into srtt. ack_delay is us << exponent(3).
            largest = f.fields["largest"]
            for pn, sp in acked:
                if pn == largest and sp.ack_eliciting:
                    ack_delay = f.fields.get("ack_delay", 0) * 8 / 1e6
                    self.rtt.on_sample(now - sp.time, ack_delay)
                    break
            # Packet-threshold loss (RFC 9002 §6.1.1, kPacketThreshold=3):
            # anything 3+ below the new largest acked is lost NOW - the
            # fast-retransmit path that does not wait out a PTO.
            # Time-threshold loss (§6.1.2, kTimeThreshold = 9/8): a packet
            # older than 9/8 * max(srtt, latest_rtt) relative to `now`
            # that the newest ack skipped is also lost — catches tail and
            # small-flight losses a 3-packet gap can never form for.
            srtt = self.rtt.smoothed_rtt
            base_rtt = (max(srtt, self.rtt.latest_rtt)
                        if srtt is not None else 2 * self.rtt.initial_rtt)
            time_thresh = max(9 * base_rtt / 8, RttEstimator.K_GRANULARITY)
            for pn in list(space.sent.keys()):
                if pn <= space.largest_acked - 3 or (
                    pn < space.largest_acked
                    and space.sent[pn].time <= now - time_thresh
                ):
                    self._retransmit(space, pn)
        elif t == wire.FRAME_CRYPTO:
            self._on_crypto(level, f.fields["offset"], f.data)
        elif wire.FRAME_STREAM_BASE <= t <= wire.FRAME_STREAM_BASE | 7:
            self._on_stream_frame(f)
        elif t == wire.FRAME_HANDSHAKE_DONE:
            if not self.is_server:
                self.established = True
                self.spaces[LEVEL_HANDSHAKE].drop_keys()
        elif t == wire.FRAME_PATH_CHALLENGE:
            # Echo on the active path (RFC 9000 §8.3; single-socket model
            # approximates "same path" by replying to the current peer).
            self._path_frames.append(wire.encode_path_frame(
                wire.FRAME_PATH_RESPONSE,
                f.fields["data8"].to_bytes(8, "big"),
            ))
        elif t == wire.FRAME_PATH_RESPONSE:
            data = f.fields["data8"].to_bytes(8, "big")
            if (self._probe_data is not None and data == self._probe_data
                    and self._last_rx_addr == self._probe_addr):
                # Path validated: adopt the new address (§9.3).
                self.peer_addr = self._probe_addr
                self._probe_addr = self._probe_data = None
                self.stat_migrations += 1
        elif t in (wire.FRAME_CONN_CLOSE_QUIC, wire.FRAME_CONN_CLOSE_APP):
            self.closed = True
            self.close_reason = f.data.decode("utf-8", "replace")
        # MAX_DATA/MAX_STREAMS/NEW_CONNECTION_ID etc: tracked loosely; the
        # TPU role never hits the limits within a connection's lifetime.

    def _on_retry(self, pkt: bytes, hdr: wire.LongHeader, now: float) -> None:
        """Client-side Retry handling (RFC 9000 §17.2.5.2): validate the
        integrity tag against our ORIGINAL dcid, adopt the server's new
        cid (re-deriving Initial keys from it, RFC 9001 §5.2), stash the
        token for all subsequent Initials, and re-queue the ClientHello.
        At most one Retry per connection; ignored after any decrypted
        server packet (the tag alone does not authenticate the server,
        possession of our Initial does — which an on-path observer has,
        exactly the threat model Retry is scoped to)."""
        if self.is_server or self._retry_used or self.established:
            return
        if any(s.largest_rx >= 0 for s in self.spaces):
            return  # §17.2.5.2: discard after any processed packet
        token = wire.check_retry(pkt, self.orig_dcid)
        if token is None:
            return
        self._retry_used = True
        self._retry_token = token
        self.stat_retries += 1
        self.dcid = hdr.scid
        ckeys, skeys = initial_secrets(self.dcid)
        ini = self.spaces[LEVEL_INITIAL]
        ini.keys_tx, ini.keys_rx = ckeys, skeys
        # Re-queue everything in flight (the ClientHello): packet numbers
        # continue, they are not reset after Retry (RFC 9000 §17.2.5.3).
        for pn in list(ini.sent.keys()):
            self._retransmit(ini, pn)

    def _on_crypto(self, level: int, offset: int, data: bytes) -> None:
        space = self.spaces[level]
        if offset + len(data) <= space.crypto_rx_off:
            return  # fully duplicate
        space.crypto_rx[offset] = data
        # feed contiguous bytes to TLS
        progressed = True
        while progressed:
            progressed = False
            for off, chunk in sorted(space.crypto_rx.items()):
                if off <= space.crypto_rx_off < off + len(chunk):
                    take = chunk[space.crypto_rx_off - off :]
                    try:
                        self.tls.consume(level, take)
                    except TlsError as e:
                        self.abort(0x0128, f"tls: {e}")
                        return
                    space.crypto_rx_off = off + len(chunk)
                    del space.crypto_rx[off]
                    progressed = True
                    break
                if off + len(chunk) <= space.crypto_rx_off:
                    del space.crypto_rx[off]
                    progressed = True
                    break
        self._drain_tls()

    def _on_stream_frame(self, f: wire.Frame) -> None:
        sid = f.fields["stream_id"]
        st = self._recv_streams.get(sid)
        if st is None:
            st = self._recv_streams[sid] = _RecvStream()
        if st.delivered:
            return
        st.add(f.fields["offset"], f.data, bool(f.fields["fin"]))
        self._rx_data_total += len(f.data)
        done = st.complete()
        if done is not None:
            self._streams_consumed += 1
            if self.on_stream is not None:
                self.on_stream(sid, done)
            # retire reassembly state; keep the tombstone for dup filtering
            st.chunks.clear()

    # --------------------------------------------------------------- tls ---

    def _drain_tls(self) -> None:
        for level, msg in self.tls.take_output():
            self.spaces[level].queue_crypto(msg)
        if (
            self.tls.hs_secrets is not None
            and self.spaces[LEVEL_HANDSHAKE].keys_tx is None
        ):
            c, s = self.tls.hs_secrets
            ck, sk = PacketKeys.from_secret(c), PacketKeys.from_secret(s)
            hs = self.spaces[LEVEL_HANDSHAKE]
            if self.is_server:
                hs.keys_rx, hs.keys_tx = ck, sk
            else:
                hs.keys_rx, hs.keys_tx = sk, ck
        if (
            self.tls.app_secrets is not None
            and self.spaces[LEVEL_APP].keys_tx is None
        ):
            c, s = self.tls.app_secrets
            ck, sk = PacketKeys.from_secret(c), PacketKeys.from_secret(s)
            ap = self.spaces[LEVEL_APP]
            if self.is_server:
                ap.keys_rx, ap.keys_tx = ck, sk
            else:
                ap.keys_rx, ap.keys_tx = sk, ck
        if self.tls.peer_transport_params is not None and not self.peer_tp:
            self.peer_tp = parse_transport_params(
                self.tls.peer_transport_params
            )
            tok = self.peer_tp.get(TP_STATELESS_RESET_TOKEN)
            if tok is not None and len(tok) == 16:
                self.peer_reset_token = tok
        if self.tls.handshake_complete and self.is_server and not self.established:
            self.established = True
            self._hs_done_pending = True
            self.spaces[LEVEL_INITIAL].drop_keys()
            self.spaces[LEVEL_HANDSHAKE].drop_keys()

    # ---------------------------------------------------------------- tx ---

    def send_stream(self, data: bytes, fin: bool = True) -> int:
        """Open a new unidirectional stream carrying `data` (one txn)."""
        sid = self._next_uni_stream
        self._next_uni_stream += 4
        self._send_queue.append((sid, 0, data, fin))
        return sid

    def pending_datagrams(self, now: float) -> List[bytes]:
        """Assemble everything sendable into coalesced datagrams."""
        out: List[bytes] = []
        if not self.addr_validated and (
            self._amp_tx_bytes + MAX_DATAGRAM
            > AMP_LIMIT * self._amp_rx_bytes
        ):
            # Anti-amplification (§8.1): sending one more full datagram
            # could exceed 3x the bytes this unvalidated address has sent
            # us. Everything stays queued (crypto_tx untouched) until the
            # peer's next datagram buys more budget or validates the
            # address — a spoofed-source Initial flood can at most make
            # us echo 3x its own traffic at the victim.
            self.stat_amp_blocked += 1
            return out
        segments: List[bytes] = []
        pad_initial = False
        for level in (LEVEL_INITIAL, LEVEL_HANDSHAKE, LEVEL_APP):
            space = self.spaces[level]
            if space.keys_tx is None or space.dropped:
                continue
            frames: List[bytes] = []
            sent = _SentPacket(time=now, ack_eliciting=False)
            if space.ack_needed:
                ack = space.ack_frame()
                if ack:
                    frames.append(ack)
                space.ack_needed = False
            budget = self.max_datagram - 96  # header + AEAD margin
            while space.crypto_tx and budget > 24:
                off, data = space.crypto_tx.pop(0)
                room = budget - 12
                if len(data) > room:
                    space.crypto_tx.insert(0, (off + room, data[room:]))
                    data = data[:room]
                frames.append(wire.encode_crypto(off, data))
                sent.crypto.append((off, data))
                sent.ack_eliciting = True
                budget -= 12 + len(data)
            if level == LEVEL_APP:
                if self._hs_done_pending:
                    frames.append(bytes([wire.FRAME_HANDSHAKE_DONE]))
                    sent.handshake_done = True
                    sent.ack_eliciting = True
                    self._hs_done_pending = False
                while self._path_frames and budget > 16:
                    frames.append(self._path_frames.pop(0))
                    sent.ack_eliciting = True
                    budget -= 9
                while self._send_queue and budget > 32:
                    sid, off, data, fin = self._send_queue.pop(0)
                    room = budget - 16
                    if len(data) > room:
                        self._send_queue.insert(
                            0, (sid, off + room, data[room:], fin)
                        )
                        data, fin_now = data[:room], False
                    else:
                        fin_now = fin
                    frames.append(
                        wire.encode_stream(sid, off, data, fin_now)
                    )
                    sent.streams.append((sid, off, data, fin_now))
                    sent.ack_eliciting = True
                    budget -= 16 + len(data)
            if not frames:
                continue
            payload = b"".join(frames)
            # the header-protection sample needs pn_len+payload+tag >= 20
            # bytes past the pn offset: pad tiny payloads (PADDING frames)
            if len(payload) < 8:
                payload += bytes(8 - len(payload))
            pn = space.next_pn
            space.next_pn += 1
            pn_len = 2
            if level == LEVEL_APP:
                header = wire.encode_short_header(
                    self.dcid, pn, pn_len, key_phase=self.tx_key_phase
                )
            else:
                header = wire.encode_long_header(
                    _LEVEL_TO_PKT[level],
                    self.dcid if self.dcid else self.orig_dcid,
                    self.scid,
                    pn,
                    pn_len,
                    len(payload) + AEAD_OVERHEAD,
                    # Initials echo the server's retry token (§8.1.2).
                    token=(self._retry_token
                           if level == LEVEL_INITIAL else b""),
                )
                if level == LEVEL_INITIAL and not self.is_server:
                    pad_initial = True
            if sent.ack_eliciting:
                space.sent[pn] = sent
            segments.append(
                protect_packet(space.keys_tx, header, pn, pn_len, payload)
            )
        if not segments:
            return out
        self._amp_tx_bytes += sum(len(s) for s in segments)
        datagram = b"".join(segments)
        if pad_initial and len(datagram) < 1200:
            # client Initial datagrams must be >=1200B (RFC 9000 §14.1):
            # pre-pad the *first* segment's payload is complex post-AEAD, so
            # append PADDING inside a trailing app/hs segment if one exists;
            # otherwise rebuild with padding. Simplest correct approach:
            # append raw zero bytes is NOT valid post-protection, so instead
            # re-emit padding as a separate Initial packet is overkill —
            # we pad by constructing the datagram again below.
            datagram = self._pad_initial_datagram(segments, now)
        out.append(datagram)
        return out

    def _pad_initial_datagram(self, segments: List[bytes], now: float) -> bytes:
        """Pad a client datagram containing an Initial to 1200B by sending
        an extra PADDING-only Initial packet sized to fill the gap."""
        space = self.spaces[LEVEL_INITIAL]
        if space.keys_tx is None:
            return b"".join(segments)
        gap = 1200 - sum(len(s) for s in segments)
        pn = space.next_pn
        space.next_pn += 1
        pn_len = 2
        # long header for dcid/scid as in normal initial
        overhead = 7 + 1 + len(self.dcid or self.orig_dcid) + 1 + len(self.scid) + 1 + 2 + pn_len + AEAD_OVERHEAD
        pad_len = max(8, gap - overhead)
        payload = bytes(pad_len)  # PADDING frames
        header = wire.encode_long_header(
            wire.PKT_INITIAL,
            self.dcid if self.dcid else self.orig_dcid,
            self.scid,
            pn,
            pn_len,
            len(payload) + AEAD_OVERHEAD,
            token=self._retry_token,
        )
        segments.append(
            protect_packet(space.keys_tx, header, pn, pn_len, payload)
        )
        return b"".join(segments)

    # ------------------------------------------------------------ service --

    def _retransmit(self, space: "_PnSpace", pn: int) -> None:
        """Re-queue a sent packet's retransmittable content."""
        sp = space.sent.pop(pn)
        if sp.pmtu_probe:
            # A lost probe is the DPLPMTUD answer, not data to re-send:
            # the path cannot carry pmtu_probe bytes — stop the search
            # at the last validated size (RFC 8899 SEARCH_COMPLETE).
            if self._pmtu_inflight == sp.pmtu_probe:
                self._pmtu_inflight = 0
                self._pmtu_done = True
            return
        for off, data in sp.crypto:
            space.crypto_tx.insert(0, (off, data))
        for st in sp.streams:
            self._send_queue.insert(0, st)
        if sp.handshake_done:
            self._hs_done_pending = True

    def service(self, now: float) -> List[bytes]:
        """Timers: idle timeout + PTO retransmission (RTT-driven, RFC 9002;
        the estimator's PTO backs off exponentially while no acks arrive).
        -> datagrams to send."""
        if self.closed:
            return []
        if now - self._last_activity > self.idle_timeout:
            self.closed = True
            self.close_reason = "idle timeout"
            return []
        pto = self.rtt.pto()
        fired = False
        for space in self.spaces:
            if space.dropped:
                continue
            for pn in list(space.sent.keys()):
                if now - space.sent[pn].time > pto:
                    probe = space.sent[pn].pmtu_probe != 0
                    self._retransmit(space, pn)
                    if not probe:   # a lost PMTU probe is an answer,
                        fired = True  # not a congestion signal
        if fired:
            self.rtt.pto_count += 1
        out = self.pending_datagrams(now)
        probe = self._pmtu_probe_datagram(now)
        if probe is not None:
            out.append(probe)
        return out

    def _pmtu_probe_datagram(self, now: float) -> Optional[bytes]:
        """DPLPMTUD search step (RFC 8899, RFC 9000 §14.3): one padded
        PING datagram at the next candidate size; adopted on ack, search
        ended on loss. Never carries data, so a blackholed probe costs
        nothing but itself."""
        if (not self.established or self._pmtu_done or self._pmtu_inflight
                or not self.addr_validated
                or self.spaces[LEVEL_APP].keys_tx is None):
            return None
        target = next(
            (r for r in self._pmtu_rungs if r > self.max_datagram), None
        )
        if target is None:
            self._pmtu_done = True
            return None
        space = self.spaces[LEVEL_APP]
        pn = space.next_pn
        space.next_pn += 1
        pn_len = 2
        header = wire.encode_short_header(
            self.dcid, pn, pn_len, key_phase=self.tx_key_phase
        )
        payload = bytes([wire.FRAME_PING])
        payload += bytes(target - len(header) - AEAD_OVERHEAD - len(payload))
        space.sent[pn] = _SentPacket(
            time=now, ack_eliciting=True, pmtu_probe=target
        )
        self._pmtu_inflight = target
        self.stat_pmtu_probes += 1
        return protect_packet(space.keys_tx, header, pn, pn_len, payload)

    def on_peer_address_change(self, addr, now: float) -> None:
        """A post-handshake datagram arrived from an unvalidated address:
        start (or continue) a PATH_CHALLENGE probe of it. The connection
        keeps sending to the validated address until the probe round
        trip completes (RFC 9000 §9.1)."""
        if self._probe_data is not None and now < self._probe_expire:
            # A validation is already in flight: a different candidate
            # address must NOT clobber it (round-2 ADVICE: an off-path
            # attacker racing copies of genuine datagrams from spoofed
            # sources could otherwise overwrite the probe indefinitely
            # and starve a real NAT-rebind migration). The loser will
            # re-trigger once this probe validates or expires.
            return
        self._probe_addr = addr
        self._probe_data = os.urandom(8)
        self._probe_expire = now + 3 * max(self.rtt.pto(), 0.1)
        self._probe_next_tx = now

    def path_probe_datagrams(self, now: float) -> List[tuple]:
        """[(addr, datagram)] of PATH_CHALLENGE probes due now; resent
        once per PTO until the probe validates or expires."""
        if (self.closed or self._probe_data is None
                or self.spaces[LEVEL_APP].keys_tx is None):
            return []
        if now >= self._probe_expire:
            self._probe_addr = self._probe_data = None
            return []
        if now < self._probe_next_tx:
            return []
        self._probe_next_tx = now + max(self.rtt.pto(), 0.05)
        space = self.spaces[LEVEL_APP]
        payload = wire.encode_path_frame(
            wire.FRAME_PATH_CHALLENGE, self._probe_data
        )
        pn = space.next_pn
        space.next_pn += 1
        header = wire.encode_short_header(
            self.dcid, pn, 2, key_phase=self.tx_key_phase
        )
        return [(self._probe_addr,
                 protect_packet(space.keys_tx, header, pn, 2, payload))]

    def initiate_key_update(self) -> None:
        """Roll the 1-RTT send keys one generation (RFC 9001 §6.1); the
        peer detects the flipped Key Phase bit and responds in kind.
        Only valid once the handshake is confirmed, and not before the
        peer has answered the previous update (§6.2 MUST NOT — rolling
        twice within one round trip returns the phase BIT to its old
        value while the keys advance two generations, silently killing
        the connection)."""
        if not self.established:
            raise RuntimeError("key update before handshake confirmation")
        if self.tx_key_phase != self.rx_key_phase or self._ku_pending:
            raise RuntimeError(
                "previous key update not yet acknowledged by the peer"
            )
        space = self.spaces[LEVEL_APP]
        space.keys_tx = space.keys_tx.next_generation()
        self.tx_key_phase ^= 1
        self._ku_pending = True
        self._ku_min_ack_pn = space.next_pn
        self.stat_key_updates += 1

    def reassembly_pressure(self) -> Tuple[int, int]:
        """(incomplete_streams, buffered_bytes) held by streams that
        have NOT completed: the slowloris posture gauge. A peer
        dribbling partial streams grows exactly this — the quic tile's
        FD_QUIC_SLOW_MAX_BUF defense reads it at housekeeping rate and
        quarantines the connection past the budget, so held-open
        streams cannot grow server state unboundedly."""
        n = 0
        nbytes = 0
        for st in self._recv_streams.values():
            if st.delivered:
                continue
            sz = sum(len(c) for c in st.chunks.values())
            if sz:
                n += 1
                nbytes += sz
        return n, nbytes

    def abort(self, error: int, reason: str) -> None:
        self.closed = True
        self.close_reason = reason
