"""Top-level QUIC endpoint: connection map, datagram routing, service loop.

Role parity with /root/reference/src/tango/quic/fd_quic.{h,c}: the object an
aio backend feeds datagrams into (fd_quic_process_packet) and that produces
datagrams out through an aio tx callback, managing server-side connection
creation keyed by destination connection id and driving per-conn timers via
service() (fd_quic_service). Transport is pluggable: anything that can call
`rx()` with (peer_addr, datagram) and accept `tx(peer_addr, datagram)`
callbacks works — UDP sockets (tango/udpsock), in-process paired wires for
tests (the reference's fd_quic_test_helpers virtual pairs), or pcap replay.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from firedancer_tpu.tango.quic import wire
from firedancer_tpu.tango.quic.conn import CID_LEN, QuicConn


@dataclass
class QuicConfig:
    is_server: bool
    identity_seed: bytes
    alpns: Tuple[bytes, ...] = (b"solana-tpu",)
    idle_timeout: float = 10.0
    max_conns: int = 1024
    initial_max_streams_uni: int = 2048


class Quic:
    """A QUIC endpoint (one server or one client side)."""

    def __init__(
        self,
        cfg: QuicConfig,
        tx: Callable[[object, bytes], None],
        on_stream: Optional[Callable[[QuicConn, int, bytes], None]] = None,
        on_conn_new: Optional[Callable[[QuicConn], None]] = None,
        on_conn_closed: Optional[Callable[[QuicConn], None]] = None,
    ):
        self.cfg = cfg
        self._tx = tx
        self._on_stream = on_stream
        self._on_conn_new = on_conn_new
        self._on_conn_closed = on_conn_closed
        self._conns_by_cid: Dict[bytes, QuicConn] = {}
        self.conns: List[QuicConn] = []
        # metrics (reference: fd_quic_metrics)
        self.metrics = {
            "rx_datagrams": 0,
            "tx_datagrams": 0,
            "conns_created": 0,
            "conns_closed": 0,
            "streams_completed": 0,
            "rx_dropped": 0,
        }

    # ------------------------------------------------------------- client --

    def connect(self, peer_addr, now: float = 0.0) -> QuicConn:
        assert not self.cfg.is_server
        conn = QuicConn(
            is_server=False,
            identity_seed=self.cfg.identity_seed,
            peer_addr=peer_addr,
            alpns=self.cfg.alpns,
            idle_timeout=self.cfg.idle_timeout,
            on_stream=None,
            now=now,
        )
        self._register(conn)
        self._flush(conn, now)
        return conn

    # ----------------------------------------------------------------- rx --

    def rx(self, peer_addr, datagram: bytes, now: float) -> None:
        """Feed one received UDP datagram into the endpoint."""
        self.metrics["rx_datagrams"] += 1
        if not datagram:
            return
        conn = self._route(datagram)
        if conn is None:
            if not self.cfg.is_server or not wire.is_long_header(datagram[0]):
                self.metrics["rx_dropped"] += 1
                return
            try:
                hdr = wire.parse_long_header(datagram)
            except wire.QuicWireError:
                self.metrics["rx_dropped"] += 1
                return
            if (
                hdr.pkt_type != wire.PKT_INITIAL
                or hdr.version != wire.QUIC_VERSION_1
                or len(self.conns) >= self.cfg.max_conns
            ):
                self.metrics["rx_dropped"] += 1
                return
            conn = QuicConn(
                is_server=True,
                identity_seed=self.cfg.identity_seed,
                peer_addr=peer_addr,
                alpns=self.cfg.alpns,
                orig_dcid=hdr.dcid,
                idle_timeout=self.cfg.idle_timeout,
                on_stream=None,
                now=now,
                initial_max_streams_uni=self.cfg.initial_max_streams_uni,
            )
            self._register(conn)
            self._conns_by_cid[hdr.dcid] = conn  # route follow-up initials
            if self._on_conn_new is not None:
                self._on_conn_new(conn)
        if not conn.established:
            conn.peer_addr = peer_addr   # pre-handshake address learning
        # Post-handshake address changes are detected INSIDE
        # recv_datagram, after AEAD authentication succeeds and only for
        # the highest-numbered packet (RFC 9000 §9.3) — a spoofed or
        # reordered datagram must not be able to start or clobber a path
        # probe. Traffic keeps flowing to the validated address until
        # the PATH_CHALLENGE round trip completes.
        conn.recv_datagram(datagram, now, from_addr=peer_addr)
        self._flush(conn, now)

    def _route(self, datagram: bytes) -> Optional[QuicConn]:
        if wire.is_long_header(datagram[0]):
            try:
                hdr = wire.parse_long_header(datagram)
            except wire.QuicWireError:
                return None
            return self._conns_by_cid.get(hdr.dcid)
        if 1 + CID_LEN > len(datagram):
            return None
        return self._conns_by_cid.get(datagram[1 : 1 + CID_LEN])

    # ------------------------------------------------------------ service --

    def service(self, now: float) -> None:
        """Drive timers on every connection; reap closed conns."""
        for conn in list(self.conns):
            for dg in conn.service(now):
                self._tx(conn.peer_addr, dg)
                self.metrics["tx_datagrams"] += 1
            for addr, dg in conn.path_probe_datagrams(now):
                self._tx(addr, dg)
                self.metrics["tx_datagrams"] += 1
            if conn.closed:
                self._unregister(conn)

    # ------------------------------------------------------------ helpers --

    def _register(self, conn: QuicConn) -> None:
        self.conns.append(conn)
        self._conns_by_cid[conn.scid] = conn
        self.metrics["conns_created"] += 1
        conn.on_stream = self._make_stream_cb(conn)

    def _make_stream_cb(self, conn: QuicConn):
        def cb(sid: int, data: bytes) -> None:
            self.metrics["streams_completed"] += 1
            if self._on_stream is not None:
                self._on_stream(conn, sid, data)

        return cb

    def _unregister(self, conn: QuicConn) -> None:
        if conn in self.conns:
            self.conns.remove(conn)
            self.metrics["conns_closed"] += 1
            if self._on_conn_closed is not None:
                self._on_conn_closed(conn)
        for cid in [k for k, v in self._conns_by_cid.items() if v is conn]:
            del self._conns_by_cid[cid]

    def _flush(self, conn: QuicConn, now: float) -> None:
        for dg in conn.pending_datagrams(now):
            self._tx(conn.peer_addr, dg)
            self.metrics["tx_datagrams"] += 1
        for addr, dg in conn.path_probe_datagrams(now):
            self._tx(addr, dg)
            self.metrics["tx_datagrams"] += 1
        if conn.closed:
            self._unregister(conn)
