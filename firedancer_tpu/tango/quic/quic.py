"""Top-level QUIC endpoint: connection map, datagram routing, service loop.

Role parity with /root/reference/src/tango/quic/fd_quic.{h,c}: the object an
aio backend feeds datagrams into (fd_quic_process_packet) and that produces
datagrams out through an aio tx callback, managing server-side connection
creation keyed by destination connection id and driving per-conn timers via
service() (fd_quic_service). Transport is pluggable: anything that can call
`rx()` with (peer_addr, datagram) and accept `tx(peer_addr, datagram)`
callbacks works — UDP sockets (tango/udpsock), in-process paired wires for
tests (the reference's fd_quic_test_helpers virtual pairs), or pcap replay.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from firedancer_tpu.tango.quic import wire
from firedancer_tpu.tango.quic.conn import CID_LEN, QuicConn


@dataclass
class QuicConfig:
    is_server: bool
    identity_seed: bytes
    alpns: Tuple[bytes, ...] = (b"solana-tpu",)
    idle_timeout: float = 10.0
    max_conns: int = 1024
    initial_max_streams_uni: int = 2048
    # DoS hardening for a public ingest port (RFC 9000 §8):
    # retry=True answers token-less Initials with a stateless Retry —
    # no connection state is allocated until the client echoes a valid
    # address-bound token, so a spoofed-source Initial flood costs the
    # server one small datagram each and zero memory.
    retry: bool = False
    token_lifetime: float = 30.0
    # stateless_reset=True answers short-header datagrams for unknown
    # cids with a Stateless Reset (§10.3), letting peers of a rebooted
    # endpoint tear down dead connections instead of timing out.
    stateless_reset: bool = True
    # Server-side handshake deadline (seconds): a connection that has
    # not completed its handshake within this window is reaped by
    # service() — the half-open-connection flood defense (a spoofed or
    # junk Initial buys an attacker at most hs_timeout of state
    # lifetime, not a full idle_timeout slot). 0 disables.
    hs_timeout: float = 0.0


class Quic:
    """A QUIC endpoint (one server or one client side)."""

    def __init__(
        self,
        cfg: QuicConfig,
        tx: Callable[[object, bytes], None],
        on_stream: Optional[Callable[[QuicConn, int, bytes], None]] = None,
        on_conn_new: Optional[Callable[[QuicConn], None]] = None,
        on_conn_closed: Optional[Callable[[QuicConn], None]] = None,
        on_rx_drop: Optional[Callable[[object], None]] = None,
    ):
        self.cfg = cfg
        self._tx = tx
        self._on_stream = on_stream
        self._on_conn_new = on_conn_new
        self._on_conn_closed = on_conn_closed
        # Peer-attributed drop notification: called with the source
        # address every time an rx datagram is dropped unprocessed
        # (junk, unknown cid, bad token, conn-cap overflow). The quic
        # tile's abuse breaker scores peers on this — the endpoint
        # itself stays policy-free.
        self._on_rx_drop = on_rx_drop
        self._conns_by_cid: Dict[bytes, QuicConn] = {}
        self.conns: List[QuicConn] = []
        # Endpoint-static secrets: the token key binds retry tokens to
        # this endpoint instance; the reset key derives per-cid stateless
        # reset tokens (deterministic, so they survive connection-state
        # loss — the whole point of a stateless reset).
        self._token_key = os.urandom(32)
        self._reset_key = os.urandom(32)
        # Reset handling must stay cheap under junk floods: incoming
        # candidate resets match against an O(1) token index (rebuilt at
        # most once a second — peer tokens arrive asynchronously inside
        # the TLS flight, so the index is a snapshot by design), and
        # outgoing resets are token-bucket limited (RFC 9000 §10.3
        # recommends bounding resets sent).
        self._reset_index: Dict[bytes, QuicConn] = {}
        self._reset_index_at = -1.0
        self._reset_budget = 10.0
        self._reset_budget_at = 0.0
        # metrics (reference: fd_quic_metrics)
        self.metrics = {
            "rx_datagrams": 0,
            "tx_datagrams": 0,
            "conns_created": 0,
            "conns_closed": 0,
            "streams_completed": 0,
            "rx_dropped": 0,
            "retries_sent": 0,
            "tokens_accepted": 0,
            "tokens_rejected": 0,
            "resets_sent": 0,
        }

    # ------------------------------------------------------------- client --

    def connect(self, peer_addr, now: float = 0.0) -> QuicConn:
        if self.cfg.is_server:
            raise ValueError("connect() is a client-endpoint operation")
        conn = QuicConn(
            is_server=False,
            identity_seed=self.cfg.identity_seed,
            peer_addr=peer_addr,
            alpns=self.cfg.alpns,
            idle_timeout=self.cfg.idle_timeout,
            on_stream=None,
            now=now,
        )
        self._register(conn)
        self._flush(conn, now)
        return conn

    # ----------------------------------------------------------------- rx --

    def _drop(self, peer_addr) -> None:
        """Count + attribute one unprocessable rx datagram (every
        rx_dropped increment routes through here so the tile's abuse
        breaker sees the peer address)."""
        self.metrics["rx_dropped"] += 1
        if self._on_rx_drop is not None:
            self._on_rx_drop(peer_addr)

    def rx(self, peer_addr, datagram: bytes, now: float) -> None:
        """Feed one received UDP datagram into the endpoint."""
        self.metrics["rx_datagrams"] += 1
        if not datagram:
            return
        conn = self._route(datagram)
        if conn is None:
            if not wire.is_long_header(datagram[0]):
                # A datagram we cannot associate with any connection:
                # first check whether IT is a stateless reset aimed at
                # one of our conns (RFC 9000 §10.3.1 — a reset carries a
                # random dcid, so it never routes; the endpoint matches
                # the trailing 16 bytes against the token index).
                if len(datagram) >= 21:
                    if now - self._reset_index_at >= 1.0:
                        self._reset_index = {
                            c.peer_reset_token: c for c in self.conns
                            if c.peer_reset_token is not None
                        }
                        self._reset_index_at = now
                    c = self._reset_index.get(datagram[-16:])
                    if c is not None and not c.closed:
                        c.closed = True
                        c.close_reason = "stateless reset"
                        c.stat_stateless_reset += 1
                        self._unregister(c)
                        return
                # Otherwise: short header for a cid we have no state
                # for — answer with a Stateless Reset (§10.3) so the
                # peer can tear down instead of retransmitting into a
                # void. MUST be smaller than what triggered it
                # (§10.3.3, the reset-loop guard), so tiny datagrams
                # get nothing.
                self._maybe_stateless_reset(peer_addr, datagram, now)
                self._drop(peer_addr)
                return
            if not self.cfg.is_server:
                self._drop(peer_addr)
                return
            try:
                hdr = wire.parse_long_header(datagram)
            except wire.QuicWireError:
                self._drop(peer_addr)
                return
            if (
                hdr.pkt_type != wire.PKT_INITIAL
                or hdr.version != wire.QUIC_VERSION_1
                or len(self.conns) >= self.cfg.max_conns
            ):
                self._drop(peer_addr)
                return
            token_odcid = None
            addr_validated = None
            if self.cfg.retry:
                if not hdr.token:
                    # Stateless Retry: bind a token to (address, odcid)
                    # and allocate NOTHING until it comes back.
                    self._tx(peer_addr, wire.encode_retry(
                        dcid=hdr.scid,
                        scid=os.urandom(CID_LEN),
                        token=self._make_token(peer_addr, hdr.dcid, now),
                        odcid=hdr.dcid,
                    ))
                    self.metrics["retries_sent"] += 1
                    self.metrics["tx_datagrams"] += 1
                    return
                token_odcid = self._check_token(hdr.token, peer_addr, now)
                if token_odcid is None:
                    self.metrics["tokens_rejected"] += 1
                    self._drop(peer_addr)
                    return
                self.metrics["tokens_accepted"] += 1
                addr_validated = True
            scid = os.urandom(CID_LEN)
            conn = QuicConn(
                is_server=True,
                identity_seed=self.cfg.identity_seed,
                peer_addr=peer_addr,
                alpns=self.cfg.alpns,
                orig_dcid=hdr.dcid,
                idle_timeout=self.cfg.idle_timeout,
                on_stream=None,
                now=now,
                initial_max_streams_uni=self.cfg.initial_max_streams_uni,
                scid=scid,
                reset_token=(self._reset_token(scid)
                             if self.cfg.stateless_reset else None),
                retry_odcid=token_odcid,
                addr_validated=addr_validated,
            )
            self._register(conn)
            self._conns_by_cid[hdr.dcid] = conn  # route follow-up initials
            if self._on_conn_new is not None:
                self._on_conn_new(conn)
        if not conn.established:
            conn.peer_addr = peer_addr   # pre-handshake address learning
        # Post-handshake address changes are detected INSIDE
        # recv_datagram, after AEAD authentication succeeds and only for
        # the highest-numbered packet (RFC 9000 §9.3) — a spoofed or
        # reordered datagram must not be able to start or clobber a path
        # probe. Traffic keeps flowing to the validated address until
        # the PATH_CHALLENGE round trip completes.
        conn.recv_datagram(datagram, now, from_addr=peer_addr)
        self._flush(conn, now)

    def _route(self, datagram: bytes) -> Optional[QuicConn]:
        if wire.is_long_header(datagram[0]):
            try:
                hdr = wire.parse_long_header(datagram)
            except wire.QuicWireError:
                return None
            return self._conns_by_cid.get(hdr.dcid)
        if 1 + CID_LEN > len(datagram):
            return None
        return self._conns_by_cid.get(datagram[1 : 1 + CID_LEN])

    # ------------------------------------------------------------ service --

    def service(self, now: float) -> None:
        """Drive timers on every connection; reap closed conns — and
        enforce the handshake deadline: a server conn still
        unestablished past cfg.hs_timeout is closed here (half-open
        flood defense; see QuicConfig.hs_timeout)."""
        for conn in list(self.conns):
            if (self.cfg.hs_timeout and self.cfg.is_server
                    and not conn.established and not conn.closed
                    and now - conn.created > self.cfg.hs_timeout):
                conn.closed = True
                conn.close_reason = "handshake timeout"
            for dg in conn.service(now):
                self._tx(conn.peer_addr, dg)
                self.metrics["tx_datagrams"] += 1
            for addr, dg in conn.path_probe_datagrams(now):
                self._tx(addr, dg)
                self.metrics["tx_datagrams"] += 1
            if conn.closed:
                self._unregister(conn)

    # ------------------------------------------------------------ helpers --

    def _reset_token(self, cid: bytes) -> bytes:
        """Deterministic per-cid stateless-reset token (RFC 9000 §10.3.2):
        HMAC of the cid under the endpoint-static reset key, so the token
        can be recomputed with NO per-connection state."""
        import hashlib
        import hmac

        return hmac.new(self._reset_key, b"sr" + cid,
                        hashlib.sha256).digest()[:16]

    def _maybe_stateless_reset(self, peer_addr, datagram: bytes,
                               now: float) -> None:
        if not self.cfg.stateless_reset or len(datagram) < 22:
            return
        # Token bucket (10/s, burst 10): a junk flood must not buy an
        # HMAC + urandom + reflected datagram per packet (§10.3).
        self._reset_budget = min(
            10.0, self._reset_budget + (now - self._reset_budget_at) * 10.0
        )
        self._reset_budget_at = now
        if self._reset_budget < 1.0:
            return
        self._reset_budget -= 1.0
        dcid = datagram[1 : 1 + CID_LEN]
        if len(dcid) < CID_LEN:
            return
        # Strictly smaller than the trigger (reset-loop guard §10.3.3),
        # and bounded so a flood cannot use us as an amplifier.
        size = min(len(datagram) - 1, 64)
        self._tx(peer_addr,
                 wire.encode_stateless_reset(self._reset_token(dcid), size))
        self.metrics["resets_sent"] += 1
        self.metrics["tx_datagrams"] += 1

    def _make_token(self, peer_addr, odcid: bytes, now: float) -> bytes:
        """Retry token: timestamp + odcid, MACed together with the client
        address under the endpoint-static token key (§8.1.3 — address-
        bound, expiring, stateless)."""
        import hashlib
        import hmac
        import struct

        body = struct.pack(">d", now) + bytes([len(odcid)]) + odcid
        mac = hmac.new(self._token_key, repr(peer_addr).encode() + body,
                       hashlib.sha256).digest()[:16]
        return body + mac

    def _check_token(self, token: bytes, peer_addr, now: float):
        """-> the original dcid bound into a valid token, else None."""
        import hashlib
        import hmac
        import struct

        if len(token) < 8 + 1 + 16:
            return None
        body, mac = token[:-16], token[-16:]
        want = hmac.new(self._token_key, repr(peer_addr).encode() + body,
                        hashlib.sha256).digest()[:16]
        if not hmac.compare_digest(mac, want):
            return None
        ts = struct.unpack(">d", body[:8])[0]
        if not (now - self.cfg.token_lifetime <= ts <= now + 1.0):
            return None
        ln = body[8]
        odcid = body[9 : 9 + ln]
        if len(odcid) != ln or len(body) != 9 + ln:
            return None
        return odcid

    def _register(self, conn: QuicConn) -> None:
        self.conns.append(conn)
        self._conns_by_cid[conn.scid] = conn
        self.metrics["conns_created"] += 1
        conn.on_stream = self._make_stream_cb(conn)

    def _make_stream_cb(self, conn: QuicConn):
        def cb(sid: int, data: bytes) -> None:
            self.metrics["streams_completed"] += 1
            if self._on_stream is not None:
                self._on_stream(conn, sid, data)

        return cb

    def _unregister(self, conn: QuicConn) -> None:
        if conn in self.conns:
            self.conns.remove(conn)
            self.metrics["conns_closed"] += 1
            if self._on_conn_closed is not None:
                self._on_conn_closed(conn)
        for cid in [k for k, v in self._conns_by_cid.items() if v is conn]:
            del self._conns_by_cid[cid]

    def _flush(self, conn: QuicConn, now: float) -> None:
        for dg in conn.pending_datagrams(now):
            self._tx(conn.peer_addr, dg)
            self.metrics["tx_datagrams"] += 1
        for addr, dg in conn.path_probe_datagrams(now):
            self._tx(addr, dg)
            self.metrics["tx_datagrams"] += 1
        if conn.closed:
            self._unregister(conn)
