"""QUIC v1 (RFC 9000/9001) — the TPU-native equivalent of the reference's
tango/quic layer (/root/reference/src/tango/quic/): wire codecs, packet
protection, a from-scratch TLS 1.3 handshake over CRYPTO frames, connection
state machine, and stream reassembly, speaking the Solana TPU ALPN.

The reference's split is mirrored by module:
  wire.py          <- templ/fd_quic_templ.h + fd_quic_proto.{h,c} (codecs)
  crypto_suites.py <- crypto/fd_quic_crypto_suites.{h,c} (AEAD + HP + keys)
  tls.py           <- tls/fd_quic_tls.{h,c} (handshake engine; here built
                      from scratch on ballet aes/hkdf/x25519/x509 instead of
                      delegating to a TLS library)
  conn.py          <- fd_quic_conn.{h,c} + fd_quic_stream.* (per-conn state)
  quic.py          <- fd_quic.{h,c} (top object: conn map, aio, service loop)
"""

def __getattr__(name):
    if name in ("Quic", "QuicConfig"):
        from firedancer_tpu.tango.quic import quic as _q

        return getattr(_q, name)
    raise AttributeError(name)
