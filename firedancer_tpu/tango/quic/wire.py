"""QUIC v1 wire codecs: varints, packet headers, frames.

Role parity with the reference's preprocessor-templated codec DSL
(/root/reference/src/tango/quic/templ/fd_quic_templ.h and
fd_quic_parsers/encoders generated from it): here the same idea is a
declarative Python table (`_FRAME_SPECS`) driving a generic parse/encode
pair, with the two irregular frames (ACK's range groups, STREAM's
flag-dependent fields) handled explicitly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

QUIC_VERSION_1 = 0x00000001

# long-header packet types (RFC 9000 §17.2)
PKT_INITIAL = 0
PKT_ZERO_RTT = 1
PKT_HANDSHAKE = 2
PKT_RETRY = 3


class QuicWireError(ValueError):
    pass


# --------------------------------------------------------------- varint ----

def varint_decode(buf: bytes, off: int) -> Tuple[int, int]:
    """-> (value, new_off). RFC 9000 §16: 2-bit length prefix, big-endian."""
    if off >= len(buf):
        raise QuicWireError("varint: truncated")
    first = buf[off]
    n = 1 << (first >> 6)
    if off + n > len(buf):
        raise QuicWireError("varint: truncated body")
    v = first & 0x3F
    for i in range(1, n):
        v = (v << 8) | buf[off + i]
    return v, off + n


def varint_encode(v: int) -> bytes:
    if v < 0x40:
        return bytes([v])
    if v < 0x4000:
        return (0x4000 | v).to_bytes(2, "big")
    if v < 0x40000000:
        return (0x80000000 | v).to_bytes(4, "big")
    if v < 0x4000000000000000:
        return (0xC000000000000000 | v).to_bytes(8, "big")
    raise QuicWireError("varint: value too large")


# ------------------------------------------------------- packet headers ----

@dataclass
class LongHeader:
    pkt_type: int
    version: int
    dcid: bytes
    scid: bytes
    token: bytes = b""  # Initial only
    length: int = 0  # pn + payload length (varint field)
    hdr_end: int = 0  # offset where the packet number begins
    first_byte: int = 0


@dataclass
class ShortHeader:
    dcid: bytes
    hdr_end: int = 0
    first_byte: int = 0


def is_long_header(first_byte: int) -> bool:
    return bool(first_byte & 0x80)


def parse_long_header(buf: bytes, off: int = 0) -> LongHeader:
    if off >= len(buf):
        raise QuicWireError("empty datagram")
    first = buf[off]
    if not (first & 0x80):
        raise QuicWireError("not a long header")
    if off + 6 > len(buf):
        raise QuicWireError("long header truncated")
    version = int.from_bytes(buf[off + 1 : off + 5], "big")
    p = off + 5
    dcil = buf[p]
    p += 1
    if dcil > 20 or p + dcil > len(buf):
        raise QuicWireError("bad dcid")
    dcid = bytes(buf[p : p + dcil])
    p += dcil
    if p >= len(buf):
        raise QuicWireError("long header truncated at scid")
    scil = buf[p]
    p += 1
    if scil > 20 or p + scil > len(buf):
        raise QuicWireError("bad scid")
    scid = bytes(buf[p : p + scil])
    p += scil
    pkt_type = (first >> 4) & 0x3
    token = b""
    if pkt_type == PKT_INITIAL:
        tok_len, p = varint_decode(buf, p)
        if p + tok_len > len(buf):
            raise QuicWireError("bad token")
        token = bytes(buf[p : p + tok_len])
        p += tok_len
    length = 0
    if pkt_type != PKT_RETRY:
        length, p = varint_decode(buf, p)
    return LongHeader(
        pkt_type=pkt_type,
        version=version,
        dcid=dcid,
        scid=scid,
        token=token,
        length=length,
        hdr_end=p,
        first_byte=first,
    )


def encode_long_header(
    pkt_type: int,
    dcid: bytes,
    scid: bytes,
    pn: int,
    pn_len: int,
    payload_len: int,
    token: bytes = b"",
    version: int = QUIC_VERSION_1,
) -> bytes:
    """Header bytes up to and including the (unprotected) packet number."""
    first = 0xC0 | (pkt_type << 4) | (pn_len - 1)
    out = bytearray([first])
    out += version.to_bytes(4, "big")
    out.append(len(dcid))
    out += dcid
    out.append(len(scid))
    out += scid
    if pkt_type == PKT_INITIAL:
        out += varint_encode(len(token))
        out += token
    out += varint_encode(pn_len + payload_len)
    out += pn.to_bytes(pn_len, "big")[-pn_len:]
    return bytes(out)


def parse_short_header(buf: bytes, dcid_len: int, off: int = 0) -> ShortHeader:
    if off >= len(buf):
        raise QuicWireError("empty datagram")
    first = buf[off]
    if first & 0x80:
        raise QuicWireError("not a short header")
    p = off + 1
    if p + dcid_len > len(buf):
        raise QuicWireError("short header truncated")
    dcid = bytes(buf[p : p + dcid_len])
    return ShortHeader(dcid=dcid, hdr_end=p + dcid_len, first_byte=first)


def encode_short_header(dcid: bytes, pn: int, pn_len: int,
                        key_phase: int = 0) -> bytes:
    first = 0x40 | ((key_phase & 1) << 2) | (pn_len - 1)
    return bytes([first]) + dcid + pn.to_bytes(pn_len, "big")[-pn_len:]


def pn_decode(truncated: int, pn_len: int, largest_acked: int) -> int:
    """Recover a full packet number from its truncated encoding (§A.3)."""
    expected = largest_acked + 1
    win = 1 << (pn_len * 8)
    half = win // 2
    candidate = (expected & ~(win - 1)) | truncated
    if candidate <= expected - half and candidate + win < (1 << 62):
        return candidate + win
    if candidate > expected + half and candidate >= win:
        return candidate - win
    return candidate


# ---------------------------------------------------------------- frames ---

FRAME_PADDING = 0x00
FRAME_PING = 0x01
FRAME_ACK = 0x02  # 0x03 with ECN
FRAME_RESET_STREAM = 0x04
FRAME_STOP_SENDING = 0x05
FRAME_CRYPTO = 0x06
FRAME_NEW_TOKEN = 0x07
FRAME_STREAM_BASE = 0x08  # 0x08..0x0f, flags OFF=4 LEN=2 FIN=1
FRAME_MAX_DATA = 0x10
FRAME_MAX_STREAM_DATA = 0x11
FRAME_MAX_STREAMS_BIDI = 0x12
FRAME_MAX_STREAMS_UNI = 0x13
FRAME_DATA_BLOCKED = 0x14
FRAME_STREAM_DATA_BLOCKED = 0x15
FRAME_STREAMS_BLOCKED_BIDI = 0x16
FRAME_STREAMS_BLOCKED_UNI = 0x17
FRAME_NEW_CONNECTION_ID = 0x18
FRAME_RETIRE_CONNECTION_ID = 0x19
FRAME_PATH_CHALLENGE = 0x1A
FRAME_PATH_RESPONSE = 0x1B
FRAME_CONN_CLOSE_QUIC = 0x1C
FRAME_CONN_CLOSE_APP = 0x1D
FRAME_HANDSHAKE_DONE = 0x1E


@dataclass
class Frame:
    ftype: int
    fields: Dict[str, int] = field(default_factory=dict)
    data: bytes = b""
    # ACK only: list of (gap, range) pairs after the first range
    ack_ranges: List[Tuple[int, int]] = field(default_factory=list)


# field kinds: v = varint, b8 = 8-byte blob, b16 = 16-byte blob,
# lv = varint-length-prefixed bytes (-> .data), cid = u8-length-prefixed
# bytes (-> .data)
_FRAME_SPECS: Dict[int, List[Tuple[str, str]]] = {
    FRAME_PING: [],
    FRAME_RESET_STREAM: [
        ("stream_id", "v"), ("app_error", "v"), ("final_size", "v")],
    FRAME_STOP_SENDING: [("stream_id", "v"), ("app_error", "v")],
    FRAME_NEW_TOKEN: [("token", "lv")],
    FRAME_MAX_DATA: [("max_data", "v")],
    FRAME_MAX_STREAM_DATA: [("stream_id", "v"), ("max_stream_data", "v")],
    FRAME_MAX_STREAMS_BIDI: [("max_streams", "v")],
    FRAME_MAX_STREAMS_UNI: [("max_streams", "v")],
    FRAME_DATA_BLOCKED: [("limit", "v")],
    FRAME_STREAM_DATA_BLOCKED: [("stream_id", "v"), ("limit", "v")],
    FRAME_STREAMS_BLOCKED_BIDI: [("limit", "v")],
    FRAME_STREAMS_BLOCKED_UNI: [("limit", "v")],
    FRAME_RETIRE_CONNECTION_ID: [("seq", "v")],
    FRAME_PATH_CHALLENGE: [("data8", "b8")],
    FRAME_PATH_RESPONSE: [("data8", "b8")],
    FRAME_HANDSHAKE_DONE: [],
}


def parse_frames(buf: bytes) -> List[Frame]:
    """Parse a decrypted packet payload into frames."""
    frames: List[Frame] = []
    off = 0
    n = len(buf)
    while off < n:
        ftype = buf[off]
        off += 1
        if ftype == FRAME_PADDING:
            continue
        if ftype in (FRAME_ACK, FRAME_ACK | 1):
            f = Frame(ftype=FRAME_ACK)
            f.fields["largest"], off = varint_decode(buf, off)
            f.fields["ack_delay"], off = varint_decode(buf, off)
            cnt, off = varint_decode(buf, off)
            f.fields["first_range"], off = varint_decode(buf, off)
            for _ in range(cnt):
                gap, off = varint_decode(buf, off)
                rng, off = varint_decode(buf, off)
                f.ack_ranges.append((gap, rng))
            if ftype & 1:  # ECN counts, parsed and dropped
                for _ in range(3):
                    _, off = varint_decode(buf, off)
            frames.append(f)
            continue
        if ftype == FRAME_CRYPTO:
            f = Frame(ftype=FRAME_CRYPTO)
            f.fields["offset"], off = varint_decode(buf, off)
            ln, off = varint_decode(buf, off)
            if off + ln > n:
                raise QuicWireError("crypto frame truncated")
            f.data = bytes(buf[off : off + ln])
            off += ln
            frames.append(f)
            continue
        if FRAME_STREAM_BASE <= ftype <= FRAME_STREAM_BASE | 0x07:
            f = Frame(ftype=ftype)
            f.fields["stream_id"], off = varint_decode(buf, off)
            if ftype & 0x04:
                f.fields["offset"], off = varint_decode(buf, off)
            else:
                f.fields["offset"] = 0
            if ftype & 0x02:
                ln, off = varint_decode(buf, off)
            else:
                ln = n - off
            if off + ln > n:
                raise QuicWireError("stream frame truncated")
            f.fields["fin"] = ftype & 0x01
            f.data = bytes(buf[off : off + ln])
            off += ln
            frames.append(f)
            continue
        if ftype == FRAME_NEW_CONNECTION_ID:
            f = Frame(ftype=ftype)
            f.fields["seq"], off = varint_decode(buf, off)
            f.fields["retire_prior_to"], off = varint_decode(buf, off)
            if off >= n:
                # buf[off] past the end would IndexError out of the
                # parser — an UNTYPED escape the conn layer's
                # QuicWireError handler cannot catch (attacker-
                # controlled bytes must only ever produce typed rejects).
                raise QuicWireError("NEW_CONNECTION_ID truncated")
            cil = buf[off]
            off += 1
            if cil == 0 or cil > 20 or off + cil + 16 > n:
                raise QuicWireError("bad NEW_CONNECTION_ID")
            f.data = bytes(buf[off : off + cil])
            off += cil
            f.fields["reset_token"] = int.from_bytes(
                buf[off : off + 16], "big"
            )
            off += 16
            frames.append(f)
            continue
        if ftype in (FRAME_CONN_CLOSE_QUIC, FRAME_CONN_CLOSE_APP):
            f = Frame(ftype=ftype)
            f.fields["error"], off = varint_decode(buf, off)
            if ftype == FRAME_CONN_CLOSE_QUIC:
                f.fields["frame_type"], off = varint_decode(buf, off)
            ln, off = varint_decode(buf, off)
            if off + ln > n:
                raise QuicWireError("close frame truncated")
            f.data = bytes(buf[off : off + ln])
            off += ln
            frames.append(f)
            continue
        spec = _FRAME_SPECS.get(ftype)
        if spec is None:
            raise QuicWireError(f"unknown frame type 0x{ftype:02x}")
        f = Frame(ftype=ftype)
        for name, kind in spec:
            if kind == "v":
                f.fields[name], off = varint_decode(buf, off)
            elif kind == "b8":
                if off + 8 > n:
                    # int.from_bytes over a short slice would silently
                    # accept a truncated PATH_CHALLENGE/RESPONSE as a
                    # smaller integer — a typed reject, never laxity.
                    raise QuicWireError("frame 8-byte field truncated")
                f.fields[name] = int.from_bytes(buf[off : off + 8], "big")
                off += 8
            elif kind == "lv":
                ln, off = varint_decode(buf, off)
                if off + ln > n:
                    raise QuicWireError("frame blob truncated")
                f.data = bytes(buf[off : off + ln])
                off += ln
        frames.append(f)
    return frames


# ------------------------------------------------------ retry / reset ------

# RFC 9001 §5.8: fixed key/nonce protecting Retry packet integrity (v1).
RETRY_INTEGRITY_KEY = bytes.fromhex("be0c690b9f66575a1d766b54e368c84e")
RETRY_INTEGRITY_NONCE = bytes.fromhex("461599d35d632bf2239825bb")


_RETRY_AEAD = None


def _retry_tag(odcid: bytes, retry_sans_tag: bytes) -> bytes:
    """16-byte Retry Integrity Tag: AES-128-GCM over the empty string
    with the retry pseudo-packet (ODCID-prefixed packet) as AAD. The
    key is a fixed RFC 9001 §5.8 constant, so ONE cached cipher serves
    every packet — constructing it per Retry would re-pay key schedule
    + GHASH setup on the flood path this feature exists to cheapen."""
    global _RETRY_AEAD
    if _RETRY_AEAD is None:
        from firedancer_tpu.ballet.aes import AesGcm

        _RETRY_AEAD = AesGcm(RETRY_INTEGRITY_KEY)
    pseudo = bytes([len(odcid)]) + odcid + retry_sans_tag
    return _RETRY_AEAD.seal(RETRY_INTEGRITY_NONCE, b"", pseudo)


def encode_retry(dcid: bytes, scid: bytes, token: bytes,
                 odcid: bytes) -> bytes:
    """Server Retry packet (RFC 9000 §17.2.5): no packet number, no
    payload — just the token and the integrity tag binding it to the
    client's original DCID (so an off-path attacker cannot forge one
    without having seen the Initial)."""
    first = 0xC0 | (PKT_RETRY << 4)
    body = bytearray([first])
    body += QUIC_VERSION_1.to_bytes(4, "big")
    body += bytes([len(dcid)]) + dcid
    body += bytes([len(scid)]) + scid
    body += token
    return bytes(body) + _retry_tag(odcid, bytes(body))


def check_retry(datagram: bytes, odcid: bytes) -> Optional[bytes]:
    """Validate a Retry packet's integrity tag against the original DCID
    this client sent. -> the retry token, or None if invalid."""
    if len(datagram) < 23:  # header floor + 16-byte tag
        return None
    try:
        hdr = parse_long_header(datagram)
    except QuicWireError:
        return None
    if hdr.pkt_type != PKT_RETRY or hdr.version != QUIC_VERSION_1:
        return None
    token = datagram[hdr.hdr_end:-16]
    if not token:
        return None  # §17.2.5.1: a Retry MUST carry a non-empty token
    if _retry_tag(odcid, datagram[:-16]) != datagram[-16:]:
        return None
    return bytes(token)


def encode_stateless_reset(token16: bytes, size: int = 41) -> bytes:
    """Stateless Reset (RFC 9000 §10.3): indistinguishable from a short-
    header packet — fixed bit + unpredictable bytes, with the 16-byte
    reset token in the last 16 bytes. Minimum 21 bytes total."""
    import os as _os

    if len(token16) != 16:
        raise QuicWireError(
            f"stateless reset token must be 16 bytes, got {len(token16)}"
        )
    size = max(21, size)
    rand = bytearray(_os.urandom(size - 16))
    rand[0] = 0x40 | (rand[0] & 0x3F)
    return bytes(rand) + token16


def encode_path_frame(ftype: int, data8: bytes) -> bytes:
    """PATH_CHALLENGE / PATH_RESPONSE: type + 8 opaque bytes (RFC 9000
    §19.17-18)."""
    if ftype not in (FRAME_PATH_CHALLENGE, FRAME_PATH_RESPONSE):
        raise QuicWireError(f"not a path frame type: 0x{ftype:02x}")
    if len(data8) != 8:
        raise QuicWireError(
            f"path frame payload must be 8 bytes, got {len(data8)}"
        )
    return bytes([ftype]) + data8


def encode_ack(
    largest: int,
    ack_delay: int,
    first_range: int,
    ranges: List[Tuple[int, int]] = (),
) -> bytes:
    out = bytearray([FRAME_ACK])
    out += varint_encode(largest)
    out += varint_encode(ack_delay)
    out += varint_encode(len(ranges))
    out += varint_encode(first_range)
    for gap, rng in ranges:
        out += varint_encode(gap)
        out += varint_encode(rng)
    return bytes(out)


def encode_crypto(offset: int, data: bytes) -> bytes:
    return (
        bytes([FRAME_CRYPTO])
        + varint_encode(offset)
        + varint_encode(len(data))
        + data
    )


def encode_stream(
    stream_id: int, offset: int, data: bytes, fin: bool
) -> bytes:
    ftype = FRAME_STREAM_BASE | 0x02 | (0x04 if offset else 0) | int(fin)
    out = bytearray([ftype])
    out += varint_encode(stream_id)
    if offset:
        out += varint_encode(offset)
    out += varint_encode(len(data))
    out += data
    return bytes(out)


def encode_simple(ftype: int, *varints: int) -> bytes:
    out = bytearray([ftype])
    for v in varints:
        out += varint_encode(v)
    return bytes(out)


def encode_conn_close(
    error: int, frame_type: int, reason: bytes = b"", app: bool = False
) -> bytes:
    out = bytearray([FRAME_CONN_CLOSE_APP if app else FRAME_CONN_CLOSE_QUIC])
    out += varint_encode(error)
    if not app:
        out += varint_encode(frame_type)
    out += varint_encode(len(reason))
    out += reason
    return bytes(out)
