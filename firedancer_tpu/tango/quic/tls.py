"""TLS 1.3 handshake engine over QUIC CRYPTO streams (RFC 8446 + RFC 9001).

Role parity with /root/reference/src/tango/quic/tls/fd_quic_tls.{h,c}: the
reference wraps a quictls/OpenSSL QUIC-TLS integration (fd_quic_tls.h:14-17);
here the handshake is implemented from scratch on ballet primitives
(x25519 key exchange, HKDF key schedule, Ed25519 CertificateVerify over the
ballet x509 self-signed cert). Scope: TLS_AES_128_GCM_SHA256, x25519,
Ed25519 certs, ALPN, quic_transport_parameters — exactly the profile the
Solana TPU uses. No session resumption / 0-RTT / HelloRetryRequest.

The QUIC layer talks to this through three hooks, mirroring the reference's
callback struct (fd_quic_tls.h client_hello/alert/secret/handshake_complete):
`take_output()` drains (level, bytes) to send as CRYPTO frames, `consume()`
feeds reassembled peer CRYPTO bytes, and key events appear as attributes
(hs_secrets, app_secrets) the conn promotes into PacketKeys.
"""

from __future__ import annotations

import hashlib
import os
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from firedancer_tpu.ballet.ed25519 import oracle
from firedancer_tpu.ballet.ed25519.x25519 import x25519, x25519_public
from firedancer_tpu.ballet.hkdf import hkdf_expand_label, hkdf_extract
from firedancer_tpu.ballet.hmac import hmac_sha256
from firedancer_tpu.ballet import x509


def _ed_verify(msg: bytes, sig: bytes, pub: bytes) -> int:
    """Ed25519 verify via the native backend when built (bit-exact vs
    the oracle — differentially pinned), else the Python oracle: the
    CertificateVerify check is on the per-connection handshake path."""
    from firedancer_tpu.ballet.ed25519 import native

    if native.available():
        try:
            return native.verify(msg, sig, pub)
        except Exception:
            pass
    return oracle.verify(msg, sig, pub)

# encryption levels (== reference's fd_quic_crypto enc levels)
LEVEL_INITIAL = 0
LEVEL_HANDSHAKE = 1
LEVEL_APP = 2

# handshake message types
HS_CLIENT_HELLO = 1
HS_SERVER_HELLO = 2
HS_NEW_SESSION_TICKET = 4
HS_ENCRYPTED_EXTENSIONS = 8
HS_CERTIFICATE = 11
HS_CERTIFICATE_VERIFY = 15
HS_FINISHED = 20

# extensions
EXT_SERVER_NAME = 0
EXT_SUPPORTED_GROUPS = 10
EXT_SIGNATURE_ALGORITHMS = 13
EXT_ALPN = 16
EXT_SUPPORTED_VERSIONS = 43
EXT_KEY_SHARE = 51
EXT_QUIC_TRANSPORT_PARAMS = 0x39

CIPHER_AES128_GCM_SHA256 = 0x1301
GROUP_X25519 = 0x001D
SIGALG_ED25519 = 0x0807
TLS13 = 0x0304


class TlsError(ValueError):
    pass


def _u16(v: int) -> bytes:
    return struct.pack(">H", v)


def _u24(v: int) -> bytes:
    return v.to_bytes(3, "big")


def _hs_msg(mtype: int, body: bytes) -> bytes:
    return bytes([mtype]) + _u24(len(body)) + body


def _ext(etype: int, body: bytes) -> bytes:
    return _u16(etype) + _u16(len(body)) + body


def _derive_secret(secret: bytes, label: bytes, transcript_hash: bytes) -> bytes:
    return hkdf_expand_label(secret, label, transcript_hash, 32)


_CV_SERVER_CTX = b" " * 64 + b"TLS 1.3, server CertificateVerify" + b"\x00"
_CV_CLIENT_CTX = b" " * 64 + b"TLS 1.3, client CertificateVerify" + b"\x00"


@dataclass
class TlsConfig:
    is_server: bool
    identity_seed: bytes  # Ed25519 seed; cert is generated from it
    alpns: Tuple[bytes, ...] = (b"solana-tpu",)
    transport_params: bytes = b""
    server_name: Optional[str] = None
    cert_der: Optional[bytes] = None  # override the generated cert


class TlsEndpoint:
    """One endpoint of a TLS 1.3 handshake carried over CRYPTO frames."""

    def __init__(self, cfg: TlsConfig):
        self.cfg = cfg
        self.is_server = cfg.is_server
        self._out: List[Tuple[int, bytes]] = []
        self._rx_buf: Dict[int, bytearray] = {
            LEVEL_INITIAL: bytearray(),
            LEVEL_HANDSHAKE: bytearray(),
            LEVEL_APP: bytearray(),
        }
        self._transcript = hashlib.sha256()
        self._ecdh_priv = os.urandom(32)
        self._cert = cfg.cert_der or x509.generate_self_signed(
            cfg.identity_seed, cn="firedancer-tpu"
        )
        # outputs / events
        self.alpn: Optional[bytes] = None
        self.peer_transport_params: Optional[bytes] = None
        self.peer_pubkey: Optional[bytes] = None
        self.hs_secrets: Optional[Tuple[bytes, bytes]] = None  # (client, server)
        self.app_secrets: Optional[Tuple[bytes, bytes]] = None
        self.handshake_complete = False
        self.alert: Optional[str] = None
        # internals
        self._hs_secret: Optional[bytes] = None
        self._master: Optional[bytes] = None
        self._client_hs: Optional[bytes] = None
        self._server_hs: Optional[bytes] = None
        self._th_to_cert: Optional[bytes] = None
        self._th_to_cv: Optional[bytes] = None
        self._th_to_server_fin: Optional[bytes] = None
        self._state = "start"
        self._client_random = os.urandom(32)

    # ------------------------------------------------------------- output --

    def take_output(self) -> List[Tuple[int, bytes]]:
        out, self._out = self._out, []
        return out

    def _send(self, level: int, msg: bytes) -> None:
        self._transcript.update(msg)
        self._out.append((level, msg))

    # -------------------------------------------------------------- start --

    def start(self) -> None:
        """Client: emit the ClientHello."""
        if self.is_server:
            return
        exts = b"".join(
            [
                _ext(
                    EXT_SUPPORTED_VERSIONS, bytes([2]) + _u16(TLS13)
                ),
                _ext(
                    EXT_SUPPORTED_GROUPS, _u16(2) + _u16(GROUP_X25519)
                ),
                _ext(
                    EXT_SIGNATURE_ALGORITHMS, _u16(2) + _u16(SIGALG_ED25519)
                ),
                _ext(
                    EXT_KEY_SHARE,
                    _u16(2 + 2 + 32)
                    + _u16(GROUP_X25519)
                    + _u16(32)
                    + x25519_public(self._ecdh_priv),
                ),
                _ext(
                    EXT_ALPN,
                    _u16(sum(1 + len(a) for a in self.cfg.alpns))
                    + b"".join(
                        bytes([len(a)]) + a for a in self.cfg.alpns
                    ),
                ),
                _ext(EXT_QUIC_TRANSPORT_PARAMS, self.cfg.transport_params),
            ]
        )
        if self.cfg.server_name:
            sn = self.cfg.server_name.encode()
            exts += _ext(
                EXT_SERVER_NAME,
                _u16(len(sn) + 3) + b"\x00" + _u16(len(sn)) + sn,
            )
        body = (
            _u16(0x0303)
            + self._client_random
            + b"\x00"  # empty legacy session id (QUIC)
            + _u16(2)
            + _u16(CIPHER_AES128_GCM_SHA256)
            + b"\x01\x00"  # null compression
            + _u16(len(exts))
            + exts
        )
        self._send(LEVEL_INITIAL, _hs_msg(HS_CLIENT_HELLO, body))
        self._state = "wait_sh"

    # -------------------------------------------------------------- input --

    def consume(self, level: int, data: bytes) -> None:
        """Feed reassembled CRYPTO-stream bytes received at `level`."""
        buf = self._rx_buf[level]
        buf += data
        while len(buf) >= 4:
            mlen = int.from_bytes(buf[1:4], "big")
            if len(buf) < 4 + mlen:
                break
            msg = bytes(buf[: 4 + mlen])
            del buf[: 4 + mlen]
            self._on_message(level, msg[0], msg)

    def _on_message(self, level: int, mtype: int, msg: bytes) -> None:
        if self.is_server:
            if mtype == HS_CLIENT_HELLO and self._state == "start":
                self._server_on_client_hello(msg)
            elif mtype == HS_FINISHED and self._state == "wait_client_fin":
                self._on_peer_finished(msg, self._client_hs)
                self.handshake_complete = True
                self._state = "done"
            else:
                raise TlsError(
                    f"server: unexpected msg {mtype} in {self._state}"
                )
        else:
            if mtype == HS_SERVER_HELLO and self._state == "wait_sh":
                self._client_on_server_hello(msg)
            elif mtype == HS_ENCRYPTED_EXTENSIONS and self._state == "wait_ee":
                self._parse_enc_exts(msg)
                self._transcript.update(msg)
                self._state = "wait_cert"
            elif mtype == HS_CERTIFICATE and self._state == "wait_cert":
                self._th_to_cert = self._pre_update_hash(msg)
                self._parse_certificate(msg)
                self._state = "wait_cv"
            elif mtype == HS_CERTIFICATE_VERIFY and self._state == "wait_cv":
                self._verify_cert_verify(msg)
                self._state = "wait_fin"
            elif mtype == HS_FINISHED and self._state == "wait_fin":
                self._on_peer_finished(msg, self._server_hs)
                self._client_finish()
            elif mtype == HS_NEW_SESSION_TICKET:
                pass  # resumption not supported; ignore
            else:
                raise TlsError(
                    f"client: unexpected msg {mtype} in {self._state}"
                )

    def _pre_update_hash(self, msg: bytes) -> bytes:
        """Transcript hash *before* absorbing msg, then absorb it."""
        th = self._transcript.digest()
        self._transcript.update(msg)
        return th

    # ------------------------------------------------------------- server --

    def _server_on_client_hello(self, msg: bytes) -> None:
        self._transcript.update(msg)
        body = msg[4:]
        off = 2 + 32  # legacy_version + random
        sid_len = body[off]
        self._session_id = body[off + 1 : off + 1 + sid_len]
        off += 1 + sid_len
        cs_len = struct.unpack(">H", body[off : off + 2])[0]
        suites = body[off + 2 : off + 2 + cs_len]
        off += 2 + cs_len
        comp_len = body[off]
        off += 1 + comp_len
        if len(body) < off + 2:
            raise TlsError("CH: no extensions")
        ext_len = struct.unpack(">H", body[off : off + 2])[0]
        exts = self._parse_exts(body[off + 2 : off + 2 + ext_len])
        if not any(
            struct.unpack(">H", suites[i : i + 2])[0]
            == CIPHER_AES128_GCM_SHA256
            for i in range(0, len(suites), 2)
        ):
            raise TlsError("CH: no common cipher suite")
        sv = exts.get(EXT_SUPPORTED_VERSIONS)
        if sv is None or TLS13.to_bytes(2, "big") not in bytes(sv):
            raise TlsError("CH: TLS 1.3 not offered")
        ks = exts.get(EXT_KEY_SHARE)
        peer_share = self._find_key_share_ch(ks)
        if peer_share is None:
            raise TlsError("CH: no x25519 key share")
        alpn_ext = exts.get(EXT_ALPN)
        if alpn_ext is not None:
            offered = self._parse_alpn(alpn_ext)
            for a in self.cfg.alpns:
                if a in offered:
                    self.alpn = a
                    break
            if self.alpn is None:
                raise TlsError("CH: no common ALPN")
        tp = exts.get(EXT_QUIC_TRANSPORT_PARAMS)
        if tp is None:
            raise TlsError("CH: missing quic transport params")
        self.peer_transport_params = bytes(tp)

        shared = x25519(self._ecdh_priv, peer_share)
        sh_exts = _ext(
            EXT_SUPPORTED_VERSIONS, _u16(TLS13)
        ) + _ext(
            EXT_KEY_SHARE,
            _u16(GROUP_X25519) + _u16(32) + x25519_public(self._ecdh_priv),
        )
        sh_body = (
            _u16(0x0303)
            + os.urandom(32)
            + bytes([len(self._session_id)])
            + bytes(self._session_id)
            + _u16(CIPHER_AES128_GCM_SHA256)
            + b"\x00"
            + _u16(len(sh_exts))
            + sh_exts
        )
        self._send(LEVEL_INITIAL, _hs_msg(HS_SERVER_HELLO, sh_body))
        self._compute_hs_secrets(shared)

        # EncryptedExtensions
        ee = _ext(EXT_QUIC_TRANSPORT_PARAMS, self.cfg.transport_params)
        if self.alpn is not None:
            ee += _ext(
                EXT_ALPN,
                _u16(1 + len(self.alpn))
                + bytes([len(self.alpn)])
                + self.alpn,
            )
        self._send(
            LEVEL_HANDSHAKE, _hs_msg(HS_ENCRYPTED_EXTENSIONS, _u16(len(ee)) + ee)
        )
        # Certificate
        entry = _u24(len(self._cert)) + self._cert + _u16(0)
        cert_body = b"\x00" + _u24(len(entry)) + entry
        self._send(LEVEL_HANDSHAKE, _hs_msg(HS_CERTIFICATE, cert_body))
        # CertificateVerify over transcript-to-here. Sign via the
        # native ed25519 backend when built (bit-exact vs the oracle;
        # ballet/x509._ed_sign) — the Python oracle's ~180 ms here was
        # a dominant term of the handshake rate the fd_siege
        # connection-churn profile measures.
        th = self._transcript.digest()
        sig = x509._ed_sign(_CV_SERVER_CTX + th, self.cfg.identity_seed)
        cv_body = _u16(SIGALG_ED25519) + _u16(len(sig)) + sig
        self._send(LEVEL_HANDSHAKE, _hs_msg(HS_CERTIFICATE_VERIFY, cv_body))
        # Finished
        fin_key = hkdf_expand_label(self._server_hs, b"finished", b"", 32)
        verify = hmac_sha256(fin_key, self._transcript.digest())
        self._send(LEVEL_HANDSHAKE, _hs_msg(HS_FINISHED, verify))
        # app secrets from transcript through server Finished
        self._th_to_server_fin = self._transcript.digest()
        self._compute_app_secrets()
        self._state = "wait_client_fin"

    # ------------------------------------------------------------- client --

    def _client_on_server_hello(self, msg: bytes) -> None:
        self._transcript.update(msg)
        body = msg[4:]
        off = 2 + 32
        sid_len = body[off]
        off += 1 + sid_len
        cipher = struct.unpack(">H", body[off : off + 2])[0]
        if cipher != CIPHER_AES128_GCM_SHA256:
            raise TlsError("SH: unexpected cipher")
        off += 3  # cipher + null compression
        ext_len = struct.unpack(">H", body[off : off + 2])[0]
        exts = self._parse_exts(body[off + 2 : off + 2 + ext_len])
        ks = exts.get(EXT_KEY_SHARE)
        if ks is None:
            raise TlsError("SH: no key share")
        group = struct.unpack(">H", ks[:2])[0]
        klen = struct.unpack(">H", ks[2:4])[0]
        if group != GROUP_X25519 or klen != 32:
            raise TlsError("SH: unsupported group")
        shared = x25519(self._ecdh_priv, bytes(ks[4:36]))
        self._compute_hs_secrets(shared)
        self._state = "wait_ee"

    def _parse_enc_exts(self, msg: bytes) -> None:
        body = msg[4:]
        ext_len = struct.unpack(">H", body[:2])[0]
        exts = self._parse_exts(body[2 : 2 + ext_len])
        tp = exts.get(EXT_QUIC_TRANSPORT_PARAMS)
        if tp is None:
            raise TlsError("EE: missing quic transport params")
        self.peer_transport_params = bytes(tp)
        alpn_ext = exts.get(EXT_ALPN)
        if alpn_ext is not None:
            chosen = self._parse_alpn(alpn_ext)
            if len(chosen) != 1 or chosen[0] not in self.cfg.alpns:
                raise TlsError("EE: bad ALPN selection")
            self.alpn = chosen[0]

    def _parse_certificate(self, msg: bytes) -> None:
        body = msg[4:]
        ctx_len = body[0]
        off = 1 + ctx_len
        list_len = int.from_bytes(body[off : off + 3], "big")
        off += 3
        if list_len == 0:
            raise TlsError("cert: empty certificate list")
        cert_len = int.from_bytes(body[off : off + 3], "big")
        off += 3
        cert = bytes(body[off : off + cert_len])
        self.peer_pubkey = x509.extract_ed25519_pubkey(cert)

    def _verify_cert_verify(self, msg: bytes) -> None:
        th = self._pre_update_hash(msg)
        body = msg[4:]
        alg = struct.unpack(">H", body[:2])[0]
        if alg != SIGALG_ED25519:
            raise TlsError("CV: unsupported sig alg")
        slen = struct.unpack(">H", body[2:4])[0]
        sig = bytes(body[4 : 4 + slen])
        ctx = _CV_CLIENT_CTX if self.is_server else _CV_SERVER_CTX
        if _ed_verify(ctx + th, sig, self.peer_pubkey) != 0:
            raise TlsError("CV: signature verification failed")

    def _client_finish(self) -> None:
        self._th_to_server_fin = self._transcript.digest()
        self._compute_app_secrets()
        fin_key = hkdf_expand_label(self._client_hs, b"finished", b"", 32)
        verify = hmac_sha256(fin_key, self._th_to_server_fin)
        self._send(LEVEL_HANDSHAKE, _hs_msg(HS_FINISHED, verify))
        self.handshake_complete = True
        self._state = "done"

    # -------------------------------------------------------------- common --

    def _on_peer_finished(self, msg: bytes, peer_hs_secret: bytes) -> None:
        th = self._pre_update_hash(msg)
        fin_key = hkdf_expand_label(peer_hs_secret, b"finished", b"", 32)
        expect = hmac_sha256(fin_key, th)
        if expect != msg[4:]:
            raise TlsError("finished: verify_data mismatch")

    def _compute_hs_secrets(self, ecdh_shared: bytes) -> None:
        empty_hash = hashlib.sha256(b"").digest()
        early = hkdf_extract(bytes(32), bytes(32))
        derived = _derive_secret(early, b"derived", empty_hash)
        self._hs_secret = hkdf_extract(derived, ecdh_shared)
        th = self._transcript.digest()  # through ServerHello
        self._client_hs = _derive_secret(self._hs_secret, b"c hs traffic", th)
        self._server_hs = _derive_secret(self._hs_secret, b"s hs traffic", th)
        self.hs_secrets = (self._client_hs, self._server_hs)

    def _compute_app_secrets(self) -> None:
        empty_hash = hashlib.sha256(b"").digest()
        derived = _derive_secret(self._hs_secret, b"derived", empty_hash)
        self._master = hkdf_extract(derived, bytes(32))
        th = self._th_to_server_fin
        c_ap = _derive_secret(self._master, b"c ap traffic", th)
        s_ap = _derive_secret(self._master, b"s ap traffic", th)
        self.app_secrets = (c_ap, s_ap)

    # ------------------------------------------------------------- helpers --

    @staticmethod
    def _parse_exts(buf: bytes) -> Dict[int, bytes]:
        exts: Dict[int, bytes] = {}
        off = 0
        while off + 4 <= len(buf):
            etype, elen = struct.unpack(">HH", buf[off : off + 4])
            exts[etype] = buf[off + 4 : off + 4 + elen]
            off += 4 + elen
        return exts

    @staticmethod
    def _find_key_share_ch(ks: Optional[bytes]) -> Optional[bytes]:
        if ks is None or len(ks) < 2:
            return None
        total = struct.unpack(">H", ks[:2])[0]
        off = 2
        end = min(2 + total, len(ks))
        while off + 4 <= end:
            group, klen = struct.unpack(">HH", ks[off : off + 4])
            if group == GROUP_X25519 and klen == 32:
                return bytes(ks[off + 4 : off + 36])
            off += 4 + klen
        return None

    @staticmethod
    def _parse_alpn(ext: bytes) -> List[bytes]:
        if len(ext) < 2:
            return []
        total = struct.unpack(">H", ext[:2])[0]
        out = []
        off = 2
        end = min(2 + total, len(ext))
        while off < end:
            ln = ext[off]
            out.append(bytes(ext[off + 1 : off + 1 + ln]))
            off += 1 + ln
        return out
