"""QUIC packet protection (RFC 9001): key derivation, AEAD, header protection.

Role parity with /root/reference/src/tango/quic/crypto/
fd_quic_crypto_suites.{h,c} (suite TLS_AES_128_GCM_SHA256, fd_quic_gen_keys,
fd_quic_crypto_encrypt/decrypt, header-protection masking), built on the
ballet AES/HKDF primitives instead of OpenSSL EVP.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional

from firedancer_tpu.ballet.aes import Aes, AesGcm
from firedancer_tpu.ballet.hkdf import hkdf_expand_label, hkdf_extract

# RFC 9001 §5.2 initial salt for QUIC v1
INITIAL_SALT_V1 = bytes.fromhex("38762cf7f55934b34d179ae6a4c80cadccbb7f0a")

AEAD_OVERHEAD = 16  # GCM tag


class QuicCryptoError(ValueError):
    pass


@dataclass
class PacketKeys:
    """One direction's packet-protection keys for one encryption level."""

    secret: bytes
    key: bytes
    iv: bytes
    hp: bytes

    @classmethod
    def from_secret(cls, secret: bytes) -> "PacketKeys":
        return cls(
            secret=secret,
            key=hkdf_expand_label(secret, b"quic key", b"", 16),
            iv=hkdf_expand_label(secret, b"quic iv", b"", 12),
            hp=hkdf_expand_label(secret, b"quic hp", b"", 16),
        )

    def next_generation(self) -> "PacketKeys":
        """Key update (RFC 9001 §6): new secret via "quic ku".

        The header-protection key is NOT updated (§6.1: "The header
        protection key is not updated") — only the packet protection
        key and IV rotate.
        """
        nxt = hkdf_expand_label(self.secret, b"quic ku", b"", 32)
        return PacketKeys(
            secret=nxt,
            key=hkdf_expand_label(nxt, b"quic key", b"", 16),
            iv=hkdf_expand_label(nxt, b"quic iv", b"", 12),
            hp=self.hp,
        )

    def _nonce(self, pn: int) -> bytes:
        pad = bytes(len(self.iv) - 8) + struct.pack(">Q", pn)
        return bytes(a ^ b for a, b in zip(self.iv, pad))

    # The AEAD/HP cipher objects are cached PER KEY, not built per
    # packet: constructing an AesGcm costs a key schedule + GHASH table
    # (milliseconds in the Python fallback), and keys live for millions
    # of packets — per-packet construction capped the whole QUIC tile
    # at ~10^2 datagrams/s.
    def _gcm(self) -> AesGcm:
        g = self.__dict__.get("_gcm_obj")
        if g is None:
            g = self.__dict__["_gcm_obj"] = AesGcm(self.key)
        return g

    def _hp_aes(self) -> Aes:
        a = self.__dict__.get("_hp_obj")
        if a is None:
            a = self.__dict__["_hp_obj"] = Aes(self.hp)
        return a

    def seal(self, header: bytes, pn: int, payload: bytes) -> bytes:
        return self._gcm().seal(self._nonce(pn), payload, header)

    def open(self, header: bytes, pn: int, sealed: bytes) -> bytes:
        try:
            return self._gcm().open(self._nonce(pn), sealed, header)
        except ValueError as e:
            raise QuicCryptoError(str(e)) from e

    def hp_mask(self, sample: bytes) -> bytes:
        return self._hp_aes().encrypt_block(sample)[:5]


def initial_secrets(dcid: bytes) -> tuple:
    """-> (client PacketKeys, server PacketKeys) for the Initial space."""
    initial = hkdf_extract(INITIAL_SALT_V1, dcid)
    client = hkdf_expand_label(initial, b"client in", b"", 32)
    server = hkdf_expand_label(initial, b"server in", b"", 32)
    return PacketKeys.from_secret(client), PacketKeys.from_secret(server)


def protect_packet(
    keys: PacketKeys, header: bytes, pn: int, pn_len: int, payload: bytes
) -> bytes:
    """AEAD-seal payload and apply header protection. `header` includes the
    unprotected packet-number bytes at its tail."""
    sealed = keys.seal(header, pn, payload)
    pkt = bytearray(header + sealed)
    pn_off = len(header) - pn_len
    sample = bytes(pkt[pn_off + 4 : pn_off + 20])
    mask = keys.hp_mask(sample)
    if pkt[0] & 0x80:
        pkt[0] ^= mask[0] & 0x0F
    else:
        pkt[0] ^= mask[0] & 0x1F
    for i in range(pn_len):
        pkt[pn_off + i] ^= mask[1 + i]
    return bytes(pkt)


def unprotect_header(
    keys: PacketKeys, pkt: bytearray, pn_off: int
) -> tuple:
    """Remove header protection in place. -> (pn_len, truncated_pn)."""
    if pn_off + 20 > len(pkt):
        raise QuicCryptoError("packet too short for hp sample")
    sample = bytes(pkt[pn_off + 4 : pn_off + 20])
    mask = keys.hp_mask(sample)
    if pkt[0] & 0x80:
        pkt[0] ^= mask[0] & 0x0F
    else:
        pkt[0] ^= mask[0] & 0x1F
    pn_len = (pkt[0] & 0x03) + 1
    tpn = 0
    for i in range(pn_len):
        pkt[pn_off + i] ^= mask[1 + i]
        tpn = (tpn << 8) | pkt[pn_off + i]
    return pn_len, tpn
