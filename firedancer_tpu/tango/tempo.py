"""tempo — clock models and housekeeping pacing.

Role parity with the reference's fd_tempo
(/root/reference/src/tango/tempo/fd_tempo.h): tickcount<->wallclock
calibration, the `lazy` default housekeeping interval as a function of
ring depth, and jittered async timers so a fleet of tiles doesn't
heartbeat in lockstep (thundering-herd avoidance).

Python's clocks: time.perf_counter_ns is the invariant tickcount analog,
time.time_ns the wallclock.
"""

from __future__ import annotations

import time

from firedancer_tpu.utils.rng import Rng


def tickcount() -> int:
    return time.perf_counter_ns()


def wallclock() -> int:
    return time.time_ns()


def lazy_default(depth: int) -> int:
    """Default housekeeping interval in ns for a ring of `depth` frags
    (fd_tempo_lazy_default shape: ~depth microseconds / 9, clamped) —
    frequent enough that a consumer lapping the ring is detected, rare
    enough to stay off the hot path."""
    lazy = (int(depth) * 1000) // 9
    return max(1_000, min(lazy, 1_000_000_000))


def async_min(lazy: int) -> int:
    """Largest power of 2 <= max(1, lazy/2): the minimum async interval
    such that jittered reloads average near `lazy`."""
    m = max(1, lazy // 2)
    return 1 << (m.bit_length() - 1)


def async_reload(rng: Rng, amin: int) -> int:
    """Uniform in [amin, 2*amin): the jittered next-housekeeping delta."""
    return amin + rng.roll(amin)


class Clock:
    """Tick->wallclock affine model (fd_tempo_observe/ns_per_tick analog).

    For Python both clocks are ns already, but the model keeps the
    calibration discipline (and absorbs perf_counter's arbitrary epoch).
    """

    def __init__(self) -> None:
        self.recalibrate()

    def recalibrate(self) -> None:
        t0 = tickcount()
        w0 = wallclock()
        t1 = tickcount()
        self._tick0 = (t0 + t1) // 2
        self._wall0 = w0

    def wall_from_tick(self, tick: int) -> int:
        return self._wall0 + (tick - self._tick0)

    def now(self) -> int:
        return self.wall_from_tick(tickcount())
