"""fd_engine — verify-graph engine registry + latency-adaptive rung
scheduler (ROADMAP direction 3: continuous batching).

Two halves, both pure host-side (stdlib + the flight/msm_plan helpers;
jax is imported lazily only when a device graph is actually built, so
disco/tiles.py's jax-import-free contract for host-backend tiles
holds):

  REGISTRY   every verify graph is a typed, cached EngineEntry keyed by
             the flight ``engine_key`` (mode x B x shards x frontend).
             The entry carries the built async verify callable (and the
             per-lane fallback graph for rlc), its prewarm state
             (cold/warming/warm/failed), the measured compile cost
             (booked through flight.record_compile — the same per-engine
             compile accounting fd_flight introduced), the analytic
             fill-efficiency / executed-madds cost from msm_plan, and a
             measured service-time EMA. Before fd_engine this dispatch
             logic was smeared across disco/tiles.py (VerifyTile's
             backend=='tpu' branch), ops/backend.py
             (default_verify_mode) and bench.py (the worker's
             jit+rlc-wrap block); all three now resolve through the
             registry, so the compile-cache-hit accounting between
             bench workers and VerifyTile prewarm comes from ONE
             heuristic instead of three hand-rolled copies.
             ``prewarm_ladder`` warms the configured rung ladder on a
             background thread (FD_ENGINE_PREWARM policy) so a tile can
             switch rungs without paying a mid-run compile.

  SCHEDULER  RungScheduler promotes AdaptiveFlush (disco/feed/policy.py)
             into an ONLINE continuous-batching scheduler, inference-
             serving style: pick the dispatch B from the FD_ENGINE_LADDER
             rung ladder using queue depth (staged lanes + ring
             backlog), deadline slack, and each rung's registry-attached
             cost model. Low offered load takes the small-rung latency
             (the batch "fills" at the small rung and ships early);
             saturation takes the big-rung throughput (fill efficiency
             is monotone in B — msm_plan, BENCH r05: 0.63 -> 0.76 from
             8k to 32k). Pure decision logic, AdaptiveFlush pattern:
             the caller passes now_ns, no clock reads, so the policy is
             property-testable without a device — the deadline
             invariant (a partial batch is never starved past the
             deadline) is inherited verbatim because the flush verdict
             still comes from the embedded AdaptiveFlush, just with the
             chosen rung as the batch bound.

Thread discipline (docs/OWNERSHIP.md, fdlint pass 6): the registry's
entry map is lock-guarded; per-entry builds/warms serialize on the
entry's own build lock (never the registry lock — compiles take
minutes); the prewarm thread only calls the same lock-guarded acquire
path. A RungScheduler instance is single-threaded by contract (the
feed stager owns the tile's instance).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional, Tuple

from firedancer_tpu import flags, msm_plan
from firedancer_tpu.disco import flight
from firedancer_tpu.disco.feed.policy import AdaptiveFlush

# EngineEntry prewarm states.
ENGINE_COLD = "cold"         # record exists; no graph built yet
ENGINE_WARMING = "warming"   # a warm pass (compile) is in flight
ENGINE_WARM = "warm"         # compiled + warmed: dispatchable now
ENGINE_FAILED = "failed"     # last warm attempt raised (err recorded)

# Host engines (no device graph to compile): the registry still tracks
# them so every dispatch site keys its accounting the same way.
_HOST_MODES = ("cpu", "oracle")


def current_frontend() -> str:
    """The frontend half of the engine key (FD_FRONTEND_IMPL)."""
    return flags.get_str("FD_FRONTEND_IMPL") or "auto"


@dataclass(frozen=True)
class EngineSpec:
    """The typed engine identity behind flight.engine_key — mode x B x
    shards x frontend x msm plan. Hashable, so it is also the registry
    map key. ``msm`` is the fd_msm2 schedule token ("auto" = resolve
    active_plan() from the FD_MSM_* flags at build time); a pinned
    token forces that exact schedule into the built verify graph, so
    two engines at the same rung but different MSM plans are DISTINCT
    registry entries with separate compile/service accounting."""

    mode: str            # rlc | direct (device) or cpu | oracle (host)
    batch: int
    shards: int = 0      # mesh_devices of the sharded verify step
    frontend: str = "auto"
    msm: str = "auto"    # fd_msm2 plan token (msm_plan.parse_plan)

    @property
    def key(self) -> str:
        return flight.engine_key(self.mode, self.batch, self.shards,
                                 self.frontend, self.msm)

    def with_batch(self, batch: int) -> "EngineSpec":
        return replace(self, batch=batch)

    def with_msm(self, msm: str) -> "EngineSpec":
        return replace(self, msm=msm)

    def resolved_msm(self) -> str:
        """The plan token this spec's graph would bake in NOW: the
        pinned token verbatim, else the FD_MSM_* flag resolution
        (msm_plan.plan_from_flags — jax-free, so host-side registry
        bookkeeping can call this; meaningful for rlc engines only,
        direct/host engines run no Pippenger MSM)."""
        if self.msm != "auto":
            return self.msm
        return msm_plan.plan_token(msm_plan.plan_from_flags())

    @classmethod
    def for_tile(cls, backend: str, verify_mode: str, batch: int,
                 mesh_devices: int) -> "EngineSpec":
        """The spec a VerifyTile's dispatches are keyed by: device
        backends key on the resolved verify mode, host backends on the
        backend name (the long-standing engine_key convention). The
        msm field comes from the registry's per-rung plan table
        (msm_search winners), falling back to "auto" (the FD_MSM_*
        flags) for rungs no search has certified."""
        mode = verify_mode if backend == "tpu" else backend
        msm = "auto"
        if mode == "rlc":
            msm = registry().rung_plan(batch)
        return cls(mode, batch, mesh_devices, current_frontend(), msm)


def parse_key(key: str) -> EngineSpec:
    """Inverse of EngineSpec.key
    ("mode:B<batch>:shards<n>:fe<impl>[:msm<plan>]") for
    artifact/readback tooling; raises ValueError on junk. The msm
    segment is optional — every pre-fd_msm2 key parses to msm="auto",
    so old artifacts keep round-tripping."""
    parts = key.split(":")
    if (len(parts) not in (4, 5) or not parts[1].startswith("B")
            or not parts[2].startswith("shards")
            or not parts[3].startswith("fe")):
        raise ValueError(f"not an engine key: {key!r}")
    msm = "auto"
    if len(parts) == 5:
        if not parts[4].startswith("msm") or len(parts[4]) <= 3:
            raise ValueError(f"not an engine key: {key!r}")
        msm = parts[4][3:]
    return EngineSpec(parts[0], int(parts[1][1:]), int(parts[2][6:]),
                      parts[3][2:], msm)


# --------------------------------------------------------------------------
# Mode resolution — moved here from disco/tiles.py + ops/backend.py so
# ONE module owns every engine-resolution decision (the dispatch sites
# are registry lookups).
# --------------------------------------------------------------------------


def drain_mode() -> str:
    """FD_DRAIN resolution: 'auto' arms the device-resident post-verify
    drain (dedup pre-filter + optional pack coloring fused behind
    verify) wherever the substrate supports it — the fd_feed staging
    path plus the ctl-carrying bulk publisher
    (tango.rings.frag_publish_has_ctl); anywhere else it degrades to
    exactly the 'off' behavior, never to an error. 'off' disables the
    drain stage outright (the A/B and bisection hatch). An unknown
    value raises — a typo'd force must never masquerade as a
    measurement of either arm."""
    mode = flags.get_str("FD_DRAIN") or "auto"
    if mode not in ("auto", "off"):
        raise ValueError(f"unknown FD_DRAIN {mode!r} (want auto|off)")
    return mode


def default_verify_mode() -> str:
    """Verify-tile mode when the config says 'auto' (round-6 RLC
    promotion): 'rlc' — batch RLC verification over the VMEM Pallas
    Pippenger MSM (ops/verify_rlc.py) — on TPU platforms; 'direct'
    per-lane on host-jax backends (no VMEM engine to amortize, and the
    CPU-jax RLC graph is a CI/parity path, not a production one).
    FD_VERIFY_MODE forces either explicitly; an unrecognized value is
    an error, not a silent fall-through to the platform default (a
    typo'd force must never masquerade as a measurement of the mode
    the operator asked for)."""
    forced = flags.get_raw("FD_VERIFY_MODE")
    if forced:
        if forced not in ("rlc", "direct"):
            raise ValueError(
                f"unknown FD_VERIFY_MODE {forced!r} (want rlc|direct)"
            )
        return forced
    from firedancer_tpu.ops.backend import _platform_is_tpu

    return "rlc" if _platform_is_tpu() else "direct"


def resolve_verify_mode(backend: str, verify_mode: str,
                        mesh_devices: int) -> str:
    """Resolve a VerifyTile's verify mode (module-level so the
    contract is unit-testable without a workspace).

    'auto' resolves by the ATTACHED PLATFORM (default_verify_mode
    above): rlc on TPU families — including mesh_devices, now that the
    Pippenger MSM shards across the mesh (round-10) — direct on
    host-jax backends. FD_VERIFY_MODE forces either explicitly; an
    unknown value raises. The GENUINELY unsupported combination is rlc
    on a non-jax backend ('cpu'/'oracle' host verifiers have no batch
    engine for the RLC graph to run on) — that is the only remaining
    blanket rejection. FD_MSM_SHARD=0 is the bisection hatch that
    restores the pre-round-10 rlc+mesh rejection (a silent downgrade
    to direct would masquerade as a measurement of the sharded path).

    The env force is validated HERE as well as at the platform default:
    host-backend tiles must stay jax-import-free, so they cannot probe
    the platform, but an explicit force — or a typo'd one — must still
    fail loudly instead of being silently dropped."""
    if verify_mode not in ("auto", "direct", "rlc"):
        raise ValueError(
            f"unknown verify_mode {verify_mode!r} (want auto|direct|rlc)"
        )
    shard_ok = flags.get_bool("FD_MSM_SHARD")
    if verify_mode == "auto":
        forced = flags.get_raw("FD_VERIFY_MODE")
        if forced and forced not in ("rlc", "direct"):
            raise ValueError(
                f"unknown FD_VERIFY_MODE {forced!r} (want rlc|direct)"
            )
        if backend != "tpu":
            if forced == "rlc":
                raise ValueError(
                    "FD_VERIFY_MODE=rlc requires backend='tpu' (the "
                    "host cpu|oracle verifiers have no batch engine "
                    "for the RLC graph — the one genuinely "
                    "unsupported combination)"
                )
            return "direct"
        verify_mode = default_verify_mode()
        if verify_mode == "rlc" and mesh_devices and not shard_ok:
            # The FD_MSM_SHARD=0 hatch: a platform auto-pick quietly
            # stays direct, but an EXPLICIT FD_VERIFY_MODE=rlc force
            # must fail loudly, not be silently dropped.
            if forced == "rlc":
                raise ValueError(
                    "FD_VERIFY_MODE=rlc with mesh_devices needs the "
                    "sharded MSM, which FD_MSM_SHARD=0 disabled"
                )
            verify_mode = "direct"
        return verify_mode
    if verify_mode == "rlc" and backend != "tpu":
        # Silently running the oracle path while the operator believes
        # RLC is on would be indistinguishable from "no fallbacks".
        raise ValueError(
            "verify_mode='rlc' requires backend='tpu' (the host "
            "cpu|oracle verifiers have no batch engine for the RLC "
            "graph — the one genuinely unsupported combination)"
        )
    if verify_mode == "rlc" and mesh_devices and not shard_ok:
        raise ValueError(
            "verify_mode='rlc' with mesh_devices needs the sharded "
            "MSM, which FD_MSM_SHARD=0 disabled"
        )
    return verify_mode


# --------------------------------------------------------------------------
# Engine entries + registry.
# --------------------------------------------------------------------------


class EngineEntry:
    """One prepared verify engine. Mutation discipline: ``state`` /
    ``fn`` / compile fields change only under the entry's build lock
    (held by whichever thread builds or warms it — a tile constructor,
    a bench worker, or the registry prewarm thread); the dispatch-side
    counters (dispatches/lanes/service EMA) are written by the single
    dispatching tile thread that owns the engine at runtime."""

    __slots__ = (
        "spec", "key", "state", "fn", "direct_fn", "compile_s",
        "fallback_compile_s", "cache_hit_est", "err", "dispatches",
        "lanes", "service_ns", "fill_efficiency", "madds_per_lane",
        "msm_token", "built_ts", "_warmed", "_build_lock",
        # fd_pod split-step pair (mesh rlc engines under FD_POD_SPLIT):
        # the two separately-jitted graphs + their own service EMAs, so
        # the cost model can be overlap-aware (combine_tail hides
        # behind the next batch's local_fill when double-buffered).
        "fn_local", "fn_tail", "service_local_ns", "service_tail_ns",
        # fd_drain post-verify stage (None unless FD_DRAIN armed this
        # build): the dedup-filter aux graph, dispatched back-to-back
        # with fn on the same device queue so statuses + novel-mask
        # come home in one completion.
        "fn_drain",
    )

    def __init__(self, spec: EngineSpec):
        self.spec = spec
        self.key = spec.key
        self.state = ENGINE_WARM if spec.mode in _HOST_MODES \
            else ENGINE_COLD
        self.fn: Optional[Callable] = None        # async verify callable
        self.direct_fn: Optional[Callable] = None  # rlc per-lane fallback
        # fd_pod split-step graphs (None unless spec.shards + rlc +
        # FD_POD_SPLIT built this engine as a local/tail pair).
        self.fn_local: Optional[Callable] = None
        self.fn_tail: Optional[Callable] = None
        # fd_drain aux stage (None unless FD_DRAIN armed this build).
        self.fn_drain: Optional[Callable] = None
        self.service_local_ns = 0   # EMA: dispatch -> local_fill ready
        self.service_tail_ns = 0    # EMA: local ready -> combine ready
        self.compile_s = 0.0
        self.fallback_compile_s = 0.0
        self.cache_hit_est = False
        self.err: Optional[str] = None
        self.dispatches = 0
        self.lanes = 0
        self.service_ns = 0        # EMA of dispatch->complete wall ns
        # Analytic cost model (msm_plan): meaningful for the rlc MSM
        # engine; the direct/host engines scale ~linearly in lanes, so
        # their per-lane proxy is flat.
        if spec.mode == "rlc":
            self.fill_efficiency = msm_plan.fill_efficiency(
                spec.batch)["total"]
            self.madds_per_lane = msm_plan.executed_madds_per_lane(
                spec.batch)
            # fd_msm2: the schedule token this engine's graph bakes in
            # (re-resolved at _build, where the bake actually happens).
            self.msm_token = spec.resolved_msm()
        else:
            self.fill_efficiency = None
            self.madds_per_lane = None
            self.msm_token = None
        self.built_ts = 0.0
        self._warmed: set = set()   # (batch, max_msg_len) shapes warmed
        self._build_lock = threading.Lock()

    def note_dispatch(self, lanes: int) -> None:
        self.dispatches += 1
        self.lanes += lanes

    def note_service(self, ns: int) -> None:
        """Measured dispatch->complete wall time: EMA(1/8) so the cost
        model tracks the device without chasing single-batch noise."""
        self.service_ns = (ns if not self.service_ns
                           else (7 * self.service_ns + ns) // 8)

    def note_service_split(self, local_ns: int, tail_ns: int) -> None:
        """fd_pod split-step cost samples: separate EMAs for the two
        graphs, same 1/8 smoothing as note_service. The whole-batch
        EMA is fed too (local + tail) so consumers that predate the
        split keep reading a sane number."""
        self.service_local_ns = (local_ns if not self.service_local_ns
                                 else (7 * self.service_local_ns
                                       + local_ns) // 8)
        self.service_tail_ns = (tail_ns if not self.service_tail_ns
                                else (7 * self.service_tail_ns
                                      + tail_ns) // 8)
        self.note_service(local_ns + tail_ns)

    def service_est_ns(self) -> int:
        """Best service-time estimate for one batch on this engine:
        the measured EMA, 0 while unmeasured (callers treat 0 as "no
        cost information — do not cap on it").

        OVERLAP-AWARE when the split EMAs are populated: a
        double-buffered dispatcher retires one batch per
        max(local_fill, combine_tail) at steady state — the classic
        two-stage pipeline bound — because batch k's tail executes
        while batch k+1's fill is already dispatched. The estimate is
        that bound, never less than either stage (a scheduler capping
        deadline slack on the serialized sum would step down exactly
        when pipelining has already hidden the tail)."""
        if self.service_local_ns and self.service_tail_ns:
            return max(self.service_local_ns, self.service_tail_ns)
        return self.service_ns

    def overlap_hidden_est(self) -> float:
        """Fraction of combine_tail the double-buffer hides at steady
        state, per the measured EMAs: 1.0 while the tail fits inside
        the next fill entirely, shrinking as the tail dominates. 0.0
        until both split EMAs are measured (monolithic engines stay
        0.0 — nothing is split, nothing hides)."""
        lo, tl = self.service_local_ns, self.service_tail_ns
        if not lo or not tl:
            return 0.0
        return min(1.0, lo / tl)

    def account_first_call(self, seconds: float,
                           msg_len: int = 0) -> None:
        """Book a caller-measured first-call compile (the bench worker
        path: it warms on its REAL inputs so the timed reps stay
        one-execution-per-rep) through the same flight accounting the
        warm path uses. Pass the executed msg_len so the shape is
        registered as warmed — a later acquire(warm=True) at the SAME
        shape must not re-warm and double-book the compile record
        (jit retraces genuinely different shapes, so those still
        warm). Takes the build lock: these fields are build-phase
        state."""
        with self._build_lock:
            rec = flight.record_compile(self.key, seconds)
            self.compile_s = seconds
            self.cache_hit_est = bool(rec["cache_hit_est"])
            self.state = ENGINE_WARM
            self.built_ts = time.time()
            if msg_len:
                self._warmed.add((self.spec.batch, msg_len))

    def snapshot(self) -> dict:
        return {
            "key": self.key,
            "mode": self.spec.mode,
            "batch": self.spec.batch,
            "shards": self.spec.shards,
            "frontend": self.spec.frontend,
            "state": self.state,
            "compile_s": round(self.compile_s, 3),
            "fallback_compile_s": round(self.fallback_compile_s, 3),
            "cache_hit_est": self.cache_hit_est,
            "dispatches": self.dispatches,
            "lanes": self.lanes,
            "service_est_ns": self.service_est_ns(),
            # fd_pod split-step accounting ({} = monolithic engine):
            "split": ({
                "service_local_ns": self.service_local_ns,
                "service_tail_ns": self.service_tail_ns,
                "overlap_hidden_est": round(self.overlap_hidden_est(), 3),
            } if self.fn_local is not None else {}),
            "fill_efficiency": (round(self.fill_efficiency, 4)
                                if self.fill_efficiency is not None
                                else None),
            # fd_msm2: the MSM schedule token the graph bakes in
            # (None = not an MSM engine). "auto" never appears here —
            # the entry records the RESOLVED plan, so an artifact
            # reader can tell which schedule a service EMA measured
            # even when the spec deferred to the FD_MSM_* flags.
            "msm": self.msm_token,
            # fd_drain: whether this build attached the post-verify
            # drain stage (FD_DRAIN at build time).
            "drain": self.fn_drain is not None,
            "err": self.err,
        }


class EngineRegistry:
    """The process-wide map engine_key -> EngineEntry. ``acquire`` is
    the ONE dispatch-site API: get-or-create the entry, build its
    graph, optionally warm (compile) it — idempotent per (spec, warm
    shape), so N call sites resolving the same engine pay one compile
    and share one accounting record."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[EngineSpec, EngineEntry] = {}
        # fd_msm2: per-rung MSM schedule winners (batch -> plan token),
        # installed by scripts/msm_search.py's certify+parity+bench
        # pipeline; EngineSpec.for_tile consults this so a tile's rlc
        # dispatches pick up the searched plan without env plumbing.
        self._rung_plans: Dict[int, str] = {}
        self._prewarm_q: deque = deque()   # (spec, max_msg_len)
        self._prewarm_wake = threading.Event()
        self._prewarm_stop = threading.Event()
        self._prewarm_thread: Optional[threading.Thread] = None
        # Guarded by _lock: True while a prewarm thread has committed
        # to draining the queue. The exit decision and this flag flip
        # happen under ONE lock hold, so a producer appending specs
        # either sees running=False (and starts a fresh thread) or is
        # seen by the draining loop before it breaks — is_alive() alone
        # races a thread that decided to exit but hasn't died yet.
        self._prewarm_running = False

    # -- per-rung MSM plans (fd_msm2) ------------------------------------

    def set_rung_plan(self, batch: int, token: str) -> None:
        """Install the msm_search winner for a B rung. The token is
        validated through msm_plan.parse_plan — a plan the grammar
        rejects (and so the certifier never admitted) cannot be
        registered, which is the registry half of the negative-control
        contract. "auto" clears the pin (the rung falls back to the
        FD_MSM_* flags)."""
        if token != "auto":
            msm_plan.parse_plan(token)   # raises on junk/unshippable
        with self._lock:
            if token == "auto":
                self._rung_plans.pop(int(batch), None)
            else:
                self._rung_plans[int(batch)] = token

    def rung_plan(self, batch: int) -> str:
        """The pinned MSM schedule token for a B rung ("auto" when no
        search winner is installed)."""
        with self._lock:
            return self._rung_plans.get(int(batch), "auto")

    # -- entry map -------------------------------------------------------

    def entry(self, spec: EngineSpec) -> EngineEntry:
        """Get-or-create the record WITHOUT building anything (cost
        model / accounting handles for schedulers and artifacts)."""
        with self._lock:
            e = self._entries.get(spec)
            if e is None:
                e = EngineEntry(spec)
                self._entries[spec] = e
            return e

    def entries(self) -> List[EngineEntry]:
        with self._lock:
            return list(self._entries.values())

    def snapshot(self) -> List[dict]:
        """Artifact view of every known engine (bench/replay records,
        flight dumps)."""
        return [e.snapshot() for e in self.entries()]

    # -- build + warm ----------------------------------------------------

    def acquire(self, spec: EngineSpec, warm: bool = True,
                max_msg_len: int = 1232) -> Tuple[EngineEntry, bool]:
        """Resolve an engine for dispatch. Returns (entry, warmed_now):
        warmed_now is True when THIS call paid a warm pass (the caller
        books it into its own tile lane; flight.record_compile is
        already booked by the registry). warm=False builds the callable
        without compiling — the bench worker warms on its real inputs
        and books via entry.account_first_call."""
        e = self.entry(spec)
        if spec.mode in _HOST_MODES:
            return e, False
        with e._build_lock:
            if e.fn is None:
                try:
                    self._build(e)
                except BaseException as exc:
                    # Build failures must be observable too (a rung
                    # whose shape can't build, e.g. not divisible over
                    # the mesh): state=failed + err, like a failed warm
                    # — snapshot readers can tell "broken" from "never
                    # attempted", and warm_entry keeps returning None.
                    e.state = ENGINE_FAILED
                    e.err = repr(exc)[:200]
                    raise
            warmed_now = False
            if warm:
                warmed_now = self._warm_locked(e, max_msg_len)
            return e, warmed_now

    def _build(self, e: EngineEntry) -> None:
        """Construct the async verify callable(s) for a device engine —
        the dispatch logic formerly inlined in VerifyTile.__init__ and
        bench.worker. Cheap (graph wrapping only); the compile happens
        at the warm pass / first call."""
        spec = e.spec
        import jax

        from firedancer_tpu.ops.verify import verify_batch

        # fd_msm2: the MSM schedule this graph bakes in. A pinned spec
        # token forces that exact plan; "auto" passes plan=None so the
        # builders resolve active_plan() from the FD_MSM_* flags at
        # trace time (the pre-fd_msm2 behavior when all flags default).
        plan = None
        if spec.mode == "rlc":
            if spec.msm != "auto":
                plan = msm_plan.parse_plan(spec.msm)
            e.msm_token = spec.resolved_msm()

        rlc_sharded = None
        if spec.shards:
            if spec.batch % spec.shards:
                raise ValueError(
                    f"batch {spec.batch} must divide over {spec.shards} "
                    "mesh devices"
                )
            from firedancer_tpu.parallel.mesh import (
                make_mesh,
                verify_step_sharded,
            )

            mesh = make_mesh(spec.shards)
            _sharded = verify_step_sharded(mesh)

            def direct_fn(msgs, lens, sigs, pubs):
                return _sharded(msgs, lens, sigs, pubs)[0]

            if spec.mode == "rlc":
                if flags.get_bool("FD_POD_SPLIT"):
                    # fd_pod split-step pair: local_fill + combine_tail
                    # as two jitted graphs, composed here into the
                    # rlc_fn contract (status, definite, batch_ok).
                    # Dispatching through the composition enqueues BOTH
                    # graphs asynchronously, so with inflight >= 2 the
                    # tile's dispatcher already double-buffers: batch
                    # k+1's local_fill is on the queue while batch k's
                    # combine_tail executes.
                    from firedancer_tpu.parallel.mesh import (
                        verify_rlc_split_sharded,
                    )

                    local_fn, tail_fn = verify_rlc_split_sharded(
                        mesh, plan=plan)
                    e.fn_local = local_fn
                    e.fn_tail = tail_fn

                    def rlc_sharded(msgs, lens, sigs, pubs, z, u):
                        status, definite, parts = local_fn(
                            msgs, lens, sigs, pubs, z, u)
                        return status, definite, tail_fn(parts)
                else:
                    from firedancer_tpu.parallel.mesh import (
                        verify_rlc_step_sharded,
                    )

                    rlc_sharded = verify_rlc_step_sharded(mesh, plan=plan)
        else:
            direct_fn = jax.jit(verify_batch)
        fn = direct_fn
        if spec.mode == "rlc":
            # RLC batch-verify fast pass with lazy per-lane fallback
            # (ops/verify_rlc.py); clean batches cost one MSM pass.
            from firedancer_tpu.ops.verify_rlc import (
                make_async_verifier,
                verify_batch_rlc,
            )

            if rlc_sharded is None and plan is not None:
                # Single-device engine with a pinned plan: bake it into
                # the jitted RLC graph here (make_async_verifier's
                # default jit would re-resolve from the flags).
                import functools

                rlc_sharded = jax.jit(
                    functools.partial(verify_batch_rlc, plan=plan))
            fn = make_async_verifier(direct_fn, rlc_fn=rlc_sharded)
        e.direct_fn = direct_fn
        e.fn = fn
        # fd_drain: attach the dedup-filter aux graph (built like the
        # FD_POD_SPLIT pair — a separately-jitted stage the dispatcher
        # enqueues right behind fn, so the novel-mask rides home in the
        # same completion sync). Gated at build, like FD_POD_SPLIT.
        if drain_mode() != "off":
            from firedancer_tpu.disco import drain as drain_mod

            e.fn_drain = drain_mod.make_filter_fn()

    def _warm_locked(self, e: EngineEntry, max_msg_len: int) -> bool:
        """Warm (compile) the engine at (batch, max_msg_len) — caller
        holds the entry build lock. Returns True when a warm pass ran.
        The rlc fallback graph is warmed too: the zero-lane warm batch
        resolves on the RLC pass alone, and the per-lane fallback would
        otherwise compile mid-run on the first salted batch."""
        shape = (e.spec.batch, max_msg_len)
        if shape in e._warmed:
            return False
        import jax.numpy as jnp
        import numpy as np

        e.state = ENGINE_WARMING
        warm_args = (
            jnp.zeros(shape, jnp.uint8),
            jnp.zeros((e.spec.batch,), jnp.int32),
            jnp.zeros((e.spec.batch, 64), jnp.uint8),
            jnp.zeros((e.spec.batch, 32), jnp.uint8),
        )
        try:
            t0 = time.perf_counter()
            np.asarray(e.fn(*warm_args))
            e.compile_s = time.perf_counter() - t0
            rec = flight.record_compile(e.key, e.compile_s)
            e.cache_hit_est = bool(rec["cache_hit_est"])
            if e.spec.mode == "rlc":
                t0 = time.perf_counter()
                np.asarray(e.direct_fn(*warm_args))
                e.fallback_compile_s = time.perf_counter() - t0
                flight.record_compile(e.key + ":fallback",
                                      e.fallback_compile_s)
            if e.fn_drain is not None:
                # fd_drain aux graph: warm at the same batch shape so
                # the first drain dispatch never compiles mid-run.
                from firedancer_tpu.ops.dedup_filter import filter_words

                w = filter_words(flags.get_int("FD_DRAIN_FILTER_BITS"))
                for out in e.fn_drain(
                        jnp.zeros((e.spec.batch,), jnp.uint32),
                        jnp.zeros((e.spec.batch,), jnp.uint32),
                        jnp.zeros((e.spec.batch,), jnp.bool_),
                        jnp.zeros((w,), jnp.uint32),
                        jnp.zeros((w,), jnp.uint32)):
                    np.asarray(out)
        except BaseException as exc:
            e.state = ENGINE_FAILED
            e.err = repr(exc)[:200]
            raise
        e._warmed.add(shape)
        e.state = ENGINE_WARM
        e.err = None
        e.built_ts = time.time()
        return True

    def warm_entry(self, spec: EngineSpec) -> Optional[EngineEntry]:
        """The dispatch-time lookup for a rung switch: the entry iff it
        is WARM and dispatchable right now, else None (the caller keeps
        the engine it already holds — a rung switch must never stall a
        hot loop on a compile)."""
        with self._lock:
            e = self._entries.get(spec)
        if e is not None and e.state == ENGINE_WARM and e.fn is not None:
            return e
        return None

    def entry_count(self) -> int:
        """Registered engine entries (every state) — the fd_soak
        compile-cache tripwire samples this: a flat count over hours
        means the ladder is closed; monotone growth means shapes are
        leaking past the prewarmed rungs."""
        with self._lock:
            return len(self._entries)

    def retire(self, specs) -> int:
        """Drop the given specs from the registry (live-reconfig
        cleanup after a ladder swap: the OLD rungs' engines become
        unreachable and their jitted callables can be collected).
        Specs not present are ignored; returns how many were dropped.
        Callers must not retire the engine a tile still dispatches on
        — the reconfig barrier guarantees no inflight batch holds one.
        """
        dropped = 0
        with self._lock:
            for spec in specs:
                if self._entries.pop(spec, None) is not None:
                    dropped += 1
        return dropped

    # -- background prewarm ---------------------------------------------

    def prewarm_ladder(self, specs, max_msg_len: int = 1232,
                       policy: Optional[str] = None) -> None:
        """Warm a rung ladder per the FD_ENGINE_PREWARM policy:
        'background' queues the specs for the registry prewarm thread
        (started on first use; rung switches pick each engine up as it
        turns WARM), 'sync' warms inline before returning, 'off' does
        nothing (every rung but the primary stays cold — the scheduler
        then effectively pins the primary engine)."""
        policy = policy or flags.get_str("FD_ENGINE_PREWARM")
        if policy not in ("background", "sync", "off"):
            raise ValueError(
                f"unknown FD_ENGINE_PREWARM {policy!r} "
                "(want background|sync|off)"
            )
        if policy == "off":
            return
        if policy == "sync":
            for spec in specs:
                self.acquire(spec, warm=True, max_msg_len=max_msg_len)
            return
        with self._lock:
            for spec in specs:
                self._prewarm_q.append((spec, max_msg_len))
            if not self._prewarm_running:
                self._prewarm_running = True
                self._prewarm_stop.clear()
                t = threading.Thread(
                    target=self._prewarm_loop, name="fd_engine.prewarm",
                    daemon=True,
                )
                self._prewarm_thread = t
                t.start()
        self._prewarm_wake.set()

    def _prewarm_loop(self) -> None:
        # Single consumer of the prewarm queue; every mutation it
        # performs goes through the same lock-guarded acquire path the
        # foreground callers use (docs/OWNERSHIP.md row). A failed warm
        # is recorded on the entry (state=failed, err) and the loop
        # moves on — a broken rung must not kill prewarm for the rest
        # of the ladder.
        while not self._prewarm_stop.is_set():
            with self._lock:
                item = (self._prewarm_q.popleft()
                        if self._prewarm_q else None)
            if item is None:
                self._prewarm_wake.wait(timeout=0.2)
                self._prewarm_wake.clear()
                with self._lock:
                    if not self._prewarm_q:
                        # Exit decision + running-flag flip under ONE
                        # lock hold (see _prewarm_running): a producer
                        # can never enqueue into a thread that already
                        # chose to die.
                        self._prewarm_running = False
                        break
                continue
            spec, max_msg_len = item
            try:
                self.acquire(spec, warm=True, max_msg_len=max_msg_len)
            except BaseException:
                pass  # entry carries state=failed + err for observers
        with self._lock:
            self._prewarm_running = False  # stop-Event exits too

    def prewarm_idle(self) -> bool:
        """True when no background prewarm work is queued or running
        (tests + the engine smoke synchronize on this)."""
        with self._lock:
            return not self._prewarm_q and not self._prewarm_running

    def stop_prewarm(self, timeout: float = 10.0) -> None:
        """Stop background prewarm: the queue is DROPPED (stop means
        stop — leaving specs queued would strand them behind a dead
        thread) and the thread joined. A later prewarm_ladder call
        starts fresh (the running flag flips off at thread exit)."""
        with self._lock:
            self._prewarm_q.clear()
        self._prewarm_stop.set()
        self._prewarm_wake.set()
        t = self._prewarm_thread
        if t is not None:
            t.join(timeout=timeout)


_registry: Optional[EngineRegistry] = None
_registry_lock = threading.Lock()


def registry() -> EngineRegistry:
    """The process-wide registry (tiles, bench workers and smokes all
    resolve through this one instance, so engine accounting has one
    authority per process)."""
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = EngineRegistry()
        return _registry


# --------------------------------------------------------------------------
# Rung ladder + scheduler.
# --------------------------------------------------------------------------


def rung_ladder(cap: Optional[int] = None, floor: int = 0) -> List[int]:
    """The FD_ENGINE_LADDER rung list: parsed, deduped, ascending.
    `cap` drops rungs above the tile's staging batch (arenas are sized
    to the largest rung); `floor` drops rungs too small to stage a
    whole txn (MAX_SIG_CNT). A malformed entry raises — a typo'd
    ladder must never silently schedule on the wrong rungs."""
    raw = flags.get_str("FD_ENGINE_LADDER")
    rungs = set()
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            b = int(part)
        except ValueError:
            raise ValueError(
                f"bad FD_ENGINE_LADDER entry {part!r} (want a "
                "comma-separated list of batch sizes)"
            ) from None
        if b <= 0:
            raise ValueError(
                f"FD_ENGINE_LADDER rung {b} must be positive")
        rungs.add(b)
    out = sorted(r for r in rungs
                 if r >= floor and (cap is None or r <= cap))
    return out


class RungScheduler:
    """Latency-adaptive rung selection over a B ladder — AdaptiveFlush
    promoted into an online continuous-batching scheduler.

    Decision shape (all pure in the passed clock; single caller thread
    by contract — the feed stager):

      pick(now_ns, lanes, first_ns, backlog)  ->  target rung
          the largest rung the present queue depth (staged lanes +
          ring backlog) can fill — monotone rung-up in depth, the
          property test pins it — capped by deadline slack: a rung
          whose measured service estimate exceeds the staged batch's
          remaining latency budget cannot meet the deadline, so the
          pick steps down (floor: the smallest rung). Unmeasured rungs
          (cost 0) are never capped — prewarm hasn't seen them yet and
          guessing would pin the ladder small forever.

      due(...)  ->  AdaptiveFlush verdict with the CURRENT rung as the
          batch bound: the deadline/starve invariants are inherited
          verbatim (same policy object, same hwm clock hardening).

      dispatch_rung(lanes)  ->  the smallest rung that covers a staged
          lane count (engines are compiled per rung; a partial pads up
          to the chosen rung's shape).

    `cost_ns(rung)` is the registry-attached service model (EngineEntry
    service EMA); None disables slack capping (host engines, whose
    service scales with lanes rather than the padded rung).

    `shards` (fd_pod): on a mesh engine every rung is a GLOBAL batch
    split contiguously over the shards, so rungs must divide the mesh
    (a non-dividing rung raises — the tile drops them before
    construction) and `shard_rung` exposes the per-shard lane count a
    feeder lane should stage toward for a given global rung."""

    def __init__(self, rungs, deadline_ns: int,
                 cost_ns: Optional[Callable[[int], int]] = None,
                 shards: int = 1):
        rungs = sorted(set(int(r) for r in rungs))
        if not rungs:
            raise ValueError("RungScheduler needs at least one rung")
        if any(r <= 0 for r in rungs):
            raise ValueError(f"rungs must be positive, got {rungs}")
        self.shards = max(1, int(shards))
        bad = [r for r in rungs if r % self.shards]
        if bad:
            raise ValueError(
                f"rungs {bad} do not divide over {self.shards} mesh "
                "shards (every rung is a global batch split "
                "contiguously across the mesh)"
            )
        self.rungs = rungs
        self.deadline_ns = deadline_ns
        self.cost_ns = cost_ns
        self.flush = AdaptiveFlush(deadline_ns)
        self.cur = rungs[0]
        self.switches = 0
        self.decisions = 0
        self.last_inputs: Tuple[int, int, int] = (0, 0, 0)

    def shard_rung(self, rung: int) -> int:
        """Per-shard lane count of a global rung (the commit threshold
        one fd_pod feeder lane stages toward)."""
        return max(1, rung // self.shards)

    # -- pure selection --------------------------------------------------

    def pick_rung(self, depth: int, slack_ns: Optional[int] = None) -> int:
        """Stateless rung choice: largest rung fully coverable by
        `depth`, capped by the deadline slack via the cost model.
        Monotone non-decreasing in depth for fixed slack."""
        i = 0
        for j, rung in enumerate(self.rungs):
            if depth >= rung:
                i = j
        if slack_ns is not None and self.cost_ns is not None:
            while i > 0:
                c = self.cost_ns(self.rungs[i])
                if not c or c <= slack_ns:
                    break
                i -= 1
        return self.rungs[i]

    def dispatch_rung(self, lanes: int) -> int:
        """Smallest rung that covers `lanes` staged lanes (a multisig
        txn can overshoot the commit threshold); the top rung bounds
        everything by construction (arenas are sized to it)."""
        for rung in self.rungs:
            if lanes <= rung:
                return rung
        return self.rungs[-1]

    # -- online decision (stateful: switch tracking) ---------------------

    def pick(self, now_ns: int, lanes: int, first_ns: int,
             backlog: int, backlog_full: bool = False) -> int:
        """The stager-facing decision: target rung for the batch being
        staged. Queue depth = staged lanes + ring backlog (backlog is
        in txns — a lower bound on lanes, so depth under-counts and the
        rung-up errs toward latency, never toward a padded monster
        batch). Slack = the staged batch's remaining deadline budget
        (full budget while nothing is staged).

        ``backlog_full`` is the caller's saturation signal: the in-ring
        backlog is at (half of) its structural cap, i.e. the producer
        is ahead of the stager as fast as the ring can express it —
        the ring is depth-bounded, so raw backlog alone cannot reach
        big-rung territory. Saturation means the pipeline is
        queueing-bound and NO rung meets the deadline: depth is lifted
        to the top rung and the slack cap is dropped, because capping
        by service cost there shrinks batches exactly when big-rung
        fill efficiency matters most (the small-rung death spiral the
        engine smoke pins: worse throughput -> deeper backlog -> still
        capped). Monotonicity survives: backlog_full only ever lifts
        the pick."""
        depth = max(0, lanes) + max(0, backlog)
        if backlog_full or backlog >= self.rungs[-1]:
            depth = max(depth, self.rungs[-1])
            slack = None
        elif lanes > 0 and first_ns:
            slack = max(0, self.deadline_ns - max(0, now_ns - first_ns))
        else:
            slack = self.deadline_ns
        rung = self.pick_rung(depth, slack_ns=slack)
        self.decisions += 1
        self.last_inputs = (depth, slack, lanes)
        if rung != self.cur:
            self.switches += 1
            self.cur = rung
        return rung

    def due(self, now_ns: int, lanes: int, first_ns: int, *,
            starved: bool = False, device_idle: bool = False,
            backpressured: bool = False):
        """AdaptiveFlush verdict at the current rung (FLUSH_FULL when
        lanes filled the rung, FLUSH_DEADLINE at deadline expiry — the
        invariant the property test pins — FLUSH_STARVED on the idle
        early-out), or None to keep filling."""
        return self.flush.due(
            now_ns, lanes, self.cur, first_ns, starved=starved,
            device_idle=device_idle, backpressured=backpressured,
        )

    def decide(self, now_ns: int, lanes: int, first_ns: int,
               backlog: int, *, starved: bool = False,
               device_idle: bool = False, backpressured: bool = False,
               backlog_full: bool = False):
        """pick + due in one call (the property-test surface): returns
        (verdict_or_None, rung)."""
        rung = self.pick(now_ns, lanes, first_ns, backlog,
                         backlog_full=backlog_full)
        verdict = None
        if lanes > 0:
            verdict = self.due(
                now_ns, lanes, first_ns, starved=starved,
                device_idle=device_idle, backpressured=backpressured,
            )
        return verdict, rung


# --------------------------------------------------------------------- #
# fdlint pass 7 (graph-audit) contracts — literals, read with
# ast.literal_eval by firedancer_tpu/lint/graphs.py, never imported.
# These cover the registry's engine classes: the direct (non-RLC)
# verify graph, its psum-carrying sharded wrapper, and the fused
# frontend / batched decompress front-end engines.  RLC and MSM stage
# contracts live next to their builders in ops/verify_rlc.py and
# ops/msm.py.
# --------------------------------------------------------------------- #

def fabric_split_pair(mesh, batch: int, plan=None):
    """fd_fabric entry: the split rlc pair (local_fill + combine_tail)
    on a caller-provided MULTI-AXIS mesh, plus its compile-ledger key.

    The registry cannot serve this: EngineSpec keys on a flat shard
    count and _build constructs its own single-axis 'dp' mesh via
    make_mesh, but a fabric's (host, dp) topology comes from
    jax.distributed — the mesh is the caller's. So the fabric builds
    the pair here and books its own warm pass via
    flight.record_compile(key, seconds), the same ledger every
    registry engine books into (fd_report's compile table and the
    fd_soak compile tripwires see fabric compiles like any other).

    Returns (local_jit, combine_jit, key): the u3-native pair
    (parallel/mesh.verify_rlc_split_global — u is the global (K, 2, B)
    block layout, no host-side reshape, because a (K, 2B) reshape
    cannot cross processes) and the key
    "rlc:B<batch>:fabric<hosts>x<dp>:fe<frontend>:msm<plan>".
    """
    from firedancer_tpu.parallel.mesh import verify_rlc_split_global

    if plan is not None:
        token = msm_plan.plan_token(plan)
    else:
        token = EngineSpec("rlc", batch).resolved_msm()
    shape = "x".join(str(int(s)) for s in mesh.devices.shape)
    key = (f"rlc:B{batch}:fabric{shape}:fe{current_frontend()}"
           f":msm{token}")
    local_jit, combine_jit = verify_rlc_split_global(mesh, plan=plan)
    return local_jit, combine_jit, key


GRAPH_CONTRACTS = {
    "direct": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
    },
    "direct_sharded": {
        "collectives": {"psum": 3},
        "axes": ["dp"],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
        "derived_from": ["direct"],
    },
    "frontend": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
    },
    "decompress": {
        "collectives": {},
        "axes": [],
        "dtypes": ["bool", "int32", "uint32", "uint8"],
    },
}
