"""fd_sentinel — the judgment layer over fd_flight telemetry.

PR 6's fd_flight gave every tile boundary metrics, spans, and a crash
recorder; nothing JUDGED that telemetry — the docs/LATENCY.md p99
budgets and docs/ROOFLINE.md per-stage budgets were prose, the
BENCH_LOG.jsonl history was append-only and never read back, and the
nine falsifiable round-10 predictions awaited hand-reconciliation.
This module is the judgment layer, in three parts:

  SLO ENGINE   a typed, declarative SLO table (the flags.py /
               TILE_METRICS pattern: every objective specced ONCE,
               below; docs/SLO.md is rendered from it and test-pinned)
               plus a Sentinel evaluator that runs INSIDE every
               pipeline run — a low-rate poller over the fd_flight
               shared registry. Latency SLOs consume the always-on
               EdgeHist log2 histograms with multi-window burn-rate
               detection (alert only when the error budget burns at
               >= FD_SLO_BURN in BOTH the fast and the slow window —
               prompt on real breaches, deaf to transients); liveness
               SLOs watch pipeline progress and cnc heartbeats (the
               wedge signature the supervisor kills on, now visible in
               unsupervised runs too). Violations become structured
               flight-recorder events ("sentinel" recorder),
               fd_flight_slo_* prom metrics (shared "flight.slo" rows,
               so monitors and fd_top read them cross-process), and
               the PipelineResult.slo summary. The same latency rules
               evaluate standalone over a flight dump
               (evaluate_edges_summary / scripts/fd_report.py --slo).

  REGRESSION   load_timeline() parses the full BENCH_LOG.jsonl (pre-
  TRACKER      schema_version legacy lines included) plus the BENCH /
               REPLAY / MULTICHIP / PACK / HOSTFEED artifact family
               into one schema-normalized timeline; regressions() flags
               any device measurement that falls below its series'
               rolling best-of baseline. scripts/fd_report.py renders
               per-mode/per-B/per-stage trend reports from it.

  PREDICTION   the fifteen ROOFLINE.md falsifiable predictions for the
  LEDGER       next hardware run (BENCH_r06), each with a MACHINE-
               CHECKABLE match rule over the timeline: the ledger lists
               every prediction as pending until a matching artifact
               lands, then auto-grades it confirmed/falsified — the
               hardware session self-grades instead of waiting for
               hand-reconciliation.

Part 3 of the tentpole — cross-process/cross-shard aggregation — lives
in disco/flight.py (merge_tile_metrics / merge_edge_rows /
merge_snapshots): counters delta-accumulate so sums are exact, and log2
histogram rows merge by elementwise add.

Deliberately stdlib+numpy only (disco/tiles.py's jax-import-free
dispatch contract): the sentinel runs on a host thread next to the
tiles, and fd_report must load before any backend import.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from firedancer_tpu import flags
from firedancer_tpu.disco import flight

# --------------------------------------------------------------------------
# The declarative SLO table — every objective specced once. Budgets
# resolve from the FD_SLO_* flag registry at Sentinel construction (the
# rendered docs/SLO.md states the registry defaults), so the spec, the
# docs, and the evaluator can never disagree.
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class SLO:
    name: str
    kind: str            # "latency" (edge histogram burn rate) |
                         # "liveness" (progress / heartbeat stall) |
                         # "balance" (per-shard occupancy ratio over
                         # the fd_pod verify.shardN flight rows) |
                         # "effectiveness" (fd_drain definitely-novel
                         # share of published claims) |
                         # "slope" (fd_soak long-horizon resource-
                         # growth tripwires over the probe's fitted
                         # trends) |
                         # "fairness" (fd_fabric per-tenant admission:
                         # honest-tenant shed fraction over the
                         # registered tenant source)
    edge_or_stage: str   # edge label (lane variants aggregate in), or
                         # "progress" / "heartbeat" for liveness SLOs,
                         # or the shard-row suffix for balance SLOs,
                         # or "drain_claims" for the drain
                         # effectiveness SLO, or the sampled resource
                         # ("heap" / "slot_pool" / "compile_cache")
                         # for slope SLOs
    objective: str       # human statement of the objective
    budget_flag: str     # FD_SLO_* flag naming the budget (ms)
    target: float = 0.99       # latency: quantile target (error budget
                               # = 1 - target); liveness: unused
    fault_classes: Tuple[str, ...] = ()  # chaos classes whose injection
                                         # this SLO is expected to catch


SLO_TABLE: Tuple[SLO, ...] = (
    SLO("e2e_p99", "latency", "sink",
        "end-to-end (source stamp -> sink) p99 within the queue-"
        "inclusive corpus budget (docs/LATENCY.md)",
        "FD_SLO_E2E_BUDGET_MS"),
    SLO("verify_p99", "latency", "verify_dedup",
        "source -> sigverify-complete p99 within the e2e budget "
        "(cumulative stage; the ring-dwell backlog is charged here, "
        "so this binds exactly when verify stops keeping up)",
        "FD_SLO_E2E_BUDGET_MS"),
    SLO("drain_p99", "latency", "verify_drain",
        "source publish -> stager drain (fd_feed ring dwell) p99 "
        "within the e2e budget — the input-backlog stage",
        "FD_SLO_E2E_BUDGET_MS"),
    SLO("dedup_p99", "latency", "dedup_pack",
        "source -> dedup-complete p99 within the e2e budget",
        "FD_SLO_E2E_BUDGET_MS"),
    SLO("pack_p99", "latency", "pack_sink",
        "source -> pack-scheduled p99 within the e2e budget",
        "FD_SLO_E2E_BUDGET_MS"),
    SLO("source_p99", "latency", "replay_verify",
        "source-publish span p99 stays us-scale (queue-free stage; a "
        "breach is pathological host scheduling, not load)",
        "FD_SLO_SOURCE_BUDGET_MS"),
    SLO("quic_ingest_p99", "latency", "quic_ingest",
        "QUIC front-door admission span (stream completion at the quic "
        "tile -> frag publish into the feed) p99 within budget — the "
        "queue the fd_siege admission/shedding defenses keep shallow: "
        "a breach means completed txns are stalling INSIDE the front "
        "door under attack instead of being admitted or shed",
        "FD_SLO_QUIC_INGEST_MS"),
    SLO("shard_balance", "balance", "shard",
        "fd_pod shard occupancy: on a mesh run, the busiest shard "
        "lane's dispatched lanes stay within FD_SLO_SHARD_BALANCE_PCT "
        "(percent) of the laziest's once every shard has real volume "
        "— a breach means shard placement is starving a device and "
        "aggregate throughput has degraded to the slowest shard",
        "FD_SLO_SHARD_BALANCE_PCT"),
    SLO("drain_filter_effectiveness", "effectiveness", "drain_claims",
        "fd_drain dedup pre-filter effectiveness: once the verify "
        "tiles have published real claim volume, at least "
        "FD_SLO_DRAIN_EFF_PCT percent of published clean txns must "
        "carry a definitely-novel claim (drain_novel / (drain_novel + "
        "drain_maybe)) — a collapse means the filter window is "
        "undersized or bank rotation is wedged and DedupTile has "
        "degraded to probing everything (an FD_DRAIN=off run "
        "publishes no claims and never arms this)",
        "FD_SLO_DRAIN_EFF_PCT"),
    SLO("heap_slope", "slope", "heap",
        "fd_soak heap-growth tripwire: the least-squares slope of the "
        "soak probe's tracemalloc samples stays under "
        "FD_SLO_HEAP_SLOPE_KB KiB/min once MIN_SLOPE_SAMPLES have "
        "accumulated — a breach is the multi-hour leak signature the "
        "minutes-scale gates cannot see (armed only when a soak run "
        "registers a slope source; ordinary runs stay silent)",
        "FD_SLO_HEAP_SLOPE_KB"),
    SLO("pool_occupancy_slope", "slope", "slot_pool",
        "fd_soak slot-pool occupancy tripwire: the fitted trend of "
        "outstanding fd_feed slots (not FREE) stays under "
        "FD_SLO_POOL_SLOPE_MILLI milli-slots/min — a breach means "
        "slots are leaking out of the FREE->FILLING->READY->FREE "
        "cycle (stuck inflight windows, lost releases)",
        "FD_SLO_POOL_SLOPE_MILLI"),
    SLO("compile_cache_slope", "slope", "compile_cache",
        "fd_soak compile-cache tripwire: engine-registry entries + "
        "recorded compiles accrete no faster than FD_SLO_COMPILE_SLOPE "
        "entries/hour past the prewarmed ladder — a breach is the "
        "unbounded-recompile signature (shape leak, or reconfigs that "
        "never retire old engines)",
        "FD_SLO_COMPILE_SLOPE"),
    SLO("tenant_fairness", "fairness", "tenants",
        "fd_fabric multi-tenant admission fairness: once real tenant "
        "volume has offered (MIN_TENANT_OFFERED), every HONEST tenant "
        "(offering within its FD_TENANT_RATE bucket) keeps its shed "
        "fraction under FD_SLO_TENANT_SHED_PCT percent — a breach "
        "means admission is starving a within-rate tenant while an "
        "over-offering attacker should be the only one shed (armed "
        "only when a fabric run registers a tenant source; ordinary "
        "runs stay silent)",
        "FD_SLO_TENANT_SHED_PCT"),
    SLO("pipeline_progress", "liveness", "progress",
        "some pipeline edge advances at least every FD_SLO_STALL_MS "
        "while the run is live (armed after the first frag)",
        "FD_SLO_STALL_MS",
        fault_classes=("credit_starve",)),
    SLO("tile_heartbeat", "liveness", "heartbeat",
        "every RUNning tile's cnc heartbeat advances at least every "
        "FD_SLO_HB_MS (the supervised wedge-detector signature, "
        "watched in-process)",
        "FD_SLO_HB_MS",
        fault_classes=("hb_stall", "worker_kill")),
)

SLO_NAMES: Tuple[str, ...] = tuple(s.name for s in SLO_TABLE)
SLO_BY_NAME: Dict[str, SLO] = {s.name: s for s in SLO_TABLE}

# chaos fault class -> the SLO its injection must trip (derived from
# the table; scripts/slo_smoke.py gates the asymmetry both ways).
FAULT_SLO: Dict[str, str] = {
    cls: s.name for s in SLO_TABLE for cls in s.fault_classes
}

# Minimum samples in a window before a latency burn rate is believed
# (a 3-sample window "p99" is noise, not a signal).
MIN_WINDOW_N = 16

# Minimum average dispatched lanes per shard before the shard-balance
# SLO arms (the first partial batch of a run is structurally lopsided;
# judging it would cry wolf at every boot).
MIN_SHARD_LANES = 16

# Minimum published fd_drain claims (novel + maybe) before the filter-
# effectiveness SLO arms: the first batches of a run publish against
# empty banks (everything claims novel — fine) but a tiny sample must
# not grade the window, and an FD_DRAIN=off run (zero claims) must
# never arm it at all.
MIN_DRAIN_CLAIMS = 256

# Minimum resource-probe samples before a slope SLO arms: a 2-point
# "slope" is the boot transient, not a trend (allocator warmup and the
# first compile dominate the opening seconds of any run).
MIN_SLOPE_SAMPLES = 8

# fd_soak slope source: the soak harness registers a callable returning
# {"samples": n, "heap_kb_min": f, "pool_milli_min": f,
#  "compile_per_hr": f} (disco/soak.py's ResourceProbe fits); no source
# registered (every non-soak run) means the slope SLOs never arm. A
# module-level hook rather than a Sentinel ctor arg because
# start_for_run() constructs the Sentinel internally — the soak sets it
# before the pipeline boots and clears it in its finally.
_SLOPE_SOURCE: Optional[Callable[[], dict]] = None

# Maps each slope SLO's edge_or_stage to its key in the source dict.
_SLOPE_KEYS = {
    "heap": "heap_kb_min",
    "slot_pool": "pool_milli_min",
    "compile_cache": "compile_per_hr",
}


def set_slope_source(fn: Optional[Callable[[], dict]]) -> None:
    """Install (or clear, with None) the process-wide slope source the
    slope-kind SLOs evaluate against. Owned by disco/soak.py."""
    global _SLOPE_SOURCE
    _SLOPE_SOURCE = fn


# Minimum total offered transactions across tenants before the
# tenant-fairness SLO arms: the opening instants of a run (every bucket
# still on its burst allowance) carry no fairness signal, and a tiny
# sample must not grade the shed percentage.
MIN_TENANT_OFFERED = 64

# fd_fabric tenant source: the fabric front door registers a callable
# returning {tenant_name: {"offered": n, "admitted": n, "shed": n,
# "honest": bool}} (disco/fabric.py's TenantAdmission.fairness_view);
# no source registered (every non-fabric run) means the fairness SLO
# never arms. Same module-hook shape as the slope source, for the same
# reason: start_for_run() constructs the Sentinel internally.
_TENANT_SOURCE: Optional[Callable[[], Dict[str, dict]]] = None


def set_tenant_source(fn: Optional[Callable[[], Dict[str, dict]]]) -> None:
    """Install (or clear, with None) the process-wide per-tenant
    admission source the fairness SLO evaluates against. Owned by
    disco/fabric.py."""
    global _TENANT_SOURCE
    _TENANT_SOURCE = fn


def evaluate_tenant_summary(tenants: Dict[str, dict],
                            budget_pct: Optional[int] = None) -> List[dict]:
    """Standalone fairness judgment over a (merged) per-tenant ledger —
    the same rule Sentinel._eval_fairness applies live, exposed for the
    fabric coordinator judging N processes' merged dumps (the
    evaluate_edges_summary analog for the fairness kind). Returns one
    violation dict per honest tenant over budget; an empty list is the
    green gate. Ledger-parity (admitted + shed == offered) is checked
    here too: a ledger that does not reconcile is itself a violation —
    judgment over corrupt accounting would be vacuous."""
    if budget_pct is None:
        budget_pct = flags.get_int("FD_SLO_TENANT_SHED_PCT")
    out: List[dict] = []
    total_offered = 0
    for name, row in sorted(tenants.items()):
        offered = int(row.get("offered", 0))
        admitted = int(row.get("admitted", 0))
        shed = int(row.get("shed", 0))
        total_offered += offered
        if admitted + shed != offered:
            out.append({
                "slo": "tenant_fairness", "tenant": name,
                "kind": "parity",
                "detail": f"admitted {admitted} + shed {shed} != "
                          f"offered {offered}",
            })
    if total_offered < MIN_TENANT_OFFERED:
        return out  # unarmed: no fairness judgment on a cold ledger
    for name, row in sorted(tenants.items()):
        if not row.get("honest", False):
            continue  # an attacker being shed is the defense working
        offered = int(row.get("offered", 0))
        shed = int(row.get("shed", 0))
        if offered > 0 and shed * 100 > budget_pct * offered:
            out.append({
                "slo": "tenant_fairness", "tenant": name,
                "kind": "starved",
                "shed": shed, "offered": offered,
                "budget_pct": budget_pct,
                "detail": f"honest tenant shed {shed}/{offered} "
                          f"(> {budget_pct}%)",
            })
    return out

# --------------------------------------------------------------------------
# The ROOFLINE per-stage ms budgets (round-10 >=400k/s gate arithmetic,
# per 8192-lane batch on the fused path) and the throughput gates —
# machine-readable here, rendered into docs/SLO.md, consumed by the
# prediction ledger and fd_report's stage-trend tables.
# --------------------------------------------------------------------------

STAGE_BUDGETS_MS: Dict[str, float] = {
    "sha": 4.0,          # fused front half (SHA-512 + mod-L + coeff muls)
    "decompress": 5.0,   # 2B stacked lanes, curve_pallas-resident
    "sc": 0.0,           # fused into sha on the fused path
    "rlc_combine": 0.5,  # sc_sum cross-lane reduction only
    "glue": 2.5,         # inter-stage residual (transposes deleted)
    "non_msm_total": 12.0,
    "msm": 6.5,          # B=16k K=32 per 8192-equiv; re-derived PR-16
                         # from the signed-digit schedule-search winner
                         # (old 8.5 budget / the 1.3x msm_search
                         # headline gate — build/msm_search.json holds
                         # the per-candidate evidence)
    "total": 18.5,       # => >= 440k/s (headroom over the 400k gate)
}

# The PR-14 Montgomery-batched decompress raises the bar below the
# round-10 budget (prediction 7 keeps grading the 5.0 ms budget; this
# one grades the batched engine specifically — ROADMAP direction 4's
# "<= 2.5 ms and a raised ladder headline").
DECOMPRESS_BATCHED_BUDGET_MS = 2.5

THROUGHPUT_GATES: Dict[str, Dict[str, object]] = {
    "verify_device": {
        "metric": "ed25519_verify_throughput", "min": 400_000.0,
        "unit": "verifies/s",
        "doc": "round-6 on-chip gate (BENCH_r06; ROOFLINE budget table)",
    },
    "replay_device": {
        "metric": "replay_pipeline_throughput", "min": 20_000.0,
        "unit": "txns/s",
        "doc": "feed the device: REPLAY_r06 with flush_timeout ~= 0",
    },
    "replay_cpu": {
        "metric": "replay_pipeline_throughput_cpu", "min": 15_000.0,
        "unit": "txns/s",
        "doc": "host pipeline to verify-bound (REPLAY_CPU_r06)",
    },
    "aggregate_pod": {
        "metric": "ed25519_verify_throughput", "min": 1_040_000.0,
        "unit": "verifies/s",
        "doc": "beat wiredancer's 1.04M/s reference point on the "
               "8-way mesh (ROADMAP pod-scale direction)",
    },
}


def _budget_ms(slo: SLO) -> int:
    return flags.get_int(slo.budget_flag)


def _budget_default_ms(slo: SLO) -> int:
    return flags.REGISTRY[slo.budget_flag].default


def _bad_from_bucket(threshold_ns: int) -> int:
    """First log2 bucket whose LOWER bound is >= 2x the budget: only
    samples provably over twice the budget consume error budget (the
    docs/LATENCY.md one-bucket-of-slack rule; a bucket straddling the
    boundary counts good, so bucket rounding can never cry wolf)."""
    return min((2 * threshold_ns - 1).bit_length() + 1, flight.N_BUCKETS)


# --------------------------------------------------------------------------
# The in-pipeline evaluator.
# --------------------------------------------------------------------------


@dataclass
class _SloState:
    alerting: bool = False
    alerts: int = 0
    breach_polls: int = 0
    burn_milli: int = 0


class Sentinel:
    """One run's SLO evaluator. poll() is cheap (shared-memory reads +
    integer math) and single-threaded; start()/stop() run it on a
    daemon thread at FD_SENTINEL_INTERVAL_MS. The runner MUST stop()
    the sentinel before leaving the workspace (the thread reads mapped
    rows) — every pipeline runner stops it at quiescence, before HALT,
    so drain-and-halt never books a stall.

    `edges_fn` / `tiles_fn` / `clock` are injectable for tests:
    edges_fn() -> {edge_label: raw EDGE_SLOTS row}, tiles_fn() ->
    {tile: (signal, heartbeat)}.
    """

    def __init__(self, wksp=None, pod=None,
                 edges_fn: Optional[Callable] = None,
                 tiles_fn: Optional[Callable] = None,
                 metrics_fn: Optional[Callable] = None,
                 clock: Optional[Callable[[], float]] = None):
        self._wksp = wksp
        self._clock = clock or time.monotonic
        self._edges_fn = edges_fn or (
            (lambda: flight.read_edges_raw(wksp) or {}) if wksp is not None
            else (lambda: {}))
        self._tiles_fn = tiles_fn or self._make_pod_tiles_fn(wksp, pod)
        # Tile-metric reader for the balance SLOs (the fd_pod
        # verify.shardN occupancy rows): shared-memory when the
        # workspace carries the flight registry, injectable for tests.
        self._metrics_fn = metrics_fn or (
            (lambda: flight.read_tiles(wksp) or {}) if wksp is not None
            else (lambda: {}))
        self.rec = flight.recorder("sentinel")
        self.burn = flags.get_float("FD_SLO_BURN")
        self.fast_s = flags.get_float("FD_SLO_FAST_S")
        self.slow_s = flags.get_float("FD_SLO_SLOW_S")
        self.interval_s = max(0.01,
                              flags.get_int("FD_SENTINEL_INTERVAL_MS") / 1e3)
        self.budgets_ms = {s.name: _budget_ms(s) for s in SLO_TABLE}
        # History of (t, {edge: buckets copy}) for window deltas; bound
        # by the slow window plus headroom so a long run stays O(1).
        cap = int(self.slow_s / self.interval_s) + 8
        self._hist: deque = deque(maxlen=max(cap, 8))
        self._rows = {}
        for s in SLO_TABLE:
            row = flight.slo_row(wksp, s.name) if wksp is not None else None
            if row is None:
                row = np.zeros(flight.SLO_SLOTS, np.uint64)
            self._rows[s.name] = row
        self._state: Dict[str, _SloState] = {
            s.name: _SloState() for s in SLO_TABLE}
        self.alerts: List[dict] = []
        self.evals = 0
        # liveness state
        self._progress_totals: Optional[int] = None
        self._progress_last_change: Optional[float] = None
        self._hb_seen: Dict[str, Tuple[int, float]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._stopped = False
        # fd_xray alert-time autopsies: a dedicated flusher thread
        # (None unless FD_XRAY_DIR is set) so poll() only ever
        # enqueues — the judge never blocks on file IO. Imported
        # lazily: xray imports this module for the SLO budget table.
        from firedancer_tpu.disco import xray as _xray

        self._xray_flusher = _xray.flusher_for_run(wksp)

    @staticmethod
    def _make_pod_tiles_fn(wksp, pod):
        """Heartbeat reader over the pod's tile cncs (None-safe)."""
        if wksp is None or pod is None:
            return lambda: {}
        from firedancer_tpu.tango.rings import Cnc

        cncs = {}
        try:
            fd = pod.subpod("firedancer").to_dict()
        except Exception:
            fd = {}

        def walk(tree, prefix=""):
            for name, sub in sorted(tree.items()):
                if not isinstance(sub, dict):
                    continue
                dotted = f"{prefix}.{name}" if prefix else name
                if "cnc" in sub:
                    try:
                        cncs[dotted] = Cnc(wksp, sub["cnc"])
                    except Exception:
                        pass
                walk(sub, dotted)

        walk(fd)

        def read():
            out = {}
            for name, cnc in cncs.items():
                try:
                    out[name] = (cnc.signal_query(), cnc.heartbeat_query())
                except Exception:
                    continue
            return out

        return read

    # -- evaluation ------------------------------------------------------

    def _window_delta(self, now: float, window_s: float, edge_labels,
                      cur: Dict[str, np.ndarray]):
        """Bucket-count delta over the labels for the best history
        entry spanning the window, or None when the history is too
        short (early-run transients must not alert)."""
        base = None
        for t, snap in self._hist:
            if t <= now - window_s:
                base = snap   # latest entry old enough
            else:
                break
        if base is None:
            return None
        delta = np.zeros(flight.N_BUCKETS, np.int64)
        for label in edge_labels:
            c = cur.get(label)
            if c is None:
                continue
            b = base.get(label)
            d = c[1:].astype(np.int64)
            if b is not None:
                d = d - b[1:].astype(np.int64)
            delta += d
        return delta

    def _edge_labels_for(self, slo: SLO, cur) -> List[str]:
        """The edge plus its per-lane variants (replay_verify.v1 ...)."""
        e = slo.edge_or_stage
        return [label for label in cur
                if label == e or label.startswith(e + ".v")]

    def _eval_latency(self, slo: SLO, now: float, cur) -> Tuple[bool, int]:
        threshold_ns = self.budgets_ms[slo.name] * 1_000_000
        bad_from = _bad_from_bucket(threshold_ns)
        err_budget = max(1e-9, 1.0 - slo.target)
        labels = self._edge_labels_for(slo, cur)
        if not labels:
            return False, 0
        burns = []
        for w in (self.fast_s, self.slow_s):
            delta = self._window_delta(now, w, labels, cur)
            if delta is None:
                return False, 0   # window not spanned yet
            n = int(delta.sum())
            if n < MIN_WINDOW_N:
                return False, 0
            bad = int(delta[bad_from:].sum())
            burns.append((bad / n) / err_budget)
        breach = all(b >= self.burn for b in burns)
        return breach, int(max(burns) * 1000)

    def _eval_balance(self, slo: SLO, now: float) -> Tuple[bool, int]:
        """fd_pod shard-occupancy balance over the `<base>.shardN`
        tile-metric rows: armed once every shard group has seen real
        volume (MIN_SHARD_LANES average per shard), breaches when the
        busiest shard's dispatched lanes exceed the laziest's by more
        than the budget ratio (FD_SLO_SHARD_BALANCE_PCT, percent) —
        or when a shard sits at zero under load (the starved-device
        signature). Returns (breach, worst ratio in milli-x)."""
        rows = self._metrics_fn() or {}
        budget_pct = self.budgets_ms[slo.name]   # percent, not ms
        groups: Dict[str, list] = {}
        for label, m in rows.items():
            base, sep, idx = label.rpartition(".shard")
            if not sep or not idx.isdigit():
                continue
            groups.setdefault(base, []).append(int(m.get("lanes", 0)))
        breach = False
        worst_milli = 0
        for base, occ in groups.items():
            if len(occ) < 2:
                continue
            total = sum(occ)
            if total < MIN_SHARD_LANES * len(occ):
                continue   # not armed until every shard could have fed
            lo, hi = min(occ), max(occ)
            ratio_milli = (int(hi * 1000 / lo) if lo else (1 << 30))
            worst_milli = max(worst_milli, ratio_milli)
            if lo == 0 or hi * 100 > budget_pct * lo:
                breach = True
        return breach, worst_milli

    def _eval_drain_eff(self, slo: SLO, now: float) -> Tuple[bool, int]:
        """fd_drain filter effectiveness over the verify tiles' claim
        counters (the drain_novel / drain_maybe flight rows, summed
        across lanes and shards): armed once MIN_DRAIN_CLAIMS claims
        have published, breaches when the definitely-novel share of
        published clean txns falls below the budget percentage
        (FD_SLO_DRAIN_EFF_PCT). Returns (breach, effectiveness in
        milli — novel per mille of all claims)."""
        rows = self._metrics_fn() or {}
        novel = maybe = 0
        for m in rows.values():
            novel += int(m.get("drain_novel", 0))
            maybe += int(m.get("drain_maybe", 0))
        total = novel + maybe
        if total < MIN_DRAIN_CLAIMS:
            return False, 0   # not armed: off-run or early transient
        pct = self.budgets_ms[slo.name]   # percent, not ms
        return novel * 100 < pct * total, int(novel * 1000 / total)

    def _eval_slope(self, slo: SLO, now: float) -> Tuple[bool, int]:
        """fd_soak resource-growth tripwire: evaluates the registered
        slope source's fitted trend for this SLO's resource against the
        budget (flag units: KiB/min, milli-slots/min, entries/hour).
        Unarmed — (False, 0) — without a source (every non-soak run),
        before MIN_SLOPE_SAMPLES probe samples, or when the source
        omits the key. Returns (breach, slope as milli-multiples of
        the budget, floored at 0 — a shrinking resource is not negative
        burn)."""
        src = _SLOPE_SOURCE
        if src is None:
            return False, 0
        try:
            d = src() or {}
        except Exception:
            return False, 0   # a dying probe must not take down polls
        if int(d.get("samples") or 0) < MIN_SLOPE_SAMPLES:
            return False, 0
        v = d.get(_SLOPE_KEYS[slo.edge_or_stage])
        if v is None:
            return False, 0
        budget = max(1, self.budgets_ms[slo.name])   # flag units, not ms
        milli = max(0, int(float(v) * 1000 / budget))
        return float(v) > budget, milli

    def _eval_fairness(self, slo: SLO, now: float) -> Tuple[bool, int]:
        """fd_fabric per-tenant admission fairness over the registered
        tenant source (evaluate_tenant_summary's live twin). Unarmed —
        (False, 0) — without a source (every non-fabric run) or before
        MIN_TENANT_OFFERED total offered txns. Returns (breach, worst
        honest-tenant shed per-mille of its offered)."""
        src = _TENANT_SOURCE
        if src is None:
            return False, 0
        try:
            tenants = src() or {}
        except Exception:
            return False, 0   # a dying source must not take down polls
        total = sum(int(r.get("offered", 0)) for r in tenants.values())
        if total < MIN_TENANT_OFFERED:
            return False, 0
        budget_pct = self.budgets_ms[slo.name]   # percent, not ms
        breach = False
        worst_milli = 0
        for row in tenants.values():
            if not row.get("honest", False):
                continue
            offered = int(row.get("offered", 0))
            shed = int(row.get("shed", 0))
            if offered <= 0:
                continue
            worst_milli = max(worst_milli, int(shed * 1000 / offered))
            if shed * 100 > budget_pct * offered:
                breach = True
        return breach, worst_milli

    def _eval_progress(self, slo: SLO, now: float, cur) -> Tuple[bool, int]:
        total = sum(int(row[1:].sum()) for row in cur.values())
        if self._progress_totals is None or total != self._progress_totals:
            self._progress_totals = total
            self._progress_last_change = now
        if not total or self._progress_last_change is None:
            return False, 0   # not armed until the first frag moves
        stall_ms = int((now - self._progress_last_change) * 1e3)
        return stall_ms > self.budgets_ms[slo.name], stall_ms

    def _eval_heartbeat(self, slo: SLO, now: float) -> Tuple[bool, int, list]:
        worst_ms = 0
        stalled = []
        for name, (signal, hb) in self._tiles_fn().items():
            if signal != 1 or not hb:   # only RUNning, beating tiles
                self._hb_seen.pop(name, None)
                continue
            seen = self._hb_seen.get(name)
            if seen is None or seen[0] != hb:
                self._hb_seen[name] = (hb, now)
                continue
            age_ms = int((now - seen[1]) * 1e3)
            worst_ms = max(worst_ms, age_ms)
            if age_ms > self.budgets_ms[slo.name]:
                stalled.append(name)
        return bool(stalled), worst_ms, stalled

    def poll(self, now: Optional[float] = None) -> None:
        """One evaluation pass over every declared SLO."""
        if now is None:
            now = self._clock()
        cur = {label: np.asarray(row, np.uint64).copy()
               for label, row in self._edges_fn().items()}
        self.evals += 1
        for slo in SLO_TABLE:
            detail: dict = {}
            if slo.kind == "latency":
                breach, burn_milli = self._eval_latency(slo, now, cur)
            elif slo.kind == "balance":
                breach, burn_milli = self._eval_balance(slo, now)
            elif slo.kind == "effectiveness":
                breach, burn_milli = self._eval_drain_eff(slo, now)
            elif slo.kind == "slope":
                breach, burn_milli = self._eval_slope(slo, now)
            elif slo.kind == "fairness":
                breach, burn_milli = self._eval_fairness(slo, now)
            elif slo.edge_or_stage == "progress":
                breach, burn_milli = self._eval_progress(slo, now, cur)
            else:
                breach, burn_milli, stalled = self._eval_heartbeat(slo, now)
                if stalled:
                    detail["tiles"] = stalled
            st = self._state[slo.name]
            st.burn_milli = burn_milli
            if breach:
                st.breach_polls += 1
                if not st.alerting:
                    st.alerting = True
                    st.alerts += 1
                    alert = {
                        "slo": slo.name,
                        # NB not "kind": these fields land verbatim in
                        # FlightRecorder.record(kind, **fields).
                        "slo_kind": slo.kind,
                        "edge_or_stage": slo.edge_or_stage,
                        "burn_milli": burn_milli,
                        "budget_ms": self.budgets_ms[slo.name],
                        "fault_classes": list(slo.fault_classes),
                        **detail,
                    }
                    self.alerts.append(alert)
                    self.rec.record("slo_alert", **alert)
                    if self._xray_flusher is not None:
                        # Automated postmortem: bundle the window's
                        # exemplars + waterfall + suspects off-thread.
                        self._xray_flusher.request(
                            f"slo:{slo.name}", [alert])
            elif st.alerting:
                st.alerting = False
                self.rec.record("slo_clear", slo=slo.name,
                                burn_milli=burn_milli)
            row = self._rows[slo.name]
            row[flight.SLO_EVALS] += np.uint64(1)
            row[flight.SLO_ALERTS] = np.uint64(st.alerts)
            row[flight.SLO_BREACH_POLLS] = np.uint64(st.breach_polls)
            row[flight.SLO_BURN_MILLI] = np.uint64(max(burn_milli, 0))
            row[flight.SLO_STATE] = np.uint64(1 if st.alerting else 0)
        self._hist.append((now, cur))

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Sentinel":
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.poll()
                except Exception as e:
                    # The judge must never take down the judged — but a
                    # dead judge must not be silent either (a swallowed
                    # TypeError here once suppressed every later alert):
                    # record the death so it shows in the flight dump.
                    self.rec.record("sentinel_error", err=repr(e)[:200])
                    return

        self._thread = threading.Thread(target=loop, name="fd_sentinel",
                                        daemon=True)
        self._thread.start()
        return self

    def alive(self) -> bool:
        """True while the poller thread exists and has not exited —
        the runners' wksp.leave() guard must include this: a poll
        descheduled past stop()'s join budget still holds numpy views
        over the mapped registry rows."""
        return (self._thread is not None and self._thread.is_alive()) or (
            self._xray_flusher is not None and self._xray_flusher.alive())

    def stop(self) -> dict:
        """Stop the poller (idempotent), run one final pass, return the
        run summary that lands in PipelineResult.slo."""
        if not self._stopped:
            self._stop.set()
            if self._thread is not None:
                # One poll is bounded work (shared-memory reads + int
                # math), so a generous join covers even a heavily
                # loaded host; alive() lets the runner's leave-guard
                # catch the pathological remainder.
                self._thread.join(timeout=10.0)
            if self._thread is None or not self._thread.is_alive():
                # Final pass ONLY once the loop thread is provably
                # dead: poll() mutates the history deque and the
                # shared rows unsynchronized, so racing a straggler
                # poll would tear both.
                try:
                    self.poll()
                except Exception:
                    pass
            if self._xray_flusher is not None:
                # Drain + stop the autopsy writer BEFORE the runner can
                # leave the workspace (it reads mapped registry rows).
                self._xray_flusher.stop()
            self._stopped = True
        return self.summary()

    def summary(self) -> dict:
        return {
            "evals": self.evals,
            "alert_cnt": len(self.alerts),
            "alerts": list(self.alerts),
            "slos": {
                name: {
                    "state": "alert" if st.alerting else "ok",
                    "alerts": st.alerts,
                    "breach_polls": st.breach_polls,
                    "burn_milli": st.burn_milli,
                }
                for name, st in self._state.items()
            },
        }


def start_for_run(wksp, pod=None) -> Optional[Sentinel]:
    """The one pipeline-runner entry point: a started Sentinel when
    FD_SENTINEL is on, else None. The caller owns stop()."""
    if not flags.get_bool("FD_SENTINEL"):
        return None
    return Sentinel(wksp, pod).start()


def evaluate_edges_summary(edges: Dict[str, dict],
                           budgets_ms: Optional[Dict[str, int]] = None,
                           ) -> List[dict]:
    """Standalone latency-SLO evaluation over EDGE SUMMARIES (a flight
    dump's "edges" section / PipelineResult.stage_hist): a whole-run,
    single-window check of the docs/LATENCY.md rule p99_ns_le <= 2x
    budget. Returns the violation list (empty = clean)."""
    budgets = budgets_ms or {s.name: _budget_ms(s) for s in SLO_TABLE}
    out = []
    for slo in SLO_TABLE:
        if slo.kind != "latency":
            continue
        labels = [label for label in (edges or {})
                  if label == slo.edge_or_stage
                  or label.startswith(slo.edge_or_stage + ".v")]
        for label in labels:
            s = edges[label]
            # Accept-and-ignore anything that is not an edge summary:
            # newer dumps nest extra sections (fd_xray queue rows,
            # future schema growth) and this evaluator must keep
            # parsing BOTH old and new envelopes.
            if not isinstance(s, dict) or not s.get("n") \
                    or "p99_ns_le" not in s:
                continue
            limit = 2 * budgets[slo.name] * 1_000_000
            if s["p99_ns_le"] > limit:
                out.append({
                    "slo": slo.name, "edge": label,
                    "p99_ns_le": s["p99_ns_le"],
                    "limit_ns": limit, "n": s["n"],
                })
    return out


# --------------------------------------------------------------------------
# Perf-regression tracker: the schema-normalized timeline.
# --------------------------------------------------------------------------

ARTIFACT_GLOBS = (
    "BENCH_r[0-9]*.json", "REPLAY_r[0-9]*.json", "REPLAY_CPU_r[0-9]*.json",
    "MULTICHIP_r[0-9]*.json", "PACK_r[0-9]*.json", "HOSTFEED_r[0-9]*.json",
    "SIEGE_r[0-9]*.json", "POD_r[0-9]*.json", "DRAIN_r[0-9]*.json",
    "SOAK_r[0-9]*.json", "FABRIC_r[0-9]*.json",
)

_METRIC_KIND = {
    "ed25519_verify_throughput": "verify_bench",
    "replay_pipeline_throughput": "replay",
    "replay_pipeline_throughput_cpu": "replay_cpu",
    "pack_gc_schedule": "pack",
    "hostfeed_native_rates": "hostfeed",
    "feed_replay_smoke": "feed_smoke",
    "quic_siege_profile": "siege",
    "pod_aggregate_throughput": "pod",
    "drain_pipeline_throughput": "drain",
    "soak_run": "soak",
    "fabric_aggregate_throughput": "fabric",
    "note": "note",
}


@dataclass
class TimelineEntry:
    source: str                 # "BENCH_LOG.jsonl:7" / artifact filename
    kind: str                   # verify_bench | replay | replay_cpu |
                                # pack | multichip | hostfeed | note |
                                # round_status | feed_smoke | unknown
    rec: dict                   # the normalized record
    ts: Optional[str] = None
    schema_version: int = 0     # 0 = pre-schema legacy line
    legacy: bool = True
    parse_error: Optional[str] = None


def _classify(rec: dict, source: str) -> TimelineEntry:
    metric = rec.get("metric")
    if metric in _METRIC_KIND:
        kind = _METRIC_KIND[metric]
    elif "n_devices" in rec and "rc" in rec:
        kind = "multichip"
    elif "cmd" in rec and "rc" in rec:
        kind = "round_status"
    elif "rlc_mesh_speedup" in rec or metric == "rlc_mesh_scaling":
        kind = "mesh_scaling"
    else:
        kind = "unknown"
    try:
        sv = int(rec.get("schema_version") or 0)
    except (TypeError, ValueError):
        # A non-numeric schema_version is valid JSON, so it lands here
        # instead of a parse_error: classify it LEGACY (it can never
        # grade a prediction) and let bench_log_check flag the shape.
        sv = 0
    return TimelineEntry(source=source, kind=kind, rec=rec,
                         ts=rec.get("ts"), schema_version=sv,
                         legacy=not sv)


def parse_bench_log(path: str) -> List[TimelineEntry]:
    """Every BENCH_LOG.jsonl line as a timeline entry — tolerant of
    malformed lines (they become parse_error entries; the STRICT shape
    gate is scripts/bench_log_check.py, wired into ci.sh)."""
    out: List[TimelineEntry] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            src = f"{os.path.basename(path)}:{i}"
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                out.append(TimelineEntry(source=src, kind="invalid",
                                         rec={}, parse_error=str(e)))
                continue
            out.append(_classify(rec, src))
    return out


def _tail_json(tail: str) -> Optional[dict]:
    """Last JSON-object line hiding in a round wrapper's captured tail
    (old BENCH_rNN.json artifacts wrap the runner output)."""
    for line in reversed((tail or "").splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def parse_artifact(path: str) -> List[TimelineEntry]:
    src = os.path.basename(path)
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [TimelineEntry(source=src, kind="invalid", rec={},
                              parse_error=str(e))]
    if not isinstance(rec, dict):
        return [TimelineEntry(source=src, kind="invalid", rec={},
                              parse_error="artifact is not a JSON object")]
    entries = [_classify(rec, src)]
    if entries[0].kind in ("round_status", "multichip"):
        # Salvage the measurement line a wrapper captured, when any.
        inner = rec.get("parsed") or _tail_json(rec.get("tail", ""))
        if isinstance(inner, dict) and inner.get("metric"):
            e = _classify(inner, src + " (tail)")
            entries.append(e)
    return entries


def load_timeline(root: str) -> List[TimelineEntry]:
    """BENCH_LOG.jsonl + the artifact family under `root`, in log order
    then filename order — the ingest surface fd_report renders."""
    out: List[TimelineEntry] = []
    log = os.path.join(root, "BENCH_LOG.jsonl")
    if os.path.exists(log):
        out.extend(parse_bench_log(log))
    for pattern in ARTIFACT_GLOBS:
        for path in sorted(glob.glob(os.path.join(root, pattern))):
            out.extend(parse_artifact(path))
    return out


def _device_measurement(e: TimelineEntry) -> bool:
    """A real on-device measurement (regression/ledger material): has a
    value, not the CPU-fallback rung, not a stale re-print."""
    r = e.rec
    return bool(
        e.kind in ("verify_bench", "replay", "replay_cpu")
        and r.get("value")
        and not r.get("cpu_fallback")
        and not r.get("stale")
        and not r.get("error")
    )


def series_key(e: TimelineEntry) -> str:
    r = e.rec
    if e.kind == "verify_bench":
        return f"{r.get('metric')}:{r.get('mode')}:B{r.get('batch')}"
    return str(r.get("metric"))


def regressions(timeline: List[TimelineEntry],
                pct: Optional[float] = None) -> List[dict]:
    """Flag device measurements below the rolling best-of baseline of
    their series (metric x mode x batch) by more than pct percent."""
    if pct is None:
        pct = flags.get_float("FD_REPORT_REGRESS_PCT")
    best: Dict[str, float] = {}
    out = []
    for e in timeline:
        if not _device_measurement(e):
            continue
        key = series_key(e)
        v = float(e.rec["value"])
        b = best.get(key)
        if b is not None and v < b * (1.0 - pct / 100.0):
            out.append({
                "series": key, "source": e.source, "ts": e.ts,
                "value": v, "rolling_best": b,
                "drop_pct": round(100.0 * (1.0 - v / b), 1),
            })
        best[key] = max(b or 0.0, v)
    return out


def pod_status(timeline: List[TimelineEntry]) -> List[dict]:
    """Every fd_pod artifact (POD_r*.json) with its graded gates:
    digest parity vs single-shard, zero sentinel alerts, shard
    occupancy balance, and the overlap probe under its recorded gate
    basis. scripts/pod_smoke.py writes the verdicts; fd_report renders
    this table and prediction 11 grades the on-device rows."""
    out = []
    for e in timeline:
        if e.kind != "pod":
            continue
        r = e.rec
        out.append({
            "source": e.source,
            "ts": e.ts,
            "value": r.get("value"),
            "unit": r.get("unit"),
            "devices": r.get("devices"),
            "on_device": bool(r.get("on_device")),
            "ok": bool(r.get("ok")),
            "digest_parity": bool(r.get("digest_parity")),
            "alert_cnt": r.get("alert_cnt"),
            "shard_balance": r.get("shard_balance"),
            "overlap_ms": (r.get("overlap") or {}).get("overlap_ms"),
            "tail_hidden_est": (r.get("overlap") or {}).get(
                "tail_hidden_est"),
            "gate": (r.get("overlap") or {}).get("gate"),
            "failures": list(r.get("failures") or []),
        })
    return out


def fabric_status(timeline: List[TimelineEntry]) -> List[dict]:
    """Every fd_fabric artifact (FABRIC_r*.json) with its graded gates:
    merged sink digests bit-exact vs the single-process control, exact
    per-tenant ledger parity, cross-host balance, zero sentinel/
    fairness alerts, and the aggregate-vs-control scaling under its
    recorded gate basis. scripts/fabric_smoke.py writes the verdicts;
    fd_report renders this table and prediction 15 grades the
    on-device rows."""
    out = []
    for e in timeline:
        if e.kind != "fabric":
            continue
        r = e.rec
        control = r.get("control") or {}
        out.append({
            "source": e.source,
            "ts": e.ts,
            "value": r.get("value"),
            "unit": r.get("unit"),
            "hosts": r.get("hosts"),
            "devices": r.get("devices"),
            "on_device": bool(r.get("on_device")),
            "ok": bool(r.get("ok")),
            "digest_parity": bool(r.get("digest_parity")),
            "alert_cnt": r.get("alert_cnt"),
            "balance_ratio": r.get("balance_ratio"),
            "control_value": control.get("value"),
            "gate_basis": r.get("gate_basis"),
            "profile": r.get("profile"),
            "failures": list(r.get("failures") or []),
        })
    return out


def drain_status(timeline: List[TimelineEntry]) -> List[dict]:
    """Every fd_drain artifact (DRAIN_r*.json) with its graded gates:
    drain on/off digest parity, probe-skip accounting parity (skipped
    + probed == novel-claims + maybe-dups), device-pack admissibility
    with exact fallback accounting, zero sentinel alerts.
    scripts/drain_smoke.py writes the verdicts; fd_report renders this
    table and prediction 13 grades the on-device rows."""
    out = []
    for e in timeline:
        if e.kind != "drain":
            continue
        r = e.rec
        pack = r.get("pack") or {}
        out.append({
            "source": e.source,
            "ts": e.ts,
            "value": r.get("value"),
            "unit": r.get("unit"),
            "on_device": bool(r.get("on_device")),
            "ok": bool(r.get("ok")),
            "digest_parity": bool(r.get("digest_parity")),
            "probe_skips": r.get("probe_skips"),
            "false_novel": r.get("false_novel"),
            "drain_speedup": r.get("drain_speedup"),
            "pack_blocks_device": pack.get("blocks_device"),
            "pack_fallbacks": pack.get("fallbacks"),
            "alert_cnt": r.get("alert_cnt"),
            "failures": list(r.get("failures") or []),
        })
    return out


def siege_status(timeline: List[TimelineEntry]) -> List[dict]:
    """Every fd_siege profile artifact (SIEGE_r*.json) with its graded
    gates: zero sentinel burn-rate alerts, shed-accounting parity
    (admitted + shed == offered), chaos tri-counter parity, bit-exact
    sink digests for admitted traffic. scripts/fd_siege.py writes the
    verdicts into the artifact; fd_report renders this table."""
    out = []
    for e in timeline:
        if e.kind != "siege":
            continue
        r = e.rec
        out.append({
            "source": e.source,
            "profile": r.get("profile"),
            "ts": e.ts,
            "value": r.get("value"),
            "unit": r.get("unit"),
            "ok": bool(r.get("ok")),
            "alert_cnt": (r.get("slo") or {}).get("alert_cnt"),
            "offered": (r.get("quic") or {}).get("offered"),
            "admitted": (r.get("quic") or {}).get("admitted"),
            "shed": (r.get("quic") or {}).get("shed_total"),
            "failures": list(r.get("failures") or []),
        })
    return out


def soak_status(timeline: List[TimelineEntry]) -> List[dict]:
    """Every fd_soak artifact (SOAK_r*.json) with its graded gates:
    zero unexplained sentinel alerts, slope rows within budget, the
    reconfig trail (applied swaps with digest-exact continuity),
    respawn rate under budget, and zero dropped txns.
    scripts/fd_soak.py / scripts/soak_smoke.py write the verdicts;
    fd_report renders this table and prediction 14 grades the
    on-device rows."""
    out = []
    for e in timeline:
        if e.kind != "soak":
            continue
        r = e.rec
        slo = r.get("slo") or {}
        slopes = r.get("slopes") or {}
        reconfig = r.get("reconfig") or {}
        cont = r.get("continuity") or {}
        out.append({
            "source": e.source,
            "ts": e.ts,
            "value": r.get("value"),
            "unit": r.get("unit"),
            "on_device": bool(r.get("on_device")),
            "ok": bool(r.get("ok")),
            "duration_s": r.get("duration_s"),
            "phases": len(r.get("phases") or []),
            "alert_cnt": slo.get("alert_cnt"),
            "unexplained_alerts": slo.get("unexplained_alerts"),
            "slopes_within_budget": slopes.get("within_budget"),
            "heap_kb_min": slopes.get("heap_kb_min"),
            "reconfigs_applied": reconfig.get("applied"),
            "reconfigs_refused": reconfig.get("refused"),
            "digest_match": cont.get("digest_match"),
            "dropped": cont.get("dropped"),
            "respawn_ok": (r.get("respawn") or {}).get("ok"),
            "failures": list(r.get("failures") or []),
        })
    return out


# --------------------------------------------------------------------------
# The prediction ledger: the fifteen ROOFLINE.md falsifiable predictions,
# each with a machine-checkable match rule over the timeline. A rule
# matches only schema_version >= 2, on-device, non-stale records — the
# fused-front-end era — so the pre-round-10 history can neither confirm
# nor falsify, and the BENCH_r06 hardware session auto-grades.
# --------------------------------------------------------------------------


def _sv2_verify(timeline, mode=None, batch=None):
    for e in timeline:
        if (e.kind == "verify_bench" and e.schema_version >= 2
                and _device_measurement(e)
                and (mode is None or e.rec.get("mode") == mode)
                and (batch is None or e.rec.get("batch") == batch)):
            yield e


def _best(entries) -> Optional[TimelineEntry]:
    entries = list(entries)
    if not entries:
        return None
    return max(entries, key=lambda e: float(e.rec["value"]))


def _stage(e: TimelineEntry, key: str) -> Optional[float]:
    sm = e.rec.get("stage_ms")
    if isinstance(sm, dict) and key in sm and sm[key] is not None:
        return float(sm[key])
    return None


def _check_p1(timeline):
    rlc = _best(_sv2_verify(timeline, "rlc", 8192))
    direct = _best(_sv2_verify(timeline, "direct", 8192))
    if rlc is None or direct is None:
        return "pending", None, None
    ratio = float(rlc.rec["value"]) / float(direct.rec["value"])
    return (("confirmed" if ratio >= 1.0 else "falsified"),
            f"rlc/direct = {ratio:.2f}x", rlc.source)


def _check_p2(timeline):
    rlc = _best(_sv2_verify(timeline, "rlc", 16384))
    direct = _best(_sv2_verify(timeline, "direct", 8192))
    if rlc is None or direct is None:
        return "pending", None, None
    ratio = float(rlc.rec["value"]) / float(direct.rec["value"])
    return (("confirmed" if ratio >= 1.8 else "falsified"),
            f"rlc@16384/direct@8192 = {ratio:.2f}x", rlc.source)


def _check_p3(timeline):
    k32 = _best(e for e in _sv2_verify(timeline, "rlc", 8192)
                if e.rec.get("torsion_k") == 32)
    k64 = _best(e for e in _sv2_verify(timeline, "rlc", 8192)
                if e.rec.get("torsion_k") == 64)
    if k32 is None or k64 is None:
        return "pending", None, None
    gain = float(k32.rec["value"]) / float(k64.rec["value"]) - 1.0
    return (("confirmed" if 0.05 <= gain <= 0.25 else "falsified"),
            f"K=32 vs K=64: {gain * 100:+.1f}%", k32.source)


def _check_p4(timeline):
    e = _best(_sv2_verify(timeline, "rlc"))
    if e is None or "rlc_fallbacks" not in e.rec:
        return "pending", None, None
    fb = int(e.rec["rlc_fallbacks"])
    return (("confirmed" if fb == 0 else "falsified"),
            f"rlc_fallbacks = {fb}", e.source)


def _check_stage(timeline, key, budget_ms, fused_only=False):
    for e in _sv2_verify(timeline, "rlc"):
        v = _stage(e, key)
        if v is None:
            continue
        if fused_only and not (e.rec.get("stage_ms") or {}).get("fused"):
            continue
        return (("confirmed" if v <= budget_ms else "falsified"),
                f"stage_ms.{key} = {v:.2f} ms (budget {budget_ms})",
                e.source)
    return "pending", None, None


def _check_p8(timeline):
    for e in timeline:
        r = e.rec
        speedup = r.get("rlc_mesh_speedup")
        if speedup is None and r.get("metric") == "rlc_mesh_scaling":
            speedup = r.get("speedup")
        # The devices field is REQUIRED for a match: a record that
        # omits it must stay pending, not default its way into grading
        # a multi-chip prediction.
        if speedup is None or "devices" not in r or int(r["devices"]) < 2:
            continue
        return (("confirmed" if float(speedup) >= 1.8 else "falsified"),
                f"2-device rlc speedup = {float(speedup):.2f}x", e.source)
    return "pending", None, None


def _check_p9(timeline):
    for e in reversed(list(_sv2_verify(timeline, "rlc"))):
        sweep = e.rec.get("b_sweep_measured")
        if not isinstance(sweep, dict):
            continue
        vals = {int(k): float(v) for k, v in sweep.items()}
        if not {8192, 16384, 32768} <= set(vals):
            continue
        ordered = vals[32768] > vals[16384] > vals[8192]
        return (("confirmed" if ordered else "falsified"),
                "b_sweep " + " / ".join(
                    f"{b}:{vals[b]:.0f}" for b in (8192, 16384, 32768)),
                e.source)
    # The headline-shape note also carries the sweep dict.
    for e in timeline:
        if e.kind == "note" and isinstance(
                e.rec.get("b_sweep_measured"), dict):
            vals = {int(k): float(v)
                    for k, v in e.rec["b_sweep_measured"].items()}
            if {8192, 16384, 32768} <= set(vals):
                ordered = vals[32768] > vals[16384] > vals[8192]
                return (("confirmed" if ordered else "falsified"),
                        "b_sweep " + " / ".join(
                            f"{b}:{vals[b]:.0f}"
                            for b in (8192, 16384, 32768)),
                        e.source)
    return "pending", None, None


def _check_p10(timeline):
    for e in _sv2_verify(timeline, "rlc"):
        sm = e.rec.get("stage_ms") or {}
        v = sm.get("decompress")
        if v is None or not sm.get("decompress_batched"):
            continue
        inv = sm.get("decompress_inversions")
        verdict = ("confirmed"
                   if float(v) <= DECOMPRESS_BATCHED_BUDGET_MS
                   else "falsified")
        return (verdict,
                f"stage_ms.decompress = {float(v):.2f} ms batched "
                f"(analytic inversions {inv})", e.source)
    return "pending", None, None


def _check_p12(timeline):
    """fd_msm2 signed-digit headline: matches rlc records whose
    stage_ms carries the msm_signed: true plan attribution
    (profile_stages writes it alongside the msm_plan token whenever
    the active schedule is balanced-recode) — the unsigned-baseline
    history can never grade this, exactly like the fused_only rule on
    predictions 5/6. Grades stage_ms.msm against the PR-16 re-derived
    budget; the schedule-search evidence behind the budget lives in
    build/msm_search.json."""
    for e in _sv2_verify(timeline, "rlc"):
        sm = e.rec.get("stage_ms") or {}
        v = sm.get("msm")
        if v is None or not sm.get("msm_signed"):
            continue
        budget = STAGE_BUDGETS_MS["msm"]
        return (("confirmed" if float(v) <= budget else "falsified"),
                f"stage_ms.msm = {float(v):.2f} ms under "
                f"{sm.get('msm_plan')} (budget {budget})", e.source)
    return "pending", None, None


def _check_p11(timeline):
    """fd_pod hardware headline: matches ON-DEVICE pod artifacts only
    (metric pod_aggregate_throughput, on_device true, >= 8 devices) —
    the virtual-CPU-mesh POD_r* smokes carry on_device false and can
    never grade this, exactly like the sv<2 rule elsewhere. Confirmed
    iff the aggregate beats wiredancer's 1.04M/s reference AND the
    double buffer demonstrably pipelined (the MEASURED overlap gate
    with overlap_ms > 0 — tail_hidden_est alone is a stage-time
    RATIO from the serialized probe halves and would read 1.0 even
    with the pipeline broken) AND that ratio shows >= 80% of the tail
    fits behind the next batch's local_fill. A record without the
    measured gate (a 1-core basis cannot exist on device hardware)
    stays pending rather than grading on unmeasurable evidence."""
    for e in timeline:
        r = e.rec
        if (r.get("metric") != "pod_aggregate_throughput"
                or e.schema_version < 2 or not r.get("on_device")):
            continue
        try:
            devices = int(r.get("devices") or 0)
        except (TypeError, ValueError):
            continue
        if devices < 8:
            continue
        overlap = r.get("overlap") or {}
        hidden = overlap.get("tail_hidden_est")
        oms = overlap.get("overlap_ms")
        v = r.get("value")
        if (v is None or hidden is None or oms is None
                or overlap.get("gate") != "measured"):
            continue   # unmeasurable record: keep pending
        ok = (float(v) >= 1_040_000.0 and float(oms) > 0
              and float(hidden) >= 0.8)
        return (("confirmed" if ok else "falsified"),
                f"{float(v):,.0f} verifies/s @ {devices} shards, "
                f"overlap {float(oms):.1f} ms, tail hidden "
                f"{float(hidden) * 100:.0f}%", e.source)
    return "pending", None, None


def _check_p13(timeline):
    """fd_drain device headline: matches ON-DEVICE drain artifacts
    only (metric drain_pipeline_throughput, on_device true) that carry
    BOTH halves of the prediction — the replay speedup over the PR-13
    host-drain baseline AND the device pack rewards/CU ratio at a
    >= 65536-txn block. The CPU-backend DRAIN_r* smokes carry
    on_device: false and can never grade this; a device record missing
    either half stays pending rather than grading on partial
    evidence."""
    for e in timeline:
        r = e.rec
        if (r.get("metric") != "drain_pipeline_throughput"
                or e.schema_version < 2 or not r.get("on_device")):
            continue
        speedup = r.get("drain_speedup")
        pack = r.get("pack") or {}
        ratio = pack.get("rewards_per_cu_ratio")
        try:
            batch = int(pack.get("batch") or 0)
        except (TypeError, ValueError):
            continue
        if speedup is None or ratio is None or batch < 65536:
            continue   # partial record: keep pending
        ok = float(speedup) >= 1.5 and float(ratio) >= 1.0
        return (("confirmed" if ok else "falsified"),
                f"drain speedup {float(speedup):.2f}x, pack rewards/CU "
                f"ratio {float(ratio):.2f} @ B={batch}", e.source)
    return "pending", None, None


def _check_p14(timeline):
    """fd_soak hardware headline: matches ON-DEVICE soak artifacts
    only (metric soak_run, on_device true) that carry every judgment
    block — duration, the sentinel's unexplained-alert count, the
    slope verdict, the reconfig trail, and the continuity accounting.
    The compressed CPU soak_smoke lane carries on_device: false and
    can never grade this; a device record missing any block, or one
    shorter than an hour, stays pending rather than grading on
    partial evidence."""
    for e in timeline:
        r = e.rec
        if (r.get("metric") != "soak_run" or e.schema_version < 2
                or not r.get("on_device")):
            continue
        slo = r.get("slo") or {}
        slopes = r.get("slopes") or {}
        reconfig = r.get("reconfig") or {}
        cont = r.get("continuity") or {}
        dur = r.get("duration_s")
        unexplained = slo.get("unexplained_alerts")
        within = slopes.get("within_budget")
        applied = reconfig.get("applied")
        dropped = cont.get("dropped")
        if (dur is None or unexplained is None or within is None
                or applied is None or dropped is None):
            continue   # partial record: keep pending
        if float(dur) < 3600.0:
            continue   # a sub-hour burst is not a soak
        ok = (int(unexplained) == 0 and bool(within)
              and int(applied) >= 1 and int(dropped) == 0)
        return (("confirmed" if ok else "falsified"),
                f"{float(dur) / 3600:.1f} h soak: {unexplained} "
                f"unexplained alerts, slopes within budget: "
                f"{bool(within)}, {applied} reconfig(s), "
                f"{dropped} dropped", e.source)
    return "pending", None, None


def _check_p15(timeline):
    for e in timeline:
        r = e.rec
        if (r.get("metric") != "fabric_aggregate_throughput"
                or e.schema_version < 2 or not r.get("on_device")):
            continue
        control = (r.get("control") or {}).get("value")
        v = r.get("value")
        try:
            hosts = int(r.get("hosts") or 0)
        except (TypeError, ValueError):
            continue
        if hosts < 2 or v is None or control is None or float(control) <= 0:
            continue   # partial record: keep pending
        ratio = float(v) / float(control)
        return (("confirmed" if ratio >= 1.9 else "falsified"),
                f"aggregate/control = {ratio:.2f}x at {hosts} hosts",
                e.source)
    return "pending", None, None


@dataclass(frozen=True)
class Prediction:
    pid: int
    name: str
    predicted: str
    rule: str                       # the machine-checkable match rule,
                                    # stated for the doc render
    check: Callable = field(repr=False, compare=False, default=None)


PREDICTIONS: Tuple[Prediction, ...] = (
    Prediction(1, "rlc beats direct at B=8192",
               "~1.5x on device",
               "best sv>=2 device rlc@8192 / best sv>=2 device "
               "direct@8192 >= 1.0",
               _check_p1),
    Prediction(2, "RLC advantage grows with batch",
               ">= 1.8x at B=16384 vs direct@8192",
               "best sv>=2 device rlc@16384 / best sv>=2 device "
               "direct@8192 >= 1.8",
               _check_p2),
    Prediction(3, "K=32 torsion saves ~10-15% at B=8192",
               "+10-15% over K=64",
               "sv>=2 device rlc@8192 records with torsion_k 32 vs 64: "
               "gain in [5%, 25%]",
               _check_p3),
    Prediction(4, "zero fallbacks on clean traffic",
               "rlc_fallbacks == 0 in the bench record",
               "best sv>=2 device rlc record has rlc_fallbacks == 0",
               _check_p4),
    Prediction(5, "fused front half <= 4 ms/8192",
               "stage_ms.sha <= 4.0 with fused: true",
               "first sv>=2 device rlc record whose stage_ms has "
               "fused: true — sha <= 4.0 ms",
               lambda t: _check_stage(t, "sha", STAGE_BUDGETS_MS["sha"],
                                      fused_only=True)),
    Prediction(6, "glue collapses on the fused path",
               "stage_ms.glue <= 2.5 ms",
               "first sv>=2 device rlc record whose stage_ms has "
               "fused: true — glue <= 2.5 ms",
               lambda t: _check_stage(t, "glue", STAGE_BUDGETS_MS["glue"],
                                      fused_only=True)),
    Prediction(7, "decompress <= 5 ms/8192",
               "stage_ms.decompress <= 5.0 ms at 2B stacked lanes",
               "first sv>=2 device rlc record with stage_ms — "
               "decompress <= 5.0 ms",
               lambda t: _check_stage(t, "decompress",
                                      STAGE_BUDGETS_MS["decompress"])),
    Prediction(8, "sharded MSM scales",
               ">= 1.8x single-device rlc rate at 2 devices, fixed "
               "per-device B",
               "any record carrying rlc_mesh_speedup (or metric "
               "rlc_mesh_scaling with a speedup field) at devices >= 2 "
               "— speedup >= 1.8",
               _check_p8),
    Prediction(9, "B-sweep follows fill efficiency",
               "rlc value ordering 32768 > 16384 > 8192",
               "latest sv>=2 rlc record (or headline-shape note) with "
               "b_sweep_measured covering 8192/16384/32768 — strictly "
               "increasing in B",
               _check_p9),
    Prediction(10, "Montgomery-batched decompress <= 2.5 ms/8192",
               "stage_ms.decompress <= 2.5 ms with decompress_batched: "
               "true (one fe_invert chain per 64 of the 2B stacked "
               "lanes)",
               "first sv>=2 device rlc record whose stage_ms has "
               "decompress_batched: true — decompress <= 2.5 ms",
               _check_p10),
    Prediction(11, "fd_pod 8-shard aggregate beats wiredancer",
               ">= 1.04M verifies/s aggregate on an 8+ device pod, "
               "with combine_tail >= 80% hidden behind the next "
               "batch's local_fill",
               "first sv>=2 pod_aggregate_throughput record with "
               "on_device: true, devices >= 8, and the MEASURED "
               "overlap gate — value >= 1.04e6 AND overlap.overlap_ms "
               "> 0 AND overlap.tail_hidden_est >= 0.8 "
               "(virtual-CPU-mesh POD_r* smokes carry on_device: "
               "false and never grade this)",
               _check_p11),
    Prediction(12, "signed-digit MSM holds the re-derived budget",
               "stage_ms.msm <= 6.5 ms per 8192-equiv under a signed "
               "(balanced-recode) schedule-search winner",
               "first sv>=2 device rlc record whose stage_ms has "
               "msm_signed: true — msm <= STAGE_BUDGETS_MS['msm'] "
               "(unsigned-baseline records never grade this; the "
               "candidate evidence is build/msm_search.json)",
               _check_p12),
    Prediction(13, "fd_drain device drain lifts the host pipeline",
               ">= 1.5x REPLAY_CPU throughput over the PR-13 "
               "host-drain baseline, with device pack schedules "
               "matching CPU greedy rewards/CU at B=65536",
               "first sv>=2 drain_pipeline_throughput record with "
               "on_device: true carrying drain_speedup and "
               "pack.rewards_per_cu_ratio at pack.batch >= 65536 — "
               "speedup >= 1.5 AND ratio >= 1.0 (CPU-backend DRAIN_r* "
               "smokes carry on_device: false and never grade this)",
               _check_p13),
    Prediction(14, "fd_soak N-hour soak survives live reconfig",
               ">= 1 h on-device soak under drifting load + chaos "
               "with zero unexplained sentinel alerts, flat "
               "resource slopes, >= 1 mid-run prewarmed ladder swap, "
               "and zero dropped txns",
               "first sv>=2 soak_run record with on_device: true and "
               "duration_s >= 3600 carrying slo.unexplained_alerts, "
               "slopes.within_budget, reconfig.applied, and "
               "continuity.dropped — unexplained == 0 AND "
               "within_budget AND applied >= 1 AND dropped == 0 "
               "(the compressed CPU soak_smoke lane carries "
               "on_device: false and never grades this)",
               _check_p14),
    Prediction(15, "fd_fabric 2-host aggregate scales near-linearly",
               ">= 1.9x the single-process control at 2 hosts (per-"
               "host ingest stays host-local; only the tiny rlc "
               "window/trial partials cross DCN)",
               "first sv>=2 fabric_aggregate_throughput record with "
               "on_device: true, hosts >= 2, and a control block — "
               "value / control.value >= 1.9 (the 2-process CPU-mesh "
               "FABRIC_r* smokes carry on_device: false and never "
               "grade this)",
               _check_p15),
)


def prediction_ledger(timeline: List[TimelineEntry]) -> List[dict]:
    """Every ROOFLINE prediction with its current verdict: pending
    until a matching artifact lands, then confirmed/falsified with the
    measured value and the artifact that graded it."""
    out = []
    for p in PREDICTIONS:
        verdict, measured, source = p.check(timeline)
        out.append({
            "id": p.pid,
            "name": p.name,
            "predicted": p.predicted,
            "rule": p.rule,
            "verdict": verdict,
            "measured": measured,
            "source": source,
        })
    return out


# --------------------------------------------------------------------------
# docs/SLO.md render — budgets stated once (here + the flag registry),
# rendered into docs, test-pinned like docs/FLAGS.md.
# --------------------------------------------------------------------------


def dump_slo_markdown() -> str:
    lines = [
        "# SLOs, stage budgets, and the prediction ledger",
        "",
        "Generated from the typed spec (`firedancer_tpu/disco/sentinel.py`)",
        "by `python scripts/fd_report.py --dump-spec > docs/SLO.md`.",
        "Do not edit by hand; edit the spec and regenerate",
        "(tests/test_sentinel.py pins this file against the spec).",
        "",
        "This file is the single source of truth for the budgets that",
        "docs/LATENCY.md and docs/ROOFLINE.md used to state as prose.",
        "The fd_sentinel evaluator (`FD_SENTINEL`, on by default) enforces",
        "the SLO table inside every pipeline run with multi-window",
        "burn-rate detection over the always-on fd_flight histograms;",
        "`scripts/fd_report.py` reconciles the prediction ledger against",
        "BENCH_LOG.jsonl and the artifact family on every invocation.",
        "",
        "## SLO table",
        "",
        "Latency SLOs consume the log2 edge histograms: a sample counts",
        "against the error budget (1 - target) only when it is provably",
        "> 2x the budget (one log2 bucket of slack, the docs/LATENCY.md",
        "rule), and an alert fires only when the burn rate is >=",
        "`FD_SLO_BURN` in BOTH the fast and the slow window. Liveness",
        "SLOs alert when the stall exceeds the budget outright.",
        "Balance SLOs (fd_pod) compare per-shard dispatched-lane",
        "occupancy across the `<tile>.shardN` flight rows: armed once",
        "every shard has real volume, breached when the busiest/laziest",
        "ratio exceeds the budget (stated in percent, not ms).",
        "Effectiveness SLOs (fd_drain) watch the verify tiles'",
        "published claim counters: armed once real claim volume has",
        "published (an `FD_DRAIN=off` run publishes none and stays",
        "silent), breached when the definitely-novel share falls below",
        "the budget percentage.",
        "Slope SLOs (fd_soak) are the long-horizon resource-growth",
        "tripwires: armed only when a soak run registers a slope",
        "source (`sentinel.set_slope_source` — ordinary runs never",
        "arm them) with at least MIN_SLOPE_SAMPLES probe samples,",
        "breached when the least-squares trend of the sampled",
        "resource (tracemalloc heap, outstanding feed slots, engine-",
        "cache entries) exceeds the budget — stated per resource in",
        "KiB/min, milli-slots/min, and entries/hour respectively.",
        "The fairness SLO (fd_fabric) watches the per-tenant admission",
        "ledger: armed only when a fabric run registers a tenant source",
        "(`sentinel.set_tenant_source` — ordinary runs never arm it)",
        "with at least MIN_TENANT_OFFERED offered transactions,",
        "breached when any HONEST tenant's shed fraction exceeds the",
        "budget percentage (an over-offering attacker being shed is",
        "the defense working, never a breach).",
        "",
        "| SLO | kind | edge / stage | budget (default) | target |"
        " trips on (chaos class) | objective |",
        "|---|---|---|---|---|---|---|",
    ]
    _SLOPE_UNITS = {"heap": "KiB/min", "slot_pool": "milli-slots/min",
                    "compile_cache": "entries/h"}
    for s in SLO_TABLE:
        if s.kind == "slope":
            unit = _SLOPE_UNITS[s.edge_or_stage]
        else:
            unit = ("%" if s.kind in ("balance", "effectiveness",
                                      "fairness") else "ms")
        budget = f"`{s.budget_flag}` = {_budget_default_ms(s)} {unit}"
        target = f"p{int(s.target * 100)}" if s.kind == "latency" else "—"
        faults = ", ".join(s.fault_classes) if s.fault_classes else "—"
        lines.append(
            f"| `{s.name}` | {s.kind} | `{s.edge_or_stage}` | {budget} | "
            f"{target} | {faults} | {s.objective} |"
        )
    lines += [
        "",
        "## ROOFLINE per-stage budgets (ms per 8192-lane batch, fused path)",
        "",
        "| stage | budget |",
        "|---|---|",
    ]
    for k, v in STAGE_BUDGETS_MS.items():
        lines.append(f"| `{k}` | {v} |")
    lines += [
        "",
        "## Throughput gates",
        "",
        "| gate | metric | minimum | provenance |",
        "|---|---|---|---|",
    ]
    for name, g in THROUGHPUT_GATES.items():
        lines.append(
            f"| `{name}` | `{g['metric']}` | {g['min']:,.0f} {g['unit']} | "
            f"{g['doc']} |"
        )
    lines += [
        "",
        "## Prediction ledger (ROOFLINE round-10 falsifiables)",
        "",
        "Match rules key on `schema_version >= 2`, on-device, non-stale",
        "records, so the pre-round-10 history can neither confirm nor",
        "falsify a prediction; the BENCH_r06 hardware session auto-grades",
        "them the moment its artifacts land (`python scripts/fd_report.py`",
        "renders verdicts).",
        "",
        "| # | prediction | predicted | match rule |",
        "|---|---|---|---|",
    ]
    for p in PREDICTIONS:
        lines.append(
            f"| {p.pid} | {p.name} | {p.predicted} | {p.rule} |")
    lines.append("")
    return "\n".join(lines)
