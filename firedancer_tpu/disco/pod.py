"""fd_pod — pod-scale sharded verify service (ROADMAP direction 1).

The rlc×mesh composition was proven at 2 shards (round 10), every
shard books its own flight lane (round 12), and the engine registry
keys on shard count (round 16) — this module composes them into a
SERVICE: N feeder lanes (one SlotPool staging arena per mesh shard,
the fd_feed slot machinery) drain one work stream into ONE shard_map'd
RLC verify graph over an 8+ device mesh, with the step split into two
separately-jitted graphs (parallel/mesh.verify_rlc_split_sharded):

    local_fill     per-shard SHA / decompress / status ladder /
                   Pippenger bucket fill+aggregation — no collectives
    combine_tail   the window-partial all_gather + unified adds + the
                   doubling-chain tails — the only cross-shard traffic

so the dispatcher can DOUBLE-BUFFER the way wiredancer double-buffers
DMA slots (wd_f1.c:327-408): batch k's combine_tail executes while
batch k+1's local_fill is already dispatched. SZKP (arXiv 2408.05890)
and ZK-Flex (2606.03046) teach the same dataflow at the accelerator
level — aggregate MSM throughput is won by scheduling many bucket-fill
units against one work stream and hiding the cross-unit reduction
behind the next batch's fill; this is that schedule on the mesh.

Shard placement is BACKLOG-AWARE round-robin: a transaction's
signature lanes land together on the least-backlogged shard lane
(round-robin among ties), so a burst of multisig transactions cannot
starve one device while another pads. Per-shard occupancy is booked
into `<label>.shardN` flight rows — the sentinel's shard-balance SLO
(docs/SLO.md) and the pod smoke's 1.5x gate read those rows, and
flight.merge_tile_metrics over them reproduces the service totals.

The hardware headline (8-shard aggregate >= 1.04M verifies/s, beating
wiredancer's 1.04M/s reference point) stays a LEDGERED PREDICTION
(sentinel prediction 11) that auto-grades when an on-device
MULTICHIP_r06+/POD artifact lands; on the virtual CPU mesh this module
gates what CAN be gated there — bit-exact digests vs single-shard,
split == monolithic, occupancy balance, and measured fill/tail overlap
(pipelined 2-batch wall < serialized split-step sum).

Host-side: numpy + the flight/engine/feed helpers; jax is imported
lazily when the service actually builds its graphs.
"""

from __future__ import annotations

import time
from hashlib import sha256 as _sha256
from typing import Dict, List, Optional, Tuple

import numpy as np

from firedancer_tpu import flags
from firedancer_tpu.disco import flight
from firedancer_tpu.disco.feed.slots import SlotPool

FD_POD_MTU = 1232


class ShardLane:
    """One per-shard feeder lane: a SlotPool staging arena plus the
    shard's flight row. The service's placement loop stages whole
    transactions into the lane's FILLING slot; a slot commits (READY)
    when it reaches the per-shard rung, and the dispatcher assembles
    one global batch from one READY slot per lane."""

    def __init__(self, idx: int, per_shard: int, max_msg_len: int,
                 wksp=None, label: str = "verify.pod",
                 n_slots: Optional[int] = None):
        self.idx = idx
        self.per_shard = per_shard
        self.max_msg_len = max_msg_len
        self.pool = SlotPool(n_slots or flags.get_int("FD_FEED_SLOTS"),
                             per_shard, max_msg_len)
        self.fl = flight.tile_lane(wksp, f"{label}.shard{idx}")
        self.cur = None               # FILLING slot (service-owned)
        # Per-slot txn metadata ((psig, payload digest) in stage
        # order), keyed by slot index: a slot is exclusively filled,
        # dispatched, then retired before reuse, so the retire pops
        # its list before release. Keying by psig instead would
        # collide on corrupted copies sharing the first 8 sig bytes.
        self._slot_meta: Dict[int, list] = {}

    # -- staging ---------------------------------------------------------

    def room(self) -> int:
        """Lane room left in the FILLING slot (per_shard with none)."""
        if self.cur is None:
            return self.per_shard
        return self.per_shard - self.cur.n_lane

    def backlog(self) -> int:
        """Staged-but-undispatched lanes: the FILLING slot's fill plus
        the READY queue, the placement signal (least-backlogged shard
        wins a new transaction)."""
        cur = self.cur.n_lane if self.cur is not None else 0
        return cur + self.pool.ready_cnt() * self.per_shard

    def _acquire(self):
        slot = self.pool.acquire(5.0)
        if slot is None:
            raise RuntimeError(
                f"fd_pod shard {self.idx}: no FREE staging slot within "
                "5 s — the dispatcher stopped retiring batches"
            )
        return slot

    def stage(self, items, psig: int, tsorig: int = 0,
              digest: Optional[bytes] = None) -> None:
        """Stage one transaction's (sig, pub, msg) lanes contiguously
        into the FILLING slot (committing it first when the txn cannot
        fit the remaining room — a txn's lanes never straddle slots,
        so per-txn verdict folding stays self-contained)."""
        n = len(items)
        if n > self.per_shard:
            raise ValueError(
                f"txn with {n} signature lanes exceeds the per-shard "
                f"batch {self.per_shard}"
            )
        if self.cur is not None and self.cur.n_lane + n > self.per_shard:
            self.commit("capacity")
        if self.cur is None:
            self.cur = self._acquire()
        slot = self.cur
        for (sig, pub, msg) in items:
            i = slot.n_lane
            m = np.frombuffer(msg, np.uint8)[: self.max_msg_len]
            slot.msgs[i, : len(m)] = m
            slot.msgs[i, len(m):] = 0
            slot.lens[i] = len(m)
            slot.sigs[i] = np.frombuffer(sig, np.uint8)
            slot.pubs[i] = np.frombuffer(pub, np.uint8)
            slot.n_lane += 1
        t = slot.n_txn
        slot.tlanes[t] = n
        slot.psigs[t] = psig
        slot.tsorigs[t] = tsorig
        if t == 0:
            slot.t_first = time.monotonic_ns()
        slot.n_txn += 1
        self._slot_meta.setdefault(slot.idx, []).append((psig, digest))

    def pop_meta(self, slot) -> list:
        return self._slot_meta.pop(slot.idx, [])

    def commit(self, verdict: str = "full") -> None:
        if self.cur is None:
            return
        self.cur.flush_verdict = verdict
        slot, self.cur = self.cur, None
        self.pool.commit(slot)

    def pop_ready(self):
        return self.pool.pop_ready()

    def release(self, slot) -> None:
        self.pool.release(slot)


class _PodInflight:
    """One double-buffered batch: the async local_fill outputs, the
    async combine_tail verdict, and the shard slots whose arenas the
    global batch was assembled from."""

    __slots__ = ("status", "definite", "ok", "slots", "arrays",
                 "t_dispatch", "lanes")

    def __init__(self, status, definite, ok, slots, arrays,
                 t_dispatch: int, lanes: int):
        self.status = status
        self.definite = definite
        self.ok = ok
        self.slots = slots          # one per shard; None = padded shard
        self.arrays = arrays        # (msgs, lens, sigs, pubs) jnp globals
        self.t_dispatch = t_dispatch
        self.lanes = lanes


class PodVerifyService:
    """The pod-scale sharded verify service: N ShardLanes feeding the
    split-step mesh engine through a double-buffered dispatcher.

    Single-threaded by contract (one placement/dispatch loop owns the
    service — the fd_feed stager-thread split is the tile integration,
    disco/tiles.py); every graph call is ASYNC, so the pipeline depth
    comes from FD_POD_INFLIGHT, not host threads."""

    def __init__(self, batch: int, n_shards: Optional[int] = None,
                 max_msg_len: int = 256, wksp=None,
                 label: str = "verify.pod",
                 torsion_k: Optional[int] = None,
                 inflight: Optional[int] = None,
                 n_slots: Optional[int] = None,
                 warm: bool = False):
        import jax

        from firedancer_tpu.disco import engine as fd_engine

        self.n_shards = n_shards or flags.get_int("FD_MESH_DEVICES")
        if batch % self.n_shards:
            raise ValueError(
                f"global batch {batch} must divide over {self.n_shards} "
                "shards"
            )
        if not flags.get_bool("FD_POD_SPLIT"):
            raise ValueError(
                "PodVerifyService needs the split-step engine pair; "
                "FD_POD_SPLIT=0 disables it (use the monolithic "
                "verify_rlc_step_sharded path instead)"
            )
        self.batch = batch
        self.per_shard = batch // self.n_shards
        self.max_msg_len = max_msg_len
        self.label = label
        self.inflight_max = max(1, inflight
                                or flags.get_int("FD_POD_INFLIGHT"))
        self._torsion_k = torsion_k or flags.get_int("FD_RLC_TORSION_K")
        self._jax = jax

        # ONE registry engine (mode x B x shards x frontend): the split
        # pair + the sharded per-lane fallback, with compile accounting
        # booked where every other dispatch site books it.
        self.spec = fd_engine.EngineSpec(
            "rlc", batch, self.n_shards, fd_engine.current_frontend())
        self.registry = fd_engine.registry()
        self.entry, _ = self.registry.acquire(
            self.spec, warm=warm, max_msg_len=max_msg_len)
        if self.entry.fn_local is None or self.entry.fn_tail is None:
            raise RuntimeError(
                "engine build did not produce the fd_pod split pair "
                f"for {self.spec.key} (FD_POD_SPLIT raced off?)"
            )
        self.fl = flight.tile_lane(wksp, label)
        self.lanes = [
            ShardLane(i, self.per_shard, max_msg_len, wksp=wksp,
                      label=label, n_slots=n_slots)
            for i in range(self.n_shards)
        ]
        self._rr = 0                  # round-robin tiebreak cursor
        self._inflight: List[_PodInflight] = []
        self.stat_batches = 0
        self.stat_lanes = 0
        self.stat_fallbacks = 0
        self.stat_pad_slots = 0
        self._results: List[Tuple[int, bool]] = []  # (psig, ok) folds
        self._digests: List[bytes] = []

    # -- placement -------------------------------------------------------

    def place(self, n_lanes: int) -> int:
        """Backlog-aware round-robin shard choice for a transaction
        with n_lanes signature lanes: the least-backlogged lane that
        can hold the txn wins; ties resolve round-robin so a quiet pod
        still interleaves shards instead of piling on shard 0."""
        order = [(self._rr + i) % self.n_shards
                 for i in range(self.n_shards)]
        fit = [i for i in order
               if self.lanes[i].room() >= n_lanes] or order
        best = min(fit, key=lambda i: self.lanes[i].backlog())
        self._rr = (best + 1) % self.n_shards
        return best

    def stage_txn(self, payload: bytes, tsorig: int = 0) -> bool:
        """Parse + place one transaction; False = parse reject (never
        staged). The whole txn lands on one shard lane."""
        from firedancer_tpu.ballet.txn import TxnParseError, parse_txn
        from firedancer_tpu.disco.tiles import meta_sig

        try:
            txn = parse_txn(payload)
            items = list(txn.verify_items(payload))
        except TxnParseError:
            return False
        if not items or any(len(m) > self.max_msg_len
                            for (_, _, m) in items):
            return False
        psig = meta_sig(payload)
        shard = self.place(len(items))
        self.lanes[shard].stage(items, psig, tsorig,
                                digest=_sha256(payload).digest())
        if self.lanes[shard].room() == 0:
            self.lanes[shard].commit("full")
        return True

    # -- dispatch --------------------------------------------------------

    def _assemble(self):
        """One READY slot per shard -> the global batch arrays (shards
        with nothing READY contribute a zero pad region — pad lanes
        resolve definite exactly like the feed path's zeroed tail
        rows). Returns None when NO shard has anything READY."""
        slots = [lane.pop_ready() for lane in self.lanes]
        if all(s is None for s in slots):
            return None
        jnp = self._jax.numpy
        per, mml = self.per_shard, self.max_msg_len
        msgs = np.zeros((self.batch, mml), np.uint8)
        lens = np.zeros(self.batch, np.int32)
        sigs = np.zeros((self.batch, 64), np.uint8)
        pubs = np.zeros((self.batch, 32), np.uint8)
        n_lanes = 0
        for i, s in enumerate(slots):
            if s is None:
                self.stat_pad_slots += 1
                continue
            lo = i * per
            n = s.n_lane
            msgs[lo:lo + n] = s.msgs[:n]
            lens[lo:lo + n] = s.lens[:n]
            sigs[lo:lo + n] = s.sigs[:n]
            pubs[lo:lo + n] = s.pubs[:n]
            n_lanes += n
            self.lanes[i].fl.inc("batches")
            self.lanes[i].fl.inc("lanes", n)
        arrays = (jnp.asarray(msgs), jnp.asarray(lens),
                  jnp.asarray(sigs), jnp.asarray(pubs))
        return slots, arrays, n_lanes

    def dispatch_ready(self, force: bool = False) -> bool:
        """Assemble + double-buffer-dispatch one global batch when the
        pod has READY work (force commits every FILLING slot first —
        the flush/drain path). Returns True when a batch went out."""
        if force:
            for lane in self.lanes:
                if lane.cur is not None and lane.cur.n_txn:
                    lane.commit("deadline")
        asm = self._assemble()
        if asm is None:
            return False
        slots, arrays, n_lanes = asm
        # Enforce the window BEFORE enqueueing: at most inflight_max
        # batch pairs live after this call, and FD_POD_INFLIGHT=1
        # genuinely serializes (retire blocks on batch k's tail before
        # batch k+1's fill is dispatched — the bisection behavior).
        while len(self._inflight) >= self.inflight_max:
            self._retire(self._inflight.pop(0))
        from firedancer_tpu.ops.verify_rlc import fresh_u, fresh_z

        jnp = self._jax.numpy
        z = jnp.asarray(fresh_z(self.batch))
        u = jnp.asarray(fresh_u(self._torsion_k, 2 * self.batch))
        t0 = time.monotonic_ns()
        # The double buffer: BOTH graphs enqueue asynchronously, so by
        # the time this returns, batch k+1's local_fill can be
        # dispatched while this batch's combine_tail still executes.
        status, definite, parts = self.entry.fn_local(*arrays, z, u)
        ok = self.entry.fn_tail(parts)
        self._inflight.append(_PodInflight(
            status, definite, ok, slots, arrays, t0, n_lanes))
        self.entry.note_dispatch(n_lanes)
        self.stat_batches += 1
        self.stat_lanes += n_lanes
        self.fl.inc("batches")
        self.fl.inc("lanes", n_lanes)
        return True

    def _retire(self, ib: _PodInflight) -> None:
        """Block on one batch's verdict, fall back per-lane when the
        batch equation fails, fold per-txn results, release slots."""
        ok = bool(np.asarray(ib.ok))
        if ok:
            statuses = np.asarray(ib.status)
        else:
            self.stat_fallbacks += 1
            self.fl.inc("rlc_fallback")
            statuses = np.asarray(self.entry.direct_fn(*ib.arrays))
        # Deliberately NOT fed into entry.note_service: retirement is
        # deferred until the inflight window overflows, so
        # now - t_dispatch includes host staging/dwell of later batches
        # — polluting the engine's shared cost model would make a
        # VerifyTile RungScheduler on the same spec cap slack on queue
        # dwell. The split EMAs come from measure_overlap's serialized
        # halves, the only place the stages are individually observable.
        per = self.per_shard
        for i, s in enumerate(ib.slots):
            if s is None:
                continue
            meta = self.lanes[i].pop_meta(s)
            lo = i * per
            off = lo
            for t in range(s.n_txn):
                cnt = int(s.tlanes[t])
                lane_ok = bool(
                    (statuses[off:off + cnt] == 0).all()) and cnt > 0
                psig, digest = (meta[t] if t < len(meta)
                                else (int(s.psigs[t]), None))
                self._results.append((psig, lane_ok))
                if lane_ok and digest is not None:
                    self._digests.append(digest)
                off += cnt
            self.lanes[i].release(s)

    def drain(self) -> None:
        """Flush every staged txn and retire every in-flight batch."""
        while True:
            progressed = self.dispatch_ready(force=True)
            while self._inflight:
                self._retire(self._inflight.pop(0))
            if not progressed:
                if any(lane.cur is not None and lane.cur.n_txn
                       for lane in self.lanes) or any(
                           lane.pool.ready_cnt()
                           for lane in self.lanes):
                    continue
                break

    # -- results / stats -------------------------------------------------

    def replay(self, payloads: List[bytes]) -> dict:
        """The service driver: place + stage + dispatch the whole
        payload list through the double-buffered pipeline, then drain.
        Returns verdicts, sha256 digests of verified txns (sink-digest
        parity material), and the occupancy/overlap stats."""
        t0 = time.perf_counter()
        parse_rejects = 0
        for p in payloads:
            if not self.stage_txn(p):
                parse_rejects += 1
            # Ship as soon as every shard can contribute — the
            # steady-state cadence that keeps the double buffer full.
            if all(lane.pool.ready_cnt() > 0 for lane in self.lanes):
                self.dispatch_ready()
        self.drain()
        elapsed = time.perf_counter() - t0
        ok_cnt = sum(1 for _, ok in self._results if ok)
        return {
            "n": len(payloads),
            "parse_rejects": parse_rejects,
            "verified_ok": ok_cnt,
            "verified_fail": len(self._results) - ok_cnt,
            "digests": list(self._digests),
            "elapsed_s": elapsed,
            "stats": self.stats(),
        }

    def shard_occupancy(self) -> List[int]:
        return [lane.fl.get("lanes") for lane in self.lanes]

    def balance_ratio(self) -> float:
        """Busiest/laziest shard dispatched-lane ratio (the 1.5x
        acceptance gate; inf when a shard never saw a lane)."""
        occ = self.shard_occupancy()
        lo = min(occ)
        return float(max(occ)) / lo if lo else float("inf")

    def stats(self) -> dict:
        return {
            "engine": self.spec.key,
            "shards": self.n_shards,
            "batch": self.batch,
            "batches": self.stat_batches,
            "lanes": self.stat_lanes,
            "fill_ratio": round(
                self.stat_lanes / float(self.stat_batches * self.batch),
                4) if self.stat_batches else 0.0,
            "rlc_fallbacks": self.stat_fallbacks,
            "pad_slots": self.stat_pad_slots,
            "shard_lanes": self.shard_occupancy(),
            "shard_balance": (round(self.balance_ratio(), 3)
                              if self.stat_lanes else 0.0),
            "split": {
                "service_local_ns": self.entry.service_local_ns,
                "service_tail_ns": self.entry.service_tail_ns,
                "overlap_hidden_est": round(
                    self.entry.overlap_hidden_est(), 3),
            },
        }

    # -- the overlap probe (the acceptance measurement) ------------------

    def measure_overlap(self, payloads: List[bytes],
                        rounds: int = 2) -> dict:
        """Pipelined vs serialized split-step wall time over TWO global
        batches assembled from `payloads` (best-of-`rounds` each, the
        bench discipline for jittery hosts).

        serialized  = lf(1); BLOCK; ct(1); BLOCK; lf(2); BLOCK; ct(2); BLOCK
        pipelined   = lf(1); ct(1); lf(2); ct(2); BLOCK — the double
                      buffer: batch 2's fill is dispatched while batch
                      1's tail executes, so any overlap the runtime
                      finds (host dispatch under device execution, and
                      on real hardware the collective under the next
                      fill) shows up as pipelined < serialized.

        Feeds the engine's split service EMAs from the serialized
        halves (the only place the two stages are individually
        observable). Returns the measured walls + overlap."""
        jax, jnp = self._jax, self._jax.numpy
        from firedancer_tpu.ops.verify_rlc import fresh_u, fresh_z

        batches = []
        for k in range(2):
            svc_slice = payloads[k::2]
            msgs = np.zeros((self.batch, self.max_msg_len), np.uint8)
            lens = np.zeros(self.batch, np.int32)
            sigs = np.zeros((self.batch, 64), np.uint8)
            pubs = np.zeros((self.batch, 32), np.uint8)
            i = 0
            from firedancer_tpu.ballet.txn import (
                TxnParseError,
                parse_txn,
            )

            for p in svc_slice:
                try:
                    items = list(parse_txn(p).verify_items(p))
                except TxnParseError:
                    continue
                # Whole txns only, stage_txn's rule: a truncated
                # multisig would time a batch shape the service never
                # produces.
                if (i + len(items) > self.batch
                        or any(len(m) > self.max_msg_len
                               for (_, _, m) in items)):
                    continue
                for (sg, pb, m) in items:
                    mm = np.frombuffer(m, np.uint8)
                    msgs[i, : len(mm)] = mm
                    lens[i] = len(mm)
                    sigs[i] = np.frombuffer(sg, np.uint8)
                    pubs[i] = np.frombuffer(pb, np.uint8)
                    i += 1
            rng = np.random.default_rng(0xF1D0 + k)
            batches.append((
                (jnp.asarray(msgs), jnp.asarray(lens),
                 jnp.asarray(sigs), jnp.asarray(pubs)),
                jnp.asarray(fresh_z(self.batch, rng)),
                jnp.asarray(fresh_u(self._torsion_k, 2 * self.batch,
                                    rng)),
            ))

        lf, ct = self.entry.fn_local, self.entry.fn_tail
        # Warm both graphs on the real shapes first (compile must not
        # pollute either measurement).
        for arrays, z, u in batches:
            out = lf(*arrays, z, u)
            jax.block_until_ready(ct(out[2]))

        best_serial = best_pipe = float("inf")
        local_ns = tail_ns = 0
        for _ in range(max(1, rounds)):
            t0 = time.perf_counter()
            l_ns = t_ns = 0
            for arrays, z, u in batches:
                ta = time.monotonic_ns()
                out = jax.block_until_ready(lf(*arrays, z, u))
                tb = time.monotonic_ns()
                jax.block_until_ready(ct(out[2]))
                tc = time.monotonic_ns()
                l_ns += tb - ta
                t_ns += tc - tb
            serial = time.perf_counter() - t0
            if serial < best_serial:
                best_serial, local_ns, tail_ns = serial, l_ns // 2, \
                    t_ns // 2

            t0 = time.perf_counter()
            pending = []
            for arrays, z, u in batches:
                out = lf(*arrays, z, u)
                pending.append(ct(out[2]))
            jax.block_until_ready(pending)
            best_pipe = min(best_pipe, time.perf_counter() - t0)

        self.entry.note_service_split(local_ns, tail_ns)
        overlap_s = best_serial - best_pipe
        return {
            "serialized_ms": round(best_serial * 1e3, 3),
            "pipelined_ms": round(best_pipe * 1e3, 3),
            "overlap_ms": round(overlap_s * 1e3, 3),
            "overlap_frac": round(overlap_s / best_serial, 4)
            if best_serial else 0.0,
            "local_fill_ms": round(local_ns / 1e6, 3),
            "combine_tail_ms": round(tail_ns / 1e6, 3),
            "tail_hidden_est": round(self.entry.overlap_hidden_est(), 3),
        }


def pod_replay(payloads: List[bytes], batch: int,
               n_shards: Optional[int] = None, max_msg_len: int = 256,
               wksp=None, **kw) -> dict:
    """One-call service replay (the smoke/test surface): build a
    PodVerifyService, run the payload list through the double-buffered
    pipeline, return the result dict with the service attached."""
    svc = PodVerifyService(batch, n_shards=n_shards,
                           max_msg_len=max_msg_len, wksp=wksp, **kw)
    out = svc.replay(payloads)
    out["service"] = svc
    return out
