"""fd_chaos — deterministic, schedule-driven fault injection.

The reference validator's whole design is crash-only: a misbehaving
tile is killed and respawned and the lossy-by-design tango rings heal
around it. This module makes that property TESTABLE for every boundary
the pipeline crosses, the way wiredancer treats the FPGA as a component
that can disappear and must degrade to the host path: faults are
injected at fixed, replayable points (ordinal counters per hook site,
byte/position choices from a seeded counter-based Rng), so a failing
chaos run re-runs bit-identically from (seed, schedule).

Fault classes and their hook sites:

  ring_ctl_err   source publish path: emit a CTL_ERR frag (garbage
                 payload) ahead of the scheduled publish. Consumers
                 must drop it at the ctl word, not launder it.
  ring_overrun   consumer side (stager drain round): rewind the in-ring
                 cursor past the ring depth so the seqlock poll reports
                 a producer overrun and the drain repositions. Re-read
                 frags are healed by the HA tcache (dup filter) — a
                 reliable-link producer-side seq gap would deadlock the
                 credit loop, so the overrun is injected where real
                 ones appear: at the consumer.
  credit_starve  source publish path: report zero credits for a window
                 of publish attempts (forced backpressure; liveness
                 fault, heals when the window closes).
  stager_kill    raise out of the stager thread at a scheduled drain
                 round; healed by the feeder's thread supervision
                 (restart with exponential backoff, staged slots kept).
  slot_corrupt   flip one byte in a staged slot's msg sidecar (the
                 verify staging, NOT the payload): the lane must fail
                 sigverify and the txn must be dropped without wedging
                 the slot pool. Keyed to the Nth non-duplicate STAGED
                 TXN (not the drain round): a single in-order producer
                 makes that ordinal — and therefore WHICH txn is hit —
                 replay-exact, where round boundaries depend on ring
                 timing.
  backend_raise  raise at a scheduled batch completion (the shape of a
                 backend/XLA error surfacing from an async dispatch);
                 healed by poisoned-batch quarantine (CPU oracle lane
                 re-verify, offenders published CTL_ERR, slot freed).
  device_lost    raise at scheduled dispatch ordinals (device
                 unavailable); healed by the verify circuit breaker
                 (trip -> CPU failover lane -> half-open re-probe).
  hb_stall       suppress a tile's cnc heartbeat for a window of that
                 tile's OWN housekeeping passes (ordinals are per-tile:
                 in-process runs housekeep from every tile thread, and
                 a shared counter would tie WHICH tile stalls to thread
                 interleaving). Supervised runs: the wedge detector
                 must kill + respawn.
  worker_kill    supervisor monitor pass: SIGKILL the verify worker at
                 a scheduled pass ordinal (supervised runs).
  quic_malformed QUIC tile rx round: feed one seeded junk datagram into
                 the endpoint; it must drop it unprocessed (drop-type:
                 detection is the heal). Runs concurrently with a live
                 swarm — the fd_siege contract.
  quic_conn_churn QUIC tile churn round: feed a well-formed garbage
                 Initial from a synthetic peer (half-open conn flood
                 shape); healed when the handshake-deadline reaper (or
                 the conn-cap refusal) retires it.
  quic_slowloris window over QUIC rx rounds: completed streams are
                 deferred (held, not lost) while open — injected at
                 window open, healed at close when the held txns
                 requeue (window-edge accounting, hb_stall pattern).

Schedule grammar (FD_CHAOS_SCHEDULE):

    entry[,entry...]    entry := class@N | class@N:M

N/M are 1-based ordinals of the class's hook site (publish attempt,
drain round, staged txn, dispatch, completion, housekeep pass,
monitor pass).
Point classes may repeat (`ring_ctl_err@5,ring_ctl_err@40`); window
classes (credit_starve, device_lost, hb_stall) take N:M inclusive.

Accounting: every class carries injected/detected/healed counters; the
chaos smoke lane (scripts/chaos_smoke.py) gates on per-class parity
(injected == detected == healed), so recovery is audited, not assumed.
For drop-type faults (ring_ctl_err, ring_overrun, slot_corrupt) the
detection IS the heal (the frag/lane is filtered and the machinery
carries on); pool integrity is gated separately (slots_leaked == 0).
Counters are process-local: in supervised (multi-process) runs the
supervisor-level classes are asserted behaviorally (restart counts,
content exactness) rather than through the tri-counter.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from firedancer_tpu import flags
from firedancer_tpu.utils.rng import Rng

FAULT_CLASSES = (
    "ring_ctl_err",
    "ring_overrun",
    "credit_starve",
    "stager_kill",
    "slot_corrupt",
    "backend_raise",
    "device_lost",
    "hb_stall",
    "worker_kill",
    # fd_siege front-door classes (hook sites inside QuicTile.step —
    # runnable CONCURRENTLY with a live attack swarm, the siege suite's
    # whole point):
    #   quic_malformed   point: feed one seeded junk datagram into the
    #                    endpoint at the Nth rx-service round; the
    #                    endpoint must drop it unprocessed (detection ==
    #                    heal, the drop-type pattern).
    #   quic_conn_churn  point: feed a well-formed-but-garbage Initial
    #                    from a synthetic peer at the Nth churn round —
    #                    the server allocates a half-open conn (or
    #                    refuses at the conn cap); healed when the
    #                    handshake-deadline reaper (or the cap refusal)
    #                    retires it.
    #   quic_slowloris   window over rx-service rounds: completed
    #                    streams are DEFERRED (held, not lost) while
    #                    the window is open — the shape of a client
    #                    dribbling bytes; injected==detected at window
    #                    open, healed at close when the held txns
    #                    requeue (window-edge accounting, the hb_stall
    #                    pattern).
    "quic_malformed",
    "quic_conn_churn",
    "quic_slowloris",
)

_WINDOW_CLASSES = ("credit_starve", "device_lost", "hb_stall",
                   "quic_slowloris")


class ChaosFault(RuntimeError):
    """Base of every injected exception; `cls` names the fault class so
    healing paths can attribute detected/healed counters exactly."""

    cls = "chaos"


class ChaosStagerKill(ChaosFault):
    cls = "stager_kill"


class ChaosBackendError(ChaosFault):
    cls = "backend_raise"


class ChaosDeviceLost(ChaosFault):
    cls = "device_lost"


def parse_schedule(spec: str) -> Dict[str, List[Tuple[int, int]]]:
    """`class@N[:M],...` -> {class: [(lo, hi), ...]} (1-based, inclusive).

    Point entries become (N, N). Unknown classes, malformed ordinals,
    or windows on point-only classes raise ValueError — a typo'd
    schedule must never silently inject nothing.
    """
    out: Dict[str, List[Tuple[int, int]]] = {}
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        if "@" not in entry:
            raise ValueError(f"chaos schedule entry {entry!r}: missing '@N'")
        cls, _, ord_s = entry.partition("@")
        cls = cls.strip()
        if cls not in FAULT_CLASSES:
            raise ValueError(
                f"unknown chaos fault class {cls!r} "
                f"(want one of {', '.join(FAULT_CLASSES)})"
            )
        if ":" in ord_s:
            if cls not in _WINDOW_CLASSES:
                raise ValueError(
                    f"chaos class {cls!r} takes a point ordinal, "
                    f"not a window ({entry!r})"
                )
            lo_s, _, hi_s = ord_s.partition(":")
        else:
            lo_s = hi_s = ord_s
        try:
            lo, hi = int(lo_s), int(hi_s)
        except ValueError:
            raise ValueError(
                f"chaos schedule entry {entry!r}: ordinals must be ints"
            ) from None
        if lo < 1 or hi < lo:
            raise ValueError(
                f"chaos schedule entry {entry!r}: want 1 <= N <= M"
            )
        out.setdefault(cls, []).append((lo, hi))
    return out


class ChaosInjector:
    """One run's injection plan + fault accounting.

    Hook ordinals are per-site counters (source publish attempts,
    stager drain rounds, dispatches, completions, per-tile housekeeping
    passes) — each site is driven by exactly one thread (housekeeping
    is keyed per tile precisely to keep that true), so the ordinals are
    deterministic given the run's configuration.
    """

    def __init__(self, seed: int = 0, schedule: str = ""):
        self.seed = seed
        self.schedule = parse_schedule(schedule or "")
        if "ring_ctl_err" in self.schedule:
            # The audit for this class counts typed CTL_ERR drops at the
            # native drain (counters[6]); a stale .so stages err frags
            # like any other and the parity gate would fail with a
            # misleading detected=0. Refuse up front instead. (Pure
            # Python consumers check frag.ctl directly and need no
            # native support.)
            from firedancer_tpu.tango.rings import (
                native_available,
                verify_drain_ctl_err,
            )

            if native_available() and not verify_drain_ctl_err():
                raise RuntimeError(
                    "FD_CHAOS_SCHEDULE includes ring_ctl_err but the "
                    "native .so predates the CTL_ERR drop counter "
                    "(fd_verify_drain_ctl_err absent) — rebuild native/"
                )
        # Per-site Rng streams (counter-based, splittable): byte/position
        # choices must not depend on how draws from DIFFERENT threads
        # interleave, or the replay contract dies to scheduler noise.
        self._junk_rng = Rng(seq=seed ^ 0xC4A05)      # ring_ctl_err payloads
        self._corrupt_rng = Rng(seq=seed ^ 0x51077)   # slot_corrupt flips
        self._lock = threading.Lock()
        self.counters: Dict[str, Dict[str, int]] = {
            cls: {"injected": 0, "detected": 0, "healed": 0}
            for cls in self.schedule
        }
        # fd_flight: every injected/detected/healed event also lands in
        # the "chaos" flight recorder, so a crash dump carries the
        # fault timeline and the obs smoke can gate injected ==
        # recorded per class against the tri-counter audit.
        from firedancer_tpu.disco import flight

        self._flightrec = flight.recorder("chaos")
        # per-site ordinal counters
        self._ord: Dict[str, int] = {}
        # match-based detection state (consume-one-pending per event so
        # an unrelated lookalike cannot inflate parity)
        self._overrun_pending = 0
        self._corrupt_psigs: List[int] = []
        self._starve_active = False
        self._hb_stall_active: set = set()   # tile_ids inside a window
        self._slowloris_active = False       # quic_slowloris window open
        self.corrupted_sha256: List[str] = []

    # -- plumbing --------------------------------------------------------

    def note(self, cls: str, kind: str, n: int = 1) -> None:
        """Record a detected/healed (or extra injected) event for a
        scheduled class; events for unscheduled classes are ignored so
        organic faults don't skew the parity audit."""
        with self._lock:
            c = self.counters.get(cls)
            if c is None:
                return
            c[kind] += n
        self._flightrec.record("chaos", cls=cls, event=kind, n=n)

    def _tick(self, site: str) -> int:
        """Next 1-based ordinal of a hook site. Locked: most sites are
        single-threaded by construction, but the housekeep site family
        is ticked from every tile thread of an in-process run, and a
        lost read-modify-write there would skew ordinals off the
        schedule (chaos-armed runs are test traffic — the lock is not
        on any production path)."""
        with self._lock:
            n = self._ord.get(site, 0) + 1
            self._ord[site] = n
            return n

    def _hit(self, cls: str, ordinal: int, consume: bool = False) -> bool:
        """True when `ordinal` falls in one of cls's scheduled windows.
        consume=True removes a matched POINT entry — for hook sites
        whose ordinal can be retried (a deferred injection must fire
        exactly once, not once per retry)."""
        wins = self.schedule.get(cls, [])
        for i, (lo, hi) in enumerate(wins):
            if lo <= ordinal <= hi:
                if consume and lo == hi:
                    wins.pop(i)
                return True
        return False

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "seed": self.seed,
                "counters": {
                    cls: dict(v) for cls, v in self.counters.items()
                },
                "corrupted_sha256": list(self.corrupted_sha256),
            }

    # -- ring level (source publish path) --------------------------------

    def source_starved(self) -> bool:
        """True while the credit_starve window covers this publish
        attempt: the source must treat the link as backpressured."""
        n = self._tick("source_attempt")
        if self._hit("credit_starve", n):
            if not self._starve_active:
                self._starve_active = True
                self.note("credit_starve", "injected")
                # forced backpressure is observed the moment the source
                # takes the backoff path — detection is the injection
                # point's own visibility in the BACKP diag.
                self.note("credit_starve", "detected")
            return True
        if self._starve_active:
            self._starve_active = False
            self.note("credit_starve", "healed")  # window closed, flow back
        return False

    def source_inject(self, out_link, publish_ord: int) -> None:
        """Called by the source right before publishing payload number
        `publish_ord` (1-based): may emit a CTL_ERR frag ahead of it.
        The err frag spends a credit like any frag — with none to spare
        the injection defers to the next attempt at the SAME ordinal
        (the entry is consumed only when it actually fires). The err
        payload is seeded garbage, so even a consumer without the ctl
        check (stale .so) drops it at parse."""
        from firedancer_tpu.tango.rings import CTL_ERR

        if not self._hit("ring_ctl_err", publish_ord):
            return
        if not out_link.can_publish():
            return
        self._hit("ring_ctl_err", publish_ord, consume=True)
        junk = bytes(self._junk_rng.roll(256) for _ in range(24))
        out_link.publish(junk, sig=0, ctl=CTL_ERR)
        self.note("ring_ctl_err", "injected")

    def on_ctl_err_drop(self, n: int = 1) -> None:
        """A consumer dropped n CTL_ERR frags at the ctl word: the drop
        is both the detection and the heal for this class."""
        self.note("ring_ctl_err", "detected", n)
        self.note("ring_ctl_err", "healed", n)

    # -- ring level (consumer drain) -------------------------------------

    def overrun_rewind(self, in_link) -> None:
        """Maybe rewind the consumer cursor past the ring depth so the
        next seqlock poll reports an overrun and the drain repositions
        (counted in DIAG_OVRNR_CNT). Deferred until enough frags have
        flowed that the rewound lines are guaranteed stale."""
        n = self._tick("drain_round")
        depth = in_link.mcache.depth
        if self._hit("ring_overrun", n):
            self._ord["_overrun_due"] = self._ord.get("_overrun_due", 0) + 1
        if self._ord.get("_overrun_due", 0) and in_link.seq > depth + 1:
            self._ord["_overrun_due"] -= 1
            in_link.seq -= depth + 1
            with self._lock:
                self._overrun_pending += 1
            self.note("ring_overrun", "injected")

    def on_overrun_observed(self) -> None:
        """The drain repositioned past an overrun; consume one pending
        injection (organic overruns beyond the pending count are not
        booked against the chaos audit)."""
        with self._lock:
            if self._overrun_pending <= 0:
                return
            self._overrun_pending -= 1
        self.note("ring_overrun", "detected")
        self.note("ring_overrun", "healed")

    # -- feed level (stager) ---------------------------------------------

    def stager_round_hook(self) -> None:
        """Top of every stager drain round; raises ChaosStagerKill at
        scheduled rounds (before the round's C call, so the kill point
        is state-clean: nothing half-booked in the slot)."""
        n = self._tick("stager_round")
        if self._hit("stager_kill", n):
            self.note("stager_kill", "injected")
            raise ChaosStagerKill(f"injected stager kill at round {n}")

    def post_stage_hook(self, slot, k0: int, n: int, lane0: int) -> None:
        """After a drain round staged txns [k0, k0+n) with lanes starting
        at lane0: maybe flip one byte in a scheduled txn's staged
        MESSAGE row (the payload sidecar stays pristine — the fault
        models staging-arena corruption, and the expected outcome is a
        sigverify drop of exactly that txn). The ordinal counts
        non-HA-masked STAGED txns: ring order is the single producer's
        publish order and duplicates are masked, so the same schedule
        hits the same txn on every run regardless of how the stream
        happened to split into drain rounds."""
        import hashlib

        lane = lane0
        for t in range(k0, k0 + n):
            if not bool(slot.ha_mask[t]):
                ordn = self._tick("staged_txn")
                msg_len = int(slot.lens[lane])
                if msg_len > 0 and self._hit(
                        "slot_corrupt", ordn, consume=True):
                    slot.msgs[lane, self._corrupt_rng.roll(msg_len)] ^= (
                        1 + self._corrupt_rng.roll(255)
                    )
                    off = int(slot.offs[t])
                    ln = int(slot.plens[t])
                    pay = slot.pay[off:off + ln].tobytes()
                    with self._lock:
                        self._corrupt_psigs.append(int(slot.psigs[t]))
                        self.corrupted_sha256.append(
                            hashlib.sha256(pay).hexdigest())
                    self.note("slot_corrupt", "injected")
            lane += int(slot.tlanes[t])

    def on_sv_drop(self, psigs) -> None:
        """Sigverify dropped txns with these meta sigs; consume matching
        corruption records (detected + healed: the poisoned lane was
        filtered and the slot carries on)."""
        hits = 0
        with self._lock:
            for p in psigs:
                try:
                    self._corrupt_psigs.remove(int(p))
                    hits += 1
                except ValueError:
                    continue
        if hits:
            self.note("slot_corrupt", "detected", hits)
            self.note("slot_corrupt", "healed", hits)

    # -- verify level ----------------------------------------------------

    def verify_dispatch_hook(self) -> None:
        """Before each device/executor dispatch; raises ChaosDeviceLost
        during scheduled dispatch windows (the breaker's trip fuel).
        Only ATTEMPTED device dispatches tick the ordinal — while the
        breaker is open the CPU lane serves and no injection fires, so
        injected == detected == healed holds per raise."""
        n = self._tick("dispatch")
        if self._hit("device_lost", n):
            self.note("device_lost", "injected")
            raise ChaosDeviceLost(f"injected device loss at dispatch {n}")

    def verify_complete_hook(self) -> None:
        """Before each batch completion is consumed; raises
        ChaosBackendError at scheduled completion ordinals (the shape
        of an async backend error surfacing at result time)."""
        n = self._tick("complete")
        if self._hit("backend_raise", n):
            self.note("backend_raise", "injected")
            raise ChaosBackendError(f"injected backend error at batch {n}")

    # -- quic front-door level (fd_siege classes; hooks in QuicTile) -----

    def quic_malformed_junk(self) -> Optional[bytes]:
        """Ticked once per QuicTile rx-service round: seeded junk bytes
        to feed straight into the endpoint at scheduled ordinals (the
        tile bypasses its own quarantine gate for the injection so the
        endpoint-level drop is what gets audited), else None. The junk
        wears a short-header first byte so it takes the unknown-cid
        path — the endpoint must count it rx_dropped, which the tile
        verifies synchronously (on_quic_malformed_dropped)."""
        n = self._tick("quic_rx_round")
        if not self._hit("quic_malformed", n, consume=True):
            return None
        junk = bytes([0x40 | self._junk_rng.roll(0x40)]) + bytes(
            self._junk_rng.roll(256) for _ in range(39))
        self.note("quic_malformed", "injected")
        return junk

    def on_quic_malformed_dropped(self) -> None:
        """The endpoint dropped the injected junk unprocessed: the drop
        is both the detection and the heal (drop-type class)."""
        self.note("quic_malformed", "detected")
        self.note("quic_malformed", "healed")

    def quic_churn_initial(self) -> Optional[bytes]:
        """Ticked once per QuicTile churn round: a well-formed Initial
        datagram with seeded garbage payload at scheduled ordinals
        (else None). The server allocates a connection that can never
        complete its handshake — the half-open-flood shape — or
        refuses it at the conn cap; the tile books detected when the
        conn appears (or the refusal drops), healed when the
        handshake-deadline reaper retires it."""
        n = self._tick("quic_churn_round")
        if not self._hit("quic_conn_churn", n, consume=True):
            return None
        from firedancer_tpu.tango.quic import wire

        rng = self._junk_rng
        dcid = bytes(rng.roll(256) for _ in range(8))
        scid = bytes(rng.roll(256) for _ in range(8))
        payload = bytes(rng.roll(256) for _ in range(64))
        hdr = wire.encode_long_header(
            wire.PKT_INITIAL, dcid, scid, pn=0, pn_len=2,
            payload_len=len(payload))
        self.note("quic_conn_churn", "injected")
        return hdr + payload

    def quic_slowloris_held(self) -> bool:
        """Ticked once per QuicTile rx-service round: True while the
        quic_slowloris window covers this round — the tile defers
        completed streams instead of admitting them (a client
        dribbling bytes). Window-edge accounting like hb_stall: ONE
        injected+detected at open (the deferral is immediately visible
        in the tile's hold buffer), healed at close when the held txns
        requeue for admission."""
        n = self._tick("quic_service_round")
        if self._hit("quic_slowloris", n):
            if not self._slowloris_active:
                self._slowloris_active = True
                self.note("quic_slowloris", "injected")
                self.note("quic_slowloris", "detected")
            return True
        if self._slowloris_active:
            self._slowloris_active = False
            self.note("quic_slowloris", "healed")
        return False

    def quic_slowloris_active(self) -> bool:
        """True while a quic_slowloris window is open (no tick): the
        stream-completion path checks this to route into the hold
        buffer; only the rx-round hook advances the window."""
        return self._slowloris_active

    def quic_slowloris_halt(self) -> None:
        """Tile halt with the deferral window still open: the tile
        flushes its hold buffer (nothing is lost) and the window closes
        here so the tri-counter stays balanced on truncated runs."""
        if self._slowloris_active:
            self._slowloris_active = False
            self.note("quic_slowloris", "healed")

    # -- supervisor level ------------------------------------------------

    def hb_stalled(self, tile_id: str) -> bool:
        """True while the hb_stall window covers this housekeeping pass
        OF THIS TILE: the tile must skip its heartbeat (the supervised
        wedge detector is the intended observer). Ordinals are keyed
        per tile — in-process runs drive housekeeping from every tile's
        own thread, and a shared counter would make WHICH tile stalls
        depend on thread interleaving, breaking replay. (Supervised
        runs are unchanged: one tile per process, one injector each.)"""
        n = self._tick(f"housekeep:{tile_id}")
        if self._hit("hb_stall", n):
            # Window-edge accounting (the credit_starve pattern): ONE
            # injected per window per tile, not one per suppressed
            # pass — a 20k-pass window would otherwise flood the
            # 256-event chaos flight ring and evict every other
            # class's record from the dump. As with credit_starve,
            # detection is the injection point's own visibility (the
            # frozen heartbeat is observable in monitor.snapshot /
            # the fd_sentinel tile_heartbeat SLO the moment the beat
            # is skipped), so the tri-counter stays balanced:
            # injected == detected at window open, healed at close.
            if tile_id not in self._hb_stall_active:
                self._hb_stall_active.add(tile_id)
                self.note("hb_stall", "injected")
                self.note("hb_stall", "detected")
            return True
        if tile_id in self._hb_stall_active:
            self._hb_stall_active.discard(tile_id)
            self.note("hb_stall", "healed")  # window closed, beat resumes
        return False

    def quic_faults_pending(self) -> bool:
        """True while a scheduled quic_* fault has not yet fired (or a
        slowloris window is still open). The quic tile folds this into
        its done() predicate the way the supervisor folds
        supervisor_faults_pending into quiescence: the tile keeps
        stepping — each step ticks the hook ordinals — until every
        scheduled injection has landed, so WHETHER a fault fires never
        races swarm speed against host speed."""
        with self._lock:
            if self._slowloris_active:
                return True
            for cls in ("quic_malformed", "quic_conn_churn"):
                if self.schedule.get(cls):
                    return True  # unconsumed point entries remain
            n = self._ord.get("quic_service_round", 0)
            return any(hi > n
                       for lo, hi in self.schedule.get("quic_slowloris", []))

    def supervisor_faults_pending(self) -> bool:
        """True while a scheduled supervisor-level fault (worker_kill)
        has not yet reached its monitor-pass ordinal. The supervisor
        folds this into its quiescence condition: a drained pipeline
        keeps taking monitor passes (each one ticks the ordinal) until
        every scheduled kill has fired, so WHETHER the fault lands no
        longer races corpus size against host speed — the round-12
        flake was exactly that race (quiescence at pass <20 on a fast
        1-core host silently skipped worker_kill@20)."""
        with self._lock:
            n = self._ord.get("monitor_pass", 0)
            return any(hi > n
                       for lo, hi in self.schedule.get("worker_kill", []))

    def supervisor_hook(self, tiles) -> None:
        """One supervisor monitor pass: SIGKILL the verify worker at
        scheduled pass ordinals (detected/healed are booked by the
        supervisor's own respawn accounting)."""
        import os
        import signal

        n = self._tick("monitor_pass")
        if not self._hit("worker_kill", n):
            return
        tp = tiles.get("verify")
        if tp is not None and tp.proc.poll() is None:
            self.note("worker_kill", "injected")
            os.kill(tp.proc.pid, signal.SIGKILL)


# -- process-global active injector ---------------------------------------

_active: Optional[ChaosInjector] = None


def active() -> Optional[ChaosInjector]:
    return _active


def install(injector: Optional[ChaosInjector]) -> None:
    global _active
    _active = injector


def uninstall() -> None:
    install(None)


def init_for_run() -> Optional[ChaosInjector]:
    """Pipeline-run entry point: FD_CHAOS on installs a FRESH injector
    (per-run ordinal counters — the determinism contract: the same
    seed + schedule + corpus replays the same faults), FD_CHAOS off
    clears any previous one. Called by every pipeline runner and by
    worker processes at boot."""
    if flags.get_bool("FD_CHAOS"):
        install(ChaosInjector(
            seed=flags.get_int("FD_CHAOS_SEED"),
            schedule=flags.get_str("FD_CHAOS_SCHEDULE") or "",
        ))
    else:
        install(None)
    return _active
