"""monitor — diag-counter snapshots + dashboard rendering.

Role parity with the reference's fd_frank_mon
(/root/reference/src/app/frank/fd_frank_mon.c): join every tile's cnc and
every link's fseq from the pod, snapshot the standardized diag counter
slots, and render heartbeat age / backpressure / filter counts / per-link
rates. snapshot() returns plain dicts (the programmatic surface the tests
and bench use); render() produces the ANSI dashboard string.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from firedancer_tpu.tango import tempo
from firedancer_tpu.tango.rings import (
    DIAG_FILT_CNT,
    DIAG_FILT_SZ,
    DIAG_OVRNP_CNT,
    DIAG_OVRNR_CNT,
    DIAG_PUB_CNT,
    DIAG_PUB_SZ,
    DIAG_SLOW_CNT,
    Cnc,
    FSeq,
    MCache,
    Workspace,
)
from firedancer_tpu.utils.pod import Pod

_SIGNAL_NAMES = {0: "boot", 1: "run", 2: "halt", 3: "fail"}


def _walk_objects(tree: dict, prefix: str = ""):
    """Yield (dotted_name, subdict) for every nested pod node that names a
    cnc or link (lane links like replay_verify.v1 nest one level down)."""
    for name, sub in sorted(tree.items()):
        if not isinstance(sub, dict):
            continue
        dotted = f"{prefix}.{name}" if prefix else name
        if "cnc" in sub or "fseq" in sub:
            yield dotted, sub
        yield from _walk_objects(sub, dotted)


def snapshot(wksp: Workspace, pod: Pod) -> Dict[str, Dict[str, int]]:
    """One diag snapshot of every tile cnc + link fseq named in the pod."""
    out: Dict[str, Dict[str, int]] = {}
    fd = pod.subpod("firedancer")
    from firedancer_tpu.tango.rings import cnc_diag_cap

    feed_cap = cnc_diag_cap() >= 16
    for name, sub in _walk_objects(fd.to_dict()):
        if "cnc" in sub:
            cnc = Cnc(wksp, sub["cnc"])
            from firedancer_tpu.disco.tiles import (
                CNC_DIAG_BACKOFF_MS,
                CNC_DIAG_BACKP_CNT,
                CNC_DIAG_FEED_BATCHES,
                CNC_DIAG_FEED_DEADLINE,
                CNC_DIAG_FEED_IDLE_NS,
                CNC_DIAG_FEED_LANES,
                CNC_DIAG_FEED_SLOT_STALL,
                CNC_DIAG_FEED_STARVED,
                CNC_DIAG_HA_FILT_CNT,
                CNC_DIAG_HA_FILT_SZ,
                CNC_DIAG_IN_BACKP,
                CNC_DIAG_RESTARTS,
                CNC_DIAG_SV_FILT_CNT,
                CNC_DIAG_SV_FILT_SZ,
            )

            d = {
                "signal": cnc.signal_query(),
                "heartbeat": cnc.heartbeat_query(),
                "in_backp": cnc.diag(CNC_DIAG_IN_BACKP),
                "backp_cnt": cnc.diag(CNC_DIAG_BACKP_CNT),
                "ha_filt_cnt": cnc.diag(CNC_DIAG_HA_FILT_CNT),
                "ha_filt_sz": cnc.diag(CNC_DIAG_HA_FILT_SZ),
                "sv_filt_cnt": cnc.diag(CNC_DIAG_SV_FILT_CNT),
                "sv_filt_sz": cnc.diag(CNC_DIAG_SV_FILT_SZ),
            }
            if feed_cap:
                # fd_feed feeder gauges (verify tiles publish them;
                # zeros elsewhere). Slots 8.. only exist on the 16-slot
                # cnc ABI — never read them against a stale .so.
                d.update({
                    "feed_batches": cnc.diag(CNC_DIAG_FEED_BATCHES),
                    "feed_lanes": cnc.diag(CNC_DIAG_FEED_LANES),
                    "feed_deadline_flush": cnc.diag(CNC_DIAG_FEED_DEADLINE),
                    "feed_starved_flush": cnc.diag(CNC_DIAG_FEED_STARVED),
                    "feed_slot_stall": cnc.diag(CNC_DIAG_FEED_SLOT_STALL),
                    "feed_idle_ns": cnc.diag(CNC_DIAG_FEED_IDLE_NS),
                    # Crash-only recovery state (supervisor-written):
                    # restart count + currently-pending respawn backoff.
                    "restarts": cnc.diag(CNC_DIAG_RESTARTS),
                    "backoff_ms": cnc.diag(CNC_DIAG_BACKOFF_MS),
                })
            out[f"tile.{name}"] = d
        if "fseq" in sub:
            fs = FSeq(wksp, sub["fseq"])
            mc = MCache(wksp, sub["mcache"]) if "mcache" in sub else None
            d = {
                "seq": fs.query(),
                "pub_cnt": fs.diag(DIAG_PUB_CNT),
                "pub_sz": fs.diag(DIAG_PUB_SZ),
                "filt_cnt": fs.diag(DIAG_FILT_CNT),
                "filt_sz": fs.diag(DIAG_FILT_SZ),
                "ovrnp_cnt": fs.diag(DIAG_OVRNP_CNT),
                "ovrnr_cnt": fs.diag(DIAG_OVRNR_CNT),
                "slow_cnt": fs.diag(DIAG_SLOW_CNT),
            }
            if mc is not None:
                d["tx_seq"] = mc.seq_next()
            out[f"link.{name}"] = d
    # fd_flight registry overlay: the typed metric rows (breaker state,
    # quarantine/failover counters, compile accounting — everything the
    # 16-slot cnc diag never had room for) merged into each tile's
    # snapshot dict, plus the per-edge trace-span summaries.
    from firedancer_tpu.disco import flight

    ftiles = flight.read_tiles(wksp)
    if ftiles:
        for label, metrics in ftiles.items():
            key = f"tile.{label}"
            if key in out:
                out[key].update(
                    {f"fl_{k}": v for k, v in metrics.items()})
    fedges = flight.read_edges(wksp)
    if fedges:
        for label, summ in fedges.items():
            out[f"span.{label}"] = summ
    # fd_sentinel SLO rows: evaluation/alert counters + current burn
    # and state per declared SLO (the live view of the judgment layer;
    # fd_top renders them as the SLO panel).
    fslos = flight.read_slos(wksp)
    if fslos:
        for label, row in fslos.items():
            out[f"slo.{label}"] = row
    # fd_xray queue/backpressure rows: per-edge dwell histogram summary
    # + producer stall / consumer idle / depth / credits — fd_top's
    # XRAY panel and the waterfall read these.
    from firedancer_tpu.disco import xray

    xq = xray.read_queue(wksp)
    if xq:
        for label, row in xq.items():
            d = dict(row)
            dwell = d.pop("dwell", {}) or {}
            d.update({f"dwell_{k}": v for k, v in dwell.items()})
            out[f"xq.{label}"] = d
    return out


def render(
    snap: Dict[str, Dict[str, int]],
    prev: Optional[Dict[str, Dict[str, int]]] = None,
    dt_s: float = 1.0,
    ansi: bool = True,
) -> str:
    """ANSI dashboard: tiles (state, heartbeat age, backpressure, filters)
    then links (seq progress, rates vs the prev snapshot)."""
    now = tempo.tickcount()
    bold = "\x1b[1m" if ansi else ""
    dim = "\x1b[2m" if ansi else ""
    rst = "\x1b[0m" if ansi else ""
    lines = []
    lines.append(
        f"{bold}{'TILE':<14}{'state':>6}{'hb-age-ms':>11}{'backp':>8}"
        f"{'ha-filt':>9}{'sv-filt':>9}{'rst':>5}{'boff-ms':>9}{rst}"
    )
    for name, d in sorted(snap.items()):
        if not name.startswith("tile."):
            continue
        hb_age = (now - d["heartbeat"]) / 1e6 if d["heartbeat"] else -1
        lines.append(
            f"{name[5:]:<14}{_SIGNAL_NAMES.get(d['signal'], '?'):>6}"
            f"{hb_age:>11.1f}{d['backp_cnt']:>8}"
            f"{d['ha_filt_cnt']:>9}{d['sv_filt_cnt']:>9}"
            f"{d.get('restarts', 0):>5}{d.get('backoff_ms', 0):>9}"
        )
    # fd_feed feeder panel: only tiles that actually dispatched feeder
    # batches (verify tiles under fd_feed) — fill%, flush buckets,
    # stalls, device-idle estimate per snapshot interval, plus the
    # fd_flight healing columns the cnc diag never had room for:
    # circuit-breaker state/trips and the quarantine counters (before
    # fd_flight the breaker was only visible in verify_stats, never on
    # the live dashboard).
    _BRK = {0: "clsd", 1: "OPEN", 2: "half", 3: "-"}
    feeders = [
        (name, d) for name, d in sorted(snap.items())
        if name.startswith("tile.")
        and (d.get("feed_batches") or d.get("fl_batches"))
    ]
    if feeders:
        lines.append("")
        lines.append(
            f"{bold}{'FEEDER':<14}{'batches':>9}{'lanes':>9}{'dl-fl':>7}"
            f"{'st-fl':>7}{'stall':>7}{'idle-ms':>9}"
            f"{'brk':>6}{'trip':>6}{'quar':>6}{'q-err':>7}{'cpu-fo':>8}"
            f"{rst}"
        )
        for name, d in feeders:
            p = (prev or {}).get(name, {})
            idle_ns = d.get("feed_idle_ns", d.get("fl_feed_idle_ns", 0))
            idle_ms = (idle_ns - p.get(
                "feed_idle_ns", p.get("fl_feed_idle_ns", 0))) / 1e6
            brk = _BRK.get(d.get("fl_breaker_state", 3), "?")
            lines.append(
                f"{name[5:]:<14}"
                f"{d.get('feed_batches', d.get('fl_batches', 0)):>9}"
                f"{d.get('feed_lanes', d.get('fl_lanes', 0)):>9}"
                f"{d.get('feed_deadline_flush', d.get('fl_flush_timeout', 0)):>7}"
                f"{d.get('feed_starved_flush', d.get('fl_flush_starved', 0)):>7}"
                f"{d.get('feed_slot_stall', d.get('fl_slot_stall', 0)):>7}"
                f"{idle_ms:>9.1f}"
                f"{brk:>6}{d.get('fl_breaker_trips', 0):>6}"
                f"{d.get('fl_quarantined', 0):>6}"
                f"{d.get('fl_quarantine_err_txn', 0):>7}"
                f"{d.get('fl_cpu_failover', 0):>8}"
            )
    lines.append("")
    lines.append(
        f"{bold}{'LINK':<16}{'tx_seq':>9}{'rx_seq':>9}{'pub/s':>10}"
        f"{'MB/s':>8}{'filt':>7}{'ovrn':>6}{'slow':>6}{rst}"
    )
    for name, d in sorted(snap.items()):
        if not name.startswith("link."):
            continue
        p = (prev or {}).get(name, {})
        rate = (d["pub_cnt"] - p.get("pub_cnt", 0)) / max(dt_s, 1e-9)
        mbps = (d["pub_sz"] - p.get("pub_sz", 0)) / max(dt_s, 1e-9) / 1e6
        ovrn = d["ovrnp_cnt"] + d["ovrnr_cnt"]
        lines.append(
            f"{name[5:]:<16}{d.get('tx_seq', 0):>9}{d['seq']:>9}"
            f"{rate:>10.0f}{mbps:>8.2f}{d['filt_cnt']:>7}{ovrn:>6}"
            f"{d['slow_cnt']:>6}"
        )
    return "\n".join(lines)


def watch(wksp: Workspace, pod: Pod, interval_s: float = 1.0,
          iterations: int = 0) -> None:
    """Live dashboard loop (fdctl monitor analog). iterations=0 -> forever."""
    prev = None
    i = 0
    while not iterations or i < iterations:
        snap = snapshot(wksp, pod)
        print("\x1b[2J\x1b[H" + render(snap, prev, interval_s))
        prev = snap
        time.sleep(interval_s)
        i += 1
