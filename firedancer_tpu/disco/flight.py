"""fd_flight — unified metrics registry, trace spans, flight recorder.

The observability layer the round-6 gates (>=400k verifies/s,
>=20k txn/s replay) and the ROOFLINE.md falsifiable predictions are
attributed THROUGH. Before this module the numbers were assembled by
hand from three disjoint sources — verify_stats dicts built in
feed/runtime.py, 16-slot cnc diag counters mirrored per gauge, and
sampled stage_latency_ms — with no per-transaction trace and no
postmortem record (the PR 3 compile-stall respawn storm was invisible
until it had destroyed throughput). fd_flight replaces that with:

  REGISTRY   typed central metric specs (the flags.py pattern: name /
             kind / doc declared ONCE, below) backed by preallocated
             shared-memory rows in the tango workspace. build_topology
             creates two regions — ``flight.metrics`` (one row of u64
             slots per tile) and ``flight.edges`` (one log2 histogram
             row per link edge + the e2e span) — with self-describing
             label headers, so tiles, the feeder stager/dispatcher,
             the supervisor, and worker processes all attach by label
             and write through one API. verify_stats / replay / bench
             artifacts are VIEWS assembled from these rows, not
             hand-rolled dicts. Every row has exactly one writer (the
             owning tile; a crash-respawned incarnation resumes
             delta-exact because counters only ever accumulate), so no
             cross-process atomics are needed.

  SPANS      the trace id of a txn is its 32-bit ``tsorig`` stamp,
             minted exactly once at source publish (replay/quic tile)
             and propagated bit-exactly through parse -> dedup ->
             verify (stage/flush/dispatch/complete — the feed slot
             sidecars carry it through staging, quarantine re-verify
             and the bulk completion) -> pack -> sink. Every OutLink
             publish observes (tspub - tsorig) into its edge's
             ALWAYS-ON log2 histogram — full-population latency per
             edge, replacing the sampling-only p50/p99 as the
             docs/LATENCY.md budget surface (the reservoirs remain for
             fine-grained percentiles).

  RECORDER   a per-tile ring buffer of the last N structured events
             (dispatches, adaptive-flush verdicts, breaker
             transitions, quarantines, chaos injections, stager /
             worker respawns, HALT) that dumps to a JSON artifact on
             crash, HALT, or signal when FD_FLIGHT_DUMP names a
             directory — the postmortem the respawn-storm class of
             failure requires.

Deliberately stdlib+numpy only: host-side tiles must stay
jax-import-free (disco/tiles.py's dispatch contract).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from firedancer_tpu import flags

# Artifact schema (BENCH/REPLAY/PACK artifacts + BENCH_LOG.jsonl lines
# + flight dumps). 2 = the fd_flight era: schema_version itself,
# stage_hist, engine_key/compile accounting. 3 = the fdgraph era:
# verify/engine artifacts carry a graph_cert block (sha256 of the
# committed lint_graph_cert.json + per-rung MSM cost-drift pct), so a
# bench number is always attributable to the proved graph contract set
# it ran under.
ARTIFACT_SCHEMA_VERSION = 3

_U64 = (1 << 64) - 1


# --------------------------------------------------------------------------
# Metric specs — the typed central registry. Declared once, like flags.py:
# a metric that is not specced here cannot be written (IndexError at the
# lane), so names/semantics cannot drift per call site.
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Metric:
    name: str
    kind: str          # "counter" (monotonic, delta-accumulated across
                       # tile incarnations) | "gauge" (last-write-wins)
    doc: str


# One row of these per tile in the ``flight.metrics`` region. The verify
# tile is the main writer; other tiles leave unused slots at 0.
TILE_METRICS: Tuple[Metric, ...] = (
    Metric("batches", "counter", "verify batches dispatched"),
    Metric("lanes", "counter",
           "signature lanes in dispatched batches (fill_ratio = lanes / "
           "(batches * batch))"),
    Metric("flush_timeout", "counter",
           "partial batches flushed by deadline expiry (ROADMAP round-6 "
           "gate: ~0 at steady state)"),
    Metric("flush_starved", "counter",
           "partial batches flushed by the starved-input early-out"),
    Metric("inflight_stall", "counter",
           "dispatches that blocked on the in-flight batch cap"),
    Metric("rlc_fallback", "counter",
           "batches that took the per-lane fallback after the RLC batch "
           "equation failed"),
    Metric("cpu_failover", "counter",
           "batches served by the CPU oracle lane (breaker open or "
           "dispatch error)"),
    Metric("quarantined", "counter",
           "poisoned batches re-verified on the CPU oracle lane at "
           "completion"),
    Metric("quarantine_err_txn", "counter",
           "quarantine offenders published downstream as CTL_ERR audit "
           "frags"),
    Metric("ctl_err_drop", "counter",
           "producer-flagged CTL_ERR frags dropped at the ctl word"),
    Metric("stager_restarts", "counter",
           "fd_feed stager-thread supervision respawns"),
    Metric("slot_stall", "counter",
           "stager slot acquires that had to wait for a FREE slot"),
    Metric("feed_idle_ns", "counter",
           "dispatcher device-idle estimate (nothing in flight AND "
           "nothing READY), ns"),
    Metric("compile_cnt", "counter",
           "verify-engine (pre)compiles paid by this tile"),
    Metric("compile_ns", "counter",
           "total wall ns spent in verify-engine (pre)compiles"),
    Metric("compile_cache_hit", "counter",
           "(pre)compiles that resolved fast enough to be persistent-"
           "cache hits (< 1 s heuristic)"),
    # fd_engine rung scheduler (disco/engine.py): target-B changes and
    # the current target, mirrored from the stager's decisions; the
    # per-rung dispatch histogram lives in verify_stats.rung_hist (the
    # ladder is config-sized, so it cannot be a fixed metric row).
    Metric("rung_switches", "counter",
           "fd_engine rung-scheduler target-B changes (ladder moves "
           "between the 8k/16k/32k-style rungs)"),
    Metric("rung_cur", "gauge",
           "current fd_engine scheduler target B (0 = scheduler off)"),
    Metric("breaker_state", "gauge",
           "verify failover breaker state: 0 closed, 1 open, 2 half_open, "
           "3 disabled/absent"),
    Metric("breaker_trips", "gauge",
           "times the failover circuit opened from closed"),
    Metric("breaker_reprobes", "gauge",
           "half-open device re-probes attempted"),
    # fd_siege QUIC front-door defense counters (written by the quic
    # tile's lane; zero everywhere else). Shed work is ACCOUNTED, never
    # silent: admitted + shed == offered is a siege-smoke gate.
    Metric("admit_shed", "counter",
           "txns shed by per-connection token-bucket admission at the "
           "QUIC tile (FD_QUIC_ADMIT_RATE/_BURST)"),
    Metric("queue_shed", "counter",
           "txns shed by credit-aware lowest-priority load shedding "
           "when the front-door ready queue exceeds FD_QUIC_SHED_DEPTH"),
    Metric("conn_quarantine", "counter",
           "abusive peers quarantined by the connection-level circuit "
           "breaker (FD_QUIC_ABUSE_THRESHOLD trips within 1 s)"),
    Metric("quarantine_drop", "counter",
           "datagrams dropped at the socket from quarantined peers "
           "(cooldown window; half-open re-admit after it)"),
    # fd_drain device-resident post-verify pipeline (disco/drain.py).
    # Verify-tile rows: the filter aux dispatch + novel/maybe claim
    # split over PUBLISHED clean txns (CTL_ERR and quarantine-dropped
    # lanes excluded, so at quiescence drain_novel + drain_maybe ==
    # drain_probe_skip + drain_probed on the dedup lane).
    Metric("drain_batches", "counter",
           "verify batches dispatched with the fused fd_drain dedup "
           "pre-filter aux graph"),
    Metric("drain_novel", "counter",
           "published clean txns the device filter claimed DEFINITELY "
           "novel (ctl CTL_NOVEL set)"),
    Metric("drain_maybe", "counter",
           "published clean txns left maybe-dup (host TCache stays the "
           "authority)"),
    Metric("drain_rot", "counter",
           "fd_drain filter window rotations (bank B <- A after the "
           "eviction-covering publish quota)"),
    # Dedup-tile rows: what the novel claims bought downstream.
    Metric("drain_probe_skip", "counter",
           "clean frags whose dup verdict came from the device novel "
           "claim — the TCache probe skipped as decision authority"),
    Metric("drain_probed", "counter",
           "clean frags probed against the host TCache (maybe-dup "
           "lanes)"),
    Metric("drain_false_novel", "counter",
           "tripwire: novel claims the TCache contradicted (one-sided "
           "contract breach; frag dropped as duplicate, ~0 always)"),
    # Pack-tile rows: device pack_gc wave schedules vs the CPU greedy
    # oracle. Exact accounting gate: pack_block_device +
    # pack_sched_fallback == blocks scheduled.
    Metric("pack_wave_device", "counter",
           "pack waves published from device pack_gc wave colors"),
    Metric("pack_block_device", "counter",
           "pack blocks whose device schedule validated and beat (or "
           "tied) CPU greedy rewards/CU"),
    Metric("pack_sched_fallback", "counter",
           "pack blocks that fell back to the exact CPU greedy "
           "schedule (validation miss or losing rewards/CU)"),
    # fd_soak live-reconfig rows: ladder/flag swaps applied at the
    # inflight-window barrier vs requests refused at validation.
    Metric("reconfigs", "counter",
           "live reconfigs applied at the inflight-window barrier "
           "(ladder swap / engine-flag flip / drain-mode change, zero "
           "dropped txns by construction)"),
    Metric("reconfig_refused", "counter",
           "live reconfig requests refused at validation (invalid "
           "mode/backend combo, unusable ladder, or a swap already "
           "pending)"),
)

TILE_IDX: Dict[str, int] = {m.name: i for i, m in enumerate(TILE_METRICS)}
_TILE_KIND: Tuple[str, ...] = tuple(m.kind for m in TILE_METRICS)

BREAKER_STATE_CODE = {"closed": 0, "open": 1, "half_open": 2, "disabled": 3}
BREAKER_STATE_NAME = {v: k for k, v in BREAKER_STATE_CODE.items()}

# Log2 latency histogram shape per edge: bucket b counts samples with
# bit_length(ns) == b, i.e. ns in [2^(b-1), 2^b). 40 buckets cover up
# to ~18 minutes; everything larger clamps into the last bucket. Row
# layout: [sum_ns, bucket_0 .. bucket_{N-1}]  (count = sum of buckets).
N_BUCKETS = 40
EDGE_SLOTS = 1 + N_BUCKETS

# Region names + header layout. Header: [magic, n_rows, n_slots, 0];
# each row: 4 u64 of utf-8 label (32 bytes, NUL-padded) + n_slots u64.
_METRICS_REGION = "flight.metrics"
_EDGES_REGION = "flight.edges"
_SLO_REGION = "flight.slo"
_MAGIC_TILES = 0xF11687_0001
_MAGIC_EDGES = 0xF11687_0002
_MAGIC_SLO = 0xF11687_0003
_LABEL_U64 = 4   # 32-byte label field

# fd_sentinel SLO rows (disco/sentinel.py is the single writer — one
# sentinel per run, in the runner process). Slot layout per SLO:
# [evals, alerts, breach_polls, burn_milli, state]; evals/alerts/
# breach_polls are counters, burn_milli (current burn rate x1000, or
# stall ms for liveness SLOs) and state (0 ok / 1 alert) are gauges.
SLO_SLOTS = 5
SLO_EVALS, SLO_ALERTS, SLO_BREACH_POLLS, SLO_BURN_MILLI, SLO_STATE = range(5)


def _region_footprint(n_rows: int, n_slots: int) -> int:
    return 8 * (4 + n_rows * (_LABEL_U64 + n_slots))


def _pack_label(label: str) -> bytes:
    b = label.encode()[: _LABEL_U64 * 8 - 1]
    return b + b"\x00" * (_LABEL_U64 * 8 - len(b))


def create_regions(wksp, tile_labels, edge_labels, slo_labels=()) -> None:
    """Allocate + initialize the shared-memory registry regions (called
    by build_topology; every row is pre-labeled so attachers never
    race a claim). slo_labels pre-labels the fd_sentinel SLO rows
    (sentinel.SLO_NAMES); empty skips the region — old callers keep
    working and the sentinel degrades to process-local rows."""
    regions = [
        (_METRICS_REGION, _MAGIC_TILES, tile_labels, len(TILE_METRICS)),
        (_EDGES_REGION, _MAGIC_EDGES, edge_labels, EDGE_SLOTS),
    ]
    if slo_labels:
        regions.append((_SLO_REGION, _MAGIC_SLO, slo_labels, SLO_SLOTS))
    for region, magic, labels, n_slots in regions:
        labels = list(labels)
        wksp.alloc(region, _region_footprint(len(labels), n_slots))
        a = np.frombuffer(wksp.view(region), np.uint64)
        a[:] = 0
        a[0] = magic
        a[1] = len(labels)
        a[2] = n_slots
        for i, label in enumerate(labels):
            row = 4 + i * (_LABEL_U64 + n_slots)
            a[row: row + _LABEL_U64] = np.frombuffer(
                _pack_label(label), np.uint64)


def _region_rows(wksp, region: str, magic: int, n_slots: int):
    """[(label, u64_row_view)] of a registry region, or None when the
    region is absent / from a different schema (old workspace: callers
    degrade to process-local arrays)."""
    try:
        view = wksp.view(region)
    except KeyError:
        return None
    a = np.frombuffer(view, np.uint64)
    if a.size < 4 or int(a[0]) != magic or int(a[2]) != n_slots:
        return None
    out = []
    n_rows = int(a[1])
    for i in range(n_rows):
        row = 4 + i * (_LABEL_U64 + n_slots)
        label = a[row: row + _LABEL_U64].tobytes().split(b"\x00")[0]
        out.append((label.decode("utf-8", "replace"),
                    a[row + _LABEL_U64: row + _LABEL_U64 + n_slots]))
    return out


def _attach_row(wksp, region: str, magic: int, n_slots: int, label: str):
    rows = _region_rows(wksp, region, magic, n_slots)
    if rows is None:
        return None
    for lab, row in rows:
        if lab == label:
            return row
    return None


# --------------------------------------------------------------------------
# Writer handles.
# --------------------------------------------------------------------------


class TileLane:
    """One tile's metric row. ``inc``/``set_gauge`` write the LOCAL
    array (allocation-free: a preallocated u64 vector, one indexed
    add); ``publish`` mirrors it into the shared row — counters as
    deltas (so a crash-respawned incarnation accumulates instead of
    rewinding the shared view), gauges as last-write-wins."""

    __slots__ = ("label", "a", "_shm", "_last")

    def __init__(self, label: str, shm_row=None):
        self.label = label
        self.a = np.zeros(len(TILE_METRICS), np.uint64)
        self._shm = shm_row
        self._last = np.zeros(len(TILE_METRICS), np.uint64)

    def inc(self, name: str, n: int = 1) -> None:
        self.a[TILE_IDX[name]] += np.uint64(n)

    def set_gauge(self, name: str, v: int) -> None:
        self.a[TILE_IDX[name]] = np.uint64(v)

    def get(self, name: str) -> int:
        return int(self.a[TILE_IDX[name]])

    def publish(self) -> None:
        if self._shm is None:
            return
        # SNAPSHOT the live array first: in fd_feed mode the stager
        # thread incs this lane while the dispatcher publishes, and
        # computing deltas against the live view would fold a
        # concurrent increment into _last without ever mirroring it
        # (a permanently lost count). With the snapshot, an inc that
        # lands mid-publish is simply carried by the NEXT publish.
        cur = self.a.copy()
        last = self._last
        if np.array_equal(cur, last):
            return
        for i, kind in enumerate(_TILE_KIND):
            if kind == "counter":
                d = int(cur[i]) - int(last[i])
                if d:
                    self._shm[i] += np.uint64(d & _U64)
            elif cur[i] != self._shm[i]:
                self._shm[i] = cur[i]
        self._last = cur

    def as_dict(self) -> Dict[str, int]:
        return {m.name: int(self.a[i]) for i, m in enumerate(TILE_METRICS)}


class EdgeHist:
    """Always-on log2 latency histogram for one pipeline edge. The row
    (shared-memory when the workspace carries the registry region, a
    process-local array otherwise) is written directly — each edge has
    exactly one producing tile, so the writes are single-writer."""

    __slots__ = ("label", "row")

    def __init__(self, label: str, row=None):
        self.label = label
        self.row = row if row is not None else np.zeros(EDGE_SLOTS, np.uint64)

    def observe(self, ns: int) -> None:
        b = min(int(ns).bit_length(), N_BUCKETS - 1)
        # sum_ns wraps mod 2^64 by design (a counter, not a gauge);
        # int-side math avoids numpy's overflow warning on the wrap.
        self.row[0] = np.uint64((int(self.row[0]) + ns) & _U64)
        self.row[1 + b] += np.uint64(1)

    def observe_many(self, ns_arr) -> None:
        """Vectorized observe (the fd_feed bulk completion path)."""
        a = np.asarray(ns_arr, np.int64)
        if a.size == 0:
            return
        # bit_length via log2: bucket b holds [2^(b-1), 2^b).
        b = np.zeros(a.shape, np.int64)
        pos = a > 0
        b[pos] = np.floor(np.log2(a[pos])).astype(np.int64) + 1
        np.clip(b, 0, N_BUCKETS - 1, out=b)
        counts = np.bincount(b, minlength=N_BUCKETS).astype(np.uint64)
        self.row[1:] += counts
        self.row[0] = np.uint64((int(self.row[0]) + int(a.sum())) & _U64)

    # -- read side --------------------------------------------------------

    def count(self) -> int:
        return int(self.row[1:].sum())

    def percentile_ns(self, q: float) -> int:
        """Upper bucket bound of the q-quantile (q in [0,1]): the
        histogram's conservative estimate of p50/p99 — coarse (factor
        2) by construction, but over the FULL population, always on."""
        buckets = self.row[1:]
        n = int(buckets.sum())
        if n == 0:
            return 0
        target = q * n
        acc = 0
        for b in range(N_BUCKETS):
            acc += int(buckets[b])
            if acc >= target:
                return (1 << b) if b else 0
        return 1 << (N_BUCKETS - 1)

    def summary(self) -> Dict[str, int]:
        return {
            "n": self.count(),
            "p50_ns_le": self.percentile_ns(0.50),
            "p99_ns_le": self.percentile_ns(0.99),
            "sum_ns": int(self.row[0]),
        }


def tile_lane(wksp, label: str) -> TileLane:
    """The one write API for tile metrics: attaches the tile's shared
    row when the workspace carries the registry (build_topology
    workspaces do), else degrades to a process-local lane (raw test
    workspaces, direct tile construction)."""
    row = None
    if wksp is not None:
        try:
            row = _attach_row(wksp, _METRICS_REGION, _MAGIC_TILES,
                              len(TILE_METRICS), label)
        except Exception:
            row = None
    return TileLane(label, row)


def edge_hist(wksp, label: str) -> EdgeHist:
    row = None
    if wksp is not None:
        try:
            row = _attach_row(wksp, _EDGES_REGION, _MAGIC_EDGES,
                              EDGE_SLOTS, label)
        except Exception:
            row = None
    return EdgeHist(label, row)


# --------------------------------------------------------------------------
# Read side — snapshot views assembled FROM the registry.
# --------------------------------------------------------------------------


def read_tiles(wksp) -> Optional[Dict[str, Dict[str, int]]]:
    """{tile_label: {metric: value}} from the shared region (None when
    the workspace predates fd_flight)."""
    rows = _region_rows(wksp, _METRICS_REGION, _MAGIC_TILES,
                        len(TILE_METRICS))
    if rows is None:
        return None
    return {
        label: {m.name: int(row[i]) for i, m in enumerate(TILE_METRICS)}
        for label, row in rows
    }


def read_edges(wksp) -> Optional[Dict[str, Dict[str, int]]]:
    """{edge_label: histogram summary} from the shared region."""
    rows = _region_rows(wksp, _EDGES_REGION, _MAGIC_EDGES, EDGE_SLOTS)
    if rows is None:
        return None
    return {label: EdgeHist(label, row).summary() for label, row in rows}


def read_edges_raw(wksp) -> Optional[Dict[str, np.ndarray]]:
    """{edge_label: COPY of the raw [sum_ns, bucket_0..] row} — the
    form fd_sentinel's windowed burn-rate deltas and the cross-shard
    histogram merge need (summaries cannot be merged; log2 bucket rows
    merge by elementwise add)."""
    rows = _region_rows(wksp, _EDGES_REGION, _MAGIC_EDGES, EDGE_SLOTS)
    if rows is None:
        return None
    return {label: np.array(row, dtype=np.uint64) for label, row in rows}


def slo_row(wksp, label):
    """The shared row for one SLO (sentinel is the single writer), or
    None when the workspace predates the region / lacks the label —
    callers degrade to a process-local array."""
    if wksp is None:
        return None
    try:
        return _attach_row(wksp, _SLO_REGION, _MAGIC_SLO, SLO_SLOTS, label)
    except Exception:
        return None


def read_slos(wksp) -> Optional[Dict[str, Dict[str, int]]]:
    """{slo_name: {evals, alerts, breach_polls, burn_milli, state}}
    from the shared region (None when absent)."""
    rows = _region_rows(wksp, _SLO_REGION, _MAGIC_SLO, SLO_SLOTS)
    if rows is None:
        return None
    keys = ("evals", "alerts", "breach_polls", "burn_milli", "state")
    return {label: {k: int(row[i]) for i, k in enumerate(keys)}
            for label, row in rows}


# --------------------------------------------------------------------------
# Cross-process / cross-shard aggregation (fd_sentinel part 3): roll
# per-process and per-shard registry rows into ONE snapshot. Counters
# sum (they delta-accumulate, so the sum over rows IS the pod total);
# log2 histogram rows merge by elementwise add (bucketing is identical
# everywhere, so the merged histogram is exactly the histogram of the
# concatenated samples); gauges need a policy — breaker_state merges
# most-severe (an open breaker anywhere must not be averaged away),
# every other gauge sums (trips/reprobes are per-row totals whose pod
# aggregate is their sum).
# --------------------------------------------------------------------------

# Severity order for merging breaker_state codes: open > half_open >
# closed > disabled (codes 1, 2, 0, 3 — see BREAKER_STATE_CODE).
_BREAKER_SEVERITY = {1: 3, 2: 2, 0: 1, 3: 0}


def merge_tile_metrics(rows) -> Dict[str, int]:
    """Aggregate several tile metric dicts (as read_tiles values /
    TileLane.as_dict) into one rollup row."""
    out = {m.name: 0 for m in TILE_METRICS}
    breaker = 3  # disabled until any row says otherwise
    for row in rows:
        for m in TILE_METRICS:
            v = int(row.get(m.name, 0))
            if m.name == "breaker_state":
                if (_BREAKER_SEVERITY.get(v, 0)
                        > _BREAKER_SEVERITY.get(breaker, 0)):
                    breaker = v
            else:
                out[m.name] += v
    out["breaker_state"] = breaker
    return out


def merge_edge_rows(rows) -> np.ndarray:
    """Elementwise-add several raw edge rows into one (sum_ns wraps
    mod 2^64 like the per-row counter it is)."""
    out = np.zeros(EDGE_SLOTS, np.uint64)
    sum_ns = 0
    for row in rows:
        a = np.asarray(row, np.uint64)
        out[1:] += a[1:]
        sum_ns = (sum_ns + int(a[0])) & _U64
    out[0] = np.uint64(sum_ns)
    return out


def snapshot_raw(wksp) -> Dict[str, dict]:
    """One registry snapshot in mergeable form: {"metrics": {tile:
    {metric: value}}, "edges": {edge: raw row}}."""
    return {
        "metrics": read_tiles(wksp) or {},
        "edges": read_edges_raw(wksp) or {},
    }


def merge_snapshots(snaps) -> Dict[str, dict]:
    """Merge several snapshot_raw() results (one per process workspace
    / verify shard) into ONE: per-label counter sums and histogram
    adds, plus summaries of the merged edges. The contract the pod-
    scale verify service stands on: counters of the merge equal the
    sum of the per-source rows (test-pinned in tests/test_sentinel.py)."""
    snaps = list(snaps)
    metric_rows: Dict[str, List[dict]] = {}
    edge_rows: Dict[str, List[np.ndarray]] = {}
    for s in snaps:
        for label, row in (s.get("metrics") or {}).items():
            metric_rows.setdefault(label, []).append(row)
        for label, row in (s.get("edges") or {}).items():
            edge_rows.setdefault(label, []).append(row)
    edges_raw = {label: merge_edge_rows(rows)
                 for label, rows in edge_rows.items()}
    return {
        "metrics": {label: merge_tile_metrics(rows)
                    for label, rows in metric_rows.items()},
        "edges_raw": edges_raw,
        "edges": {label: EdgeHist(label, row).summary()
                  for label, row in edges_raw.items()},
    }


def verify_stats_view(wksp, label: str, batch: int) -> Optional[dict]:
    """The verify_stats record for one tile, assembled from the shared
    registry — the cross-process view the supervisor publishes (the
    in-process runners read the richer tile-object view via
    feed/runtime.verify_tile_stats; both carry the same keys)."""
    tiles = read_tiles(wksp)
    if tiles is None or label not in tiles:
        return None
    t = tiles[label]
    batches = t["batches"]
    return {
        "batches": batches,
        "lanes": t["lanes"],
        "fill_ratio": round(t["lanes"] / float(batches * batch), 4)
        if batches else 0.0,
        "flush_timeout": t["flush_timeout"],
        "flush_starved": t["flush_starved"],
        "inflight_stall": t["inflight_stall"],
        "rlc_fallback": t["rlc_fallback"],
        "slot_stall": t["slot_stall"],
        "device_idle_est_ms": round(t["feed_idle_ns"] / 1e6, 2),
        "stager_restarts": t["stager_restarts"],
        "cpu_failover": t["cpu_failover"],
        "quarantined": t["quarantined"],
        "quarantine_err_txn": t["quarantine_err_txn"],
        "ctl_err_drop": t["ctl_err_drop"],
        "breaker_state": BREAKER_STATE_NAME.get(
            t["breaker_state"], "disabled"),
        "breaker_trips": t["breaker_trips"],
        "breaker_reprobes": t["breaker_reprobes"],
        "compile_cnt": t["compile_cnt"],
        "compile_ms": round(t["compile_ns"] / 1e6, 1),
        "compile_cache_hit": t["compile_cache_hit"],
        # fd_engine rung scheduler: the shared lane carries the switch
        # counter + current-target gauge; the per-rung histogram is
        # tile-object state (config-sized), so the cross-process view
        # reports the same keys with the shape the artifact schema
        # allows for "unknown" ({}).
        "rung_switches": t["rung_switches"],
        "rung_cur": t["rung_cur"],
        "rung_hist": {},
        "rung_ladder": [],
        # fd_drain: filter claim split over published clean txns.
        "drain_batches": t["drain_batches"],
        "drain_novel": t["drain_novel"],
        "drain_maybe": t["drain_maybe"],
        "drain_rot": t["drain_rot"],
    }


def render_prom(wksp) -> str:
    """Prometheus-style text snapshot of the shared registry (+ this
    process's compile records). Exposition-format compatible enough
    for promtool/scrapers; the schema gate in scripts/obs_smoke.py
    pins the metric families."""
    lines: List[str] = []
    tiles = read_tiles(wksp) or {}
    for m in TILE_METRICS:
        prom_kind = "gauge" if m.kind == "gauge" else "counter"
        lines.append(f"# HELP fd_flight_{m.name} {m.doc}")
        lines.append(f"# TYPE fd_flight_{m.name} {prom_kind}")
        for label, t in sorted(tiles.items()):
            lines.append(
                f'fd_flight_{m.name}{{tile="{label}"}} {t[m.name]}')
    edges = _region_rows(wksp, _EDGES_REGION, _MAGIC_EDGES, EDGE_SLOTS) or []
    lines.append("# HELP fd_flight_edge_latency_ns trace-span latency "
                 "(tsorig -> tspub) per pipeline edge, log2 buckets")
    lines.append("# TYPE fd_flight_edge_latency_ns histogram")
    for label, row in edges:
        acc = 0
        for b in range(N_BUCKETS):
            acc += int(row[1 + b])
            lines.append(
                f'fd_flight_edge_latency_ns_bucket{{edge="{label}",'
                f'le="{1 << b}"}} {acc}')
        lines.append(
            f'fd_flight_edge_latency_ns_bucket{{edge="{label}",'
            f'le="+Inf"}} {acc}')
        lines.append(
            f'fd_flight_edge_latency_ns_sum{{edge="{label}"}} {int(row[0])}')
        lines.append(
            f'fd_flight_edge_latency_ns_count{{edge="{label}"}} {acc}')
    # fd_sentinel SLO rows (the fl_slo_* families): evaluation counts,
    # alert transitions, breach polls, current burn (x1000) and state
    # per declared SLO — scrapers alert on fd_flight_slo_state.
    slos = _region_rows(wksp, _SLO_REGION, _MAGIC_SLO, SLO_SLOTS) or []
    if slos:
        fams = (
            ("evals", SLO_EVALS, "counter", "sentinel evaluation passes"),
            ("alerts", SLO_ALERTS, "counter",
             "ok->alert transitions (burn-rate breaches)"),
            ("breach_polls", SLO_BREACH_POLLS, "counter",
             "evaluation passes spent in breach"),
            ("burn_milli", SLO_BURN_MILLI, "gauge",
             "current burn rate x1000 (stall/heartbeat-age ms for "
             "liveness SLOs)"),
            ("state", SLO_STATE, "gauge", "0 ok, 1 alerting"),
        )
        for name, slot, kind, doc in fams:
            lines.append(f"# HELP fd_flight_slo_{name} {doc}")
            lines.append(f"# TYPE fd_flight_slo_{name} {kind}")
            for label, row in slos:
                lines.append(
                    f'fd_flight_slo_{name}{{slo="{label}"}} '
                    f"{int(row[slot])}")
    with _compile_lock:
        recs = list(_compiles)
    lines.append("# HELP fd_flight_compile_seconds verify-engine compile "
                 "wall time per engine key (mode x B x shards x frontend)")
    lines.append("# TYPE fd_flight_compile_seconds gauge")
    for r in recs:
        lines.append(
            f'fd_flight_compile_seconds{{engine="{r["engine"]}",'
            f'cache_hit_est="{str(r["cache_hit_est"]).lower()}"}} '
            f'{r["seconds"]}')
    lines.append("")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Compile accounting (process-local; mirrored into the tile lane by the
# caller when one exists). Engine keys are mode x B x shards x frontend
# — the engine-registry refactor (ROADMAP direction 3) made observable
# before it lands.
# --------------------------------------------------------------------------

_compiles: List[dict] = []
_compile_lock = threading.Lock()
_COMPILE_CAP = 256
_CACHE_HIT_S = 1.0   # persistent-cache loads come back well under this


def engine_key(mode: str, batch: int, shards: int, frontend: str,
               msm: str = "auto") -> str:
    """mode:B<batch>:shards<n>:fe<impl>[:msm<plan>] — the msm segment
    (fd_msm2 schedule token, e.g. s7l3) appears ONLY when a non-auto
    plan is pinned, so every pre-fd_msm2 key (and every auto-plan
    engine) keeps its exact historical spelling and compile records
    stay comparable across rounds."""
    key = f"{mode}:B{batch}:shards{shards}:fe{frontend}"
    if msm and msm != "auto":
        key += f":msm{msm}"
    return key


def compile_cache_hit_est(seconds: float) -> bool:
    """THE persistent-cache-hit heuristic: one predicate shared by the
    compile records, the bench artifacts, and the fd_engine registry
    entries, so 'cache hit' can never mean two different thresholds at
    two dispatch sites (the PR-13 bench/prewarm consistency fix)."""
    return seconds < _CACHE_HIT_S


def record_compile(engine: str, seconds: float) -> dict:
    rec = {
        "engine": engine,
        "seconds": round(seconds, 3),
        "cache_hit_est": compile_cache_hit_est(seconds),
        "ts": time.time(),
    }
    with _compile_lock:
        _compiles.append(rec)
        del _compiles[:-_COMPILE_CAP]
    return rec


def compile_records() -> List[dict]:
    with _compile_lock:
        return list(_compiles)


# --------------------------------------------------------------------------
# Flight recorder — per-tile ring of structured events, dumpable.
# --------------------------------------------------------------------------

_recorders: Dict[str, "FlightRecorder"] = {}
_recorders_lock = threading.Lock()


def enabled() -> bool:
    """FD_FLIGHT=0 is the overhead-bisection hatch: event recording and
    span histograms off; metric lanes stay on (artifacts need them).
    Read per construction site, never per frag — the hot paths gate on
    the None-ness of the handles this decides."""
    return flags.get_bool("FD_FLIGHT")


class FlightRecorder:
    """Bounded ring of (tick, kind, fields) events. record() is a
    locked list store + int math — events are per-batch / per-fault
    (never per-frag), and recorders ARE written from several threads
    (the chaos injector's note() fires from the source, stager, and
    dispatcher threads), so an unlocked pos++ would drop events."""

    __slots__ = ("name", "buf", "pos", "n", "_lock")

    def __init__(self, name: str, cap: int):
        self.name = name
        self.buf: List[Optional[tuple]] = [None] * max(cap, 8)
        self.pos = 0
        self.n = 0
        self._lock = threading.Lock()

    def record(self, kind: str, **fields) -> None:
        from firedancer_tpu.tango import tempo

        t = tempo.tickcount()
        with self._lock:
            self.buf[self.pos] = (t, kind, fields or None)
            self.pos = (self.pos + 1) % len(self.buf)
            self.n += 1

    def events(self) -> List[dict]:
        """Chronological events currently held (oldest first)."""
        with self._lock:
            buf = list(self.buf)
            pos, n = self.pos, self.n
        cap = len(buf)
        start = pos if n >= cap else 0
        out = []
        for i in range(min(n, cap)):
            e = buf[(start + i) % cap]
            if e is None:
                continue
            t, kind, fields = e
            d = {"t": t, "kind": kind}
            if fields:
                d.update(fields)
            out.append(d)
        return out


class _NullRecorder:
    __slots__ = ()
    name = "null"
    n = 0

    def record(self, kind: str, **fields) -> None:
        pass

    def events(self) -> List[dict]:
        return []


_NULL = _NullRecorder()


def recorder(name: str):
    """A FRESH recorder registered under `name` (latest wins — each
    tile incarnation / chaos injector gets its own ring; the dump shows
    the current run's). Returns a no-op recorder when FD_FLIGHT=0."""
    if not enabled():
        return _NULL
    rec = FlightRecorder(name, flags.get_int("FD_FLIGHT_EVENTS"))
    with _recorders_lock:
        _recorders[name] = rec
    return rec


def dump(reason: str, wksp=None) -> dict:
    """The postmortem artifact: every live recorder's ring + the
    registry snapshot (when a workspace is given) + compile records."""
    with _recorders_lock:
        recs = dict(_recorders)
    out: dict = {
        "schema_version": ARTIFACT_SCHEMA_VERSION,
        "kind": "fd_flight_dump",
        "reason": reason,
        "pid": os.getpid(),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "recorders": {
            name: {"n_total": r.n, "events": r.events()}
            for name, r in sorted(recs.items())
        },
        "compiles": compile_records(),
    }
    # fd_xray exemplar rings + queue telemetry ride in the SAME dump
    # envelope (one postmortem artifact per trigger; lazy import —
    # xray imports this module). Readers that predate the section
    # ignore the key; sentinel.evaluate_edges_summary explicitly
    # accepts-and-ignores non-edge sections.
    try:
        from firedancer_tpu.disco import xray as _xray

        out["xray"] = {"spans": _xray.dump_spans()}
    except Exception:
        pass
    # A left workspace (leave() nulls the handle) must be skipped, not
    # dereferenced: fd_wksp_* with a NULL handle is a crash, not an
    # exception — and the signal handler can outlive the run that
    # registered the workspace.
    if wksp is not None and getattr(wksp, "_h", None):
        try:
            out["metrics"] = read_tiles(wksp)
            out["edges"] = read_edges(wksp)
            out["slos"] = read_slos(wksp)
            if "xray" in out:
                from firedancer_tpu.disco import xray as _xray

                out["xray"]["queue"] = _xray.read_queue(wksp)
        except Exception:
            pass
    return out


def maybe_dump(reason: str, wksp=None) -> Optional[str]:
    """Write the dump as a JSON artifact when FD_FLIGHT_DUMP names a
    directory (crash / HALT / signal triggers all route here); returns
    the path or None. Never raises — a failing postmortem writer must
    not mask the fault it is documenting."""
    try:
        d = flags.get_raw("FD_FLIGHT_DUMP")
        if not d or not enabled():
            return None
        os.makedirs(d, exist_ok=True)
        slug = "".join(c if c.isalnum() else "_" for c in reason)[:48]
        path = os.path.join(
            d, f"flight_{os.getpid()}_{int(time.time() * 1e3)}_{slug}.json")
        with open(path, "w") as f:
            json.dump(dump(reason, wksp=wksp), f, indent=1)
        return path
    except Exception:
        return None


_signal_installed = False
_dump_wksp = None


def install_dump_signal(wksp=None) -> None:
    """SIGUSR1 -> flight dump (live postmortem of a running pipeline).
    Main-thread only; a no-op off the main thread. Re-invocation
    REBINDS the dumped workspace (each run calls this, so the handler
    always reads the CURRENT run's registry, not the first run's
    long-left mapping)."""
    global _signal_installed, _dump_wksp
    if not enabled():
        return
    _dump_wksp = wksp  # rebind every call; dump() skips left handles
    if _signal_installed:
        return
    import signal

    def _h(signum, frame):
        maybe_dump("signal", wksp=_dump_wksp)

    try:
        signal.signal(signal.SIGUSR1, _h)
        _signal_installed = True
    except (ValueError, OSError):
        pass  # not the main thread / restricted environment
