"""Adaptive partial-batch flush policy for the verify feeder.

Replaces VerifyTile's fixed max-wait timer (round-2's 500 us partial-
batch timeout). The fixed timer has the wrong shape at both ends: at
steady state it chops full batches into partials whenever staging takes
longer than the timer (the round-5 replay artifact flushed 77 of 88
batches partial), and under trickle traffic it makes every stray txn
wait the full timer even when the device is sitting idle.

The policy is deadline-based with one adaptive early-out:

  full      lanes filled the batch — dispatch, always.
  deadline  the oldest staged txn is older than the latency deadline,
            anchored at STAGING time: dispatch NOW. This is the hard
            bound the property test pins — a partial batch is never
            starved past the deadline. Ring dwell (publish -> drain) is
            deliberately NOT folded into this anchor: with a backlog
            the next drain round fills the batch in O(ms) anyway, so
            counting dwell would only trade fill ratio for nothing —
            dwell is instead reported as the `verify_drain` stage
            latency so a growing backlog stays visible as input-side
            pressure.
  starved   the input ran dry AND the device is idle AND downstream has
            credits: waiting longer cannot improve fill and only adds
            latency, so dispatch after a short debounce (deadline/16,
            clamped) that absorbs momentary producer stalls (GIL hiccups
            must not collapse batch sizes).

At steady state arrivals fill batches before the deadline and the
device is never idle, so deadline/starved flushes both go to ~0 — the
ROADMAP round-6 `flush_timeout ~= 0` gate becomes the natural operating
point instead of a tuning exercise.
"""

from __future__ import annotations

from typing import Optional

# due() verdicts (also the stat-bucket names in verify_stats)
FLUSH_FULL = "full"
FLUSH_DEADLINE = "deadline"
FLUSH_STARVED = "starved"

_STARVE_MIN_NS = 100_000       # debounce floor: 100 us
_STARVE_MAX_NS = 5_000_000     # debounce ceiling: 5 ms


class AdaptiveFlush:
    """Clock-free decision logic (no clock READS — the caller passes
    now_ns) so the property test can drive it through arbitrary arrival
    schedules, including pathological ones: the policy keeps a
    high-water mark of the now_ns it has been shown FOR THE CURRENT
    BATCH (keyed by the first_ns anchor), so a clock that stutters or
    jumps BACKWARD can never un-expire a deadline — once a partial
    batch has been observed past its deadline, every later poll
    flushes it regardless of what the clock claims. The hwm resets
    with each new anchor: batches are independent latency contracts,
    and a prior batch's late clock must not pre-expire the next."""

    def __init__(self, deadline_ns: int):
        if deadline_ns <= 0:
            raise ValueError(f"deadline_ns must be positive, got {deadline_ns}")
        self.deadline_ns = deadline_ns
        self.starve_ns = min(
            max(deadline_ns // 16, _STARVE_MIN_NS), _STARVE_MAX_NS
        )
        # A debounce longer than the deadline could never fire first;
        # keep the invariant starve <= deadline explicit.
        self.starve_ns = min(self.starve_ns, deadline_ns)
        self._now_hwm = 0      # monotonic view of the caller's clock...
        self._hwm_anchor = None  # ...scoped to this batch anchor

    def due(
        self,
        now_ns: int,
        lanes: int,
        batch: int,
        first_ns: int,
        starved: bool = False,
        device_idle: bool = False,
        backpressured: bool = False,
    ) -> Optional[str]:
        """Flush verdict for the currently staged partial batch.

        now_ns/first_ns are the caller's tickcount and the batch's
        oldest-txn anchor; `starved` means the last drain round returned
        nothing; `device_idle` means no batch is in flight and no READY
        slot is queued; `backpressured` means the out link has no
        credits (flushing could not publish anyway, so the starved
        early-out defers — the DEADLINE still fires, because the staged
        txns' latency budget keeps burning while downstream recovers).
        Returns None (keep filling) or one of FLUSH_*.
        """
        if lanes <= 0:
            return None
        if lanes >= batch:
            return FLUSH_FULL
        # Clock-jitter hardening: within one batch (anchor), a backward
        # jump must not rewind the deadline (the staged txns' budget
        # keeps burning in real time), and an anchor stamped "in the
        # future" by a glitch must not produce a negative age that
        # defers the starved early-out.
        if first_ns != self._hwm_anchor:
            self._hwm_anchor = first_ns
            self._now_hwm = now_ns
        elif now_ns < self._now_hwm:
            now_ns = self._now_hwm
        else:
            self._now_hwm = now_ns
        age = max(0, now_ns - first_ns)
        if age >= self.deadline_ns:
            return FLUSH_DEADLINE
        if (
            starved
            and device_idle
            and not backpressured
            and age >= self.starve_ns
        ):
            return FLUSH_STARVED
        return None


# due-state names of the device->CPU verify failover breaker
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class CircuitBreaker:
    """Device->CPU verify failover circuit (the fd_chaos healing lane).

    The device (or verify executor) is a component that can disappear —
    wiredancer's FPGA model, SZKP's host fallback behind the accelerator
    scheduler — and its loss must degrade THROUGHPUT, not liveness:

      closed     dispatches go to the device; `threshold` CONSECUTIVE
                 device errors trip the breaker (one transient error
                 followed by a success resets the count — that is the
                 quarantine path's job, not an outage).
      open       dispatches are served by the CPU oracle lane for
                 `cooldown_ns`; then one half-open probe is allowed.
      half_open  exactly one dispatch probes the device. Success closes
                 the breaker (and resets the cooldown multiplier);
                 failure re-opens with the cooldown doubled, up to 8x —
                 a dead device is re-probed at a decaying rate instead
                 of once per cooldown forever.

    Pure decision logic like AdaptiveFlush: the caller passes now_ns,
    and only the dispatcher thread drives it (no locking needed).
    """

    def __init__(self, threshold: int, cooldown_ns: int):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got {threshold}")
        if cooldown_ns <= 0:
            raise ValueError(
                f"breaker cooldown_ns must be positive, got {cooldown_ns}")
        self.threshold = threshold
        self.cooldown_ns = cooldown_ns
        self.state = BREAKER_CLOSED
        self.errors = 0          # consecutive device errors while closed
        self.trips = 0           # times the circuit opened from closed
        self.reprobes = 0        # half-open probes attempted
        self._open_until = 0
        self._mult = 1

    def allow_device(self, now_ns: int) -> bool:
        """May this dispatch go to the device? Transitions open ->
        half_open when the cooldown has elapsed (granting exactly one
        probe; everything else stays on the CPU lane until the probe's
        own completion decides)."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_OPEN and now_ns >= self._open_until:
            self.state = BREAKER_HALF_OPEN
            self.reprobes += 1
            return True
        return False

    def record_error(self, now_ns: int) -> bool:
        """A device dispatch/completion failed. Returns True when this
        error tripped (or re-opened) the circuit."""
        if self.state == BREAKER_HALF_OPEN:
            self._mult = min(self._mult * 2, 8)
            self.state = BREAKER_OPEN
            self._open_until = now_ns + self.cooldown_ns * self._mult
            return True
        if self.state == BREAKER_OPEN:
            # Straggler completion from the outage window: extend nothing.
            return False
        self.errors += 1
        if self.errors >= self.threshold:
            self.state = BREAKER_OPEN
            self.trips += 1
            self.errors = 0
            self._mult = 1
            self._open_until = now_ns + self.cooldown_ns
            return True
        return False

    def record_success(self) -> None:
        """A device batch completed cleanly. Closes a half-open circuit
        (probe passed); a success from a pre-outage straggler while
        open changes nothing."""
        if self.state == BREAKER_HALF_OPEN:
            self.state = BREAKER_CLOSED
            self._mult = 1
        if self.state == BREAKER_CLOSED:
            self.errors = 0


class TokenBucket:
    """One admission token bucket: `rate` tokens per unit of the
    CALLER'S clock, capacity `burst`, one token per admit.

    Pure decision logic like AdaptiveFlush/CircuitBreaker: no clock
    reads — the caller passes `now` in whatever unit its clock ticks
    (fd_quic passes seconds, fd_fabric passes a virtual-nanosecond
    arrival clock with rate pre-scaled to per-ns), so the property
    tests can drive arbitrary arrival schedules and the fabric's
    deterministic replay admission is a pure function of the stream.
    A backward clock jump refills nothing (tokens never mint from
    jitter) but still charges the admit — the bucket is monotone in
    the work it lets through, not in the clock it is shown.
    """

    def __init__(self, rate: float, burst: float):
        if rate <= 0.0:
            raise ValueError(f"bucket rate must be positive, got {rate}")
        if burst < 1.0:
            raise ValueError(f"bucket burst must be >= 1, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = self.burst
        self._at: Optional[float] = None

    def admit(self, now) -> bool:
        """Spend one token at clock-time `now`; False means shed."""
        if self._at is None or now < self._at:
            self._at = now
        else:
            self.tokens = min(
                self.burst, self.tokens + (now - self._at) * self.rate
            )
            self._at = now
        if self.tokens < 1.0:
            return False
        self.tokens -= 1.0
        return True


def respawn_backoff_s(restarts: int, base_s: float, max_s: float,
                      rng) -> float:
    """Crash-only respawn delay AFTER `restarts` crashes (restarts >= 1):
    base * 2^(restarts-1) + 0-25% jitter, capped at max_s. Pure so the
    policy is unit-testable; base_s == 0 keeps immediate respawn. The
    jitter de-lockstops components that all died to one shared cause
    (e.g. a wedged workspace) from respawning as one thundering herd.
    Shared by the process supervisor's tile respawn and the feeder's
    stager-thread restart — ONE backoff policy, two supervision layers.
    """
    if base_s <= 0.0:
        return 0.0
    d = min(base_s * (1 << min(restarts - 1, 30)), max_s)
    return min(d * (1.0 + 0.25 * rng.float01()), max_s)
