"""Adaptive partial-batch flush policy for the verify feeder.

Replaces VerifyTile's fixed max-wait timer (round-2's 500 us partial-
batch timeout). The fixed timer has the wrong shape at both ends: at
steady state it chops full batches into partials whenever staging takes
longer than the timer (the round-5 replay artifact flushed 77 of 88
batches partial), and under trickle traffic it makes every stray txn
wait the full timer even when the device is sitting idle.

The policy is deadline-based with one adaptive early-out:

  full      lanes filled the batch — dispatch, always.
  deadline  the oldest staged txn is older than the latency deadline,
            anchored at STAGING time: dispatch NOW. This is the hard
            bound the property test pins — a partial batch is never
            starved past the deadline. Ring dwell (publish -> drain) is
            deliberately NOT folded into this anchor: with a backlog
            the next drain round fills the batch in O(ms) anyway, so
            counting dwell would only trade fill ratio for nothing —
            dwell is instead reported as the `verify_drain` stage
            latency so a growing backlog stays visible as input-side
            pressure.
  starved   the input ran dry AND the device is idle AND downstream has
            credits: waiting longer cannot improve fill and only adds
            latency, so dispatch after a short debounce (deadline/16,
            clamped) that absorbs momentary producer stalls (GIL hiccups
            must not collapse batch sizes).

At steady state arrivals fill batches before the deadline and the
device is never idle, so deadline/starved flushes both go to ~0 — the
ROADMAP round-6 `flush_timeout ~= 0` gate becomes the natural operating
point instead of a tuning exercise.
"""

from __future__ import annotations

from typing import Optional

# due() verdicts (also the stat-bucket names in verify_stats)
FLUSH_FULL = "full"
FLUSH_DEADLINE = "deadline"
FLUSH_STARVED = "starved"

_STARVE_MIN_NS = 100_000       # debounce floor: 100 us
_STARVE_MAX_NS = 5_000_000     # debounce ceiling: 5 ms


class AdaptiveFlush:
    """Pure decision logic (no clocks, no rings) so the property test
    can drive it through arbitrary arrival schedules."""

    def __init__(self, deadline_ns: int):
        if deadline_ns <= 0:
            raise ValueError(f"deadline_ns must be positive, got {deadline_ns}")
        self.deadline_ns = deadline_ns
        self.starve_ns = min(
            max(deadline_ns // 16, _STARVE_MIN_NS), _STARVE_MAX_NS
        )
        # A debounce longer than the deadline could never fire first;
        # keep the invariant starve <= deadline explicit.
        self.starve_ns = min(self.starve_ns, deadline_ns)

    def due(
        self,
        now_ns: int,
        lanes: int,
        batch: int,
        first_ns: int,
        starved: bool = False,
        device_idle: bool = False,
        backpressured: bool = False,
    ) -> Optional[str]:
        """Flush verdict for the currently staged partial batch.

        now_ns/first_ns are the caller's tickcount and the batch's
        oldest-txn anchor; `starved` means the last drain round returned
        nothing; `device_idle` means no batch is in flight and no READY
        slot is queued; `backpressured` means the out link has no
        credits (flushing could not publish anyway, so the starved
        early-out defers — the DEADLINE still fires, because the staged
        txns' latency budget keeps burning while downstream recovers).
        Returns None (keep filling) or one of FLUSH_*.
        """
        if lanes <= 0:
            return None
        if lanes >= batch:
            return FLUSH_FULL
        age = now_ns - first_ns
        if age >= self.deadline_ns:
            return FLUSH_DEADLINE
        if (
            starved
            and device_idle
            and not backpressured
            and age >= self.starve_ns
        ):
            return FLUSH_STARVED
        return None
