"""fd_feed — host-side ingest runtime for the verify pipeline.

The round-5 replay artifact pushed 674 txn/s through a verify engine
that sustains ~117k verifies/s standalone: the device idled ~99% because
txn parse, dedup, pack, and device dispatch all stepped inside one
GIL-serialized process. fd_feed is the input pipeline every
training/inference stack bolts onto an accelerator (and the role
wiredancer's async DMA-slot model plays for the FPGA): keep the
accelerator's staging queues full, off the dispatch thread.

Three pieces:

  slots.py    SlotPool — preallocated staging arenas (one numpy arena
              per in-flight slot, the exact fd_verify_drain layout) with
              a FREE -> FILLING -> READY -> dispatched lifecycle, so
              batch assembly happens while the previous batch is on the
              device. No per-frag allocation.
  policy.py   AdaptiveFlush — the deadline-based partial-batch flush
              policy that replaces VerifyTile's fixed max-wait timer
              (flush_timeout ~= 0 at steady state; a partial batch is
              never starved past the deadline).
  runtime.py  run_feed_pipeline — the pipeline runner that keeps source
              + verify (stager thread + dispatcher) in-process and moves
              dedup/pack/sink into a worker process (disco/worker.py
              tiles over the same tango shm rings, credit-backpressured
              by the existing fctl), then folds feeder stats and
              per-stage latency into the PipelineResult.

The legacy step loop stays selectable with FD_FEED=0 for bisection.
"""

from .policy import AdaptiveFlush
from .slots import Slot, SlotPool

__all__ = ["AdaptiveFlush", "Slot", "SlotPool"]
