"""Staging-slot arenas for the fd_feed ingest runtime.

A Slot is one preallocated host arena in the exact layout
native/verify_drain.cc stages and ops.verify.verify_batch /
ballet.ed25519.native.verify_arrays consume: row-major msgs/lens/sigs/
pubs plus the packed payload sidecar (offs/lens/sigs/lanes/tsorig/tspub)
the completion path publishes from. Nothing is allocated per frag — the
stager writes into the slot via one C call per drain round.

The SlotPool is the handoff between the stager thread (fills slots) and
the dispatch thread (ships READY slots to the device): a bounded ring of
slots in FREE -> FILLING -> READY -> (dispatched) -> FREE rotation, the
software analog of wiredancer's DMA slot table (wd_f1.c:327-408 — the
request queue the FPGA drains while the host stages the next request).
Backpressure is structural: when every slot is FILLING/READY the stager
blocks in acquire() (counted in slot_stall / stall_ns) until the
dispatcher releases one, which in turn only happens as device batches
retire — so host-side staging can never run unboundedly ahead of the
device.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

FREE = 0
FILLING = 1
READY = 2

_MTU = 1232  # FD_TPU_MTU (kept literal: tiles.py imports from here)


class Slot:
    """One staging arena + the per-txn bookkeeping the completion path
    needs. Arrays are preallocated once and reused for the pool's whole
    lifetime; reset() only rewinds the cursors (rows are overwritten and
    row tails zeroed by the native drain, so stale bytes cannot leak
    between incarnations of the slot)."""

    __slots__ = (
        "idx", "state", "msgs", "lens", "sigs", "pubs", "pay", "offs",
        "plens", "psigs", "tlanes", "tsorigs", "tspubs", "hashes",
        "ha_mask", "n_txn", "n_lane", "pay_fill", "t_first", "drain_end",
        "flush_verdict", "rung", "rung_depth",
    )

    def __init__(self, idx: int, batch: int, max_msg_len: int):
        self.idx = idx
        self.state = FREE
        self.msgs = np.zeros((batch, max_msg_len), np.uint8)
        self.lens = np.zeros(batch, np.uint32)
        self.sigs = np.zeros((batch, 64), np.uint8)
        self.pubs = np.zeros((batch, 32), np.uint8)
        self.pay = np.zeros(batch * _MTU, np.uint8)
        # Per-txn sidecars, accumulated ACROSS drain rounds at txn index
        # (offs are converted to absolute pay offsets as rounds land):
        # the completion path publishes straight out of these arrays via
        # fd_frag_publish_bulk — no per-txn Python objects anywhere.
        self.offs = np.zeros(batch, np.uint32)
        self.plens = np.zeros(batch, np.uint32)
        self.psigs = np.zeros(batch, np.uint64)
        self.tlanes = np.zeros(batch, np.uint32)
        self.tsorigs = np.zeros(batch, np.uint32)
        self.tspubs = np.zeros(batch, np.uint32)
        self.hashes = np.zeros(batch, np.uint64)   # FNV HA tags (drain)
        # True = HA-duplicate at staging time: lanes verify (they are
        # already staged) but the result must not publish.
        self.ha_mask = np.zeros(batch, np.bool_)
        self.n_txn = 0
        self.n_lane = 0
        self.pay_fill = 0
        self.t_first = 0       # deadline anchor (tickcount ns)
        self.drain_end = 0     # in-ring seq after the last drain round
                               # (the batch's ack target once verified)
        # Why this slot shipped ("full" / "capacity" / "deadline" /
        # "starved" / "ring_starved" / "halt") — stamped at commit so
        # fd_xray's exemplar batch context can attribute the flush
        # decision per dispatched batch.
        self.flush_verdict = "full"
        # fd_engine rung context: the scheduler's target B for this
        # batch and the queue depth it decided from (0/0 = scheduler
        # off) — stamped by the stager, read by the dispatcher's
        # exemplar capture.
        self.rung = 0
        self.rung_depth = 0

    def reset(self) -> None:
        self.ha_mask[: max(self.n_txn, 1)] = False
        self.n_txn = 0
        self.n_lane = 0
        self.pay_fill = 0
        self.t_first = 0
        self.drain_end = 0
        self.flush_verdict = "full"
        self.rung = 0
        self.rung_depth = 0


class SlotPool:
    """Bounded FREE/FILLING/READY rotation between one stager thread and
    one dispatcher thread. READY order is commit order (FIFO), so device
    batches retire in the order their txns were drained — the property
    VerifyTile's ack cursor relies on."""

    def __init__(self, n_slots: int, batch: int, max_msg_len: int):
        if n_slots < 2:
            # 1 slot cannot overlap fill with dispatch — the whole point
            # of the pool; a typo'd FD_FEED_SLOTS=1 must not silently
            # serialize the feeder.
            raise ValueError(f"SlotPool needs >= 2 slots, got {n_slots}")
        self.batch = batch
        self.slots: List[Slot] = [
            Slot(i, batch, max_msg_len) for i in range(n_slots)
        ]
        self._free: List[Slot] = list(self.slots)
        self._ready: List[Slot] = []
        self._lock = threading.Lock()
        self._free_cv = threading.Condition(self._lock)
        # Feeder stats (read by VerifyTile into verify_stats/cnc diag).
        # Batch/lane/fill accounting lives on the TILE (stat_batches /
        # stat_lanes, counted at dispatch) — one authority, not two.
        self.slot_stall = 0          # acquires that had to wait
        self.stall_ns = 0            # total time the stager spent waiting

    # -- stager side -----------------------------------------------------

    def acquire(self, timeout_s: float) -> Optional[Slot]:
        """FREE -> FILLING. Blocks up to timeout_s when no slot is free
        (counted once per wait in slot_stall, wall time in stall_ns) so
        the stager stays interruptible for HALT."""
        import time

        with self._free_cv:
            if not self._free:
                self.slot_stall += 1
                t0 = time.perf_counter_ns()
                self._free_cv.wait(timeout_s)
                self.stall_ns += time.perf_counter_ns() - t0
            if not self._free:
                return None
            slot = self._free.pop(0)
            slot.state = FILLING
            return slot

    def commit(self, slot: Slot) -> None:
        """FILLING -> READY (FIFO): hand a filled slot to the dispatcher."""
        with self._lock:
            if slot.state != FILLING:
                raise ValueError(
                    f"commit of slot {slot.idx} in state {slot.state} "
                    "(want FILLING) — slot lifecycle violated"
                )
            slot.state = READY
            self._ready.append(slot)

    # -- dispatcher side -------------------------------------------------

    def pop_ready(self) -> Optional[Slot]:
        with self._lock:
            if not self._ready:
                return None
            return self._ready.pop(0)

    def release(self, slot: Slot) -> None:
        """Dispatched slot back to FREE (arenas reusable)."""
        slot.reset()
        with self._free_cv:
            slot.state = FREE
            self._free.append(slot)
            self._free_cv.notify()

    # -- shared observers ------------------------------------------------

    def ready_cnt(self) -> int:
        with self._lock:
            return len(self._ready)

    def outstanding(self) -> int:
        """Slots not currently FREE (FILLING + READY + dispatched). At
        post-halt quiescence this must be 0 — the chaos smoke's "no
        slot is permanently lost from the pool" gate: every fault path
        (quarantine, failover, stager restart) must return its slot."""
        with self._lock:
            return len(self.slots) - len(self._free)

    def idle(self) -> bool:
        """True when no slot holds staged-but-undispatched txns (no
        READY backlog, and the stager's FILLING slot — if any — is
        empty). A popped-but-undispatched slot keeps its n_txn until the
        dispatcher has recorded the batch in flight, so there is no
        window where staged work is invisible to both this check and
        the tile's _inflight list. Quiescence checks read this from
        another thread."""
        with self._lock:
            if self._ready:
                return False
            return all(s.n_txn == 0 for s in self.slots)

