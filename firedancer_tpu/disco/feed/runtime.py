"""run_feed_pipeline — the fd_feed pipeline runner.

Topology is the same ring graph build_topology creates; what moves is
WHERE the stages run:

    main process   replay source (thread) + VerifyTile in feed mode
                   (stager thread + dispatcher thread)
    worker process dedup + pack + sink (disco/worker.py --tile
                   dedup,pack,sink — three tiles on threads over the
                   same shm rings, credit-backpressured by fctl)

The legacy runner interleaves every per-frag Python stage on one GIL
with ~5 ms thread-switch quanta; here the main process spends its GIL on
source publish + completion publish while the stager's ring drain and
the CPU verifier's batch call run GIL-released, and ALL downstream
per-frag Python runs on the other core. FD_FEED_PROC=0 keeps the
downstream tiles on in-process threads (parity/debug).

Quiescence is supervisor-style (the downstream tiles are another
process, so only shared memory is visible): source exhausted + feeder
fully drained (stager cursor caught up, no staged slots, nothing in
flight) + every downstream consumer cursor caught up to its producer
and stable across a settle window (covers PackTile's internal pending
set, which rings cannot see).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from firedancer_tpu import flags
from firedancer_tpu.tango.rings import (
    CNC_HALT,
    Cnc,
    FSeq,
    MCache,
    Workspace,
)


def latency_percentiles(samples) -> Dict[str, int]:
    """{n, p50_ns, p99_ns} of a latency sample list (0s when empty)."""
    if not samples:
        return {"n": 0, "p50_ns": 0, "p99_ns": 0}
    s = sorted(samples)
    return {
        "n": len(s),
        "p50_ns": int(s[len(s) // 2]),
        "p99_ns": int(s[(len(s) * 99) // 100]),
    }


def verify_tile_stats(v) -> Dict[str, object]:
    """The verify_stats record for one VerifyTile: a VIEW assembled
    from the tile's fd_flight registry lane (disco/flight.py — the one
    authority every dispatch/healing counter is written through), plus
    the tile-object-only extras (mode, pool wall times, chaos audit).
    Legacy tiles report the same schema with zeroed feeder gauges, so
    artifact consumers see ONE shape; the supervisor's cross-process
    variant (flight.verify_stats_view) reads the same lane through
    shared memory."""
    from firedancer_tpu.disco import chaos

    m = v.fl.as_dict()
    lanes = m["lanes"]
    batches = m["batches"]
    fill = lanes / float(batches * v.batch) if batches else 0.0
    breaker = getattr(v, "_breaker", None)
    st = {
        "batches": batches,
        "lanes": lanes,
        "fill_ratio": round(fill, 4),
        "flush_timeout": m["flush_timeout"],
        "flush_starved": m["flush_starved"],
        "inflight_stall": m["inflight_stall"],
        "mode": v.verify_mode,
        "rlc_fallback": m["rlc_fallback"],
        "feed": bool(getattr(v, "_feed", False)),
        "slot_stall": 0,
        "slot_stall_ms": 0.0,
        "device_idle_est_ms": round(m["feed_idle_ns"] / 1e6, 2),
        # fd_chaos healing accounting (all zero on a fault-free run):
        "stager_restarts": m["stager_restarts"],
        "cpu_failover": m["cpu_failover"],
        "quarantined": m["quarantined"],
        "quarantine_err_txn": m["quarantine_err_txn"],
        "ctl_err_drop": m["ctl_err_drop"],
        "breaker_state": (breaker.state if breaker is not None
                          else "disabled"),
        "breaker_trips": breaker.trips if breaker is not None else 0,
        "breaker_reprobes": breaker.reprobes if breaker is not None else 0,
        "slots_leaked": 0,
        # Per-engine compile accounting (fd_flight): the prewarm's
        # wall time + cache-hit estimate for this tile's engine.
        "compile_cnt": m["compile_cnt"],
        "compile_ms": round(m["compile_ns"] / 1e6, 1),
        "compile_cache_hit": m["compile_cache_hit"],
        # fd_engine rung scheduler (disco/engine.py): the per-rung
        # dispatch histogram (JSON-keyed by str(B)), the ladder in
        # force, and the switch count — {} / [] / 0 with the scheduler
        # off, so artifact consumers see ONE shape either way.
        "rung_hist": {str(k): v for k, v in
                      sorted(getattr(v, "stat_rung_hist", {}).items())},
        "rung_ladder": (list(v.rung_sched.rungs)
                        if getattr(v, "rung_sched", None) is not None
                        else []),
        "rung_switches": m["rung_switches"],
        "rung_cur": m["rung_cur"],
        # fd_pod per-shard occupancy (round-18): the mesh shard lanes'
        # dispatched-lane counts + the busiest/laziest balance ratio —
        # [] / 0.0 off-mesh so artifact consumers see ONE shape. The
        # same verify.shardN flight rows feed the sentinel's
        # shard_balance SLO; this is the artifact-facing mirror.
        "shard_lanes": [sh.get("lanes") for sh in
                        (s.as_dict() for s in v.fl_shards)],
        "shard_balance": 0.0,
        # fd_drain (round-20): the fused dedup pre-filter's claim split
        # over published clean txns + window rotations — all zero with
        # FD_DRAIN=off so artifact consumers see ONE shape either way.
        "drain_batches": m["drain_batches"],
        "drain_novel": m["drain_novel"],
        "drain_maybe": m["drain_maybe"],
        "drain_rot": m["drain_rot"],
        # fd_soak live reconfig (applied swaps vs refused requests) —
        # both zero on a run with no control channel, one shape always.
        "reconfigs": m["reconfigs"],
        "reconfig_refused": m["reconfig_refused"],
    }
    if st["shard_lanes"]:
        # lo==0 (a starved shard) degrades to max/1 — a huge but
        # FINITE ratio, so the artifact stays strict-JSON.
        lo = max(1, min(st["shard_lanes"]))
        st["shard_balance"] = round(max(st["shard_lanes"]) / lo, 3)
    if getattr(v, "_feed", False):
        st["slot_stall"] = v.feed_pool.slot_stall
        st["slot_stall_ms"] = round(v.feed_pool.stall_ns / 1e6, 2)
        st["slots_leaked"] = v.feed_pool.outstanding()
    c = chaos.active()
    if c is not None:
        st["chaos"] = c.snapshot()
    return st


def _spawn_worker(tile: str, wksp_path: str, pod_path: str, opts: dict,
                  max_ns: int, result_path: str, log_dir: str):
    cmd = [
        sys.executable, "-m", "firedancer_tpu.disco.worker",
        "--wksp", wksp_path, "--pod", pod_path, "--tile", tile,
        "--opts", json.dumps(opts), "--max-ns", str(max_ns),
    ]
    if result_path:
        cmd += ["--result", result_path]
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    log = os.path.join(log_dir, f"{tile.split(',')[0]}.log")
    with open(log, "ab") as stderr:
        return subprocess.Popen(cmd, cwd=repo, stderr=stderr)


def run_feed_pipeline(
    topo,
    payloads: List[bytes],
    verify_backend: str = "cpu",
    verify_batch: int = 128,
    verify_max_msg_len: Optional[int] = None,
    bank_cnt: int = 4,
    timeout_s: float = 60.0,
    tcache_depth: int = 4096,
    verify_opts: Optional[dict] = None,
    record_digests: bool = False,
    pack_scheduler: str = "greedy",
    tile_cpus: Optional[List[int]] = None,
    source_tile=None,
    source_done=None,
    pre_wait=None,
    tile_hook=None,
):
    """Same contract as pipeline.run_pipeline (which routes here when
    FD_FEED is on and the topology qualifies); returns a PipelineResult
    with feed=True, feeder verify_stats, and per-stage latency.

    source_tile (with its source_done exhaustion predicate and an
    optional pre_wait hook that runs after threads start and returns a
    cleanup callable) swaps the payload-replay source for an already-
    constructed tile publishing on replay_verify — run_quic_pipeline
    passes its QuicTile here, making QUIC -> feed staging -> verify a
    first-class run_pipeline topology instead of a legacy-loop-only
    path. A custom source always runs in-process (it owns host state —
    the QUIC tile's socket — that cannot cross a worker boundary)."""
    from firedancer_tpu.disco import chaos

    # Fresh injector per run (no-op with FD_CHAOS off): direct callers
    # (smoke lanes) get the same determinism contract as run_pipeline.
    chaos.init_for_run()
    # Tiles import feed.policy at module load; import them lazily here
    # to keep the package import graph acyclic.
    from firedancer_tpu.disco.pipeline import (
        PipelineResult,
        _link_names,
        _make_out_link,
        _make_source_out_links,
    )
    from firedancer_tpu.disco.tiles import (
        FD_TPU_MTU,
        DedupTile,
        InLink,
        PackTile,
        ReplayTile,
        SinkTile,
        VerifyTile,
    )

    pod = topo.pod
    wksp = Workspace.join(topo.wksp_path)
    mtu = pod.query_ulong("firedancer.mtu", FD_TPU_MTU)
    from firedancer_tpu.disco import flight

    flight.install_dump_signal(wksp)  # SIGUSR1 -> live postmortem dump

    # Process layout (FD_FEED_PROC): with worker processes the MAIN
    # process is only the feeder — stager thread (C drain) + dispatcher
    # thread (device / native verify + completion publish) — while the
    # SOURCE and the downstream per-frag tiles each get their own
    # interpreter. That wins only when cores exist to put under them:
    # on a 2-core host the extra boots + oversubscription cost more
    # than the GIL they dodge (especially since the PyDLL ring-op
    # routing removed most cross-thread GIL handoffs), so 'auto' uses
    # processes only on >= 4 cores.
    proc_mode = flags.get_str("FD_FEED_PROC")
    if proc_mode == "auto":
        use_proc = (os.cpu_count() or 1) >= 4
    else:
        use_proc = proc_mode not in ("0", "false", "no")
    if pack_scheduler == "gc":
        # The GC scheduler batches txns in pack-internal state and its
        # first drain pays an XLA compile, during which every ring
        # cursor sits STABLE — cursor-settle quiescence would HALT the
        # run mid-compile and drop the block. In-process tiles let the
        # quiescence check read the pack's pending set directly (the
        # same contract the legacy runner uses).
        use_proc = False
    if chaos.active() is not None:
        # Armed chaos forces in-process placement: the injector and its
        # tri-counters are process-local, and the parity audit
        # (injected == detected == healed) only adds up when the
        # source-side injection sites (ring_ctl_err, credit_starve) and
        # the verify-side detection sites book into ONE injector.
        # Supervisor-level classes keep their own multi-process path
        # (run_supervised), asserted behaviorally per the RUNBOOK.
        use_proc = False
    replay = None
    source_proc = use_proc
    if source_tile is not None:
        # Custom source (the QUIC tile): always in-process — it owns a
        # socket/endpoint no worker process can adopt. Downstream
        # worker placement is unaffected.
        source_proc = False
    elif not use_proc:
        replay = ReplayTile(
            wksp, pod.query_cstr("firedancer.replay.cnc"),
            out_links=_make_source_out_links(wksp, pod),
            payloads=payloads,
        )
    vopts = dict(verify_opts or {})
    vopts["feed"] = True
    verify = VerifyTile(
        wksp, pod.query_cstr("firedancer.verify.cnc"),
        in_link=InLink(wksp, _link_names(pod, "replay_verify"), edge="replay_verify"),
        out_link=_make_out_link(wksp, pod, "verify_dedup", "verify_dedup",
                                mtu),
        backend=verify_backend, batch=verify_batch,
        max_msg_len=verify_max_msg_len or mtu,
        tcache_depth=tcache_depth,
        **vopts,
    )

    downstream_opts = {
        "tcache_depth": tcache_depth,
        "bank_cnt": bank_cnt,
        "pack_scheduler": pack_scheduler,
        "record_digests": record_digests,
        # Pin children to the host platform the parent runs under: this
        # image's sitecustomize force-registers the TPU plugin, and a
        # pack-gc worker importing jax must not claim the tunnel.
        "jax_platform": os.environ.get("JAX_PLATFORMS") or None,
    }
    in_tiles: List = []
    if not use_proc:
        dedup = DedupTile(
            wksp, pod.query_cstr("firedancer.dedup.cnc"),
            in_links=[InLink(wksp, _link_names(pod, "verify_dedup"), edge="verify_dedup")],
            out_link=_make_out_link(wksp, pod, "dedup_pack", "dedup_pack",
                                    mtu),
            tcache_depth=tcache_depth,
        )
        pack = PackTile(
            wksp, pod.query_cstr("firedancer.pack.cnc"),
            in_link=InLink(wksp, _link_names(pod, "dedup_pack"), edge="dedup_pack"),
            out_link=_make_out_link(wksp, pod, "pack_sink", "pack_sink",
                                    mtu),
            bank_cnt=bank_cnt, scheduler=pack_scheduler,
        )
        sink = SinkTile(
            wksp, pod.query_cstr("firedancer.sink.cnc"),
            in_link=InLink(wksp, _link_names(pod, "pack_sink"), edge="pack_sink"),
            record_digests=record_digests,
        )
        in_tiles = [dedup, pack, sink]

    src_inproc = source_tile if source_tile is not None else replay
    threads_tiles = [verify] if src_inproc is None else [src_inproc, verify]
    threads_tiles += in_tiles
    if tile_cpus:
        for i, t in enumerate(threads_tiles):
            t.cpu_idx = tile_cpus[i % len(tile_cpus)]
        if use_proc:
            downstream_opts["cpu_map"] = {
                name: tile_cpus[(2 + i) % len(tile_cpus)]
                for i, name in enumerate(("dedup", "pack", "sink"))
            }

    tile_max_ns = int((timeout_s + 30.0) * 1e9)
    threads = [
        threading.Thread(target=t.run, args=(tile_max_ns,), name=t.name,
                         daemon=True)
        for t in threads_tiles
    ]

    tmp = tempfile.mkdtemp(prefix="fd_feed_")
    result_path = os.path.join(tmp, "downstream.json")
    procs: Dict[str, object] = {}
    t0 = time.perf_counter()
    # fd_sentinel: the in-run SLO evaluator (stopped at quiescence,
    # before HALT — and unconditionally in the finally, so the poller
    # can never outlive the workspace mapping).
    from firedancer_tpu.disco import sentinel as sentinel_mod

    snt = None
    slo_summary = None
    try:
        if use_proc:
            import pickle

            pod_path = os.path.join(tmp, "topo.pod")
            with open(pod_path, "wb") as f:
                f.write(pod.serialize())
            procs["downstream"] = _spawn_worker(
                "dedup,pack,sink", topo.wksp_path, pod_path,
                downstream_opts, tile_max_ns, result_path, tmp)
            if source_proc:
                payloads_path = os.path.join(tmp, "payloads.pkl")
                with open(payloads_path, "wb") as f:
                    pickle.dump(list(payloads), f)
                procs["replay"] = _spawn_worker(
                    "replay", topo.wksp_path, pod_path,
                    dict(downstream_opts, payloads_path=payloads_path),
                    tile_max_ns, "", tmp)
        for th in threads:
            th.start()
        if tile_hook is not None:
            # fd_soak's window into the live run: the hook receives the
            # in-process VerifyTile (reconfig control channel, slot-
            # pool/ladder probes) right after the tile threads start.
            tile_hook(verify)
        post_wait = pre_wait() if pre_wait is not None else None
        snt = sentinel_mod.start_for_run(wksp, pod)

        links = [
            (MCache(wksp, pod.query_cstr(f"firedancer.{n}.mcache")),
             FSeq(wksp, pod.query_cstr(f"firedancer.{n}.fseq")))
            for n in ("verify_dedup", "dedup_pack", "pack_sink")
        ]
        worker_cncs = [
            Cnc(wksp, pod.query_cstr(f"firedancer.{n}.cnc"))
            for n in (("dedup", "pack", "sink")
                      + (("replay",) if source_proc else ()))
        ] if use_proc else []
        src_mcache = MCache(
            wksp, pod.query_cstr("firedancer.replay_verify.mcache"))
        n_payloads = len(payloads)

        def src_done() -> bool:
            if source_done is not None:
                return source_done()
            if replay is not None:
                return replay.done()
            # Source in a worker: only its out-ring cursor is visible.
            return src_mcache.seq_next() >= n_payloads

        def feeder_drained() -> bool:
            return (
                verify.in_link.seq >= src_mcache.seq_next()
                and verify.feed_pool.idle()
                and not verify._inflight
            )

        def downstream_idle() -> bool:
            # In-process downstream tiles expose their internal pending
            # work (PackTile holds scheduled-but-unpublished txns that
            # no ring cursor reflects); worker processes are covered by
            # the cursor-settle window alone (greedy pack only — see
            # the gc guard above).
            if not in_tiles:
                return True
            return (pack.pack.pending_cnt() == 0
                    and not pack._gc_pending)

        # Settle-window quiescence (supervisor-style): PackTile's
        # CU-deferred pending set is invisible through the rings, so
        # "drained" must also be STABLE across several passes.
        deadline = t0 + timeout_s
        settle, settle_needed = 0, 5
        last_cursors = None
        worker_died = None
        while time.perf_counter() < deadline:
            for name, proc in procs.items():
                rc = proc.poll()
                if rc is not None:
                    # Workers must outlive the run (they exit only
                    # after HALT): an early exit is fatal, not
                    # something to time out on.
                    worker_died = (name, rc)
                    break
            if worker_died:
                break
            if any(not th.is_alive() for th in threads):
                # A tile thread can only exit before HALT by raising
                # (stager death, verify dispatch error): stop waiting
                # for a quiescence that cannot come.
                worker_died = ("tile-thread", -1)
                break
            cursors = tuple(
                (mc.seq_next(), fs.query()) for mc, fs in links
            )
            drained = all(fs >= mc for mc, fs in cursors)
            if (src_done() and feeder_drained() and drained
                    and downstream_idle() and cursors == last_cursors):
                settle += 1
                if settle >= settle_needed:
                    break
            else:
                settle = 0
            last_cursors = cursors
            time.sleep(0.005)

        if snt is not None:
            slo_summary = snt.stop()   # before HALT: drain != stall
        # HALT — but a worker tile that has not reached its run loop yet
        # would overwrite HALT with RUN at startup and spin to max_ns.
        # Wait (bounded) until every worker cnc has left BOOT or its
        # process is gone.
        if procs and worker_died is None:
            boot_deadline = time.perf_counter() + 60.0
            while time.perf_counter() < boot_deadline:
                if any(p.poll() is not None for p in procs.values()):
                    break
                if all(c.signal_query() != 0 for c in worker_cncs):
                    break
                time.sleep(0.01)
        for t in threads_tiles:
            t.cnc.signal(CNC_HALT)
        for c in worker_cncs:
            c.signal(CNC_HALT)
        join_deadline = time.perf_counter() + timeout_s + 35.0
        for th in threads:
            th.join(timeout=max(0.1, join_deadline - time.perf_counter()))
        if post_wait is not None:
            post_wait()
        if worker_died is None:
            for proc in procs.values():
                try:
                    proc.wait(timeout=60.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        elapsed = time.perf_counter() - t0

        if worker_died is not None:
            name, rc = worker_died
            log_path = os.path.join(
                tmp, ("dedup" if name == "downstream" else name) + ".log")
            tail = ""
            if os.path.exists(log_path):
                with open(log_path, "rb") as f:
                    tail = f.read()[-2000:].decode("utf-8", "replace")
            raise RuntimeError(
                f"fd_feed {name} worker exited rc={rc} mid-run; "
                f"stderr tail:\n{tail}"
            )

        from firedancer_tpu.disco.monitor import snapshot

        diag = snapshot(wksp, pod)

        src_out = (src_inproc.out_link if src_inproc is not None else None)
        stage_latency = {
            "replay_pub": latency_percentiles(
                src_out.lat_ns if src_out is not None else []),
            # Ring dwell (source publish -> stager drain): the feeder's
            # input-backlog distribution, from the drain's tspub export.
            "verify_drain": latency_percentiles(verify.stat_ring_dwell_ns),
            "verify_pub": latency_percentiles(verify.out_link.lat_ns),
        }
        down = {}
        if use_proc:
            if os.path.exists(result_path):
                with open(result_path) as f:
                    down = json.load(f)
            sink_res = down.get("sink", {})
            stage_latency["dedup_pub"] = down.get("dedup", {}).get(
                "pub_lat", latency_percentiles([]))
            stage_latency["pack_pub"] = down.get("pack", {}).get(
                "pub_lat", latency_percentiles([]))
            recv_cnt = sink_res.get("recv_cnt", 0)
            recv_sz = sink_res.get("recv_sz", 0)
            bank_hist = {int(k): v for k, v in
                         (sink_res.get("bank_hist") or {}).items()}
            lat_p50 = sink_res.get("latency_p50_ns", 0)
            lat_p99 = sink_res.get("latency_p99_ns", 0)
            digests = ([bytes.fromhex(d) for d in sink_res["digests"]]
                       if sink_res.get("digests") is not None else None)
            stage_latency["sink"] = sink_res.get(
                "e2e_lat", latency_percentiles([]))
        else:
            stage_latency["dedup_pub"] = latency_percentiles(
                dedup.out_link.lat_ns)
            stage_latency["pack_pub"] = latency_percentiles(
                pack.out_link.lat_ns)
            recv_cnt = sink.recv_cnt
            recv_sz = sink.recv_sz
            bank_hist = dict(sink.bank_hist)
            lat = sorted(sink.latencies_ns)
            lat_p50 = lat[len(lat) // 2] if lat else 0
            lat_p99 = lat[(len(lat) * 99) // 100] if lat else 0
            digests = list(sink.digests) if record_digests else None
            stage_latency["sink"] = latency_percentiles(sink.latencies_ns)

        from firedancer_tpu.disco import xray
        from firedancer_tpu.disco.pipeline import finish_flight_run

        res = PipelineResult(
            recv_cnt=recv_cnt,
            recv_sz=recv_sz,
            bank_hist=bank_hist,
            diag=diag,
            elapsed_s=elapsed,
            latency_p50_ns=lat_p50,
            latency_p99_ns=lat_p99,
            sink_digests=digests,
            verify_stats=[verify_tile_stats(verify)],
            stage_latency=stage_latency,
            stage_hist=finish_flight_run(wksp, slo_summary),
            feed=True,
            slo=slo_summary,
        )
        # fd_xray: this process's exemplar rings + the worker pool's
        # (its result file carries a spans dump, so cross-process span
        # chains correlate at one place — by trace id, the same
        # deterministic hash everywhere).
        res.xray = xray.run_summary(
            wksp, extra_spans=(down.get("xray") or {}).get("spans"),
            alerts=(slo_summary or {}).get("alerts"))
        if all(not th.is_alive() for th in threads) and (
                snt is None or not snt.alive()):
            wksp.leave()  # else leak the mapping rather than segfault
        return res
    finally:
        if snt is not None:
            snt.stop()   # idempotent; error paths must stop the poller
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
