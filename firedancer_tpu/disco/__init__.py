"""disco — tiles (long-running actors over tango) + topology + monitor.

Role mirrors the reference's src/disco + src/app/frank: the tile run-loop
blueprint, the concrete hot-path tiles (replay/verify/dedup/pack/sink),
the topology builder (configure `frank` stage analog) and the monitor
dashboard. See tiles.py, pipeline.py, monitor.py.
"""
