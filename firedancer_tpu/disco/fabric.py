"""fd_fabric — multi-host, multi-tenant verify fabric (ROADMAP
direction 1, the pod taken across processes).

The reference scales past one host by running independent firedancer
instances; wiredancer scales past one FPGA by sharding the signature
stream over cards. The TPU-native composition: EVERY fabric process
runs its own complete ingest stack — tenant front door (token-bucket
admission, disco/feed/policy.TokenBucket), fd_feed SlotPool staging
lanes (one per local 'dp' device, disco/pod.ShardLane), its own flight
workspace — and all processes join ONE jax.distributed mesh of shape
(host, dp): 'host' is the DCN axis (one row per process), 'dp' the
on-host axis (ICI on real pods). The verify graphs are the PR-13 split
pair built over that mesh (parallel/mesh.verify_rlc_split_global):

    local_fill     per-shard SHA / decompress / status ladder /
                   Pippenger bucket fills — NO collectives, so a
                   host's batch bytes never leave the host
    combine_tail   ONE all_gather of the tiny per-shard window/trial
                   partials + doubling chains — the ONLY payload that
                   crosses DCN

matching tango's philosophy exactly: lossy broadcast stays host-local,
only partials cross the wire.

LOCKSTEP CONTRACT. shard_map collectives require every process to
dispatch the same graphs in the same order. Each fabric step all-
gathers a single "I still have work" int32 per host
(multihost_utils.process_allgather — the 4-byte control plane) and
every host dispatches one global batch per step, zero-padding when its
own lanes are empty; pad lanes resolve definite exactly like the feed
path's zeroed warm rows, so a short host never perturbs the verdict.

DETERMINISM / DIGEST PARITY. Tenant admission is a pure function of
the tenant's OWN virtual arrival clock (siege.TenantSpec.arrival_ns
drives the bucket, not wall time), and tenants are assigned WHOLE to
hosts by a deterministic greedy pack over simulated admitted counts
(assign_tenants). So the union of admitted transactions is identical
however many hosts the fabric runs — the merged verified-digest
multiset from an N-process run is bit-exact against the 1-process
control, the fabric smoke's headline gate.

JUDGMENT. Each process publishes its flight registry + tenant ledger
as a JSON dump; process 0 (or the parent runner) merges them with
flight.merge_snapshots and grades the merged edges/ledger with
sentinel.evaluate_edges_summary + evaluate_tenant_summary — one
cross-host judgment with exact counter/parity arithmetic
(admitted + shed == offered per tenant, always).

Host-side: numpy + flight/feed/pod helpers; jax is imported lazily in
FabricHost (the coordinator-side merge functions never touch jax).
"""

from __future__ import annotations

import json
import os
import time
from hashlib import sha256 as _sha256
from typing import Dict, List, Optional, Tuple

import numpy as np

from firedancer_tpu import flags
from firedancer_tpu.disco import flight
from firedancer_tpu.disco.feed.policy import TokenBucket
from firedancer_tpu.disco.pod import ShardLane

FABRIC_SCHEMA_VERSION = 2
DUMP_PREFIX = "fabric_proc"

# Virtual-clock scale: TenantSpec rates are txns/s, arrival clocks are
# ns — one bucket token per 1e9 virtual ns per rate_tps.
_NS_PER_S = 1e9


# --------------------------------------------------------------------------
# Tenant admission: per-tenant token buckets over virtual arrival time.
# --------------------------------------------------------------------------


class TenantAdmission:
    """The fabric front door for one host's OWNED tenants.

    One policy.TokenBucket per tenant, driven by the tenant's virtual
    arrival clock (TenantSpec.arrival_ns) with the rate pre-scaled to
    tokens/ns — admission is a pure function of the tenant's own
    stream, independent of host placement and wall time (the lockstep
    and digest-parity keystone). Shed work is ACCOUNTED, never silent:
    the ledger keeps admitted + shed == offered per tenant exactly
    (sentinel.evaluate_tenant_summary's parity gate), and shed payload
    digests land in shed_sha256, the same audit discipline as the QUIC
    front door's shed ledger."""

    def __init__(self, tenants, owned: Optional[List[str]] = None):
        specs = list(tenants)
        if owned is not None:
            keep = set(owned)
            specs = [t for t in specs if t.name in keep]
        self.specs = {t.name: t for t in specs}
        self.buckets: Dict[str, TokenBucket] = {
            t.name: TokenBucket(t.rate_tps / _NS_PER_S, t.burst)
            for t in specs
        }
        self.ledger: Dict[str, Dict[str, int]] = {
            t.name: {"offered": 0, "admitted": 0, "shed": 0}
            for t in specs
        }
        self.shed_sha256: List[bytes] = []

    def admit(self, tenant: str, arrival_ns: int,
              payload: Optional[bytes] = None) -> bool:
        """One offered txn at the tenant's virtual arrival instant."""
        led = self.ledger[tenant]
        led["offered"] += 1
        if self.buckets[tenant].admit(float(arrival_ns)):
            led["admitted"] += 1
            return True
        led["shed"] += 1
        if payload is not None:
            self.shed_sha256.append(_sha256(payload).digest())
        return False

    def fairness_view(self) -> Dict[str, dict]:
        """The sentinel tenant-source / artifact-ledger shape:
        {tenant: {offered, admitted, shed, honest}}."""
        return {
            name: dict(self.ledger[name],
                       honest=bool(self.specs[name].honest))
            for name in self.ledger
        }

    def parity_ok(self) -> bool:
        return all(v["admitted"] + v["shed"] == v["offered"]
                   for v in self.ledger.values())


def admitted_counts(plan) -> Dict[str, int]:
    """Simulated per-tenant admitted totals: every host runs this same
    pure bucket replay over every tenant's arrival clock, so the whole
    fabric agrees on placement weights without communication."""
    out: Dict[str, int] = {}
    for t in plan.tenants:
        b = TokenBucket(t.rate_tps / _NS_PER_S, t.burst)
        out[t.name] = sum(
            1 for ns in t.arrival_ns if b.admit(float(ns)))
    return out


def assign_tenants(plan, n_hosts: int) -> List[List[str]]:
    """Deterministic whole-tenant placement: largest simulated admitted
    load first onto the least-loaded host (ties break on host index,
    tenant order on (-load, name)). Whole tenants keep each bucket on
    exactly one host — the ledger needs no cross-host reconciliation
    and a tenant's admission decisions are single-writer by
    construction."""
    loads = admitted_counts(plan)
    order = sorted(loads, key=lambda n: (-loads[n], n))
    hosts: List[List[str]] = [[] for _ in range(n_hosts)]
    totals = [0] * n_hosts
    for name in order:
        h = min(range(n_hosts), key=lambda i: (totals[i], i))
        hosts[h].append(name)
        totals[h] += loads[name]
    return hosts


# --------------------------------------------------------------------------
# FabricHost: one process's ingest stack + its row of the global mesh.
# --------------------------------------------------------------------------


class _FabricInflight:
    __slots__ = ("status", "ok", "slots", "t_dispatch", "lanes")

    def __init__(self, status, ok, slots, t_dispatch: int, lanes: int):
        self.status = status
        self.ok = ok
        self.slots = slots
        self.t_dispatch = t_dispatch
        self.lanes = lanes


class FabricHost:
    """One fabric process: owned-tenant admission, per-dp ShardLane
    staging, one row of the (host, dp) mesh, a private flight
    workspace, and the lockstep dispatcher.

    Single-threaded by contract like PodVerifyService — one loop owns
    staging + dispatch; graph calls are async so FD_POD_INFLIGHT still
    sets the double-buffer depth. Works unchanged at n_hosts == 1 (the
    control run / graceful single-process fallback): the mesh is then
    (1, dp) and the control-plane all_gather short-circuits."""

    def __init__(self, plan, wksp_dir: str, per_shard: int,
                 max_msg_len: int = 256, label: str = "fabric.host",
                 torsion_k: Optional[int] = None,
                 inflight: Optional[int] = None, seed: int = 0):
        import jax

        from firedancer_tpu.disco import engine as fd_engine
        from firedancer_tpu.parallel import multihost
        from firedancer_tpu.tango.rings import Workspace

        self._jax = jax
        self.plan = plan
        self.proc_id = jax.process_index()
        self.n_hosts = jax.process_count()
        self.mesh = multihost.global_mesh()
        self._axes = tuple(self.mesh.axis_names)
        self.dp = int(self.mesh.devices.shape[1])
        self.per_shard = per_shard
        self.local_batch = self.dp * per_shard
        self.global_batch = self.n_hosts * self.local_batch
        self.max_msg_len = max_msg_len
        self.label = label
        self.seed = seed
        self._torsion_k = torsion_k or flags.get_int("FD_RLC_TORSION_K")
        self.inflight_max = max(1, inflight
                                or flags.get_int("FD_POD_INFLIGHT"))

        # Per-process flight workspace: fabric.py is the single writer
        # of every row here (fdlint ownership); other processes write
        # their OWN files, and the rows meet only in the coordinator's
        # merge_snapshots.
        from firedancer_tpu.disco.sentinel import SLO_NAMES

        os.makedirs(wksp_dir, exist_ok=True)
        self.wksp = Workspace.create(
            os.path.join(wksp_dir, f"fabric{self.proc_id}.wksp"),
            1 << 22)
        tile_labels = [label] + [f"{label}.shard{i}"
                                 for i in range(self.dp)]
        flight.create_regions(self.wksp, tile_labels, ["sink"],
                              slo_labels=SLO_NAMES)
        self.fl = flight.tile_lane(self.wksp, label)
        self.edge = flight.edge_hist(self.wksp, "sink")
        self.lanes = [
            ShardLane(i, per_shard, max_msg_len, wksp=self.wksp,
                      label=label, n_slots=self._slots_needed())
            for i in range(self.dp)
        ]
        self._rr = 0

        owned = assign_tenants(plan, self.n_hosts)[self.proc_id]
        self.admission = TenantAdmission(plan.tenants, owned=owned)

        # The split pair over the caller's (host, dp) mesh — registry
        # bypass, see engine.fabric_split_pair.
        self.fn_local, self.fn_tail, self.engine_key = \
            fd_engine.fabric_split_pair(self.mesh, self.global_batch)
        self.compile_s: Optional[float] = None
        self.cache_hit_est = False

        self._inflight: List[_FabricInflight] = []
        self._slot_payloads: Dict[Tuple[int, int], List[bytes]] = {}
        self._results: List[Tuple[int, bool]] = []
        self._digests: List[bytes] = []
        self._step = 0
        self.stat_lanes = 0
        self.stat_batches = 0
        self.stat_fallbacks = 0
        self.stat_parse_rejects = 0
        self.elapsed_s = 0.0

    def _slots_needed(self) -> int:
        """Enough FREE slots that a whole owned-tenant stream stages
        without blocking on retirement (the lockstep loop retires on
        its own cadence): every plan txn could land on one lane in the
        worst case, one lane per slot."""
        total = sum(len(t.txn_idx) for t in self.plan.tenants)
        return max(flags.get_int("FD_FEED_SLOTS"), total + 4)

    # -- global array plumbing -------------------------------------------

    def _global(self, local: np.ndarray):
        """This host's rows -> one global jax.Array sharded over
        (host, dp) on dim 0; batch bytes never cross DCN."""
        jax = self._jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        gshape = (local.shape[0] * self.n_hosts,) + local.shape[1:]
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(self._axes)), local, gshape)

    def _global_u3(self, u3: np.ndarray):
        """(K, 2, B_local) trial weights -> global (K, 2, B) sharded on
        the LANE axis. Per-host entropy is sound: each lane's z/u
        weight is only ever read on the device owning that lane, so
        hosts drawing independent randomness still compute the exact
        RLC equation."""
        jax = self._jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        gshape = u3.shape[:2] + (u3.shape[2] * self.n_hosts,)
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(None, None, self._axes)),
            u3, gshape)

    def _local_rows(self, arr) -> np.ndarray:
        """This host's row block of a lane-sharded global output."""
        out = np.zeros((self.local_batch,) + arr.shape[1:], arr.dtype)
        base = self.proc_id * self.local_batch
        for sh in arr.addressable_shards:
            lo = (sh.index[0].start or 0) - base
            data = np.asarray(sh.data)
            out[lo:lo + data.shape[0]] = data
        return out

    def _any_host(self, flag: bool) -> bool:
        """The 4-byte lockstep control plane: OR of per-host work flags
        (process_allgather over DCN; short-circuits single-process)."""
        if self.n_hosts == 1:
            return flag
        from jax.experimental import multihost_utils

        out = multihost_utils.process_allgather(
            np.asarray([1 if flag else 0], np.int32))
        return bool(np.asarray(out).any())

    def _barrier(self, name: str) -> None:
        if self.n_hosts > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"fd_fabric:{name}")

    def _kv_barrier(self, name: str,
                    timeout_ms: int = 1_800_000) -> None:
        """Rendezvous through the distributed-runtime KV store — NO
        collectives, so it works before the gloo clique exists. The
        compile-skew killer: two processes timesharing one core can
        finish the big local_step compile minutes apart, and gloo's
        context rendezvous inside the FIRST collective execution times
        out at 30 s — every process must compile first, meet here,
        and only then execute."""
        if self.n_hosts == 1:
            return
        from jax._src import distributed

        client = getattr(distributed.global_state, "client", None)
        if client is not None:
            client.wait_at_barrier(f"fd_fabric:{name}", timeout_ms)

    # -- warm -------------------------------------------------------------

    def warm(self) -> float:
        """AOT-compile both graphs, rendezvous, THEN warm on zero
        batches (the zero-lane batch resolves on the RLC pass alone,
        _warm_locked's trick); books the compile into the flight
        ledger under the fabric key.

        Compile and execute are deliberately split: compilation is
        process-local and its duration varies wildly across
        timeshared hosts, while the first EXECUTION initializes the
        gloo clique under a hard 30 s rendezvous — so every process
        compiles first, meets at the KV barrier, and only then
        executes. The replay loop keeps the AOT executables (same
        shapes/shardings every step), so no step ever recompiles."""
        jax = self._jax
        zeros = self._zero_batch()
        t0 = time.perf_counter()
        local_c = self.fn_local.lower(*zeros).compile()
        # the tail specializes on the parts output's exact shardings
        out_sds = jax.eval_shape(self.fn_local, *zeros)
        parts_sds = jax.tree_util.tree_map(
            lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype,
                                               sharding=sh),
            out_sds[2], local_c.output_shardings[2])
        tail_c = self.fn_tail.lower(parts_sds).compile()
        self.compile_s = time.perf_counter() - t0
        self.fn_local, self.fn_tail = local_c, tail_c
        self._kv_barrier("warm")
        out = self.fn_local(*zeros)
        ok = self.fn_tail(out[2])
        np.asarray(ok)
        rec = flight.record_compile(self.engine_key, self.compile_s)
        self.cache_hit_est = bool(rec["cache_hit_est"])
        return self.compile_s

    def _zero_batch(self):
        lb, mml = self.local_batch, self.max_msg_len
        rng = np.random.default_rng(0xFAB51C ^ self.seed)
        from firedancer_tpu.ops.verify_rlc import fresh_u, fresh_z

        u3 = fresh_u(self._torsion_k, 2 * lb, rng).reshape(
            self._torsion_k, 2, lb)
        return (
            self._global(np.zeros((lb, mml), np.uint8)),
            self._global(np.zeros(lb, np.int32)),
            self._global(np.zeros((lb, 64), np.uint8)),
            self._global(np.zeros((lb, 32), np.uint8)),
            self._global(fresh_z(lb, rng)),
            self._global_u3(u3),
        )

    # -- staging (pod placement over local dp lanes) ----------------------

    def _place(self, n_lanes: int) -> int:
        order = [(self._rr + i) % self.dp for i in range(self.dp)]
        fit = [i for i in order
               if self.lanes[i].room() >= n_lanes] or order
        best = min(fit, key=lambda i: self.lanes[i].backlog())
        self._rr = (best + 1) % self.dp
        return best

    def _stage_txn(self, payload: bytes) -> bool:
        from firedancer_tpu.ballet.txn import TxnParseError, parse_txn
        from firedancer_tpu.disco.tiles import meta_sig

        try:
            txn = parse_txn(payload)
            items = list(txn.verify_items(payload))
        except TxnParseError:
            return False
        if not items or any(len(m) > self.max_msg_len
                            for (_, _, m) in items):
            return False
        shard = self._place(len(items))
        lane = self.lanes[shard]
        lane.stage(items, meta_sig(payload),
                   digest=_sha256(payload).digest())
        # Payload kept per (lane, slot) for the per-txn CPU-oracle
        # fallback — the only correctness path when a salted batch
        # fails the global RLC equation.
        self._slot_payloads.setdefault(
            (shard, lane.cur.idx), []).append(payload)
        if lane.room() == 0:
            lane.commit("full")
        return True

    # -- lockstep dispatch ------------------------------------------------

    def _assemble_local(self):
        """One READY slot per local lane -> this host's row block
        (zero-pad missing lanes; all-None still produces the all-pad
        block a lockstep collective step requires)."""
        slots = [lane.pop_ready() for lane in self.lanes]
        per, mml = self.per_shard, self.max_msg_len
        msgs = np.zeros((self.local_batch, mml), np.uint8)
        lens = np.zeros(self.local_batch, np.int32)
        sigs = np.zeros((self.local_batch, 64), np.uint8)
        pubs = np.zeros((self.local_batch, 32), np.uint8)
        n_lanes = 0
        for i, s in enumerate(slots):
            if s is None:
                continue
            lo = i * per
            n = s.n_lane
            msgs[lo:lo + n] = s.msgs[:n]
            lens[lo:lo + n] = s.lens[:n]
            sigs[lo:lo + n] = s.sigs[:n]
            pubs[lo:lo + n] = s.pubs[:n]
            n_lanes += n
            self.lanes[i].fl.inc("batches")
            self.lanes[i].fl.inc("lanes", n)
        return slots, (msgs, lens, sigs, pubs), n_lanes

    def _dispatch_step(self) -> None:
        from firedancer_tpu.ops.verify_rlc import fresh_u, fresh_z

        slots, (msgs, lens, sigs, pubs), n_lanes = \
            self._assemble_local()
        while len(self._inflight) >= self.inflight_max:
            self._retire(self._inflight.pop(0))
        rng = np.random.default_rng(
            (0xFAB51C, self.seed, self.proc_id, self._step))
        lb = self.local_batch
        u3 = fresh_u(self._torsion_k, 2 * lb, rng).reshape(
            self._torsion_k, 2, lb)
        args = (self._global(msgs), self._global(lens),
                self._global(sigs), self._global(pubs),
                self._global(fresh_z(lb, rng)), self._global_u3(u3))
        t0 = time.monotonic_ns()
        status, definite, parts = self.fn_local(*args)
        ok = self.fn_tail(parts)
        self._inflight.append(
            _FabricInflight(status, ok, slots, t0, n_lanes))
        self._step += 1
        self.stat_batches += 1
        self.stat_lanes += n_lanes
        self.fl.inc("batches")
        if n_lanes:
            self.fl.inc("lanes", n_lanes)

    def _oracle_payload_ok(self, payload: bytes) -> bool:
        from firedancer_tpu.ballet.txn import TxnParseError, parse_txn

        try:
            items = list(parse_txn(payload).verify_items(payload))
        except TxnParseError:
            return False
        from firedancer_tpu.ballet.ed25519 import native as ed_native

        if ed_native.available():
            try:
                return all(st == 0
                           for st in ed_native.verify_items(items))
            except Exception:
                pass
        from firedancer_tpu.ballet.ed25519 import oracle as ed_oracle

        return all(ed_oracle.verify(msg, sig, pub) == 0
                   for (sig, pub, msg) in items)

    def _retire(self, ib: _FabricInflight) -> None:
        """Block on one batch's replicated verdict; fold per-txn
        results from this host's row block; per-txn CPU-oracle fallback
        when the global batch equation fails."""
        ok = bool(np.asarray(ib.ok))
        statuses = self._local_rows(ib.status) if ok else None
        if not ok:
            self.stat_fallbacks += 1
            self.fl.inc("rlc_fallback")
        now = time.monotonic_ns()
        per = self.per_shard
        for i, s in enumerate(ib.slots):
            if s is None:
                continue
            meta = self.lanes[i].pop_meta(s)
            payloads = self._slot_payloads.pop((i, s.idx), [])
            off = i * per
            for t in range(s.n_txn):
                cnt = int(s.tlanes[t])
                if ok:
                    lane_ok = cnt > 0 and bool(
                        (statuses[off:off + cnt] == 0).all())
                else:
                    lane_ok = (t < len(payloads)
                               and self._oracle_payload_ok(payloads[t]))
                psig, digest = (meta[t] if t < len(meta)
                                else (int(s.psigs[t]), None))
                self._results.append((psig, lane_ok))
                if lane_ok and digest is not None:
                    self._digests.append(digest)
                self.edge.observe(max(1, now - ib.t_dispatch))
                off += cnt
            self.lanes[i].release(s)

    # -- the replay driver -----------------------------------------------

    def replay(self, payloads: List[bytes]) -> dict:
        """Admit + stage this host's owned tenant streams, then run the
        lockstep step loop until EVERY host drains. Returns this
        host's result row (the per-process dump body)."""
        # Merged owned-tenant arrival order (virtual clock): realistic
        # interleave, still a pure function of the plan.
        events = sorted(
            (t.arrival_ns[j], t.name, t.txn_idx[j])
            for t in self.plan.tenants
            if t.name in self.admission.specs
            for j in range(len(t.txn_idx))
        )
        self._barrier("replay_start")
        t0 = time.perf_counter()
        for arrival_ns, tenant, idx in events:
            p = payloads[idx]
            if not self.admission.admit(tenant, arrival_ns, payload=p):
                self.fl.inc("admit_shed")
                continue
            if not self._stage_txn(p):
                self.stat_parse_rejects += 1
        for lane in self.lanes:
            if lane.cur is not None and lane.cur.n_txn:
                lane.commit("deadline")
        while True:
            my_more = any(lane.pool.ready_cnt() for lane in self.lanes)
            if not self._any_host(my_more):
                break
            self._dispatch_step()
        while self._inflight:
            self._retire(self._inflight.pop(0))
        self._barrier("replay_end")
        self.elapsed_s = time.perf_counter() - t0
        ok_cnt = sum(1 for _, okk in self._results if okk)
        return {
            "verified_ok": ok_cnt,
            "verified_fail": len(self._results) - ok_cnt,
            "parse_rejects": self.stat_parse_rejects,
            "steps": self._step,
            "lanes": self.stat_lanes,
            "batches": self.stat_batches,
            "rlc_fallbacks": self.stat_fallbacks,
            "elapsed_s": self.elapsed_s,
        }

    def shard_occupancy(self) -> List[int]:
        return [lane.fl.get("lanes") for lane in self.lanes]

    # -- the per-process dump --------------------------------------------

    def publish(self) -> None:
        self.fl.publish()
        for lane in self.lanes:
            lane.fl.publish()

    def write_dump(self, out_dir: str, result: dict) -> str:
        """Publish flight rows and write this process's judgment dump
        (atomic rename — the coordinator polls for completed files)."""
        from firedancer_tpu.parallel import multihost

        self.publish()
        snap = flight.snapshot_raw(self.wksp)
        active, reason = multihost.fabric_state()
        doc = {
            "schema_version": FABRIC_SCHEMA_VERSION,
            "proc_id": self.proc_id,
            "n_hosts": self.n_hosts,
            "dp": self.dp,
            "per_shard": self.per_shard,
            "global_batch": self.global_batch,
            "engine": self.engine_key,
            "compile_s": self.compile_s,
            "compile_cache_hit_est": self.cache_hit_est,
            "fabric_active": active,
            "fabric_fallback_reason": reason,
            "tenants": self.admission.fairness_view(),
            "digests": sorted(d.hex() for d in self._digests),
            "shed_sha256": sorted(
                d.hex() for d in self.admission.shed_sha256),
            "shard_lanes": [int(x) for x in self.shard_occupancy()],
            "snapshot": {
                "metrics": snap["metrics"],
                "edges": {k: np.asarray(v, np.uint64).tolist()
                          for k, v in snap["edges"].items()},
            },
            **result,
        }
        path = dump_path(out_dir, self.proc_id)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return path


# --------------------------------------------------------------------------
# Coordinator: collect per-process dumps, merge, judge.
# --------------------------------------------------------------------------


def dump_path(out_dir: str, proc_id: int) -> str:
    return os.path.join(out_dir, f"{DUMP_PREFIX}{proc_id}.json")


def collect_dumps(out_dir: str, n_procs: int,
                  timeout_s: float = 600.0,
                  poll_s: float = 0.5) -> List[dict]:
    """Poll for all n_procs dumps (atomic-rename complete files);
    raises TimeoutError naming the missing processes."""
    deadline = time.monotonic() + timeout_s
    paths = [dump_path(out_dir, i) for i in range(n_procs)]
    while True:
        missing = [p for p in paths if not os.path.exists(p)]
        if not missing:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"fd_fabric coordinator: {len(missing)} process "
                f"dump(s) never arrived: {missing}")
        time.sleep(poll_s)
    out = []
    for p in paths:
        with open(p, encoding="utf-8") as f:
            out.append(json.load(f))
    return out


def merge_tenant_ledgers(dumps) -> Dict[str, dict]:
    """Union of per-host tenant ledgers (each tenant is owned by ONE
    host; summing is defensive, parity still must hold exactly)."""
    merged: Dict[str, dict] = {}
    for d in dumps:
        for name, row in (d.get("tenants") or {}).items():
            m = merged.setdefault(
                name, {"offered": 0, "admitted": 0, "shed": 0,
                       "honest": bool(row.get("honest", True))})
            for k in ("offered", "admitted", "shed"):
                m[k] += int(row.get(k, 0))
    return merged


def merge_and_judge(dumps: List[dict],
                    control: Optional[dict] = None,
                    budgets_ms: Optional[Dict[str, float]] = None
                    ) -> dict:
    """The cross-host judgment: flight.merge_snapshots over every
    process registry, sentinel grading over the MERGED edges + tenant
    ledger, per-host balance, digest multiset vs the single-process
    control. Returns the FABRIC_r* artifact core (the runner stamps
    ts/ok/gate_basis). jax-free — the parent runner judges without
    joining the mesh."""
    from firedancer_tpu.disco import sentinel

    dumps = sorted(dumps, key=lambda d: d.get("proc_id", 0))
    snaps = [
        {"metrics": d["snapshot"].get("metrics") or {},
         "edges": {k: np.asarray(v, np.uint64)
                   for k, v in (d["snapshot"].get("edges")
                                or {}).items()}}
        for d in dumps
    ]
    merged = flight.merge_snapshots(snaps)
    tenants = merge_tenant_ledgers(dumps)
    alerts = list(sentinel.evaluate_edges_summary(
        merged["edges"], budgets_ms=budgets_ms))
    alerts += sentinel.evaluate_tenant_summary(tenants)

    per_host = []
    for d in dumps:
        el = float(d.get("elapsed_s") or 0.0)
        ok_cnt = int(d.get("verified_ok") or 0)
        per_host.append({
            "proc_id": int(d.get("proc_id", 0)),
            "verified_ok": ok_cnt,
            "lanes": int(d.get("lanes") or 0),
            "steps": int(d.get("steps") or 0),
            "elapsed_s": round(el, 3),
            "throughput": round(ok_cnt / el, 3) if el else 0.0,
            "rlc_fallbacks": int(d.get("rlc_fallbacks") or 0),
            "shard_lanes": d.get("shard_lanes") or [],
            "fabric_fallback_reason": d.get("fabric_fallback_reason"),
        })
    total_ok = sum(h["verified_ok"] for h in per_host)
    wall = max((h["elapsed_s"] for h in per_host), default=0.0)
    host_lanes = [h["lanes"] for h in per_host]
    lo = min(host_lanes) if host_lanes else 0
    balance = (float(max(host_lanes)) / lo) if lo else float("inf")

    digests = sorted(x for d in dumps for x in (d.get("digests") or []))
    rec = {
        "metric": "fabric_aggregate_throughput",
        "schema_version": FABRIC_SCHEMA_VERSION,
        "unit": "verifies/s",
        "hosts": len(dumps),
        "devices": sum(int(d.get("dp") or 0) for d in dumps),
        "value": round(total_ok / wall, 3) if wall else 0.0,
        "wall_s": round(wall, 3),
        "verified_ok": total_ok,
        "per_host": per_host,
        "balance_ratio": (round(balance, 3)
                          if balance != float("inf") else None),
        "tenants": tenants,
        "tenant_parity": all(
            v["admitted"] + v["shed"] == v["offered"]
            for v in tenants.values()),
        "alert_cnt": len(alerts),
        "alerts": alerts,
        "digests": len(digests),
        "merged": {"metrics": merged["metrics"],
                   "edges": merged["edges"]},
    }
    if control is not None:
        c_digests = sorted(control.get("digests") or [])
        c_el = float(control.get("elapsed_s") or 0.0)
        c_ok = int(control.get("verified_ok") or 0)
        c_val = round(c_ok / c_el, 3) if c_el else 0.0
        rec["control"] = {
            "hosts": 1,
            "verified_ok": c_ok,
            "elapsed_s": round(c_el, 3),
            "value": c_val,
        }
        rec["digest_parity"] = bool(digests) and digests == c_digests
        rec["scaling_ratio"] = (round(rec["value"] / c_val, 3)
                                if c_val else 0.0)
    return rec
