"""Mainnet-shaped transaction corpus generation for the replay gate.

The reference keeps real transaction fixtures (src/ballet/txn/fixtures/)
and a pcap replay harness (src/disco/replay/fd_replay.h:4-6) for
deterministic end-to-end runs; this environment has no mainnet pcaps, so
the corpus is synthesized to the same shape instead:

  * signer-count mix (mostly 1, tail of 2-4 — multisig),
  * legacy and v0 (address-lookup-table) message formats,
  * a fraction carrying ComputeBudgetProgram instructions with varied
    priority fees (what fd_pack orders by),
  * variable instruction-data sizes (so message lengths vary up to MTU),
  * exact duplicates (the dedup tile's job),
  * corrupted signatures / messages (the verify tile's job),
  * truncated garbage (the parse path's job).

Every valid signature comes from ops.sign.sign_batch — proven bit-exact
against the RFC 8032 CPU oracle — so each payload's expected verify
status is known BY CONSTRUCTION and the 100k gate doesn't need 100k
half-second Python-oracle verifies. tests/test_replay_gate.py still
spot-checks a random subsample against the live oracle to anchor the
chain of trust.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from firedancer_tpu.ballet.txn import build_txn

OK = 0          # expected to verify and reach the sink (unless a dup)
DUP = 1         # exact duplicate of an earlier payload: dedup drops it
BAD_SIG = 2     # corrupted signature bytes: verify drops it
BAD_PARSE = 3   # malformed wire bytes: parse drops it


@dataclass
class Corpus:
    payloads: list            # wire bytes, shuffled
    expected: np.ndarray      # per-payload class above (int8)
    n_unique_ok: int          # distinct valid txns (sink should see these)


def _deferred_signer(jobs: list):
    """build_txn sign_fn that records (msg, seed) and leaves a hole."""

    def sign_fn(msg: bytes, seed: bytes) -> bytes:
        jobs.append((msg, seed))
        return b"\x00" * 64

    return sign_fn


def _splice_signatures(payload: bytes, sigs: list) -> bytes:
    """Replace the zero-hole signatures in a built txn."""
    n = payload[0]
    assert n < 0x80 and n == len(sigs)  # 1-byte compact-u16 for sig counts
    out = bytearray(payload)
    for i, sig in enumerate(sigs):
        out[1 + 64 * i : 1 + 64 * (i + 1)] = sig
    return bytes(out)


def mainnet_corpus(
    n: int,
    seed: int = 0,
    dup_rate: float = 0.05,
    corrupt_rate: float = 0.03,
    parse_err_rate: float = 0.01,
    v0_rate: float = 0.3,
    budget_rate: float = 0.6,
    max_data_sz: int = 700,
    sign_batch_size: int = 4096,
) -> Corpus:
    """Generate n unique valid txns plus dup/corrupt/garbage traffic."""
    from firedancer_tpu.ballet.compute_budget import COMPUTE_BUDGET_PROGRAM_ID

    rng = np.random.RandomState(seed)
    jobs: list = []
    sign_fn = _deferred_signer(jobs)
    sig_spans: list = []      # payload index -> number of signatures
    raw: list = []

    # Mainnet-ish signer mix: ~87% single-sig.
    signer_counts = rng.choice(
        [1, 2, 3, 4], size=n, p=[0.87, 0.08, 0.03, 0.02]
    )
    for i in range(int(n)):
        n_sign = int(signer_counts[i])
        seeds = [
            struct.pack("<IIB", i, j, seed & 0xFF) + bytes(23)
            for j in range(n_sign)
        ]
        extra = [COMPUTE_BUDGET_PROGRAM_ID,
                 rng.randint(0, 256, 32, dtype=np.uint8).tobytes(),
                 rng.randint(0, 256, 32, dtype=np.uint8).tobytes()]
        instrs = []
        if rng.rand() < budget_rate:
            instrs.append((n_sign, [],
                           b"\x02" + struct.pack("<I", int(rng.randint(50_000, 1_400_000)))))
            instrs.append((n_sign, [],
                           b"\x03" + struct.pack("<Q", int(rng.randint(0, 3_000_000)))))
        data_sz = int(rng.randint(8, max_data_sz))
        instrs.append(
            (n_sign + 1, [0],
             rng.randint(0, 256, data_sz, dtype=np.uint8).tobytes())
        )
        kw = {}
        if rng.rand() < v0_rate:
            kw = dict(
                version=0,
                addr_luts=[(
                    rng.randint(0, 256, 32, dtype=np.uint8).tobytes(),
                    [int(rng.randint(0, 64))],
                    [int(rng.randint(0, 64))],
                )],
            )
        blockhash = rng.randint(0, 256, 32, dtype=np.uint8).tobytes()

        def _build():
            return build_txn(
                signer_seeds=seeds,
                extra_accounts=extra,
                n_readonly_unsigned=len(extra),
                instrs=instrs,
                recent_blockhash=blockhash,
                sign_fn=sign_fn,
                **kw,
            )

        p = _build()
        if len(p) > 1232:
            # Mainnet txns never exceed the TPU MTU (1232 B,
            # src/disco/quic/fd_quic.h:46-47): a fat multi-sig + 700 B
            # data draw can overshoot, so rebuild with the payload
            # trimmed to fit (the deferred-sign jobs for the oversized
            # attempt are discarded with it).
            del jobs[len(jobs) - n_sign:]
            instrs[-1] = (instrs[-1][0], instrs[-1][1],
                          instrs[-1][2][: max(8, 1232 - (len(p) - data_sz))])
            p = _build()
            assert len(p) <= 1232, len(p)
        raw.append(p)
        sig_spans.append(n_sign)

    # Batch-sign every (msg, seed) job on the device.
    all_sigs = _sign_jobs(jobs, batch=sign_batch_size)
    payloads: list = []
    pos = 0
    for i, p in enumerate(raw):
        k = sig_spans[i]
        payloads.append(_splice_signatures(p, all_sigs[pos : pos + k]))
        pos += k

    out = [(p, OK) for p in payloads]

    # Exact duplicates (dedup tile traffic).
    for _ in range(int(n * dup_rate)):
        out.append((payloads[int(rng.randint(0, n))], DUP))

    # Corrupted signatures (verify tile traffic): flip one sig byte.
    for _ in range(int(n * corrupt_rate)):
        t = bytearray(payloads[int(rng.randint(0, n))])
        t[1 + int(rng.randint(0, 64))] ^= 1 + int(rng.randint(0, 255))
        out.append((bytes(t), BAD_SIG))

    # Truncated / garbage (parse traffic).
    for _ in range(int(n * parse_err_rate)):
        src = payloads[int(rng.randint(0, n))]
        cut = int(rng.randint(1, max(2, len(src) - 1)))
        out.append((src[:cut], BAD_PARSE))

    order = rng.permutation(len(out))
    payloads_shuffled = [out[int(j)][0] for j in order]
    expected = np.asarray([out[int(j)][1] for j in order], np.int8)
    # A dup published before its original swaps roles; dedup-by-content
    # doesn't care which copy survives, so the gate counts classes, and
    # unique-OK stays n either way.
    return Corpus(payloads_shuffled, expected, n_unique_ok=n)


def expected_sink_digests(corpus: Corpus):
    """sha256 multiset the sink must receive for a content-exact gate.

    Shared by the checked-in CPU gate (tests/test_replay_gate.py) and the
    hardware gate (bench.py --replay) so the two cannot drift. Count
    equality alone would let a wrongly-dropped valid txn cancel against a
    wrongly-passed corrupt one.
    """
    import hashlib
    from collections import Counter

    return Counter(
        hashlib.sha256(p).digest()
        for p, e in zip(corpus.payloads, corpus.expected)
        if e == OK
    )


def sink_mismatch_count(corpus: Corpus, sink_digests) -> int:
    """Symmetric difference size between expected and received multisets."""
    missing, unexpected = sink_delta(corpus, sink_digests)
    return missing + unexpected


def sink_delta(corpus: Corpus, sink_digests) -> tuple[int, int]:
    """(missing, unexpected) vs the expected sink multiset.

    `missing` — expected txns the sink never received: a run cut short
    (timeout, crash) shows up HERE, not as content corruption.
    `unexpected` — txns the sink received that the oracle says it must
    not have (invalid/duplicate leaked through, or content corrupted).
    The round-4 gate artifact booked a timeout's 99,725 missing txns as
    "mismatches"; keeping the two separate makes that unrepresentable.
    """
    from collections import Counter

    want = expected_sink_digests(corpus)
    got = Counter(sink_digests or [])
    return sum((want - got).values()), sum((got - want).values())


def _sign_jobs(jobs: list, batch: int = 4096) -> list:
    """Batch-sign (msg, seed) jobs; returns 64-byte sigs.

    Fast path: the native C++ signer (one C call for the whole corpus,
    ~8k sigs/s/core, bit-identical to the oracle — differentially
    pinned in tests/test_ed25519_cpu.py). Fallback: ops.sign batched on
    the attached device (the r3 path; ~5 h for a 100k corpus on a
    1-core CPU host, which is why the native path exists)."""
    from firedancer_tpu.ballet.ed25519 import native as _native

    got = _native.sign_jobs(jobs)
    if got is not None:
        return got
    import jax.numpy as jnp

    from firedancer_tpu.ops.sign import sign_batch_jit

    sigs: list = []
    for start in range(0, len(jobs), batch):
        chunk = jobs[start : start + batch]
        # Bucket both dims so a handful of XLA program shapes serve every
        # chunk (each TPU recompile costs minutes): batch padded to the
        # full batch size, message length to a 256-byte bucket.
        max_len = -(-max(len(m) for m, _ in chunk) // 256) * 256
        bsz = batch if len(jobs) > batch else len(chunk)
        msgs = np.zeros((bsz, max_len), np.uint8)
        lens = np.zeros(bsz, np.int32)
        seeds = np.zeros((bsz, 32), np.uint8)
        for i, (m, s) in enumerate(chunk):
            msgs[i, : len(m)] = np.frombuffer(m, np.uint8)
            lens[i] = len(m)
            seeds[i] = np.frombuffer(s, np.uint8)
        got = np.asarray(
            sign_batch_jit(
                jnp.asarray(msgs), jnp.asarray(lens), jnp.asarray(seeds)
            )[0]
        )
        sigs.extend(got[i].tobytes() for i in range(len(chunk)))
    return sigs
