"""disco tiles — long-running actors over tango rings.

Role parity with the reference's disco/frank layer: the generic tile
run-loop blueprint (housekeeping / backpressure / frag drain, modeled on
/root/reference/src/disco/mux/fd_mux.h:56-175 and
app/frank/fd_frank_verify.c:140-207), plus the concrete tiles of the hot
path: replay (pcap/synthetic source, disco/replay/), verify (sigverify —
the TPU offload point, app/frank/load/fd_frank_verify_synth_load.c),
dedup (tcache on meta sig, disco/dedup/), pack (account-lock scheduling
into bank lanes, app/frank/fd_frank_pack.c), and a sink (bank stub).

Tiles here are Python threads/processes joined to the same native
shared-memory rings (native/tango.cc via tango.rings); the hot math is
batched onto the device inside VerifyTile. Frag payloads on the
replay->verify link are whole Solana transaction wire bytes; the verify
tile parses in-tile exactly like the reference quic tile does
(fd_quic_tile.c:492 fd_txn_parse into the dcache slot).
"""

from __future__ import annotations

import os
import time
from hashlib import sha256 as _sha256
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

from firedancer_tpu import flags
from firedancer_tpu.ballet import ed25519 as oracle
from firedancer_tpu.ballet.txn import MAX_SIG_CNT, TxnParseError, parse_txn
from firedancer_tpu.disco import chaos, flight, xray
from firedancer_tpu.disco.feed.policy import (
    FLUSH_DEADLINE,
    FLUSH_FULL,
    FLUSH_STARVED,
    AdaptiveFlush,
    CircuitBreaker,
    respawn_backoff_s,
)
from firedancer_tpu.tango import tempo
from firedancer_tpu.tango.fctl import make_fctl_for_fseqs
from firedancer_tpu.tango.rings import (
    CNC_BOOT,
    CNC_HALT,
    CNC_RUN,
    CTL_ERR,
    DIAG_FILT_CNT,
    DIAG_FILT_SZ,
    DIAG_OVRNR_CNT,
    DIAG_PUB_CNT,
    DIAG_PUB_SZ,
    DIAG_SLOW_CNT,
    POLL_EMPTY,
    POLL_FRAG,
    POLL_OVERRUN,
    Cnc,
    DCache,
    FSeq,
    Frag,
    MCache,
    Workspace,
)
from firedancer_tpu.tango.tcache import TCache
from firedancer_tpu.utils.rng import Rng

# cnc diag slots (frank/fd_frank.h:20-36 ABI analog)
CNC_DIAG_IN_BACKP = 0
CNC_DIAG_BACKP_CNT = 1
CNC_DIAG_HA_FILT_CNT = 2
CNC_DIAG_HA_FILT_SZ = 3
CNC_DIAG_SV_FILT_CNT = 4
CNC_DIAG_SV_FILT_SZ = 5
# Gauge (not a counter): consumed-but-unverified frags a verify tile is
# holding its ack cursor back for. Supervisors/tests read it to know,
# deterministically, when staged device work exists (the crash window
# the held-back fseq protects).
CNC_DIAG_UNACKED = 6
# Fault-injection hold entries (FD_VERIFY_HOLD_AFTER_DISPATCH_S): the
# deterministic kill trigger for crash tests — UNACKED counts txns
# while batches fill by signature LANES, so a "staged >= batch" gauge
# test can miss the hold window on multisig-bearing corpora.
CNC_DIAG_HOLDS = 7
# fd_feed feeder gauges (verify tiles; slots 8.. exist only on the
# 16-slot cnc ABI — writers MUST gate on rings.cnc_diag_cap() >= 16, an
# 8-slot .so would take these as out-of-bounds wksp writes). Counters
# mirror the in-process verify_stats so monitors/supervisors see the
# feeder across process boundaries: batches dispatched, lanes in them
# (fill_ratio = lanes / (batches * batch)), deadline vs starved partial
# flushes, stager slot-acquire stalls, and the dispatcher's
# device-idle-estimate ns.
CNC_DIAG_FEED_BATCHES = 8
CNC_DIAG_FEED_LANES = 9
CNC_DIAG_FEED_DEADLINE = 10
CNC_DIAG_FEED_STARVED = 11
CNC_DIAG_FEED_SLOT_STALL = 12
CNC_DIAG_FEED_IDLE_NS = 13
# Supervisor respawn accounting (written by the SUPERVISOR, read by
# monitor.py): crash-only restarts of this tile, and the current
# respawn backoff in ms (a gauge, delta-published). 16-slot ABI only.
CNC_DIAG_RESTARTS = 14
CNC_DIAG_BACKOFF_MS = 15

CTL_SOM_EOM = 3

# Cap on the stager-thread restart backoff (thread-scale supervision: a
# stager outage past ~2 s blows the flush deadline regardless, so the
# exponential decay stops here; the process supervisor's analogous cap
# is flag-tunable via FD_SUP_BACKOFF_MAX_MS).
_STAGER_BACKOFF_CAP_S = 2.0

FD_TPU_MTU = 1232  # disco/quic/fd_quic.h:46-47

_U64 = (1 << 64) - 1


def meta_sig(payload: bytes) -> int:
    """Frag meta sig: first 8 bytes of the txn's first Ed25519 signature
    (the dedup identity) — the layout every publisher and the dedup tile
    must agree on (byte 0 is the compact signature count)."""
    return int.from_bytes(payload[1:9], "little") if len(payload) > 8 else 0


@dataclass
class LinkNames:
    """Workspace object names for one mcache/dcache/fseq link."""

    mcache: str
    dcache: str
    fseq: str


class InLink:
    """Consumer side of a link: poll frags in seq order, detect overruns."""

    def __init__(self, wksp: Workspace, names: LinkNames,
                 edge: Optional[str] = None):
        self.mcache = MCache(wksp, names.mcache)
        self.dcache = DCache(wksp, names.dcache)
        self.fseq = FSeq(wksp, names.fseq)
        # Resume from the published consumer progress: 0 on a fresh
        # fseq, the last-acknowledged seq after a crash-restart (the
        # supervisor's crash-only recovery relies on this).
        self.seq = self.fseq.query()
        # fd_xray consumer-side queue telemetry for this edge (sampled
        # dwell = producer tspub -> drain, depth, consumer idle): None
        # when the link has no edge name (direct test construction) or
        # FD_XRAY=0 — hot paths gate on the handle's None-ness.
        self.edge = edge
        self.xq: Optional[xray.EdgeRx] = (
            xray.edge_rx(wksp, edge) if edge else None)
        self.xq_cnt = 0
        # Clamped to >= 1: the stride is a modulus on the hot drain
        # path, and a 0 from the environment must tighten sampling to
        # every frag, never divide-by-zero a consuming tile.
        self.xq_every = (max(1, flags.get_int("FD_XRAY_QUEUE_SAMPLE"))
                         if self.xq is not None else 0)

    def dwell_sample(self, tspub: int, now: int = 0) -> None:
        """Sampled queue-dwell observe (every FD_XRAY_QUEUE_SAMPLE'th
        drained frag): the queue-wait half of the xray waterfall. The
        stride check runs FIRST so non-sampled frags cost one counter
        increment — callers without a hoisted clock pass now=0 and the
        tick is read only on the sampled Nth frag."""
        self.xq_cnt += 1
        if tspub and self.xq_cnt % self.xq_every == 0:
            if not now:
                now = tempo.tickcount() & 0xFFFFFFFF
            self.xq.observe_dwell((now - tspub) & 0xFFFFFFFF)

    def poll(self):
        """Returns (status, frag, payload_bytes_or_None)."""
        r, f = self.mcache.poll(self.seq)
        if r == POLL_EMPTY:
            return r, None, None
        if r == POLL_OVERRUN:
            # Jump forward to the oldest frag still in the ring; only the
            # frags actually skipped over count as lost.
            new_seq = self.mcache.seq_next()
            new_pos = max(new_seq - self.mcache.depth + 1, self.seq + 1)
            self.fseq.diag_add(DIAG_OVRNR_CNT, new_pos - self.seq)
            self.seq = new_pos
            return r, None, None
        payload = self.dcache.read(f.chunk, f.sz)
        return r, f, payload

    def advance(self):
        self.seq += 1

    def housekeep(self):
        self.fseq.update(self.seq)


class OutLink:
    """Producer side: dcache chunk walk + mcache publish + credit control."""

    def __init__(
        self,
        wksp: Workspace,
        names: LinkNames,
        mtu: int = FD_TPU_MTU,
        reliable_fseqs: Optional[Sequence[FSeq]] = None,
        edge: Optional[str] = None,
    ):
        self.mcache = MCache(wksp, names.mcache)
        self.dcache = DCache(wksp, names.dcache)
        self.mtu = mtu
        self.seq = self.mcache.seq_next()
        # Restart-safe chunk resume: a respawned producer must continue
        # the dcache walk where the dead incarnation stopped, or it
        # would overwrite the payload bytes of still-unconsumed frags
        # (whose mcache entries remain valid — silent corruption, not an
        # overrun). The last published frag's own meta records where the
        # walk was.
        self.chunk = 0
        if self.seq > 0:
            r, last = self.mcache.poll(self.seq - 1)
            if r == POLL_FRAG and last is not None:
                self.chunk = self.dcache.next_chunk(
                    last.chunk, last.sz, mtu
                )
        self.fctl = make_fctl_for_fseqs(
            self.mcache.depth, reliable_fseqs or [], cr_burst=1
        )
        self.cr_avail = 0
        # Per-stage latency reservoir (docs/LATENCY.md): tsorig -> tspub
        # of every frag published on THIS link, i.e. source-stamp to
        # this-stage-complete. publish() already computes both stamps,
        # so the sample is one subtraction on a path that costs ~40 us —
        # bounded reservoir (algorithm R) keeps long soaks at constant
        # memory. The replay artifacts report p50/p99 per stage.
        self.lat_ns: list = []
        self.lat_cap = 16384
        self._lat_seen = 0
        self._lat_rng = Rng(seq=0x1a7)
        # fd_flight trace span: this link's ALWAYS-ON log2 latency
        # histogram (full population, unlike the sampled reservoir) in
        # the shared registry. None when the link has no edge name
        # (direct test construction) or spans are hatched off.
        self.span: Optional[flight.EdgeHist] = None
        if (edge and flight.enabled()
                and flags.get_bool("FD_TRACE_SPANS")):
            self.span = flight.edge_hist(wksp, edge)
        # fd_xray producer-side handles: exemplar sampler (head/tail
        # capture riding the same publish-latency computation) and the
        # credit-stall/credits tx row. Both None when xray is off.
        self.xspan: Optional[xray.SpanCtx] = (
            xray.span_ctx(edge) if edge else None)
        self.xq_tx: Optional[xray.EdgeTx] = (
            xray.edge_tx(wksp, edge) if edge else None)

    def _reservoir_insert(self, lat: int) -> None:
        """Algorithm-R insert: every publish-latency sample in the
        link's lifetime has equal selection probability, so a long
        soak's percentiles reflect the whole run, not the warmup
        window. ONE body, shared by both sampling entry points."""
        self._lat_seen += 1
        if len(self.lat_ns) < self.lat_cap:
            self.lat_ns.append(lat)
        else:
            j = self._lat_rng.roll(self._lat_seen)
            if j < self.lat_cap:
                self.lat_ns[j] = lat

    def lat_sample(self, lat: int, tsorig: int = 0, tspub: int = 0) -> None:
        """Per-frag sample: always-on span histogram + reservoir +
        (when xray is armed and the caller passed the stamps) the
        deterministic exemplar head/tail capture."""
        if self.span is not None:
            self.span.observe(lat)
        if self.xspan is not None and tsorig:
            self.xspan.observe(tsorig, tspub, lat)
        self._reservoir_insert(lat)

    def lat_sample_many(self, lats, tsorigs=None) -> None:
        """Bulk-completion variant: one vectorized histogram update for
        the whole batch, reservoir inserts per sample as before; the
        exemplar capture is one vectorized mask over the trace ids."""
        if self.span is not None:
            self.span.observe_many(lats)
        if self.xspan is not None and tsorigs is not None:
            self.xspan.observe_many(tsorigs, lats)
        for lat in lats.tolist():
            self._reservoir_insert(lat)

    def housekeep(self):
        self.cr_avail = self.fctl.tx_cr_update(self.cr_avail, self.seq)

    def can_publish(self) -> bool:
        if self.cr_avail > 0:
            return True
        self.housekeep()
        return self.cr_avail > 0

    def publish(self, payload: bytes, sig: int, tsorig: int = 0,
                ctl: int = CTL_SOM_EOM) -> None:
        """Copy payload into the dcache and publish its frag meta.
        `ctl` defaults to SOM|EOM; the quarantine/audit paths publish
        offending txns with CTL_ERR set so the fault is visible on the
        ring instead of silently vanishing."""
        if len(payload) > self.mtu:
            # Not an assert: python -O would strip it, and an oversized
            # payload published past the MTU tramples the next frag's
            # dcache chunk (shared-memory corruption, not a local bug).
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds the link MTU "
                f"({self.mtu}): refusing to publish past the dcache "
                "chunk walk"
            )
        self.dcache.write(self.chunk, payload)
        tspub = tempo.tickcount() & 0xFFFFFFFF
        if tsorig:
            self.lat_sample((tspub - tsorig) & 0xFFFFFFFF,
                            tsorig=tsorig, tspub=tspub)
        self.mcache.publish(
            self.seq, sig, self.chunk, len(payload), ctl, tsorig, tspub
        )
        self.chunk = self.dcache.next_chunk(self.chunk, len(payload), self.mtu)
        self.seq += 1
        self.cr_avail = max(0, self.cr_avail - 1)


class Tile:
    """Generic run loop: housekeeping on jittered intervals + frag drain.

    Subclasses implement on_frag(frag, payload) and optionally on_idle().
    """

    name = "tile"

    def __init__(
        self,
        wksp: Workspace,
        cnc_name: str,
        in_link: Optional[InLink] = None,
        out_link: Optional[OutLink] = None,
        in_links: Optional[List[InLink]] = None,
        lazy_ns: Optional[int] = None,
        seed: int = 0,
    ):
        if in_link is not None and in_links is not None:
            raise ValueError("pass in_link or in_links, not both")
        self.wksp = wksp
        self.cnc_name = cnc_name  # stable tile identity (chaos hb ordinals)
        # fd_flight identity: the cnc name minus its ".cnc" suffix is
        # the registry row label AND the flight-recorder name.
        self.flight_label = (
            cnc_name[:-4] if cnc_name.endswith(".cnc") else cnc_name
        )
        self.flightrec = flight.recorder(self.flight_label)
        self.cnc = Cnc(wksp, cnc_name)
        # Multi-input tiles (the mux pattern, mux/fd_mux.h:56-175) poll
        # every in-link round-robin; in_link stays as the first for the
        # common single-input case.
        self.in_links: List[InLink] = (
            list(in_links) if in_links is not None
            else ([in_link] if in_link is not None else [])
        )
        self.in_link = self.in_links[0] if self.in_links else None
        self.in_cur = self.in_link  # link of the frag being processed
        self.out_link = out_link
        self.rng = Rng(seq=seed)
        depth = self.in_links[0].mcache.depth if self.in_links else (
            out_link.mcache.depth if out_link else 128
        )
        lazy = lazy_ns if lazy_ns is not None else tempo.lazy_default(depth)
        self._async_min = tempo.async_min(lazy)
        self._last_in_backp = 0
        self.halted = False
        # Optional core pin (fd_tile's dedicated-core model, fd_tile.h:13;
        # set by the pipeline from the layout.tile_cpus config). Python
        # threads share the GIL, but pinning still removes migration
        # jitter from the hot poll loops and matches the reference's
        # affinity contract for the native drain path.
        self.cpu_idx: Optional[int] = None
        # fd_xray consumer-idle accounting: ns this tile spent in its
        # idle naps, accumulated locally and flushed to the in-edge rx
        # row at housekeep (single-writer: this tile's thread).
        self._xq_idle_ns = 0
        self._xq_on = any(il.xq is not None for il in self.in_links)

    # -- overridables ----------------------------------------------------

    def on_frag(self, frag: Frag, payload: bytes) -> None:
        raise NotImplementedError

    def on_idle(self) -> None:
        """Called when the input is empty (flush partial batches etc.)."""

    def on_housekeep(self) -> None:
        """Extra per-tile housekeeping."""

    def done(self) -> bool:
        """Source tiles return True when exhausted."""
        return False

    # Bulk-drain batch per in-link per round (native fd_frag_drain): one
    # C call replaces ~18 us of per-frag Python ring hop. Bounded so the
    # crash-replay window (frags consumed but not yet fseq-published)
    # stays small — the pipeline is crash-only and dedup absorbs
    # replays, exactly as with the 1-frag window of the Python poll.
    BULK_FRAGS = 64

    def _bulk_state(self, il):
        st = getattr(il, "_bulk", None)
        if st is None:
            import ctypes as _ct

            from firedancer_tpu.tango.rings import (
                frag_drain_has_ctl as _has_ctl,
            )
            from firedancer_tpu.tango.rings import (
                frag_drain_has_tspub as _has_tspub,
            )
            from firedancer_tpu.tango.rings import lib as _rings_lib

            n = self.BULK_FRAGS
            # The staging buffer is sized so ANY frag fits it alone
            # (frag sz is u16, max 65535 < n * FD_TPU_MTU) and the
            # per-frag cap passed to the C side is the u16 ceiling —
            # the drain must never truncate a payload (it defers frags
            # that don't fit the REMAINING room instead).
            st = {
                "lib": _rings_lib(),
                "ct": _ct,
                "pay": np.zeros(n * FD_TPU_MTU, np.uint8),
                "offs": np.zeros(n, np.uint32),
                "lens": np.zeros(n, np.uint32),
                "sigs": np.zeros(n, np.uint64),
                "ts": np.zeros(n, np.uint32),
                "seqs": np.zeros(n, np.uint64),
                "ctls": np.zeros(n, np.uint16),
                "has_ctl": _has_ctl(),
                "tspubs": np.zeros(n, np.uint32),
                "has_tspub": _has_tspub(),
                "ctr": np.zeros(2, np.uint64),
                "cap": 0xFFFF,
            }
            il._bulk = st
        return st

    _bulk_ok: bool | None = None  # class-level: probed once per process

    def poll_inputs(self):
        """One drain round over the in-links. Returns (progressed,
        overrun). Tiles with their own native drain override this."""
        if Tile._bulk_ok is None:
            from firedancer_tpu.tango.rings import native_available

            Tile._bulk_ok = native_available()
        if not Tile._bulk_ok:
            return self._poll_inputs_py()
        progressed = False
        overrun = False
        for il in self.in_links:
            st = self._bulk_state(il)
            ct = st["ct"]
            seq = ct.c_uint64(il.seq)
            ovr0 = int(st["ctr"][1])
            args = [
                il.mcache._mem, ct.addressof(il.dcache._buf),
                ct.byref(seq), self.BULK_FRAGS, st["cap"],
                st["pay"].ctypes.data, st["pay"].nbytes,
                st["offs"].ctypes.data, st["lens"].ctypes.data,
                st["sigs"].ctypes.data, st["ts"].ctypes.data,
                st["seqs"].ctypes.data,
            ]
            if st["has_ctl"]:  # stale .so builds lack the ctl output
                args.append(st["ctls"].ctypes.data)
            if st["has_tspub"]:  # stale .so builds lack the tspub output
                args.append(st["tspubs"].ctypes.data)
            args.append(st["ctr"].ctypes.data)
            n = st["lib"].fd_frag_drain(*args)
            d_ovr = int(st["ctr"][1]) - ovr0
            if d_ovr:
                il.fseq.diag_add(DIAG_OVRNR_CNT, d_ovr)
                overrun = True
            if n > 0:
                self.in_cur = il
                pay = st["pay"]
                offs, lens = st["offs"], st["lens"]
                sigs, tss, seqs = st["sigs"], st["ts"], st["seqs"]
                ctls, tspubs = st["ctls"], st["tspubs"]
                has_tspub = st["has_tspub"]
                xq_now = (tempo.tickcount() & 0xFFFFFFFF
                          if il.xq is not None and has_tspub else 0)
                for i in range(n):
                    off = int(offs[i])
                    ln = int(lens[i])
                    # Propagate the producer's ctl word (ADVICE r5 low
                    # #3): a CTL_ERR frag must reach on_frag as an
                    # error frag on the bulk path exactly as it does on
                    # the per-frag Python poll. Stale .so builds have
                    # no ctl output; they keep the old synthesized
                    # SOM|EOM.
                    ctl = int(ctls[i]) if st["has_ctl"] else CTL_SOM_EOM
                    tspub = int(tspubs[i]) if has_tspub else 0
                    if xq_now:
                        # fd_xray queue-dwell (producer publish -> this
                        # drain), sampled every Nth frag per edge.
                        il.dwell_sample(tspub, xq_now)
                    frag = Frag(seq=int(seqs[i]), sig=int(sigs[i]),
                                chunk=0, sz=ln, ctl=ctl,
                                tsorig=int(tss[i]), tspub=tspub)
                    self.on_frag(frag, pay[off:off + ln].tobytes())
                progressed = True
            # Publish-cursor semantics match the per-frag path: il.seq
            # advances only after the batch is fully processed (housekeep
            # publishes from il.seq, so a crash mid-batch replays it).
            il.seq = seq.value
        return progressed, overrun

    def _poll_inputs_py(self):
        progressed = False
        overrun = False
        for il in self.in_links:
            r, frag, payload = il.poll()
            if r == POLL_FRAG:
                self.in_cur = il
                if il.xq is not None:
                    il.dwell_sample(frag.tspub)  # tick read only when due
                self.on_frag(frag, payload)
                il.advance()
                progressed = True
            elif r == POLL_OVERRUN:
                # InLink.poll repositioned + counted; the consumer is
                # behind, so keep polling hot — never throttle it.
                overrun = True
        return progressed, overrun

    # -- run loop --------------------------------------------------------

    def _housekeep_out(self) -> None:
        """Out-link credit refresh + backpressure diag mirror — shared
        by the base housekeep and overrides that replace only the
        in-link fseq publication (VerifyTile's verified cursor)."""
        if self.out_link:
            self.out_link.housekeep()
            if self.out_link.xq_tx is not None:
                self.out_link.xq_tx.sample_credits(self.out_link.cr_avail)
            # Mirror the fctl backpressure gauge into the cnc diag
            # (IN_BACKP slot, frank/fd_frank.h:20-36 semantics).
            backp = 1 if self.out_link.fctl.in_backpressure else 0
            if backp != self._last_in_backp:
                self.cnc.diag_add(
                    CNC_DIAG_IN_BACKP, (backp - self._last_in_backp) & _U64
                )
                self._last_in_backp = backp

    def _beat(self, now: int) -> None:
        """Publish the cnc heartbeat — unless a chaos hb_stall window is
        open (the supervised wedge detector is the intended observer of
        a stalled heartbeat; healing is the kill + respawn)."""
        c = chaos.active()
        if c is not None and c.hb_stalled(self.cnc_name):
            return
        self.cnc.heartbeat(now)

    def _xq_housekeep(self) -> None:
        """fd_xray queue telemetry at housekeeping rate: sampled ring
        depth per in-edge + the idle-ns flush (both cheap; the depth
        probe is one ns-scale PyDLL call per link). Runs on the tile
        thread — the same thread that drains the in-links — so every
        rx-row write stays single-threaded. VerifyTile's overridden
        housekeep does NOT route here: in feed mode the STAGER thread
        drains (and owns the row — see _stager_drain), and the legacy
        native path books its telemetry at the drain site too."""
        if not self._xq_on:
            return
        first = True
        for il in self.in_links:
            if il.xq is None:
                continue
            il.xq.sample_depth(il.mcache.seq_next() - il.seq)
            if first and self._xq_idle_ns:
                il.xq.add_idle(self._xq_idle_ns)
                self._xq_idle_ns = 0
                first = False

    def housekeep(self, now: int) -> None:
        self._beat(now)
        for il in self.in_links:
            il.housekeep()
        self._xq_housekeep()
        self._housekeep_out()
        self.on_housekeep()

    def run(self, max_ns: int = 30_000_000_000) -> None:
        """Run until HALT signal, done(), or max_ns wall time."""
        if self.cpu_idx is not None and hasattr(os, "sched_setaffinity"):
            # NB Linux inherits the affinity mask into threads created
            # FROM this thread — lazily-spawned pools (XLA's intra-op
            # pool, etc.) must already exist. VerifyTile guarantees this
            # by pre-warming its jit on the constructing (main) thread.
            try:
                os.sched_setaffinity(0, {self.cpu_idx})  # calling thread
            except OSError:
                pass  # affinity is best-effort (cpuset may forbid it)
        try:
            self._run_loop(max_ns)
        except BaseException as e:
            # Postmortem BEFORE re-raising: the flight dump is the
            # record of what the tile was doing when it died (no-op
            # unless FD_FLIGHT_DUMP names a directory), and the xray
            # autopsy bundles the window's exemplars + waterfall +
            # suspects (no-op unless FD_XRAY_DIR names a directory).
            self.flightrec.record("crash", err=repr(e)[:200])
            flight.maybe_dump(f"crash:{self.flight_label}", wksp=self.wksp)
            xray.maybe_autopsy(f"crash:{self.flight_label}",
                               wksp=self.wksp)
            raise
        finally:
            # teardown must happen even if step()/on_frag() raised, or
            # sockets leak and the supervisor spins until its timeout;
            # on_halt() runs first so a failing final housekeep (broken
            # shared state) can't skip the socket teardown
            try:
                self.on_halt()
            finally:
                self.halted = True
                self.flightrec.record("halt")
                try:
                    self.housekeep(tempo.tickcount())
                finally:
                    self.cnc.signal(CNC_BOOT)

    def _run_loop(self, max_ns: int) -> None:
        self.cnc.signal(CNC_RUN)
        start = tempo.tickcount()
        then = start
        idle_spins = 0
        while True:
            now = tempo.tickcount()
            if now >= then:
                self.housekeep(now)
                if self.cnc.signal_query() == CNC_HALT:
                    break
                if now - start > max_ns:
                    break
                then = now + tempo.async_reload(self.rng, self._async_min)
            if self.done():
                if self.cnc.signal_query() == CNC_HALT:
                    break
                time.sleep(50e-6)
                continue
            if not self.in_links:
                self.step()
                continue
            progressed, overrun = self.poll_inputs()
            if progressed or overrun:
                idle_spins = 0
            else:
                self.on_idle()
                idle_spins += 1
                if idle_spins > 64:
                    time.sleep(20e-6)  # FD_SPIN_PAUSE analog
                    if self._xq_on:
                        self._xq_idle_ns += 20_000

    def on_halt(self) -> None:
        """Tile-specific teardown (close sockets etc)."""

    def publish_backp(self, payload: bytes, sig: int, tsorig: int = 0,
                      count_diag: bool = True) -> bool:
        """Publish downstream, spinning through backpressure (counted in
        the cnc BACKP diag) until credits arrive or HALT. Returns False if
        the frag was dropped because HALT arrived first."""
        t_stall = 0
        while not self.out_link.can_publish():
            if self.cnc.signal_query() == CNC_HALT:
                return False
            if not t_stall:
                t_stall = tempo.tickcount()
            self.cnc.diag_add(CNC_DIAG_BACKP_CNT, 1)
            time.sleep(20e-6)
        if t_stall and self.out_link.xq_tx is not None:
            # fd_xray producer credit-stall: the wall time this publish
            # spent blocked on downstream credits (the backpressure
            # half of the waterfall attribution).
            self.out_link.xq_tx.add_stall(tempo.tickcount() - t_stall)
        self.out_link.publish(payload, sig, tsorig=tsorig)
        if count_diag and self.in_cur is not None:
            self.in_cur.fseq.diag_add(DIAG_PUB_CNT, 1)
            self.in_cur.fseq.diag_add(DIAG_PUB_SZ, len(payload))
        return True

    def step(self) -> None:
        """Source tiles (no in_link) override or rely on done()."""
        time.sleep(50e-6)


class MuxTile(Tile):
    """N-in -> 1-out frag multiplexer (disco/mux/fd_mux.c analog): forwards
    every input frag downstream in arrival order, preserving sig/tsorig.
    The generic multi-input run loop in Tile *is* the mux blueprint; this
    tile is the identity instance of it."""

    name = "mux"

    def __init__(self, wksp, cnc_name, in_links: List[InLink], out_link, **kw):
        super().__init__(wksp, cnc_name, in_links=in_links, out_link=out_link,
                         **kw)

    def on_frag(self, frag: Frag, payload: bytes) -> None:
        self.publish_backp(payload, frag.sig, tsorig=frag.tsorig)


class ReplayTile(Tile):
    """Source: publishes a list of payloads downstream with flow control
    (disco/replay/fd_replay.c analog; feed it utils.pcap.read_all(path)).
    With several out_links (one per verify lane) payloads round-robin
    across lanes — the data-parallel ingest fan-out the reference gets
    from N flow-steered quic+verify tile pairs (config verify_tile_count,
    configure/frank.c:215-224)."""

    name = "replay"

    def __init__(self, wksp, cnc_name, out_link=None, payloads: List[bytes] = (),
                 out_links: Optional[List[OutLink]] = None, **kw):
        if (out_link is None) == (out_links is None):
            raise ValueError("pass exactly one of out_link / out_links")
        self.out_links = list(out_links) if out_links else [out_link]
        super().__init__(wksp, cnc_name, out_link=self.out_links[0], **kw)
        self.payloads = payloads
        self.pos = 0
        self.pub_cnt = 0
        self.pub_sz = 0

    def done(self) -> bool:
        return self.pos >= len(self.payloads)

    def housekeep(self, now: int) -> None:
        super().housekeep(now)
        for ol in self.out_links[1:]:
            ol.housekeep()

    def step(self) -> None:
        lane = self.out_links[self.pos % len(self.out_links)]
        c = chaos.active()
        if c is not None and c.source_starved():
            # Injected credit starvation: behave exactly like real
            # backpressure (count + back off) until the window closes.
            self.cnc.diag_add(CNC_DIAG_BACKP_CNT, 1)
            if lane.xq_tx is not None:
                lane.xq_tx.add_stall(20_000)
            time.sleep(20e-6)
            return
        if not lane.can_publish():
            self.cnc.diag_add(CNC_DIAG_BACKP_CNT, 1)
            if lane.xq_tx is not None:
                # fd_xray: source-side credit stall (one 20 us backoff
                # per refused attempt) — a credit_starve chaos window
                # shows up as stall_ns on the replay_verify edge.
                lane.xq_tx.add_stall(20_000)
            time.sleep(20e-6)
            return
        if c is not None:
            # Ring-level injection keyed to the upcoming payload ordinal
            # (1-based): may publish a CTL_ERR frag ahead of it. Re-check
            # credits afterward — the err frag spent one.
            c.source_inject(lane, self.pos + 1)
            if not lane.can_publish():
                return
        payload = self.payloads[self.pos]
        lane.publish(payload, meta_sig(payload),
                     tsorig=tempo.tickcount() & 0xFFFFFFFF)
        self.pos += 1
        self.pub_cnt += 1
        self.pub_sz += len(payload)


def _txn_batch_arrays(items, max_len: int):
    """Pack (sig, pub, msg) tuples into padded arrays for verify_batch."""
    n = len(items)
    msgs = np.zeros((n, max_len), np.uint8)
    lens = np.zeros(n, np.int32)
    sigs = np.zeros((n, 64), np.uint8)
    pubs = np.zeros((n, 32), np.uint8)
    for i, (sig, pub, msg) in enumerate(items):
        m = np.frombuffer(msg, np.uint8)[:max_len]
        msgs[i, : len(m)] = m
        lens[i] = len(m)
        sigs[i] = np.frombuffer(sig, np.uint8)
        pubs[i] = np.frombuffer(pub, np.uint8)
    return msgs, lens, sigs, pubs


@dataclass
class _InflightBatch:
    """One dispatched device batch awaiting completion (the software analog
    of a wiredancer DMA slot, wd_f1.c:327-408: request pushed async, result
    later completed into the consumer mcache keyed by seq)."""

    out: object                    # jax.Array of statuses, dispatched async
    todo: list                     # [(payload, n_items, tsorig)] whole txns
    oversize: list                 # per-lane True if msg exceeded staging
    t_dispatch: int                # tickcount at dispatch (diag)
    # fd_feed cpu path: the staging slot the verify executor is still
    # reading from; released back to the pool when the batch retires.
    slot: object = None
    # True when the batch went to the PRIMARY verify lane (device, or
    # the feed cpu executor): its outcome feeds the failover circuit
    # breaker. CPU-failover and quarantine re-verifies set False.
    device: bool = False
    # fd_engine accounting: the dispatch rung (B the engine ran at; 0 =
    # scheduler off / legacy path) and the registry entry whose service
    # EMA the completion feeds.
    rung: int = 0
    entry: object = None
    # fd_drain: the dedup pre-filter aux dispatch riding the same
    # round trip — (novel jax.Array, novel_cnt jax.Array) or None when
    # the drain stage is off / disarmed for this batch.
    drain: object = None


class _ReadyBatch:
    """Completed-synchronously result with the async-batch surface
    (_complete polls .is_ready() and np.asarray's the result)."""

    def __init__(self, statuses):
        self._s = statuses

    def is_ready(self) -> bool:
        return True

    def __array__(self, dtype=None):
        import numpy as _np

        return _np.asarray(self._s, dtype=dtype)


# Verify-mode resolution lives in the fd_engine registry module since
# PR 13 (ONE owner for every engine-resolution decision); re-exported
# here because the tile construction sites and a decade of tests spell
# it tiles.resolve_verify_mode.
from firedancer_tpu.disco.engine import resolve_verify_mode  # noqa: E402


class _FutureBatch:
    """concurrent.futures result with the async-batch surface — the
    fd_feed cpu dispatch path, where a verify executor thread runs the
    GIL-releasing fd_ed25519_cpu_verify_batch call concurrently with
    staging (the host-verifier analog of an async device dispatch)."""

    def __init__(self, fut):
        self._f = fut

    def is_ready(self) -> bool:
        return self._f.done()

    def __array__(self, dtype=None):
        import numpy as _np

        return _np.asarray(self._f.result(), dtype=dtype)


class VerifyTile(Tile):
    """Sigverify: parse txn in-tile, ha-dedup, verify signatures, forward.

    backend='cpu' verifies per-txn on the host — the native C++
    verifier when built, else the Python oracle. backend='oracle' PINS
    the pure-Python reference implementation (differential tests rely
    on it being the bit-exact oracle, never an out-of-band .so).
    backend='tpu' accumulates a batch and dispatches the fused
    verify_batch XLA program ASYNCHRONOUSLY (the wiredancer offload shim,
    wd_f1.c:327-408): up to `inflight` batches are in flight on the device
    while the tile keeps draining its in-ring; completions are polled
    non-blockingly (jax async dispatch + Array.is_ready) and published
    into the out mcache in dispatch order. Partial batches are governed
    by the ADAPTIVE flush policy (disco/feed/policy.py): hard latency
    deadline (max_wait_us if passed, else FD_FEED_DEADLINE_US), plus a
    fast starved-input flush when the device is idle — at steady state
    batches fill and flush_timeout stays ~0 (the ROADMAP round-6 gate).
    Failed/parse-error/duplicate txns are dropped and counted in the cnc
    diag (SV/HA filter slots).

    feed=True (the fd_feed ingest runtime) moves the whole ring-drain /
    parse / HA-dedup / staging path onto a dedicated STAGER thread that
    fills preallocated SlotPool arenas (disco/feed/slots.py) while this
    tile's run loop becomes a pure dispatcher: pop READY slots, ship
    them to the device (or the native CPU verifier — a GIL-releasing C
    call, so staging genuinely overlaps it), publish completions. Feeder
    stats (fill_ratio, slot_stall, device_idle_est) land in verify_stats
    and — on the 16-slot cnc ABI — in the CNC_DIAG_FEED_* gauges that
    monitors and supervisors read across process boundaries.
    """

    name = "verify"

    def __init__(
        self,
        wksp,
        cnc_name,
        in_link,
        out_link,
        backend: str = "cpu",
        batch: int = 128,
        max_msg_len: int = FD_TPU_MTU,
        tcache_depth: int = 4096,
        inflight: int = 2,
        max_wait_us: Optional[int] = None,
        native_drain: bool = True,
        verify_mode: str = "auto",
        mesh_devices: int = 0,
        feed: bool = False,
        feed_slots: Optional[int] = None,
        **kw,
    ):
        super().__init__(wksp, cnc_name, in_link=in_link, out_link=out_link, **kw)
        # Typed raises, not asserts (python -O strips asserts, and a
        # typo'd config here silently verifies on the wrong engine):
        if backend not in ("oracle", "cpu", "tpu"):
            raise ValueError(
                f"unknown verify backend {backend!r} (want oracle|cpu|tpu)"
            )
        # Production default (round-6 un-park, round-10 mesh
        # composition): RLC batch verify is the PRIMARY device mode —
        # one Pippenger MSM pass per clean batch (sharded across
        # mesh_devices when configured), exact per-lane fallback on
        # batch-equation failure or fill overflow (ops/verify_rlc.py).
        # Resolution + validation live in resolve_verify_mode above.
        verify_mode = resolve_verify_mode(backend, verify_mode,
                                          mesh_devices)
        self.backend = backend
        self.verify_mode = verify_mode
        self.batch = batch
        self.max_msg_len = max_msg_len
        self.ha_tcache = TCache(tcache_depth)
        self.inflight_max = max(1, inflight)
        # Partial-batch flush: deadline-based adaptive policy (replaces
        # the round-2 fixed max-wait timer). An explicit max_wait_us
        # still pins the deadline (the device replay gate passes 200 ms
        # for the slow remote tunnel); otherwise FD_FEED_DEADLINE_US.
        deadline_us = (
            max_wait_us if max_wait_us is not None
            else flags.get_int("FD_FEED_DEADLINE_US")
        )
        self.max_wait_ns = deadline_us * 1_000  # kept: tests/monitors read it
        self.flush_policy = AdaptiveFlush(self.max_wait_ns)
        self._pending: list = []       # [(payload, items, tsorig, seq_end)]
        self._pending_lanes = 0
        self._pending_since = 0        # tickcount of oldest pending txn
        self._inflight: list = []      # FIFO of _InflightBatch
        # Crash-consistency cursor: the fseq published to the producer is
        # held back to the last seq whose txn is FULLY verified (not just
        # consumed), so a SIGKILL between consume and verify-complete
        # cannot lose staged txns — the respawned worker re-reads them
        # (duplicates are healed by the downstream dedup tile).
        self._acked_seq = self.in_link.seq if self.in_link else 0
        # The delta mirror for the UNACKED gauge must seed from the
        # SHARED slot, not 0: the cnc diag survives a worker crash while
        # this process-local mirror does not, and a zero seed would make
        # the respawned incarnation re-add the dead one's last gauge
        # value forever (phantom staged work — the exact crash this
        # gauge exists to instrument).
        self._last_unacked = int(self.cnc.diag(CNC_DIAG_UNACKED))
        # Fault-injection knob (the reference's synth-load style): hold
        # the tile once, right after its first dispatch, with the
        # UNACKED gauge freshly published — a deterministic window for
        # crash tests to SIGKILL a tile that provably holds staged
        # batches (tests/test_supervisor.py). 0 = disabled (production).
        self._hold_s = flags.get_float("FD_VERIFY_HOLD_AFTER_DISPATCH_S")
        # A respawned incarnation (nonzero crash-surviving gauge) must
        # not hold again: the knob freezes only the first incarnation,
        # so the post-crash re-read path runs at full speed.
        self._held = self._last_unacked > 0
        self._verify_batch_fn = None
        # fd_flight: dispatch/completion/healing stats live in the
        # tile's registry LANE (one typed metric row, shared-memory
        # backed when the workspace carries the flight region) — the
        # stat_* names below are read-only VIEWS over it, so monitors,
        # verify_stats, and the replay/bench artifacts all read one
        # authority instead of hand-mirrored attributes.
        self.fl = flight.tile_lane(wksp, self.flight_label)
        # fd_xray: the tile's trigger/batch-context exemplar ring (one
        # span per sampled txn of every dispatched batch — batch id,
        # engine key, flush verdict, shard lane — plus quarantine /
        # breaker / CTL_ERR trigger events), and the cached sampling
        # threshold so the per-batch mask costs one vectorized hash.
        self._xr_on = xray.enabled()
        self.xr = xray.ring(f"tile:{self.flight_label}")
        self._xr_thr = xray.sample_threshold() if self._xr_on else 0
        # fd_engine identity: the registry spec this tile's dispatches
        # are keyed by (mode x B x shards x frontend — the flight
        # engine_key, now a typed registry key).
        from firedancer_tpu.disco import engine as fd_engine

        self._engine_spec = fd_engine.EngineSpec.for_tile(
            backend, verify_mode, batch, mesh_devices)
        self._engine_key = self._engine_spec.key
        # The registry record exists for host engines too (cpu/oracle
        # have nothing to compile, but their dispatch/service
        # accounting keys the same way); the tpu branch below replaces
        # this with the acquire()'d (built + warmed) entry.
        self._registry = fd_engine.registry()
        self._engine_entry = self._registry.entry(self._engine_spec)
        # Per-mesh-shard metric lanes (round-12 distributed aggregation:
        # populated only when mesh_devices > 1 — one row per shard,
        # booked at dispatch with the lanes that shard's slice of the
        # batch actually carries, so flight.merge_tile_metrics over them
        # reproduces this tile's own row; shared-memory backed when
        # build_topology(verify_shards=N) pre-labeled the rows).
        self.fl_shards: list = []
        self.stat_ring_dwell_ns: list = []  # publish->drain backlog samples
        self._dwell_span: Optional[flight.EdgeHist] = None
        self._breaker_pub = (None, 0, 0)   # last published breaker view
        # Device->CPU failover circuit (fd_feed mode; None elsewhere).
        self._breaker: Optional[CircuitBreaker] = None
        # Feeder gauge mirror (CNC_DIAG_FEED_*): published by EVERY
        # verify tile — legacy tiles report batches/lanes/flush buckets
        # too, so the supervisor's cross-process verify_stats are never
        # blind — but only on the 16-slot cnc ABI.
        self._feed_diag_mirror = [0] * 6
        from firedancer_tpu.tango.rings import cnc_diag_cap

        self._feed_diag_ok = cnc_diag_cap() >= 16
        # Native bulk drain (native/verify_drain.cc): one C call per batch
        # round replaces the per-frag Python poll/parse/copy loop (~30 us
        # per txn measured; the loop is the host-side throughput cap,
        # microbench.py ring_tile_hop). Requires the single-in-link tpu
        # path; per-frag semantics (parse errors, HA dedup, diag
        # counters) are preserved — parse is differentially fuzz-tested
        # against ballet/txn.py.
        self._nd = False
        self._jnp = None
        self._feed = False
        from firedancer_tpu.ballet.txn import MAX_SIG_CNT

        nd_ok = (backend in ("tpu", "cpu") and native_drain
                 and in_link is not None and batch >= MAX_SIG_CNT)
        if nd_ok:
            # batch >= MAX_SIG_CNT guarantees every parseable txn fits a
            # fresh batch; smaller batches fall back to the Python path,
            # which oracles outsized multisig txns instead of dropping.
            # backend='cpu' additionally needs the native verifier: the
            # drained staging layout feeds fd_ed25519_cpu_verify_batch
            # directly (one C call per batch — the per-frag Python loop
            # was the replay gate's 30x cap).
            from firedancer_tpu.ballet.ed25519 import native as _ed_native

            nd_ok = backend == "tpu" or _ed_native.available()
        if feed:
            if not nd_ok:
                # A feeder that silently fell back to the per-frag loop
                # would report legacy throughput as fd_feed numbers;
                # run_pipeline's routing checks the same preconditions
                # and picks the legacy runner instead of ever hitting
                # this.
                raise ValueError(
                    "feed=True requires the native drain path (cpu|tpu "
                    "backend, a single in_link, batch >= MAX_SIG_CNT, "
                    "and the native verifier for backend='cpu')"
                )
            self._feed_setup(feed_slots)
        elif nd_ok:
            self._nd_setup()
        if backend == "tpu":
            import jax.numpy as jnp

            self._jnp = jnp
            if mesh_devices:
                # Data-parallel verify over a device mesh: the ring
                # pipeline stays host-side, the batch axis shards over
                # 'dp' (parallel/mesh.py) — XLA inserts the collectives.
                # The shim is unchanged: the sharded step returns one
                # global statuses array whose .is_ready()/np.asarray
                # surface matches the single-device path.
                self.fl_shards = [
                    flight.tile_lane(wksp,
                                     f"{self.flight_label}.shard{i}")
                    for i in range(mesh_devices)
                ]
            # fd_engine registry resolution: build + pre-warm the
            # engine (compile the fixed (batch, max_msg_len) shape now
            # so the run loop never stalls on first-flush compilation;
            # rlc additionally warms its per-lane fallback graph). The
            # warm can take minutes (cold jit, or even a compile-cache
            # LOAD on a small host); in the supervised path worker.py's
            # boot-heartbeat thread keeps the cnc alive throughout, so
            # the wedge detector does not fire on a compiling tile.
            # Per-engine compile accounting (mode x B x shards x
            # frontend impl) is booked by the registry into the flight
            # compile records and mirrored into this tile's lane below:
            # the respawn-storm class of failure is a COMPILE-TIME
            # pathology, and before fd_flight it was invisible until it
            # had destroyed throughput.
            entry, warmed_now = self._registry.acquire(
                self._engine_spec, warm=True, max_msg_len=max_msg_len)
            self._engine_entry = entry
            self._verify_batch_fn = entry.fn
            if warmed_now:
                self._account_compile(entry.key, entry.compile_s)
                if verify_mode == "rlc":
                    self._account_compile(entry.key + ":fallback",
                                          entry.fallback_compile_s)
        # fd_engine rung scheduler (feed mode): pick the dispatch B from
        # the FD_ENGINE_LADDER rungs by queue depth + deadline slack
        # (disco/engine.py). Needs >= 2 usable rungs at or below the
        # staging batch (arenas are sized to the batch, which always
        # tops the ladder); anything else — including every
        # legacy/non-feed topology — keeps the fixed-B behavior, and
        # FD_ENGINE_SCHED=0 is the bisection hatch.
        self.rung_sched = None
        self.stat_rung_hist: dict = {}
        self._rung_entries: dict = {}
        self._rung_last = 0
        if self._feed and flags.get_bool("FD_ENGINE_SCHED"):
            rungs = fd_engine.rung_ladder(cap=batch, floor=MAX_SIG_CNT)
            if mesh_devices:
                # A rung that does not divide the mesh cannot build its
                # sharded engine (the same check the tile's own batch
                # passed) — drop it rather than letting prewarm crash
                # the boot (sync) or silently fail the rung (background).
                rungs = [r for r in rungs if r % mesh_devices == 0]
            if batch not in rungs:
                rungs.append(batch)
                rungs.sort()
            if len(rungs) >= 2:
                cost = None
                if backend == "tpu":
                    # Per-rung engines: registry entries (cost model =
                    # each rung's measured service EMA) + background
                    # prewarm of the non-primary rungs, so a rung
                    # switch picks up a WARM engine instead of paying
                    # a mid-run compile (a cold rung falls back to the
                    # primary engine at dispatch).
                    self._rung_entries = {
                        r: self._registry.entry(
                            self._engine_spec.with_batch(r))
                        for r in rungs
                    }
                    ents = self._rung_entries

                    def cost(r, _e=ents):
                        return _e[r].service_est_ns()

                    self._registry.prewarm_ladder(
                        [self._engine_spec.with_batch(r)
                         for r in rungs if r != batch],
                        max_msg_len=max_msg_len)
                self.rung_sched = fd_engine.RungScheduler(
                    rungs, self.max_wait_ns, cost_ns=cost,
                    shards=mesh_devices or 1)
                # ONE flush policy object: the stager's verdict calls
                # go through the scheduler's embedded AdaptiveFlush, so
                # the property-tested decide()/due() surface and the
                # shipped wiring share state (hwm clock hardening
                # included) instead of drifting as two instances.
                self.flush_policy = self.rung_sched.flush
                self.fl.set_gauge("rung_cur", rungs[0])
                self._rung_last = rungs[0]
                self.flightrec.record(
                    "rung_ladder", rungs=list(rungs),
                    prewarm=flags.get_str("FD_ENGINE_PREWARM"))
        # fd_soak zero-downtime live reconfig: request_reconfig()
        # validates + parks ONE pending request; _feed_poll drains the
        # inflight window to a barrier and _apply_reconfig swaps the
        # engine/ladder in the dispatch gap — per inflight window,
        # never per pipeline (staging keeps running throughout).
        import threading

        self.mesh_devices = mesh_devices
        self._reconfig_lock = threading.Lock()
        self._reconfig_pending: Optional[dict] = None
        self._reconfig_seq = 0

    # -- fd_flight views: the registry lane is the ONE authority for
    # dispatch/healing stats; these read-only properties keep the
    # long-standing stat_* read surface for monitors and tests. --------

    @property
    def stat_batches(self) -> int:
        return self.fl.get("batches")

    @property
    def stat_lanes(self) -> int:
        return self.fl.get("lanes")

    @property
    def stat_flush_timeout(self) -> int:
        return self.fl.get("flush_timeout")

    @property
    def stat_flush_starved(self) -> int:
        return self.fl.get("flush_starved")

    @property
    def stat_inflight_stall(self) -> int:
        return self.fl.get("inflight_stall")

    @property
    def stat_rlc_fallback(self) -> int:
        return self.fl.get("rlc_fallback")

    @property
    def stat_feed_idle_ns(self) -> int:
        return self.fl.get("feed_idle_ns")

    @property
    def stat_stager_restarts(self) -> int:
        return self.fl.get("stager_restarts")

    @property
    def stat_cpu_failover(self) -> int:
        return self.fl.get("cpu_failover")

    @property
    def stat_quarantined(self) -> int:
        return self.fl.get("quarantined")

    @property
    def stat_quarantine_err_txn(self) -> int:
        return self.fl.get("quarantine_err_txn")

    @property
    def stat_ctl_err(self) -> int:
        return self.fl.get("ctl_err_drop")

    def _xr_batch(self, tsorigs, n: int, verdict: str, device: bool,
                  slot_idx=None, tlanes=None, rung=None,
                  rung_target: int = 0, rung_depth: int = 0) -> None:
        """fd_xray batch-context exemplars: one span per HEAD-SAMPLED
        txn of a dispatched batch, carrying the batch ordinal, engine
        key (mode x B x shards x frontend), flush verdict, and — on a
        sharded mesh — the shard lane the txn's signatures land on.
        One vectorized hash per batch; Python only for the hits."""
        if not self._xr_on or n <= 0:
            return
        ids = np.asarray(tsorigs[:n], np.uint64)
        idxs = np.nonzero(xray.sampled_mask(ids, self._xr_thr))[0]
        if idxs.size == 0:
            return
        now = tempo.tickcount() & 0xFFFFFFFF
        batch_no = self.stat_batches
        shards = len(self.fl_shards)
        lane_start = None
        if shards and tlanes is not None:
            lane_start = np.zeros(n, np.int64)
            np.cumsum(np.asarray(tlanes[:n], np.int64)[:-1],
                      out=lane_start[1:])
        # Shard attribution partitions the DISPATCHED shape: a reduced
        # rung on a mesh engine splits `rung` lanes over the shards,
        # not the tile's staging batch.
        per = ((rung or self.batch) // shards) if shards else 0
        for i in idxs[:16]:
            extra = {
                "batch": batch_no,
                "engine": self._engine_key,
                "verdict": verdict,
                "device": device,
            }
            if slot_idx is not None:
                extra["slot"] = slot_idx
            if rung is not None:
                # fd_engine rung context: the B this batch actually
                # dispatched at, plus the stager's TARGET rung and the
                # queue depth behind that decision — a deadline/starved
                # flush or a cold-rung fallback can dispatch a B other
                # than the target, and the exemplar must not pair one
                # rung with the other's inputs.
                extra["rung"] = rung
                extra["rung_target"] = rung_target
                extra["rung_depth"] = rung_depth
            if lane_start is not None:
                extra["shard"] = int(lane_start[i]) // per
            t = int(ids[i])
            self.xr.record(t, t, now, "head", extra)

    def _xr_trigger(self, trigger: str, tsorigs=None, **extra) -> None:
        """fd_xray tail-trigger event (quarantine / breaker / ctl_err):
        recorded with up to 8 of the affected trace ids so the
        autopsy's exemplar section names transactions, not just
        counters."""
        if not self._xr_on:
            return
        ids = []
        if tsorigs is not None:
            ids = [int(t) for t in np.asarray(tsorigs).ravel()[:8]]
        now = tempo.tickcount() & 0xFFFFFFFF
        self.xr.record(ids[0] if ids else 0, ids[0] if ids else 0, now,
                       trigger,
                       dict(extra, traces=ids, engine=self._engine_key))

    def _account_compile(self, engine: str, seconds: float) -> None:
        """Book one engine (pre)compile into the tile lane. The
        process-level flight compile record was already appended by the
        fd_engine registry's warm pass — this mirror is the per-tile
        accounting (compile counters + the boot flight event)."""
        hit = flight.compile_cache_hit_est(seconds)
        self.fl.inc("compile_cnt")
        self.fl.inc("compile_ns", int(seconds * 1e9))
        if hit:
            self.fl.inc("compile_cache_hit")
        self.flightrec.record("compile", engine=engine,
                              s=round(seconds, 3))

    def _with_live_heartbeat(self, fn):
        """Run a blocking host-side operation inside the RUN loop (where
        worker.py's boot beat no longer covers us) while a daemon thread
        keeps the cnc heartbeat fresh, so supervision can tell 'held /
        busy' from 'wedged'. Used by the fault-injection hold."""
        import threading

        stop = threading.Event()

        def beat():
            while not stop.is_set():
                self.cnc.heartbeat(tempo.tickcount())
                stop.wait(1.0)

        t = threading.Thread(target=beat, daemon=True)
        t.start()
        try:
            return fn()
        finally:
            stop.set()
            t.join(timeout=5.0)

    def _nd_bindings(self) -> None:
        """ctypes bindings + drain scratch shared by the legacy native
        staging path and the fd_feed stager."""
        import ctypes

        from firedancer_tpu.tango.rings import lib as rings_lib
        from firedancer_tpu.tango.rings import verify_drain_abi2

        self._nd_lib = rings_lib()
        self._nd_ct = ctypes
        self._nd_abi2 = verify_drain_abi2()
        # 8 slots: the current drain ABI appends {ctl_err, ctl_err_bytes}
        # at [6]/[7]; a stale .so writes only [0..5] and the pair stays 0.
        self._nd_counters = np.zeros(8, np.uint64)
        self._nd_prev = np.zeros(8, np.uint64)

    def _nd_setup(self) -> None:
        self._nd_bindings()
        b, mtu = self.batch, self.max_msg_len
        self._nd_msgs = np.zeros((b, mtu), np.uint8)
        self._nd_lens = np.zeros(b, np.uint32)
        self._nd_sigs = np.zeros((b, 64), np.uint8)
        self._nd_pubs = np.zeros((b, 32), np.uint8)
        self._nd_pay = np.zeros(b * FD_TPU_MTU, np.uint8)
        self._nd_offs = np.zeros(b, np.uint32)
        self._nd_plens = np.zeros(b, np.uint32)
        self._nd_psigs = np.zeros(b, np.uint64)
        self._nd_tlanes = np.zeros(b, np.uint32)
        self._nd_tsorig = np.zeros(b, np.uint32)
        self._nd_tspub = np.zeros(b, np.uint32)
        self._nd_hash = np.zeros(b, np.uint64)
        self._nd_pay_fill = 0
        self._nd = True

    def _feed_setup(self, feed_slots: Optional[int]) -> None:
        """fd_feed mode: staging slots + stager-thread state. The slot
        arenas replace the single _nd_* staging buffer; the stager is
        started lazily by the first dispatcher poll (construction must
        stay side-effect-free for tiles that are built but never run)."""
        import threading as _threading

        from firedancer_tpu.disco.feed.slots import SlotPool
        from firedancer_tpu.tango.rings import feed_abi_ok

        if not feed_abi_ok():
            # The feeder's staging + completion are built on drain ABI
            # v2 + the bulk publisher; a stale .so must route to the
            # legacy runner (run_pipeline checks this), never half-run.
            raise ValueError(
                "feed=True requires the current native ABI "
                "(fd_verify_drain_abi2 + fd_frag_publish_bulk); "
                "rebuild native/ or run with FD_FEED=0"
            )
        self._nd_bindings()
        n_slots = feed_slots or flags.get_int("FD_FEED_SLOTS")
        self.feed_pool = SlotPool(n_slots, self.batch, self.max_msg_len)
        self._feed_exec = None
        if self.backend == "cpu":
            # Concurrent GIL-releasing native verify calls: the cpu
            # "device" is every core the host can spare, not one
            # serialized C call (the wiredancer shim's multiple DMA
            # slots, in host form).
            from concurrent.futures import ThreadPoolExecutor

            n_thr = flags.get_int("FD_FEED_VERIFY_THREADS")
            if n_thr <= 0:
                n_thr = min(2, os.cpu_count() or 1)
            self._feed_exec = ThreadPoolExecutor(
                max_workers=n_thr,
                thread_name_prefix=f"{self.name}.verify",
            )
            self.inflight_max = max(self.inflight_max, n_thr)
        self._feed = True
        self._feed_started = False
        self._feed_stop = _threading.Event()
        self._feed_thread: Optional[_threading.Thread] = None
        self._feed_slot = None          # current FILLING slot (stager-owned)
        self._feed_idle_mark = 0        # dispatcher idle-window anchor
        # Device->CPU verify failover circuit: consecutive primary-lane
        # errors trip it, the CPU oracle lane serves while it is open,
        # and a half-open probe restores the primary path once the
        # device recovers — device loss costs throughput, not liveness.
        if flags.get_bool("FD_VERIFY_BREAKER"):
            self._breaker = CircuitBreaker(
                threshold=flags.get_int("FD_VERIFY_BREAKER_THRESHOLD"),
                cooldown_ns=flags.get_int(
                    "FD_VERIFY_BREAKER_COOLDOWN_MS") * 1_000_000,
            )
        # Feeder-internal thread supervision (crash-only, like the
        # process supervisor one level up): a dead stager is restarted
        # with exponential backoff instead of taking the whole feeder
        # down; staged slots (READY backlog + the FILLING arena) are
        # preserved across the restart. Beyond the restart budget the
        # feeder fails loudly — a permanently crashing stager is a bug,
        # not an operational fault.
        self._stager_restart_max = flags.get_int("FD_FEED_STAGER_RESTART_MAX")
        self._stager_backoff_ns = flags.get_int(
            "FD_FEED_STAGER_BACKOFF_MS") * 1_000_000
        self._stager_restart_at = 0     # 0 = no restart pending
        self._stager_err_cls: Optional[str] = None
        # Ring-dwell trace span (source publish -> stager drain): the
        # feeder's input-backlog distribution, always-on in the flight
        # registry next to the publish edges.
        if flight.enabled() and flags.get_bool("FD_TRACE_SPANS"):
            self._dwell_span = flight.edge_hist(self.wksp, "verify_drain")
        self._drain_setup()

    def _drain_setup(self) -> None:
        """fd_drain arming (feed mode only): the dedup pre-filter graph
        rides every verify dispatch on the same queue, and its
        novel-mask (+ pack colors under FD_DRAIN_PACK) travels
        downstream in the frag ctl word. Disarms silently — behavior
        then bit-identical to FD_DRAIN=off — when the native .so
        predates fd_frag_publish_bulk_ctl or jax is unavailable."""
        from firedancer_tpu.disco import engine as fd_engine
        from firedancer_tpu.tango import rings

        self._drain = None
        self._drain_fn = None
        self._drain_pack_fn = None
        self._drain_block = 0
        if fd_engine.drain_mode() == "off" or self.out_link is None:
            return
        if not rings.frag_publish_has_ctl():
            return
        try:
            import jax.numpy as jnp

            from firedancer_tpu.disco import drain as drain_mod
        except Exception:
            return
        self._drain_jnp = jnp
        self._drain_mod = drain_mod
        quota = flags.get_int("FD_DRAIN_ROT_QUOTA")
        if quota <= 0:
            # Auto quota: the disco/drain.py eviction proof with the
            # DEFAULT downstream tcache depth. Operators running a
            # deeper dedup tcache must set FD_DRAIN_ROT_QUOTA.
            quota = drain_mod.rot_quota(
                4096, self.out_link.mcache.depth, self.batch)
        self._drain = drain_mod.DrainWindow(
            flags.get_int("FD_DRAIN_FILTER_BITS"), quota)
        self._drain_fn = drain_mod.make_filter_fn()
        if flags.get_bool("FD_DRAIN_PACK"):
            from firedancer_tpu.ballet.pack import CuEstimator
            from firedancer_tpu.ops.pack_gc import (
                H_BITS_DEFAULT,
                MAX_COLORS_DEFAULT,
            )

            self._drain_est = CuEstimator()
            self._drain_pack_fn = drain_mod.make_pack_fn(
                n_colors=min(MAX_COLORS_DEFAULT,
                             drain_mod.MAX_CTL_COLORS),
                h_bits=H_BITS_DEFAULT, cu_cap=12_000_000)

    def _drain_pack_arrays(self, slot):
        """Hashed account-lock arrays for the FD_DRAIN_PACK coloring
        graph, straight off the slot's payload sidecar. Unparseable /
        budget-less rows become lock-free zero-score placeholders (they
        color freely and their colors are ignored downstream — PackTile
        re-parses and validates, so a hint here is never authority)."""
        from firedancer_tpu.ballet.compute_budget import (
            estimate_rewards_and_compute,
        )
        from firedancer_tpu.ballet.pack import PackTxn
        from firedancer_tpu.ballet.txn import MAX_ACCT_CNT
        from firedancer_tpu.ops.pack_gc import PackTxnPad, build_arrays

        txns: list = [PackTxnPad] * self.batch
        for t in range(slot.n_txn):
            off = int(slot.offs[t])
            ln = int(slot.plens[t])
            payload = slot.pay[off:off + ln].tobytes()
            try:
                txn = parse_txn(payload)
            except TxnParseError:
                continue
            rce = estimate_rewards_and_compute(
                txn, payload, lamports_per_signature=5000,
                estimator=self._drain_est)
            if rce is None:
                continue
            rewards, est_cus, _cu_limit = rce
            txns[t] = PackTxn(
                txn_id=t, rewards=rewards, est_cus=est_cus,
                writable=frozenset(
                    txn.account(payload, i)
                    for i in range(txn.acct_cnt) if txn.is_writable(i)),
                readonly=frozenset(
                    txn.account(payload, i)
                    for i in range(txn.acct_cnt)
                    if not txn.is_writable(i)),
            )
        return build_arrays(txns, max_w=MAX_ACCT_CNT, max_r=MAX_ACCT_CNT)

    def _drain_dispatch(self, slot):
        """Ship the fd_drain aux graph for a staged slot right behind
        its verify dispatch (same device queue, one completion sync —
        the PR-13 split-pair discipline). Banks commit immediately: jax
        chains the still-in-flight bank array, so consecutive batches
        filter against each other's inserts with no host sync. Returns
        (novel, colors, block) device handles, or None on any failure —
        which disarms THIS batch only (no claims = all maybe-dup =
        exactly the drain-off behavior) and resets the window to empty
        banks (safe: emptier banks only widen maybe-dup)."""
        jnp = self._drain_jnp
        drain_mod = self._drain_mod
        try:
            from firedancer_tpu.ops.dedup_filter import split_tags

            hi, lo = split_tags(slot.psigs)
            valid = np.zeros(self.batch, np.bool_)
            valid[: slot.n_txn] = True
            bits_a, bits_b = self._drain.banks()
            colors = None
            block = 0
            if self._drain_pack_fn is not None:
                w_idx, r_idx, scores, cus = self._drain_pack_arrays(slot)
                novel, bits_new, _cnt, colors = self._drain_pack_fn(
                    jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid),
                    bits_a, bits_b, jnp.asarray(w_idx),
                    jnp.asarray(r_idx), jnp.asarray(scores),
                    jnp.asarray(cus))
                block = self._drain_block
                self._drain_block = (self._drain_block + 1) \
                    % (drain_mod.CTL_BLOCK_MASK + 1)
            else:
                novel, bits_new, _cnt = self._drain_fn(
                    jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid),
                    bits_a, bits_b)
            self._drain.commit(bits_new)
            return novel, colors, block
        except Exception:
            # A failed aux dispatch may have poisoned the chained bank
            # array: start a fresh window (empty banks claim nothing,
            # so the one-sided contract holds trivially).
            try:
                self._drain = drain_mod.DrainWindow(
                    self._drain.h_bits, self._drain.rot_quota)
            except Exception:
                self._drain = None  # jax gone entirely: stay disarmed
            return None

    def _nd_account(self, il) -> bool:
        """Fold one native drain round's counter deltas into the diag
        counters (parse errors, oversize, CTL_ERR drops, overruns) and
        the chaos audit; returns True when the round crossed an
        overrun. Shared by the legacy staging path and the stager."""
        d = self._nd_counters - self._nd_prev
        self._nd_prev = self._nd_counters.copy()
        if d[1] or d[3]:  # parse errors + oversize -> sv filter diag
            self.cnc.diag_add(CNC_DIAG_SV_FILT_CNT, int(d[1] + d[3]))
            self.cnc.diag_add(CNC_DIAG_SV_FILT_SZ, int(d[4] + d[5]))
        c = chaos.active()
        if d[6]:
            # Producer-flagged CTL_ERR frags dropped at the ctl word
            # (never staged): filtered traffic, and the detection+heal
            # of the chaos ring_ctl_err class.
            self.fl.inc("ctl_err_drop", int(d[6]))
            self.flightrec.record("ctl_err_drop", n=int(d[6]))
            self._xr_trigger("ctl_err", n=int(d[6]))
            self.cnc.diag_add(CNC_DIAG_SV_FILT_CNT, int(d[6]))
            self.cnc.diag_add(CNC_DIAG_SV_FILT_SZ, int(d[7]))
            if c is not None:
                c.on_ctl_err_drop(int(d[6]))
        overrun = False
        if d[2]:
            il.fseq.diag_add(DIAG_OVRNR_CNT, int(d[2]))
            overrun = True
            if c is not None:
                c.on_overrun_observed()
        return overrun

    def poll_inputs(self):
        if self._feed:
            return self._feed_poll()
        if not self._nd:
            return super().poll_inputs()
        il = self.in_link
        ct = self._nd_ct
        room_lanes = self.batch - self._pending_lanes
        if room_lanes <= 0:
            self._dispatch()
            self._complete(block=False)
            return False, False
        lane0 = self._pending_lanes
        seq = ct.c_uint64(il.seq)
        n = self._nd_lib.fd_verify_drain(
            il.mcache._mem, ct.addressof(il.dcache._buf),
            ct.byref(seq),
            self.batch - len(self._pending), room_lanes,
            self.batch, self.max_msg_len,
            self._nd_msgs.ctypes.data + lane0 * self.max_msg_len,
            self._nd_lens.ctypes.data + lane0 * 4,
            self._nd_sigs.ctypes.data + lane0 * 64,
            self._nd_pubs.ctypes.data + lane0 * 32,
            self._nd_pay.ctypes.data + self._nd_pay_fill,
            self._nd_pay.nbytes - self._nd_pay_fill,
            self._nd_offs.ctypes.data, self._nd_plens.ctypes.data,
            self._nd_psigs.ctypes.data,
            self._nd_tlanes.ctypes.data, self._nd_tsorig.ctypes.data,
            *([self._nd_tspub.ctypes.data, self._nd_hash.ctypes.data]
              if self._nd_abi2 else []),
            self._nd_counters.ctypes.data,
        )
        overrun = self._nd_account(il)
        if n <= 0:
            il.seq = seq.value
            if not self._pending and not self._inflight:
                self._acked_seq = il.seq  # everything consumed is done
            return False, overrun
        if not self._pending:
            self._pending_since = tempo.tickcount()
        drain_end = seq.value  # ack target once this round's txns verify
        base = self._nd_pay_fill
        for i in range(n):
            off = base + int(self._nd_offs[i])
            ln = int(self._nd_plens[i])
            payload = self._nd_pay[off : off + ln].tobytes()
            cnt = int(self._nd_tlanes[i])
            # Ack granularity is the drain round: only the round's LAST
            # entry carries the post-round seq — a batch boundary inside
            # the round must not let the ack run past unverified txns.
            seq_end = drain_end if i == n - 1 else 0
            if self.ha_tcache.insert(hash(payload)):
                self.cnc.diag_add(CNC_DIAG_HA_FILT_CNT, 1)
                self.cnc.diag_add(CNC_DIAG_HA_FILT_SZ, ln)
                # Lanes stay staged; completion skips publish (None).
                self._pending.append((None, cnt, 0, seq_end))
            else:
                self._pending.append(
                    (payload, cnt, int(self._nd_tsorig[i]), seq_end)
                )
            self._nd_pay_fill = off + ln
            self._pending_lanes += cnt
        # Advance the consumed-seq marker only AFTER the txns are visible
        # in _pending: the supervisor's quiescence check reads both from
        # another thread, and seq-first would open a consumed-but-unqueued
        # window where the pipeline looks drained and HALT races in.
        il.seq = seq.value
        if self._pending_lanes >= self.batch:
            self._dispatch()
        elif self._ring_starved():
            self._dispatch(force=True)
        self._complete(block=False)
        return True, overrun

    # -- fd_feed: stager thread + slot dispatcher ------------------------

    def _feed_start(self) -> None:
        import threading as _threading

        self._feed_started = True
        self._feed_stager_err: Optional[BaseException] = None

        def _guarded():
            try:
                self._stager_loop()
            except BaseException as e:  # propagate to the dispatcher
                self._feed_stager_err = e

        t = _threading.Thread(
            target=_guarded, name=f"{self.name}.stager", daemon=True
        )
        self._feed_thread = t
        t.start()

    def _stager_supervise(self) -> None:
        """Feeder-internal crash-only supervision of the stager thread:
        a raise out of the stager loop is DETECTED here (the dispatcher
        keeps running — it still retires in-flight batches and ships the
        READY backlog), and the stager is restarted after an
        exponential backoff with jitter. Nothing staged is lost across
        the restart: the READY queue lives in the SlotPool, the FILLING
        arena stays parked in self._feed_slot, and the in-ring cursor
        plus held-back ack cover anything the dead incarnation had
        consumed (the property tests/test_chaos.py pins). Past the
        restart budget the original error is re-raised — the old
        fail-loudly behavior for genuinely broken code."""
        err = self._feed_stager_err
        if err is not None:
            self._feed_stager_err = None
            self.fl.inc("stager_restarts")
            self.flightrec.record("stager_restart",
                                  n=self.stat_stager_restarts,
                                  err=repr(err)[:120])
            c = chaos.active()
            if c is not None and isinstance(err, chaos.ChaosFault):
                c.note(err.cls, "detected")
                self._stager_err_cls = err.cls
            if self.stat_stager_restarts > self._stager_restart_max:
                raise RuntimeError(
                    f"fd_feed stager died {self.stat_stager_restarts} times "
                    f"(> FD_FEED_STAGER_RESTART_MAX="
                    f"{self._stager_restart_max}); giving up"
                ) from err
            # Same backoff law as the process supervisor's tile respawn
            # (feed/policy.respawn_backoff_s), with a thread-scale cap:
            # a stager outage beyond 2 s would blow the flush deadline
            # anyway, so decaying further buys nothing.
            backoff_s = respawn_backoff_s(
                self.stat_stager_restarts,
                self._stager_backoff_ns / 1e9,
                _STAGER_BACKOFF_CAP_S,
                self.rng,
            )
            self._stager_restart_at = (
                tempo.tickcount() + int(backoff_s * 1e9))
            import logging

            logging.getLogger("firedancer_tpu.disco.feed").warning(
                "fd_feed stager died (%r); restart %d/%d in %.1f ms",
                err, self.stat_stager_restarts, self._stager_restart_max,
                backoff_s * 1e3,
            )
            return
        if (
            self._stager_restart_at
            and not self._feed_stop.is_set()
            and (self._feed_thread is None
                 or not self._feed_thread.is_alive())
            and tempo.tickcount() >= self._stager_restart_at
        ):
            self._stager_restart_at = 0
            self._feed_start()
            if self._stager_err_cls is not None:
                c = chaos.active()
                if c is not None:
                    c.note(self._stager_err_cls, "healed")
                self._stager_err_cls = None

    def _stager_drain(self, slot) -> int:
        """One fd_verify_drain round into `slot` at its current fill
        cursors. Per-txn bookkeeping stays in the slot's numpy sidecar
        arrays (offs converted to absolute arena offsets) — the only
        per-txn Python here is the HA-tcache insert of the drain's FNV
        tag. Returns staged txn count; updates diag counters."""
        il = self.in_link
        c = chaos.active()
        if c is not None:
            # Injection points, both state-clean (before the C call, so
            # a raise leaves no half-booked slot): scheduled stager
            # death, and the consumer-side cursor rewind that produces
            # a deterministic overrun on the next poll.
            c.stager_round_hook()
            c.overrun_rewind(il)
        if il.xq is not None:
            # The STAGER thread is the one writer of the feeder's
            # in-edge rx row (VerifyTile.housekeep deliberately skips
            # the base _xq_housekeep — a tile-thread write here would
            # break the row's single-writer contract), so depth is
            # sampled per drain round alongside the dwell below.
            il.xq.sample_depth(il.mcache.seq_next() - il.seq)
        ct = self._nd_ct
        k0 = slot.n_txn
        seq = ct.c_uint64(il.seq)
        n = self._nd_lib.fd_verify_drain(
            il.mcache._mem, ct.addressof(il.dcache._buf),
            ct.byref(seq),
            self.batch - k0, self.batch - slot.n_lane,
            self.batch, self.max_msg_len,
            slot.msgs.ctypes.data + slot.n_lane * self.max_msg_len,
            slot.lens.ctypes.data + slot.n_lane * 4,
            slot.sigs.ctypes.data + slot.n_lane * 64,
            slot.pubs.ctypes.data + slot.n_lane * 32,
            slot.pay.ctypes.data + slot.pay_fill,
            slot.pay.nbytes - slot.pay_fill,
            slot.offs.ctypes.data + k0 * 4,
            slot.plens.ctypes.data + k0 * 4,
            slot.psigs.ctypes.data + k0 * 8,
            slot.tlanes.ctypes.data + k0 * 4,
            slot.tsorigs.ctypes.data + k0 * 4,
            slot.tspubs.ctypes.data + k0 * 4,
            slot.hashes.ctypes.data + k0 * 8,
            self._nd_counters.ctypes.data,
        )
        self._nd_account(il)
        if n <= 0:
            il.seq = seq.value  # consumed-but-unstageable (errors) frags
            if (
                slot.n_txn == 0 and not self._inflight
                and self.feed_pool.idle()
            ):
                # Everything consumed is fully handled: nothing staged in
                # this slot, no READY backlog, nothing on the device. New
                # staged work can only come from THIS thread, so the ack
                # fast-path cannot race a dispatch.
                self._acked_seq = il.seq
            return 0
        now = tempo.tickcount()
        if k0 == 0:
            slot.t_first = now  # deadline anchor: first STAGED txn
        # Ring dwell (producer publish -> this drain) of the round's
        # oldest frag: the feeder's input-backlog gauge (reported as
        # stage latency). tspub is a 32-bit tick; xray.dwell32 recovers
        # the modular difference (exact across any number of 2^32 ns
        # clock wraps — tests/test_clock_wrap.py) and rejects absurd
        # dwells (> ~4 s) as wrap artifacts. Dwell is NOT folded into
        # the flush deadline: with a backlog the next round fills the
        # batch in O(ms) anyway, and turning old-but-plentiful input
        # into partial flushes would trade fill ratio for nothing.
        dwell = xray.dwell32(now, int(slot.tspubs[k0]))
        if dwell >= 0:
            if len(self.stat_ring_dwell_ns) < 65536:
                self.stat_ring_dwell_ns.append(dwell)
            if self._dwell_span is not None:
                self._dwell_span.observe(dwell)
            if il.xq is not None:
                # fd_xray queue row for the feeder's in-edge: the same
                # round-oldest dwell the verify_drain stage reports.
                il.xq.observe_dwell(dwell)
        # Offsets came back relative to the round's arena base; make
        # them absolute so the completion's bulk publish can read every
        # round of this slot with one base pointer.
        slot.offs[k0 : k0 + n] += slot.pay_fill
        # HA dedup on the drain's whole-payload FNV tags — the only
        # per-txn Python in the feeder (~1 us/txn); duplicates keep
        # their staged lanes but are masked out of the publish.
        ha_filt_cnt = 0
        ha_filt_sz = 0
        hashes = slot.hashes[k0 : k0 + n].tolist()
        insert = self.ha_tcache.insert
        for i, h in enumerate(hashes):
            if insert(h):
                k = k0 + i
                slot.ha_mask[k] = True
                ha_filt_cnt += 1
                ha_filt_sz += int(slot.plens[k])
        if ha_filt_cnt:
            self.cnc.diag_add(CNC_DIAG_HA_FILT_CNT, ha_filt_cnt)
            self.cnc.diag_add(CNC_DIAG_HA_FILT_SZ, ha_filt_sz)
        if c is not None:
            # slot_corrupt injection: flip one staged MESSAGE byte of a
            # txn from this round (lanes started at the pre-round
            # n_lane). The sidecar payload stays pristine — sigverify
            # must fail exactly that txn and the pool must carry on.
            c.post_stage_hook(slot, k0, n, lane0=slot.n_lane)
        last = k0 + n - 1
        slot.pay_fill = int(slot.offs[last]) + int(slot.plens[last])
        slot.n_lane += int(slot.tlanes[k0 : k0 + n].sum())
        slot.n_txn += n
        slot.drain_end = seq.value
        # Consumed-seq marker only AFTER the txns are visible in the
        # slot (n_txn above): the quiescence check and the ack fast
        # path read both from other threads, and seq-first would open a
        # consumed-but-invisible window where the pipeline looks
        # drained while staged txns exist.
        il.seq = seq.value
        return n

    def _stager_loop(self) -> None:
        """fd_feed stager: drain the in-ring into slot arenas and hand
        full (or flush-due partial) slots to the dispatcher. Everything
        per-frag — seqlock'd ring drain, parse, payload copy, HA dedup —
        lives here, OFF the dispatch thread; the drain itself is one
        GIL-releasing C call per round."""
        pool = self.feed_pool
        il = self.in_link
        idle_spins = 0
        while not self._feed_stop.is_set():
            slot = self._feed_slot
            if slot is None:
                slot = pool.acquire(0.05)  # stalls counted by the pool
                if slot is None:
                    continue
                self._feed_slot = slot
            seq_before = il.seq
            n = self._stager_drain(slot)
            # fd_engine rung target: the scheduler's pick (staged lanes
            # + ring backlog + deadline slack) bounds the batch this
            # slot fills toward; self.batch with the scheduler off. Low
            # offered load makes a small rung "full" early (small-rung
            # latency); a deep backlog targets the top rung (big-rung
            # fill efficiency).
            rung = self._sched_rung(slot)
            if slot.n_lane >= rung:
                self._feed_commit(slot, FLUSH_FULL)
                idle_spins = 0
                continue
            if n > 0:
                idle_spins = 0
                continue
            if (slot.n_txn and il.seq == seq_before
                    and self.batch - slot.n_lane < MAX_SIG_CNT
                    and il.mcache.seq_next() > il.seq):
                # Capacity-blocked, not starved: the ring head is a
                # multisig txn that cannot fit the remaining lane room.
                # Ship the slot as effectively-full instead of letting
                # the deadline timer misbook a 25 ms stall per batch.
                self._feed_commit(slot, "capacity")
                idle_spins = 0
                continue
            if slot.n_txn:
                if self._ring_starved():
                    # Held-back acks are about to exhaust the producer's
                    # credits: a partial batch beats a stalled pipeline
                    # (uncounted force, matching the legacy path).
                    self._feed_commit(slot, "ring_starved")
                    continue
                verdict = self.flush_policy.due(
                    tempo.tickcount(), slot.n_lane, rung,
                    slot.t_first, starved=True,
                    device_idle=(not self._inflight
                                 and pool.ready_cnt() == 0),
                    backpressured=self.out_link.fctl.probe(
                        self.out_link.seq) <= 0,
                )
                if verdict is not None:
                    if verdict == FLUSH_DEADLINE:
                        self.fl.inc("flush_timeout")
                    elif verdict == FLUSH_STARVED:
                        self.fl.inc("flush_starved")
                    self.flightrec.record("flush", verdict=verdict,
                                          lanes=slot.n_lane)
                    self._feed_commit(slot, verdict)
                    continue
            # Empty drain round: sleep IMMEDIATELY rather than hot-spin.
            # The feeder works at batch granularity (a cpu batch is
            # ~20 ms of verify), so a 100 us reaction lag is free — while
            # a spinning stager holds the GIL in ~5 ms scheduler quanta
            # and starves the in-process source publisher, which was
            # measured to cost more end-to-end than the device idle it
            # was trying to avoid.
            idle_spins += 1
            time.sleep(20e-6 if idle_spins <= 8 else 100e-6)

    def _sched_rung(self, slot) -> int:
        """Target rung for the batch being staged (stager thread): the
        fd_engine scheduler's pick from staged lanes + ring backlog +
        deadline slack, stamped on the slot for the xray batch-context
        exemplars; self.batch with the scheduler off. Rung changes book
        a flight `rung` event (with the decision inputs) and the
        rung_switches counter, so a sentinel p99 win or regression can
        be attributed to scheduling from the event trail alone."""
        if self.rung_sched is None:
            return self.batch
        il = self.in_link
        backlog = max(0, il.mcache.seq_next() - il.seq)
        # Saturation signal: the ring backlog at half its structural
        # cap means the producer is ahead as fast as the depth-bounded
        # ring can express it — the scheduler drops its latency
        # protections and goes for big-rung fill efficiency.
        rung = self.rung_sched.pick(
            tempo.tickcount(), slot.n_lane, slot.t_first, backlog,
            backlog_full=backlog * 2 >= il.mcache.depth)
        if rung != self._rung_last:
            depth, slack, lanes = self.rung_sched.last_inputs
            self.fl.inc("rung_switches")
            self.fl.set_gauge("rung_cur", rung)
            self.flightrec.record("rung", b=rung, prev=self._rung_last,
                                  depth=depth, slack_ns=slack,
                                  lanes=lanes)
            self._rung_last = rung
        slot.rung = rung
        slot.rung_depth = self.rung_sched.last_inputs[0]
        return rung

    def _feed_commit(self, slot, verdict: str = FLUSH_FULL) -> None:
        slot.flush_verdict = verdict  # fd_xray batch-context exemplars
        self._feed_slot = None
        self.feed_pool.commit(slot)

    def _feed_dispatch(self, slot) -> None:
        """Ship one READY slot to the verify engine and record the
        in-flight batch. The slot stays attached to the batch until it
        retires — the completion publishes straight out of its sidecar
        arrays (fd_frag_publish_bulk) — so the stager refills OTHER
        slots while this one verifies."""
        # fd_engine dispatch rung: the smallest rung covering the
        # staged lanes (engines are compiled per rung; a partial pads
        # up to the rung's shape). A rung whose engine is not WARM yet
        # falls back to the always-warm primary engine rather than
        # stalling the dispatcher on a compile.
        rung = self.batch
        entry = self._engine_entry
        fn = self._verify_batch_fn
        if self.rung_sched is not None:
            rung = self.rung_sched.dispatch_rung(slot.n_lane)
            if self.backend == "tpu" and rung != self.batch:
                e = self._registry.warm_entry(
                    self._engine_spec.with_batch(rung))
                if e is None:
                    rung = self.batch
                else:
                    entry, fn = e, e.fn
        if slot.n_lane < rung:
            # Zero the stale tail rows exactly like _dispatch_py's pad
            # lanes (zero sig/pub/len): a previous batch's leftovers in
            # the arena must never verify — and under rlc they would
            # poison the batch equation into a permanent fallback.
            # Only the rows the chosen rung's engine reads need it.
            slot.lens[slot.n_lane:rung] = 0
            slot.sigs[slot.n_lane:rung] = 0
            slot.pubs[slot.n_lane:rung] = 0
        out = None
        via_device = False
        c = chaos.active()
        now = tempo.tickcount()
        allow = self._breaker is None or self._breaker.allow_device(now)
        fault_cls = None
        if allow:
            try:
                if c is not None:
                    c.verify_dispatch_hook()  # may raise ChaosDeviceLost
                if self.backend == "cpu":
                    from firedancer_tpu.ballet.ed25519 import (
                        native as ed_native,
                    )

                    out = _FutureBatch(self._feed_exec.submit(
                        ed_native.verify_arrays,
                        slot.msgs, slot.lens, slot.sigs, slot.pubs,
                        slot.n_lane,
                    ))
                else:
                    jnp = self._jnp
                    out = fn(
                        jnp.asarray(slot.msgs[:rung]),
                        jnp.asarray(slot.lens[:rung].astype(np.int32)),
                        jnp.asarray(slot.sigs[:rung]),
                        jnp.asarray(slot.pubs[:rung]),
                    )
                via_device = True
            except Exception as e:
                # Device unavailable at dispatch (or the executor
                # refused the batch): feed the breaker and fall through
                # to the CPU oracle lane — the slot is NEVER lost to a
                # dispatch failure, and the loop keeps running.
                if self._breaker is not None:
                    self._breaker.record_error(now)
                if c is not None and isinstance(e, chaos.ChaosFault):
                    c.note(e.cls, "detected")
                    fault_cls = e.cls
        if out is None:
            out = _ReadyBatch(self._verify_slot_cpu(slot))
            self.fl.inc("cpu_failover")
            self.flightrec.record("cpu_failover", lanes=slot.n_lane)
            if fault_cls is not None and c is not None:
                c.note(fault_cls, "healed")
        # fd_drain: the dedup pre-filter (+ optional pack coloring) aux
        # dispatch rides the same round trip — even behind a CPU
        # failover verify, the filter verdict is orthogonal to the
        # verify result.
        drain_out = None
        if self._drain is not None and slot.n_txn:
            drain_out = self._drain_dispatch(slot)
            if drain_out is not None:
                self.fl.inc("drain_batches")
        self._inflight.append(_InflightBatch(
            out=out, todo=[], oversize=[False] * self.batch,
            t_dispatch=tempo.tickcount(), slot=slot, device=via_device,
            rung=rung if self.rung_sched is not None else 0,
            entry=entry if via_device else None,
            drain=drain_out,
        ))
        self.fl.inc("batches")
        self.fl.inc("lanes", slot.n_lane)
        # fd_pod occupancy: the feed path books per-shard lanes too
        # (the legacy dispatchers always did), over the DISPATCHED
        # shape — a reduced rung splits `rung` lanes over the mesh,
        # not the tile's staging batch. The shard rows are what the
        # sentinel's shard-balance SLO and the smoke's 1.5x occupancy
        # gate read.
        self._book_shard_lanes(slot.n_lane, shape=rung)
        ev = {"lanes": slot.n_lane, "device": via_device}
        if self.rung_sched is not None:
            # Per-rung dispatch accounting: the histogram the replay
            # artifact carries (verify_stats.rung_hist) + the registry
            # entry's own dispatch counters.
            self.stat_rung_hist[rung] = self.stat_rung_hist.get(rung, 0) + 1
            if entry is not None:
                entry.note_dispatch(slot.n_lane)
            ev["b"] = rung
        self.flightrec.record("dispatch", **ev)
        self._xr_batch(slot.tsorigs, slot.n_txn, slot.flush_verdict,
                       via_device, slot_idx=slot.idx, tlanes=slot.tlanes,
                       rung=rung if self.rung_sched is not None else None,
                       rung_target=getattr(slot, "rung", 0),
                       rung_depth=getattr(slot, "rung_depth", 0))

    def _verify_slot_cpu(self, slot):
        """The CPU oracle lane over a staged slot: the failover target
        when the device (or verify executor) is gone, and the re-verify
        engine of the poisoned-batch quarantine. Bisection ladder: the
        native batch verifier first; if IT raises, per-lane through the
        pure-Python RFC 8032 oracle — the lane of last resort cannot
        itself be an offload."""
        from firedancer_tpu.ballet.ed25519 import native as ed_native

        if ed_native.available():
            try:
                return np.asarray(ed_native.verify_arrays(
                    slot.msgs, slot.lens, slot.sigs, slot.pubs,
                    slot.n_lane,
                ))
            except Exception:
                pass  # bisect further: per-lane oracle below
        from firedancer_tpu.ballet.ed25519 import oracle as ed_oracle

        out = np.ones(self.batch, np.int32)
        for lane in range(slot.n_lane):
            ln = int(slot.lens[lane])
            out[lane] = ed_oracle.verify(
                slot.msgs[lane, :ln].tobytes(),
                slot.sigs[lane].tobytes(),
                slot.pubs[lane].tobytes(),
            )
        return out

    def _oracle_verify_payload(self, payload: bytes) -> bool:
        """Whole-txn CPU oracle verdict (quarantine lane for batches
        staged outside slot arenas)."""
        try:
            txn = parse_txn(payload)
            items = list(txn.verify_items(payload))
        except TxnParseError:
            return False
        from firedancer_tpu.ballet.ed25519 import native as ed_native

        if ed_native.available():
            try:
                return all(st == 0 for st in ed_native.verify_items(items))
            except Exception:
                pass
        from firedancer_tpu.ballet.ed25519 import oracle as ed_oracle

        return all(
            ed_oracle.verify(msg, sig, pub) == 0 for (sig, pub, msg) in items
        )

    def _quarantine_statuses(self, ib):
        """Poisoned-batch quarantine: the batch's verify raised, so its
        result is untrusted — bisect to the CPU oracle lane and produce
        per-lane statuses in the batch's own layout. Clean txns go on
        to publish normally (an injected/transient backend error loses
        nothing); genuinely bad txns fail here and are published with
        CTL_ERR by the completion path."""
        if ib.slot is not None:
            return self._verify_slot_cpu(ib.slot)
        return self._oracle_statuses_todo(ib.todo)

    def _oracle_statuses_todo(self, todo):
        """Per-lane statuses for a todo-list batch (legacy staging
        layout) from whole-txn CPU oracle verdicts — the quarantine
        re-verify for batches staged outside slot arenas."""
        statuses = np.ones(self.batch, np.int32)
        off = 0
        for payload, cnt, _tsorig, _seq_end in todo:
            ok = payload is None or self._oracle_verify_payload(payload)
            statuses[off:off + cnt] = 0 if ok else 1
            off += cnt
        return statuses

    def _publish_err(self, payload: bytes, sig: int) -> None:
        """Quarantine audit trail: an offending txn goes downstream as a
        CTL_ERR frag — visible on the ring (dedup counts + drops it
        without letting it shadow a valid same-sig txn) instead of
        silently vanishing. Same HALT/backpressure discipline as
        publish_backp."""
        t_stall = 0
        while not self.out_link.can_publish():
            if self.cnc.signal_query() == CNC_HALT:
                return
            if not t_stall:
                t_stall = tempo.tickcount()
            self.cnc.diag_add(CNC_DIAG_BACKP_CNT, 1)
            time.sleep(20e-6)
        if t_stall and self.out_link.xq_tx is not None:
            self.out_link.xq_tx.add_stall(tempo.tickcount() - t_stall)
        self.out_link.publish(payload, sig, ctl=CTL_SOM_EOM | CTL_ERR)
        self.fl.inc("quarantine_err_txn")

    def _publish_feed_batch(self, slot, statuses,
                            quarantined: bool = False,
                            drain=None) -> int:
        """Completion half of the feeder: fold per-lane statuses to
        per-txn verdicts (numpy reduceat over the slot's lane counts)
        and publish every passing, non-HA-duplicate txn downstream with
        ONE bulk native call per credit window. Returns the batch's ack
        target (the in-ring seq after the slot's last drain round).
        quarantined=True (the batch's verify raised and these statuses
        came from the CPU oracle lane) additionally publishes each
        offending txn with CTL_ERR — the audit trail of the quarantine."""
        n = slot.n_txn
        if n == 0:
            return slot.drain_end
        lanes = slot.tlanes[:n].astype(np.int64)
        starts = np.zeros(n, np.int64)
        np.cumsum(lanes[:-1], out=starts[1:])
        bad = (np.asarray(statuses)[: slot.n_lane] != 0).astype(np.int32)
        anybad = np.add.reduceat(bad, starts) > 0
        ha = slot.ha_mask[:n]
        ok = ~anybad & ~ha
        sv = anybad & ~ha
        sv_cnt = int(sv.sum())
        if sv_cnt:
            self.cnc.diag_add(CNC_DIAG_SV_FILT_CNT, sv_cnt)
            self.cnc.diag_add(
                CNC_DIAG_SV_FILT_SZ, int(slot.plens[:n][sv].sum()))
            c = chaos.active()
            if c is not None:
                # slot_corrupt audit: consume corruption records whose
                # txn just failed sigverify (the drop IS the heal).
                c.on_sv_drop(slot.psigs[:n][sv])
            if quarantined:
                for t in np.nonzero(sv)[0]:
                    off_b = int(slot.offs[t])
                    ln = int(slot.plens[t])
                    self._publish_err(
                        slot.pay[off_b:off_b + ln].tobytes(),
                        int(slot.psigs[t]),
                    )
        n_ok = int(ok.sum())
        if not n_ok:
            return slot.drain_end
        # fd_drain claims: fetch the aux graph's novel-mask (+ colors)
        # at completion — dispatched alongside the verify, so this is a
        # ready device array, not a sync. Any fetch failure simply
        # publishes claim-free (all maybe-dup — the off behavior).
        novel_t = None
        colors_t = None
        ctls = None
        block = 0
        if drain is not None:
            try:
                novel_dev, colors_dev, block = drain
                novel_t = np.asarray(novel_dev)[:n]
                if colors_dev is not None:
                    colors_t = np.asarray(colors_dev)[:n].astype(np.int32)
                ctls = self._drain_mod.encode_ctl(
                    CTL_SOM_EOM, novel_t, colors_t, block)
            except Exception:
                novel_t = None
                ctls = None
        mask8 = ok.astype(np.uint8)
        ol = self.out_link
        ct = self._nd_ct
        seqv = ct.c_uint64(ol.seq)
        chunkv = ct.c_uint32(ol.chunk)
        cursor = ct.c_uint32(0)
        bytes_out = np.zeros(1, np.uint64)
        now32 = tempo.tickcount() & 0xFFFFFFFF
        published = 0
        halted = False
        novel_pub = 0
        maybe_pub = 0
        while published < n_ok and not halted:
            # Credit-windowed bulk publish: same fctl discipline as
            # publish_backp (spin through backpressure, drop on HALT),
            # amortized over the window instead of paid per frag.
            t_stall = 0
            while not ol.can_publish():
                if self.cnc.signal_query() == CNC_HALT:
                    halted = True  # drop the rest, like publish_backp
                    break
                if not t_stall:
                    t_stall = tempo.tickcount()
                self.cnc.diag_add(CNC_DIAG_BACKP_CNT, 1)
                time.sleep(20e-6)
            if t_stall and ol.xq_tx is not None:
                ol.xq_tx.add_stall(tempo.tickcount() - t_stall)
            if halted:
                break
            cur0 = cursor.value
            if ctls is not None:
                pub = self._nd_lib.fd_frag_publish_bulk_ctl(
                    ol.mcache._mem, ct.addressof(ol.dcache._buf),
                    ol.dcache.chunk_cnt, ol.mtu,
                    ct.byref(seqv), ct.byref(chunkv),
                    slot.pay.ctypes.data,
                    slot.offs.ctypes.data, slot.plens.ctypes.data,
                    slot.psigs.ctypes.data, slot.tsorigs.ctypes.data,
                    ctls.ctypes.data,
                    mask8.ctypes.data, ct.byref(cursor), n,
                    min(ol.cr_avail, n_ok - published), now32,
                    bytes_out.ctypes.data,
                )
            else:
                pub = self._nd_lib.fd_frag_publish_bulk(
                    ol.mcache._mem, ct.addressof(ol.dcache._buf),
                    ol.dcache.chunk_cnt, ol.mtu,
                    ct.byref(seqv), ct.byref(chunkv),
                    slot.pay.ctypes.data,
                    slot.offs.ctypes.data, slot.plens.ctypes.data,
                    slot.psigs.ctypes.data, slot.tsorigs.ctypes.data,
                    mask8.ctypes.data, ct.byref(cursor), n,
                    min(ol.cr_avail, n_ok - published), now32,
                    bytes_out.ctypes.data,
                )
            ol.seq = seqv.value
            ol.chunk = chunkv.value
            ol.cr_avail = max(0, ol.cr_avail - pub)
            published += pub
            if novel_t is not None:
                # Per-window claim accounting over the cursor range the
                # C call actually examined: only mask-selected lanes in
                # [cur0, cursor) were published (HALT-dropped tails
                # never count — the rotation quota is over PUBLISHES).
                w = slice(cur0, cursor.value)
                novel_pub += int((novel_t[w] & ok[w]).sum())
                maybe_pub += int((~novel_t[w] & ok[w]).sum())
            if pub <= 0:
                break  # defensive: cursor exhausted without publishes
        if novel_t is not None:
            self.fl.inc("drain_novel", novel_pub)
            self.fl.inc("drain_maybe", maybe_pub)
            if self._drain is not None:
                self._drain.note_published(novel_pub)
                if self._drain.maybe_rotate(
                        blocked=chaos.active() is not None):
                    self.fl.inc("drain_rot")
        il = self.in_link
        il.fseq.diag_add(DIAG_PUB_CNT, published)
        il.fseq.diag_add(DIAG_PUB_SZ, int(bytes_out[0]))
        # Stage-latency reservoir (OutLink.publish is bypassed on the
        # bulk path): same Algorithm-R insert per sample, so long-soak
        # percentiles stay run-representative, not warmup-biased.
        ts = slot.tsorigs[:n][ok]
        ts = ts[ts != 0]
        if ts.size:
            lats = (now32 - ts.astype(np.int64)) & 0xFFFFFFFF
            ol.lat_sample_many(lats, ts)
        return slot.drain_end

    # -- fd_soak zero-downtime live reconfig -----------------------------

    def request_reconfig(self, req: dict) -> tuple:
        """Validate + park ONE live-reconfig request (callable from any
        thread); the dispatcher applies it at the next inflight-window
        barrier (_feed_poll -> _apply_reconfig). Returns
        (accepted, detail).

        The request dict: 'ladder' (optional list of rung batch sizes
        — the staging batch is always appended: arenas are sized to
        it, so a swap replaces the ladder BELOW it), 'verify_mode'
        (optional rlc|direct re-resolution), 'env' (optional FD_* flag
        flips the controller has ALREADY exported — FD_FRONTEND_IMPL /
        FD_DECOMPRESS_IMPL / FD_DRAIN — which the barrier apply
        re-resolves through the registry). A request that cannot
        produce a dispatchable configuration is REFUSED here,
        atomically, with the running config untouched: an invalid
        mode/backend combination (rlc on a host backend), a ladder
        with fewer than 2 usable rungs, or a swap already pending (the
        double-swap race — one barrier, one swap)."""
        from firedancer_tpu.disco import engine as fd_engine

        def refuse(reason: str) -> tuple:
            self.fl.inc("reconfig_refused")
            self.flightrec.record("reconfig_refused", reason=reason)
            return False, reason

        if not self._feed:
            return refuse("reconfig requires the fd_feed staging path")
        mode = req.get("verify_mode") or self.verify_mode
        try:
            mode = fd_engine.resolve_verify_mode(
                self.backend, mode, self.mesh_devices)
        except ValueError as e:
            return refuse(str(e))
        ladder = req.get("ladder")
        rungs = None
        if ladder is not None:
            if not flags.get_bool("FD_ENGINE_SCHED"):
                return refuse("ladder swap with FD_ENGINE_SCHED=0")
            try:
                rungs = sorted({int(r) for r in ladder})
            except (TypeError, ValueError):
                return refuse(f"unparseable ladder {ladder!r}")
            rungs = [r for r in rungs if MAX_SIG_CNT <= r <= self.batch]
            if self.mesh_devices:
                rungs = [r for r in rungs
                         if r % self.mesh_devices == 0]
            if self.batch not in rungs:
                rungs.append(self.batch)
                rungs.sort()
            if len(rungs) < 2:
                return refuse(
                    f"ladder {ladder!r} leaves < 2 usable rungs under "
                    f"staging batch {self.batch}")
        with self._reconfig_lock:
            if self._reconfig_pending is not None:
                return refuse(
                    "a reconfig is already pending (one barrier, one "
                    "swap)")
            self._reconfig_seq += 1
            pend = {"seq": self._reconfig_seq, "verify_mode": mode,
                    "env": dict(req.get("env") or {})}
            if rungs is not None:
                pend["ladder"] = rungs
            self._reconfig_pending = pend
        self.flightrec.record("reconfig_request", seq=pend["seq"],
                              mode=mode,
                              ladder=list(rungs) if rungs else None)
        return True, f"pending (seq {pend['seq']})"

    def _apply_reconfig(self) -> None:
        """Swap the engine configuration in the dispatch gap: called by
        the dispatcher ONLY at the inflight-window barrier (zero
        batches in flight), so no dispatch holds an engine across the
        swap and sink continuity is digest-exact by construction —
        staged/READY slots are untouched and simply dispatch on the
        new engines. Old rung engines unreachable under the new
        configuration are retired from the registry."""
        from firedancer_tpu.disco import engine as fd_engine

        with self._reconfig_lock:
            req = self._reconfig_pending
        if req is None:
            return
        t0 = time.perf_counter()
        old_specs = {self._engine_spec}
        if self.rung_sched is not None and self.backend == "tpu":
            old_specs |= {self._engine_spec.with_batch(r)
                          for r in self.rung_sched.rungs}
        mode = req["verify_mode"]
        spec = fd_engine.EngineSpec.for_tile(
            self.backend, mode, self.batch, self.mesh_devices)
        cold_primary = False
        if self.backend == "tpu":
            e = self._registry.warm_entry(spec)
            if e is None:
                # Unwarmed target (the controller prewarms before
                # requesting; this is the cold-swap fallback): one
                # blocking acquire — the barrier already paused
                # dispatch, and stalling here beats dispatching on a
                # half-built engine.
                cold_primary = True
                e, warmed_now = self._registry.acquire(
                    spec, warm=True, max_msg_len=self.max_msg_len)
                if warmed_now:
                    self._account_compile(e.key, e.compile_s)
                    if mode == "rlc":
                        self._account_compile(
                            e.key + ":fallback", e.fallback_compile_s)
            self._engine_entry = e
            self._verify_batch_fn = e.fn
        else:
            self._engine_entry = self._registry.entry(spec)
        self._engine_spec = spec
        self._engine_key = spec.key
        self.verify_mode = mode
        rungs = req.get("ladder")
        if rungs is None and self.rung_sched is not None:
            # Flag-flip-only reconfig under an active scheduler: keep
            # the rung list, rebuild the per-rung engines on the new
            # spec below.
            rungs = list(self.rung_sched.rungs)
        new_specs = {spec}
        if rungs is not None and len(rungs) >= 2:
            cost = None
            if self.backend == "tpu":
                self._rung_entries = {
                    r: self._registry.entry(spec.with_batch(r))
                    for r in rungs
                }
                ents = self._rung_entries

                def cost(r, _e=ents):
                    return _e[r].service_est_ns()

                self._registry.prewarm_ladder(
                    [spec.with_batch(r) for r in rungs
                     if r != self.batch],
                    max_msg_len=self.max_msg_len)
                new_specs |= {spec.with_batch(r) for r in rungs}
            self.rung_sched = fd_engine.RungScheduler(
                rungs, self.max_wait_ns, cost_ns=cost,
                shards=self.mesh_devices or 1)
            self.flush_policy = self.rung_sched.flush
            self.fl.set_gauge("rung_cur", rungs[0])
            self._rung_last = rungs[0]
        retired = 0
        if self.backend == "tpu":
            retired = self._registry.retire(
                [s for s in old_specs if s not in new_specs])
        drain_flip = "FD_DRAIN" in (req.get("env") or {})
        if drain_flip:
            # _drain_setup re-reads drain_mode() and rebuilds (or
            # tears down) the aux graph from scratch — it is the one
            # FD_DRAIN resolution point, so the flip routes through it.
            self._drain_setup()
        with self._reconfig_lock:
            self._reconfig_pending = None
        self.fl.inc("reconfigs")
        self.flightrec.record(
            "reconfig", seq=req["seq"], mode=mode, engine=spec.key,
            rungs=list(rungs) if rungs else None, retired=retired,
            cold_primary=cold_primary, drain=drain_flip,
            barrier_acked=self._acked_seq,
            apply_ms=round((time.perf_counter() - t0) * 1e3, 3))

    def _feed_poll(self):
        """Dispatcher round (the feed-mode poll_inputs): retire one
        completion, ship every READY slot up to the in-flight cap, and
        account device idleness (nothing in flight AND nothing READY =
        the engine is starving — the gauge this subsystem exists to
        drive to zero)."""
        if not self._feed_started:
            self._feed_start()
        self._stager_supervise()
        self._complete(block=False)
        if self._reconfig_pending is not None and not self._inflight:
            # fd_soak live-reconfig barrier: with a swap pending, new
            # dispatches hold until the inflight WINDOW drains (the
            # stager keeps staging — READY slots queue and upstream
            # rings absorb offered load), then the swap happens in the
            # gap. Never a whole-pipeline drain.
            self._apply_reconfig()
        progressed = False
        if self._reconfig_pending is None:
            while len(self._inflight) < self.inflight_max:
                slot = self.feed_pool.pop_ready()
                if slot is None:
                    break
                self._feed_dispatch(slot)
                progressed = True
        now = tempo.tickcount()
        if self.stat_batches and not self._inflight \
                and self.feed_pool.ready_cnt() == 0:
            if self._feed_idle_mark:
                self.fl.inc("feed_idle_ns", now - self._feed_idle_mark)
            self._feed_idle_mark = now
        else:
            self._feed_idle_mark = 0
        if not progressed:
            # Same GIL-citizenship as the stager: the dispatcher has
            # nothing until a slot commits (>= one drain round away) or
            # a device batch completes — don't hot-spin the run loop at
            # the source publisher's expense. Completions of an ALREADY
            # in-flight batch are polled on a shorter nap.
            time.sleep(50e-6 if self._inflight else 100e-6)
        return progressed, False

    def _publish_feed_diag(self) -> None:
        """Publish the tile's flight-registry lane (breaker gauges,
        slot stalls, and every dispatch/healing counter) to shared
        memory, and keep the legacy CNC_DIAG_FEED_* mirror for the
        16-slot cnc ABI (crash-surviving, read by old tooling)."""
        if self._feed:
            # Pool-owned stat: fold into the lane so the shared row is
            # the one authority (delta via counter semantics: the lane
            # value tracks the pool's monotonically).
            stall = self.feed_pool.slot_stall
            have = self.fl.get("slot_stall")
            if stall > have:
                self.fl.inc("slot_stall", stall - have)
        b = self._breaker
        bstate = b.state if b is not None else "disabled"
        self.fl.set_gauge("breaker_state",
                          flight.BREAKER_STATE_CODE.get(bstate, 3))
        if b is not None:
            self.fl.set_gauge("breaker_trips", b.trips)
            self.fl.set_gauge("breaker_reprobes", b.reprobes)
            cur = (b.state, b.trips, b.reprobes)
            if cur != self._breaker_pub and self._breaker_pub[0] is not None:
                self.flightrec.record("breaker", state=b.state,
                                      trips=b.trips, reprobes=b.reprobes)
                self._xr_trigger("breaker", state=b.state, trips=b.trips,
                                 reprobes=b.reprobes)
            self._breaker_pub = cur
        self.fl.publish()
        for shard in self.fl_shards:
            shard.publish()
        if not self._feed_diag_ok:
            return
        vals = (
            self.stat_batches, self.stat_lanes, self.stat_flush_timeout,
            self.stat_flush_starved,
            self.feed_pool.slot_stall if self._feed else 0,
            self.stat_feed_idle_ns,
        )
        for i, (slot_idx, v) in enumerate(zip(
            (CNC_DIAG_FEED_BATCHES, CNC_DIAG_FEED_LANES,
             CNC_DIAG_FEED_DEADLINE, CNC_DIAG_FEED_STARVED,
             CNC_DIAG_FEED_SLOT_STALL, CNC_DIAG_FEED_IDLE_NS),
            vals,
        )):
            if v != self._feed_diag_mirror[i]:
                self.cnc.diag_add(
                    slot_idx, (v - self._feed_diag_mirror[i]) & _U64
                )
                self._feed_diag_mirror[i] = v

    def _dispatch_native(self, force: bool = False) -> None:
        jnp = self._jnp
        if not self._pending:
            return
        if not force and self._pending_lanes < self.batch:
            return
        while len(self._inflight) >= self.inflight_max:
            self.fl.inc("inflight_stall")
            self._complete(block=True)
        via_device = False
        if self.backend == "cpu":
            # Host path: one synchronous C call over the staged rows —
            # no copies (the buffers are free to reuse once it returns).
            from firedancer_tpu.ballet.ed25519 import native as ed_native

            try:
                out = _ReadyBatch(ed_native.verify_arrays(
                    self._nd_msgs, self._nd_lens, self._nd_sigs,
                    self._nd_pubs, self._pending_lanes,
                ))
            except Exception:
                # Verifier raised mid-batch: quarantine inline (per-txn
                # CPU oracle verdicts) instead of killing the tile.
                self.fl.inc("quarantined")
                self.flightrec.record("quarantine",
                                      lanes=self._pending_lanes)
                out = _ReadyBatch(self._oracle_statuses_todo(self._pending))
        else:
            if self._pending_lanes < self.batch:
                # Stale rows from the previous batch must verify as pad
                # lanes (zero sig/pub/len — _dispatch_py's padding), not
                # as leftover signatures: under rlc a stale lane poisons
                # the whole-batch equation into a permanent fallback.
                self._nd_lens[self._pending_lanes:] = 0
                self._nd_sigs[self._pending_lanes:] = 0
                self._nd_pubs[self._pending_lanes:] = 0
            out = self._verify_batch_fn(
                jnp.asarray(self._nd_msgs.copy()),
                jnp.asarray(self._nd_lens.astype(np.int32)),
                jnp.asarray(self._nd_sigs.copy()),
                jnp.asarray(self._nd_pubs.copy()),
            )
            via_device = True
        todo = self._pending
        lanes0 = self._pending_lanes
        self.fl.inc("lanes", self._pending_lanes)
        self._book_shard_lanes(self._pending_lanes)
        self._pending = []
        self._pending_lanes = 0
        self._nd_pay_fill = 0
        self._inflight.append(_InflightBatch(
            out=out, todo=todo, oversize=[False] * self.batch,
            t_dispatch=tempo.tickcount(), device=via_device,
        ))
        self.fl.inc("batches")
        if self._xr_on:
            self._xr_batch(
                np.array([t[2] for t in todo], np.uint64), len(todo),
                FLUSH_FULL if lanes0 >= self.batch else "partial",
                via_device)

    def _ack_inline(self, frag: Frag) -> None:
        """A frag handled to completion inside on_frag (filtered or
        oracle-verified) is ackable immediately — but only when nothing
        older is still staged on the device."""
        if not self._pending and not self._inflight:
            self._acked_seq = frag.seq + 1

    def on_frag(self, frag: Frag, payload: bytes) -> None:
        if frag.ctl & CTL_ERR:
            # Producer-flagged error frag (the Python-path analog of the
            # native drain's ctl word drop): filter, never verify.
            self.fl.inc("ctl_err_drop")
            self.flightrec.record("ctl_err_drop", n=1)
            self._xr_trigger("ctl_err", tsorigs=[frag.tsorig], n=1)
            self.cnc.diag_add(CNC_DIAG_SV_FILT_CNT, 1)
            self.cnc.diag_add(CNC_DIAG_SV_FILT_SZ, len(payload))
            c = chaos.active()
            if c is not None:
                c.on_ctl_err_drop(1)
            self._ack_inline(frag)
            self._flush_if_due()
            return
        try:
            txn = parse_txn(payload)
        except TxnParseError:
            self.cnc.diag_add(CNC_DIAG_SV_FILT_CNT, 1)
            self.cnc.diag_add(CNC_DIAG_SV_FILT_SZ, len(payload))
            self._ack_inline(frag)
            # A stream of filtered frags keeps the drain loop hot (no
            # on_idle): the staged batch's max-wait must be checked here
            # too, or a flood of junk would strand a partial batch.
            self._flush_if_due()
            return
        # High-availability dup filter before paying for the verify
        # (synth-load FD_TCACHE_INSERT ha_tag analog). The tag covers the
        # WHOLE payload, not the signature prefix: this filter runs before
        # sigverify, so a corrupted copy of a pending txn (same signature
        # bytes, flipped payload byte — or vice versa) must not shadow the
        # valid original out of the tcache. Signature-keyed dedup is safe
        # only post-verify (the dedup tile's meta_sig).
        ha_tag = hash(payload)
        if self.ha_tcache.insert(ha_tag):
            self.cnc.diag_add(CNC_DIAG_HA_FILT_CNT, 1)
            self.cnc.diag_add(CNC_DIAG_HA_FILT_SZ, len(payload))
            self._ack_inline(frag)
            self._flush_if_due()  # see TxnParseError path
            return
        items = list(txn.verify_items(payload))
        if self.backend in ("cpu", "oracle"):
            if self.backend == "cpu":
                # Bulk path: the native C++ verifier (>=10k/s/core) when
                # built, else the Python oracle — same status contract,
                # differentially pinned in tests/test_ed25519_cpu.py.
                from firedancer_tpu.ballet.ed25519 import native as ed_native

                statuses = ed_native.verify_items(items)
            else:
                # 'oracle' pins the pure-Python reference — a
                # cross-check lane must never silently dispatch to an
                # out-of-band .so (round-4 advisor finding).
                from firedancer_tpu.ballet.ed25519 import oracle as ed_oracle

                statuses = [ed_oracle.verify(msg, sig, pub)
                            for (sig, pub, msg) in items]
            ok = all(st == 0 for st in statuses)
            self._finish(payload, ok, tsorig=frag.tsorig)
            self._ack_inline(frag)
            return
        if len(items) > self.batch or any(
            len(msg) > self.max_msg_len for (_, _, msg) in items
        ):
            # A txn with more sigs than device lanes, or a message longer
            # than the staging width (can't happen when max_msg_len is
            # the MTU, but don't trust the wire — and never silently
            # truncate a message into a false reject): verify on the
            # CPU fallback, like the native drain's oversize path.
            from firedancer_tpu.ballet.ed25519 import native as ed_native

            ok = all(st == 0 for st in ed_native.verify_items(items))
            self._finish(payload, ok, tsorig=frag.tsorig)
            self._ack_inline(frag)
            return
        if not self._pending:
            self._pending_since = tempo.tickcount()
        self._pending.append((payload, items, frag.tsorig, frag.seq + 1))
        self._pending_lanes += len(items)
        self._flush_if_due()
        self._complete(block=False)

    def _book_shard_lanes(self, n_lane: int, shape: int = 0) -> None:
        """Per-mesh-shard dispatch accounting: shard_map partitions the
        batch axis contiguously over 'dp', so shard i owns lanes
        [i*per, (i+1)*per) — book each shard's slice of the real (non-
        pad) lanes into its flight row. The slices sum to n_lane by
        construction, so the merged (sum-of-shards) snapshot equals
        this tile's own lanes counter (test-pinned). `shape` is the
        dispatched batch when it differs from the staging batch (a
        reduced fd_engine rung on the feed path)."""
        if not self.fl_shards:
            return
        per = (shape or self.batch) // len(self.fl_shards)
        for i, lane in enumerate(self.fl_shards):
            lane.inc("batches")
            lane.inc("lanes", min(max(n_lane - i * per, 0), per))

    def _ring_starved(self) -> bool:
        """The held-back ack cursor is about to exhaust the producer's
        credits: flush now rather than letting max-wait decide — a
        partial batch beats a stalled pipeline."""
        il = self.in_link
        return il is not None and (
            il.seq - self._acked_seq >= max(1, il.mcache.depth - 64)
        )

    def _flush_if_due(self, starved: bool = False) -> None:
        """Dispatch a staged batch when it is full, when the held-back
        ack cursor is about to starve the producer's credits, or when
        the adaptive policy says so (deadline expiry, or starved input
        with an idle device — disco/feed/policy.py). Called from every
        path that can make progress without going idle (frag drain,
        filtered frags, housekeeping), so a continuous input stream can
        never strand a partial batch (round-2 ADVICE finding). In feed
        mode the stager owns flushing; this is a no-op."""
        if self._feed or not self._pending:
            return
        if self._pending_lanes >= self.batch:
            self._dispatch()
            return
        if self._ring_starved():
            self._dispatch(force=True)
            return
        verdict = self.flush_policy.due(
            tempo.tickcount(), self._pending_lanes, self.batch,
            self._pending_since, starved=starved,
            device_idle=not self._inflight,
            # The housekeep-refreshed gauge, not a fresh fseq probe:
            # this runs per frag on the Python path.
            backpressured=bool(self.out_link.fctl.in_backpressure)
            if self.out_link else False,
        )
        if verdict == FLUSH_DEADLINE:
            self.fl.inc("flush_timeout")
            self.flightrec.record("flush", verdict=verdict,
                                  lanes=self._pending_lanes)
            self._dispatch(force=True)
        elif verdict == FLUSH_STARVED:
            self.fl.inc("flush_starved")
            self.flightrec.record("flush", verdict=verdict,
                                  lanes=self._pending_lanes)
            self._dispatch(force=True)
        # FLUSH_FULL is unreachable here: the lanes >= batch case
        # dispatched above, and this method is single-threaded.

    def on_idle(self) -> None:
        if self._inflight:
            self._complete(block=False)
        self._flush_if_due(starved=True)

    def housekeep(self, now: int) -> None:
        # Publish the VERIFIED cursor, not the consumed one: a crash
        # between consume and verify-complete must leave the frags
        # re-readable for the respawned worker (crash-only recovery).
        # Flow control self-heals: held-back credits return as batches
        # complete, and the max-wait flush bounds how long a partial
        # batch can hold them. Everything else (out-link credit refresh,
        # backpressure diag mirror, on_housekeep's max-wait backstop)
        # must still run — the base housekeep minus the in-link fseq
        # publication, which is replaced by the verified cursor above.
        self._beat(now)
        for il in self.in_links:
            il.fseq.update(min(self._acked_seq, il.seq))
        self._publish_unacked()
        self._publish_feed_diag()
        self._housekeep_out()
        self.on_housekeep()

    def _publish_unacked(self) -> None:
        unacked = 0
        for il in self.in_links:
            unacked += max(0, il.seq - self._acked_seq)
        if unacked != self._last_unacked:
            self.cnc.diag_add(
                CNC_DIAG_UNACKED, (unacked - self._last_unacked) & _U64
            )
            self._last_unacked = unacked

    def on_housekeep(self) -> None:
        # The housekeeping interval is the latency backstop when the tile
        # sits in the frag-drain fast path and never goes idle.
        if self._inflight:
            self._complete(block=False)
        self._flush_if_due()

    def on_halt(self) -> None:
        # Drain device work so no async computation outlives the tile;
        # results are published best-effort (publish_backp drops on HALT).
        if self._feed:
            # Stop the stager first (it owns the in-ring cursor), then
            # flush everything it staged: the leftover FILLING slot,
            # every READY slot, and all in-flight batches.
            self._feed_stop.set()
            if self._feed_thread is not None:
                self._feed_thread.join(timeout=10.0)
            slot = self._feed_slot
            if slot is not None:
                if slot.n_txn:
                    self._feed_commit(slot, "halt")
                else:
                    # An empty FILLING slot must return to FREE, or the
                    # pool-integrity audit (slots_leaked) reads a
                    # phantom leak at every shutdown.
                    self._feed_slot = None
                    self.feed_pool.release(slot)
            while True:
                s = self.feed_pool.pop_ready()
                if s is None:
                    break
                self._feed_dispatch(s)
            self._complete(block=True, drain_all=True)
            if self._feed_exec is not None:
                self._feed_exec.shutdown(wait=True)
            self._publish_feed_diag()
            return
        if self._pending and (self.backend == "tpu" or self._nd):
            self._dispatch(force=True)
        self._complete(block=True, drain_all=True)

    # -- async offload shim ----------------------------------------------

    def _dispatch(self, force: bool = False) -> None:
        if self._nd:
            self._dispatch_native(force)
        else:
            self._dispatch_py(force)
        if self._hold_s and not self._held and self._inflight:
            # Fault-injection hold (see __init__): gauge first, so the
            # supervisor-side observer is guaranteed to see the staged
            # work before the window closes. Heartbeats stay live so
            # the wedge detector doesn't race the test's fault hook for
            # the kill.
            self._held = True
            self._publish_unacked()
            self.cnc.diag_add(CNC_DIAG_HOLDS, 1)
            self._with_live_heartbeat(lambda: time.sleep(self._hold_s))

    def _dispatch_py(self, force: bool = False) -> None:
        """Ship pending txns to the device as fixed-shape batches without
        waiting for results (jax dispatches asynchronously). Whole txns
        only per batch — a txn's sigs never straddle two batches, so each
        completion is self-contained. Unless force, a trailing partial
        batch stays pending (it ships on batch-full or max-wait)."""
        jnp = self._jnp
        while self._pending and (force or self._pending_lanes >= self.batch):
            # Txns stay in _pending until the in-flight record exists: the
            # supervisor's quiescence check reads `_pending or _inflight`
            # from another thread, and a batch held only in locals would be
            # invisible to it — HALT could race in and drop the batch.
            take = 0
            flat = []
            for _, items, _, _ in self._pending:
                if len(flat) + len(items) > self.batch:
                    break
                flat.extend(items)
                take += 1
            todo = [
                (payload, len(items), tsorig, seq_end)
                for payload, items, tsorig, seq_end in self._pending[:take]
            ]
            # Back-pressure the shim, not the device: cap in-flight batches
            # (wiredancer polls the DMA fill level, wd_f1.c:352-358).
            while len(self._inflight) >= self.inflight_max:
                self.fl.inc("inflight_stall")
                self._complete(block=True)
            pad = [(b"\x00" * 64, b"\x00" * 32, b"")] * (self.batch - len(flat))
            msgs, lens, sigs, pubs = _txn_batch_arrays(
                flat + pad, self.max_msg_len
            )
            out = self._verify_batch_fn(
                jnp.asarray(msgs), jnp.asarray(lens), jnp.asarray(sigs),
                jnp.asarray(pubs),
            )
            # A message longer than the staging width cannot be verified on
            # device; fail it rather than trusting a truncated hash.
            oversize = [len(msg) > self.max_msg_len for (_, _, msg) in flat]
            self._inflight.append(_InflightBatch(
                out=out, todo=todo, oversize=oversize,
                t_dispatch=tempo.tickcount(), device=True,
            ))
            self.fl.inc("batches")
            self.fl.inc("lanes", len(flat))
            self._book_shard_lanes(len(flat))
            if self._xr_on:
                self._xr_batch(
                    np.array([t[2] for t in todo], np.uint64), len(todo),
                    FLUSH_FULL if len(flat) >= self.batch else "partial",
                    True,
                    tlanes=np.array([t[1] for t in todo], np.int64))
            del self._pending[:take]
            self._pending_lanes -= len(flat)
            if self._pending:
                self._pending_since = tempo.tickcount()

    def _complete(self, block: bool, drain_all: bool = False) -> None:
        """Retire finished device batches in dispatch order, publishing
        results downstream (the completion half of the wiredancer shim)."""
        while self._inflight:
            ib = self._inflight[0]
            if not block and not ib.out.is_ready():
                return
            c = chaos.active()
            quarantined = False
            try:
                if c is not None:
                    c.verify_complete_hook()  # may raise ChaosBackendError
                statuses = np.asarray(ib.out)  # blocks only if not ready
            except Exception as e:
                # Poisoned batch: the verify raised instead of returning
                # statuses. Quarantine — re-verify the whole batch on
                # the CPU oracle lane (offenders will publish CTL_ERR,
                # clean txns publish normally) — so a backend error
                # fails at most the txns that deserve it and the slot
                # always returns to the pool. Device-lane failures also
                # feed the failover breaker.
                quarantined = True
                self.fl.inc("quarantined")
                self.flightrec.record("quarantine",
                                      err=repr(e)[:120])
                if self._xr_on:
                    ids = (ib.slot.tsorigs[:ib.slot.n_txn]
                           if ib.slot is not None
                           else np.array([t[2] for t in ib.todo],
                                         np.uint64))
                    self._xr_trigger("quarantine", ids,
                                     err=repr(e)[:80])
                if ib.device and self._breaker is not None:
                    self._breaker.record_error(tempo.tickcount())
                fault_cls = (e.cls if isinstance(e, chaos.ChaosFault)
                             else None)
                if c is not None and fault_cls is not None:
                    c.note(fault_cls, "detected")
                statuses = self._quarantine_statuses(ib)
                if c is not None and fault_cls is not None:
                    c.note(fault_cls, "healed")
            if not quarantined:
                if ib.device and self._breaker is not None:
                    self._breaker.record_success()
                if getattr(ib.out, "used_fallback", False):
                    self.fl.inc("rlc_fallback")
                if ib.entry is not None:
                    # fd_engine cost model: feed the engine's service
                    # EMA (dispatch -> clean completion wall time) so
                    # the rung scheduler's slack capping tracks the
                    # device instead of a guess.
                    ib.entry.note_service(
                        tempo.tickcount() - ib.t_dispatch)
            if ib.slot is not None:
                # fd_feed batch: verdicts + publishes straight off the
                # slot's sidecar arrays (one bulk native call). A
                # quarantined batch publishes claim-free: its drain aux
                # dispatch shares the poisoned queue, so its claims are
                # untrusted too (all maybe-dup = exact downstream).
                batch_ack = self._publish_feed_batch(
                    ib.slot, statuses, quarantined=quarantined,
                    drain=None if quarantined else ib.drain)
            else:
                off = 0
                batch_ack = 0
                for payload, cnt, tsorig, seq_end in ib.todo:
                    batch_ack = max(batch_ack, seq_end)
                    if payload is None:  # HA-filtered post-staging
                        off += cnt
                        continue
                    lane = statuses[off : off + cnt]
                    over = any(ib.oversize[off : off + cnt])
                    ok = cnt > 0 and not over and bool((lane == 0).all())
                    self._finish(payload, ok, tsorig=tsorig)
                    if quarantined and not ok:
                        self._publish_err(payload, meta_sig(payload))
                    off += cnt
            # Pop only AFTER the batch's results are published: the
            # supervisor's quiescence check reads _inflight from another
            # thread, and popping first opens a window where the
            # pipeline looks drained, HALT lands, and publish_backp
            # drops this batch's output.
            self._inflight.pop(0)
            if ib.slot is not None:
                self.feed_pool.release(ib.slot)
            # Batches retire in dispatch order, so the newest seq carried
            # by this batch is now fully verified and ackable; with the
            # device idle, everything consumed is. In feed mode "device
            # idle" must also mean the STAGER holds nothing: frags
            # consumed into a slot but not yet dispatched are exactly
            # the crash window the held-back ack protects (the stager
            # makes staged txns visible — slot.n_txn — BEFORE advancing
            # il.seq, so this check cannot race past them).
            self._acked_seq = max(self._acked_seq, batch_ack)
            if (not self._pending and not self._inflight and self.in_link
                    and (not self._feed or self.feed_pool.idle())):
                self._acked_seq = self.in_link.seq
            if not drain_all:
                return  # retire at most one per call; keep the loop hot

    def _finish(self, payload: bytes, ok: bool, tsorig: int = 0) -> None:
        if not ok:
            self.cnc.diag_add(CNC_DIAG_SV_FILT_CNT, 1)
            self.cnc.diag_add(CNC_DIAG_SV_FILT_SZ, len(payload))
            return
        self.publish_backp(payload, meta_sig(payload), tsorig=tsorig)


class DedupTile(Tile):
    """tcache dedup on the frag meta sig (disco/dedup/fd_dedup.c).

    The hot path is VECTORIZED over the bulk fd_frag_drain rounds
    (round-18, the REPLAY_CPU lever): one C drain call per round, the
    membership test batched through TCache.insert_batch (numpy
    unique/scatter instead of a per-frag Python probe), the CTL_ERR
    and duplicate masks folded with numpy, diag counters published as
    per-round sums, and every surviving frag forwarded with ONE
    fd_frag_publish_bulk call per credit window — the per-frag Python
    (Frag construction, on_frag dispatch, per-frag dcache/mcache
    ctypes round-trips) that made dedup the host pipeline's widest
    per-frag stage is gone from the steady state. on_frag keeps the
    exact legacy semantics for the pure-Python poll path (no native
    .so) and is the behavior oracle the bulk path is content-pinned
    against."""

    name = "dedup"

    def __init__(self, wksp, cnc_name, in_link=None, out_link=None,
                 tcache_depth: int = 4096, in_links=None, **kw):
        # The reference dedup is mux+tcache (dedup/fd_dedup.h:57-80):
        # several verify lanes fan in here via in_links.
        super().__init__(wksp, cnc_name, in_link=in_link, out_link=out_link,
                         in_links=in_links, **kw)
        self.tcache = TCache(tcache_depth)
        # fd_drain consumption: device novel claims arrive in the ctl
        # word (CTL_NOVEL); a claimed frag's dup verdict is owed to the
        # filter (probe skip), the map lookup downgrades to a contract
        # tripwire, and the claim bit is stripped before forwarding
        # (pack color/block bits pass through untouched). The lane rows
        # here are the dedup half of the smoke's probe parity gate:
        # drain_probe_skip + drain_probed == verify's novel + maybe.
        self.fl = flight.tile_lane(wksp, self.flight_label)

    def poll_inputs(self):
        if Tile._bulk_ok is None:
            from firedancer_tpu.tango.rings import native_available

            Tile._bulk_ok = native_available()
        if not Tile._bulk_ok or self.out_link is None:
            return super().poll_inputs()
        progressed = False
        overrun = False
        for il in self.in_links:
            st = self._bulk_state(il)
            ct = st["ct"]
            seq = ct.c_uint64(il.seq)
            ovr0 = int(st["ctr"][1])
            args = [
                il.mcache._mem, ct.addressof(il.dcache._buf),
                ct.byref(seq), self.BULK_FRAGS, st["cap"],
                st["pay"].ctypes.data, st["pay"].nbytes,
                st["offs"].ctypes.data, st["lens"].ctypes.data,
                st["sigs"].ctypes.data, st["ts"].ctypes.data,
                st["seqs"].ctypes.data,
            ]
            if st["has_ctl"]:
                args.append(st["ctls"].ctypes.data)
            if st["has_tspub"]:
                args.append(st["tspubs"].ctypes.data)
            args.append(st["ctr"].ctypes.data)
            n = st["lib"].fd_frag_drain(*args)
            d_ovr = int(st["ctr"][1]) - ovr0
            if d_ovr:
                il.fseq.diag_add(DIAG_OVRNR_CNT, d_ovr)
                overrun = True
            if n > 0:
                self.in_cur = il
                self._dedup_round(il, st, n)
                progressed = True
            # Cursor semantics match the base bulk path: il.seq
            # advances only after the round is fully processed, so a
            # crash mid-round replays it (dedup itself absorbs the
            # replays downstream of a respawn).
            il.seq = seq.value
        return progressed, overrun

    def _dedup_round(self, il, st, n: int) -> None:
        """One vectorized dedup round: masks + counters + bulk publish
        — per-frag semantics (CTL_ERR drop before the tcache insert,
        whole-payload order preserved, tsorig carried through, sampled
        xray dwell) exactly as on_frag, minus the per-frag Python."""
        lens = st["lens"][:n]
        sigs = st["sigs"][:n]
        err = ((st["ctls"][:n] & CTL_ERR) != 0) if st["has_ctl"] \
            else np.zeros(n, np.bool_)
        # CTL_ERR frags (quarantine audit trail) are counted + dropped
        # BEFORE the tcache probe — a poisoned copy must never shadow
        # the valid same-sig txn out of the dedup window — so only the
        # clean frags' sigs enter the batched membership test.
        clean = ~err
        novel = np.zeros(n, np.bool_)
        if st["has_ctl"]:
            from firedancer_tpu.disco.drain import CTL_NOVEL

            novel = ((st["ctls"][:n] & CTL_NOVEL) != 0) & clean
        dup = np.zeros(n, np.bool_)
        if clean.any():
            fn0 = self.tcache.false_novel_cnt
            dup[clean] = self.tcache.insert_batch(
                sigs[clean],
                novel=novel[clean] if novel.any() else None)
            n_novel = int(novel.sum())
            if n_novel:
                self.fl.inc("drain_probe_skip", n_novel)
            self.fl.inc("drain_probed", int(clean.sum()) - n_novel)
            d_fn = self.tcache.false_novel_cnt - fn0
            if d_fn:
                # One-sided contract breach: ledger it loudly (the
                # offending frags already got the exact dup verdict, so
                # correctness held — but a nonzero here means the
                # filter/rotation proof is broken upstream).
                self.fl.inc("drain_false_novel", d_fn)
                self.flightrec.record("drain_false_novel", n=d_fn)
        filt = err | dup
        n_filt = int(filt.sum())
        if n_filt:
            il.fseq.diag_add(DIAG_FILT_CNT, n_filt)
            il.fseq.diag_add(DIAG_FILT_SZ, int(lens[filt].sum()))
        if il.xq is not None and st["has_tspub"]:
            # Stride-sampled queue dwell, same cadence as the per-frag
            # path (every xq_every'th drained frag).
            now32 = tempo.tickcount() & 0xFFFFFFFF
            sel = np.nonzero((il.xq_cnt + 1 + np.arange(n))
                             % il.xq_every == 0)[0]
            il.xq_cnt += n
            for i in sel.tolist():
                tspub = int(st["tspubs"][i])
                if tspub:
                    il.xq.observe_dwell((now32 - tspub) & 0xFFFFFFFF)
        mask8 = (~filt).astype(np.uint8)
        n_ok = int(mask8.sum())
        self.fl.publish()
        if not n_ok:
            return
        # Forward ctl: strip the consumed NOVEL claim, pass the pack
        # color/block hints through to PackTile. Needs the ctl-capable
        # bulk publisher; without it the plain publisher writes ctl=3
        # (colors lost -> PackTile schedules those txns itself — safe).
        ctls_fwd = None
        if st["has_ctl"]:
            from firedancer_tpu.disco.drain import CTL_NOVEL
            from firedancer_tpu.tango.rings import frag_publish_has_ctl

            if frag_publish_has_ctl():
                ctls_fwd = st["ctls"][:n] & np.uint16(0xFFFF ^ CTL_NOVEL)
        ol = self.out_link
        ct = st["ct"]
        seqv = ct.c_uint64(ol.seq)
        chunkv = ct.c_uint32(ol.chunk)
        cursor = ct.c_uint32(0)
        bytes_out = np.zeros(1, np.uint64)
        now32 = tempo.tickcount() & 0xFFFFFFFF
        published = 0
        halted = False
        while published < n_ok and not halted:
            # Credit-windowed bulk publish: publish_backp's fctl
            # discipline (spin through backpressure, drop on HALT),
            # amortized over the window instead of paid per frag.
            t_stall = 0
            while not ol.can_publish():
                if self.cnc.signal_query() == CNC_HALT:
                    halted = True  # drop the rest, like publish_backp
                    break
                if not t_stall:
                    t_stall = tempo.tickcount()
                self.cnc.diag_add(CNC_DIAG_BACKP_CNT, 1)
                time.sleep(20e-6)
            if t_stall and ol.xq_tx is not None:
                ol.xq_tx.add_stall(tempo.tickcount() - t_stall)
            if halted:
                break
            if ctls_fwd is not None:
                pub = st["lib"].fd_frag_publish_bulk_ctl(
                    ol.mcache._mem, ct.addressof(ol.dcache._buf),
                    ol.dcache.chunk_cnt, ol.mtu,
                    ct.byref(seqv), ct.byref(chunkv),
                    st["pay"].ctypes.data,
                    st["offs"].ctypes.data, st["lens"].ctypes.data,
                    st["sigs"].ctypes.data, st["ts"].ctypes.data,
                    ctls_fwd.ctypes.data,
                    mask8.ctypes.data, ct.byref(cursor), n,
                    min(ol.cr_avail, n_ok - published), now32,
                    bytes_out.ctypes.data,
                )
            else:
                pub = st["lib"].fd_frag_publish_bulk(
                    ol.mcache._mem, ct.addressof(ol.dcache._buf),
                    ol.dcache.chunk_cnt, ol.mtu,
                    ct.byref(seqv), ct.byref(chunkv),
                    st["pay"].ctypes.data,
                    st["offs"].ctypes.data, st["lens"].ctypes.data,
                    st["sigs"].ctypes.data, st["ts"].ctypes.data,
                    mask8.ctypes.data, ct.byref(cursor), n,
                    min(ol.cr_avail, n_ok - published), now32,
                    bytes_out.ctypes.data,
                )
            ol.seq = seqv.value
            ol.chunk = chunkv.value
            ol.cr_avail = max(0, ol.cr_avail - pub)
            published += pub
            if pub <= 0:
                break  # defensive: cursor exhausted without publishes
        il.fseq.diag_add(DIAG_PUB_CNT, published)
        il.fseq.diag_add(DIAG_PUB_SZ, int(bytes_out[0]))
        # Stage-latency samples (OutLink.publish is bypassed on the
        # bulk path): vectorized histogram + reservoir, the
        # _publish_feed_batch pattern.
        ts = st["ts"][:n][~filt]
        ts = ts[ts != 0]
        if ts.size:
            lats = (now32 - ts.astype(np.int64)) & 0xFFFFFFFF
            ol.lat_sample_many(lats, ts)

    def on_frag(self, frag: Frag, payload: bytes) -> None:
        from firedancer_tpu.disco.drain import CTL_NOVEL

        if frag.ctl & CTL_ERR:
            # Quarantine audit frags (verify's CTL_ERR offenders) end
            # here: counted + dropped BEFORE the tcache insert — a
            # poisoned copy must never shadow the valid same-sig txn
            # out of the dedup window.
            self.in_cur.fseq.diag_add(DIAG_FILT_CNT, 1)
            self.in_cur.fseq.diag_add(DIAG_FILT_SZ, frag.sz)
            return
        if frag.ctl & CTL_NOVEL:
            # fd_drain claim on the per-frag path: verdict owed to the
            # device filter; the insert keeps exact ring order and the
            # tripwire restores the dup verdict on a contract breach.
            self.fl.inc("drain_probe_skip")
            breach = self.tcache.insert_novel_batch([frag.sig])
            if not breach[0]:
                self.publish_backp(payload, frag.sig, tsorig=frag.tsorig)
                return
            self.fl.inc("drain_false_novel")
            self.flightrec.record("drain_false_novel", n=1)
            self.in_cur.fseq.diag_add(DIAG_FILT_CNT, 1)
            self.in_cur.fseq.diag_add(DIAG_FILT_SZ, frag.sz)
            return
        self.fl.inc("drain_probed")
        if self.tcache.insert(frag.sig):
            self.in_cur.fseq.diag_add(DIAG_FILT_CNT, 1)
            self.in_cur.fseq.diag_add(DIAG_FILT_SZ, frag.sz)
            return
        self.publish_backp(payload, frag.sig, tsorig=frag.tsorig)


class PackTile(Tile):
    """Account-lock conflict scheduling into bank lanes
    (app/frank/fd_frank_pack.c + ballet/pack semantics). Scheduled txns
    are published downstream with the bank index in the high sig bits;
    completion is immediate (the sink stands in for bank execution)."""

    name = "pack"

    def __init__(self, wksp, cnc_name, in_link, out_link, bank_cnt: int = 4,
                 scheduler: str = "greedy", gc_block: int = 1024, **kw):
        from firedancer_tpu.ballet.pack import CuEstimator, Pack

        super().__init__(wksp, cnc_name, in_link=in_link, out_link=out_link, **kw)
        if scheduler not in ("greedy", "gc"):
            raise ValueError(f"unknown pack scheduler {scheduler!r}")
        self.pack = Pack(bank_cnt=bank_cnt)
        self.est = CuEstimator()
        self.bank_cnt = bank_cnt
        # scheduler="gc": block-batched XLA graph coloring (ops/pack_gc,
        # the BASELINE stretch) instead of the streaming CPU greedy heap.
        # Waves are conflict-free parallel batches; txns within a wave
        # spread round-robin over banks. gc_block bounds batching latency.
        self.scheduler = scheduler
        self.gc_block = gc_block
        self._gc_pending: list = []
        self._next_txn_id = 0
        self._payloads: dict = {}
        self._tsorig: dict = {}
        self._rr_bank = 0
        # fd_drain device wave schedules: txns arriving with a ctl
        # color hint accumulate per device block id and publish as the
        # device's waves once the block closes — IF the block passes
        # ballet.pack.validate_schedule AND beats CPU greedy rewards/CU
        # (else exact ledgered fallback to the greedy waves). The lane
        # rows carry the accounting gate: pack_block_device +
        # pack_sched_fallback == blocks scheduled.
        self._dev_block: list = []          # [(color, PackTxn)]
        self._dev_block_id: Optional[int] = None
        self.fl = flight.tile_lane(wksp, self.flight_label)

    def on_frag(self, frag: Frag, payload: bytes) -> None:
        from firedancer_tpu.ballet.pack import PackTxn

        try:
            txn = parse_txn(payload)
        except TxnParseError:
            self.in_cur.fseq.diag_add(DIAG_FILT_CNT, 1)
            return
        writable = frozenset(
            txn.account(payload, i)
            for i in range(txn.acct_cnt)
            if txn.is_writable(i)
        )
        readonly = frozenset(
            txn.account(payload, i)
            for i in range(txn.acct_cnt)
            if not txn.is_writable(i)
        )
        from firedancer_tpu.ballet.compute_budget import (
            estimate_rewards_and_compute,
        )

        rce = estimate_rewards_and_compute(
            txn, payload, lamports_per_signature=5000, estimator=self.est
        )
        if rce is None:
            # Malformed ComputeBudgetProgram instruction: whole txn fails
            # (fd_pack.c:298-299 drops it at insert time).
            self.in_cur.fseq.diag_add(DIAG_FILT_CNT, 1)
            return
        rewards, est_cus, _cu_limit = rce
        if est_cus > self.pack.max_cu_per_bank:
            # Can never fit any bank/wave budget: no scheduler ever picks
            # it (the greedy heap would hold it forever; the GC rounds
            # would re-color it forever). The reference similarly bounds
            # insertable cost. Drop + count.
            self.in_cur.fseq.diag_add(DIAG_FILT_CNT, 1)
            self.in_cur.fseq.diag_add(DIAG_FILT_SZ, len(payload))
            return
        tid = self._next_txn_id
        self._next_txn_id += 1
        pt = PackTxn(
            txn_id=tid,
            rewards=rewards,
            est_cus=est_cus,
            writable=writable,
            readonly=readonly,
        )
        self._payloads[tid] = payload
        self._tsorig[tid] = frag.tsorig
        if self.scheduler == "gc":
            from firedancer_tpu.disco.drain import ctl_block, ctl_color

            color = ctl_color(frag.ctl)
            if color >= 0:
                # fd_drain device color: collect into the current
                # device block (block id changes close the previous
                # one — frags arrive in publish order, so a block's
                # txns are contiguous).
                blk = ctl_block(frag.ctl)
                if self._dev_block_id is not None \
                        and blk != self._dev_block_id:
                    self._close_dev_block()
                self._dev_block_id = blk
                self._dev_block.append((color, pt))
                if len(self._dev_block) >= self.gc_block:
                    self._close_dev_block()
                return
            self._gc_pending.append(pt)
            if len(self._gc_pending) >= self.gc_block:
                self._drain_gc()
            return
        self.pack.insert(pt)
        self._drain()

    def on_idle(self) -> None:
        if self.scheduler == "gc":
            if self._dev_block:
                self._close_dev_block()
            if self._gc_pending:
                self._drain_gc()
            return
        self._drain()

    def _drain_gc(self) -> None:
        """Schedule the pending block on the device scheduler and publish
        wave by wave (waves are admissible parallel batches; the CPU
        Pack/validate_schedule semantics are pinned by tests/test_pack_gc
        and the bench's admissibility gate)."""
        from firedancer_tpu.ops.pack_gc import schedule_block

        # _gc_pending stays populated through the (slow: possible XLA
        # compile) device call and the publishes — the supervisor's
        # quiescence check reads it from another thread, and a batch held
        # only in locals would let HALT race in and drop it (same
        # invariant _dispatch_py documents).
        from firedancer_tpu.ballet.txn import MAX_ACCT_CNT

        txns = list(self._gc_pending)
        # Pinned shapes: one compiled program serves every block size in
        # [1, gc_block] x any account mix (review finding: per-block
        # shape drift recompiled the scan in the hot path).
        waves, leftover = schedule_block(
            txns, pad_to=self.gc_block,
            max_w=MAX_ACCT_CNT, max_r=MAX_ACCT_CNT)
        waves, leftover = self._gate_device_waves(txns, waves, leftover)
        self._publish_waves(waves)
        # CU-capped leftovers stay pending; the next round has fresh wave
        # budgets, so the set strictly shrinks (unschedulably large txns
        # were rejected at insert time).
        self._gc_pending = list(leftover)
        self.fl.publish()

    def _gate_device_waves(self, txns, dev_waves, dev_left):
        """The fd_drain schedule gate: a device-emitted wave schedule
        publishes only if it is ADMISSIBLE (ballet.pack.
        validate_schedule — the exact lock-set authority, immune to the
        device's hash collisions) and at least matches the CPU greedy
        baseline on rewards/CU; otherwise the block falls back to the
        greedy waves with exact accounting (pack_block_device +
        pack_sched_fallback == blocks)."""
        from firedancer_tpu.ballet.pack import validate_schedule
        from firedancer_tpu.disco import drain as drain_mod
        from firedancer_tpu.ops.pack_gc import MAX_COLORS_DEFAULT

        cpu_waves, cpu_left = drain_mod.greedy_waves(
            txns, MAX_COLORS_DEFAULT, 12_000_000)
        if validate_schedule(dev_waves) and drain_mod.device_beats_greedy(
                dev_waves, dev_left, cpu_waves, cpu_left):
            self.fl.inc("pack_block_device")
            self.fl.inc("pack_wave_device", len(dev_waves))
            return dev_waves, dev_left
        self.fl.inc("pack_sched_fallback")
        self.flightrec.record("pack_sched_fallback",
                              txns=len(txns), waves=len(dev_waves))
        return cpu_waves, cpu_left

    def _close_dev_block(self) -> None:
        """Close the current fd_drain device block: reassemble its ctl
        colors into waves, gate them exactly like a locally-scheduled
        block, and publish. Subset safety: a block's waves were colored
        over the whole verify batch, and any subset of an admissible
        wave is still admissible (locks and CU only shrink) — but the
        gate re-validates the arrived subset anyway, never the hint."""
        entries = self._dev_block
        self._dev_block = []
        self._dev_block_id = None
        if not entries:
            return
        waves_map: dict = {}
        for color, pt in entries:
            waves_map.setdefault(color, []).append(pt)
        dev_waves = [waves_map[c] for c in sorted(waves_map)]
        txns = [pt for _color, pt in entries]
        waves, leftover = self._gate_device_waves(txns, dev_waves, [])
        self._publish_waves(waves)
        self._gc_pending.extend(leftover)
        self.fl.publish()

    def _publish_waves(self, waves) -> None:
        for wave in waves:
            for txn in wave:
                # Persistent round-robin: within a wave txns may run in
                # parallel (no conflicts), across waves banks just take
                # the next slot — trickle arrivals (1-txn waves) still
                # spread over all banks.
                bank = self._rr_bank
                self._rr_bank = (self._rr_bank + 1) % self.bank_cnt
                payload = self._payloads.pop(txn.txn_id)
                sig = (bank << 48) | (txn.txn_id & 0xFFFFFFFFFFFF)
                self.publish_backp(payload, sig, count_diag=False,
                                   tsorig=self._tsorig.pop(txn.txn_id, 0))

    def _drain(self) -> None:
        """Schedule as many non-conflicting txns as possible, rotating
        banks after each success; stop after a full cycle of failures."""
        misses = 0
        block_ended = False
        while misses < self.bank_cnt:
            bank = self._rr_bank
            self._rr_bank = (self._rr_bank + 1) % self.bank_cnt
            txn = self.pack.schedule(bank)
            if txn is None:
                misses += 1
                if misses >= self.bank_cnt and not block_ended:
                    # All banks refused. With nothing in flight the only
                    # cause is exhausted per-block CU budgets: in the
                    # reference a new PoH slot resets them; the slice has
                    # no PoH clock, so end the block here to avoid a
                    # permanent scheduling wedge.
                    if (
                        self.pack.pending_cnt() > 0
                        and self.pack.inflight_cnt() == 0
                    ):
                        self.pack.end_block()
                        block_ended = True
                        misses = 0
                continue
            block_ended = False
            misses = 0
            payload = self._payloads.pop(txn.txn_id)
            sig = (bank << 48) | (txn.txn_id & 0xFFFFFFFFFFFF)
            self.publish_backp(payload, sig, count_diag=False,
                               tsorig=self._tsorig.pop(txn.txn_id, 0))
            # Bank execution is immediate in the slice: release locks.
            self.pack.complete(bank, txn.txn_id)


class SinkTile(Tile):
    """Terminal consumer (bank stub): counts everything it receives."""

    name = "sink"

    def __init__(self, wksp, cnc_name, in_link, record_digests: bool = False,
                 **kw):
        super().__init__(wksp, cnc_name, in_link=in_link, **kw)
        self.recv_cnt = 0
        self.recv_sz = 0
        self.bank_hist: dict = {}
        # Optional content audit: sha256 of every received payload, so
        # replay gates can assert the sink saw EXACTLY the expected txns
        # (count equality alone would let compensating errors cancel).
        self.record_digests = record_digests
        self.digests: list = []
        # End-to-end latency samples (ns, 32-bit wrap-safe under ~4.29 s):
        # source tsorig stamp -> sink arrival. Feeds the p50/p99 the bench
        # and replay gate report. Bounded reservoir (algorithm R) so a
        # long soak stays at constant memory.
        self.latencies_ns: list = []
        self.latency_sample_cap = 65536
        self._latency_seen = 0
        # Trace-id audit trail: with record_digests on, the tsorig
        # stamp (the txn's trace id, minted once at source publish) of
        # every received frag — the propagation tests assert these
        # survive the pipeline bit-exactly.
        self.trace_ids: list = []
        # End-to-end trace span: the "sink" edge of the flight registry
        # (always-on log2 histogram; the reservoir below stays for
        # fine-grained percentiles).
        self._e2e_span: Optional[flight.EdgeHist] = None
        if flight.enabled() and flags.get_bool("FD_TRACE_SPANS"):
            self._e2e_span = flight.edge_hist(wksp, "sink")
        # fd_xray e2e exemplar sampler: the sink's head/tail capture
        # closes every sampled txn's span chain (correlated by the
        # deterministic trace-id hash — no coordination with upstream).
        self._xr_ctx: Optional[xray.SpanCtx] = xray.span_ctx("sink")

    def on_frag(self, frag: Frag, payload: bytes) -> None:
        self.recv_cnt += 1
        self.recv_sz += frag.sz
        bank = frag.sig >> 48
        self.bank_hist[bank] = self.bank_hist.get(bank, 0) + 1
        if self.record_digests:
            self.digests.append(_sha256(payload).digest())
            self.trace_ids.append(frag.tsorig)
        if frag.tsorig:
            lat = (tempo.tickcount() - frag.tsorig) & 0xFFFFFFFF
            if self._e2e_span is not None:
                self._e2e_span.observe(lat)
            if self._xr_ctx is not None:
                self._xr_ctx.observe(frag.tsorig,
                                     (frag.tsorig + lat) & 0xFFFFFFFF, lat)
            self._latency_seen += 1
            if len(self.latencies_ns) < self.latency_sample_cap:
                self.latencies_ns.append(lat)
            else:
                j = self.rng.roll(self._latency_seen)
                if j < self.latency_sample_cap:
                    self.latencies_ns[j] = lat
        self.in_cur.fseq.diag_add(DIAG_PUB_CNT, 1)
        self.in_cur.fseq.diag_add(DIAG_PUB_SZ, frag.sz)
        # Checkpoint the cursor WITH the count: if the fseq only moved on
        # housekeep, a sink crash would make the respawned incarnation
        # re-read (and re-count) every frag since the last housekeep —
        # the delivery counters would over-count the unpublished window
        # (round-2 ADVICE finding). Publishing per frag shrinks the
        # replay window to at most the single in-flight frag (a crash
        # between the diag_add above and this store); counting before
        # publishing means the counters can only ever over-count by that
        # one frag, never under-count.
        self.in_cur.fseq.update(frag.seq + 1)
