"""Crash-only process supervisor for the tile pipeline.

The process analog of fdctl run's supervision (src/app/fdctl/run/run.c:
spawn tiles as processes, watch them, restart on failure): each tile is
its own OS process (disco/worker.py) sharing the workspace file; the
supervisor monitors process liveness and cnc heartbeats THROUGH the
workspace, and its recovery policy is crash-only — no in-place repair,
a misbehaving tile is killed and respawned, resuming from its rings'
durable cursors (fseq for consumers, mcache seq for producers).

Where the thread runner (pipeline._run_tiles) can inspect tile objects
for quiescence, the supervisor sees only shared memory: the pipeline is
quiescent when the source process has exited and every link's consumer
cursor (fseq) has caught up to its producer cursor (mcache seq) and
stayed stable across a settle window (covers in-flight verify batches,
whose max-wait flush bounds how long a partial batch may linger).
"""

from __future__ import annotations

import json
import os
import pickle
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from firedancer_tpu import flags
from firedancer_tpu.disco import chaos
# Shared with the feeder's stager-thread supervision (one backoff law,
# two supervision layers); re-exported here as its test-facing home.
from firedancer_tpu.disco.feed.policy import respawn_backoff_s  # noqa: F401
from firedancer_tpu.disco.pipeline import (
    LINKS,
    PipelineResult,
    Topology,
    lane_link,
)
from firedancer_tpu.tango.rings import Cnc, FSeq, MCache, Workspace
from firedancer_tpu.utils.rng import Rng

_U64 = (1 << 64) - 1


def respawn_budget(restarts: int, elapsed_s: float,
                   budget_per_h: Optional[int] = None) -> dict:
    """fd_soak's respawn-rate judgment over either supervision layer
    (tile-process respawns here, stager-thread restarts in the
    feeder): pro-rates FD_SOAK_RESPAWN_BUDGET (restarts per hour)
    over the elapsed window — with a floor of one full budget so a
    compressed smoke lane is judged against at least the hourly
    allowance — and verdicts the observed count against it. A storm
    of individually-successful restarts is a soak failure even though
    each respawn 'worked'; that is exactly the failure mode a
    minutes-scale gate cannot see."""
    if budget_per_h is None:
        budget_per_h = flags.get_int("FD_SOAK_RESPAWN_BUDGET")
    allowed = max(float(budget_per_h),
                  budget_per_h * max(0.0, elapsed_s) / 3600.0)
    return {
        "restarts": int(restarts),
        "elapsed_s": round(float(elapsed_s), 1),
        "budget_per_h": int(budget_per_h),
        "allowed": round(allowed, 2),
        "rate_per_h": round(restarts * 3600.0 / elapsed_s, 2)
        if elapsed_s > 0 else 0.0,
        "ok": int(restarts) <= allowed,
    }


@dataclass
class TileProc:
    name: str
    cmd: List[str]
    proc: subprocess.Popen
    restarts: int = 0


def _spawn(name: str, wksp_path: str, pod_path: str, opts: dict,
           max_ns: int, result_path: str,
           log_dir: str | None = None) -> TileProc:
    cmd = [
        sys.executable, "-m", "firedancer_tpu.disco.worker",
        "--wksp", wksp_path, "--pod", pod_path, "--tile", name,
        "--opts", json.dumps(opts), "--max-ns", str(max_ns),
    ]
    if name == "sink":
        cmd += ["--result", result_path]
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    stderr = None
    if log_dir:
        stderr = open(os.path.join(log_dir, f"{name}.log"), "ab")
    proc = subprocess.Popen(cmd, cwd=repo, stderr=stderr)
    if stderr is not None:
        stderr.close()
    return TileProc(name=name, cmd=cmd, proc=proc)


def run_pipeline_supervised(
    topo: Topology,
    payloads: List[bytes],
    **kwargs,
) -> PipelineResult:
    """Run the replay pipeline with per-tile processes + supervision.

    fault_hook(tiles: dict[name, TileProc], t_elapsed) is called every
    monitor pass — tests use it to murder a tile mid-run and assert the
    crash-only restart heals the pipeline.

    Delivery semantics through a crash window (matching the reference's
    lossy-by-design rings, NOT exactly-once): a respawned consumer
    re-reads from its last PUBLISHED fseq, so frags consumed after the
    final housekeep are reprocessed — duplicates are filtered where a
    downstream dedup exists (verify restarts are healed by the dedup
    tile), and the verify tile holds its fseq back to the last fully
    verified txn so staged-but-unverified work is never lost.

    Returns a PipelineResult whose recv counters come from the sink's
    cnc diag (accumulated in shared memory, surviving sink restarts);
    latency/digests come from the final sink incarnation's result file.
    """
    import shutil

    # FD_SUP_KEEP_LOGS=<dir>: run out of <dir> and keep the per-tile
    # logs + pod + result files after the run (post-mortem debugging of
    # crash/restart scenarios; normally everything is ephemeral).
    keep = flags.get_raw("FD_SUP_KEEP_LOGS")
    if keep:
        os.makedirs(keep, exist_ok=True)
        # A reused keep dir must not leak a previous run's sink result
        # into this run's PipelineResult (the loader is existence-gated).
        stale = os.path.join(keep, "sink.json")
        if os.path.exists(stale):
            os.unlink(stale)
        return _supervised(topo, payloads, keep, **kwargs)
    tmp = tempfile.mkdtemp(prefix="fd_sup_")
    try:
        return _supervised(topo, payloads, tmp, **kwargs)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _supervised(
    topo: Topology,
    payloads: List[bytes],
    tmp: str,
    verify_backend: str = "cpu",
    verify_batch: int = 128,
    verify_max_msg_len: Optional[int] = None,
    bank_cnt: int = 4,
    timeout_s: float = 120.0,
    tcache_depth: int = 4096,
    verify_opts: Optional[dict] = None,
    record_digests: bool = False,
    heartbeat_timeout_s: float = 5.0,
    restart: bool = True,
    fault_hook=None,
    tile_cpus: Optional[List[int]] = None,
    jax_platform: Optional[str] = None,
    stall_timeout_s: float = 300.0,
    boot_grace_s: float = 300.0,
) -> PipelineResult:
    pod = topo.pod
    pod_path = os.path.join(tmp, "topo.pod")
    with open(pod_path, "wb") as f:
        f.write(pod.serialize())
    payloads_path = os.path.join(tmp, "payloads.pkl")
    with open(payloads_path, "wb") as f:
        pickle.dump(list(payloads), f)
    result_path = os.path.join(tmp, "sink.json")

    lanes = pod.query_ulong("firedancer.layout.verify_lane_cnt", 1)
    tile_names = (
        ["replay"]
        + ["verify" if i == 0 else f"verify.v{i}" for i in range(lanes)]
        + ["dedup", "pack", "sink"]
    )
    base_opts = {
        "verify_backend": verify_backend,
        "verify_batch": verify_batch,
        "verify_max_msg_len": verify_max_msg_len,
        "verify_opts": verify_opts or {},
        "tcache_depth": tcache_depth,
        "bank_cnt": bank_cnt,
        "record_digests": record_digests,
        "payloads_path": payloads_path,
        "jax_platform": jax_platform,
    }
    max_ns = int((timeout_s + 30.0) * 1e9)

    def opts_for(i: int) -> dict:
        if not tile_cpus:
            return base_opts
        return dict(base_opts, cpu_idx=tile_cpus[i % len(tile_cpus)])

    tile_opts = {n: opts_for(i) for i, n in enumerate(tile_names)}
    tiles: Dict[str, TileProc] = {
        n: _spawn(n, topo.wksp_path, pod_path, tile_opts[n], max_ns,
                  result_path, log_dir=tmp)
        for n in tile_names
    }

    # Supervisor-side views into the shared rings.
    wksp = Workspace.join(topo.wksp_path)
    link_names = [lane_link(l, 0) for l in LINKS]
    link_names += [lane_link(l, i) for l in ("replay_verify", "verify_dedup")
                   for i in range(1, lanes)]
    links = [
        (MCache(wksp, pod.query_cstr(f"firedancer.{n}.mcache")),
         FSeq(wksp, pod.query_cstr(f"firedancer.{n}.fseq")))
        for n in link_names
    ]
    src_mcaches = [
        MCache(wksp, pod.query_cstr(
            f"firedancer.{lane_link('replay_verify', i)}.mcache"))
        for i in range(lanes)
    ]
    n_payloads = len(payloads)
    cncs = {n: Cnc(wksp, pod.query_cstr(f"firedancer.{n}.cnc"))
            for n in tile_names}

    chaos.init_for_run()  # worker_kill / hb_stall injection (FD_CHAOS)
    from firedancer_tpu.disco import flight, xray
    from firedancer_tpu.disco import sentinel as sentinel_mod

    fr = flight.recorder("supervisor")
    # fd_sentinel: supervised runs are first-class SLO citizens — the
    # worker processes write the same shared registry, so the
    # supervisor-side evaluator sees every edge histogram and heartbeat
    # exactly as the in-process runners do.
    snt = sentinel_mod.start_for_run(wksp, pod)
    t0 = time.perf_counter()
    deadline = t0 + timeout_s
    settle_needed = 5
    settle = 0
    last_cursors = None
    last_beat: Dict[str, tuple] = {}
    total_restarts = 0
    # Respawn backoff policy (crash-only recovery, bounded rate): a
    # crashed tile waits base * 2^(restarts-1) + jitter before its
    # respawn — immediate respawn turned a crash-looping tile into a
    # respawn storm that starved the healthy tiles (and, round 8, never
    # let a cold compile cache fill). The per-tile restart count and
    # the currently-pending backoff are mirrored into the tile's cnc
    # diag so monitors see recovery state through shared memory.
    backoff_base_s = flags.get_int("FD_SUP_BACKOFF_MS") / 1e3
    backoff_max_s = flags.get_int("FD_SUP_BACKOFF_MAX_MS") / 1e3
    backoff_rng = Rng(seq=os.getpid())
    respawn_due: Dict[str, float] = {}   # name -> perf_counter deadline
    backoff_gauge: Dict[str, int] = {}   # name -> ms currently published
    from firedancer_tpu.disco.tiles import (
        CNC_DIAG_BACKOFF_MS,
        CNC_DIAG_RESTARTS,
    )
    from firedancer_tpu.tango.rings import cnc_diag_cap

    diag16 = cnc_diag_cap() >= 16

    def _publish_backoff(name: str, ms: int) -> None:
        if not diag16:
            return
        prev = backoff_gauge.get(name, 0)
        if ms != prev:
            cncs[name].diag_add(CNC_DIAG_BACKOFF_MS, (ms - prev) & _U64)
            backoff_gauge[name] = ms
    # Progress-scaled deadline (round-3 verdict: fixed wall deadlines
    # made the crash tests cry wolf on loaded hosts). The run is
    # aborted only after stall_timeout_s with NO progress, where
    # progress = any ring cursor OR any tile heartbeat advancing; the
    # wall deadline remains as the hard safety cap.
    last_progress_sig = None
    last_progress_at = t0

    try:
        while time.perf_counter() < deadline:
            now = time.perf_counter()
            if now - last_progress_at > stall_timeout_s:
                break  # no cursor/heartbeat movement for stall_timeout_s
            if fault_hook is not None:
                fault_hook(tiles, now - t0)
            c = chaos.active()
            if c is not None:
                # Scheduled worker_kill injection (FD_CHAOS): SIGKILL the
                # verify worker at this monitor-pass ordinal; the crash-only
                # machinery below is the heal under test.
                c.supervisor_hook(tiles)
            # Liveness + heartbeat supervision (crash-only recovery).
            for name, tp in tiles.items():
                due = respawn_due.get(name)
                if due is not None:
                    # Dead, waiting out its respawn backoff.
                    if now < due:
                        continue
                    respawn_due.pop(name)
                    _publish_backoff(name, 0)
                    cncs[name].heartbeat(0)
                    fresh = _spawn(name, topo.wksp_path, pod_path,
                                   tile_opts[name], max_ns, result_path,
                                   log_dir=tmp)
                    fresh.restarts = tp.restarts + 1
                    tiles[name] = fresh
                    total_restarts += 1
                    fr.record("respawn", tile=name, restarts=fresh.restarts)
                    xray.maybe_autopsy(f"crash:{name}", wksp=wksp)
                    last_beat.pop(name, None)
                    continue
                rc = tp.proc.poll()
                if rc == 0:
                    # Clean exit: the source when exhausted (and any tile
                    # that saw HALT). Not a fault — and its heartbeat is
                    # legitimately frozen now, so skip that check too.
                    last_beat.pop(name, None)
                    continue
                dead = rc is not None
                if not dead:
                    hb = cncs[name].heartbeat_query()
                    seen_at, seen_hb = last_beat.get(name, (now, hb))
                    # A worker whose cnc signal is still BOOT gets the
                    # generous boot grace even when its heartbeat has been
                    # seen nonzero: the worker's boot-beat thread CAN stall
                    # for >heartbeat_timeout_s behind a long GIL-holding
                    # compile phase, and killing it there re-pays the whole
                    # compile before the persistent cache entry is ever
                    # written — a respawn storm that never converges (the
                    # round-8 cold-cache hang; the round-3 flake was the
                    # hb==0 variant of the same storm). A genuinely hung
                    # boot is caught by boot_grace_s and the global
                    # no-progress stall timeout.
                    booting = cncs[name].signal_query() == 0  # CNC_BOOT
                    limit = (boot_grace_s if (seen_hb == 0 or booting)
                             else heartbeat_timeout_s)
                    if hb != seen_hb:
                        last_beat[name] = (now, hb)
                    elif now - seen_at > limit:
                        dead = True  # wedged: kill, then crash-only restart
                        tp.proc.kill()
                        tp.proc.wait()
                        last_beat.pop(name, None)
                    else:
                        last_beat.setdefault(name, (now, hb))
                if dead and restart:
                    if tp.proc.poll() is None:
                        tp.proc.kill()
                        tp.proc.wait()
                    if diag16:
                        cncs[name].diag_add(CNC_DIAG_RESTARTS, 1)
                    delay = respawn_backoff_s(
                        tp.restarts + 1, backoff_base_s, backoff_max_s,
                        backoff_rng)
                    if delay > 0.0:
                        # Exponential backoff + jitter per tile: schedule
                        # the respawn instead of spawning in-pass, so a
                        # crash-looping tile is rate-limited and the
                        # backoff is visible in the monitor panel.
                        respawn_due[name] = now + delay
                        _publish_backoff(name, int(delay * 1e3))
                        last_beat.pop(name, None)
                        continue
                    # Zero the stale heartbeat BEFORE respawning: the cnc
                    # still holds the dead incarnation's stamp, and a fresh
                    # worker must get the 4x BOOT grace, not the run-loop
                    # timeout, or slow boots turn into a respawn storm.
                    cncs[name].heartbeat(0)
                    fresh = _spawn(name, topo.wksp_path, pod_path,
                                   tile_opts[name], max_ns, result_path,
                                   log_dir=tmp)
                    fresh.restarts = tp.restarts + 1
                    tiles[name] = fresh
                    total_restarts += 1
                    fr.record("respawn", tile=name, restarts=fresh.restarts)
                    xray.maybe_autopsy(f"crash:{name}", wksp=wksp)
                    last_beat.pop(name, None)
            # Quiescence: source finished publishing (visible in its out
            # rings — source tiles spin until HALT, so process exit can't be
            # the signal) + cursors caught up + stable.
            src_done = sum(mc.seq_next() for mc in src_mcaches) >= n_payloads
            cursors = tuple(
                (mc.seq_next(), fs.query()) for mc, fs in links
            )
            progress_sig = (cursors,
                            tuple(c.heartbeat_query() for c in cncs.values()))
            if progress_sig != last_progress_sig:
                last_progress_sig = progress_sig
                last_progress_at = now
            drained = all(fs >= mc for mc, fs in cursors)
            # A drained pipeline may NOT quiesce while a scheduled
            # supervisor-level chaos fault is still pending: monitor
            # passes keep ticking (supervisor_hook above), so the
            # scheduled ordinal is always reached and the kill fires
            # deterministically on any host speed (the fixed-ordinal
            # wait this replaces made worker_kill@N a race against
            # corpus drain on fast hosts).
            chaos_pending = c is not None and c.supervisor_faults_pending()
            if (src_done and drained and cursors == last_cursors
                    and not chaos_pending):
                settle += 1
                if settle >= settle_needed:
                    break
            else:
                settle = 0
            last_cursors = cursors
            time.sleep(0.05)

    finally:
        # Idempotent, and in the finally on purpose: a raising
        # fault_hook / spawn failure must still stop the poller
        # before teardown can unmap the rows it reads.
        slo_summary = snt.stop() if snt is not None else None
    for name, cnc in cncs.items():
        from firedancer_tpu.disco.tiles import CNC_HALT

        cnc.signal(CNC_HALT)
    join_deadline = time.perf_counter() + 30.0
    for tp in tiles.values():
        try:
            tp.proc.wait(timeout=max(0.1, join_deadline - time.perf_counter()))
        except subprocess.TimeoutExpired:
            tp.proc.kill()
            tp.proc.wait()
    elapsed = time.perf_counter() - t0

    from firedancer_tpu.disco.monitor import snapshot

    diag = snapshot(wksp, pod)
    sink_res = {}
    if os.path.exists(result_path):
        with open(result_path) as f:
            sink_res = json.load(f)
    # Delivery counters come from the pack_sink fseq diag — the sink
    # accumulates them in SHARED memory on every frag, so they survive
    # sink crash-restarts; the result file (latency/digests/bank_hist)
    # only reflects the final sink incarnation and is best-effort.
    from firedancer_tpu.tango.rings import DIAG_PUB_CNT, DIAG_PUB_SZ

    # Verify-tile stats survive worker crashes in the fd_flight shared
    # registry (counters delta-accumulate across tile incarnations);
    # the supervised verify_stats are assembled as a VIEW over it —
    # the round-11 replacement for the hand-built cnc-diag dict, which
    # had room for only six of the feeder gauges. The cnc diag keeps
    # the supervisor-written restart/backoff accounting (it must
    # survive even when the worker never booted far enough to attach
    # its flight lane).
    from firedancer_tpu.disco import flight
    from firedancer_tpu.tango.rings import cnc_diag_cap

    verify_stats = []
    diag16 = cnc_diag_cap() >= 16
    for name in tile_names:
        if not name.startswith("verify"):
            continue
        st = flight.verify_stats_view(wksp, name, verify_batch)
        if st is None:
            continue
        if diag16:
            c = cncs[name]
            st["restarts"] = c.diag(CNC_DIAG_RESTARTS)
            st["backoff_ms"] = c.diag(CNC_DIAG_BACKOFF_MS)
        verify_stats.append(st)

    sink_fseq = FSeq(wksp, pod.query_cstr("firedancer.pack_sink.fseq"))
    res = PipelineResult(
        recv_cnt=sink_fseq.diag(DIAG_PUB_CNT),
        recv_sz=sink_fseq.diag(DIAG_PUB_SZ),
        bank_hist={int(k): v for k, v in
                   (sink_res.get("bank_hist") or {}).items()},
        diag=diag,
        elapsed_s=elapsed,
        latency_p50_ns=sink_res.get("latency_p50_ns", 0),
        latency_p99_ns=sink_res.get("latency_p99_ns", 0),
        sink_digests=[bytes.fromhex(d) for d in sink_res["digests"]]
        if sink_res.get("digests") else None,
        verify_stats=verify_stats,
        slo=slo_summary,
    )
    from firedancer_tpu.disco.pipeline import finish_flight_run

    res.stage_hist = finish_flight_run(wksp, slo_summary)
    # fd_xray: supervised runs read the shared queue region + this
    # process's rings (worker exemplars live in the worker processes;
    # their crash/HALT dumps carry them — the autopsy correlates what
    # the supervisor can see: waterfall, suspects, alerts).
    res.xray = xray.run_summary(
        wksp, alerts=(slo_summary or {}).get("alerts"))
    res.supervisor_restarts = total_restarts  # type: ignore[attr-defined]
    res.tile_restarts = {  # type: ignore[attr-defined]
        name: tp.restarts for name, tp in tiles.items() if tp.restarts
    }
    # The ONE merged flight snapshot of the run: every verify-LANE row
    # — one per worker PROCESS (verify, verify.v1, ...) — rolled up
    # with counter sums (counters delta-accumulate, so the sum over
    # rows IS the pod total; test-pinned in tests/test_sentinel.py).
    # Mesh-shard rows (verify.shardN) are excluded: they mirror lanes
    # the owning tile's row already counts, and folding both in would
    # double-book every dispatched lane.
    ftiles = flight.read_tiles(wksp) or {}
    res.flight_merged = flight.merge_tile_metrics(  # type: ignore[attr-defined]
        [row for label, row in ftiles.items()
         if (label == "verify" or label.startswith("verify."))
         and ".shard" not in label])
    return res
