"""fd_soak — long-horizon soak harness: phase-scripted drifting workload,
resource-growth tripwires, and zero-downtime live reconfig.

A soak is NOT a bench: the question is not "how fast" but "does anything
grow, leak, wedge, or drift after hours under a workload that keeps
changing shape". The harness answers it with four layers:

  plan      build_plan() scripts the run up front, deterministically from
            one seed: per-phase siege profile rotation (the fd_siege
            adversarial vocabulary reused as WORKLOAD shapes), per-phase
            corpus mix (dup/corrupt/parse-err/v0 rates follow the
            profile), drifting offered load, and a chaos schedule that
            fires concurrently with the phases. Same seed -> same phase
            table, same payload schedule, same digest multiset — which is
            what makes the no-reconfig control run comparable.

  source    SoakSourceTile subclasses the replay source with token-bucket
            pacing per phase: the payload INDEX decides the phase (so the
            offered multiset is timing-independent), the phase's rate
            decides how fast the index advances. Phase transitions land
            in phase_log for the judgment layer.

  probes    ResourceProbe samples, on a fixed cadence: tracemalloc heap,
            feed slot-pool occupancy, in-flight window depth, engine-
            registry entry count, and the live fd_sentinel alert totals.
            Least-squares slopes over the full window feed the three
            slope-kind sentinel SLO rows (sentinel.set_slope_source) —
            the resource-growth tripwires: a leak alarms DURING the run,
            not in a post-mortem. ReconfigController is the live control
            channel: SIGHUP or an FD_RECONFIG file touch reads a JSON
            request (ladder / verify_mode / env flips), exports the env,
            and parks it on the verify tile; the dispatcher applies it at
            the next inflight-window barrier — drain-to-barrier per
            inflight window, never per pipeline, zero dropped txns.

  judgment  judge() folds the run into one SOAK_r artifact record
            (metric "soak_run"): per-phase alert attribution + burn-rate
            continuity across phase boundaries, unexplained-alert count
            (alerts whose fault classes the chaos injector did NOT
            inject), slope-vs-budget verdicts + ring high-water marks,
            reconfig trail (applied/refused + events), respawn-rate
            budget (supervisor.respawn_budget), and sink-continuity
            accounting. scripts/fd_soak.py writes it as SOAK_rNN.json —
            an artifact family the sentinel ingests and fd_report renders
            (prediction 14).

Everything here is host-side orchestration — no jax import, no tracing;
fdlint's trace-safety pass has nothing to look at.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import tracemalloc
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Dict, List, Optional, Sequence, Tuple

from firedancer_tpu import flags
from firedancer_tpu.disco import flight, sentinel
from firedancer_tpu.disco.siege import PROFILES
from firedancer_tpu.disco.tiles import ReplayTile
from firedancer_tpu.utils.rng import Rng

SCHEMA_VERSION = 2
METRIC = "soak_run"

# Per-profile workload shape: corpus-mix overrides (mainnet_corpus
# kwargs) + offered-load factor. The siege PROFILES vocabulary reused as
# drifting WORKLOAD shapes on the replay path: dup_storm leans on the
# dedup tcache, malformed_flood on the parse/verify reject path,
# slowloris starves the rings, oversize_abuse stretches payload sizes,
# keyupdate_churn flips the txn-version mix.
PROFILE_MIX: Dict[str, Tuple[Dict[str, float], float]] = {
    "conn_churn": ({}, 1.0),
    "dup_storm": ({"dup_rate": 0.35}, 1.1),
    "malformed_flood": ({"corrupt_rate": 0.12, "parse_err_rate": 0.15},
                        1.2),
    "slowloris": ({}, 0.35),
    "oversize_abuse": ({"max_data_sz": 900}, 0.9),
    "keyupdate_churn": ({"v0_rate": 0.7, "budget_rate": 0.4}, 1.0),
}

# Chaos classes the drift rotation arms, phase-aligned best-effort (the
# schedule is in pass ordinals, so windows are generous): window classes
# only — point classes (stager_kill) belong to the crash_storm profile.
_CHAOS_ROTATION: Tuple[Optional[str], ...] = (
    None, "hb_stall", None, "credit_starve",
)

# Injected fault class -> the SLOs it may legitimately trip: the direct
# sentinel.FAULT_SLO mapping plus known COLLATERAL — a stalled
# heartbeat stalls edge progress too, a killed stager/worker stalls
# both. slo_smoke's chaos expectation set ({tile_heartbeat,
# pipeline_progress}) is this table evaluated over its schedule; an
# alert outside the injected classes' union is UNEXPLAINED and fails
# the soak.
_FAULT_COLLATERAL: Dict[str, Tuple[str, ...]] = {
    "hb_stall": ("tile_heartbeat", "pipeline_progress"),
    "worker_kill": ("tile_heartbeat", "pipeline_progress"),
    "stager_kill": ("tile_heartbeat", "pipeline_progress"),
    "credit_starve": ("pipeline_progress",),
}


@dataclass
class SoakPhase:
    """One scripted phase: payload index range [start_idx, end_idx) at
    `rate` txns/s under `profile`'s corpus mix, with `chaos` armed."""

    idx: int
    name: str
    profile: str
    chaos: Optional[str]
    rate: float                    # offered txns/s (token-bucket pace)
    n_txns: int
    corpus_kw: Dict[str, float] = field(default_factory=dict)
    start_idx: int = 0
    end_idx: int = 0
    n_unique_ok: int = 0           # filled by build_payloads


@dataclass
class SoakPlan:
    seed: int
    phases: Tuple[SoakPhase, ...]
    chaos_schedule: str            # chaos.parse_schedule grammar ("" = off)
    duration_s: float              # scripted wall-clock target
    n_txns: int


def build_plan(seed: Optional[int] = None, n_phases: Optional[int] = None,
               phase_s: Optional[float] = None, rate: float = 100.0,
               profile: str = "drift",
               max_txns: int = 200_000) -> SoakPlan:
    """Script the whole soak deterministically from one seed.

    profile "drift" rotates the siege profiles phase by phase with a
    seeded load drift in [0.6, 1.4]x; "crash_storm" holds a steady
    workload and fires stager_kill points every phase (the respawn-storm
    soak scripts/soak_crash_test.sh runs). Any siege profile name pins
    every phase to that one shape.

    max_txns caps the TOTAL payload schedule (payloads are held in
    memory); when rate * duration exceeds it, per-phase counts scale
    down proportionally — the run simply finishes its script early, and
    duration_s in the artifact records what actually ran.
    """
    seed = flags.get_int("FD_SOAK_SEED") if seed is None else int(seed)
    n_phases = (flags.get_int("FD_SOAK_PHASES") if n_phases is None
                else int(n_phases))
    phase_s = (flags.get_float("FD_SOAK_PHASE_S") if phase_s is None
               else float(phase_s))
    rng = Rng(seed)
    rot0 = rng.roll(len(PROFILES))
    phases: List[SoakPhase] = []
    chaos_parts: List[str] = []
    pos = 0
    for i in range(n_phases):
        if profile == "drift":
            pname = PROFILES[(rot0 + i) % len(PROFILES)]
            chaos_cls = _CHAOS_ROTATION[i % len(_CHAOS_ROTATION)]
        elif profile == "crash_storm":
            pname = "conn_churn"
            chaos_cls = "stager_kill"
        else:
            if profile not in PROFILES:
                raise ValueError(f"unknown soak profile {profile!r}")
            pname = profile
            chaos_cls = None
        mix, factor = PROFILE_MIX[pname]
        drift = 0.6 + 0.8 * rng.float01()   # seeded load drift
        ph_rate = max(1.0, rate * factor * drift)
        n = max(32, int(ph_rate * phase_s))
        if chaos_cls == "stager_kill":
            # Point class: kill attempts, spaced one per phase.
            chaos_parts.append(f"stager_kill@{400 * (i + 1)}")
        elif chaos_cls is not None:
            # Window class in pass ordinals (pass counts are timing-
            # dependent, so the windows are generous; the judgment
            # layer explains alerts by CLASS, not by phase).
            lo = 200 + 5000 * i
            chaos_parts.append(f"{chaos_cls}@{lo}:{lo + 2000}")
        phases.append(SoakPhase(
            idx=i, name=f"p{i:02d}_{pname}", profile=pname,
            chaos=chaos_cls, rate=ph_rate, n_txns=n, corpus_kw=dict(mix)))
        pos += n
    if pos > max_txns:
        scale = max_txns / pos
        pos = 0
        for ph in phases:
            ph.n_txns = max(32, int(ph.n_txns * scale))
            pos += ph.n_txns
    off = 0
    for ph in phases:
        ph.start_idx = off
        off += ph.n_txns
        ph.end_idx = off
    duration = sum(ph.n_txns / ph.rate for ph in phases)
    return SoakPlan(seed=seed, phases=tuple(phases),
                    chaos_schedule=",".join(chaos_parts),
                    duration_s=duration, n_txns=off)


def chaos_env(plan: SoakPlan) -> Dict[str, str]:
    """The FD_CHAOS env triplet that arms the plan's chaos schedule —
    pure; the SCRIPT exports it (slo_smoke precedent), keeping the
    harness free of implicit env mutation at plan time."""
    if not plan.chaos_schedule:
        return {}
    return {
        "FD_CHAOS": "1",
        "FD_CHAOS_SEED": str(plan.seed),
        "FD_CHAOS_SCHEDULE": plan.chaos_schedule,
    }


def build_payloads(plan: SoakPlan,
                   sign_batch_size: int = 4096) -> List[bytes]:
    """Generate the per-phase corpora (seeded per phase off the plan
    seed, mix per profile) and concatenate into the payload schedule.
    Fills each phase's n_unique_ok (the sink-continuity expectation:
    only unique well-formed txns survive dedup+verify)."""
    from firedancer_tpu.disco.corpus import mainnet_corpus

    out: List[bytes] = []
    for ph in plan.phases:
        c = mainnet_corpus(ph.n_txns, seed=plan.seed * 1009 + ph.idx,
                           sign_batch_size=sign_batch_size,
                           **ph.corpus_kw)
        ph.n_unique_ok = c.n_unique_ok
        out.extend(c.payloads)
        # Corpus generation may round counts; keep the index ranges
        # exact so phase boundaries stay payload-index-driven.
        ph.end_idx = len(out)
    start = 0
    for ph in plan.phases:
        ph.start_idx = start
        start = ph.end_idx
        ph.n_txns = ph.end_idx - ph.start_idx
    return out


class SoakSourceTile(ReplayTile):
    """Replay source with the plan's token-bucket pacing: the payload
    index decides the phase (offered multiset timing-independent), the
    phase rate decides how fast the index advances. Phase transitions
    append to phase_log (read by the judgment layer after the run)."""

    name = "replay"

    def __init__(self, wksp, cnc_name, out_links, payloads,
                 phases: Sequence[SoakPhase], **kw):
        super().__init__(wksp, cnc_name, out_links=out_links,
                         payloads=payloads, **kw)
        self.phases = list(phases)
        self.phase_log: List[dict] = []
        self._ph_i = -1
        self._ph_t0 = 0.0
        self._ph_pos0 = 0

    def _current_phase(self) -> Optional[SoakPhase]:
        while (self._ph_i < len(self.phases)
               and (self._ph_i < 0
                    or self.pos >= self.phases[self._ph_i].end_idx)):
            now = time.perf_counter()
            if 0 <= self._ph_i < len(self.phases) and self.phase_log:
                ent = self.phase_log[-1]
                ent["t_end"] = now
                ent["published"] = self.pos - self._ph_pos0
            self._ph_i += 1
            if self._ph_i < len(self.phases):
                ph = self.phases[self._ph_i]
                self._ph_t0 = now
                self._ph_pos0 = self.pos
                self.phase_log.append({
                    "phase": ph.name, "profile": ph.profile,
                    "chaos": ph.chaos, "offered_tps": round(ph.rate, 1),
                    "n_txns": ph.n_txns, "t_start": now,
                })
        if 0 <= self._ph_i < len(self.phases):
            return self.phases[self._ph_i]
        return None

    def step(self) -> None:
        ph = self._current_phase()
        if ph is not None and ph.rate > 0:
            allowed = (time.perf_counter() - self._ph_t0) * ph.rate
            if (self.pos - self._ph_pos0) >= allowed:
                time.sleep(200e-6)   # paced: ahead of the token bucket
                return
        super().step()


def _lsq_slope(pairs: Sequence[Tuple[float, float]]) -> float:
    """Least-squares slope of y over x (x in the caller's unit)."""
    n = len(pairs)
    if n < 2:
        return 0.0
    mx = sum(p[0] for p in pairs) / n
    my = sum(p[1] for p in pairs) / n
    den = sum((p[0] - mx) ** 2 for p in pairs)
    if den <= 0.0:
        return 0.0
    num = sum((p[0] - mx) * (p[1] - my) for p in pairs)
    return num / den


class ResourceProbe:
    """Fixed-cadence resource sampler + the slope source for the three
    slope-kind sentinel SLO rows (resource-growth tripwires).

    Samples: tracemalloc heap KiB, feed slot-pool occupancy, in-flight
    window depth, engine-registry entry count, and the live sentinel
    alert total (per-phase attribution + burn continuity). The probe
    thread ONLY appends to the sample list (GIL-atomic; no cross-thread
    attribute stores) — the blessed-channel discipline ownership.py's
    scan enforces."""

    def __init__(self, wksp, interval_ms: Optional[int] = None):
        self.wksp = wksp
        self.interval_s = max(
            0.02,
            (flags.get_int("FD_SOAK_PROBE_MS") if interval_ms is None
             else int(interval_ms)) / 1e3)
        self.samples: List[dict] = []
        self.tile = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def attach(self, tile) -> None:
        self.tile = tile

    def start(self) -> "ResourceProbe":
        self._thread = threading.Thread(target=self._loop,
                                        name="soak-probe", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _sample(self) -> dict:
        from firedancer_tpu.disco import engine as fd_engine

        row = {"t": time.perf_counter()}
        row["heap_kb"] = (tracemalloc.get_traced_memory()[0] / 1024.0
                          if tracemalloc.is_tracing() else 0.0)
        t = self.tile
        if t is not None and getattr(t, "_feed", False):
            try:
                row["pool_out"] = t.feed_pool.outstanding()
                row["inflight"] = len(t._inflight)
            except Exception:
                pass
        try:
            row["engines"] = fd_engine.registry().entry_count()
        except Exception:
            row["engines"] = 0
        try:
            slos = flight.read_slos(self.wksp) or {}
            row["alerts"] = sum(int(v.get("alerts", 0))
                                for v in slos.values())
        except Exception:
            row["alerts"] = 0
        return row

    def _loop(self) -> None:
        self.samples.append(self._sample())
        while not self._stop.wait(self.interval_s):
            self.samples.append(self._sample())
        self.samples.append(self._sample())

    # -- judgment surfaces -----------------------------------------------

    def source(self) -> dict:
        """The sentinel slope source: growth rates in the slope SLO
        rows' units, over the sample window MINUS the first quarter —
        the warmup discard: startup allocation and first-dispatch
        compiles are one-time transients that a short window's
        least-squares fit would extrapolate into a phantom leak. The
        reported "samples" count is the USED (post-discard) count, so
        the sentinel's MIN_SLOPE_SAMPLES arming threshold applies to
        steady-state evidence only."""
        rows = list(self.samples)
        if len(rows) >= 4:
            cut = rows[0]["t"] + 0.25 * (rows[-1]["t"] - rows[0]["t"])
            rows = [r for r in rows if r["t"] >= cut]
        out = {"samples": len(rows)}
        if len(rows) < 2:
            return out
        t0 = rows[0]["t"]
        mins = [(r["t"] - t0) / 60.0 for r in rows]
        out["heap_kb_min"] = _lsq_slope(
            list(zip(mins, (r["heap_kb"] for r in rows))))
        pool = [(m, float(r["pool_out"]) * 1000.0)
                for m, r in zip(mins, rows) if "pool_out" in r]
        if pool:
            out["pool_milli_min"] = _lsq_slope(pool)
        out["compile_per_hr"] = _lsq_slope(
            list(zip(mins, (float(r.get("engines", 0))
                            for r in rows)))) * 60.0
        return out

    def ring_hwm(self) -> dict:
        rows = list(self.samples)
        return {
            "slot_pool": max((r.get("pool_out", 0) for r in rows),
                             default=0),
            "inflight": max((r.get("inflight", 0) for r in rows),
                            default=0),
        }

    def alerts_between(self, t0: float, t1: float) -> int:
        """Cumulative-alert delta between two wall-clock instants, from
        the nearest samples at-or-before each bound."""
        rows = list(self.samples)

        def at(t: float) -> int:
            v = 0
            for r in rows:
                if r["t"] <= t:
                    v = r.get("alerts", 0)
                else:
                    break
            return v

        return max(0, at(t1) - at(t0))


def _read_request(path: Optional[str]) -> dict:
    if not path:
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            req = json.load(f)
        return req if isinstance(req, dict) else {}
    except (OSError, ValueError):
        return {}


def _export_env(env: Dict[str, object]) -> None:
    """Export the request's FD_* flag flips BEFORE parking the request:
    the barrier apply re-resolves engines/drain through flags.py, so the
    environment must already say the new configuration. (Env WRITES are
    legal outside flags.py — only reads are registry-routed; siege's
    siege_env sets the precedent.)"""
    for k, v in env.items():
        if v is None:
            os.environ.pop(str(k), None)
        else:
            os.environ[str(k)] = str(v)


class ReconfigController:
    """The live-reconfig control channel: SIGHUP (via trigger()) or an
    FD_RECONFIG file mtime change reads a JSON request
    {"ladder": [...], "verify_mode": ..., "env": {...}}, exports the env
    flips, and parks the request on the verify tile; the dispatcher
    applies it at the inflight-window barrier. Every attempt (accepted
    or refused) lands in self.log."""

    def __init__(self, path: Optional[str] = None, poll_s: float = 0.2):
        self.path = path if path is not None else flags.get_str(
            "FD_RECONFIG")
        self.poll_s = poll_s
        self.log: List[dict] = []
        self.tile = None
        self.hup = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def attach(self, tile) -> None:
        self.tile = tile

    def trigger(self) -> None:
        """SIGHUP entry point (signal handlers only call Event.set)."""
        self.hup.set()

    def start(self) -> "ReconfigController":
        self._thread = threading.Thread(target=self._loop,
                                        name="soak-reconfig", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def apply(self, req: dict) -> dict:
        """Export env flips + park the request; one log entry either
        way. Callable directly (tests) or from the poll loop."""
        _export_env(dict(req.get("env") or {}))
        tile = self.tile
        if tile is None:
            ok, detail = False, "no tile attached"
        else:
            ok, detail = tile.request_reconfig(req)
        ent = {"ok": bool(ok), "detail": detail,
               "t": time.perf_counter(),
               "ladder": req.get("ladder"),
               "verify_mode": req.get("verify_mode"),
               "env": sorted(dict(req.get("env") or {}))}
        self.log.append(ent)
        return ent

    def _loop(self) -> None:
        seen = -1.0
        if self.path:
            try:
                seen = os.stat(self.path).st_mtime
            except OSError:
                seen = -1.0
        while not self._stop.wait(self.poll_s):
            fire = self.hup.is_set()
            if self.path:
                try:
                    m = os.stat(self.path).st_mtime
                except OSError:
                    m = None
                if m is not None and m != seen:
                    seen = m
                    fire = True
            if not fire:
                continue
            self.hup.clear()
            req = _read_request(self.path)
            if req:
                self.apply(req)


def run_soak(plan: SoakPlan, *, payloads: Optional[List[bytes]] = None,
             verify_backend: str = "cpu", verify_batch: int = 256,
             tcache_depth: int = 1 << 16,
             timeout_s: Optional[float] = None,
             controller: Optional[ReconfigController] = None,
             probe: Optional[ResourceProbe] = None,
             install_sighup: bool = True,
             record_digests: bool = True,
             workdir: Optional[str] = None):
    """Run the plan through the full feed pipeline with the soak
    instrumentation attached; returns (record, PipelineResult).

    The record is the SOAK_r artifact dict (judge()'s output). The
    PipelineResult rides along for continuity comparison — soak_smoke
    diffs sink_digests against a no-reconfig control run.

    A controller is created automatically when FD_RECONFIG names a
    request file; pass one explicitly to drive reconfigs from a test.
    SIGHUP is installed only from the main thread (signal module
    contract) and restored on exit.

    record_digests=False for hour-scale runs: the sink digest ledger is
    O(txns) host memory — the exact linear growth the heap tripwire
    exists to catch — so long soaks judge continuity by COUNT
    (expected_sink vs received) and leave the digest-multiset diff to
    the compressed smoke, where the ledger is tiny."""
    import tempfile

    from firedancer_tpu.disco.feed.runtime import run_feed_pipeline
    from firedancer_tpu.disco.pipeline import (
        Workspace,
        _make_source_out_links,
        build_topology,
    )

    if payloads is None:
        payloads = build_payloads(plan)
    tmp = workdir or tempfile.mkdtemp(prefix="fd_soak_")
    os.makedirs(tmp, exist_ok=True)
    topo = build_topology(os.path.join(tmp, "soak.wksp"), depth=2048,
                          wksp_sz=1 << 26)
    wksp = Workspace.join(topo.wksp_path)
    src = SoakSourceTile(
        wksp, topo.pod.query_cstr("firedancer.replay.cnc"),
        out_links=_make_source_out_links(wksp, topo.pod),
        payloads=payloads, phases=plan.phases)
    probe = probe or ResourceProbe(wksp)
    if controller is None and flags.get_str("FD_RECONFIG"):
        controller = ReconfigController()

    started_tm = False
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        started_tm = True
    old_hup = None
    if (controller is not None and install_sighup
            and threading.current_thread() is threading.main_thread()):
        try:
            old_hup = signal.signal(
                signal.SIGHUP, lambda *_: controller.trigger())
        except (ValueError, OSError):
            old_hup = None
    sentinel.set_slope_source(probe.source)

    def hook(verify) -> None:
        probe.attach(verify)
        probe.start()
        if controller is not None:
            controller.attach(verify)
            controller.start()

    t0 = time.perf_counter()
    try:
        res = run_feed_pipeline(
            topo, [], verify_backend=verify_backend,
            verify_batch=verify_batch, tcache_depth=tcache_depth,
            timeout_s=(timeout_s if timeout_s is not None
                       else plan.duration_s * 2.0 + 60.0),
            record_digests=record_digests,
            source_tile=src, source_done=src.done, tile_hook=hook)
    finally:
        elapsed = time.perf_counter() - t0
        probe.stop()
        if controller is not None:
            controller.stop()
        sentinel.set_slope_source(None)
        if old_hup is not None:
            try:
                signal.signal(signal.SIGHUP, old_hup)
            except (ValueError, OSError):
                pass
        if started_tm:
            tracemalloc.stop()
    record = judge(plan, res, src, probe, controller, elapsed,
                   backend=verify_backend)
    return record, res


def judge(plan: SoakPlan, res, src: SoakSourceTile,
          probe: ResourceProbe,
          controller: Optional[ReconfigController],
          elapsed_s: float, *, backend: str = "cpu") -> dict:
    """Fold the run into the SOAK_r artifact record — the long-horizon
    judgment layer (see the module docstring for the verdicts)."""
    from firedancer_tpu.disco import supervisor

    vs = (res.verify_stats or [{}])[0]
    slo = res.slo or {"alert_cnt": 0, "alerts": [], "slos": {}}
    alerts = list(slo.get("alerts") or [])
    chaos_snap = vs.get("chaos") or {}
    injected = sorted(
        cls for cls, c in (chaos_snap.get("counters") or {}).items()
        if isinstance(c, dict) and c.get("injected"))
    explained_slos = set()
    for cls in injected:
        explained_slos.update(_FAULT_COLLATERAL.get(cls, ()))
        direct = sentinel.FAULT_SLO.get(cls)
        if direct:
            explained_slos.add(direct)
    unexplained = [
        a for a in alerts
        if not ((set(a.get("fault_classes") or ()) & set(injected))
                or a.get("slo") in explained_slos)
    ]

    # Per-phase attribution + burn continuity: alert deltas inside each
    # phase window, and NO alert within +-2 probe intervals of a phase
    # boundary (a reconfig/profile flip must not cost a burn blip).
    # Probe counters carry totals, not attribution, so a boundary blip
    # is only judged when it CANNOT be chaos: injected windows are
    # scheduled in pass ordinals (timing-dependent) and may legitimately
    # straddle a boundary; an alert any injected class does not explain
    # already fails the unexplained gate above, which owns that case.
    log = [dict(e) for e in src.phase_log]
    t_last = (probe.samples[-1]["t"] if probe.samples
              else time.perf_counter())
    boundaries_clean = True
    blame_blips = bool(unexplained) or not injected
    for i, ent in enumerate(log):
        ent.setdefault("t_end", t_last)
        ent.setdefault("published", ent.get("n_txns", 0))
        ent["alerts"] = probe.alerts_between(ent["t_start"], ent["t_end"])
        ent["duration_s"] = round(ent["t_end"] - ent["t_start"], 3)
        if i > 0 and blame_blips:
            w = 2 * probe.interval_s
            if probe.alerts_between(ent["t_start"] - w,
                                    ent["t_start"] + w):
                boundaries_clean = False
        for k in ("t_start", "t_end"):
            ent[k] = round(ent[k], 3)

    slopes = probe.source()
    budgets = {
        "heap_kb_min": flags.get_int("FD_SLO_HEAP_SLOPE_KB"),
        "pool_milli_min": flags.get_int("FD_SLO_POOL_SLOPE_MILLI"),
        "compile_per_hr": flags.get_int("FD_SLO_COMPILE_SLOPE"),
    }
    armed = slopes.get("samples", 0) >= sentinel.MIN_SLOPE_SAMPLES
    within = all(
        float(slopes.get(k, 0.0)) <= b for k, b in budgets.items()
    ) if armed else True

    restarts = int(vs.get("stager_restarts", 0) or 0)
    restarts += int(getattr(res, "supervisor_restarts", 0) or 0)
    respawn = supervisor.respawn_budget(restarts, elapsed_s)

    applied = int(vs.get("reconfigs", 0) or 0)
    refused = int(vs.get("reconfig_refused", 0) or 0)
    events = list(controller.log) if controller is not None else []

    expected_sink = sum(ph.n_unique_ok for ph in plan.phases)
    recv = int(getattr(res, "recv_cnt", 0) or 0)
    dropped = max(0, expected_sink - recv) if expected_sink else 0
    leaked = int(vs.get("slots_leaked", 0) or 0)

    failures: List[str] = []
    if unexplained:
        failures.append(
            f"{len(unexplained)} alert(s) not explained by injected "
            f"chaos {injected}")
    if not within:
        failures.append("resource slope over budget")
    if not respawn["ok"]:
        failures.append(
            f"respawn storm: {respawn['rate_per_h']:.1f}/h over budget "
            f"{respawn['budget_per_h']}/h")
    if dropped:
        failures.append(f"{dropped} txn(s) dropped vs corpus expectation")
    if leaked:
        failures.append(f"{leaked} staging slot(s) leaked")
    if not boundaries_clean:
        failures.append("burn-rate blip at a phase boundary")

    return {
        "metric": METRIC,
        "schema_version": SCHEMA_VERSION,
        "ts": datetime.now(timezone.utc).isoformat(),
        "ok": not failures,
        "on_device": backend == "tpu",
        "value": round(recv / elapsed_s, 1) if elapsed_s > 0 else 0.0,
        "unit": "txns/s",
        "seed": plan.seed,
        "duration_s": round(elapsed_s, 3),
        "backend": backend,
        "phases": log,
        "slo": {
            "alert_cnt": int(slo.get("alert_cnt", 0) or 0),
            "unexplained_alerts": len(unexplained),
            "alerts": [
                {"slo": a.get("slo"), "kind": a.get("slo_kind"),
                 "edge_or_stage": a.get("edge_or_stage"),
                 "burn_milli": a.get("burn_milli"),
                 "fault_classes": list(a.get("fault_classes") or ())}
                for a in alerts
            ],
            "explained": injected,
            "burn_continuity": {
                "boundaries": max(0, len(log) - 1),
                "clean": boundaries_clean,
            },
        },
        "slopes": {
            "samples": int(slopes.get("samples", 0)),
            "heap_kb_min": round(float(slopes.get("heap_kb_min", 0.0)), 3),
            "pool_milli_min": round(
                float(slopes.get("pool_milli_min", 0.0)), 3),
            "compile_per_hr": round(
                float(slopes.get("compile_per_hr", 0.0)), 3),
            "within_budget": within,
            "budgets": budgets,
            "ring_hwm": probe.ring_hwm(),
        },
        "reconfig": {
            "requested": applied + refused,
            "applied": applied,
            "refused": refused,
            "events": events,
        },
        "respawn": respawn,
        "continuity": {
            "offered": len(src.payloads),
            "published": src.pub_cnt,
            "expected_sink": expected_sink,
            "received": recv,
            "dropped": dropped,
            "slots_leaked": leaked,
            "digest_match": None,   # filled by a control-run comparison
        },
        "autopsy_index": sorted(
            {a["autopsy"] for a in alerts if a.get("autopsy")}),
        "failures": failures,
    }
