"""Pipeline topology builder + in-process runner for the tile graph.

Role parity with the reference's configure `frank` stage + `fdctl run`
(/root/reference/src/app/fdctl/configure/frank.c:195-266 builds every
cnc/mcache/dcache/fseq into the wksp and records names in the pod;
run.c:292-300 spawns the tiles): here build_topology() creates the rings
in a Workspace and records the wiring in a utils.pod.Pod; run_pipeline()
joins the tiles to the rings and drives them on threads (the rings are
process-shared, so tiles can equally be spawned as processes — the test
suite exercises the multi-process path at the tango layer).

Topology (the minimum end-to-end slice, SURVEY.md §7 step 5):
    replay -> verify -> dedup -> pack -> sink
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from firedancer_tpu.tango.rings import (
    CNC_HALT,
    Cnc,
    DCache,
    FSeq,
    MCache,
    Workspace,
)
from firedancer_tpu.utils.pod import Pod

from .tiles import (
    FD_TPU_MTU,
    DedupTile,
    InLink,
    LinkNames,
    OutLink,
    PackTile,
    ReplayTile,
    SinkTile,
    VerifyTile,
)

LINKS = ("replay_verify", "verify_dedup", "dedup_pack", "pack_sink")
TILES = ("replay", "verify", "dedup", "pack", "sink")


@dataclass
class Topology:
    wksp_path: str
    depth: int = 128
    mtu: int = FD_TPU_MTU
    pod: Pod = field(default_factory=Pod)


def build_topology(
    wksp_path: str, depth: int = 128, mtu: int = FD_TPU_MTU,
    wksp_sz: int = 1 << 24,
) -> Topology:
    """Create workspace + all rings; record names/params in the pod."""
    topo = Topology(wksp_path=wksp_path, depth=depth, mtu=mtu)
    wksp = Workspace.create(wksp_path, wksp_sz)
    mtu_chunks = (mtu + 63) // 64
    dcache_sz = 64 * mtu_chunks * (depth + 2)  # room for depth in-flight frags
    for link in LINKS:
        MCache(wksp, f"{link}.mcache", depth=depth, create=True)
        DCache(wksp, f"{link}.dcache", data_sz=dcache_sz, create=True)
        FSeq(wksp, f"{link}.fseq", create=True)
        topo.pod.insert_cstr(f"firedancer.{link}.mcache", f"{link}.mcache")
        topo.pod.insert_cstr(f"firedancer.{link}.dcache", f"{link}.dcache")
        topo.pod.insert_cstr(f"firedancer.{link}.fseq", f"{link}.fseq")
        topo.pod.insert_ulong(f"firedancer.{link}.depth", depth)
    for tile in TILES:
        Cnc(wksp, f"{tile}.cnc", create=True)
        topo.pod.insert_cstr(f"firedancer.{tile}.cnc", f"{tile}.cnc")
    topo.pod.insert_ulong("firedancer.mtu", mtu)
    wksp.leave()
    return topo


def _link_names(pod: Pod, link: str) -> LinkNames:
    return LinkNames(
        mcache=pod.query_cstr(f"firedancer.{link}.mcache"),
        dcache=pod.query_cstr(f"firedancer.{link}.dcache"),
        fseq=pod.query_cstr(f"firedancer.{link}.fseq"),
    )


@dataclass
class PipelineResult:
    recv_cnt: int
    recv_sz: int
    bank_hist: Dict[int, int]
    diag: Dict[str, Dict[str, int]]
    elapsed_s: float


def run_pipeline(
    topo: Topology,
    payloads: List[bytes],
    verify_backend: str = "oracle",
    verify_batch: int = 128,
    verify_max_msg_len: Optional[int] = None,
    bank_cnt: int = 4,
    timeout_s: float = 60.0,
) -> PipelineResult:
    """Join tiles to the topology, run them on threads, wait for the sink
    to drain, HALT everything, and return counts + diag snapshot.

    Shutdown is quiescence-based (source exhausted + every link drained);
    filtered frags never reach the sink, so the caller asserts on
    PipelineResult.recv_cnt rather than passing an expected count in.
    """
    pod = topo.pod
    wksp = Workspace.join(topo.wksp_path)
    mtu = pod.query_ulong("firedancer.mtu", FD_TPU_MTU)

    def in_link(link):
        return InLink(wksp, _link_names(pod, link))

    def out_link(link, consumer_fseq_link):
        fs = FSeq(wksp, pod.query_cstr(f"firedancer.{consumer_fseq_link}.fseq"))
        return OutLink(wksp, _link_names(pod, link), mtu=mtu,
                       reliable_fseqs=[fs])

    replay = ReplayTile(
        wksp, pod.query_cstr("firedancer.replay.cnc"),
        out_link=out_link("replay_verify", "replay_verify"),
        payloads=payloads,
    )
    verify = VerifyTile(
        wksp, pod.query_cstr("firedancer.verify.cnc"),
        in_link=in_link("replay_verify"),
        out_link=out_link("verify_dedup", "verify_dedup"),
        backend=verify_backend, batch=verify_batch,
        max_msg_len=verify_max_msg_len or mtu,
    )
    dedup = DedupTile(
        wksp, pod.query_cstr("firedancer.dedup.cnc"),
        in_link=in_link("verify_dedup"),
        out_link=out_link("dedup_pack", "dedup_pack"),
    )
    pack = PackTile(
        wksp, pod.query_cstr("firedancer.pack.cnc"),
        in_link=in_link("dedup_pack"),
        out_link=out_link("pack_sink", "pack_sink"),
        bank_cnt=bank_cnt,
    )
    sink = SinkTile(
        wksp, pod.query_cstr("firedancer.sink.cnc"),
        in_link=in_link("pack_sink"),
    )
    tiles = [replay, verify, dedup, pack, sink]

    # Tiles run until HALT; max_ns is a hung-pipeline safety net and must
    # outlast the supervisor's own timeout or slow runs silently truncate.
    tile_max_ns = int((timeout_s + 30.0) * 1e9)
    threads = [
        threading.Thread(
            target=t.run, args=(tile_max_ns,), name=t.name, daemon=True
        )
        for t in tiles
    ]
    t0 = time.perf_counter()
    for th in threads:
        th.start()

    def quiesced() -> bool:
        """Source exhausted and every link fully drained end to end."""
        return (
            replay.pos >= len(payloads)
            and verify.in_link.seq >= replay.out_link.seq
            and not verify._pending
            and dedup.in_link.seq >= verify.out_link.seq
            and pack.in_link.seq >= dedup.out_link.seq
            and pack.pack.pending_cnt() == 0
            and sink.in_link.seq >= pack.out_link.seq
        )

    deadline = t0 + timeout_s
    while time.perf_counter() < deadline:
        if quiesced():
            break
        time.sleep(0.005)
    # Signal HALT through every cnc (supervisor role, run.c:318-340 analog
    # without the kill-the-namespace part).
    for t in tiles:
        t.cnc.signal(CNC_HALT)
    for th in threads:
        th.join(timeout=10.0)
    elapsed = time.perf_counter() - t0

    from firedancer_tpu.disco.monitor import snapshot

    diag = snapshot(wksp, pod)
    res = PipelineResult(
        recv_cnt=sink.recv_cnt,
        recv_sz=sink.recv_sz,
        bank_hist=dict(sink.bank_hist),
        diag=diag,
        elapsed_s=elapsed,
    )
    wksp.leave()
    return res
